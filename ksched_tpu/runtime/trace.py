"""First-class round tracing.

The reference times rounds ad hoc in its CLI (`time.Since` around
ScheduleAllJobs, cmd/k8sscheduler/scheduler.go:146-150) and discards the
solver's own timing lines (placement/solver.go:169-170). Here every
round yields a structured record — per-phase wall clock (the RoundTiming
breakdown, itself derived from obs span durations), mutation counts
(ChangeStats), solver effort — exportable as JSON lines and
summarizable as percentiles.

The tracer is also the metrics publication point: every record it
appends is simultaneously published to the obs metrics registry
(rounds/faults/retries/degradations counters, per-phase latency
histograms), so the live `/metricsz` surface and the JSONL artifact
are two views of the same records and reconcile exactly at any
instant — the obs smoke asserts this over a chaos soak.
"""

from __future__ import annotations

import json
import time
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional

import numpy as np

from ..obs.metrics import get_registry, log_buckets


@dataclass
class RoundRecord:
    round_index: int
    wall_time: float  # epoch seconds at record time
    phases_ms: Dict[str, float]
    num_scheduled: int = 0
    solver_work: int = 0  # supersteps / iterations / augmentations
    nodes_added: int = 0
    arcs_added: int = 0
    arcs_changed: int = 0
    arcs_removed: int = 0
    # -- robustness observability (chaos harness / hardened loop): every
    # injected fault, retry, degradation, and heartbeat expiry is
    # attributable to the round it landed in --------------------------------
    faults_injected: Dict[str, int] = field(default_factory=dict)
    retries: int = 0  # control-plane retry/re-post attempts this round
    degradations: int = 0  # solver rungs stepped down this round
    solver_rung: int = 0  # ladder rung that produced the round; -1 = no solve (NOOP if noop_round, else an idle sweep)
    noop_round: bool = False  # ladder exhausted: previous assignments kept
    deadline_miss: bool = False  # round blew its watchdog deadline
    machines_lost: int = 0  # heartbeat-expired machines this sweep
    tasks_failed: int = 0  # heartbeat-expired tasks this sweep
    #: owning cell in a multi-tenant service ("" = single-tenant): the
    #: per-tenant soak/obs_report group round records on this, and the
    #: zero-cross-tenant-interference check relies on fault/degradation
    #: counters landing ONLY in the chaos tenant's records
    tenant: str = ""


class RoundTracer:
    def __init__(self, capacity: Optional[int] = None, registry=None) -> None:
        self.records: List[RoundRecord] = []
        self.capacity = capacity
        # metric handles resolve at construction time (scoped_registry
        # gives a soak run private per-run accounting); with obs
        # disabled these are inert null metrics
        reg = registry if registry is not None else get_registry()
        self._m_rounds = reg.counter(
            "ksched_rounds_total",
            "scheduling rounds by kind (sched = solved, idle = sweep-only, "
            "noop = ladder exhausted, previous assignments kept)",
            labelnames=("kind",),
        )
        self._m_phase = reg.histogram(
            "ksched_round_phase_ms",
            "per-phase round latency (solved rounds only; idle sweeps and "
            "NOOP rounds carry no phase timings)",
            labelnames=("phase",),
        )
        self._m_scheduled = reg.counter(
            "ksched_scheduled_tasks_total", "tasks placed across all rounds"
        )
        self._m_faults = reg.counter(
            "ksched_faults_attributed_total",
            "injected faults attributed to a round's record, by kind "
            "(reconciles against ksched_chaos_injected_total)",
            labelnames=("kind",),
        )
        self._m_retries = reg.counter(
            "ksched_retries_total", "control-plane retry/re-post attempts"
        )
        self._m_degr = reg.counter(
            "ksched_round_degradations_total",
            "solver rungs stepped down, attributed per round",
        )
        self._m_miss = reg.counter(
            "ksched_deadline_misses_total", "rounds that blew the watchdog deadline"
        )
        self._m_lost = reg.counter(
            "ksched_machines_lost_total", "heartbeat-expired machines"
        )
        self._m_failed = reg.counter(
            "ksched_tasks_failed_total", "heartbeat-expired tasks"
        )
        self._m_graph = reg.counter(
            "ksched_graph_changes_total",
            "graph-delta journal records by kind",
            labelnames=("kind",),
        )
        self._m_work = reg.histogram(
            "ksched_round_solver_work",
            "solver supersteps/iterations per solved round",
            buckets=log_buckets(1, 1 << 20, 2.0),
        )

    def _publish(self, rec: RoundRecord) -> None:
        """Mirror one record onto the metrics registry. Called for every
        appended record, so summed records == served counters, always."""
        kind = (
            "noop" if rec.noop_round
            else ("idle" if rec.solver_rung == -1 else "sched")
        )
        self._m_rounds.labels(kind=kind).inc()
        if kind == "sched":
            for phase, ms in rec.phases_ms.items():
                self._m_phase.labels(phase=phase).observe(ms)
            if rec.solver_work:
                self._m_work.observe(rec.solver_work)
        if rec.num_scheduled:
            self._m_scheduled.inc(rec.num_scheduled)
        for k, v in rec.faults_injected.items():
            if v:
                self._m_faults.labels(kind=k).inc(v)
        if rec.retries:
            self._m_retries.inc(rec.retries)
        if rec.degradations:
            self._m_degr.inc(rec.degradations)
        if rec.deadline_miss:
            self._m_miss.inc()
        if rec.machines_lost:
            self._m_lost.inc(rec.machines_lost)
        if rec.tasks_failed:
            self._m_failed.inc(rec.tasks_failed)
        for kind_, n in (
            ("nodes_added", rec.nodes_added),
            ("arcs_added", rec.arcs_added),
            ("arcs_changed", rec.arcs_changed),
            ("arcs_removed", rec.arcs_removed),
        ):
            if n:
                self._m_graph.labels(kind=kind_).inc(n)

    # -- recording --------------------------------------------------------

    def record_flow_round(
        self,
        scheduler,
        num_scheduled: int,
        extra: Optional[Dict] = None,
        solved: bool = True,
    ) -> RoundRecord:
        """Capture a FlowScheduler round from its last_timing + stats.
        ``extra`` carries the robustness counters (faults_injected,
        retries, degradations, …) the hardened service loop attributes
        to this round; unknown keys are rejected so counter names
        cannot silently drift from the RoundRecord schema.

        ``solved=False`` marks an idle sweep (no graph rebuild/solve
        ran): the scheduler's dimacs_stats and solver-work counters
        still hold the *previous* solved round's values and must not be
        re-reported, or trace aggregations would multi-count that round
        once per quiet poll."""
        t = scheduler.last_timing
        stats = scheduler.dimacs_stats if solved else None
        backend = getattr(scheduler.solver, "backend", None) if solved else None
        rec = RoundRecord(
            round_index=len(self.records),
            wall_time=time.time(),
            phases_ms={
                "stats": t.stats_s * 1e3,
                "graph_update": t.graph_update_s * 1e3,
                "solve": t.solve_s * 1e3,
                "deltas": t.deltas_s * 1e3,
                "apply": t.apply_s * 1e3,
                "total": t.total_s * 1e3,
            },
            num_scheduled=num_scheduled,
            solver_work=getattr(backend, "last_iterations", 0)
            or getattr(backend, "last_supersteps", 0),
            nodes_added=stats.nodes_added if stats else 0,
            arcs_added=stats.arcs_added if stats else 0,
            arcs_changed=stats.arcs_changed if stats else 0,
            arcs_removed=stats.arcs_removed if stats else 0,
        )
        for k, v in (extra or {}).items():
            if not hasattr(rec, k):
                raise ValueError(f"unknown RoundRecord field {k!r}")
            setattr(rec, k, v)
        self._append(rec)
        return rec

    def record_bulk_round(self, cluster, result) -> RoundRecord:
        """Capture a BulkCluster round from its BulkRoundResult."""
        backend = cluster.backend
        return self.record_timed_round(
            result.timing,
            num_scheduled=len(result.placed_tasks),
            solver_work=getattr(backend, "last_supersteps", 0)
            or getattr(backend, "last_iterations", 0),
        )

    def record_timed_round(
        self,
        timing: Dict[str, float],
        total_ms: Optional[float] = None,
        num_scheduled: int = 0,
        solver_work: int = 0,
    ) -> RoundRecord:
        """Capture an externally timed round from a `{phase}_s` dict
        (bench.py's post-measurement publication path). `total_ms`
        overrides the summed-phases total with a measured wall time.
        This is the one place the timing-key → phase-name mapping
        lives, so bench snapshots carry exactly the series the service
        publishes."""
        phases_ms = {k[:-2]: v * 1e3 for k, v in timing.items()}
        phases_ms["total"] = (
            total_ms if total_ms is not None else sum(phases_ms.values())
        )
        rec = RoundRecord(
            round_index=len(self.records),
            wall_time=time.time(),
            phases_ms=phases_ms,
            num_scheduled=num_scheduled,
            solver_work=solver_work,
        )
        self._append(rec)
        return rec

    def _append(self, rec: RoundRecord) -> None:
        self._publish(rec)
        self.records.append(rec)
        if self.capacity is not None and len(self.records) > self.capacity:
            del self.records[0]

    # -- export -----------------------------------------------------------

    def to_jsonl(self) -> str:
        return "\n".join(json.dumps(asdict(r)) for r in self.records)

    def dump(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_jsonl() + ("\n" if self.records else ""))

    def summary(self, phase: str = "total") -> Dict[str, float]:
        """Phase percentiles over SOLVED rounds. Idle sweeps (sweep-only
        quiet polls: ``solver_rung == -1`` without ``noop_round``) carry
        zeroed phase timings by construction and would drag an
        idle-heavy soak's p50 toward zero, so they are excluded from
        the percentiles and reported as ``idle_rounds`` instead. NOOP
        rounds are different — a *failed* solve is part of the latency
        story, not a skipped one — so they stay in the population."""
        idle = sum(
            1 for r in self.records if r.solver_rung == -1 and not r.noop_round
        )
        vals = np.array(
            [
                r.phases_ms.get(phase, 0.0)
                for r in self.records
                if not (r.solver_rung == -1 and not r.noop_round)
            ],
            dtype=np.float64,
        )
        if not len(vals):
            return {"rounds": 0, "idle_rounds": idle}
        return {
            "rounds": len(vals),
            "idle_rounds": idle,
            "p50_ms": float(np.percentile(vals, 50)),
            "p90_ms": float(np.percentile(vals, 90)),
            "p99_ms": float(np.percentile(vals, 99)),
            "mean_ms": float(vals.mean()),
            "max_ms": float(vals.max()),
        }
