"""First-class round tracing.

The reference times rounds ad hoc in its CLI (`time.Since` around
ScheduleAllJobs, cmd/k8sscheduler/scheduler.go:146-150) and discards the
solver's own timing lines (placement/solver.go:169-170). Here every
round yields a structured record — per-phase wall clock (the RoundTiming
breakdown), mutation counts (ChangeStats), solver effort — exportable as
JSON lines and summarizable as percentiles.
"""

from __future__ import annotations

import json
import time
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional

import numpy as np


@dataclass
class RoundRecord:
    round_index: int
    wall_time: float  # epoch seconds at record time
    phases_ms: Dict[str, float]
    num_scheduled: int = 0
    solver_work: int = 0  # supersteps / iterations / augmentations
    nodes_added: int = 0
    arcs_added: int = 0
    arcs_changed: int = 0
    arcs_removed: int = 0
    # -- robustness observability (chaos harness / hardened loop): every
    # injected fault, retry, degradation, and heartbeat expiry is
    # attributable to the round it landed in --------------------------------
    faults_injected: Dict[str, int] = field(default_factory=dict)
    retries: int = 0  # control-plane retry/re-post attempts this round
    degradations: int = 0  # solver rungs stepped down this round
    solver_rung: int = 0  # ladder rung that produced the round; -1 = no solve (NOOP if noop_round, else an idle sweep)
    noop_round: bool = False  # ladder exhausted: previous assignments kept
    deadline_miss: bool = False  # round blew its watchdog deadline
    machines_lost: int = 0  # heartbeat-expired machines this sweep
    tasks_failed: int = 0  # heartbeat-expired tasks this sweep


class RoundTracer:
    def __init__(self, capacity: Optional[int] = None) -> None:
        self.records: List[RoundRecord] = []
        self.capacity = capacity

    # -- recording --------------------------------------------------------

    def record_flow_round(
        self,
        scheduler,
        num_scheduled: int,
        extra: Optional[Dict] = None,
        solved: bool = True,
    ) -> RoundRecord:
        """Capture a FlowScheduler round from its last_timing + stats.
        ``extra`` carries the robustness counters (faults_injected,
        retries, degradations, …) the hardened service loop attributes
        to this round; unknown keys are rejected so counter names
        cannot silently drift from the RoundRecord schema.

        ``solved=False`` marks an idle sweep (no graph rebuild/solve
        ran): the scheduler's dimacs_stats and solver-work counters
        still hold the *previous* solved round's values and must not be
        re-reported, or trace aggregations would multi-count that round
        once per quiet poll."""
        t = scheduler.last_timing
        stats = scheduler.dimacs_stats if solved else None
        backend = getattr(scheduler.solver, "backend", None) if solved else None
        rec = RoundRecord(
            round_index=len(self.records),
            wall_time=time.time(),
            phases_ms={
                "stats": t.stats_s * 1e3,
                "graph_update": t.graph_update_s * 1e3,
                "solve": t.solve_s * 1e3,
                "deltas": t.deltas_s * 1e3,
                "apply": t.apply_s * 1e3,
                "total": t.total_s * 1e3,
            },
            num_scheduled=num_scheduled,
            solver_work=getattr(backend, "last_iterations", 0)
            or getattr(backend, "last_supersteps", 0),
            nodes_added=stats.nodes_added if stats else 0,
            arcs_added=stats.arcs_added if stats else 0,
            arcs_changed=stats.arcs_changed if stats else 0,
            arcs_removed=stats.arcs_removed if stats else 0,
        )
        for k, v in (extra or {}).items():
            if not hasattr(rec, k):
                raise ValueError(f"unknown RoundRecord field {k!r}")
            setattr(rec, k, v)
        self._append(rec)
        return rec

    def record_bulk_round(self, cluster, result) -> RoundRecord:
        """Capture a BulkCluster round from its BulkRoundResult."""
        backend = cluster.backend
        phases_ms = {k[:-2]: v * 1e3 for k, v in result.timing.items()}
        phases_ms.setdefault("total", sum(phases_ms.values()))
        rec = RoundRecord(
            round_index=len(self.records),
            wall_time=time.time(),
            phases_ms=phases_ms,
            num_scheduled=len(result.placed_tasks),
            solver_work=getattr(backend, "last_supersteps", 0)
            or getattr(backend, "last_iterations", 0),
        )
        self._append(rec)
        return rec

    def _append(self, rec: RoundRecord) -> None:
        self.records.append(rec)
        if self.capacity is not None and len(self.records) > self.capacity:
            del self.records[0]

    # -- export -----------------------------------------------------------

    def to_jsonl(self) -> str:
        return "\n".join(json.dumps(asdict(r)) for r in self.records)

    def dump(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_jsonl() + ("\n" if self.records else ""))

    def summary(self, phase: str = "total") -> Dict[str, float]:
        vals = np.array(
            [r.phases_ms.get(phase, 0.0) for r in self.records], dtype=np.float64
        )
        if not len(vals):
            return {"rounds": 0}
        return {
            "rounds": len(vals),
            "p50_ms": float(np.percentile(vals, 50)),
            "p90_ms": float(np.percentile(vals, 90)),
            "p99_ms": float(np.percentile(vals, 99)),
            "mean_ms": float(vals.mean()),
            "max_ms": float(vals.max()),
        }
