"""First-class round tracing.

The reference times rounds ad hoc in its CLI (`time.Since` around
ScheduleAllJobs, cmd/k8sscheduler/scheduler.go:146-150) and discards the
solver's own timing lines (placement/solver.go:169-170). Here every
round yields a structured record — per-phase wall clock (the RoundTiming
breakdown), mutation counts (ChangeStats), solver effort — exportable as
JSON lines and summarizable as percentiles.
"""

from __future__ import annotations

import json
import time
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional

import numpy as np


@dataclass
class RoundRecord:
    round_index: int
    wall_time: float  # epoch seconds at record time
    phases_ms: Dict[str, float]
    num_scheduled: int = 0
    solver_work: int = 0  # supersteps / iterations / augmentations
    nodes_added: int = 0
    arcs_added: int = 0
    arcs_changed: int = 0
    arcs_removed: int = 0


class RoundTracer:
    def __init__(self, capacity: Optional[int] = None) -> None:
        self.records: List[RoundRecord] = []
        self.capacity = capacity

    # -- recording --------------------------------------------------------

    def record_flow_round(self, scheduler, num_scheduled: int) -> RoundRecord:
        """Capture a FlowScheduler round from its last_timing + stats."""
        t = scheduler.last_timing
        stats = scheduler.dimacs_stats
        backend = getattr(scheduler.solver, "backend", None)
        rec = RoundRecord(
            round_index=len(self.records),
            wall_time=time.time(),
            phases_ms={
                "stats": t.stats_s * 1e3,
                "graph_update": t.graph_update_s * 1e3,
                "solve": t.solve_s * 1e3,
                "deltas": t.deltas_s * 1e3,
                "apply": t.apply_s * 1e3,
                "total": t.total_s * 1e3,
            },
            num_scheduled=num_scheduled,
            solver_work=getattr(backend, "last_iterations", 0)
            or getattr(backend, "last_supersteps", 0),
            nodes_added=stats.nodes_added,
            arcs_added=stats.arcs_added,
            arcs_changed=stats.arcs_changed,
            arcs_removed=stats.arcs_removed,
        )
        self._append(rec)
        return rec

    def record_bulk_round(self, cluster, result) -> RoundRecord:
        """Capture a BulkCluster round from its BulkRoundResult."""
        backend = cluster.backend
        phases_ms = {k[:-2]: v * 1e3 for k, v in result.timing.items()}
        phases_ms.setdefault("total", sum(phases_ms.values()))
        rec = RoundRecord(
            round_index=len(self.records),
            wall_time=time.time(),
            phases_ms=phases_ms,
            num_scheduled=len(result.placed_tasks),
            solver_work=getattr(backend, "last_supersteps", 0)
            or getattr(backend, "last_iterations", 0),
        )
        self._append(rec)
        return rec

    def _append(self, rec: RoundRecord) -> None:
        self.records.append(rec)
        if self.capacity is not None and len(self.records) > self.capacity:
            del self.records[0]

    # -- export -----------------------------------------------------------

    def to_jsonl(self) -> str:
        return "\n".join(json.dumps(asdict(r)) for r in self.records)

    def dump(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_jsonl() + ("\n" if self.records else ""))

    def summary(self, phase: str = "total") -> Dict[str, float]:
        vals = np.array(
            [r.phases_ms.get(phase, 0.0) for r in self.records], dtype=np.float64
        )
        if not len(vals):
            return {"rounds": 0}
        return {
            "rounds": len(vals),
            "p50_ms": float(np.percentile(vals, 50)),
            "p90_ms": float(np.percentile(vals, 90)),
            "p99_ms": float(np.percentile(vals, 99)),
            "mean_ms": float(vals.mean()),
            "max_ms": float(vals.max()),
        }
