"""MultiTenantService: N independent cells, one warm solver process.

Each admitted tenant is a full `SchedulerService` cell — its own
ClusterAPI adapter, resource topology, pod/task maps, degradation
ladder, deadline watchdog, heartbeat monitor, flight recorder, and
(when chaos is configured) its own fault injector — multiplexed through
a four-phase round:

1. **dispatch** (per cell, fairness-rotated order): poll the tenant's
   control plane, ingest pods, journal the graph delta, and dispatch
   the solve — the tenant's `LaneSolver` parks a lane with the shared
   `StackedBatcher` instead of running its own program;
2. **flush**: the batcher groups same-bucket/same-policy lanes and
   dispatches ONE stacked program per group (jax async dispatch — the
   host is immediately free);
3. **post window** (per cell): the PREVIOUS round's binding POSTs ride
   the in-flight batched solve — the `--pipeline` dispatch window,
   generalized per tenant;
4. **complete** (per cell): synchronize the lane, apply deltas, queue
   this round's bindings, heartbeat sweep, and trace attribution —
   including the NOOP backstop when the tenant's whole ladder failed.

Isolation properties (asserted by tests/test_tenancy.py and the
`make tenant-smoke` soak):

- a lane's solve is bit-identical to the same tenant running alone
  (stacked vmap semantics + per-tenant warm state + per-tenant RNG
  streams);
- chaos on one tenant degrades only its own lane: injected faults
  raise at that cell's dispatch/complete (never entering the shared
  batch), its ladder degrades to its own jax/cpu_ref rungs, and at
  worst ITS round goes NOOP while every other cell's record stays
  fault-free;
- accounting is per-tenant end to end: every cell's metric handles
  resolve against a ``tenant``-labelled scoped view of one shared
  registry, round records carry ``tenant``, flight dumps are
  tenant-scoped files, and soltel stall events are tagged with the
  tenant whose lane produced them.
"""

from __future__ import annotations

import warnings
from typing import Dict, Optional, Tuple

from ..cli import SchedulerService
from ..cluster import ClusterAPI, SyntheticClusterAPI
from ..costmodels import CostModelType
from ..obs import metrics as obs_metrics
from ..obs import soltel
from ..obs.flight import FlightRecorder
from ..obs.spans import span
from ..runtime.trace import RoundTracer
from ..utils.ids import rng as global_rng
from ..utils.ids import seed_rng
from .batch import LaneSolver, StackedBatcher
from .manager import AdmissionPolicy, TenantManager


class TenantCell:
    """One tenant's slice of the process: the cell's SchedulerService
    plus the per-round glue (RNG stream swapping, injector clock, span
    marks, quarantine attribution)."""

    def __init__(
        self,
        service: "MultiTenantService",
        tenant_id: str,
        api: ClusterAPI,
        svc: SchedulerService,
        lane: LaneSolver,
        injector=None,
        poll_timeout_s: float = 0.005,
    ) -> None:
        self.service = service
        self.tenant_id = tenant_id
        self.api = api
        self.svc = svc
        self.lane = lane
        self.injector = injector
        self.poll_timeout_s = poll_timeout_s
        self.tick = 0
        self._begin_events = None
        self._noop_mark = 0
        self._rng_state = None  # installed by add_tenant after build

    # -- per-tenant RNG stream ---------------------------------------------
    # Task/job/machine ids come from the process-global seeded RNG
    # (utils/ids.py). Interleaved cells must each consume their OWN
    # continuation of their seed's stream, or ids — and therefore
    # placements — would differ between a multi-tenant run and the same
    # tenant run in isolation (the bit-parity acceptance). Same pattern
    # as bench.py's interleaved arms: park/swap the stream around every
    # cell phase that can create ids.

    def _swap_in(self):
        outer = global_rng().getstate()
        global_rng().setstate(self._rng_state)
        return outer

    def _park(self, outer) -> None:
        self._rng_state = global_rng().getstate()
        global_rng().setstate(outer)

    # -- round phases ------------------------------------------------------

    def begin(self, now: Optional[float] = None) -> int:
        """Phase 1: injector clock, poll, ingest, dispatch."""
        outer = self._swap_in()
        try:
            if self.injector is not None:
                self.injector.begin_round(self.tick)
            self.tick += 1
            pods = self.api.poll_pod_batch(self.poll_timeout_s)
            tracer = self.service.span_tracer
            mark = tracer.mark() if tracer is not None else 0
            self._noop_mark = self.svc.noop_rounds
            # the quarantine signal must be THIS round's: a round whose
            # rung-0 dispatch fails (chaos) never reaches the lane's
            # complete(), and a stale True from a previous round would
            # count as a fresh escape in the manager's streak
            self.lane.last_warm_escape = False
            with soltel.stall_scope(self.tenant_id), span(
                "tenant_dispatch", tenant=self.tenant_id, pods=len(pods)
            ):
                self.svc.dispatch_round(pods)
            # snapshot this cell's OWN dispatch-phase spans now: the
            # wall-clock window until finish() contains every other
            # cell's phases, which must not leak into a tenant-scoped
            # flight dump (finish passes this slice as the prefix)
            self._begin_events = (
                list(tracer.events_since(mark)) if tracer is not None else None
            )
            return len(pods)
        finally:
            self._park(outer)

    def post_window(self) -> int:
        """Phase 3: the previous round's binding POSTs, inside the
        batched-solve window (pipeline mode; a no-op otherwise)."""
        if not self.svc._pending_bindings:
            return 0
        with span("tenant_post_window", tenant=self.tenant_id):
            return self.svc.flush_pending_bindings()

    def finish(self, now: Optional[float] = None) -> int:
        """Phase 4: synchronize the lane, apply, sweep, trace; then
        feed the manager's quarantine accounting."""
        outer = self._swap_in()
        try:
            tracer = self.service.span_tracer
            mark = tracer.mark() if tracer is not None else 0
            with soltel.stall_scope(self.tenant_id), span(
                "tenant_finish", tenant=self.tenant_id
            ):
                bound = self.svc.complete_round(
                    now=now, span_mark=mark, span_prefix=self._begin_events
                )
        finally:
            self._park(outer)
        self.service.manager.note_round(
            self.tenant_id,
            noop=self.svc.noop_rounds > self._noop_mark,
            warm_escape=self.lane.last_warm_escape,
        )
        return bound

    def drain(self) -> None:
        """Post anything still queued (service shutdown / eviction)."""
        self.svc.flush_pending_bindings()


class MultiTenantService:
    """The scheduler-as-a-service process: admit cells, run rounds.

    ``registry`` is the SHARED parent registry; each cell's handles
    resolve against ``registry.scoped(tenant=<id>)``, so one /metricsz
    surface serves every tenant with a ``tenant`` label. ``pipeline``
    turns on the per-tenant dispatch windows (phase 3); without it each
    cell posts its bindings synchronously in phase 4."""

    def __init__(
        self,
        registry=None,
        policy: Optional[AdmissionPolicy] = None,
        round_deadline_s: float = 30.0,
        pipeline: bool = True,
        device_resident: bool = False,
        flight_dir: Optional[str] = None,
        flight_capacity: int = 32,
        span_tracer=None,
        alpha: int = 8,
        max_supersteps: int = 50_000,
    ) -> None:
        self.registry = (
            registry if registry is not None else obs_metrics.get_registry()
        )
        # batcher/manager handles resolve against the PARENT registry
        # (process-level families; per-tenant families ride the scoped
        # views built in add_tenant)
        with obs_metrics.scoped_registry(self.registry):
            self.batcher = StackedBatcher(
                alpha=alpha, max_supersteps=max_supersteps
            )
            self.manager = TenantManager(policy)
        self.round_deadline_s = round_deadline_s
        self.pipeline = pipeline
        self.device_resident = device_resident
        self.flight_dir = flight_dir
        self.flight_capacity = flight_capacity
        self.span_tracer = span_tracer
        self.cells: Dict[str, TenantCell] = {}
        self.round_index = 0

    def _scoped(self, tenant_id: str):
        """The tenant's labelled registry view (the parent itself when
        it cannot scope — the null registry)."""
        scoped = getattr(self.registry, "scoped", None)
        return scoped(tenant=tenant_id) if scoped is not None else self.registry

    # -- tenant lifecycle --------------------------------------------------

    def add_tenant(
        self,
        tenant_id: str,
        api: Optional[ClusterAPI] = None,
        machines: int = 4,
        pus_per_core: int = 2,
        slots: int = 16,
        cost_model: CostModelType = CostModelType.TRIVIAL,
        injector=None,
        seed: int = 0,
        restart_budget: Optional[int] = 64,
        bucket_floor: Optional[Tuple[int, int]] = None,
        machine_timeout_s: float = 0.0,
        est_nodes: Optional[int] = None,
        est_arcs: Optional[int] = None,
        poll_timeout_s: float = 0.005,
        audit_every: int = 0,
    ) -> TenantCell:
        """Admit one cell: admission control first, then the cell's
        SchedulerService is built under the tenant's scoped registry
        and its own seeded RNG stream (so the cell is reproducible in
        isolation). ``api`` defaults to an in-process synthetic control
        plane; pass an `HTTPClusterAPI` to multiplex real control
        planes through one process."""
        pus = machines * pus_per_core
        if est_nodes is None:
            # rough pow2-bucket estimate: topology nodes + a working
            # set of tasks/ECs; the DeviceGraphState bucket is what
            # actually gets priced, this just gates admission
            est_nodes = 2 * (machines * (2 + pus_per_core) + pus * slots + 16)
        if est_arcs is None:
            est_arcs = 4 * est_nodes
        account = self.manager.admit(tenant_id, est_nodes, est_arcs)
        scoped = self._scoped(tenant_id)
        if api is None:
            api = SyntheticClusterAPI()
        outer = global_rng().getstate()
        seed_rng(seed)
        try:
            with obs_metrics.scoped_registry(scoped):
                lane = LaneSolver(
                    self.batcher,
                    tenant=tenant_id,
                    restart_budget=restart_budget,
                    bucket_floor=bucket_floor,
                )
                flight = None
                if self.flight_dir:
                    flight = FlightRecorder(
                        capacity=self.flight_capacity,
                        dump_dir=self.flight_dir,
                        registry=scoped,
                        scope=tenant_id,
                        min_rounds_between_dumps=8,
                    )
                svc = SchedulerService(
                    api,
                    max_tasks_per_pu=slots,
                    cost_model=cost_model,
                    backend=lane,
                    backend_name="lane",
                    degrade=True,
                    injector=injector,
                    tracer=RoundTracer(registry=scoped),
                    round_deadline_s=self.round_deadline_s,
                    flight=flight,
                    span_tracer=self.span_tracer,
                    pipeline=self.pipeline,
                    device_resident=self.device_resident,
                    tenant=tenant_id,
                    audit_every=audit_every,
                )
                if machine_timeout_s > 0:
                    svc.enable_heartbeats(machine_timeout_s=machine_timeout_s)
                svc.init_topology(
                    fake_machines=machines, pus_per_core=pus_per_core
                )
            cell = TenantCell(
                self, tenant_id, api, svc, lane,
                injector=injector, poll_timeout_s=poll_timeout_s,
            )
            cell._rng_state = global_rng().getstate()
        except BaseException:
            self.manager.evict(tenant_id)
            raise
        finally:
            global_rng().setstate(outer)
        self.manager.register_lane(tenant_id, lane)
        account.extra["seed"] = seed
        self.cells[tenant_id] = cell
        return cell

    def save_tenant_checkpoint(self, tenant_id: str, path: str) -> None:
        """Checkpoint ONE cell (sidecar + .sched + warm .wal manifest,
        via its SchedulerService) under that tenant's scoped registry
        and parked RNG stream — the per-tenant slice of the state
        manifest: its own slot-plan geometry, warm endpoints, and
        ladder counters, with the cell's quarantine streak riding the
        sidecar-adjacent meta returned to the manager's account."""
        cell = self.cells[tenant_id]
        outer = global_rng().getstate()
        global_rng().setstate(cell._rng_state)
        try:
            with obs_metrics.scoped_registry(self._scoped(tenant_id)):
                cell.svc.save_checkpoint(path)
            cell._rng_state = global_rng().getstate()
        finally:
            global_rng().setstate(outer)
        account = self.manager.accounts.get(tenant_id)
        if account is not None:
            account.extra["checkpoint"] = path
            account.extra["quarantine_streak"] = account.bad_streak

    def remove_tenant(self, tenant_id: str) -> None:
        cell = self.cells.pop(tenant_id, None)
        if cell is not None:
            cell.drain()
        self.manager.evict(tenant_id)

    # -- the multiplexed round ---------------------------------------------

    def run_round(self, now: Optional[float] = None) -> Dict[str, int]:
        """One multiplexed round across every cell; returns bindings
        queued/posted per tenant.

        Per-cell fault barrier: one tenant's failure must not wedge the
        fleet. A cell whose begin/finish raises is skipped for the rest
        of the round (its own split-round latch always clears — a
        failed dispatch never sets it, and complete_round clears it on
        entry), every OTHER dispatched cell still completes, and the
        first error re-raises only after the round is consistent. A
        POST failure in a cell's dispatch window is warned and retried
        at that cell's next flush point (the batch restores itself),
        exactly the single-tenant retry semantics — it never blocks
        other tenants' phases."""
        order = [
            self.cells[tid]
            for tid in self.manager.order(self.round_index)
            if tid in self.cells
        ]
        errors: list = []
        dispatched: list = []
        # BaseException on purpose at every barrier: a KeyboardInterrupt
        # landing in one cell's phase must still let every OTHER
        # dispatched cell synchronize (the same in-flight-latch
        # invariant _run_once_pipelined documents) — it re-raises AS
        # ITSELF after the round is consistent, never wrapped
        for cell in order:
            try:
                cell.begin(now)
            except BaseException as e:  # noqa: BLE001 — re-raised after the round
                errors.append((cell.tenant_id, e))
            else:
                dispatched.append(cell)
        with span(
            "batch_flush",
            lanes=len(self.batcher._parked),
        ):
            # flush contains its own per-GROUP fault barrier (a failed
            # group's lanes re-raise at complete and degrade their own
            # ladders); it does not raise for solver-shaped failures
            self.batcher.flush()
        for cell in dispatched:
            try:
                cell.post_window()
            except Exception as e:  # noqa: BLE001 — batch restored for retry
                warnings.warn(
                    f"tenant {cell.tenant_id!r}: binding POST failed in the "
                    f"dispatch window ({e}); batch queued for retry at the "
                    "next flush point",
                    RuntimeWarning,
                    stacklevel=2,
                )
            except BaseException as e:  # noqa: BLE001 — KI: finish cells first
                errors.append((cell.tenant_id, e))
        bound: Dict[str, int] = {}
        for cell in dispatched:
            try:
                bound[cell.tenant_id] = cell.finish(now)
            except BaseException as e:  # noqa: BLE001 — re-raised after the round
                errors.append((cell.tenant_id, e))
        self.round_index += 1
        if errors:
            for _tid, err in errors:
                if not isinstance(err, Exception):
                    raise err  # KeyboardInterrupt/SystemExit as themselves
            tid, err = errors[0]
            raise RuntimeError(
                f"tenant {tid!r} failed its round (fleet state is "
                f"consistent; {len(errors)} cell(s) affected)"
            ) from err
        return bound

    def run(self, rounds: int, now_fn=None) -> None:
        """Drive ``rounds`` multiplexed rounds (logical time via
        ``now_fn(round_index)`` when given), then drain every cell's
        queued POSTs."""
        for r in range(rounds):
            self.run_round(now=now_fn(r) if now_fn is not None else None)
        self.drain()

    def drain(self) -> None:
        for cell in self.cells.values():
            cell.drain()

    def close(self) -> None:
        self.drain()
        for cell in self.cells.values():
            cell.api.close()

    # -- reporting ---------------------------------------------------------

    def tenant_summary(self, phase: str = "total") -> Dict[str, dict]:
        """Per-tenant round-latency percentiles (RoundTracer.summary
        per cell) — the per-tenant p50/p99 surface the soak and bench
        publish."""
        return {
            tid: cell.svc.tracer.summary(phase)
            for tid, cell in self.cells.items()
            if cell.svc.tracer is not None
        }
