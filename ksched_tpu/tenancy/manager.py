"""Tenant lifecycle policy: admission control, fairness, quarantine.

The policy layer is deliberately separate from the mechanics (batch.py
solves lanes, service.py drives cells) so it is unit-testable without a
solver in sight:

- **admission control** bounds what one warm process accepts: a tenant
  count cap, per-tenant graph-size caps (the pow2 bucket a tenant may
  occupy is priced in nodes/arcs), and a per-bucket lane cap so one
  popular shape bucket cannot crowd out the rest of the process.
- **fairness** is a rotation: the processing order of cells advances
  by one each round, so no tenant systematically polls/dispatches/
  completes last. (Within the stacked solve fairness is structural:
  per-lane budgets bound every lane's supersteps, and escalations run
  per-lane.)
- **quarantine** handles the pathological tenant: a lane whose warm
  attempts keep blowing their restart budget (or whose rounds keep
  ending NOOP) is moved into its OWN stacked group for a penalty
  window — it still solves, with its own budgets, but it can no longer
  stretch the shared program's while-loop. Chaos-injected faults never
  reach the batch at all (they raise at dispatch, before the lane
  parks), so quarantine is about *convergence* pathology, not faults.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..obs.metrics import get_registry


class AdmissionError(RuntimeError):
    """The process refused a tenant (capacity or size caps)."""


@dataclass
class AdmissionPolicy:
    #: hard cap on admitted tenants per process
    max_tenants: int = 64
    #: per-tenant graph-size caps (pow2 bucket extents)
    max_nodes: int = 1 << 20
    max_arcs: int = 1 << 22
    #: lanes one shape bucket may hold (a stacked program's width)
    max_lanes_per_bucket: int = 64
    #: consecutive bad rounds (warm-budget escapes or NOOPs) before a
    #: lane is quarantined into its own stacked group
    quarantine_after: int = 3
    #: rounds a quarantined lane stays solo before re-probation
    quarantine_rounds: int = 16


@dataclass
class TenantAccount:
    """Per-tenant policy state the manager maintains."""

    tenant_id: str
    bucket: Tuple[int, int]  # (n_cap, m_cap) admitted bucket
    rounds: int = 0
    noop_rounds: int = 0
    warm_escapes: int = 0
    bad_streak: int = 0
    quarantined_until: int = -1
    quarantine_count: int = 0
    extra: Dict[str, object] = field(default_factory=dict)

    @property
    def quarantined(self) -> bool:
        return self.quarantined_until > self.rounds


class TenantManager:
    """Admission + fairness + quarantine bookkeeping for one process.

    The service registers each admitted tenant's `LaneSolver` so the
    manager can flip its ``quarantined`` flag; everything else here is
    plain accounting."""

    def __init__(self, policy: Optional[AdmissionPolicy] = None) -> None:
        self.policy = policy or AdmissionPolicy()
        self.accounts: Dict[str, TenantAccount] = {}
        self._lanes: Dict[str, object] = {}  # tenant_id -> LaneSolver
        reg = get_registry()
        self._m_admitted = reg.gauge(
            "ksched_tenants", "tenants currently admitted"
        )
        self._m_rejected = reg.counter(
            "ksched_tenant_admission_rejected_total",
            "admission refusals, by why",
            labelnames=("reason",),
        )
        self._m_quarantined = reg.counter(
            "ksched_tenant_quarantines_total",
            "lanes moved into solo stacked groups",
        )

    # -- admission ---------------------------------------------------------

    def admit(
        self,
        tenant_id: str,
        est_nodes: int,
        est_arcs: int,
    ) -> TenantAccount:
        """Admit a tenant or raise AdmissionError. ``est_nodes``/
        ``est_arcs`` are the tenant's expected pow2 bucket extents (the
        bucket is priced at admission; a tenant that later outgrows its
        admitted caps shows up in ``oversized_tenants``)."""
        from ..utils import next_pow2

        if tenant_id in self.accounts:
            raise AdmissionError(f"tenant {tenant_id!r} already admitted")
        if len(self.accounts) >= self.policy.max_tenants:
            self._m_rejected.labels(reason="max_tenants").inc()
            raise AdmissionError(
                f"process at max_tenants={self.policy.max_tenants}"
            )
        if est_nodes > self.policy.max_nodes or est_arcs > self.policy.max_arcs:
            self._m_rejected.labels(reason="size_cap").inc()
            raise AdmissionError(
                f"tenant {tenant_id!r} bucket ({est_nodes} nodes, {est_arcs} "
                f"arcs) exceeds the per-tenant caps "
                f"({self.policy.max_nodes}, {self.policy.max_arcs})"
            )
        bucket = (max(next_pow2(est_nodes), 16), max(next_pow2(est_arcs), 16))
        peers = sum(1 for a in self.accounts.values() if a.bucket == bucket)
        if peers >= self.policy.max_lanes_per_bucket:
            self._m_rejected.labels(reason="bucket_full").inc()
            raise AdmissionError(
                f"bucket {bucket} already holds "
                f"{self.policy.max_lanes_per_bucket} lanes"
            )
        account = TenantAccount(tenant_id=tenant_id, bucket=bucket)
        self.accounts[tenant_id] = account
        self._m_admitted.set(len(self.accounts))
        return account

    def register_lane(self, tenant_id: str, lane) -> None:
        """Attach the admitted tenant's LaneSolver so quarantine
        decisions can flip its grouping (the lane usually does not
        exist yet at admit time — the service builds it after the
        admission check passes)."""
        if tenant_id not in self.accounts:
            raise AdmissionError(f"tenant {tenant_id!r} is not admitted")
        self._lanes[tenant_id] = lane

    def evict(self, tenant_id: str) -> None:
        self.accounts.pop(tenant_id, None)
        self._lanes.pop(tenant_id, None)
        self._m_admitted.set(len(self.accounts))

    # -- fairness ----------------------------------------------------------

    def order(self, round_index: int) -> List[str]:
        """Cell processing order for a round: admission order rotated
        by the round index, so every tenant periodically goes first
        (and last) in the poll/dispatch/complete phases."""
        ids = list(self.accounts)
        if not ids:
            return ids
        k = round_index % len(ids)
        return ids[k:] + ids[:k]

    # -- quarantine --------------------------------------------------------

    def note_round(
        self, tenant_id: str, noop: bool = False, warm_escape: bool = False
    ) -> None:
        """Attribute one finished round to a tenant and update its
        quarantine state. Called by the service after each cell's
        complete phase."""
        a = self.accounts.get(tenant_id)
        if a is None:
            return
        was_quarantined = a.quarantined
        a.rounds += 1
        if noop:
            a.noop_rounds += 1
        if warm_escape:
            a.warm_escapes += 1
        if noop or warm_escape:
            a.bad_streak += 1
        else:
            a.bad_streak = 0
        if (
            not was_quarantined
            and a.bad_streak >= self.policy.quarantine_after
        ):
            a.quarantined_until = a.rounds + self.policy.quarantine_rounds
            a.quarantine_count += 1
            a.bad_streak = 0
            self._m_quarantined.inc()
        lane = self._lanes.get(tenant_id)
        if lane is not None:
            lane.quarantined = a.quarantined

    def oversized_tenants(self) -> List[str]:
        """Tenants whose lanes now exceed their admitted bucket (the
        operator's resize-or-evict signal)."""
        out = []
        for tid, a in self.accounts.items():
            lane = self._lanes.get(tid)
            prev = getattr(lane, "_prev_src_host", None) if lane is not None else None
            if prev is not None and len(prev) > a.bucket[1]:
                out.append(tid)
        return out
