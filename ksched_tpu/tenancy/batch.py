"""Block-diagonal stacked-CSR batching: N tenant lanes, one program.

Independent flow components in a block-diagonal stack never interact:
lane i's nodes are its local ids offset by ``i * n_cap`` and its arc
slots by ``i * m_cap``, no arc crosses lanes, and every per-node
segment reduction stays inside its lane. The stacked arrays are that
flat block-diagonal problem reshaped ``[L, ...]`` — the shape the
compiled program (solver/jax_solver.stacked_solve_fn) consumes, with
per-lane convergence masks from jax's while-loop batching. Each lane's
solve is bit-identical to the lane solved alone (flows, potentials,
supersteps, telemetry rows — tests/test_tenancy.py).

Two classes:

- **LaneSolver** — the per-tenant FlowSolver front-end. It mirrors
  `JaxSolver`'s host-path warm policy exactly (journal-scoped warm
  restart, endpoint-masked warm flow, dirty-frontier price refit,
  budgeted restart escape), but instead of dispatching its own
  program it PARKS a lane request with the shared batcher and reads
  its lane's slice back at complete(). Escalations (price-war escape,
  cost-scaling fallback) run per-lane through the ordinary
  single-lane `_solve_mcmf` — a pathological tenant burns only its
  own budget, never another lane's wall-clock.
- **StackedBatcher** — the shared rendezvous. `flush()` groups parked
  lanes by (shape bucket, solve policy), pads each group to a pow2
  lane count (repeating a real lane — idempotent), stacks the arrays,
  and dispatches ONE program per group without synchronizing; lanes
  read (and block on) their own slices later.

Lanes use the legacy tightly-packed `build_csr_plan` layout (per-lane
host argsort on endpoint churn, cached by `plan_key` on clean rounds);
a stacked slot-stable plan is future work the docs note. Device-
resident tenants still get delta-sized h2d: the per-tenant
`DeviceResidentState` buffers are consumed directly and stacked
device-side.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..graph.device_export import FlowProblem, pad_problem, resident_solver_inputs
from ..obs.metrics import get_registry
from ..solver.base import FlowResult, FlowSolver, check_finite_costs, lower_bound_cost
from ..solver.jax_solver import (
    CsrPlan,
    _solve_mcmf,
    build_csr_plan,
    pad_lane_count,
    stacked_solve_fn,
)
from ..utils import next_pow2


class _LaneRequest:
    """One parked lane: the per-lane arrays of a stacked solve plus the
    slots the flush writes results into."""

    __slots__ = (
        "solver", "group_key", "dev_args", "flow0", "eps", "warm_p",
        "plan_args", "budget", "tel_cap", "use_warm_p", "outputs", "error",
    )

    def __init__(
        self, solver, group_key, dev_args, flow0, eps, warm_p, plan_args,
        budget, tel_cap, use_warm_p,
    ) -> None:
        self.solver = solver
        self.group_key = group_key
        self.dev_args = dev_args  # (cap, scaled cost, supply) per lane
        self.flow0 = flow0
        self.eps = eps
        self.warm_p = warm_p
        self.plan_args = plan_args  # 10-tuple, _solve_mcmf order
        self.budget = budget
        self.tel_cap = tel_cap
        self.use_warm_p = use_warm_p
        self.outputs: Optional[tuple] = None  # per-lane slices after flush
        self.error: Optional[BaseException] = None  # group dispatch failure


class StackedBatcher:
    """Shared across every tenant lane of one warm solver process.

    ``park()`` collects lane requests during the dispatch phase;
    ``flush()`` groups them by ``group_key`` — (n_cap, m_cap,
    use_warm_p, budget, telemetry cap, solo tag) — and dispatches one
    stacked program per group. Grouping by policy keeps the per-lane
    program IDENTICAL to the lane solved alone, which is what makes
    the bit-parity guarantee hold trivially; in steady state every
    same-bucket tenant is warm with the same budget, so a bucket is
    exactly one compiled call. A quarantined tenant's solo tag forces
    it into its own group: its lane still solves, but it can no longer
    stretch a shared program's wall-clock.
    """

    def __init__(
        self,
        alpha: int = 8,
        max_supersteps: int = 50_000,
        tighten_sweeps: int = 32,
    ) -> None:
        self.alpha = alpha
        self.max_supersteps = max_supersteps
        self.tighten_sweeps = tighten_sweeps
        self._parked: List[_LaneRequest] = []
        self.flushes = 0
        self.last_groups = 0
        self.last_lanes = 0
        reg = get_registry()
        self._m_flushes = reg.counter(
            "ksched_tenant_batch_flushes_total",
            "stacked-batch flushes (one per multi-tenant round with work)",
        )
        self._m_groups = reg.counter(
            "ksched_tenant_batch_groups_total",
            "stacked programs dispatched, by why the group exists",
            labelnames=("kind",),
        )
        self._m_lanes = reg.histogram(
            "ksched_tenant_batch_lanes",
            "lanes per stacked program (pre lane-count padding)",
            buckets=tuple(float(1 << i) for i in range(11)),
        )

    def park(self, req: _LaneRequest) -> _LaneRequest:
        self._parked.append(req)
        return req

    def ensure(self, req: _LaneRequest) -> None:
        """Make sure a parked lane has outputs: flush if the caller
        completes before the service-level flush (sync loops, the
        degradation ladder's synchronous fallback, tests). A lane whose
        GROUP failed to dispatch re-raises that failure as a
        degradable RuntimeError — the tenant's own ladder steps down
        to its private jax/cpu_ref rungs, and the failure never
        propagates to lanes in other groups."""
        if req.outputs is None and req.error is None:
            self.flush()
        if req.error is not None:
            raise RuntimeError(
                f"stacked batch dispatch failed for group {req.group_key}: "
                f"{req.error}"
            ) from req.error
        if req.outputs is None:
            raise RuntimeError("lane request was never parked with this batcher")

    def flush(self) -> int:
        """Group parked lanes and dispatch one stacked program per
        group WITHOUT synchronizing (jax async dispatch): the
        multi-tenant loop posts the previous round's bindings while
        the device crunches, and each lane blocks only when its own
        complete() reads its slice. Returns the number of programs
        dispatched."""
        import jax.numpy as jnp

        parked, self._parked = self._parked, []
        if not parked:
            return 0
        groups: Dict[tuple, List[_LaneRequest]] = {}
        for req in parked:
            groups.setdefault(req.group_key, []).append(req)
        for key, reqs in groups.items():
            # per-GROUP fault barrier: a dispatch failure (a compile
            # error, device OOM on a new bucket's first jit, a shape
            # bug) marks only this group's lanes failed — their
            # complete() raises a degradable error and each affected
            # tenant's ladder steps down; other groups still solve,
            # and the fleet's split-round latches always clear
            try:
                self._flush_group(key, reqs, jnp)
            except Exception as e:  # noqa: BLE001 — re-raised per lane
                for req in reqs:
                    req.error = e
        self.flushes += 1
        self.last_groups = len(groups)
        self.last_lanes = len(parked)
        self._m_flushes.inc()
        return len(groups)

    def _flush_group(self, key, reqs, jnp) -> None:
        lane_count = len(reqs)
        padded = pad_lane_count(lane_count)
        # idempotent lane padding: repeat a real lane; its outputs
        # are computed and discarded, so tenant churn inside a lane
        # bucket reuses one executable instead of recompiling
        lanes = reqs + [reqs[0]] * (padded - lane_count)
        first = reqs[0]

        def stack(pick):
            # host lanes stack on host first (ONE upload per
            # column); device-resident lanes stack device-side
            cols = [pick(r) for r in lanes]
            if all(isinstance(c, (np.ndarray, np.generic)) for c in cols):
                return jnp.asarray(np.stack(cols))
            return jnp.stack([jnp.asarray(c) for c in cols])

        args = [
            stack(lambda r, i=i: r.dev_args[i]) for i in range(3)
        ]
        args.append(stack(lambda r: r.flow0))
        args.append(stack(lambda r: r.eps))
        if first.use_warm_p:
            args.append(stack(lambda r: r.warm_p))
        args.extend(
            stack(lambda r, i=i: r.plan_args[i]) for i in range(10)
        )
        fn = stacked_solve_fn(
            alpha=self.alpha,
            max_supersteps=first.budget,
            tighten_sweeps=self.tighten_sweeps,
            telemetry_cap=first.tel_cap,
            use_warm_p=first.use_warm_p,
        )
        out = fn(*args)
        for i, req in enumerate(reqs):
            req.outputs = tuple(o[i] for o in out)
        self._m_groups.labels(
            kind="solo" if key[-1] is not None else (
                "warm" if first.use_warm_p else "fresh"
            )
        ).inc()
        self._m_lanes.observe(lane_count)


class LaneSolver(FlowSolver):
    """A tenant's lane into the shared stacked solve.

    The warm policy is `JaxSolver`'s, verbatim: node potentials always
    carry (the batched program REFITS them around the journal-dirty
    frontier via ``use_warm_p``), carried FLOW survives only rounds
    whose journal re-wired no endpoints (``plan_key`` match — the
    journal-scoped rule r12 measured), and a warm attempt that blows
    ``restart_budget`` escapes to a fresh restart, then cost-scaling —
    both escalations run per-lane through the single-lane program, so
    one tenant's price war cannot extend another tenant's round.

    ``bucket_floor=(n, m)`` pads this tenant's problems up to at least
    that pow2 bucket (graph/device_export.pad_problem). Bucket choice
    is strictly a per-tenant property: a lane's bucket never depends
    on which co-tenants share the process, so a tenant's solve in the
    multi-tenant batch is bit-identical to the same tenant solved in
    an isolated process with the same configuration.
    """

    def __init__(
        self,
        batcher: StackedBatcher,
        tenant: str = "",
        warm_start: bool = True,
        warm_potentials: bool = True,
        restart_budget: Optional[int] = None,
        journal_scoped_warm: bool = True,
        telemetry: Optional[int] = None,
        bucket_floor: Optional[Tuple[int, int]] = None,
    ) -> None:
        self.batcher = batcher
        self.tenant = tenant
        self.warm_start = warm_start
        self.warm_potentials = warm_potentials
        self.restart_budget = restart_budget
        self.journal_scoped_warm = journal_scoped_warm
        self.telemetry = telemetry
        self.bucket_floor = bucket_floor
        #: manager-controlled: True forces this lane into its own
        #: stacked group (its pathology stops sharing wall-clock)
        self.quarantined = False
        self._prev: Optional[np.ndarray] = None
        self._prev_dev = None
        self._prev_p = None
        self._prev_src_dev = None
        self._prev_dst_dev = None
        self._prev_src_host: Optional[np.ndarray] = None
        self._prev_dst_host: Optional[np.ndarray] = None
        self._plan: Optional[CsrPlan] = None
        self._plan_dev: Optional[tuple] = None
        self._plan_key = None
        self._key_solved = None
        self.last_supersteps = 0
        self.last_telemetry = None
        self.last_warm_scope = "cold"
        #: True when the LAST solve's warm attempt blew its budget and
        #: escaped (the manager's quarantine signal)
        self.last_warm_escape = False
        self.warm_escapes_total = 0

    def reset(self) -> None:
        self._prev = None
        self._prev_dev = None
        self._prev_p = None
        self._prev_src_dev = None
        self._prev_dst_dev = None
        self._prev_src_host = None
        self._prev_dst_host = None
        self._key_solved = None

    # -- lane prep ---------------------------------------------------------

    def _bucket(self, n: int, m: int) -> Tuple[int, int]:
        n_cap = max(next_pow2(n), 16)
        m_cap = max(next_pow2(m), 16)
        if self.bucket_floor is not None:
            n_cap = max(n_cap, next_pow2(self.bucket_floor[0]))
            m_cap = max(m_cap, next_pow2(self.bucket_floor[1]))
        return n_cap, m_cap

    def _plan_for(self, src: np.ndarray, dst: np.ndarray, n: int, plan_key=None) -> tuple:
        """Per-lane legacy CSR plan, cached on the endpoint generation
        key exactly like JaxSolver._plan_for (clean rounds skip the
        O(M) scans entirely)."""
        import jax.numpy as jnp

        plan = self._plan
        if plan_key is not None and self._plan_key == plan_key and plan is not None:
            return self._plan_dev
        if plan is None or len(plan.src) != len(src) or len(plan.node_first) != n or plan_key is not None or not (
            np.array_equal(plan.src, src) and np.array_equal(plan.dst, dst)
        ):
            plan = build_csr_plan(src, dst, n)
            self._plan = plan
            self._plan_dev = tuple(
                jnp.asarray(x)
                for x in (
                    plan.s_arc, plan.s_sign, plan.s_src, plan.s_dst,
                    plan.s_segstart, plan.s_isstart, plan.inv_order,
                    plan.node_first, plan.node_last, plan.node_nonempty,
                )
            )
        self._plan_key = plan_key
        return self._plan_dev

    # -- FlowSolver --------------------------------------------------------

    def solve_async(self, problem: FlowProblem):
        """Build this round's lane request and PARK it with the shared
        batcher. The service loop flushes once for all tenants; a
        caller that completes first triggers the flush itself
        (StackedBatcher.ensure), so synchronous single-tenant use works
        unchanged."""
        orig = problem
        m0 = len(problem.src)
        if m0 == 0 or problem.num_arcs == 0:
            if (problem.excess > 0).any():
                raise RuntimeError("infeasible flow problem: supply but no arcs")
            return (orig, None, None)
        check_finite_costs(problem)
        n_cap, m_cap = self._bucket(problem.num_nodes, m0)
        resident = (
            getattr(problem, "d_cap", None) is not None
            and n_cap == problem.num_nodes
            and m_cap == m0
        )
        if n_cap != problem.num_nodes or m_cap != m0:
            problem = pad_problem(problem, n_cap, m_cap)
        src = np.asarray(problem.src, np.int32)
        dst = np.asarray(problem.dst, np.int32)
        max_cost = int(np.abs(problem.cost).max()) if m_cap else 0
        if max_cost * n_cap >= (1 << 30):
            raise OverflowError(
                f"scaled costs overflow int32: max|cost|={max_cost} at {n_cap} "
                "nodes; rescale cost-model outputs or shrink the lane bucket"
            )
        plan_key = getattr(problem, "plan_key", None)
        plan_args = self._plan_for(src, dst, n_cap, plan_key=plan_key)

        from ..obs import soltel

        tel_cap = soltel.resolve_cap(self.telemetry)
        # journal-scoped warm restart: identical rule to JaxSolver
        keep_flow = True
        if self.journal_scoped_warm and plan_key is not None:
            keep_flow = (
                self._key_solved is not None and plan_key == self._key_solved
            )
        if resident:
            dev_args, flow0, warm = resident_solver_inputs(
                problem, self._prev_dev, self._prev_src_dev,
                self._prev_dst_dev, self.warm_start and keep_flow,
            )
        else:
            cap = problem.cap.astype(np.int32)
            supply = problem.excess.astype(np.int32)
            cost = problem.cost.astype(np.int32) * np.int32(n_cap)
            dev_args = (cap, cost, supply)
            warm = (
                self.warm_start
                and keep_flow
                and self._prev is not None
                and len(self._prev) == m_cap
                and self._prev_src_host is not None
                and len(self._prev_src_host) == m_cap
            )
            flow0 = np.zeros(m_cap, dtype=np.int32)
            if warm:
                same = (self._prev_src_host == src) & (self._prev_dst_host == dst)
                if self.journal_scoped_warm and plan_key is None and not same.all():
                    warm = False
                else:
                    flow0 = np.where(
                        same, np.minimum(self._prev, cap), 0
                    ).astype(np.int32)
        had_state = self._prev is not None or self._prev_dev is not None
        self.last_warm_scope = (
            "warm" if warm else ("fresh" if had_state else "cold")
        )
        warm_p_ok = (
            self.warm_potentials
            and warm
            and self._prev_p is not None
            and self._prev_p.shape[0] == n_cap
        )
        budget = min(4096, self.batcher.max_supersteps)
        if warm and self.restart_budget is not None:
            budget = min(budget, self.restart_budget)
        group_key = (
            n_cap, m_cap, warm_p_ok, budget, tel_cap,
            self.tenant if self.quarantined else None,
        )
        req = self.batcher.park(
            _LaneRequest(
                solver=self,
                group_key=group_key,
                dev_args=dev_args,
                flow0=flow0,
                eps=np.int32(1),
                warm_p=self._prev_p if warm_p_ok else None,
                plan_args=plan_args,
                budget=budget,
                tel_cap=tel_cap,
                use_warm_p=warm_p_ok,
            )
        )
        cold = (np.zeros(m_cap, dtype=np.int32), max(1, max_cost * n_cap))
        return (orig, req, (problem, cold, warm, resident))

    def _lane_attempt(self, req, flow0, eps, budget):
        """A per-lane escalation attempt (fresh restart / cost-scaling)
        through the ordinary single-lane program — exactly the attempts
        JaxSolver.complete runs, so an escaped lane's result is still
        bit-identical to the lane solved alone."""
        import jax.numpy as jnp

        return _solve_mcmf(
            *(jnp.asarray(a) for a in req.dev_args),
            jnp.asarray(flow0),
            jnp.asarray(np.int32(eps)),
            *req.plan_args,
            alpha=self.batcher.alpha,
            max_supersteps=budget,
            tighten_sweeps=self.batcher.tighten_sweeps,
            telemetry_cap=req.tel_cap,
        )

    def complete(self, pending) -> FlowResult:
        from ..obs import soltel

        orig, req, rest = pending
        if req is None:
            self.last_telemetry = None
            self.last_warm_escape = False
            return FlowResult(
                flow=np.zeros(len(orig.src), dtype=np.int64),  # kschedlint: host-only (FlowResult contract is int64)
                objective=0, iterations=0,
            )
        problem, (f0_cold, eps_cold), warm, resident = rest
        with soltel.stall_scope(self.tenant or None):
            return self._complete_scoped(
                orig, req, problem, f0_cold, eps_cold, warm, resident
            )

    def _complete_scoped(self, orig, req, problem, f0_cold, eps_cold, warm, resident):
        from ..obs import soltel

        self.batcher.ensure(req)
        tel_cap = req.tel_cap
        tel_buf = None
        if tel_cap:
            flow, p, steps, converged, p_overflow, tel_buf = req.outputs
        else:
            flow, p, steps, converged, p_overflow = req.outputs
        spent = int(steps)
        self.last_warm_escape = False
        warm_failed = warm and not (bool(converged) and not bool(p_overflow))
        if warm_failed and not bool(converged):
            self.last_warm_escape = True
            self.warm_escapes_total += 1
            soltel.warm_price_war(
                "lane",
                supersteps=int(steps),
                budget=req.budget,
                escaped_to=(
                    "fresh_restart" if self.restart_budget is not None
                    else "cost_scaling"
                ),
                tel=(
                    soltel.decode(
                        tel_buf, int(steps), tel_cap, "lane", req.budget,
                        converged=False,
                        nodes=problem.num_nodes, arcs=len(problem.src),
                    )
                    if tel_buf is not None
                    else None
                ),
            )
        if warm_failed and self.restart_budget is not None:
            out = self._lane_attempt(
                req, f0_cold, 1, min(4096, self.batcher.max_supersteps)
            )
            if tel_cap:
                flow, p, steps, converged, p_overflow, tel_buf = out
            else:
                flow, p, steps, converged, p_overflow = out
            spent += int(steps)
        if not (bool(converged) and not bool(p_overflow)):
            out = self._lane_attempt(
                req, f0_cold, eps_cold, self.batcher.max_supersteps
            )
            if tel_cap:
                flow, p, steps, converged, p_overflow, tel_buf = out
            else:
                flow, p, steps, converged, p_overflow = out
            spent += int(steps)
        self.last_supersteps = spent
        self.last_telemetry = (
            soltel.decode(
                tel_buf, int(steps), tel_cap, "lane",
                self.batcher.max_supersteps,
                converged=bool(converged) and not bool(p_overflow),
                nodes=problem.num_nodes, arcs=len(problem.src),
            )
            if tel_buf is not None
            else None
        )
        if bool(p_overflow) or not bool(converged):
            self.reset()
        if bool(p_overflow):
            raise OverflowError("push-relabel potentials approached int32 range")
        if not bool(converged):
            tel = self.last_telemetry
            raise soltel.SolverStallError(
                f"lane did not converge within {self.batcher.max_supersteps} "
                "supersteps; the flow problem may be infeasible",
                reason=soltel.detect_stall(tel) if tel is not None else None,
                telemetry=tel,
            )
        flow_np = np.asarray(flow)
        if self.warm_start:
            self._prev = flow_np.astype(np.int32)
            self._prev_dev = flow if resident else None
            self._prev_src_dev = problem.d_src if resident else None
            self._prev_dst_dev = problem.d_dst if resident else None
            self._prev_src_host = np.asarray(problem.src, np.int32)
            self._prev_dst_host = np.asarray(problem.dst, np.int32)
            self._key_solved = getattr(problem, "plan_key", None)
            self._prev_p = p
        # the FlowResult is for the CALLER's (unpadded) problem: lane
        # padding arcs are zero-capacity and carry zero flow, so the
        # real prefix is the whole answer
        m0 = len(orig.src)
        flow_out = flow_np[:m0]
        objective = int(
            (flow_out.astype(np.int64) * orig.cost.astype(np.int64)).sum()  # kschedlint: host-only (int64 objective math on host)
        ) + lower_bound_cost(orig)
        return FlowResult(flow=flow_out.astype(np.int64), objective=objective, iterations=spent)  # kschedlint: host-only (FlowResult contract is int64)

    def solve(self, problem: FlowProblem) -> FlowResult:
        return self.complete(self.solve_async(problem))


# Level-3 registry consumer hook: the batched cell solve dispatches the
# lane-stacked program owned by solver/jax_solver.py
from ..analysis.program_registry import declare_programs as _declare_programs

_declare_programs(__name__, "stacked_solve")
