"""ksched_tpu.tenancy: scheduler-as-a-service — one warm solver
process, N independent cells.

The ROADMAP's "millions of users" story is N independent clusters
multiplexed through one warm device-resident solver process. A flow
network is block-diagonal across tenants — independent components never
interact — so same-bucket tenants batch through ONE compiled stacked
program (solver/jax_solver.stacked_solve_fn) while everything that must
stay isolated stays isolated: graph state, warm flow/potentials,
restart budgets, the degradation ladder, chaos faults, accounting, and
flight recordings are all per-tenant.

Three layers:

- **batch** — `LaneSolver` (the per-tenant FlowSolver front-end,
  mirroring JaxSolver's journal-scoped warm policy bit for bit) and
  `StackedBatcher` (parks lanes, groups them by shape bucket + solve
  policy, dispatches one stacked program per group, escalates failed
  lanes per-lane);
- **manager** — `TenantManager`: admission control, pow2 bucket/lane
  assignment, fairness rotation, and quarantine for tenants whose
  lanes repeatedly blow their budgets;
- **service** — `MultiTenantService`: N `SchedulerService` cells (one
  ClusterAPI adapter each) driven through a four-phase round — dispatch
  every cell, flush the shared batch, post the previous round's
  bindings per tenant inside the batched-solve window, complete every
  cell — with per-tenant round deadlines, degradation ladders, scoped
  metrics (`tenant` label), flight recorders, and soltel stall
  attribution.

See docs/multitenancy.md for the lifecycle and the isolation
guarantees, and tests/test_tenancy.py for the bit-parity suite.
"""

from .batch import LaneSolver, StackedBatcher
from .manager import AdmissionError, AdmissionPolicy, TenantManager
from .service import MultiTenantService, TenantCell

__all__ = [
    "AdmissionError",
    "AdmissionPolicy",
    "LaneSolver",
    "MultiTenantService",
    "StackedBatcher",
    "TenantCell",
    "TenantManager",
]
