"""JAX platform hygiene.

The container's sitecustomize registers a tunneled-TPU PJRT plugin at
interpreter boot; when the tunnel is down, merely initializing that
backend hangs forever — even under JAX_PLATFORMS=cpu, because jax may
have been imported (capturing the ambient platform list) before the
caller could override it. This helper forces a clean CPU-only backend
set; it must run before the first jax backend is materialized.
"""

from __future__ import annotations


def force_cpu_platform() -> None:
    import jax
    import jax._src.xla_bridge as xb

    jax.config.update("jax_platforms", "cpu")
    for plat in list(getattr(xb, "_backend_factories", {})):
        if plat != "cpu":
            xb._backend_factories.pop(plat, None)
    # Popping the factories also removes "tpu" from xb.known_platforms(),
    # which would make importing jax.experimental.pallas.tpu blow up when
    # it registers its TPU lowering rules. Keep the name known via the
    # alias table — registering lowerings for an uninstantiable platform
    # is harmless, and the Pallas interpreter path needs the import.
    if hasattr(xb, "_platform_aliases"):
        xb._platform_aliases.setdefault("tpu", "tpu")
