"""JAX platform hygiene.

The container's sitecustomize registers a tunneled-TPU PJRT plugin at
interpreter boot; when the tunnel is down, merely initializing that
backend hangs forever — even under JAX_PLATFORMS=cpu, because jax may
have been imported (capturing the ambient platform list) before the
caller could override it. This helper forces a clean CPU-only backend
set; it must run before the first jax backend is materialized.
"""

from __future__ import annotations


def force_cpu_platform() -> None:
    import jax
    import jax._src.xla_bridge as xb

    jax.config.update("jax_platforms", "cpu")
    for plat in list(getattr(xb, "_backend_factories", {})):
        if plat != "cpu":
            xb._backend_factories.pop(plat, None)
