from .backoff import ExpBackoff
from .ids import (
    IDGenerator,
    SlotAllocator,
    equiv_class_from_bytes,
    fnv1a_64,
    job_id_from_string,
    next_pow2,
    rand_uint64,
    resource_id_from_string,
    rng,
    seed_rng,
)
from .maps import JobMap, ResourceMap, ResourceStatus, TaskMap
from .platform import force_cpu_platform

__all__ = [
    "ExpBackoff",
    "IDGenerator",
    "SlotAllocator",
    "equiv_class_from_bytes",
    "fnv1a_64",
    "job_id_from_string",
    "next_pow2",
    "rand_uint64",
    "resource_id_from_string",
    "rng",
    "seed_rng",
    "JobMap",
    "ResourceMap",
    "ResourceStatus",
    "TaskMap",
    "force_cpu_platform",
]
