"""ID generation, hashing, and parsing utilities.

Reference: pkg/util/util.go:12-86 and pkg/util/idgenerator/id_generator.go.
TaskID / JobID / ResourceID / EquivClass are plain Python ints throughout
(uint64-valued); descriptors carry them stringified in their uuid/job_id
fields exactly like the reference carries stringified uint64s.
"""

from __future__ import annotations

import random
from collections import deque
from typing import Deque, Optional

_FNV_OFFSET = 14695981039346656037
_FNV_PRIME = 1099511628211
_U64 = (1 << 64) - 1


def fnv1a_64(data: bytes) -> int:
    """FNV-1a 64-bit hash (reference: pkg/util/util.go:12-16 uses FNV to
    derive equivalence-class ids from byte strings)."""
    h = _FNV_OFFSET
    for b in data:
        h ^= b
        h = (h * _FNV_PRIME) & _U64
    return h


def equiv_class_from_bytes(data: bytes) -> int:
    return fnv1a_64(data)


_rng = random.Random()


def seed_rng(seed: int) -> None:
    """Determinism hook for tests (reference: pkg/util/util.go:52-58)."""
    _rng.seed(seed)


def rng() -> random.Random:
    """The framework's global seedable RNG (reference: pkg/util/util.go:
    52-58 SeedRNGWithInt — the determinism hook tests rely on)."""
    return _rng


def rand_uint64() -> int:
    """Uniform uint64 (the reference's RandUint64 at pkg/util/util.go:68-71
    sums two uint32s and is biased; we fix that here)."""
    return _rng.getrandbits(64)


def resource_id_from_string(s: str) -> int:
    """Parse a stringified uint64 resource id (reference: pkg/util/util.go:17-26)."""
    return int(s)


def job_id_from_string(s: str) -> int:
    """Parse a stringified uint64 job id (reference: pkg/util/util.go:28-36)."""
    return int(s)


class IDGenerator:
    """Sequential unique ids with free-list recycling (reference:
    pkg/util/idgenerator/id_generator.go:13-76). Dense, stable integer ids
    are load-bearing in the TPU build: they index directly into the flat
    device arrays."""

    def __init__(self, start: int = 1):
        self._next = start
        self._free: Deque[int] = deque()

    def take(self) -> int:
        if self._free:
            return self._free.popleft()
        nid = self._next
        self._next += 1
        return nid

    def give_back(self, id_: int) -> None:
        self._free.append(id_)

    @property
    def high_water_mark(self) -> int:
        """One past the largest id ever handed out; the dense array length."""
        return self._next


class SlotAllocator(IDGenerator):
    """IDGenerator starting at 0, for dense array-slot assignment."""

    def __init__(self) -> None:
        super().__init__(start=0)


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (>= 1). Solver arrays grow by doubling
    so XLA sees few distinct shapes (SURVEY.md: static-shape padding)."""
    p = 1
    while p < n:
        p <<= 1
    return p
