"""Typed object maps for resources, jobs, and tasks.

Reference: pkg/types/types.go:38-294 (RWMutex-guarded ResourceMap/JobMap/
TaskMap) and pkg/types/resourcestatus/resourcestatus.go:22-27. The core
scheduling loop is single-threaded by design (reference:
scheduling/flow/placement/solver.go:59), so these are thin dict wrappers
kept for API parity; cross-thread use should add external locking.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, Generic, Optional, TypeVar

from ..data import JobDescriptor, ResourceDescriptor, ResourceTopologyNodeDescriptor, TaskDescriptor

V = TypeVar("V")


@dataclass
class ResourceStatus:
    """Pairs a resource descriptor with its topology node (reference:
    pkg/types/resourcestatus/resourcestatus.go:22-27)."""

    descriptor: ResourceDescriptor
    topology_node: Optional[ResourceTopologyNodeDescriptor] = None
    endpoint_uri: str = ""
    #: None = never heartbeated. A numeric sentinel (the reference's 0)
    #: would swallow a genuine beat at t=0 under an injected clock.
    last_heartbeat: Optional[float] = None


class _TypedMap(Generic[V]):
    def __init__(self) -> None:
        self._m: Dict[int, V] = {}
        self._lock = threading.RLock()

    def find(self, key: int) -> Optional[V]:
        with self._lock:
            return self._m.get(key)

    def insert(self, key: int, value: V) -> None:
        with self._lock:
            self._m[key] = value

    def insert_if_not_present(self, key: int, value: V) -> bool:
        with self._lock:
            if key in self._m:
                return False
            self._m[key] = value
            return True

    def remove(self, key: int) -> None:
        with self._lock:
            self._m.pop(key, None)

    def contains(self, key: int) -> bool:
        with self._lock:
            return key in self._m

    def items(self):
        """Snapshot of (key, value) pairs under the lock."""
        with self._lock:
            return list(self._m.items())

    def unsafe_get(self) -> Dict[int, V]:
        """Direct access to the backing dict; caller is responsible for
        not mutating concurrently (reference: types.go UnsafeGet)."""
        return self._m

    # The warm-restore manifest (runtime/checkpoint.save_warm_manifest)
    # pickles the maps through the scheduler core; the lock is process
    # state, not data, and RLocks don't pickle.
    def __getstate__(self):
        state = dict(self.__dict__)
        state.pop("_lock", None)
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.RLock()

    def __len__(self) -> int:
        return len(self._m)


class ResourceMap(_TypedMap[ResourceStatus]):
    pass


class JobMap(_TypedMap[JobDescriptor]):
    pass


class TaskMap(_TypedMap[TaskDescriptor]):
    pass
