"""Exponential backoff with jitter and a retry budget.

One policy object shared by every layer that retries transient faults
(the HTTP adapter's binding POSTs and watch loops, podgen's pod
creation): Firmament/Borg-style production schedulers treat control-
plane blips as normal weather, and the retry cadence must be bounded
(budgeted) and de-synchronized (jittered) so a recovering API server
is not stampeded by every client retrying on the same beat.
"""

from __future__ import annotations

import random
from typing import Optional


class ExpBackoff:
    """A budgeted exponential-backoff schedule.

    ``next_delay()`` returns the wait before the next retry, or ``None``
    once the retry budget is exhausted. Delays grow as
    ``base_s * factor**attempt`` capped at ``max_s``, each scaled by a
    uniform jitter in ``[1 - jitter, 1 + jitter]``. Pass a seeded
    ``random.Random`` as ``rng`` for deterministic schedules (the chaos
    soak does); the default draws from a private unseeded stream so
    concurrent backoffs de-correlate.
    """

    def __init__(
        self,
        base_s: float = 0.05,
        max_s: float = 2.0,
        factor: float = 2.0,
        jitter: float = 0.25,
        max_retries: int = 4,
        rng: Optional[random.Random] = None,
    ) -> None:
        if base_s <= 0 or factor < 1.0 or not 0.0 <= jitter < 1.0:
            raise ValueError(
                f"bad backoff parameters: base_s={base_s} factor={factor} "
                f"jitter={jitter}"
            )
        self.base_s = base_s
        self.max_s = max_s
        self.factor = factor
        self.jitter = jitter
        self.max_retries = max_retries
        self.rng = rng if rng is not None else random.Random()
        self.attempt = 0

    def reset(self) -> None:
        self.attempt = 0

    def delay_for(self, attempt: int) -> float:
        """The jittered delay for a given attempt index, budget-free.
        The shared growth/jitter formula for unbounded failure-streak
        backoff (the watch loops); ``next_delay`` is the budgeted view."""
        raw = min(self.max_s, self.base_s * (self.factor ** attempt))
        if self.jitter:
            raw *= 1.0 + self.jitter * (2.0 * self.rng.random() - 1.0)
        return raw

    def next_delay(self) -> Optional[float]:
        """The wait before the next retry, or None when the budget is
        spent. Advances the attempt counter."""
        if self.attempt >= self.max_retries:
            return None
        delay = self.delay_for(self.attempt)
        self.attempt += 1
        return delay
