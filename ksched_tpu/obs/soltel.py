"""Solver-interior telemetry: per-superstep device counters, decoded.

PR 5 instrumented everything AROUND the solve; the solve itself — the
thing the <10 ms p50 target lives or dies on — stayed a black box once
jit'd: a `backend_solve` span carried one superstep COUNT and nothing
about the convergence shape inside it. This module is the host side of
the solver-interior instrument: every compiled general-graph backend
(scan-CSR `jax_solver`, the `mega` Pallas kernel, `layered`, `ell`,
and the sharded solver) can emit a fixed-size, superstep-indexed
telemetry buffer alongside its flows, written ON DEVICE (carried
through the solve loop / written from inside the `pallas_call`), with
zero extra host syncs — the buffer rides back with the flow fetch —
and bit-identical flows when disabled (the counters read state the
superstep already computed; they never feed back into it).

Buffer layout (`SOLTEL_COLS`, int32 `[cap, SOLTEL_WIDTH]`):

| col | name      | meaning (per executed superstep)                     |
|-----|-----------|------------------------------------------------------|
| 0   | eps       | the cost-scaling phase's eps at this superstep       |
| 1   | active    | nodes with positive excess entering the superstep    |
| 2   | excess    | total positive excess (units still to discharge)     |
| 3   | pushed    | flow units moved by this superstep's maximal pushes  |
| 4   | relabels  | nodes relabeled (active, nothing pushed)             |
| 5   | saturated | forward residual arcs at zero residual               |
| 6   | work      | admissible residual entries (the discharge frontier) |
| 7   | —         | reserved (padding keeps the row pow2-wide)           |

Rows are written RING-STYLE at `step % cap`, so when a solve exceeds
the buffer the LAST `cap` supersteps survive — exactly the window a
stall post-mortem needs. Truncation is explicit: `SolveTelemetry.
truncated` + `start_step` say precisely which supersteps the rows
cover; nothing is silently dropped.

Host side:

- `decode()` unrolls the ring into superstep order;
- `publish()` feeds the registry (`ksched_solve_supersteps{backend}`,
  per-eps-phase superstep histograms, pushed/relabeled totals) and —
  when a SpanTracer is active — synthesizes per-superstep child spans
  under the open `backend_solve` span, so a captured Perfetto trace
  shows the convergence shape with eps/active/excess args per step;
- `detect_stall()` is the stall/divergence detector: K supersteps
  without excess decrease, an eps plateau, or superstep-cap proximity
  each yield a structured reason dict;
- `note_stall()` keeps a bounded ring of structured stall events that
  `obs.flight.FlightRecorder.dump` embeds in every flight dump
  (`solver_stalls`), and `failure_reason()` is what the degradation
  ladder calls to turn a rung failure into a structured reason (with
  the final `SOLTEL_TAIL` supersteps of telemetry attached) instead of
  a bare timeout string.

`KSCHED_SOLTEL=0` (or `set_enabled(False)`) resolves every solver's
default telemetry capacity to 0; the traced program is then
hash-identical to the pre-telemetry baseline (asserted by the jaxpr
contracts in tests/test_static_analysis.py) — no cost when off.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from .metrics import get_registry, log_buckets

#: counter taxonomy; column 7 is reserved padding (pow2-wide rows)
SOLTEL_COLS = (
    "eps", "active", "excess", "pushed", "relabels", "saturated", "work",
)
SOLTEL_WIDTH = 8

#: default ring capacity (supersteps kept); solvers may clamp it down
#: (the megakernel bounds the buffer to one VMEM tile)
SOLTEL_DEFAULT_CAP = 512

#: supersteps of telemetry attached to structured stall/failure events
SOLTEL_TAIL = 32

#: window for the no-excess-decrease stall rule
SOLTEL_STALL_WINDOW = 64

#: superstep-count histogram bounds (1 .. 131072, factor 2)
COUNT_BUCKETS = log_buckets(1.0, 1 << 17, 2.0)

_enabled = os.environ.get("KSCHED_SOLTEL", "1").lower() not in (
    "0", "false", "off"
)


def set_enabled(on: bool) -> None:
    """Enable/disable solver-interior telemetry process-wide. Solvers
    resolve their capacity PER SOLVE via `resolve_cap`, so flipping
    this takes effect on the next solve (at the cost of one recompile
    per toggled executable)."""
    global _enabled
    _enabled = bool(on)


def enabled() -> bool:
    return _enabled


def resolve_cap(override: Optional[int]) -> int:
    """The telemetry buffer capacity a solver should use: an explicit
    constructor override wins; otherwise the module default — 0 when
    soltel is disabled OR all of obs is (`KSCHED_OBS=0` turns the
    whole subsystem off, solver interior included), which keeps the
    traced program identical to the pre-telemetry baseline."""
    if override is not None:
        return max(0, int(override))
    from .metrics import enabled as obs_enabled

    return SOLTEL_DEFAULT_CAP if (_enabled and obs_enabled()) else 0


# ---------------------------------------------------------------------------
# device-side helpers (pure jnp; traced into each backend's jit)
# ---------------------------------------------------------------------------
#
# One implementation of the ring scheme for every XLA backend — the
# counter SEMANTICS per column live in each solver (they read different
# per-backend intermediates), but the row layout and the ring write are
# shared here so they cannot drift. The mega Pallas kernel keeps its
# own write (a lane-iota construct; jnp.stack of scalars doesn't lower
# there). jax is imported lazily: obs stays importable host-only.


def device_rows_iota(cap: int):
    """[cap, 1] row-index iota, hoisted out of the solve loop."""
    import jax.numpy as jnp
    from jax import lax

    return lax.broadcasted_iota(jnp.int32, (cap, 1), 0)


def device_row(eps, active, excess, pushed, relabels, saturated, work):
    """One SOLTEL_COLS telemetry row from traced scalars (col 7 pad)."""
    import jax.numpy as jnp

    return jnp.stack(
        [eps, active, excess, pushed, relabels, saturated, work,
         jnp.int32(0)]
    ).astype(jnp.int32)


def device_ring_write(tel, steps, row, cap: int, rows_iota):
    """Ring write at `steps % cap` as a masked elementwise select, NOT
    a dynamic_update_slice: a DUS-written while-loop carry defeats XLA
    CPU's in-place buffer reuse for the OTHER carries (flow/potentials
    get copied every iteration — measured ~0.8 ms/superstep at 131k
    entries); the elementwise form updates in place."""
    import jax.numpy as jnp

    idx = jnp.remainder(steps, jnp.int32(cap))
    return jnp.where(rows_iota == idx, row[None, :], tel)


# ---------------------------------------------------------------------------
# decode
# ---------------------------------------------------------------------------


@dataclass
class SolveTelemetry:
    """One solve's decoded telemetry, rows in superstep order."""

    backend: str
    steps: int  # supersteps the solve executed
    budget: int  # the superstep cap the solve ran under
    cap: int  # ring capacity (rows the buffer could hold)
    truncated: bool  # steps > cap: only the final `cap` rows survive
    start_step: int  # superstep index of rows[0]
    rows: np.ndarray  # int32 [kept, SOLTEL_WIDTH]
    converged: bool = True
    nodes: int = 0
    arcs: int = 0

    def col(self, name: str) -> np.ndarray:
        return self.rows[:, SOLTEL_COLS.index(name)]

    def phases(self) -> List[Dict[str, int]]:
        """Per-eps-phase superstep counts, from eps transitions in the
        kept rows: [{"eps": e, "supersteps": k}, ...] oldest first.
        Vectorized — publish() runs this per solve on the hot path."""
        eps = self.col("eps")
        if not len(eps):
            return []
        starts = np.flatnonzero(np.diff(eps) != 0) + 1
        bounds = np.concatenate([[0], starts, [len(eps)]])
        return [
            {"eps": int(eps[a]), "supersteps": int(b - a)}
            for a, b in zip(bounds[:-1], bounds[1:])
        ]

    def tail(self, k: int = SOLTEL_TAIL) -> List[List[int]]:
        """The final k kept rows, JSON-able (for stall events/dumps)."""
        return [[int(v) for v in row] for row in self.rows[-k:]]

    def to_dict(self) -> dict:
        """JSON-able form; `obs_report.py` renders it as a convergence
        table (the `solver_telemetry` dump kind)."""
        return {
            "backend": self.backend,
            "steps": self.steps,
            "budget": self.budget,
            "cap": self.cap,
            "truncated": self.truncated,
            "start_step": self.start_step,
            "converged": self.converged,
            "nodes": self.nodes,
            "arcs": self.arcs,
            "cols": list(SOLTEL_COLS),
            "rows": [[int(v) for v in row] for row in self.rows],
        }


def decode(
    buf,
    steps: int,
    cap: int,
    backend: str,
    budget: int,
    converged: bool = True,
    nodes: int = 0,
    arcs: int = 0,
) -> SolveTelemetry:
    """Unroll a device telemetry ring into superstep order.

    `buf` is the raw `[cap, SOLTEL_WIDTH]` device/host array; `steps`
    the solve's executed superstep count. Rows past `steps` were never
    written (zeros); when `steps > cap` the ring wrapped and the kept
    rows are supersteps `steps - cap .. steps - 1` — truncation is
    REPORTED, never silent."""
    data = np.asarray(buf)
    if data.ndim != 2 or data.shape[1] != SOLTEL_WIDTH or data.shape[0] != cap:
        raise ValueError(
            f"telemetry buffer shape {data.shape} != ({cap}, {SOLTEL_WIDTH})"
        )
    steps = int(steps)
    if steps <= cap:
        rows = data[:steps]
        start = 0
    else:
        idx = np.arange(steps - cap, steps) % cap
        rows = data[idx]
        start = steps - cap
    return SolveTelemetry(
        backend=backend,
        steps=steps,
        budget=int(budget),
        cap=int(cap),
        truncated=steps > cap,
        start_step=int(start),
        rows=np.array(rows, dtype=np.int32, copy=True),
        converged=bool(converged),
        nodes=int(nodes),
        arcs=int(arcs),
    )


# ---------------------------------------------------------------------------
# stall / divergence detection
# ---------------------------------------------------------------------------


def detect_stall(
    tel: SolveTelemetry, window: int = SOLTEL_STALL_WINDOW
) -> Optional[dict]:
    """Structured stall reason for a solve's telemetry, or None.

    Rules, most-specific first:
    - `superstep_budget_exhausted`: the solve burned its whole budget
      without converging (a bare timeout, now with interior evidence);
    - `excess_plateau`: `window` consecutive supersteps without the
      total positive excess decreasing — the discharge is circulating,
      not draining (the round-3 tail pathology, tools/tail_repro.py);
    - `eps_plateau`: eps pinned at one value for 2x the window with
      active nodes throughout — a phase that cannot drain;
    - `superstep_cap_proximity`: a converged solve that consumed >=90%
      of its budget — the next churn delta may not converge at all.
    """
    if tel.steps == 0:
        return None
    excess = tel.col("excess")
    eps = tel.col("eps")
    active = tel.col("active")
    base = {
        "backend": tel.backend,
        "supersteps": tel.steps,
        "budget": tel.budget,
        "converged": tel.converged,
        "eps": int(eps[-1]) if len(eps) else 0,
        "excess": int(excess[-1]) if len(excess) else 0,
        "active": int(active[-1]) if len(active) else 0,
    }
    plateau = None
    if len(excess) >= window:
        w = excess[-window:]
        # the window must sit WITHIN one eps phase: next_phase's
        # saturate legitimately re-raises total excess at a phase
        # boundary, which is progress, not circulation — only a
        # fixed-eps window without excess decrease is the tail
        # pathology (tools/tail_repro.py)
        if (
            (w > 0).all()
            and int(w.min()) >= int(w[0])
            and (eps[-window:] == eps[-1]).all()
        ):
            plateau = {
                "kind": "excess_plateau",
                "window": window,
                "detail": (
                    f"{window} supersteps without excess decrease "
                    f"({int(w[0])} -> {int(w[-1])} units at eps {base['eps']})"
                ),
                **base,
            }
    if not tel.converged:
        if plateau is not None:
            return plateau
        if len(eps) >= 2 * window and (eps[-2 * window:] == eps[-1]).all() and (
            active[-2 * window:] > 0
        ).all():
            return {
                "kind": "eps_plateau",
                "window": 2 * window,
                "detail": (
                    f"eps pinned at {base['eps']} for {2 * window}+ "
                    "supersteps with active nodes"
                ),
                **base,
            }
        return {
            "kind": "superstep_budget_exhausted",
            "detail": (
                f"{tel.steps} supersteps consumed the {tel.budget} budget "
                "without convergence"
            ),
            **base,
        }
    if plateau is not None:
        return plateau
    if tel.budget > 0 and tel.steps >= max(1, (9 * tel.budget) // 10):
        return {
            "kind": "superstep_cap_proximity",
            "detail": (
                f"converged at {tel.steps}/{tel.budget} supersteps "
                "(>=90% of budget)"
            ),
            **base,
        }
    return None


class SolverStallError(RuntimeError):
    """Non-convergence with its interior evidence attached: `.reason`
    is `detect_stall`'s structured dict, `.telemetry` the decoded
    buffer of the failed attempt. A RuntimeError subclass, so the
    degradation ladder absorbs it like the bare timeout it replaces."""

    def __init__(
        self,
        message: str,
        reason: Optional[dict] = None,
        telemetry: Optional[SolveTelemetry] = None,
    ) -> None:
        super().__init__(message)
        self.reason = reason
        self.telemetry = telemetry


# ---------------------------------------------------------------------------
# stall-event ring (what flight dumps embed)
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_stalls: deque = deque(maxlen=32)
_last_tel: Optional[SolveTelemetry] = None
#: ambient stall attribution scope (thread-local): the multi-tenant
#: loop enters `stall_scope(tenant_id)` around each tenant's dispatch/
#: complete phases, so every stall event deposited while a tenant's
#: lane is being driven carries that tenant — tenant-scoped flight
#: recorders filter their dumps' solver_stalls section on it
_scope_tls = threading.local()


class stall_scope:
    """``with stall_scope("t3"):`` — tag stall events deposited in the
    block (this thread) with a tenant/scope discriminator. Reentrant;
    the innermost scope wins."""

    def __init__(self, scope: Optional[str]) -> None:
        self.scope = scope

    def __enter__(self) -> "stall_scope":
        stack = getattr(_scope_tls, "stack", None)
        if stack is None:
            stack = _scope_tls.stack = []
        stack.append(self.scope)
        return self

    def __exit__(self, *exc) -> None:
        _scope_tls.stack.pop()


def current_stall_scope() -> Optional[str]:
    stack = getattr(_scope_tls, "stack", None)
    return stack[-1] if stack else None


def note_stall(reason: dict, tel: Optional[SolveTelemetry] = None) -> dict:
    """Deposit a structured stall event (with the final SOLTEL_TAIL
    supersteps of telemetry) into the bounded ring the flight recorder
    dumps, and count it on the registry."""
    if tel is None:
        tel = _last_tel
    event = dict(reason)
    event.setdefault("ts", time.time())
    scope = current_stall_scope()
    if scope is not None and "tenant" not in event:
        event["tenant"] = scope
    if tel is not None:
        event["telemetry_cols"] = list(SOLTEL_COLS)
        event["telemetry_tail"] = tel.tail()
        event["telemetry_start_step"] = max(
            tel.start_step, tel.steps - len(event["telemetry_tail"])
        )
        event["telemetry_truncated"] = tel.truncated
    with _lock:
        _stalls.append(event)
    get_registry().counter(
        "ksched_solver_stalls_total",
        "solver stall/divergence events by detector rule",
        labelnames=("kind",),
    ).labels(kind=str(reason.get("kind", "unknown"))).inc()
    return event


def warm_price_war(
    backend: str,
    supersteps: int,
    budget: int,
    escaped_to: str = "fresh_restart",
    tel: Optional[SolveTelemetry] = None,
) -> dict:
    """Structured price-war event: a WARM attempt burned its superstep
    budget without converging and the solver is escaping to a restart.
    Deposited on the stall ring (so every flight dump carries it, with
    the attempt's telemetry tail when available) — flight dumps can now
    distinguish a warm-start price war (eps pinned at 1, supersteps >=
    the warm budget, solved instantly by a fresh restart) from genuine
    non-convergence. Since the dirty-frontier refit landed these should
    be RARE; a recurring stream of them means the carried prices are
    being invalidated faster than the refit can repair them."""
    reason = {
        "kind": "warm_price_war",
        "backend": backend,
        "supersteps": int(supersteps),
        "budget": int(budget),
        "converged": False,
        "eps": int(tel.col("eps")[-1]) if tel is not None and len(tel.rows) else 1,
        "excess": int(tel.col("excess")[-1]) if tel is not None and len(tel.rows) else 0,
        "active": int(tel.col("active")[-1]) if tel is not None and len(tel.rows) else 0,
        "detail": (
            f"warm attempt burned {int(supersteps)}/{int(budget)} supersteps "
            f"without converging (price war); escaping to {escaped_to}"
        ),
    }
    return note_stall(reason, tel)


def recent_stalls() -> List[dict]:
    with _lock:
        return list(_stalls)


def reset_stalls() -> None:
    global _last_tel
    with _lock:
        _stalls.clear()
    _last_tel = None


def failure_reason(rung: str, err: BaseException) -> dict:
    """The degradation ladder's structured reason for a failed rung:
    the stall detector's verdict when the error carries telemetry
    (a genuine non-convergence), otherwise a classification of the
    error itself — with the most recent solve telemetry's tail either
    way, so a flight dump always shows the interior state leading up
    to the failure."""
    reason: dict = {
        "rung": rung,
        "error": f"{type(err).__name__}: {err}",
    }
    stall = getattr(err, "reason", None)
    if isinstance(stall, dict):
        reason.update(stall)
    elif isinstance(err, OverflowError):
        reason["kind"] = "overflow"
    elif "chaos" in str(err):
        reason["kind"] = "injected_fault"
    elif isinstance(err, ValueError):
        reason["kind"] = "rejected_input"
    else:
        reason["kind"] = "backend_error"
    return reason


# ---------------------------------------------------------------------------
# publication (registry + synthesized child spans)
# ---------------------------------------------------------------------------


def publish(tel: Optional[SolveTelemetry], sp=None) -> Optional[dict]:
    """Publish one solve's telemetry: registry histograms/counters,
    per-superstep child spans under the open `backend_solve` span (when
    a tracer is recording), and the stall detector. Returns the stall
    event when one was noted. Called from `solver/base.solve_traced`
    (and the bulk scheduler's layered path) right after the solve —
    entirely host-side, after the device work is already fetched."""
    global _last_tel
    if tel is None or tel.steps == 0:
        return None
    _last_tel = tel
    reg = get_registry()
    reg.histogram(
        "ksched_solve_supersteps",
        "supersteps per solve, from solver-interior telemetry",
        labelnames=("backend",),
        buckets=COUNT_BUCKETS,
    ).labels(backend=tel.backend).observe(tel.steps)
    phase_hist = reg.histogram(
        "ksched_solve_phase_supersteps",
        "supersteps per cost-scaling eps phase",
        labelnames=("backend",),
        buckets=COUNT_BUCKETS,
    ).labels(backend=tel.backend)
    for phase in tel.phases():
        phase_hist.observe(phase["supersteps"])
    pushed = reg.counter(
        "ksched_solve_pushes_total",
        "flow units moved by solver supersteps",
        labelnames=("backend",),
    ).labels(backend=tel.backend)
    relabeled = reg.counter(
        "ksched_solve_relabels_total",
        "node relabels executed by solver supersteps",
        labelnames=("backend",),
    ).labels(backend=tel.backend)
    pushed.inc(int(tel.col("pushed").astype(np.int64).sum()))  # kschedlint: host-only (host-side accumulation of int32 telemetry)
    relabeled.inc(int(tel.col("relabels").astype(np.int64).sum()))  # kschedlint: host-only (host-side accumulation of int32 telemetry)
    if tel.truncated:
        reg.counter(
            "ksched_solve_telemetry_truncated_total",
            "solves whose telemetry ring wrapped (steps > cap)",
            labelnames=("backend",),
        ).labels(backend=tel.backend).inc()
    _synthesize_spans(tel, sp)
    stall = detect_stall(tel)
    if stall is not None:
        return note_stall(stall, tel)
    return None


def _synthesize_spans(tel: SolveTelemetry, sp) -> None:
    """Per-superstep child spans under the (still-open) backend_solve
    span. The device gives counts, not wall times, so the parent span's
    elapsed wall is apportioned across kept supersteps proportionally
    to their work column — the trace shows the convergence SHAPE (which
    supersteps were heavy, where eps phases turned over), which is the
    thing a flat superstep count cannot."""
    from .spans import active_tracer

    tracer = active_tracer()
    if tracer is None or sp is None or not getattr(sp, "sid", 0):
        return
    t0 = sp.t0_s
    t1 = time.perf_counter()
    span_s = max(t1 - t0, 1e-9)
    work = tel.col("work").astype(np.float64) + tel.col("pushed") + 1.0  # kschedlint: host-only (host-side span-time apportioning over <=cap rows)
    frac = work / work.sum()
    starts = t0 + np.concatenate([[0.0], np.cumsum(frac)[:-1]]) * span_s
    durs = frac * span_s
    for i, row in enumerate(tel.rows):
        tracer.record_event(
            "superstep",
            t0_s=float(starts[i]),
            dur_s=float(durs[i]),
            args={
                "step": tel.start_step + i,
                "eps": int(row[0]),
                "active": int(row[1]),
                "excess": int(row[2]),
                "pushed": int(row[3]),
                "relabels": int(row[4]),
                "saturated": int(row[5]),
                "work": int(row[6]),
                "parent_sid": sp.sid,
                "parent": sp.name,
            },
        )


def publish_round_supersteps(supersteps, backend: str) -> None:
    """Per-round superstep counts from a device-fused path (the
    DeviceBulkCluster scan, trace replay) onto the registry — the
    interior of those solves stays on device, but the per-round
    superstep series is solver telemetry too, and `bench.py --obs-out`
    publishes it after the clock stops instead of warning that nothing
    was recorded."""
    ss = np.asarray(supersteps).reshape(-1)
    if ss.size == 0:
        return
    hist = get_registry().histogram(
        "ksched_solve_supersteps",
        "supersteps per solve, from solver-interior telemetry",
        labelnames=("backend",),
        buckets=COUNT_BUCKETS,
    ).labels(backend=backend)
    for v in ss:
        hist.observe(int(v))
