"""Device-side accounting: solver effort, host→device traffic, and
opt-in `jax.profiler` capture.

The solvers run their superstep loops *inside* jit (a `lax.while_loop`
in solver/jax_solver.py, a single fused `pallas_call` in
ops/mcmf_pallas.py), so per-superstep host spans do not exist — what
the host can observe, this module records:

- per-solve effort (supersteps / iterations / augmentations) as a
  log-bucketed histogram and a per-backend solve counter, labeled with
  the rung that actually produced the round when the degradation
  ladder is in play;
- host→device bytes per round, from the placement driver's export
  path: a full build ships the whole FlowProblem (exact `nbytes`), an
  incremental round scatters the change journal (estimated from the
  round's ChangeStats at the flat-array record sizes);
- an opt-in `jax.profiler` trace capture bracketing the Nth solve
  (`--devprof-capture N`): one XLA-level trace of a steady-state round
  without paying profiler overhead on every round.

One module-level profiler is the default sink (`get_profiler()`), so
the placement driver needs no plumbing; the soak and tests install
private instances via `set_profiler` for per-run registries.
"""

from __future__ import annotations

import warnings
from typing import Optional

from .metrics import Registry, get_registry, log_buckets

#: estimated flat-array bytes scattered per journaled arc change: slots
#: in src/dst/cap/cost/flow_offset (4 B each, graph/device_export.py)
ARC_RECORD_BYTES = 20
#: estimated bytes per journaled node change: excess (8 B) + node_type
NODE_RECORD_BYTES = 9


def problem_nbytes(problem) -> int:
    """Exact bytes of a FlowProblem's arrays (the full-build upload)."""
    total = 0
    for name in ("excess", "node_type", "src", "dst", "cap", "cost", "flow_offset"):
        arr = getattr(problem, name, None)
        total += int(getattr(arr, "nbytes", 0))
    return total


def delta_nbytes(stats) -> int:
    """Estimated bytes scattered by one incremental round's journal
    (ChangeStats counts × flat-array record sizes)."""
    arcs = stats.arcs_added + stats.arcs_changed + stats.arcs_removed
    nodes = stats.nodes_added + stats.nodes_removed
    return arcs * ARC_RECORD_BYTES + nodes * NODE_RECORD_BYTES


def journal_nbytes(changes) -> int:
    """Estimated bytes scattered by one applied change journal, counted
    from the journal itself (arc records carry src/dst; the rest are
    node records). Preferred over `delta_nbytes`: the journal is
    exactly what apply_changes scatters, while per-round ChangeStats
    miss the previous round's post-solve mutations (they are journaled
    after the stats reset but shipped in the next round's scatter)."""
    arcs = sum(1 for c in changes if hasattr(c, "src"))
    return arcs * ARC_RECORD_BYTES + (len(changes) - arcs) * NODE_RECORD_BYTES


class DeviceProfiler:
    """The per-solve accounting sink + the Nth-solve jax.profiler hook."""

    def __init__(
        self,
        registry: Optional[Registry] = None,
        capture_solve: int = 0,
        capture_dir: str = "./jax_profile",
    ) -> None:
        reg = registry if registry is not None else get_registry()
        self.solves = reg.counter(
            "ksched_solves_total",
            "backend solves by the rung/backend that produced the result",
            labelnames=("backend",),
        )
        self.solver_work = reg.histogram(
            "ksched_solver_work",
            "supersteps/iterations per solve",
            labelnames=("backend",),
            buckets=log_buckets(1, 1 << 20, 2.0),
        )
        self.h2d_bytes = reg.counter(
            "ksched_h2d_bytes_total",
            "host->device bytes shipped by graph export (full builds exact, "
            "incremental deltas estimated from ChangeStats)",
            labelnames=("kind",),
        )
        self.problem_arcs = reg.gauge(
            "ksched_problem_arcs", "live arc slots in the last exported problem"
        )
        self.problem_nodes = reg.gauge(
            "ksched_problem_nodes", "dense node extent of the last exported problem"
        )
        self.captures = reg.counter(
            "ksched_devprof_captures_total", "jax.profiler traces captured"
        )
        self.capture_solve = capture_solve
        self.capture_dir = capture_dir
        self._solve_index = 0
        self._capturing = False
        self._capture_failed = False

    # -- export accounting -------------------------------------------------

    def note_export(
        self, problem, full: bool, stats=None, changes=None,
        exact_bytes: Optional[int] = None,
    ) -> None:
        """``exact_bytes`` is the measured host→device byte count from
        the device-resident export path (packed delta-record nbytes, or
        the rebuild upload) — exact accounting, preferred over every
        estimate below. The non-resident paths re-upload full arrays
        but their *delta-relevant* traffic is estimated from the
        journal (``journal_nbytes``) or, lacking one, ChangeStats."""
        if exact_bytes is not None:
            kind = "full_build" if full else "delta"
            self.h2d_bytes.labels(kind=kind).inc(exact_bytes)
        elif full:
            self.h2d_bytes.labels(kind="full_build").inc(problem_nbytes(problem))
        elif changes is not None:
            self.h2d_bytes.labels(kind="delta").inc(journal_nbytes(changes))
        elif stats is not None:
            self.h2d_bytes.labels(kind="delta").inc(delta_nbytes(stats))
        self.problem_arcs.set(problem.num_arcs)
        self.problem_nodes.set(problem.num_nodes)

    # -- solve accounting + Nth-solve capture ------------------------------

    def solve_starting(self) -> None:
        """Called just before a backend solve is dispatched; starts the
        jax.profiler trace when this is the configured Nth solve."""
        self._solve_index += 1
        if (
            self.capture_solve > 0
            and self._solve_index == self.capture_solve
            and not self._capture_failed
        ):
            try:
                import jax

                jax.profiler.start_trace(self.capture_dir)
                self._capturing = True
            except Exception as e:  # noqa: BLE001 — profiling is best-effort
                self._capture_failed = True
                warnings.warn(
                    f"devprof: jax.profiler capture unavailable ({e}); disabled",
                    RuntimeWarning,
                    stacklevel=2,
                )

    def _stop_capture(self) -> None:
        if not self._capturing:
            return
        self._capturing = False
        try:
            import jax

            jax.profiler.stop_trace()
            self.captures.inc()
        except Exception as e:  # noqa: BLE001
            warnings.warn(
                f"devprof: jax.profiler stop_trace failed ({e})",
                RuntimeWarning,
                stacklevel=2,
            )

    def solve_failed(self) -> None:
        """Called when a dispatched solve raises (chaos fault, ladder
        exhaustion): stop a capture started for this solve so the 'one
        solve' trace neither bleeds into later rounds nor runs forever
        when no solve ever completes."""
        self._stop_capture()

    def note_solve(self, backend, problem, result) -> None:
        """Called once per completed solve by the placement driver."""
        self._stop_capture()
        name = getattr(backend, "last_rung_name", None) or type(backend).__name__
        work = int(getattr(result, "iterations", 0) or 0)
        if not work:
            work = int(
                getattr(backend, "last_iterations", 0)
                or getattr(backend, "last_supersteps", 0)
                or 0
            )
        self.solves.labels(backend=name).inc()
        if work:
            self.solver_work.labels(backend=name).observe(work)


_profiler: Optional[DeviceProfiler] = None


def get_profiler() -> DeviceProfiler:
    """The module-default profiler (created lazily on the registry that
    is current at first use)."""
    global _profiler
    if _profiler is None:
        _profiler = DeviceProfiler()
    return _profiler


def set_profiler(profiler: Optional[DeviceProfiler]) -> None:
    """Install a configured profiler (per-run registry / Nth-solve
    capture); None resets to lazy-default."""
    global _profiler
    _profiler = profiler
