"""The crash flight recorder: a ring buffer of the last N rounds.

Black-box recording for the scheduler service: every round, the
service deposits its RoundRecord plus that round's span events into a
bounded ring; when something goes wrong the whole ring is dumped as
one JSON artifact — the last N rounds of phase timings, fault
attribution, and nested spans leading *up to* the event, which is
exactly what a post-mortem needs and what live metrics (aggregates)
cannot give.

Dump triggers:

- **deadline miss** — the round blew the PR-4 watchdog
  (`RoundRecord.deadline_miss`);
- **ladder exhaustion** — a NOOP round: every solver rung failed and
  the previous assignments were kept (`RoundRecord.noop_round`);
- **crash** — an uncaught exception, via the chained `sys.excepthook`
  installed by `install_crash_hook()`;
- **manual** — `dump("reason")`, e.g. on SIGTERM from an operator.

Dumps are rate-limited per trigger kind (a flapping solver must not
write a dump per round) and counted on the metrics registry
(`ksched_flight_dumps_total{reason=...}`). The dump file carries the
ring as `rounds` and a flattened `traceEvents` list, so the same file
loads in Perfetto directly.
"""

from __future__ import annotations

import json
import os
import sys
import time
from collections import deque
from dataclasses import asdict
from typing import List, Optional

from .metrics import Registry, get_registry


class FlightRecorder:
    def __init__(
        self,
        capacity: int = 64,
        dump_dir: str = ".",
        registry: Optional[Registry] = None,
        min_rounds_between_dumps: int = 16,
        scope: str = "",
    ) -> None:
        if capacity <= 0:
            raise ValueError("flight recorder capacity must be positive")
        self.ring: deque = deque(maxlen=capacity)
        self.dump_dir = dump_dir
        #: dump-filename discriminator (and solver-stall filter) for
        #: recorders sharing one dump dir — the multi-tenant service
        #: runs one recorder PER TENANT, and round-keyed-only filenames
        #: would let two tenants dumping in the same round clobber each
        #: other (regression-tested in tests/test_obs.py)
        self.scope = scope
        self.min_rounds_between_dumps = min_rounds_between_dumps
        self.dumps: List[str] = []  # paths written, oldest first
        self.rounds_seen = 0
        self._last_dump_round = {}  # reason -> rounds_seen at last dump
        reg = registry if registry is not None else get_registry()
        self._dump_metric = reg.counter(
            "ksched_flight_dumps_total",
            "flight-recorder dumps by trigger",
            labelnames=("reason",),
        )
        self._prev_excepthook = None

    # -- recording ---------------------------------------------------------

    def note_round(self, record, span_events: Optional[List[dict]] = None) -> Optional[str]:
        """Deposit one round (RoundRecord + its span events); auto-dumps
        and returns the dump path when the record trips a trigger."""
        self.rounds_seen += 1
        self.ring.append(
            {
                "record": asdict(record),
                "spans": list(span_events) if span_events else [],
            }
        )
        if getattr(record, "deadline_miss", False):
            return self._maybe_dump("deadline_miss")
        if getattr(record, "noop_round", False):
            return self._maybe_dump("noop_round")
        return None

    def trigger(self, reason: str) -> Optional[str]:
        """External dump trigger (e.g. a state-divergence event), with
        the same per-reason rate limit as the built-in triggers."""
        return self._maybe_dump(reason)

    def _maybe_dump(self, reason: str) -> Optional[str]:
        last = self._last_dump_round.get(reason)
        if last is not None and self.rounds_seen - last < self.min_rounds_between_dumps:
            return None
        self._last_dump_round[reason] = self.rounds_seen
        return self.dump(reason)

    # -- dumping -----------------------------------------------------------

    def dump(self, reason: str, path: Optional[str] = None) -> str:
        """Write the ring out; returns the path. The payload is both a
        flight dump (`rounds`) and a Chrome trace (`traceEvents`)."""
        if path is None:
            tag = f"{self.scope}_" if self.scope else ""
            path = os.path.join(
                self.dump_dir, f"flight_{tag}{reason}_r{self.rounds_seen:06d}.json"
            )
            # two recorders in one dir (or a restarted service whose
            # round counter reset) must never clobber an existing dump:
            # the filename is a post-mortem artifact, not a slot
            if os.path.exists(path):
                i = 1
                stem, ext = os.path.splitext(path)
                while os.path.exists(f"{stem}_{i}{ext}"):
                    i += 1
                path = f"{stem}_{i}{ext}"
        # the dir may not exist yet (--flight-dir ./flight on a fresh
        # checkout) or may have been removed mid-run; a failed dump must
        # not kill the service loop it exists to post-mortem
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        rounds = list(self.ring)
        trace_events = [ev for entry in rounds for ev in entry["spans"]]
        # solver-interior stall events (structured reasons + the final
        # K supersteps of telemetry) ride along in every dump: a NOOP
        # round's post-mortem needs to show WHY the ladder exhausted,
        # not just that it did
        from .soltel import recent_stalls

        stalls = recent_stalls()
        if self.scope:
            # a tenant-scoped recorder's post-mortem must not carry
            # OTHER tenants' stall attribution; untagged events (from
            # code outside any tenant scope) stay visible to all
            stalls = [
                s for s in stalls
                if s.get("tenant") in (None, self.scope)
            ]
        payload = {
            "reason": reason,
            "captured_at": time.time(),
            "rounds_seen": self.rounds_seen,
            "rounds": rounds,
            "traceEvents": trace_events,
            "solver_stalls": stalls,
            "scope": self.scope,
            "displayTimeUnit": "ms",
        }
        with open(path, "w") as f:
            json.dump(payload, f)
        self.dumps.append(path)
        self._dump_metric.labels(reason=reason).inc()
        return path

    # -- crash hook --------------------------------------------------------

    def install_crash_hook(self) -> None:
        """Chain onto sys.excepthook: dump the ring on an uncaught
        exception, then defer to the previous hook (traceback printing
        survives). Idempotent."""
        if self._prev_excepthook is not None:
            return
        prev = sys.excepthook
        self._prev_excepthook = prev

        def hook(exc_type, exc, tb):
            try:
                self.dump("crash")
            except Exception:  # noqa: BLE001 — never mask the original crash
                pass
            prev(exc_type, exc, tb)

        sys.excepthook = hook

    def uninstall_crash_hook(self) -> None:
        if self._prev_excepthook is not None:
            sys.excepthook = self._prev_excepthook
            self._prev_excepthook = None
