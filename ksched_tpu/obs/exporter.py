"""Prometheus text-format exposition + the stdlib metrics HTTP server.

Serves three routes from a daemon thread (no dependencies beyond
`http.server`):

- ``/metricsz`` — Prometheus text format 0.0.4 (HELP/TYPE lines, label
  escaping, cumulative ``_bucket`` series with ``+Inf``, ``_sum`` and
  ``_count``);
- ``/healthz``  — liveness JSON (status + uptime);
- ``/varz``     — the registry snapshot as JSON (the machine-readable
  twin of /metricsz, same shape as the dump-on-exit artifact).

`render_prometheus` / `parse_prometheus` are exposed separately so the
soak's obs smoke can scrape its own endpoint and reconcile the served
text against the RoundRecord JSONL, and so conformance tests can
round-trip escaping without a socket.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple

from .metrics import Registry, get_registry

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_label_value(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _escape_help(v: str) -> str:
    return v.replace("\\", r"\\").replace("\n", r"\n")


def _fmt(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _fmt_le(bound) -> str:
    return "+Inf" if bound == "+Inf" else _fmt(float(bound))


def _labels_text(labels: Dict[str, str], extra: Optional[Tuple[str, str]] = None) -> str:
    items = list(labels.items())
    if extra is not None:
        items.append(extra)
    if not items:
        return ""
    body = ",".join(f'{k}="{_escape_label_value(str(v))}"' for k, v in items)
    return "{" + body + "}"


def render_prometheus(registry: Registry) -> str:
    """The registry as Prometheus exposition text."""
    lines = []
    for fam in registry.collect():
        if fam.help:
            lines.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
        lines.append(f"# TYPE {fam.name} {fam.kind}")
        for labels, child in fam.samples():
            if fam.kind == "histogram":
                bounds, counts, total_sum, total_count = child.snapshot()
                cum = 0
                for bound, n in zip(list(bounds) + ["+Inf"], counts):
                    cum += n
                    lt = _labels_text(labels, ("le", _fmt_le(bound)))
                    lines.append(f"{fam.name}_bucket{lt} {cum}")
                lt = _labels_text(labels)
                lines.append(f"{fam.name}_sum{lt} {_fmt(total_sum)}")
                lines.append(f"{fam.name}_count{lt} {total_count}")
            else:
                lines.append(f"{fam.name}{_labels_text(labels)} {_fmt(child.value)}")
    return "\n".join(lines) + ("\n" if lines else "")


def _parse_labels(body: str) -> Dict[str, str]:
    """Parse `a="x",b="y"` with Prometheus label-value escapes."""
    labels: Dict[str, str] = {}
    i = 0
    while i < len(body):
        eq = body.index("=", i)
        name = body[i:eq].strip().lstrip(",").strip()
        assert body[eq + 1] == '"', f"malformed label at {body[i:]!r}"
        j = eq + 2
        out = []
        while body[j] != '"':
            ch = body[j]
            if ch == "\\":
                j += 1
                nxt = body[j]
                out.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt, "\\" + nxt))
            else:
                out.append(ch)
            j += 1
        labels[name] = "".join(out)
        i = j + 1
    return labels


def parse_prometheus(text: str) -> Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float]:
    """Exposition text → {(series_name, sorted label items): value}.

    Series names include the `_bucket`/`_sum`/`_count` suffixes as
    written. Used by conformance tests and the soak's live-scrape
    reconciliation; not a general-purpose Prometheus parser."""
    out: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        # value is after the last space not inside braces; label values
        # may contain spaces, so split from the right of the brace
        if "}" in line:
            brace = line.index("{")
            endbrace = line.rindex("}")
            name = line[:brace]
            labels = _parse_labels(line[brace + 1:endbrace])
            value = float(line[endbrace + 1:].strip())
        else:
            name, value_s = line.rsplit(" ", 1)
            name = name.strip()
            labels = {}
            value = float(value_s)
        out[(name, tuple(sorted(labels.items())))] = value
    return out


def dump_registry(registry: Registry, path: str) -> None:
    """Dump-on-exit artifact: the registry snapshot as JSON."""
    with open(path, "w") as f:
        json.dump(
            {"captured_at": time.time(), "metrics": registry.snapshot()},
            f,
            indent=1,
        )


def scrape(url: str, timeout_s: float = 5.0) -> Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float]:
    """GET a /metricsz URL and parse it (the obs smoke's 'curl')."""
    with urllib.request.urlopen(url, timeout=timeout_s) as r:
        return parse_prometheus(r.read().decode())


class MetricsServer:
    """The observability endpoint: a ThreadingHTTPServer on a daemon
    thread serving /metricsz, /healthz, /varz. ``port=0`` binds an
    ephemeral port (CI-safe); the bound port is ``self.port``."""

    def __init__(
        self,
        port: int = 0,
        registry: Optional[Registry] = None,
        host: str = "127.0.0.1",
    ) -> None:
        self.registry = registry if registry is not None else get_registry()
        self._t0 = time.monotonic()
        server_self = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # silence per-request stderr spam
                pass

            def _send(self, code: int, content_type: str, body: bytes) -> None:
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path in ("/metricsz", "/metrics"):
                    body = render_prometheus(server_self.registry).encode()
                    self._send(200, PROMETHEUS_CONTENT_TYPE, body)
                elif path == "/healthz":
                    body = json.dumps(
                        {
                            "status": "ok",
                            "uptime_s": time.monotonic() - server_self._t0,
                        }
                    ).encode()
                    self._send(200, "application/json", body)
                elif path == "/varz":
                    body = json.dumps(server_self.registry.snapshot()).encode()
                    self._send(200, "application/json", body)
                else:
                    self._send(404, "text/plain", b"not found\n")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="ksched-metrics", daemon=True
        )
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
