"""Hierarchical span tracing with Chrome/Perfetto trace-event export.

The flat `phases_ms` dict the round trace carried (runtime/trace.py)
could say *that* a round spent 7 ms in "solve" but not *where*: graph
export vs backend dispatch vs rung fallback vs decode. Spans make the
nesting first-class — round → schedule → {stats, graph_update, solve →
{graph_export, backend_solve → solver_rung…}, deltas, apply} — and the
whole tree exports as Chrome trace-event JSON that loads directly in
Perfetto / chrome://tracing.

Two-layer design, so instrumentation costs ~nothing when unused:

- `span(name, **args)` is a context manager that ALWAYS times (two
  `perf_counter` calls — exactly what the hand-rolled timing it
  replaces cost). `RoundTiming` in scheduler/flow_scheduler.py is
  populated from these spans' durations, which is what makes the round
  trace a *consumer* of the same measurements the live trace exports:
  the JSONL artifact and a captured Perfetto trace can never disagree.
- recording only happens while a `SpanTracer` is installed
  (`tracer.install()` / `with tracer:`); with none installed the span
  skips the contextvar parenting entirely.

Parenting is contextvar-based, so spans nest correctly across threads
and (if the host app uses them) asyncio tasks; each recorded event
carries its span id and parent span id in `args` in addition to the
time containment Perfetto uses for visual nesting. A span that exits
via an exception records `args.error` and still closes cleanly, so an
aborted round leaves a well-formed trace behind (the flight recorder
depends on that).
"""

from __future__ import annotations

import contextvars
import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

_current: "contextvars.ContextVar[Optional[Span]]" = contextvars.ContextVar(
    "ksched_obs_span", default=None
)
_active: Optional["SpanTracer"] = None
_ids = itertools.count(1)


class Span:
    """One timed region. Use as a context manager, or manually via
    `start_span()` / `.finish()` when the region spans methods (the
    pipelined round's dispatch→finish gap)."""

    __slots__ = (
        "name", "args", "sid", "parent_sid", "parent_name",
        "t0_s", "t1_s", "dur_s", "_token", "_tracer",
    )

    def __init__(self, name: str, args: Optional[Dict] = None) -> None:
        self.name = name
        self.args = args
        self.sid = 0
        self.parent_sid = 0
        self.parent_name: Optional[str] = None
        self.t0_s = 0.0
        self.t1_s = 0.0
        self.dur_s = 0.0
        self._token = None
        self._tracer: Optional[SpanTracer] = None

    def set(self, key: str, value) -> None:
        """Attach an arg after entry (e.g. a superstep count only known
        once the solve returns)."""
        if self.args is None:
            self.args = {}
        self.args[key] = value

    def __enter__(self) -> "Span":
        tracer = _active
        self._tracer = tracer
        if tracer is not None:
            parent = _current.get()
            self.sid = next(_ids)
            if parent is not None:
                self.parent_sid = parent.sid
                self.parent_name = parent.name
            self._token = _current.set(self)
        self.t0_s = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self.t1_s:
            return False  # already closed (error-path re-close is a no-op)
        t1 = time.perf_counter()
        self.t1_s = t1
        self.dur_s = t1 - self.t0_s
        tracer = self._tracer
        if tracer is not None:
            _current.reset(self._token)
            self._token = None
            if exc_type is not None:
                self.set("error", f"{exc_type.__name__}: {exc}")
            tracer._record(self)
        return False

    def finish(self) -> float:
        """Close a manually-started span; returns its duration."""
        self.__exit__(None, None, None)
        return self.dur_s


def span(name: str, **args) -> Span:
    """Open a (not-yet-entered) span; `with span("solve") as sp:`."""
    return Span(name, args or None)


def start_span(name: str, **args) -> Span:
    """Enter a span immediately (manual-finish form)."""
    return Span(name, args or None).__enter__()


def active_tracer() -> Optional["SpanTracer"]:
    return _active


def unwind(outer: Span, exc_type, exc, tb) -> None:
    """Error-path close for manual-span regions: close every open span
    from the current innermost up to and including `outer`, so the
    error is recorded on each and the contextvar parenting is restored
    for whatever runs next on this thread. A span entered with no
    tracer installed never touched the contextvar — then only `outer`
    itself needs closing (for its duration; nothing records)."""
    if outer._tracer is None or outer.t1_s:
        outer.__exit__(exc_type, exc, tb)
        return
    while True:
        cur = _current.get()
        if cur is None or cur.t1_s:
            # chain unexpectedly broken; still close outer
            outer.__exit__(exc_type, exc, tb)
            return
        done = cur is outer
        cur.__exit__(exc_type, exc, tb)
        if done:
            return


class SpanTracer:
    """Collects finished spans as Chrome trace events in a bounded ring.

    `mark()`/`events_since(mark)` slice out one round's spans for the
    flight recorder; `chrome_trace()`/`dump()` export the whole ring
    for Perfetto. Thread-safe: spans finish on whichever thread ran
    them (the service thread, watch threads, the watchdog timer)."""

    def __init__(self, capacity: int = 1 << 16) -> None:
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=capacity)
        self.capacity = capacity
        self.total = 0  # spans ever recorded (ring may have dropped some)
        self.dropped = 0
        self._prev: Optional[SpanTracer] = None

    # -- recording ---------------------------------------------------------

    def _append(self, name: str, t0_s: float, dur_s: float, args: Dict) -> None:
        """One Chrome-event construction + locked ring append for both
        recorded spans and synthesized events — the schema must never
        fork between the two."""
        event = {
            "ph": "X",
            "cat": "ksched",
            "name": name,
            "ts": t0_s * 1e6,  # perf_counter base: monotonic, shared in-process
            "dur": dur_s * 1e6,
            "pid": os.getpid(),
            "tid": threading.get_ident(),
            "args": args,
        }
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self.dropped += 1
            self._events.append(event)
            self.total += 1

    def _record(self, sp: Span) -> None:
        args = dict(sp.args) if sp.args else {}
        args["sid"] = sp.sid
        if sp.parent_sid:
            args["parent_sid"] = sp.parent_sid
            args["parent"] = sp.parent_name
        self._append(sp.name, sp.t0_s, sp.t1_s - sp.t0_s, args)

    def record_event(self, name: str, t0_s: float, dur_s: float, args: Optional[Dict] = None) -> None:
        """Record a SYNTHESIZED complete event (no live Span object):
        the solver-interior telemetry decode (obs/soltel.py) fabricates
        per-superstep child spans under a backend_solve span from
        device counters, apportioning the parent's wall time — the
        device cannot produce host timestamps itself. Events land in
        the same ring with the same schema as recorded spans."""
        self._append(name, t0_s, dur_s, dict(args) if args else {})

    # -- slicing (flight recorder) -----------------------------------------

    def mark(self) -> int:
        with self._lock:
            return self.total

    def events_since(self, mark: int) -> List[dict]:
        """Events recorded after `mark` (oldest may be lost to the ring;
        what remains is returned). islice, not a full-ring copy: the
        flight recorder calls this every round to slice out the last
        ~dozen events of a ring that may hold 64k."""
        with self._lock:
            want = self.total - mark
            skip = max(0, len(self._events) - want)
            return list(itertools.islice(self._events, skip, None))

    # -- export ------------------------------------------------------------

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def chrome_trace(self) -> dict:
        return {"traceEvents": self.events(), "displayTimeUnit": "ms"}

    def dump(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)

    # -- activation --------------------------------------------------------

    def install(self) -> "SpanTracer":
        """Make this the process-active tracer (stacking: uninstall
        restores the previous one)."""
        global _active
        self._prev = _active
        _active = self
        return self

    def uninstall(self) -> None:
        global _active
        if _active is self:
            _active = self._prev
        self._prev = None

    def __enter__(self) -> "SpanTracer":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()
