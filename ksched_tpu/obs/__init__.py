"""ksched_tpu.obs: the observability subsystem.

Four pieces, threaded through every layer of the scheduling loop:

- **metrics** — a process-wide registry of Counters, Gauges, and
  log-bucketed Histograms with labels, cheap enough for per-round
  hot-path use and thread-safe for the HTTP adapter's watch threads;
- **spans** — contextvar-based hierarchical span tracing whose output
  is Chrome/Perfetto trace-event JSON; `RoundTiming` (and therefore
  the RoundRecord JSONL) is *derived from* these spans, so the trace
  artifact and the live metrics can never disagree;
- **exporter** — Prometheus text-format exposition from a stdlib HTTP
  thread (`/metricsz`, `/healthz`, `/varz`) plus dump-on-exit;
- **devprof** — device-side accounting (per-solve superstep/rung
  counters, host→device bytes per round, opt-in `jax.profiler`
  capture around the Nth solve);
- **flight** — a crash flight recorder: the last N rounds' records and
  spans, auto-dumped on deadline miss, ladder exhaustion, or crash;
- **soltel** — solver-interior telemetry: per-superstep device
  counters (eps, active/excess, pushes, relabels, saturated arcs,
  work) emitted by the compiled backends, decoded into the registry,
  synthesized as per-superstep child spans, and fed to a structured
  stall/divergence detector whose events ride in flight dumps.

`KSCHED_OBS=0` (or `metrics.set_enabled(False)`) switches the global
registry to an inert null registry; span timing still feeds
RoundTiming (it costs what the hand-rolled timers it replaced cost)
but nothing records unless a SpanTracer is installed.
"""

from .devprof import DeviceProfiler, get_profiler, set_profiler
from .exporter import (
    MetricsServer,
    dump_registry,
    parse_prometheus,
    render_prometheus,
    scrape,
)
from .flight import FlightRecorder
from .metrics import (
    DEFAULT_MS_BUCKETS,
    NULL_METRIC,
    NULL_REGISTRY,
    Registry,
    ScopedRegistry,
    enabled,
    get_registry,
    log_buckets,
    scoped_registry,
    set_enabled,
    set_registry,
)
from .soltel import (
    SOLTEL_COLS,
    SOLTEL_DEFAULT_CAP,
    SOLTEL_TAIL,
    SOLTEL_WIDTH,
    SolverStallError,
    SolveTelemetry,
    detect_stall,
)
from .spans import Span, SpanTracer, active_tracer, span, start_span

__all__ = [
    "DEFAULT_MS_BUCKETS",
    "DeviceProfiler",
    "FlightRecorder",
    "MetricsServer",
    "NULL_METRIC",
    "NULL_REGISTRY",
    "Registry",
    "ScopedRegistry",
    "SOLTEL_COLS",
    "SOLTEL_DEFAULT_CAP",
    "SOLTEL_TAIL",
    "SOLTEL_WIDTH",
    "SolveTelemetry",
    "SolverStallError",
    "Span",
    "SpanTracer",
    "active_tracer",
    "detect_stall",
    "dump_registry",
    "enabled",
    "get_profiler",
    "get_registry",
    "log_buckets",
    "parse_prometheus",
    "render_prometheus",
    "scoped_registry",
    "scrape",
    "set_enabled",
    "set_profiler",
    "set_registry",
    "span",
    "start_span",
]
