"""Process-wide metrics registry: Counters, Gauges, log-bucketed
Histograms with label support.

The reference has no metrics surface at all (round timing is a
`time.Since` print in cmd/k8sscheduler/scheduler.go); production
flow schedulers in the Firmament lineage live and die by a scrapeable
counter set. This registry is the single source every layer publishes
to — the round tracer (runtime/trace.py), the chaos injector
(runtime/chaos.py), the degradation ladder (runtime/degrade.py), the
HTTP control-plane adapter (cluster/http_api.py), and the device
profiler (obs/devprof.py) — and obs/exporter.py serves it as
Prometheus text.

Design constraints, in order:

1. **Hot-path cheap.** A metric update is one dict lookup plus one
   locked float add; handles are cached by the instrumented layers so
   the name→family resolution is not repeated per round.
2. **Thread-safe.** Every child metric carries its own lock; families
   guard their children dict. The HTTP adapter's watch threads and the
   scheduler thread publish concurrently (the seed's read-modify-write
   `Counter` race this registry replaces — see cluster/http_api.py).
3. **No-op-able.** `set_enabled(False)` (or env `KSCHED_OBS=0`) makes
   `get_registry()` hand out a null registry whose metrics are inert
   singletons, so a process that doesn't want observability pays a
   single attribute read per update site.

Registries are also first-class objects: tests and the soak create
private `Registry()` instances so per-run reconciliation is exact even
with the process-global registry in use elsewhere.
"""

from __future__ import annotations

import bisect
import os
import re
import threading
from typing import Dict, List, Optional, Sequence, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def log_buckets(lo: float, hi: float, factor: float = 2.0) -> Tuple[float, ...]:
    """Log-spaced histogram bounds: lo, lo*factor, ... up to >= hi."""
    if lo <= 0 or factor <= 1:
        raise ValueError("log_buckets needs lo > 0 and factor > 1")
    out: List[float] = []
    b = float(lo)
    while b < hi * (1 + 1e-12):
        out.append(b)
        b *= factor
    return tuple(out)


#: default bounds for millisecond timings: ~1 us to ~67 s, factor 2
DEFAULT_MS_BUCKETS = log_buckets(1e-3, 1 << 16, 2.0)


class Counter:
    """A monotone counter (one labeled child)."""

    kind = "counter"
    __slots__ = ("_lock", "_value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter increment must be >= 0, got {n}")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """A set/inc/dec value (one labeled child)."""

    kind = "gauge"
    __slots__ = ("_lock", "_value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        with self._lock:
            self._value -= n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """A log-bucketed histogram (one labeled child).

    Bucket semantics are Prometheus `le`: a sample lands in the first
    bucket whose bound is >= the value; counts are kept per-bucket here
    and cumulated at export time (obs/exporter.py)."""

    kind = "histogram"
    __slots__ = ("_lock", "bounds", "_counts", "_sum", "_count")

    def __init__(self, bounds: Sequence[float] = DEFAULT_MS_BUCKETS) -> None:
        b = tuple(float(x) for x in bounds)
        if not b or list(b) != sorted(b) or len(set(b)) != len(b):
            raise ValueError("histogram bounds must be non-empty, sorted, unique")
        self._lock = threading.Lock()
        self.bounds = b
        self._counts = [0] * (len(b) + 1)  # +1 for the +Inf overflow bucket
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    def snapshot(self) -> Tuple[Tuple[float, ...], List[int], float, int]:
        """(bounds, per-bucket counts incl. +Inf, sum, count), atomically."""
        with self._lock:
            return self.bounds, list(self._counts), self._sum, self._count

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum


_CHILD_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class Family:
    """A named metric family: the (name, kind, labelnames) triple plus
    its labeled children. Unlabeled families proxy the child API
    directly (``family.inc()``), so call sites don't special-case."""

    def __init__(
        self,
        name: str,
        help: str = "",
        kind: str = "counter",
        labelnames: Sequence[str] = (),
        buckets: Optional[Sequence[float]] = None,
    ) -> None:
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln) or ln.startswith("__"):
                raise ValueError(f"invalid label name {ln!r} for metric {name!r}")
        if kind not in _CHILD_KINDS:
            raise ValueError(f"unknown metric kind {kind!r}")
        if buckets is not None and kind != "histogram":
            raise ValueError(f"buckets= only applies to histograms ({name!r})")
        self.name = name
        self.help = help
        self.kind = kind
        self.labelnames = tuple(labelnames)
        self._buckets = tuple(buckets) if buckets is not None else None
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}
        if not self.labelnames:
            self._children[()] = self._make_child()

    def _make_child(self):
        if self.kind == "histogram":
            return Histogram(self._buckets if self._buckets is not None else DEFAULT_MS_BUCKETS)
        return _CHILD_KINDS[self.kind]()

    def labels(self, *values, **kv):
        """Get-or-create the child for one label-value combination.
        Values are coerced to str (label values are strings on the
        wire); positional and keyword forms both work."""
        if kv:
            if values:
                raise ValueError("pass label values positionally or by name, not both")
            try:
                values = tuple(kv[ln] for ln in self.labelnames)
            except KeyError as e:
                raise ValueError(f"missing label {e} for metric {self.name!r}") from e
            if len(kv) != len(self.labelnames):
                extra = set(kv) - set(self.labelnames)
                raise ValueError(f"unknown labels {sorted(extra)} for metric {self.name!r}")
        key = tuple(str(v) for v in values)
        if len(key) != len(self.labelnames):
            raise ValueError(
                f"metric {self.name!r} takes labels {self.labelnames}, got {key}"
            )
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._make_child()
                self._children[key] = child
            return child

    def samples(self) -> List[Tuple[Dict[str, str], object]]:
        """[(labels dict, child)] for every materialized child, in
        insertion order (stable for the text exposition)."""
        with self._lock:
            items = list(self._children.items())
        return [(dict(zip(self.labelnames, key)), child) for key, child in items]

    # -- unlabeled proxy ---------------------------------------------------

    def _unlabeled(self):
        if self.labelnames:
            raise ValueError(
                f"metric {self.name!r} has labels {self.labelnames}; use .labels()"
            )
        return self._children[()]

    def inc(self, n: float = 1.0) -> None:
        self._unlabeled().inc(n)

    def dec(self, n: float = 1.0) -> None:
        self._unlabeled().dec(n)

    def set(self, v: float) -> None:
        self._unlabeled().set(v)

    def observe(self, v: float) -> None:
        self._unlabeled().observe(v)

    @property
    def value(self) -> float:
        return self._unlabeled().value

    @property
    def count(self) -> int:
        return self._unlabeled().count

    @property
    def sum(self) -> float:
        return self._unlabeled().sum


class Registry:
    """A set of metric families. `counter`/`gauge`/`histogram` are
    get-or-create: re-requesting an existing name returns the same
    family (so modules can be re-instantiated), but a kind, label, or
    bucket mismatch is a hard error — silent aliasing would corrupt
    both."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._families: Dict[str, Family] = {}

    def _get_or_create(self, name, help, kind, labelnames, buckets=None) -> Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = Family(name, help, kind, labelnames, buckets)
                self._families[name] = fam
                return fam
        if fam.kind != kind or fam.labelnames != tuple(labelnames):
            raise ValueError(
                f"metric {name!r} already registered as {fam.kind} with labels "
                f"{fam.labelnames}; requested {kind} with {tuple(labelnames)}"
            )
        if kind == "histogram" and buckets is not None:
            # buckets are as identity-bearing as kind/labels: silently
            # landing samples in bounds the caller did not ask for would
            # skew every percentile estimated from them
            effective = (
                fam._buckets if fam._buckets is not None else DEFAULT_MS_BUCKETS
            )
            if tuple(float(b) for b in buckets) != effective:
                raise ValueError(
                    f"histogram {name!r} already registered with buckets "
                    f"{effective}; requested {tuple(buckets)}"
                )
        return fam

    def counter(self, name: str, help: str = "", labelnames: Sequence[str] = ()) -> Family:
        return self._get_or_create(name, help, "counter", labelnames)

    def gauge(self, name: str, help: str = "", labelnames: Sequence[str] = ()) -> Family:
        return self._get_or_create(name, help, "gauge", labelnames)

    def histogram(
        self,
        name: str,
        help: str = "",
        labelnames: Sequence[str] = (),
        buckets: Optional[Sequence[float]] = None,
    ) -> Family:
        return self._get_or_create(name, help, "histogram", labelnames, buckets)

    def collect(self) -> List[Family]:
        with self._lock:
            return [self._families[k] for k in sorted(self._families)]

    def value(self, name: str, **labels) -> float:
        """Read one sample (0.0 when absent) — the test/stats accessor."""
        with self._lock:
            fam = self._families.get(name)
        if fam is None:
            return 0.0
        key = tuple(str(labels[ln]) for ln in fam.labelnames if ln in labels)
        if len(key) != len(fam.labelnames):
            raise ValueError(f"metric {name!r} needs labels {fam.labelnames}")
        with fam._lock:
            child = fam._children.get(key)
        if child is None:
            return 0.0
        if fam.kind == "histogram":
            return float(child.count)
        return float(child.value)

    def snapshot(self) -> Dict[str, dict]:
        """JSON-able dump of every family and sample (the /varz body and
        the dump-on-exit artifact)."""
        out: Dict[str, dict] = {}
        for fam in self.collect():
            samples = []
            for lbl, child in fam.samples():
                if fam.kind == "histogram":
                    bounds, counts, s, c = child.snapshot()
                    samples.append(
                        {
                            "labels": lbl,
                            "count": c,
                            "sum": s,
                            "buckets": [
                                [b, n] for b, n in zip(list(bounds) + ["+Inf"], counts)
                            ],
                        }
                    )
                else:
                    samples.append({"labels": lbl, "value": child.value})
            out[fam.name] = {
                "kind": fam.kind,
                "help": fam.help,
                "labelnames": list(fam.labelnames),
                "samples": samples,
            }
        return out


class _NullMetric:
    """Inert metric/family singleton: every mutator is a no-op, every
    reader is zero. `labels()` returns itself so labeled call sites
    need no branching."""

    __slots__ = ()
    kind = "null"
    name = "null"
    labelnames = ()

    def labels(self, *a, **k):
        return self

    def inc(self, n: float = 1.0) -> None:
        pass

    def dec(self, n: float = 1.0) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def observe(self, v: float) -> None:
        pass

    @property
    def value(self) -> float:
        return 0.0

    @property
    def count(self) -> int:
        return 0

    @property
    def sum(self) -> float:
        return 0.0

    def samples(self):
        return []


NULL_METRIC = _NullMetric()


class NullRegistry:
    """The disabled-observability registry: hands out NULL_METRIC and
    exports nothing."""

    def counter(self, name: str, help: str = "", labelnames: Sequence[str] = ()):
        return NULL_METRIC

    def gauge(self, name: str, help: str = "", labelnames: Sequence[str] = ()):
        return NULL_METRIC

    def histogram(self, name, help="", labelnames=(), buckets=None):
        return NULL_METRIC

    def collect(self) -> List[Family]:
        return []

    def value(self, name: str, **labels) -> float:
        return 0.0

    def snapshot(self) -> Dict[str, dict]:
        return {}


NULL_REGISTRY = NullRegistry()


class _BoundFamily:
    """A Family view with a constant label prefix pre-bound — what a
    ``ScopedRegistry`` hands out. The scope labels (e.g. ``tenant``)
    come FIRST in the parent family's labelnames; the view re-exposes
    the caller's own labelnames exactly as requested, so instrumented
    code is scope-oblivious: ``fam.labels(kind="noop").inc()`` works
    identically whether ``fam`` came from a plain Registry or a
    tenant-scoped view."""

    __slots__ = ("_family", "_scope_values", "_labelnames")

    def __init__(self, family: Family, scope_values: Tuple[str, ...], labelnames: Tuple[str, ...]) -> None:
        self._family = family
        self._scope_values = scope_values
        self._labelnames = tuple(labelnames)

    @property
    def name(self) -> str:
        return self._family.name

    @property
    def kind(self) -> str:
        return self._family.kind

    @property
    def labelnames(self) -> Tuple[str, ...]:
        return self._labelnames

    def labels(self, *values, **kv):
        if kv:
            if values:
                raise ValueError("pass label values positionally or by name, not both")
            try:
                values = tuple(kv[ln] for ln in self._labelnames)
            except KeyError as e:
                raise ValueError(
                    f"missing label {e} for metric {self._family.name!r}"
                ) from e
            if len(kv) != len(self._labelnames):
                extra = set(kv) - set(self._labelnames)
                raise ValueError(
                    f"unknown labels {sorted(extra)} for metric {self._family.name!r}"
                )
        if len(values) != len(self._labelnames):
            raise ValueError(
                f"metric {self._family.name!r} takes labels {self._labelnames}, "
                f"got {tuple(values)}"
            )
        return self._family.labels(*(self._scope_values + tuple(str(v) for v in values)))

    # -- unlabeled proxy (scope-only child) --------------------------------

    def _scope_child(self):
        if self._labelnames:
            raise ValueError(
                f"metric {self._family.name!r} has labels {self._labelnames}; use .labels()"
            )
        return self._family.labels(*self._scope_values)

    def inc(self, n: float = 1.0) -> None:
        self._scope_child().inc(n)

    def dec(self, n: float = 1.0) -> None:
        self._scope_child().dec(n)

    def set(self, v: float) -> None:
        self._scope_child().set(v)

    def observe(self, v: float) -> None:
        self._scope_child().observe(v)

    @property
    def value(self) -> float:
        return self._scope_child().value

    @property
    def count(self) -> int:
        return self._scope_child().count

    @property
    def sum(self) -> float:
        return self._scope_child().sum


class ScopedRegistry:
    """A labelled child view of a parent Registry: every family
    requested through it is created on the PARENT with the scope
    labelnames prepended, and the returned handle pre-binds the scope
    values. This is how the multi-tenant service gives each tenant its
    own accounting without N private registries: one shared parent, one
    ``tenant`` label, and scope-oblivious instrumented layers.

    Unlike the old swap-in/swap-out pattern, concurrent scoped views
    are safe by construction — they never mutate process state, and the
    parent's families/children carry their own locks."""

    def __init__(self, parent: Registry, labels: Dict[str, str]) -> None:
        if not labels:
            raise ValueError("ScopedRegistry needs at least one scope label")
        for ln in labels:
            if not _LABEL_RE.match(ln) or ln.startswith("__"):
                raise ValueError(f"invalid scope label name {ln!r}")
        self.parent = parent
        self.scope_labels = dict(labels)
        self._names = tuple(labels.keys())
        self._values = tuple(str(v) for v in labels.values())

    def scoped(self, **labels) -> "ScopedRegistry":
        """Nested scope: labels accumulate (outer first)."""
        merged = dict(self.scope_labels)
        merged.update(labels)
        return ScopedRegistry(self.parent, merged)

    def _family(self, kind: str, name, help, labelnames, buckets=None) -> _BoundFamily:
        overlap = set(self._names) & set(labelnames)
        if overlap:
            raise ValueError(
                f"metric {name!r} labelnames {tuple(labelnames)} collide with "
                f"scope labels {sorted(overlap)}"
            )
        full = self._names + tuple(labelnames)
        fam = self.parent._get_or_create(name, help, kind, full, buckets)
        return _BoundFamily(fam, self._values, tuple(labelnames))

    def counter(self, name: str, help: str = "", labelnames: Sequence[str] = ()):
        return self._family("counter", name, help, labelnames)

    def gauge(self, name: str, help: str = "", labelnames: Sequence[str] = ()):
        return self._family("gauge", name, help, labelnames)

    def histogram(self, name, help="", labelnames=(), buckets=None):
        return self._family("histogram", name, help, labelnames, buckets)

    def value(self, name: str, **labels) -> float:
        """Read one sample within this scope (0.0 when absent)."""
        return self.parent.value(name, **{**self.scope_labels, **labels})

    def collect(self) -> List[Family]:
        return self.parent.collect()

    def snapshot(self) -> Dict[str, dict]:
        return self.parent.snapshot()


def _registry_scoped(self: Registry, **labels) -> ScopedRegistry:
    """``reg.scoped(tenant="t3")`` — a labelled child view (see
    ScopedRegistry)."""
    return ScopedRegistry(self, labels)


Registry.scoped = _registry_scoped  # type: ignore[attr-defined]

_default_registry = Registry()
_enabled = os.environ.get("KSCHED_OBS", "1").lower() not in ("0", "false", "off")
#: thread-local registry overlay: scoped_registry pushes here, so two
#: threads (e.g. two soak runs, or a test harness around a live
#: service) can hold DIFFERENT scoped registries concurrently without
#: clobbering each other through the process global — the multi-tenant
#: loop's safety requirement (tests/test_obs.py concurrency test)
_tls = threading.local()


def set_enabled(on: bool) -> None:
    """Globally enable/disable observability. Disabled means
    `get_registry()` returns the null registry; handles already taken
    from the real registry keep working (they are plain objects)."""
    global _enabled
    _enabled = bool(on)


def enabled() -> bool:
    return _enabled


def get_registry() -> Registry:
    """The active registry: the calling thread's scoped overlay if one
    is entered, else the process global (or the null registry when obs
    is disabled). Layers that want exact per-run accounting (the soak,
    tests) construct private Registry() instances instead — or push one
    with `scoped_registry`."""
    if not _enabled:
        return NULL_REGISTRY  # type: ignore[return-value]
    stack = getattr(_tls, "stack", None)
    if stack:
        return stack[-1]
    return _default_registry


def set_registry(reg: Registry) -> Registry:
    """Replace the PROCESS-GLOBAL registry; returns the previous one.
    This is the cross-thread-visible swap (threads started afterwards
    see it); thread-confined scoping should use `scoped_registry`,
    which never touches process state.

    Instrumented layers resolve their metric handles at CONSTRUCTION
    time (never at import time), so swapping before building a service
    gives that run a private accounting surface."""
    global _default_registry
    prev = _default_registry
    _default_registry = reg
    return prev


class scoped_registry:
    """``with scoped_registry() as reg:`` — push a fresh (or given)
    registry for the block and restore the previous one after. The
    soak's determinism double-run uses this so each run's counters
    start from zero instead of accumulating in the global registry.

    Since the multi-tenant work this is THREAD-CONFINED and reentrant:
    the registry is pushed onto a thread-local stack (read by
    `get_registry`), so nested scopes compose and concurrent scopes in
    different threads cannot clobber each other — the process-global
    swap-in/swap-out this replaces was neither. Threads SPAWNED inside
    the scope see the process global; pass the registry explicitly
    (every obs component takes a ``registry=`` argument) when a worker
    thread must publish into a scope."""

    def __init__(self, reg: Optional[Registry] = None) -> None:
        self.registry = reg if reg is not None else Registry()

    def __enter__(self) -> Registry:
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        stack.append(self.registry)
        return self.registry

    def __exit__(self, *exc) -> None:
        stack = getattr(_tls, "stack", None)
        if not stack or stack[-1] is not self.registry:
            raise RuntimeError(
                "scoped_registry exited out of order (exit must happen on "
                "the thread — and in the nesting order — that entered it)"
            )
        stack.pop()
