"""Multi-chip layered transport: the production dense solve sharded
over a device mesh.

This is the BASELINE.json north star's multi-chip sentence made
concrete for the PRODUCTION path: the machine axis of the dense
transport problem (solver/layered.py) — the collapsed resource-topology
subtree — is sharded across chips, and the per-superstep combination of
node potentials rides ICI collectives. Where the sharded CSR solver
(parallel/sharded_solver.py) partitions arbitrary graphs by owner node,
this shards the layered formulation's columns:

- machine columns [C, Mloc] (costs, capacities, flows y, prices pm) are
  device-local; Mp is a multiple of 128 so any pow2 mesh divides it;
- row state (supplies, row prices pr, sink price, eps phase) is
  replicated; each superstep reconciles it with one psum/pmax per
  reduction — tiny [C]-sized payloads over ICI;
- the rows' maximal-push allocation needs a GLOBAL exclusive prefix
  over columns in lane order; it distributes as the classic two-level
  scan: local cumsum + all_gather of the D per-device totals + masked
  offset. Global column order equals the unsharded lane order, so the
  sharded solve is BIT-IDENTICAL to the single-device XLA/Pallas solve
  — tests assert exact flow equality on the virtual 8-device mesh.

The algorithm itself is unchanged (synchronous Goldberg–Tarjan
cost-scaling push-relabel; see solver/layered.py for the derivation and
exactness argument).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..solver.layered import (
    BIG as _BIG,
    BIG_D as _BIG_D,
    LayeredProblem,
    LayeredResult,
    pad_geometry,
    solve_layered_host,
    transport_saturate,
    transport_saturate_tiered,
    validate_alpha,
)

AXIS = "x"

from ._compat import (  # noqa: E402  (see _compat.py for the version story)
    IS_EXPERIMENTAL as _SHARD_MAP_EXPERIMENTAL,
    SHARD_MAP_KWARGS as _SHARD_MAP_KWARGS,
    shard_map as _shard_map_native,
    warn_if_fallback as _warn_if_fallback,
)


def _shard_map(*args, **kwargs):
    # the one-time fallback RuntimeWarning fires at program-build time
    # (not import time), so logs attribute it to the process that
    # actually ran a sharded program
    _warn_if_fallback()
    return _shard_map_native(*args, **kwargs)  # kschedlint: disable=unregistered-program -- version-compat wrapper; the real program sites are its callers


def _pcast_varying(x):
    """`lax.pcast(..., to="varying")` on the modern shard_map; under
    the experimental one (check_rep=False, _compat.py) there is no
    varying-ness tracking to satisfy, so identity is correct. Keyed on
    WHICH shard_map was selected — not on pcast's presence — so a jax
    with modern shard_map but no pcast fails loudly at trace time
    instead of silently skipping the varying mark."""
    if _SHARD_MAP_EXPERIMENTAL:
        return x
    return lax.pcast(x, (AXIS,), to="varying")


def _global_excl_prefix(local_vals, axis_name):
    """Exclusive prefix (over the global column order) of per-column
    values sharded along axis_name: local exclusive cumsum + the sum of
    every earlier device's total. local_vals: [..., Mloc]."""
    local_cum = jnp.cumsum(local_vals, axis=-1)
    local_excl = local_cum - local_vals
    local_tot = local_cum[..., -1:]
    # [D, ...] totals of every device, gathered over ICI
    all_tot = lax.all_gather(local_tot, axis_name)  # [D, ..., 1]
    me = lax.axis_index(axis_name)
    d = all_tot.shape[0]
    mask = (jnp.arange(d, dtype=jnp.int32) < me).reshape((d,) + (1,) * (all_tot.ndim - 1))
    offset = jnp.sum(jnp.where(mask, all_tot, 0), axis=0)
    return local_excl + offset


def _sharded_transport_fn(wS, supply, col_cap, eps0, alpha, max_supersteps):
    """Runs INSIDE shard_map: wS [C, Mloc], col_cap [Mloc] local;
    supply [C], eps0 scalar replicated. Returns (y_local, steps, conv)."""
    i32 = jnp.int32
    C, Mloc = wS.shape
    U = jnp.minimum(supply[:, None], col_cap[None, :])

    def excesses(y, z):
        e_row = supply - lax.psum(jnp.sum(y, axis=1), AXIS)  # [C] repl
        e_col = jnp.sum(y, axis=0) - z  # [Mloc] local
        e_sink = lax.psum(jnp.sum(z), AXIS) - jnp.sum(supply)  # repl
        return e_row, e_col, e_sink

    # cold tighten (zeros pm): pr = global max over live arcs of -wS
    live = col_cap > 0
    pm0 = jnp.where(live, i32(0), -i32(_BIG_D))
    pr0 = lax.pmax(
        jnp.max(jnp.where(U > 0, pm0[None, :] - wS, -i32(_BIG_D)), axis=1), AXIS
    )
    has_arc = lax.psum(jnp.sum((U > 0).astype(i32), axis=1), AXIS) > 0
    pr0 = jnp.where(has_arc, pr0, i32(0))
    psink0 = lax.pmin(jnp.min(jnp.where(live, pm0, i32(_BIG_D))), AXIS)
    psink0 = jnp.where(
        lax.psum(jnp.sum(live.astype(i32)), AXIS) > 0, psink0, i32(0)
    )

    def saturate(y, z, pr, pm, psink):
        # column-local, no collectives: the single-device rule applies
        # verbatim to the shard's columns
        return transport_saturate(wS, U, col_cap, y, z, pr, pm, psink)

    def superstep(y, z, pr, pm, psink, eps):
        e_row, e_col, e_sink = excesses(y, z)
        rcf = wS + pr[:, None] - pm[None, :]

        # rows push forward: global in-row exclusive prefix (two-level)
        r_fwd = U - y
        r_adm = jnp.where((r_fwd > 0) & (rcf < 0), r_fwd, i32(0))
        excl = _global_excl_prefix(r_adm, AXIS)
        delta_f = jnp.clip(e_row[:, None] - excl, 0, r_adm)

        # columns push: sink entry first, then backward col->row — all
        # column-local given replicated pr/psink
        r_s = col_cap - z
        adm_s = jnp.where((r_s > 0) & (pm - psink < 0), r_s, i32(0))
        rc_b = pm[None, :] - pr[:, None] - wS
        adm_b = jnp.where((y > 0) & (rc_b < 0), y, i32(0))
        excl_b = adm_s[None, :] + (jnp.cumsum(adm_b, axis=0) - adm_b)
        delta_s = jnp.clip(e_col, 0, adm_s)
        delta_b = jnp.clip(e_col[None, :] - excl_b, 0, adm_b)

        # sink pushes back along sharded columns: global prefix again
        zb_adm = jnp.where((z > 0) & (psink - pm < 0), z, i32(0))
        excl_zb = _global_excl_prefix(zb_adm, AXIS)
        delta_zb = jnp.clip(e_sink - excl_zb, 0, zb_adm)

        y2 = y + delta_f - delta_b
        z2 = z + delta_s - delta_zb

        # jump relabels; row/sink candidates combine over the mesh
        pushed_row = lax.psum(jnp.sum(delta_f, axis=1), AXIS)
        best_row = lax.pmax(
            jnp.max(jnp.where(r_fwd > 0, pm[None, :] - wS, -i32(_BIG)), axis=1),
            AXIS,
        )
        pr2 = jnp.where((e_row > 0) & (pushed_row == 0), best_row - eps, pr)

        pushed_col = delta_s + jnp.sum(delta_b, axis=0)
        cand_col = jnp.maximum(
            jnp.max(jnp.where(y > 0, pr[:, None] + wS, -i32(_BIG)), axis=0),
            jnp.where(r_s > 0, psink, -i32(_BIG)),
        )
        pm2 = jnp.where((e_col > 0) & (pushed_col == 0), cand_col - eps, pm)

        pushed_sink = lax.psum(jnp.sum(delta_zb), AXIS)
        cand_sink = lax.pmax(jnp.max(jnp.where(z > 0, pm, -i32(_BIG))), AXIS)
        psink2 = jnp.where(
            (e_sink > 0) & (pushed_sink == 0), cand_sink - eps, psink
        )
        return y2, z2, pr2, pm2, psink2

    def phase_cond(state):
        *_rest, steps, done = state
        return ~done & (steps < max_supersteps)

    def phase_body(state):
        y, z, pr, pm, psink, eps, steps, done = state
        e_row, e_col, e_sink = excesses(y, z)
        any_active = (
            jnp.any(e_row > 0)
            | (lax.psum(jnp.sum((e_col > 0).astype(i32)), AXIS) > 0)
            | (e_sink > 0)
        )

        def do_step(_):
            y2, z2, pr2, pm2, psink2 = superstep(y, z, pr, pm, psink, eps)
            return y2, z2, pr2, pm2, psink2, eps, steps + 1, jnp.bool_(False)

        def next_phase(_):
            finished = eps <= 1
            new_eps = jnp.maximum(i32(1), eps // alpha)
            y2, z2 = saturate(y, z, pr, pm, psink)
            return (
                jnp.where(finished, y, y2),
                jnp.where(finished, z, z2),
                pr, pm, psink,
                jnp.where(finished, eps, new_eps),
                steps,
                finished,
            )

        return lax.cond(any_active, do_step, next_phase, operand=None)

    # zeros materialized inside the shard body are "unvarying" in
    # shard_map's manual-axes tracking; mark them device-varying so the
    # while carry types match after the first superstep
    y0 = _pcast_varying(jnp.zeros((C, Mloc), i32))
    z0 = _pcast_varying(jnp.zeros((Mloc,), i32))
    state = (y0, z0, pr0, pm0, psink0, eps0, i32(0), jnp.bool_(False))
    y, z, pr, pm, psink, eps, steps, done = lax.while_loop(
        phase_cond, phase_body, state
    )
    e_row, e_col, e_sink = excesses(y, z)
    max_abs = jnp.maximum(
        jnp.maximum(jnp.max(jnp.abs(e_row)), jnp.abs(e_sink)),
        lax.pmax(jnp.max(jnp.abs(e_col)), AXIS),
    )
    return y, steps, done & (max_abs == 0)


@functools.partial(jax.jit, static_argnames=("mesh", "alpha", "max_supersteps"))  # kschedlint: disable=unregistered-program -- sharded transport research path, bit-parity gated by tests/test_sharded_transport.py
def sharded_transport_solve(
    mesh: Mesh, wS, supply, col_cap, eps0,
    alpha: int = 8, max_supersteps: int = 1 << 17,
):
    """Solve the padded transport problem with machine columns sharded
    over `mesh`'s '{AXIS}' axis. wS int32[C, Mp], supply int32[C],
    col_cap int32[Mp]; Mp must be divisible by the mesh size.
    Returns (y [C, Mp], steps, converged), bit-identical to the
    single-device solve."""
    fn = _shard_map(  # kschedlint: disable=unregistered-program -- sharded transport research path, bit-parity gated by tests/test_sharded_transport.py
        functools.partial(
            _sharded_transport_fn, alpha=alpha, max_supersteps=max_supersteps
        ),
        mesh=mesh,
        in_specs=(P(None, AXIS), P(None), P(AXIS), P()),
        out_specs=(P(None, AXIS), P(), P()),
        **_SHARD_MAP_KWARGS,
    )
    return fn(wS, supply, col_cap, eps0)


class ShardedLayeredSolver:
    """Drop-in layered backend (BulkCluster `solve_layered` seam) that
    runs the multi-class solve sharded over a device mesh. Single-class
    and class-degenerate instances use the exact host closed form, as
    the single-device solver does."""

    def __init__(self, mesh: Mesh, alpha: int = 8, max_supersteps: int = 1 << 17):
        assert AXIS in mesh.axis_names, f"mesh must have a {AXIS!r} axis"
        self.mesh = mesh
        self.alpha = validate_alpha(alpha)
        self.max_supersteps = max_supersteps
        self.last_supersteps = 0

    def reset(self) -> None:
        pass

    def _pad_geometry(self, M: int, C: int):
        Mp, n_scale = pad_geometry(M, C)
        d = self.mesh.devices.size
        Mp = -(-Mp // (128 * d)) * 128 * d  # divisible by mesh size
        return Mp, n_scale

    def solve_layered(self, lp: LayeredProblem) -> LayeredResult:
        def solve(wS, sup, cap, eps_init):
            return sharded_transport_solve(
                self.mesh, wS, sup, cap, eps_init,
                alpha=self.alpha, max_supersteps=self.max_supersteps,
            )

        try:
            res = solve_layered_host(
                lp, pad=self._pad_geometry, solve=solve,
                max_supersteps=self.max_supersteps,
            )
        except RuntimeError:
            self.last_supersteps = self.max_supersteps  # budget exhausted
            raise
        self.last_supersteps = res.supersteps
        return res


def _sharded_transport_tiered_fn(wLo, wHi, R, supply, col_cap, eps0,
                                 alpha, max_supersteps, refine_waves=0):
    """Tiered (continuation-priced) twin of _sharded_transport_fn:
    preemption-on rounds over a device mesh. wLo/wHi/R [C, Mloc]
    column-local; supply [C], eps0 replicated. Residual rules are the
    canonical parallel-arc split (solver/layered.py
    _transport_loop_tiered, which this matches BIT-FOR-BIT at equal
    refine_waves); the cross-device structure is identical to the
    plain sharded solve — global in-row prefixes + tiny replicated-row
    reductions over ICI. refine_waves > 0 enables the tiered price
    refinement between eps phases (measured ESSENTIAL at preemption
    scale: 31-58k supersteps/round without it — solver/layered.py
    _transport_loop_tiered docstring); each wave costs two pmin
    reductions. Returns (y_local, steps, conv)."""
    i32 = jnp.int32
    C, Mloc = wLo.shape
    U = jnp.minimum(supply[:, None], col_cap[None, :])
    R = jnp.minimum(R, U)

    def excesses(y, z):
        e_row = supply - lax.psum(jnp.sum(y, axis=1), AXIS)
        e_col = jnp.sum(y, axis=0) - z
        e_sink = lax.psum(jnp.sum(z), AXIS) - jnp.sum(supply)
        return e_row, e_col, e_sink

    # cold tighten against the CHEAP tier (wLo <= wHi cellwise)
    live = col_cap > 0
    pm0 = jnp.where(live, i32(0), -i32(_BIG_D))
    pr0 = lax.pmax(
        jnp.max(jnp.where(U > 0, pm0[None, :] - wLo, -i32(_BIG_D)), axis=1),
        AXIS,
    )
    has_arc = lax.psum(jnp.sum((U > 0).astype(i32), axis=1), AXIS) > 0
    pr0 = jnp.where(has_arc, pr0, i32(0))
    psink0 = lax.pmin(jnp.min(jnp.where(live, pm0, i32(_BIG_D))), AXIS)
    psink0 = jnp.where(
        lax.psum(jnp.sum(live.astype(i32)), AXIS) > 0, psink0, i32(0)
    )

    def saturate(y, z, pr, pm, psink):
        # column-local, no collectives
        return transport_saturate_tiered(
            wLo, wHi, R, U, col_cap, y, z, pr, pm, psink
        )

    def saturate_eps(y, z, pr, pm, psink, eps):
        # column-local (solver/layered.py transport_saturate_eps_tiered)
        rcl = wLo + pr[:, None] - pm[None, :]
        rch = wHi + pr[:, None] - pm[None, :]
        yA = jnp.minimum(y, R)
        yB = y - yA
        yA2 = jnp.where(rcl < -eps, R, jnp.where(rcl > eps, i32(0), yA))
        yB2 = jnp.where(rch < -eps, U - R, jnp.where(rch > eps, i32(0), yB))
        rcs = pm - psink
        z2 = jnp.where(rcs < -eps, col_cap, jnp.where(rcs > eps, i32(0), z))
        return yA2 + yB2, z2

    def price_refine(y, z, pr, pm, psink, eps):
        """_price_refine_tiered over the mesh: bound_m is column-local
        (min over replicated rows), bound_r/bound_s are global column
        minima — one pmin each per wave."""
        big = i32(_BIG)
        big_d = i32(_BIG_D)

        def body(_, state):
            pr, pm, psink = state
            yA = jnp.minimum(y, R)
            yB = y - yA
            bound_m = jnp.minimum(
                jnp.min(jnp.where(R - yA > 0, wLo + pr[:, None] + eps, big),
                        axis=0),
                jnp.min(jnp.where((U - R) - yB > 0, wHi + pr[:, None] + eps,
                                  big), axis=0),
            )
            pm2 = jnp.maximum(jnp.minimum(pm, bound_m), -big_d)
            pm2 = jnp.minimum(pm2, jnp.where(z > 0, psink + eps, big))
            bound_r = lax.pmin(
                jnp.minimum(
                    jnp.min(jnp.where(yA > 0, pm2[None, :] - wLo + eps, big),
                            axis=1),
                    jnp.min(jnp.where(yB > 0, pm2[None, :] - wHi + eps, big),
                            axis=1),
                ),
                AXIS,
            )
            pr2 = jnp.maximum(jnp.minimum(pr, bound_r), -big_d)
            bound_s = lax.pmin(
                jnp.min(jnp.where(col_cap - z > 0, pm2 + eps, big)), AXIS
            )
            psink2 = jnp.maximum(jnp.minimum(psink, bound_s), -big_d)
            return pr2, pm2, psink2

        return lax.fori_loop(0, refine_waves, body, (pr, pm, psink))

    def superstep(y, z, pr, pm, psink, eps):
        e_row, e_col, e_sink = excesses(y, z)
        yA = jnp.minimum(y, R)
        yB = y - yA
        rcl = wLo + pr[:, None] - pm[None, :]
        rch = wHi + pr[:, None] - pm[None, :]

        # rows push forward: both tiers' admissible residuals, one
        # global in-row exclusive prefix
        rA = R - yA
        rB = (U - R) - yB
        r_adm = jnp.where((rA > 0) & (rcl < 0), rA, i32(0)) + jnp.where(
            (rB > 0) & (rch < 0), rB, i32(0)
        )
        excl = _global_excl_prefix(r_adm, AXIS)
        delta_f = jnp.clip(e_row[:, None] - excl, 0, r_adm)

        # columns push: sink entry, then dear-tier returns, then cheap
        # — column-local given replicated pr/psink (same [sink; yB; yA]
        # exclusive-prefix order as the single-device loop)
        r_s = col_cap - z
        adm_s = jnp.where((r_s > 0) & (pm - psink < 0), r_s, i32(0))
        rcb_hi = pm[None, :] - pr[:, None] - wHi
        rcb_lo = pm[None, :] - pr[:, None] - wLo
        adm_bh = jnp.where((yB > 0) & (rcb_hi < 0), yB, i32(0))
        adm_bl = jnp.where((yA > 0) & (rcb_lo < 0), yA, i32(0))
        excl_bh = adm_s[None, :] + (jnp.cumsum(adm_bh, axis=0) - adm_bh)
        excl_bl = (
            adm_s[None, :]
            + jnp.sum(adm_bh, axis=0, keepdims=True)
            + (jnp.cumsum(adm_bl, axis=0) - adm_bl)
        )
        delta_s = jnp.clip(e_col, 0, adm_s)
        delta_bh = jnp.clip(e_col[None, :] - excl_bh, 0, adm_bh)
        delta_bl = jnp.clip(e_col[None, :] - excl_bl, 0, adm_bl)
        delta_b = delta_bh + delta_bl

        # sink pushes back along sharded columns: global prefix
        zb_adm = jnp.where((z > 0) & (psink - pm < 0), z, i32(0))
        excl_zb = _global_excl_prefix(zb_adm, AXIS)
        delta_zb = jnp.clip(e_sink - excl_zb, 0, zb_adm)

        y2 = y + delta_f - delta_b
        z2 = z + delta_s - delta_zb

        # jump relabels: candidates consider both tiers' residuals
        pushed_row = lax.psum(jnp.sum(delta_f, axis=1), AXIS)
        # one pmax: max is associative, so combining the two tiers'
        # LOCAL maxima first is bit-identical and halves the reduction
        cand_row = lax.pmax(
            jnp.maximum(
                jnp.max(jnp.where(rA > 0, pm[None, :] - wLo, -i32(_BIG)),
                        axis=1),
                jnp.max(jnp.where(rB > 0, pm[None, :] - wHi, -i32(_BIG)),
                        axis=1),
            ),
            AXIS,
        )
        pr2 = jnp.where((e_row > 0) & (pushed_row == 0), cand_row - eps, pr)

        pushed_col = delta_s + jnp.sum(delta_b, axis=0)
        cand_col = jnp.maximum(
            jnp.maximum(
                jnp.max(jnp.where(yA > 0, pr[:, None] + wLo, -i32(_BIG)),
                        axis=0),
                jnp.max(jnp.where(yB > 0, pr[:, None] + wHi, -i32(_BIG)),
                        axis=0),
            ),
            jnp.where(r_s > 0, psink, -i32(_BIG)),
        )
        pm2 = jnp.where((e_col > 0) & (pushed_col == 0), cand_col - eps, pm)

        pushed_sink = lax.psum(jnp.sum(delta_zb), AXIS)
        cand_sink = lax.pmax(jnp.max(jnp.where(z > 0, pm, -i32(_BIG))), AXIS)
        psink2 = jnp.where(
            (e_sink > 0) & (pushed_sink == 0), cand_sink - eps, psink
        )
        return y2, z2, pr2, pm2, psink2

    def phase_cond(state):
        *_rest, steps, done = state
        return ~done & (steps < max_supersteps)

    def phase_body(state):
        y, z, pr, pm, psink, eps, steps, done = state
        e_row, e_col, e_sink = excesses(y, z)
        any_active = (
            jnp.any(e_row > 0)
            | (lax.psum(jnp.sum((e_col > 0).astype(i32)), AXIS) > 0)
            | (e_sink > 0)
        )

        def do_step(_):
            y2, z2, pr2, pm2, psink2 = superstep(y, z, pr, pm, psink, eps)
            return y2, z2, pr2, pm2, psink2, eps, steps + 1, jnp.bool_(False)

        def next_phase(_):
            finished = eps <= 1
            new_eps = jnp.maximum(i32(1), eps // alpha)
            if refine_waves:
                pr2, pm2, psink2 = price_refine(y, z, pr, pm, psink, new_eps)
                y2, z2 = saturate_eps(y, z, pr2, pm2, psink2, new_eps)
            else:
                pr2, pm2, psink2 = pr, pm, psink
                y2, z2 = saturate(y, z, pr, pm, psink)
            return (
                jnp.where(finished, y, y2),
                jnp.where(finished, z, z2),
                jnp.where(finished, pr, pr2),
                jnp.where(finished, pm, pm2),
                jnp.where(finished, psink, psink2),
                jnp.where(finished, eps, new_eps),
                steps,
                finished,
            )

        return lax.cond(any_active, do_step, next_phase, operand=None)

    y0 = _pcast_varying(jnp.zeros((C, Mloc), i32))
    z0 = _pcast_varying(jnp.zeros((Mloc,), i32))
    state = (y0, z0, pr0, pm0, psink0, eps0, i32(0), jnp.bool_(False))
    y, z, pr, pm, psink, eps, steps, done = lax.while_loop(
        phase_cond, phase_body, state
    )
    e_row, e_col, e_sink = excesses(y, z)
    max_abs = jnp.maximum(
        jnp.maximum(jnp.max(jnp.abs(e_row)), jnp.abs(e_sink)),
        lax.pmax(jnp.max(jnp.abs(e_col)), AXIS),
    )
    return y, steps, done & (max_abs == 0)


@functools.partial(
    jax.jit,  # kschedlint: disable=unregistered-program -- sharded transport research path, bit-parity gated by tests/test_sharded_transport.py
    static_argnames=("mesh", "alpha", "max_supersteps", "refine_waves"),
)
def sharded_transport_solve_tiered(
    mesh: Mesh, wLo, wHi, R, supply, col_cap, eps0,
    alpha: int = 8, max_supersteps: int = 1 << 17, refine_waves: int = 0,
):
    """Tiered (preemption-on) transport with machine columns sharded
    over `mesh`'s '{AXIS}' axis — the multi-chip form of the
    keep-arcs re-solve (graph_manager.go:855-888). wLo/wHi/R
    int32[C, Mp]; Mp divisible by the mesh size. Returns
    (y [C, Mp], steps, converged), bit-identical to the single-device
    tiered solve AT EQUAL refine_waves (production single-device
    preemption runs refine_waves=8 — pass it here too for the same
    superstep counts; the host-solver bit-parity convention keeps 0
    the default)."""
    fn = _shard_map(  # kschedlint: disable=unregistered-program -- sharded transport research path, bit-parity gated by tests/test_sharded_transport.py
        functools.partial(
            _sharded_transport_tiered_fn,
            alpha=alpha, max_supersteps=max_supersteps,
            refine_waves=refine_waves,
        ),
        mesh=mesh,
        in_specs=(P(None, AXIS), P(None, AXIS), P(None, AXIS), P(None),
                  P(AXIS), P()),
        out_specs=(P(None, AXIS), P(), P()),
        **_SHARD_MAP_KWARGS,
    )
    return fn(wLo, wHi, R, supply, col_cap, eps0)
