"""Batched what-if (shadow) solves: the data-parallel axis.

The reference has no data parallelism to mirror (SURVEY §2.5) — the
TPU-native analogue is batch-parallel scheduling scenarios: "what if we
drained machine m?", "what if 2k more rabbits arrived?" — K independent
transport solves evaluated in ONE compiled call via jax.vmap over the
scenario axis, sharing the padded geometry so XLA compiles one batched
program (and the VPU processes scenarios side by side) instead of K
dispatches.

Operators use this for placement planning: score every drain candidate
before a maintenance window, or probe admission headroom per class,
without perturbing the live cluster. The underlying solve is the same
cost-scaling transport as the production round (solver/layered.py);
scenario results carry objective, per-class placements, and unscheduled
counts.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import numpy as np
import jax
import jax.numpy as jnp

from ..solver.layered import (
    COST_SCALE_LIMIT,
    choose_eps0,
    pad_geometry,
    solve_single_class,
    transport_fori,
    validate_alpha,
)


@dataclass
class ScenarioBatchResult:
    y: np.ndarray  # int64[K, C, M] placements per scenario
    objective: np.ndarray  # int64[K] in full-graph units
    num_unsched: np.ndarray  # int64[K]
    converged: np.ndarray  # bool[K]


def _batch_solve(wS, supply, col_cap, n_scale, alpha, max_supersteps,
                 class_degenerate):
    """Batched transport over the leading scenario axis, one compiled
    program per (K, C, Mp) geometry.

    C == 1 vmaps the exact closed form (pure elementwise+sort — batching
    is free). C >= 2 runs `lax.map` over the convergence-exiting solve
    (the fused Pallas kernel on TPU): scenarios execute sequentially on
    device, so the batch costs the SUM of per-scenario supersteps —
    vmapping the while_loop instead would charge every scenario the
    K-wide superstep work of the slowest one, measured ~3 orders of
    magnitude slower on contended 64-scenario batches."""
    K, C, Mp = wS.shape
    if C == 1:

        def one(w, s, cap):
            y = solve_single_class(w[0], s[0], cap)[None, :]
            return y, jnp.bool_(True)

        return jax.vmap(one)(wS, supply, col_cap)

    def one(args):
        w, s, cap = args
        # per-scenario adaptive start (oversubscribed scenarios — e.g.
        # drain what-ifs removing more capacity than the backlog fits —
        # take the full-range schedule; see choose_eps0)
        eps_full = jnp.maximum(jnp.max(jnp.abs(w)), jnp.int32(1))
        y, _pm, _steps, conv = transport_fori(
            w, s, cap, max_supersteps, alpha=alpha,
            eps0=choose_eps0(
                n_scale, eps_full, jnp.sum(s), jnp.sum(cap[:-1])
            ),
            class_degenerate=class_degenerate,
        )
        return y, conv

    return jax.lax.map(one, (wS, supply, col_cap))


_batch_solve_jit = functools.partial(jax.jit, static_argnames=(  # kschedlint: disable=unregistered-program -- lax.map batch over the layered solve; the inner program is registered as layered_solve
    "n_scale", "alpha", "max_supersteps", "class_degenerate"
))(_batch_solve)


class WhatIfSolver:
    """Batch scenario evaluation over a shared cluster geometry.

    All scenarios share (num_machines, num_classes) — the compiled
    program is reused across calls with the same batch size K."""

    def __init__(
        self,
        num_machines: int,
        num_classes: int,
        unsched_cost: int,
        ec_cost: int,
        alpha: int = 8,
        max_supersteps: int = 1 << 17,
    ) -> None:
        self.M = num_machines
        self.C = num_classes
        self.unsched_cost = int(unsched_cost)
        self.ec_cost = int(ec_cost)
        self.alpha = validate_alpha(alpha)
        self.max_supersteps = max_supersteps
        self.Mp, self.n_scale = pad_geometry(num_machines, num_classes)

    def solve_batch(
        self,
        cost_cm: np.ndarray,  # int[K, C, M] or [C, M] broadcast to all
        supply: np.ndarray,  # int[K, C]
        col_cap: np.ndarray,  # int[K, M]
    ) -> ScenarioBatchResult:
        supply = np.asarray(supply, np.int64)  # kschedlint: host-only (host staging; cast at the jit boundary)
        col_cap = np.asarray(col_cap, np.int64)  # kschedlint: host-only (host staging; cast at the jit boundary)
        K = supply.shape[0]
        if cost_cm.ndim == 2:
            cost_cm = np.broadcast_to(cost_cm, (K,) + cost_cm.shape)
        cost_cm = np.asarray(cost_cm, np.int64)  # kschedlint: host-only (host staging; cast at the jit boundary)
        assert cost_cm.shape == (K, self.C, self.M), cost_cm.shape
        assert supply.shape == (K, self.C) and col_cap.shape == (K, self.M)

        w = cost_cm + self.ec_cost - self.unsched_cost
        max_w = int(np.abs(w).max()) if w.size else 0
        if max_w * self.n_scale >= COST_SCALE_LIMIT:
            raise OverflowError(
                f"scaled what-if costs overflow int32: max|w|={max_w} * {self.n_scale}"
            )
        totals = supply.sum(axis=1)
        wP = np.zeros((K, self.C, self.Mp), np.int32)
        wP[:, :, : self.M] = w * self.n_scale
        capP = np.zeros((K, self.Mp), np.int32)
        capP[:, : self.M] = col_cap
        capP[:, -1] = totals

        # Class-degenerate batches (every class the same cost row in
        # every scenario — the stock no-cost-model configuration) take
        # the closed-form collapse; the iterative solve herds on
        # identical costs (see solver/layered.py transport_fori).
        degenerate = bool((cost_cm == cost_cm[:, :1, :]).all())
        y, conv = _batch_solve_jit(
            jnp.asarray(wP),
            jnp.asarray(supply.astype(np.int32)),
            jnp.asarray(capP),
            self.n_scale,
            self.alpha,
            self.max_supersteps,
            degenerate,
        )
        y_np = np.asarray(y).astype(np.int64)[:, :, : self.M]  # kschedlint: host-only (host decode of device results)
        placed = y_np.sum(axis=(1, 2))
        objective = self.unsched_cost * (totals - placed) + (
            (cost_cm + self.ec_cost) * y_np
        ).sum(axis=(1, 2))
        return ScenarioBatchResult(
            y=y_np,
            objective=objective,
            num_unsched=totals - placed,
            converged=np.asarray(conv),
        )


def _cluster_snapshot(cluster):
    """(machine_free[M], base_supply[C], cost_cm[C,M]) of a BulkCluster's
    current round inputs — the same derivation the production round uses
    (scheduler/bulk.py _round_layered), factored so the what-if builders
    cannot drift from it."""
    C, M = cluster.C, cluster.M
    cluster._refresh_capacities()
    pu_free = cluster.S - cluster.pu_running
    pu_free[~np.repeat(cluster.machine_enabled, cluster.P)] = 0
    machine_free = pu_free.reshape(M, cluster.P).sum(axis=1)
    unplaced = cluster.task_live & (cluster.task_pu < 0)
    base_supply = np.bincount(cluster.task_class[unplaced], minlength=C)
    cost_cm = cluster.cost[
        cluster.a_ecm0 : cluster.a_ecm0 + C * M
    ].reshape(C, M).astype(np.int64)  # kschedlint: host-only (host decode of device results)
    return machine_free, base_supply, cost_cm


def drain_scenarios(cluster, machine_indices) -> ScenarioBatchResult:
    """Score draining each candidate machine: scenario k reschedules the
    cluster's current unplaced backlog PLUS machine k's displaced tasks
    with machine k's capacity removed. Returns one result per candidate
    (lower objective = cheaper drain)."""
    machine_indices = np.asarray(machine_indices, np.int64)  # kschedlint: host-only (host staging; cast at the jit boundary)
    K = len(machine_indices)
    C, M = cluster.C, cluster.M
    if K and (machine_indices.min() < 0 or machine_indices.max() >= M):
        # A negative index would silently alias the "unplaced" sentinel
        # in the placed-machine map and drain the wrong machine.
        raise IndexError(f"machine indices must be in [0, {M}), got {machine_indices}")

    machine_free, base_supply, cost_cm = _cluster_snapshot(cluster)
    placed_machine = np.where(
        cluster.task_live & (cluster.task_pu >= 0),
        cluster.task_pu // cluster.P,
        -1,
    )

    supply = np.tile(base_supply, (K, 1))
    col_cap = np.tile(machine_free, (K, 1))
    for k, m in enumerate(machine_indices):
        displaced = placed_machine == m
        supply[k] += np.bincount(cluster.task_class[displaced], minlength=C)
        col_cap[k, m] = 0

    solver = WhatIfSolver(
        M, C, unsched_cost=cluster.unsched_cost, ec_cost=cluster.ec_cost
    )
    return solver.solve_batch(cost_cm, supply, col_cap)


def surge_scenarios(cluster, extra_supply: np.ndarray) -> ScenarioBatchResult:
    """Score admission headroom: scenario k adds extra_supply[k] (per
    class) to the current backlog against today's free capacity."""
    extra_supply = np.asarray(extra_supply, np.int64)  # kschedlint: host-only (host staging; cast at the jit boundary)
    K = extra_supply.shape[0]
    C, M = cluster.C, cluster.M
    assert extra_supply.shape == (K, C)

    machine_free, base_supply, cost_cm = _cluster_snapshot(cluster)
    solver = WhatIfSolver(
        M, C, unsched_cost=cluster.unsched_cost, ec_cost=cluster.ec_cost
    )
    return solver.solve_batch(
        cost_cm,
        base_supply[None, :] + extra_supply,
        np.tile(machine_free, (K, 1)),
    )
