"""One compat seam for the shard_map API across jax versions.

jax >= 0.6 exports `jax.shard_map` (with varying-ness tracking that
`lax.pcast` feeds); earlier versions ship it under
`jax.experimental.shard_map`, whose replication checker has no rule
for `lax.while_loop` — there the solvers pass `check_rep=False` (their
psum/pmin combines are rep-correct by construction: owner-masked dense
vectors) and pcast-style varying marks are unnecessary. Both sharded
modules import from here so the two detections can never diverge.
"""

try:
    from jax import shard_map

    SHARD_MAP_KWARGS: dict = {}
    IS_EXPERIMENTAL = False
except ImportError:
    from jax.experimental.shard_map import shard_map  # noqa: F401

    SHARD_MAP_KWARGS = {"check_rep": False}
    IS_EXPERIMENTAL = True
