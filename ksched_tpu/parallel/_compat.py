"""One compat seam for the shard_map API across jax versions.

jax >= 0.6 exports `jax.shard_map` (with varying-ness tracking that
`lax.pcast` feeds); earlier versions ship it under
`jax.experimental.shard_map`, whose replication checker has no rule
for `lax.while_loop` — there the solvers pass `check_rep=False` (their
psum/pmin combines are rep-correct by construction: owner-masked dense
vectors) and pcast-style varying marks are unnecessary. Both sharded
modules import from here so the two detections can never diverge.

The fallback is no longer silent: the first sharded program built on
the experimental path emits a one-time RuntimeWarning naming the jax
version and the `check_rep=False` consequence, so a production log can
distinguish "native shard_map with replication checking" from "legacy
fallback trusting the solvers' own rep discipline" without reading
this file.
"""

import warnings

try:
    from jax import shard_map

    SHARD_MAP_KWARGS: dict = {}
    IS_EXPERIMENTAL = False
except ImportError:
    from jax.experimental.shard_map import shard_map  # noqa: F401

    SHARD_MAP_KWARGS = {"check_rep": False}
    IS_EXPERIMENTAL = True

_WARNED = False


def warn_if_fallback() -> None:
    """One-time RuntimeWarning when running on the experimental
    shard_map fallback: replication checking is OFF (check_rep=False),
    so a rep-incorrect collective would corrupt silently instead of
    failing to trace — the sharded parity suites are the guard. Called
    by every sharded solver factory; a no-op on jax >= 0.6."""
    global _WARNED
    if not IS_EXPERIMENTAL or _WARNED:
        return
    _WARNED = True
    import jax

    warnings.warn(
        f"jax {jax.__version__} has no jax.shard_map; sharded solvers "
        "fall back to jax.experimental.shard_map with check_rep=False "
        "(replication checking disabled — collective correctness rests "
        "on the owner-masked psum discipline and the bit-parity "
        "suites). Upgrade to jax >= 0.6 for native varying-ness "
        "tracking.",
        RuntimeWarning,
        stacklevel=3,
    )
