"""Multi-chip MCMF: the push-relabel solve sharded over a device mesh.

The reference scales by incremental re-solves in one external process
(SURVEY §2.5); the TPU rebuild scales across chips: residual entries are
partitioned by the OWNER of their source node (so every node's outgoing
entries — the unit of push/relabel work — live on exactly one shard),
while flow and potentials are replicated and combined with
`jax.lax.psum` over the mesh axis each superstep. ICI traffic per
superstep is one [N] node-vector and one [M] arc-vector reduction.

Design invariants (mirroring solver/jax_solver.py, which documents the
algorithm):
- no scatters: per-shard segment reductions use the same CSR-sorted
  cumsum/gather + associative-scan machinery; cross-shard combination is
  psum of owner-masked dense vectors (each node/arc has exactly one
  contributing shard, so psum implements "select the owner's value");
- pushes and relabels for a node are computed entirely on its owner
  shard from replicated state, so the single-chip eps-optimality
  argument carries over unchanged;
- price tightening (Bellman-Ford sweeps) distributes the same way: the
  per-node min over outgoing entries is owner-local, then psum-combined.

Built for `jax.sharding.Mesh` + `shard_map`; exercised on a virtual
8-device CPU mesh in tests and by __graft_entry__.dryrun_multichip.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..graph.device_export import FlowProblem
from ..solver.base import FlowResult, FlowSolver
from ..solver.layered import validate_alpha

_BIG = jnp.int32(1 << 30)
_BIG_D = 1 << 28


@dataclass
class ShardedPlan:
    """Host-prebuilt per-shard CSR data, stacked on a leading shard axis."""

    # [D, E] per-shard sorted entries (E = padded per-shard entry count)
    s_arc: np.ndarray
    s_sign: np.ndarray
    s_src: np.ndarray
    s_dst: np.ndarray
    s_segstart: np.ndarray  # local sorted index of entry's segment start
    s_isstart: np.ndarray
    s_valid: np.ndarray  # bool, padding mask
    # [D, N] per-node segment boundaries within the shard's local entries
    node_first: np.ndarray
    node_last: np.ndarray
    node_nonempty: np.ndarray
    owned: np.ndarray  # bool [D, N]: shard owns this node
    # [D, M] position of arc j's fwd/bwd entry in this shard (E = zero pad)
    pos_fwd: np.ndarray
    pos_bwd: np.ndarray
    src: np.ndarray  # [M] the endpoints this plan was built for
    dst: np.ndarray


def node_owner(node_ids: np.ndarray, num_nodes: int, num_shards: int) -> np.ndarray:
    """Owner shard per node: contiguous range partition, so resource
    subtrees laid out contiguously stay on one shard."""
    per = (num_nodes + num_shards - 1) // num_shards
    return np.minimum(node_ids // per, num_shards - 1)


def build_sharded_plan(src: np.ndarray, dst: np.ndarray, num_nodes: int, num_shards: int) -> ShardedPlan:
    m = len(src)
    esrc = np.concatenate([src, dst])
    edst = np.concatenate([dst, src])
    earc = np.concatenate([np.arange(m), np.arange(m)]).astype(np.int32)
    esign = np.concatenate([np.ones(m), -np.ones(m)]).astype(np.int32)
    owner = node_owner(esrc, num_nodes, num_shards)

    per_shard = [np.nonzero(owner == d)[0] for d in range(num_shards)]
    e_max = max((len(ix) for ix in per_shard), default=1)
    # One spare slot past the densest shard: pos_fwd/pos_bwd default
    # there, and it is invalid on every shard, so padded gathers read 0.
    e_pad = e_max + 1

    def stack(fill, dtype):
        return np.full((num_shards, e_pad), fill, dtype=dtype)

    s_arc = stack(0, np.int32)
    s_sign = stack(1, np.int32)
    s_src = stack(0, np.int32)
    s_dst = stack(0, np.int32)
    s_segstart = stack(0, np.int32)
    s_isstart = np.zeros((num_shards, e_pad), bool)
    s_valid = np.zeros((num_shards, e_pad), bool)
    node_first = np.zeros((num_shards, num_nodes), np.int32)
    node_last = np.zeros((num_shards, num_nodes), np.int32)
    node_nonempty = np.zeros((num_shards, num_nodes), bool)
    owned = np.zeros((num_shards, num_nodes), bool)
    pos_fwd = np.full((num_shards, m), e_pad - 1, np.int32)
    pos_bwd = np.full((num_shards, m), e_pad - 1, np.int32)

    node_ids = np.arange(num_nodes)
    node_owner_arr = node_owner(node_ids, num_nodes, num_shards)
    for d in range(num_shards):
        ix = per_shard[d]
        k = len(ix)
        order = np.argsort(esrc[ix], kind="stable")
        lsrc = esrc[ix][order]
        s_src[d, :k] = lsrc
        s_dst[d, :k] = edst[ix][order]
        s_arc[d, :k] = earc[ix][order]
        s_sign[d, :k] = esign[ix][order]
        s_valid[d, :k] = True
        counts = np.bincount(lsrc, minlength=num_nodes)
        row_ptr = np.zeros(num_nodes + 1, np.int64)  # kschedlint: host-only (numpy plan build)
        row_ptr[1:] = np.cumsum(counts)
        s_segstart[d, :k] = row_ptr[lsrc]
        starts = np.unique(row_ptr[lsrc]).astype(np.int64)  # kschedlint: host-only (numpy plan build)
        s_isstart[d, starts] = True
        node_first[d] = np.minimum(row_ptr[:-1], max(e_pad - 1, 0))
        node_last[d] = np.maximum(row_ptr[1:] - 1, 0)
        node_nonempty[d] = row_ptr[1:] > row_ptr[:-1]
        owned[d] = node_owner_arr == d
        # Map arc -> local entry position (padding position reads delta 0
        # because padded entries are never admissible).
        local_pos = np.empty(k, np.int64)  # kschedlint: host-only (numpy plan build)
        local_pos[:] = np.arange(k)
        glob = ix[order]
        is_fwd = glob < m
        pos_fwd[d, earc[ix][order][is_fwd]] = local_pos[is_fwd]
        pos_bwd[d, earc[ix][order][~is_fwd]] = local_pos[~is_fwd]
    return ShardedPlan(
        s_arc=s_arc,
        s_sign=s_sign,
        s_src=s_src,
        s_dst=s_dst,
        s_segstart=s_segstart,
        s_isstart=s_isstart,
        s_valid=s_valid,
        node_first=node_first,
        node_last=node_last,
        node_nonempty=node_nonempty,
        owned=owned,
        pos_fwd=pos_fwd,
        pos_bwd=pos_bwd,
        src=src.copy(),
        dst=dst.copy(),
    )


from ..solver.jax_solver import _seg_sum as _seg_sum_local  # same CSR layout


def _seg_scan(vals, isstart, combine_val):
    def combine(a, b):
        f1, v1 = a
        f2, v2 = b
        return f1 | f2, jnp.where(f2, v2, combine_val(v1, v2))

    _, scanned = lax.associative_scan(combine, (isstart, vals))
    return scanned


def make_sharded_solver(mesh: Mesh, axis: str, alpha: int, max_supersteps: int, tighten_sweeps: int = 32, telemetry_cap: int = 0):
    """Build the jitted sharded solve fn over the given mesh axis. The
    per-shard plan arrays arrive as call arguments (sharded on their
    leading axis); nothing is baked into the compiled function besides
    shapes. telemetry_cap > 0 appends the replicated soltel ring
    (obs/soltel.py) to the outputs: per-shard counter contributions are
    psum-combined, so the rows are GLOBAL — identical on every shard —
    and cap=0 traces the exact pre-telemetry program."""
    from ..obs.soltel import SOLTEL_WIDTH
    from ._compat import SHARD_MAP_KWARGS as shard_map_kwargs, shard_map

    spec_sharded = P(axis)
    spec_repl = P()

    def solve_shard(
        cap, cost, supply, flow0, eps_init, step_cap,
        s_arc, s_sign, s_src, s_dst, s_segstart, s_isstart, s_valid,
        node_first, node_last, node_nonempty, owned, pos_fwd, pos_bwd,
    ):
        # Inside shard_map: leading shard axis is stripped; arrays are
        # the local shard's slices. cap/cost/supply/flow0 replicated.
        i32 = jnp.int32
        (s_arc, s_sign, s_src, s_dst, s_segstart, s_isstart, s_valid,
         node_first, node_last, node_nonempty, owned, pos_fwd, pos_bwd) = (
            x[0] for x in (s_arc, s_sign, s_src, s_dst, s_segstart, s_isstart, s_valid,
                           node_first, node_last, node_nonempty, owned, pos_fwd, pos_bwd)
        )
        s_cost = s_sign * cost[s_arc]

        def residual(flow):
            a_flow = flow[s_arc]
            r = jnp.where(s_sign > 0, cap[s_arc] - a_flow, a_flow)
            return jnp.where(s_valid, r, i32(0))

        def excess_of(flow):
            contrib = _seg_sum_local(
                jnp.where(s_valid, s_sign * flow[s_arc], i32(0)),
                node_first, node_last, node_nonempty,
            )
            contrib = jnp.where(owned, contrib, i32(0))
            total = lax.psum(contrib, axis)
            return supply - total

        def tighten(flow):
            r = residual(flow)
            excess0 = excess_of(flow)
            d0 = jnp.where(excess0 < 0, i32(0), i32(_BIG_D))

            def t_cond(state):
                _d, changed, it = state
                return changed & (it < tighten_sweeps)

            def t_body(state):
                d, _, it = state
                cand = jnp.where(r > 0, s_cost + d[s_dst], i32(_BIG_D))
                scanned = _seg_scan(cand, s_isstart, jnp.minimum)
                best = jnp.where(node_nonempty, scanned[node_last], i32(_BIG_D))
                best = jnp.where(owned, best, i32(_BIG_D))
                best = lax.pmin(best, axis)
                # clamp below: transient negative-cost residual cycles
                # must not run d toward int32 wraparound
                d2 = jnp.maximum(jnp.minimum(d, best), -i32(_BIG_D))
                return d2, jnp.any(d2 != d), it + 1

            d, _, _ = lax.while_loop(t_cond, t_body, (d0, jnp.bool_(True), i32(0)))
            return -jnp.minimum(d, i32(_BIG_D))

        # pos_fwd/pos_bwd point either at the arc's real local entry or
        # at the spare padded slot (invalid on every shard), so gathers
        # through them read 0 after the s_valid mask.
        def arc_delta(delta):
            dz = jnp.where(s_valid, delta, i32(0))
            return lax.psum(dz[pos_fwd] - dz[pos_bwd], axis)

        def superstep(flow, p, eps, excess):
            r = residual(flow)
            rc = s_cost + p[s_src] - p[s_dst]
            e_at = excess[s_src]
            admissible = (r > 0) & (rc < 0) & (e_at > 0) & s_valid
            r_adm = jnp.where(admissible, r, i32(0))
            cum = jnp.cumsum(r_adm)
            excl = cum - r_adm
            prefix_before = excl - excl[s_segstart]
            delta = jnp.clip(e_at - prefix_before, 0, r_adm)
            new_flow = flow + arc_delta(delta)

            pushed = _seg_sum_local(delta, node_first, node_last, node_nonempty)
            sum_r = _seg_sum_local(r, node_first, node_last, node_nonempty)
            cand = jnp.where(r > 0, p[s_dst] - s_cost, -_BIG)
            scanned = _seg_scan(cand, s_isstart, jnp.maximum)
            best = jnp.where(node_nonempty, scanned[node_last], -_BIG)
            relabel = (excess > 0) & (pushed == 0) & (sum_r > 0) & owned
            p_local = jnp.where(relabel, best - eps, jnp.where(owned, p, i32(0)))
            new_p = lax.psum(jnp.where(owned, p_local, i32(0)), axis)
            if not telemetry_cap:
                return new_flow, new_p, ()
            # soltel cols 3..6: per-shard contributions psum'd to global
            # counts (each entry/owned node contributes on one shard)
            aux = (
                lax.psum(jnp.sum(jnp.where(s_valid, delta, i32(0))), axis),
                lax.psum(jnp.sum(relabel.astype(i32)), axis),
                lax.psum(
                    jnp.sum(((s_sign > 0) & s_valid & (r == 0)).astype(i32)),
                    axis,
                ),
                lax.psum(jnp.sum(admissible.astype(i32)), axis),
            )
            return new_flow, new_p, aux

        def sat_full(flow, p):
            rc = s_cost + p[s_src] - p[s_dst]
            r = residual(flow)
            want = jnp.where((rc < 0) & s_valid & (s_sign > 0), cap[s_arc], i32(-1))
            want = jnp.where((rc < 0) & s_valid & (s_sign < 0), i32(0), want)
            # translate per-entry wishes to per-arc flow targets
            wz = jnp.where(s_valid, want, i32(-1))
            tgt_f = wz[pos_fwd]
            tgt_b = wz[pos_bwd]
            tgt = jnp.maximum(lax.pmax(tgt_f, axis), lax.pmax(tgt_b, axis))
            return jnp.where(tgt >= 0, tgt, flow)

        if telemetry_cap:
            from ..obs import soltel as _soltel

            _tel_rows_iota = _soltel.device_rows_iota(telemetry_cap)

        def tel_row(eps, excess, aux):
            # excess is already the psum-combined global [N] vector,
            # identical on every shard — no further combine needed
            return _soltel.device_row(
                eps,
                jnp.sum((excess > 0).astype(i32)),
                jnp.sum(jnp.maximum(excess, 0)),
                *aux,
            )

        def tel_write(tel, steps, row):
            return _soltel.device_ring_write(
                tel, steps, row, telemetry_cap, _tel_rows_iota
            )

        def phase_cond(state):
            steps, done = state[3], state[4]
            return ~done & (steps < step_cap)

        def phase_body(state):
            if telemetry_cap:
                flow, p, eps, steps, done, tel = state
            else:
                flow, p, eps, steps, done = state
            excess = excess_of(flow)
            any_active = jnp.any(excess > 0)

            def do_superstep(_):
                f2, p2, aux = superstep(flow, p, eps, excess)
                if not telemetry_cap:
                    return f2, p2, eps, steps + 1, jnp.bool_(False)
                tel2 = tel_write(tel, steps, tel_row(eps, excess, aux))
                return f2, p2, eps, steps + 1, jnp.bool_(False), tel2

            def next_phase(_):
                finished = eps <= 1
                new_eps = jnp.maximum(i32(1), eps // alpha)
                f2 = jnp.where(finished, flow, sat_full(flow, p))
                out = (
                    f2, p, jnp.where(finished, eps, new_eps), steps, finished
                )
                return out + ((tel,) if telemetry_cap else ())

            return lax.cond(any_active, do_superstep, next_phase, operand=None)

        p0 = tighten(flow0)
        flow1 = sat_full(flow0, p0)
        state = (flow1, p0, eps_init, i32(0), jnp.bool_(False))
        if telemetry_cap:
            state = state + (jnp.zeros((telemetry_cap, SOLTEL_WIDTH), i32),)
            flow, p, eps, steps, done, tel = lax.while_loop(
                phase_cond, phase_body, state
            )
        else:
            flow, p, eps, steps, done = lax.while_loop(
                phase_cond, phase_body, state
            )
        converged = done & (jnp.max(jnp.abs(excess_of(flow))) == 0)
        p_overflow = jnp.max(jnp.abs(p)) >= (1 << 30)
        base = (flow, steps, converged, p_overflow)
        if telemetry_cap:
            return base + (tel,)
        return base

    in_specs = (
        spec_repl, spec_repl, spec_repl, spec_repl, spec_repl, spec_repl,
        spec_sharded, spec_sharded, spec_sharded, spec_sharded, spec_sharded,
        spec_sharded, spec_sharded, spec_sharded, spec_sharded, spec_sharded,
        spec_sharded, spec_sharded, spec_sharded,
    )
    out_specs = (spec_repl, spec_repl, spec_repl, spec_repl)
    if telemetry_cap:
        out_specs = out_specs + (spec_repl,)
    fn = shard_map(
        solve_shard, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        **shard_map_kwargs,
    )
    return jax.jit(fn)


class ShardedJaxSolver(FlowSolver):
    """Push-relabel MCMF sharded over a jax Mesh axis."""

    def __init__(self, mesh: Mesh, axis: str = "x", alpha: int = 8, max_supersteps: int = 50_000, warm_start: bool = True, telemetry: Optional[int] = None):
        self.mesh = mesh
        self.axis = axis
        self.alpha = validate_alpha(alpha)
        self.max_supersteps = max_supersteps
        self.warm_start = warm_start
        self.telemetry = telemetry
        self._plan: Optional[ShardedPlan] = None
        self._plan_dev = None
        self._solve_fn = None
        self._solve_fn_cap = 0  # telemetry_cap the cached fn was built for
        self._prev: Optional[np.ndarray] = None
        self.last_supersteps = 0
        self.last_telemetry = None

    def reset(self) -> None:
        self._prev = None

    @property
    def num_shards(self) -> int:
        return int(np.prod([self.mesh.shape[a] for a in self.mesh.axis_names if a == self.axis]))

    def solve(self, problem: FlowProblem) -> FlowResult:
        from ..obs import soltel

        n = problem.num_nodes
        m = len(problem.src)
        if m == 0 or problem.num_arcs == 0:
            if (problem.excess > 0).any():
                raise RuntimeError("infeasible flow problem: supply but no arcs")
            self.last_telemetry = None
            return FlowResult(flow=np.zeros(m, dtype=np.int64), objective=0, iterations=0)  # kschedlint: host-only (FlowResult contract is int64)
        src = problem.src.astype(np.int32)
        dst = problem.dst.astype(np.int32)
        cap = problem.cap.astype(np.int32)
        supply = problem.excess.astype(np.int32)
        max_cost = int(np.abs(problem.cost).max()) if m else 0
        if max_cost * n >= (1 << 30):
            raise OverflowError("scaled costs overflow int32")
        cost = problem.cost.astype(np.int32) * np.int32(n)

        tel_cap = soltel.resolve_cap(self.telemetry)
        prev_plan = self._plan
        plan = prev_plan
        if plan is None or len(plan.src) != m or plan.node_first.shape[1] != n or not (
            np.array_equal(plan.src, src) and np.array_equal(plan.dst, dst)
        ):
            plan = build_sharded_plan(src, dst, n, self.num_shards)
            self._plan = plan
            self._plan_dev = tuple(
                jnp.asarray(x)
                for x in (
                    plan.s_arc, plan.s_sign, plan.s_src, plan.s_dst,
                    plan.s_segstart, plan.s_isstart, plan.s_valid,
                    plan.node_first, plan.node_last, plan.node_nonempty,
                    plan.owned, plan.pos_fwd, plan.pos_bwd,
                )
            )
            self._solve_fn = None
        if self._solve_fn is None or self._solve_fn_cap != tel_cap:
            self._solve_fn = make_sharded_solver(
                self.mesh, self.axis, self.alpha, self.max_supersteps,
                telemetry_cap=tel_cap,
            )
            self._solve_fn_cap = tel_cap

        flow0 = np.zeros(m, dtype=np.int32)
        if (
            self.warm_start
            and self._prev is not None
            and len(self._prev) == m
            and prev_plan is not None
            and len(prev_plan.src) == m
        ):
            # Compare against the endpoints the previous flow was solved
            # for (prev_plan), not the freshly rebuilt plan.
            same = (prev_plan.src == src) & (prev_plan.dst == dst)
            flow0 = np.where(same, np.minimum(self._prev, cap), 0).astype(np.int32)

        attempts = [
            (flow0, 1, min(4096, self.max_supersteps)),
            (np.zeros(m, dtype=np.int32), max(1, max_cost * n), self.max_supersteps),
        ]
        flow = steps = None
        tel_buf = None
        budget = self.max_supersteps
        converged = p_overflow = False
        for f0, eps_init, cap_steps in attempts:
            out = self._solve_fn(
                jnp.asarray(cap), jnp.asarray(cost), jnp.asarray(supply),
                jnp.asarray(f0), jnp.asarray(np.int32(eps_init)),
                jnp.asarray(np.int32(cap_steps)),
                *self._plan_dev,
            )
            if tel_cap:
                flow, steps, converged, p_overflow, tel_buf = out
            else:
                flow, steps, converged, p_overflow = out
            budget = cap_steps
            if bool(converged) and not bool(p_overflow):
                break
        self.last_supersteps = int(steps)
        self.last_telemetry = (
            soltel.decode(
                tel_buf, int(steps), tel_cap, "sharded", budget,
                converged=bool(converged) and not bool(p_overflow),
                nodes=n, arcs=m,
            )
            if tel_buf is not None
            else None
        )
        if bool(p_overflow) or not bool(converged):
            self._prev = None
        if bool(p_overflow):
            raise OverflowError("sharded push-relabel potentials approached int32 range")
        if not bool(converged):
            tel = self.last_telemetry
            raise soltel.SolverStallError(
                "sharded push-relabel did not converge; infeasible?",
                reason=soltel.detect_stall(tel) if tel is not None else None,
                telemetry=tel,
            )
        flow_np = np.asarray(flow)
        if self.warm_start:
            self._prev = flow_np.astype(np.int32)
        objective = int(
            (flow_np.astype(np.int64) * problem.cost.astype(np.int64)).sum()  # kschedlint: host-only (int64 objective math on host)
            + (problem.flow_offset.astype(np.int64) * problem.cost.astype(np.int64)).sum()  # kschedlint: host-only (int64 objective math on host)
        )
        return FlowResult(flow=flow_np.astype(np.int64), objective=objective, iterations=int(steps))  # kschedlint: host-only (FlowResult contract is int64)
