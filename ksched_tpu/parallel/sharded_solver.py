"""Multi-chip MCMF: the push-relabel solve sharded over a device mesh.

The reference scales by incremental re-solves in one external process
(SURVEY §2.5); the TPU rebuild scales across chips: residual entries are
partitioned by the OWNER of their source node (so every node's outgoing
entries — the unit of push/relabel work — live on exactly one shard),
while flow and potentials are replicated and combined with
`jax.lax.psum` over the mesh axis each superstep. ICI traffic per
superstep is one [N] node-vector and one [M] arc-vector reduction.

Design invariants (mirroring solver/jax_solver.py, which documents the
algorithm):
- no scatters: per-shard segment reductions use the same CSR-sorted
  cumsum/gather + associative-scan machinery; cross-shard combination is
  psum of owner-masked dense vectors (each node/arc has exactly one
  contributing shard, so psum implements "select the owner's value");
- pushes and relabels for a node are computed entirely on its owner
  shard from replicated state, so the single-chip eps-optimality
  argument carries over unchanged;
- price tightening (Bellman-Ford sweeps) distributes the same way: the
  per-node min over outgoing entries is owner-local, then psum-combined.

Built for `jax.sharding.Mesh` + `shard_map`; exercised on a virtual
8-device CPU mesh in tests and by __graft_entry__.dryrun_multichip.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..graph.device_export import FlowProblem
from ..solver.base import FlowResult, FlowSolver
from ..solver.layered import validate_alpha

_BIG = jnp.int32(1 << 30)
_BIG_D = 1 << 28


@dataclass
class ShardedPlan:
    """Host-prebuilt per-shard CSR data, stacked on a leading shard axis."""

    # [D, E] per-shard sorted entries (E = padded per-shard entry count)
    s_arc: np.ndarray
    s_sign: np.ndarray
    s_src: np.ndarray
    s_dst: np.ndarray
    s_segstart: np.ndarray  # local sorted index of entry's segment start
    s_isstart: np.ndarray
    s_valid: np.ndarray  # bool, padding mask
    # [D, N] per-node segment boundaries within the shard's local entries
    node_first: np.ndarray
    node_last: np.ndarray
    node_nonempty: np.ndarray
    owned: np.ndarray  # bool [D, N]: shard owns this node
    # [D, M] position of arc j's fwd/bwd entry in this shard (E = zero pad)
    pos_fwd: np.ndarray
    pos_bwd: np.ndarray
    src: np.ndarray  # [M] the endpoints this plan was built for
    dst: np.ndarray


def node_owner(node_ids: np.ndarray, num_nodes: int, num_shards: int) -> np.ndarray:
    """Owner shard per node: contiguous range partition, so resource
    subtrees laid out contiguously stay on one shard. Delegates to
    graph/slot_plan.shard_owner — the slot-stable sharded layout and
    this legacy plan builder must agree on ownership, or a maintained
    layout and a from-scratch build would route the same node's
    entries to different chips."""
    from ..graph.slot_plan import shard_owner

    return shard_owner(node_ids, num_nodes, num_shards)


def build_sharded_plan(src: np.ndarray, dst: np.ndarray, num_nodes: int, num_shards: int) -> ShardedPlan:
    m = len(src)
    esrc = np.concatenate([src, dst])
    edst = np.concatenate([dst, src])
    earc = np.concatenate([np.arange(m), np.arange(m)]).astype(np.int32)
    esign = np.concatenate([np.ones(m), -np.ones(m)]).astype(np.int32)
    owner = node_owner(esrc, num_nodes, num_shards)

    per_shard = [np.nonzero(owner == d)[0] for d in range(num_shards)]
    e_max = max((len(ix) for ix in per_shard), default=1)
    # One spare slot past the densest shard: pos_fwd/pos_bwd default
    # there, and it is invalid on every shard, so padded gathers read 0.
    e_pad = e_max + 1

    def stack(fill, dtype):
        return np.full((num_shards, e_pad), fill, dtype=dtype)

    s_arc = stack(0, np.int32)
    s_sign = stack(1, np.int32)
    s_src = stack(0, np.int32)
    s_dst = stack(0, np.int32)
    s_segstart = stack(0, np.int32)
    s_isstart = np.zeros((num_shards, e_pad), bool)
    s_valid = np.zeros((num_shards, e_pad), bool)
    node_first = np.zeros((num_shards, num_nodes), np.int32)
    node_last = np.zeros((num_shards, num_nodes), np.int32)
    node_nonempty = np.zeros((num_shards, num_nodes), bool)
    owned = np.zeros((num_shards, num_nodes), bool)
    pos_fwd = np.full((num_shards, m), e_pad - 1, np.int32)
    pos_bwd = np.full((num_shards, m), e_pad - 1, np.int32)

    node_ids = np.arange(num_nodes)
    node_owner_arr = node_owner(node_ids, num_nodes, num_shards)
    for d in range(num_shards):
        ix = per_shard[d]
        k = len(ix)
        order = np.argsort(esrc[ix], kind="stable")
        lsrc = esrc[ix][order]
        s_src[d, :k] = lsrc
        s_dst[d, :k] = edst[ix][order]
        s_arc[d, :k] = earc[ix][order]
        s_sign[d, :k] = esign[ix][order]
        s_valid[d, :k] = True
        counts = np.bincount(lsrc, minlength=num_nodes)
        row_ptr = np.zeros(num_nodes + 1, np.int64)  # kschedlint: host-only (numpy plan build)
        row_ptr[1:] = np.cumsum(counts)
        s_segstart[d, :k] = row_ptr[lsrc]
        starts = np.unique(row_ptr[lsrc]).astype(np.int64)  # kschedlint: host-only (numpy plan build)
        s_isstart[d, starts] = True
        node_first[d] = np.minimum(row_ptr[:-1], max(e_pad - 1, 0))
        node_last[d] = np.maximum(row_ptr[1:] - 1, 0)
        node_nonempty[d] = row_ptr[1:] > row_ptr[:-1]
        owned[d] = node_owner_arr == d
        # Map arc -> local entry position (padding position reads delta 0
        # because padded entries are never admissible).
        local_pos = np.empty(k, np.int64)  # kschedlint: host-only (numpy plan build)
        local_pos[:] = np.arange(k)
        glob = ix[order]
        is_fwd = glob < m
        pos_fwd[d, earc[ix][order][is_fwd]] = local_pos[is_fwd]
        pos_bwd[d, earc[ix][order][~is_fwd]] = local_pos[~is_fwd]
    return ShardedPlan(
        s_arc=s_arc,
        s_sign=s_sign,
        s_src=s_src,
        s_dst=s_dst,
        s_segstart=s_segstart,
        s_isstart=s_isstart,
        s_valid=s_valid,
        node_first=node_first,
        node_last=node_last,
        node_nonempty=node_nonempty,
        owned=owned,
        pos_fwd=pos_fwd,
        pos_bwd=pos_bwd,
        src=src.copy(),
        dst=dst.copy(),
    )


from ..solver.jax_solver import _seg_sum as _seg_sum_local  # same CSR layout


def _seg_scan(vals, isstart, combine_val):
    def combine(a, b):
        f1, v1 = a
        f2, v2 = b
        return f1 | f2, jnp.where(f2, v2, combine_val(v1, v2))

    _, scanned = lax.associative_scan(combine, (isstart, vals))
    return scanned


def make_sharded_solver(mesh: Mesh, axis: str, alpha: int, max_supersteps: int, tighten_sweeps: int = 32, telemetry_cap: int = 0):
    """Build the jitted sharded solve fn over the given mesh axis. The
    per-shard plan arrays arrive as call arguments (sharded on their
    leading axis); nothing is baked into the compiled function besides
    shapes. telemetry_cap > 0 appends the replicated soltel ring
    (obs/soltel.py) to the outputs: per-shard counter contributions are
    psum-combined, so the rows are GLOBAL — identical on every shard —
    and cap=0 traces the exact pre-telemetry program."""
    from ..obs.soltel import SOLTEL_WIDTH
    from ._compat import SHARD_MAP_KWARGS as shard_map_kwargs, shard_map, warn_if_fallback

    warn_if_fallback()
    spec_sharded = P(axis)
    spec_repl = P()

    def solve_shard(
        cap, cost, supply, flow0, eps_init, step_cap,
        s_arc, s_sign, s_src, s_dst, s_segstart, s_isstart, s_valid,
        node_first, node_last, node_nonempty, owned, pos_fwd, pos_bwd,
    ):
        # Inside shard_map: leading shard axis is stripped; arrays are
        # the local shard's slices. cap/cost/supply/flow0 replicated.
        i32 = jnp.int32
        (s_arc, s_sign, s_src, s_dst, s_segstart, s_isstart, s_valid,
         node_first, node_last, node_nonempty, owned, pos_fwd, pos_bwd) = (
            x[0] for x in (s_arc, s_sign, s_src, s_dst, s_segstart, s_isstart, s_valid,
                           node_first, node_last, node_nonempty, owned, pos_fwd, pos_bwd)
        )
        s_cost = s_sign * cost[s_arc]

        def residual(flow):
            a_flow = flow[s_arc]
            r = jnp.where(s_sign > 0, cap[s_arc] - a_flow, a_flow)
            return jnp.where(s_valid, r, i32(0))

        def excess_of(flow):
            contrib = _seg_sum_local(
                jnp.where(s_valid, s_sign * flow[s_arc], i32(0)),
                node_first, node_last, node_nonempty,
            )
            contrib = jnp.where(owned, contrib, i32(0))
            total = lax.psum(contrib, axis)
            return supply - total

        def tighten(flow):
            r = residual(flow)
            excess0 = excess_of(flow)
            d0 = jnp.where(excess0 < 0, i32(0), i32(_BIG_D))

            def t_cond(state):
                _d, changed, it = state
                return changed & (it < tighten_sweeps)

            def t_body(state):
                d, _, it = state
                cand = jnp.where(r > 0, s_cost + d[s_dst], i32(_BIG_D))
                scanned = _seg_scan(cand, s_isstart, jnp.minimum)
                best = jnp.where(node_nonempty, scanned[node_last], i32(_BIG_D))
                best = jnp.where(owned, best, i32(_BIG_D))
                best = lax.pmin(best, axis)
                # clamp below: transient negative-cost residual cycles
                # must not run d toward int32 wraparound
                d2 = jnp.maximum(jnp.minimum(d, best), -i32(_BIG_D))
                return d2, jnp.any(d2 != d), it + 1

            d, _, _ = lax.while_loop(t_cond, t_body, (d0, jnp.bool_(True), i32(0)))
            return -jnp.minimum(d, i32(_BIG_D))

        # pos_fwd/pos_bwd point either at the arc's real local entry or
        # at the spare padded slot (invalid on every shard), so gathers
        # through them read 0 after the s_valid mask.
        def arc_delta(delta):
            dz = jnp.where(s_valid, delta, i32(0))
            return lax.psum(dz[pos_fwd] - dz[pos_bwd], axis)

        def superstep(flow, p, eps, excess):
            r = residual(flow)
            rc = s_cost + p[s_src] - p[s_dst]
            e_at = excess[s_src]
            admissible = (r > 0) & (rc < 0) & (e_at > 0) & s_valid
            r_adm = jnp.where(admissible, r, i32(0))
            cum = jnp.cumsum(r_adm)
            excl = cum - r_adm
            prefix_before = excl - excl[s_segstart]
            delta = jnp.clip(e_at - prefix_before, 0, r_adm)
            new_flow = flow + arc_delta(delta)

            pushed = _seg_sum_local(delta, node_first, node_last, node_nonempty)
            sum_r = _seg_sum_local(r, node_first, node_last, node_nonempty)
            cand = jnp.where(r > 0, p[s_dst] - s_cost, -_BIG)
            scanned = _seg_scan(cand, s_isstart, jnp.maximum)
            best = jnp.where(node_nonempty, scanned[node_last], -_BIG)
            relabel = (excess > 0) & (pushed == 0) & (sum_r > 0) & owned
            p_local = jnp.where(relabel, best - eps, jnp.where(owned, p, i32(0)))
            new_p = lax.psum(jnp.where(owned, p_local, i32(0)), axis)
            if not telemetry_cap:
                return new_flow, new_p, ()
            # soltel cols 3..6: per-shard contributions psum'd to global
            # counts (each entry/owned node contributes on one shard)
            aux = (
                lax.psum(jnp.sum(jnp.where(s_valid, delta, i32(0))), axis),
                lax.psum(jnp.sum(relabel.astype(i32)), axis),
                lax.psum(
                    jnp.sum(((s_sign > 0) & s_valid & (r == 0)).astype(i32)),
                    axis,
                ),
                lax.psum(jnp.sum(admissible.astype(i32)), axis),
            )
            return new_flow, new_p, aux

        def sat_full(flow, p):
            rc = s_cost + p[s_src] - p[s_dst]
            r = residual(flow)
            want = jnp.where((rc < 0) & s_valid & (s_sign > 0), cap[s_arc], i32(-1))
            want = jnp.where((rc < 0) & s_valid & (s_sign < 0), i32(0), want)
            # translate per-entry wishes to per-arc flow targets
            wz = jnp.where(s_valid, want, i32(-1))
            tgt_f = wz[pos_fwd]
            tgt_b = wz[pos_bwd]
            tgt = jnp.maximum(lax.pmax(tgt_f, axis), lax.pmax(tgt_b, axis))
            return jnp.where(tgt >= 0, tgt, flow)

        if telemetry_cap:
            from ..obs import soltel as _soltel

            _tel_rows_iota = _soltel.device_rows_iota(telemetry_cap)

        def tel_row(eps, excess, aux):
            # excess is already the psum-combined global [N] vector,
            # identical on every shard — no further combine needed
            return _soltel.device_row(
                eps,
                jnp.sum((excess > 0).astype(i32)),
                jnp.sum(jnp.maximum(excess, 0)),
                *aux,
            )

        def tel_write(tel, steps, row):
            return _soltel.device_ring_write(
                tel, steps, row, telemetry_cap, _tel_rows_iota
            )

        def phase_cond(state):
            steps, done = state[3], state[4]
            return ~done & (steps < step_cap)

        def phase_body(state):
            if telemetry_cap:
                flow, p, eps, steps, done, tel = state
            else:
                flow, p, eps, steps, done = state
            excess = excess_of(flow)
            any_active = jnp.any(excess > 0)

            def do_superstep(_):
                f2, p2, aux = superstep(flow, p, eps, excess)
                if not telemetry_cap:
                    return f2, p2, eps, steps + 1, jnp.bool_(False)
                tel2 = tel_write(tel, steps, tel_row(eps, excess, aux))
                return f2, p2, eps, steps + 1, jnp.bool_(False), tel2

            def next_phase(_):
                finished = eps <= 1
                new_eps = jnp.maximum(i32(1), eps // alpha)
                f2 = jnp.where(finished, flow, sat_full(flow, p))
                out = (
                    f2, p, jnp.where(finished, eps, new_eps), steps, finished
                )
                return out + ((tel,) if telemetry_cap else ())

            return lax.cond(any_active, do_superstep, next_phase, operand=None)

        p0 = tighten(flow0)
        flow1 = sat_full(flow0, p0)
        state = (flow1, p0, eps_init, i32(0), jnp.bool_(False))
        if telemetry_cap:
            state = state + (jnp.zeros((telemetry_cap, SOLTEL_WIDTH), i32),)
            flow, p, eps, steps, done, tel = lax.while_loop(
                phase_cond, phase_body, state
            )
        else:
            flow, p, eps, steps, done = lax.while_loop(
                phase_cond, phase_body, state
            )
        converged = done & (jnp.max(jnp.abs(excess_of(flow))) == 0)
        p_overflow = jnp.max(jnp.abs(p)) >= (1 << 30)
        base = (flow, steps, converged, p_overflow)
        if telemetry_cap:
            return base + (tel,)
        return base

    in_specs = (
        spec_repl, spec_repl, spec_repl, spec_repl, spec_repl, spec_repl,
        spec_sharded, spec_sharded, spec_sharded, spec_sharded, spec_sharded,
        spec_sharded, spec_sharded, spec_sharded, spec_sharded, spec_sharded,
        spec_sharded, spec_sharded, spec_sharded,
    )
    out_specs = (spec_repl, spec_repl, spec_repl, spec_repl)
    if telemetry_cap:
        out_specs = out_specs + (spec_repl,)
    fn = shard_map(  # kschedlint: program=sharded_solve
        solve_shard, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        **shard_map_kwargs,
    )
    return jax.jit(fn)  # kschedlint: program=sharded_solve


# ---------------------------------------------------------------------------
# Slot-stable sharded solve: the maintained-layout multi-chip rung (r15)
# ---------------------------------------------------------------------------
#
# The legacy path above rebuilds a ShardedPlan (host argsort) whenever
# endpoints change. The slot-stable path consumes the SAME ten
# maintained plan tensors as the single-chip scan-CSR solver
# (graph/slot_plan.SlotPlanState in sharded layout mode): the
# entry-shaped tensors reshape losslessly to [D, Es] per-shard stacked
# tables (each shard block holds exactly the segments of the nodes it
# owns), liveness rides the sign column (a dead row's residual is
# forced to 0, no mask tensor), and endpoint churn ships as per-shard
# routed records through one donated shard_map scatter — no
# build_sharded_plan host rebuild on the event path.


def sharded_entry_extent(m_pad: int, num_shards: int) -> int:
    """Per-shard entry-block extent of the slot-stable sharded layout
    in the COMMON case: the (2*m_cap)/D floor slot_plan's sharded
    sizing applies (graph/slot_plan.SlotPlanState._rebuild) — a pure
    function of the pow2 arc bucket and the shard count, never the raw
    size, which is what makes the shard-count-bucket jaxpr hash pins
    non-vacuous (tests/test_static_analysis.py)."""
    return max((2 * m_pad) // num_shards, 16)


#: Explicit PartitionSpec rules for the slot-stable sharded solve, the
#: mesh-layout contract of docs/sharding.md (the match_partition_rules
#: pattern of SNIPPETS.md [1]/[3], specialized to the plan pytree):
#: entry-shaped tensors are stacked [D, Es] and partitioned by the
#: source-node OWNER along the mesh axis (contiguous node ranges —
#: graph/slot_plan.shard_owner — so resource subtrees stay
#: shard-local); every node-/arc-space vector (problem arrays, warm
#: state, positions, boundary statics) is replicated and combined with
#: psum/pmin/pmax over ICI each superstep.
SHARDED_PARTITION_RULES = (
    (r"^(p_arc|p_sign|p_src|p_dst|seg_start|is_start)$", "sharded"),
    (r"^(cap|cost|supply|flow0|eps|steps|warm_p)$", "replicated"),
    (r"^(inv_order|node_first|node_last|node_nonempty)$", "replicated"),
)


def match_partition_rules(names, axis: str):
    """PartitionSpec per named tensor from SHARDED_PARTITION_RULES —
    first matching rule wins, unknown names are an error (a new tensor
    must be placed deliberately, not silently replicated)."""
    import re

    from jax.sharding import PartitionSpec as P  # noqa: F811

    specs = []
    for name in names:
        for rule, kind in SHARDED_PARTITION_RULES:
            if re.search(rule, name):
                specs.append(P(axis) if kind == "sharded" else P())
                break
        else:
            raise ValueError(f"no partition rule for tensor {name!r}")
    return tuple(specs)


def place_sharded_plan(mesh: Mesh, axis: str, host_tensors, num_shards: int, block_extent: int) -> Tuple:
    """Device placement of the ten maintained plan tensors
    (SlotPlanState.host_args order) per SHARDED_PARTITION_RULES: the
    six entry-shaped tensors reshape [D, Es] and partition on the mesh
    axis, the rest replicate. The ONE placement implementation — the
    sharded solver's full-upload cache and the resident mirror's
    rebuild/repair path both call it, so the entry-vs-replicated split
    can never drift between them."""
    from jax.sharding import NamedSharding

    shard = NamedSharding(mesh, P(axis))
    repl = NamedSharding(mesh, P())
    return tuple(
        jax.device_put(
            np.ascontiguousarray(
                np.reshape(x, (num_shards, block_extent))
            ),
            shard,
        )
        if i < 6
        else jax.device_put(np.asarray(x), repl)
        for i, x in enumerate(host_tensors)
    )


#: argument names of the slot-stable sharded solve, in positional
#: order (warm_p appended by the use_warm_p variant)
_SLOT_SOLVE_ARGS = (
    "cap", "cost", "supply", "flow0", "eps", "steps",
    "p_arc", "p_sign", "p_src", "p_dst", "seg_start", "is_start",
    "inv_order", "node_first", "node_last", "node_nonempty",
)


def make_sharded_slot_solver(
    mesh: Mesh,
    axis: str,
    alpha: int,
    max_supersteps: int,
    tighten_sweeps: int = 32,
    telemetry_cap: int = 0,
    use_warm_p: bool = False,
):
    """The jitted slot-stable sharded solve over the given mesh axis.

    Same algorithm and superstep structure as the single-chip
    slot-stable `_solve_mcmf` (solver/jax_solver.py) — same residual
    masking through the sign column, same prefix-sum push allocation,
    same tightening sweeps (and, with ``use_warm_p``, the same
    dirty-frontier price REFIT seeded from the carried potentials) —
    so flows, potentials, and superstep counts are bit-identical to
    the single-chip solve of the same problem over the same layout.
    Per-shard contributions combine through exactly three collective
    shapes per superstep: one [N] psum for the excess/potential
    vectors, one [M] psum for the arc deltas, and the pmin/pmax
    segment combines (telemetry adds scalar psums, off by default).

    ICI traffic per superstep is therefore one [N] node-vector and one
    [M] arc-vector reduction (the PR-1 brief's "allreduce node
    potentials over ICI each superstep"), countable from the traced
    program (analysis/jaxpr_contracts.count_collectives)."""
    from ..obs.soltel import SOLTEL_WIDTH
    from ._compat import SHARD_MAP_KWARGS as shard_map_kwargs, shard_map, warn_if_fallback

    warn_if_fallback()
    D = int(mesh.shape[axis])

    def solve_shard(*args):
        if use_warm_p:
            (cap, cost, supply, flow0, eps_init, step_cap,
             p_arc, p_sign, p_src, p_dst, seg_g, isstart,
             inv, node_first_g, node_last_g, node_nonempty, warm_p) = args
        else:
            (cap, cost, supply, flow0, eps_init, step_cap,
             p_arc, p_sign, p_src, p_dst, seg_g, isstart,
             inv, node_first_g, node_last_g, node_nonempty) = args
            warm_p = None
        i32 = jnp.int32
        # entry-shaped operands arrive [1, Es] (their shard slice);
        # strip the leading mesh dim
        s_arc, s_sign, s_src, s_dst, seg_g, isstart = (
            x[0] for x in (p_arc, p_sign, p_src, p_dst, seg_g, isstart)
        )
        Es = s_arc.shape[0]
        n = supply.shape[0]
        m = cap.shape[0]
        me = lax.axis_index(axis)
        base = me * i32(Es)
        # ownership re-derived from iota — the same contiguous-range
        # arithmetic as graph/slot_plan.shard_owner, so the kernel and
        # the host layout can never disagree on who owns a node
        per = -(-n // D)
        owned = jnp.minimum(lax.iota(i32, n) // i32(per), i32(D - 1)) == me
        # boundary statics are GLOBAL positions; translate into the
        # local block (owned nodes' regions live in this block by
        # construction; non-owned rows are masked everywhere they feed)
        node_first = jnp.clip(node_first_g - base, 0, i32(Es - 1))
        node_last = jnp.clip(node_last_g - base, 0, i32(Es - 1))
        nonempty = node_nonempty & owned
        seg_local = jnp.clip(seg_g - base, 0, i32(Es - 1))
        # per-arc entry positions: the fwd/bwd halves of inv_order.
        # A position outside this block (or a freed slot's parked 0)
        # maps to the block's reserved dead local slot 0, whose sign
        # is 0 — it can never carry flow, wants, or deltas.
        pf_g = inv[:m]
        pb_g = inv[m:]
        pf = jnp.where(pf_g // i32(Es) == me, pf_g - base, i32(0))
        pb = jnp.where(pb_g // i32(Es) == me, pb_g - base, i32(0))
        s_cost = s_sign * cost[s_arc]

        def residual(flow):
            a_flow = flow[s_arc]
            return jnp.where(
                s_sign > 0, cap[s_arc] - a_flow,
                jnp.where(s_sign < 0, a_flow, i32(0)),
            )

        def excess_of(flow):
            contrib = _seg_sum_local(
                s_sign * flow[s_arc], node_first, node_last, nonempty
            )
            contrib = jnp.where(owned, contrib, i32(0))
            return supply - lax.psum(contrib, axis)

        def tighten(flow, d0=None):
            r = residual(flow)
            if d0 is None:
                excess0 = excess_of(flow)
                d0 = jnp.where(excess0 < 0, i32(0), i32(_BIG_D))

            def t_cond(state):
                _d, changed, it = state
                return changed & (it < tighten_sweeps)

            def t_body(state):
                d, _, it = state
                cand = jnp.where(r > 0, s_cost + d[s_dst], i32(_BIG_D))
                scanned = _seg_scan(cand, isstart, jnp.minimum)
                best = jnp.where(nonempty, scanned[node_last], i32(_BIG_D))
                best = jnp.where(owned, best, i32(_BIG_D))
                best = lax.pmin(best, axis)
                d2 = jnp.maximum(jnp.minimum(d, best), -i32(_BIG_D))
                return d2, jnp.any(d2 != d), it + 1

            d, _, _ = lax.while_loop(t_cond, t_body, (d0, jnp.bool_(True), i32(0)))
            return -jnp.minimum(d, i32(_BIG_D))

        def arc_delta(delta):
            return lax.psum(delta[pf] - delta[pb], axis)

        def superstep(flow, p, eps, excess):
            r = residual(flow)
            rc = s_cost + p[s_src] - p[s_dst]
            e_at = excess[s_src]
            admissible = (r > 0) & (rc < 0) & (e_at > 0)
            r_adm = jnp.where(admissible, r, i32(0))
            cum = jnp.cumsum(r_adm)
            excl = cum - r_adm
            prefix_before = excl - excl[seg_local]
            delta = jnp.clip(e_at - prefix_before, 0, r_adm)
            new_flow = flow + arc_delta(delta)

            pushed = _seg_sum_local(delta, node_first, node_last, nonempty)
            sum_r = _seg_sum_local(r, node_first, node_last, nonempty)
            cand = jnp.where(r > 0, p[s_dst] - s_cost, -_BIG)
            scanned = _seg_scan(cand, isstart, jnp.maximum)
            best = jnp.where(nonempty, scanned[node_last], -_BIG)
            relabel = (excess > 0) & (pushed == 0) & (sum_r > 0) & owned
            p_local = jnp.where(relabel, best - eps, jnp.where(owned, p, i32(0)))
            new_p = lax.psum(jnp.where(owned, p_local, i32(0)), axis)
            if not telemetry_cap:
                return new_flow, new_p, ()
            aux = (
                lax.psum(jnp.sum(pushed), axis),
                lax.psum(jnp.sum(relabel.astype(i32)), axis),
                lax.psum(jnp.sum(((s_sign > 0) & (r == 0)).astype(i32)), axis),
                lax.psum(jnp.sum((r_adm > 0).astype(i32)), axis),
            )
            return new_flow, new_p, aux

        def sat_full(flow, p):
            rc = s_cost + p[s_src] - p[s_dst]
            want = jnp.where((rc < 0) & (s_sign > 0), cap[s_arc], i32(-1))
            want = jnp.where((rc < 0) & (s_sign < 0), i32(0), want)
            tgt = jnp.maximum(
                lax.pmax(want[pf], axis), lax.pmax(want[pb], axis)
            )
            return jnp.where(tgt >= 0, tgt, flow)

        if telemetry_cap:
            from ..obs import soltel as _soltel

            _tel_rows_iota = _soltel.device_rows_iota(telemetry_cap)

        def tel_row(eps, excess, aux):
            return _soltel.device_row(
                eps,
                jnp.sum((excess > 0).astype(i32)),
                jnp.sum(jnp.maximum(excess, 0)),
                *aux,
            )

        def tel_write(tel, steps, row):
            return _soltel.device_ring_write(
                tel, steps, row, telemetry_cap, _tel_rows_iota
            )

        def phase_cond(state):
            steps, done = state[3], state[4]
            return ~done & (steps < step_cap)

        def phase_body(state):
            if telemetry_cap:
                flow, p, eps, steps, done, tel = state
            else:
                flow, p, eps, steps, done = state
            excess = excess_of(flow)
            any_active = jnp.any(excess > 0)

            def do_superstep(_):
                f2, p2, aux = superstep(flow, p, eps, excess)
                if not telemetry_cap:
                    return f2, p2, eps, steps + 1, jnp.bool_(False)
                tel2 = tel_write(tel, steps, tel_row(eps, excess, aux))
                return f2, p2, eps, steps + 1, jnp.bool_(False), tel2

            def next_phase(_):
                finished = eps <= 1
                new_eps = jnp.maximum(i32(1), eps // alpha)
                f2 = jnp.where(finished, flow, sat_full(flow, p))
                out = (
                    f2, p, jnp.where(finished, eps, new_eps), steps, finished
                )
                return out + ((tel,) if telemetry_cap else ())

            return lax.cond(any_active, do_superstep, next_phase, operand=None)

        if use_warm_p:
            # dirty-frontier refit: the Bellman sweeps seeded from the
            # carried prices, exactly the single-chip use_warm_p path
            p0 = tighten(
                flow0, d0=jnp.clip(-warm_p, -i32(_BIG_D), i32(_BIG_D))
            )
        else:
            p0 = tighten(flow0)
        flow1 = sat_full(flow0, p0)
        state = (flow1, p0, eps_init, i32(0), jnp.bool_(False))
        if telemetry_cap:
            state = state + (jnp.zeros((telemetry_cap, SOLTEL_WIDTH), i32),)
            flow, p, eps, steps, done, tel = lax.while_loop(
                phase_cond, phase_body, state
            )
        else:
            flow, p, eps, steps, done = lax.while_loop(
                phase_cond, phase_body, state
            )
        converged = done & (jnp.max(jnp.abs(excess_of(flow))) == 0)
        p_overflow = jnp.max(jnp.abs(p)) >= (1 << 30)
        base_out = (flow, p, steps, converged, p_overflow)
        if telemetry_cap:
            return base_out + (tel,)
        return base_out

    names = _SLOT_SOLVE_ARGS + (("warm_p",) if use_warm_p else ())
    in_specs = match_partition_rules(names, axis)
    out_specs = (P(), P(), P(), P(), P())
    if telemetry_cap:
        out_specs = out_specs + (P(),)
    fn = shard_map(  # kschedlint: program=sharded_slot_solve
        solve_shard, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        **shard_map_kwargs,
    )
    return jax.jit(fn)  # kschedlint: program=sharded_slot_solve


# ---------------------------------------------------------------------------
# HBM fitting gate: when does a graph escalate off the single chip?
# ---------------------------------------------------------------------------

#: int32 entry-space vectors the scan-CSR solve holds live across a
#: superstep: the 6 resident entry tables (arc/sign/src/dst/segstart/
#: isstart) plus ~8 superstep temporaries (a_flow, residual, signed
#: cost, reduced cost, per-entry excess, admissible residual, the
#: prefix cumsum and its exclusive form) — the same live-set
#: accounting style as ops/mcmf_pallas._MEGA_LIVE_TILES, at HBM scale
_CSR_LIVE_EVECS = 14
#: [N] node-space vectors live per superstep (supply, excess, p,
#: relabel candidates, boundary statics)
_CSR_LIVE_NVECS = 8
#: [M] arc-space vectors live per solve (cap, cost, flow, flow0,
#: inv_order's two halves)
_CSR_LIVE_MVECS = 6

#: default per-chip working-set budget for ONE solver's buffers. This
#: is deliberately far below a v5e's 16 GB HBM: the budget covers the
#: solver working set only, and the serving stack holds the rest of
#: the chip — double-buffered rounds keep two problem generations
#: live, warm state and telemetry rings persist, and the multi-tenant
#: service packs many cells per chip (docs/sharding.md derives the
#: number). Overridable per AutoSolver (and by the bench configs).
DEFAULT_HBM_BUDGET_BYTES = 1 << 30


def csr_working_set_bytes(n_cap: int, m_cap: int) -> int:
    """Estimated bytes of the single-chip scan-CSR live set for a
    padded (n_cap, m_cap) bucket — the slot-stable entry extent is
    2*m_cap in the common case (analysis/jaxpr_contracts.
    slot_stable_entry_cap)."""
    e = 2 * m_cap
    return 4 * (
        _CSR_LIVE_EVECS * e + _CSR_LIVE_NVECS * n_cap + _CSR_LIVE_MVECS * m_cap
    )


def scan_csr_fits_hbm(
    n_cap: int, m_cap: int, budget_bytes: int = DEFAULT_HBM_BUDGET_BYTES
) -> bool:
    """Whether one chip's budget holds the scan-CSR working set —
    mirror of `mega_fits_vmem`'s live-set arithmetic one rung up the
    memory hierarchy. False is what escalates dispatch to the sharded
    rung (solver/graph_collapse.AutoSolver)."""
    return csr_working_set_bytes(n_cap, m_cap) <= budget_bytes


def sharded_shard_bytes(n_cap: int, m_cap: int, num_shards: int) -> int:
    """Estimated per-shard bytes of the slot-stable sharded working
    set: the entry tables shrink to the per-shard block extent, while
    the replicated node/arc vectors (the PartitionSpec rules above)
    are paid in full on every shard."""
    es = sharded_entry_extent(m_cap, num_shards)
    return 4 * (
        _CSR_LIVE_EVECS * es + _CSR_LIVE_NVECS * n_cap + _CSR_LIVE_MVECS * m_cap
    )


def sharded_fits_hbm(
    n_cap: int,
    m_cap: int,
    num_shards: int,
    budget_bytes: int = DEFAULT_HBM_BUDGET_BYTES,
) -> bool:
    """Whether the PER-SHARD working set fits the per-chip budget."""
    return sharded_shard_bytes(n_cap, m_cap, num_shards) <= budget_bytes


# ---------------------------------------------------------------------------
# Sharded plan maintenance programs (the device-resident mirror's
# sharded mode — graph/device_export.DeviceResidentState)
# ---------------------------------------------------------------------------

_SHARDED_PLAN_APPLY: dict = {}


def sharded_plan_apply_fn(mesh: Mesh, axis: str):
    """The per-shard routed plan scatter: the THIRD (and last) scoped
    scatter exemption of the solver stack, the sharded sibling of
    `graph/slot_plan.plan_apply_fn`. A round's dirty plan rows and
    relocated segment statics arrive pre-routed to their owner shards
    (``SlotPlanState.drain_records_sharded`` — positions block-local,
    one shared pow2 record bucket per stream, idempotent dead-slot
    pads), and every shard applies ITS records to ITS block of the
    donated entry tensors — O(records/shard) per shard, zero
    cross-shard traffic (no collectives in the traced program: the
    routing already happened on host). Pinned by the jaxpr contracts:
    non-vacuous (really scatters), 32-bit, pow2-record-bucket
    hash-stable (tests/test_static_analysis.py)."""
    key = (mesh, axis)
    fn = _SHARDED_PLAN_APPLY.get(key)
    if fn is None:
        from ._compat import SHARD_MAP_KWARGS as shard_map_kwargs, shard_map, warn_if_fallback

        warn_if_fallback()

        def body(p_arc, p_sign, p_src, p_dst, seg, isstart, row_rec, seg_rec):
            (p_arc, p_sign, p_src, p_dst, seg, isstart, row_rec, seg_rec) = (
                x[0] for x in (p_arc, p_sign, p_src, p_dst, seg, isstart, row_rec, seg_rec)
            )
            pos = row_rec[:, 0]
            p_arc = p_arc.at[pos].set(row_rec[:, 1])
            p_sign = p_sign.at[pos].set(row_rec[:, 2])
            p_src = p_src.at[pos].set(row_rec[:, 3])
            p_dst = p_dst.at[pos].set(row_rec[:, 4])
            spos = seg_rec[:, 0]
            seg = seg.at[spos].set(seg_rec[:, 1])
            isstart = isstart.at[spos].set(seg_rec[:, 2] != 0)
            return tuple(
                x[None] for x in (p_arc, p_sign, p_src, p_dst, seg, isstart)
            )

        inner = shard_map(  # kschedlint: program=sharded_plan_apply
            body, mesh=mesh,
            in_specs=(P(axis),) * 8, out_specs=(P(axis),) * 6,
            **shard_map_kwargs,
        )
        fn = jax.jit(inner, donate_argnums=(0, 1, 2, 3, 4, 5))  # kschedlint: program=sharded_plan_apply
        _SHARDED_PLAN_APPLY[key] = fn
    return fn


_REPL_PLAN_APPLY = None


def replicated_plan_apply_fn():
    """The replicated remainder of a sharded plan sync: inv-order and
    node-boundary records scatter into the REPLICATED plan tensors
    (the partition rules keep them whole on every shard), donated in
    place. Same record scheme as plan_apply_fn's inv/node streams."""
    global _REPL_PLAN_APPLY
    if _REPL_PLAN_APPLY is None:
        @functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3))  # kschedlint: program=replicated_plan_apply
        def _apply(inv, first, last, nonempty, inv_rec, node_rec):
            inv = inv.at[inv_rec[:, 0]].set(inv_rec[:, 1])
            nid = node_rec[:, 0]
            first = first.at[nid].set(node_rec[:, 1])
            last = last.at[nid].set(node_rec[:, 2])
            nonempty = nonempty.at[nid].set(node_rec[:, 3] != 0)
            return inv, first, last, nonempty

        _REPL_PLAN_APPLY = _apply
    return _REPL_PLAN_APPLY


_SHARDED_PLAN_FP: dict = {}


def sharded_plan_fingerprint_fn(mesh: Mesh, axis: str):
    """Per-shard fingerprints psum'd to ONE comparable checksum (the
    PR 14 integrity audit, sharded): each shard computes the weighted
    partial sum of its block with GLOBAL-index weights (global position
    = shard * Es + local iota, the same w[i] = (i*MUL + ADD) | 1 as
    `runtime/integrity.host_fingerprint`), and the psum over the mesh
    axis equals the host twin of the full tensor bit-for-bit — so a
    sharded mirror audits against the SAME host fingerprints as a
    single-chip one, no sharded-specific host math. Returns int32[10]
    in FP_PLAN_ARRAYS order."""
    key = (mesh, axis)
    fn = _SHARDED_PLAN_FP.get(key)
    if fn is None:
        from ..runtime.integrity import _FP_ADD, _FP_MUL, _device_fp1
        from ._compat import SHARD_MAP_KWARGS as shard_map_kwargs, shard_map, warn_if_fallback

        warn_if_fallback()
        i32 = jnp.int32

        def body(p_arc, p_sign, p_src, p_dst, seg, isstart):
            outs = []
            me = lax.axis_index(axis)
            for t in (p_arc, p_sign, p_src, p_dst, seg, isstart):
                v = t[0]
                es = v.shape[0]
                i = lax.iota(i32, es) + me * i32(es)
                w = (i * i32(_FP_MUL) + i32(_FP_ADD)) | i32(1)
                outs.append(lax.psum(jnp.sum(v.astype(i32) * w), axis))
            return jnp.stack(outs)

        entry_fp = shard_map(  # kschedlint: program=sharded_plan_fingerprint
            body, mesh=mesh, in_specs=(P(axis),) * 6, out_specs=P(),
            **shard_map_kwargs,
        )

        def _fp(p_arc, p_sign, p_src, p_dst, inv, seg, isstart, first, last, nonempty):
            ent = entry_fp(p_arc, p_sign, p_src, p_dst, seg, isstart)
            rep = [_device_fp1(x) for x in (inv, first, last, nonempty)]
            # FP_PLAN_ARRAYS order: p_arc, p_sign, p_src, p_dst,
            # inv_order, seg_start, is_start, node_first, node_last,
            # node_nonempty
            return jnp.stack([
                ent[0], ent[1], ent[2], ent[3], rep[0],
                ent[4], ent[5], rep[1], rep[2], rep[3],
            ])

        fn = jax.jit(_fp)  # kschedlint: program=sharded_plan_fingerprint
        _SHARDED_PLAN_FP[key] = fn
    return fn


class ShardedJaxSolver(FlowSolver):
    """Push-relabel MCMF sharded over a jax Mesh axis.

    Two dispatch paths, chosen per problem:

    - **slot-stable** (``slot_stable=True`` and the problem carries a
      slot-plan handle — every DeviceGraphState problem): the plan is
      switched into sharded layout mode (graph/slot_plan.
      enable_sharding) and the solve consumes the SAME ten maintained
      tensors as the single-chip scan-CSR rung, entry tables stacked
      [D, Es] by owner shard. Endpoint churn never rebuilds a
      ShardedPlan: the per-round records ride the sharded plan
      scatter (device-resident mirror) or the plan's cached full
      upload. Warm flow and potentials stay device-resident between
      rounds under the SAME journal-scoped policy as JaxSolver
      (carried flow only on endpoint-clean rounds, prices refit via
      the dirty-frontier Bellman seed, budgeted warm attempt escaping
      to the fresh-restart program, cost-scaling as the backstop) —
      so sharded placements stay bit-identical to the single-chip
      arm's.
    - **legacy** (plain array problems — tests, ad-hoc solves): the
      r7 build_sharded_plan argsort path, unchanged.
    """

    def __init__(self, mesh: Mesh, axis: str = "x", alpha: int = 8, max_supersteps: int = 50_000, warm_start: bool = True, telemetry: Optional[int] = None, warm_potentials: bool = True, restart_budget: Optional[int] = 64, slot_stable: bool = True, journal_scoped_warm: bool = True):
        self.mesh = mesh
        self.axis = axis
        self.alpha = validate_alpha(alpha)
        self.max_supersteps = max_supersteps
        self.warm_start = warm_start
        self.telemetry = telemetry
        self.warm_potentials = warm_potentials
        self.restart_budget = restart_budget
        self.slot_stable = slot_stable
        self.journal_scoped_warm = journal_scoped_warm
        self._plan: Optional[ShardedPlan] = None
        self._plan_dev = None
        self._solve_fn = None
        self._solve_fn_cap = 0  # telemetry_cap the cached fn was built for
        self._prev: Optional[np.ndarray] = None
        # ---- slot-stable path state ----------------------------------
        self._slot_fns = {}  # (telemetry_cap, use_warm_p) -> jitted fn
        self._splan_cache = None  # (layout_gen, value_version, tensors)
        self._prev_dev = None  # carried flow, device-resident
        self._prev_p = None  # carried potentials, device-resident
        self._prev_src_dev = None  # endpoint buffers at the last success
        self._prev_dst_dev = None
        self._prev_src_host = None  # endpoints at the last SUCCESSFUL solve
        self._prev_dst_host = None
        self._key_solved = None  # plan_key at the last successful solve
        self.last_supersteps = 0
        self.last_telemetry = None
        self.last_warm_scope = "cold"  # warm | fresh | cold
        self.last_path = "legacy"  # legacy | slot_stable (per solve)

    def reset(self) -> None:
        self._prev = None
        self._prev_dev = None
        self._prev_p = None
        self._prev_src_dev = None
        self._prev_dst_dev = None
        self._prev_src_host = None
        self._prev_dst_host = None
        self._key_solved = None

    @property
    def num_shards(self) -> int:
        return int(np.prod([self.mesh.shape[a] for a in self.mesh.axis_names if a == self.axis]))

    # -- slot-stable dispatch ----------------------------------------------

    def _slot_fn(self, tel_cap: int, use_warm_p: bool):
        key = (tel_cap, use_warm_p)
        fn = self._slot_fns.get(key)
        if fn is None:
            fn = make_sharded_slot_solver(
                self.mesh, self.axis, self.alpha, self.max_supersteps,
                telemetry_cap=tel_cap, use_warm_p=use_warm_p,
            )
            self._slot_fns[key] = fn
        return fn

    def _sharded_plan_args(self, plan_state) -> Tuple:
        """The maintained plan as sharded device tensors (the
        non-resident full-upload path, cached per (layout_gen,
        value_version) like SlotPlanState.device_args): entry-shaped
        tensors reshaped [D, Es] and placed by the partition rules,
        the rest replicated."""
        key = (plan_state.layout_gen, plan_state.value_version)
        if self._splan_cache is None or self._splan_cache[0] != key:
            self._splan_cache = (
                key,
                place_sharded_plan(
                    self.mesh, self.axis, plan_state.host_args(),
                    self.num_shards, plan_state.block_extent,
                ),
            )
        return self._splan_cache[1]

    def _solve_slot_stable(self, problem: FlowProblem, plan_state) -> FlowResult:
        from ..graph.device_export import resident_solver_inputs
        from ..obs import soltel
        from ..solver.base import check_finite_costs, lower_bound_cost

        n = problem.num_nodes
        m = len(problem.src)
        check_finite_costs(problem)
        max_cost = int(np.abs(problem.cost).max()) if m else 0
        if max_cost * n >= (1 << 30):
            raise OverflowError("scaled costs overflow int32")
        D = self.num_shards
        plan_state.enable_sharding(D)
        plan_state.ensure_built()
        tel_cap = soltel.resolve_cap(self.telemetry)
        self.last_path = "slot_stable"

        # device plan tensors: the sharded device-resident mirror's
        # scatter-maintained buffers when the handle carries them
        # ([D, Es]-shaped), else the plan's cached full upload
        d_plan = getattr(problem, "d_plan", None)
        if d_plan is not None and getattr(d_plan[0], "ndim", 1) == 2:
            plan_dev = d_plan
        else:
            plan_dev = self._sharded_plan_args(plan_state)

        # journal-scoped warm policy — verbatim JaxSolver semantics:
        # carried FLOW only when this round's journal re-wired no
        # endpoints (plan_key match against the last successful solve)
        plan_key = getattr(problem, "plan_key", None)
        keep_flow = True
        if self.journal_scoped_warm and plan_key is not None:
            keep_flow = (
                self._key_solved is not None and plan_key == self._key_solved
            )
        resident = getattr(problem, "d_cap", None) is not None
        if resident:
            dev_args, flow0_dev, warm = resident_solver_inputs(
                problem, self._prev_dev, self._prev_src_dev,
                self._prev_dst_dev, self.warm_start and keep_flow,
            )
        else:
            cap = problem.cap.astype(np.int32)
            supply = problem.excess.astype(np.int32)
            cost = problem.cost.astype(np.int32) * np.int32(n)
            dev_args = (
                jnp.asarray(cap), jnp.asarray(cost), jnp.asarray(supply),
            )
            warm = (
                self.warm_start
                and keep_flow
                and self._prev is not None
                and len(self._prev) == m
                and self._prev_src_host is not None
                and len(self._prev_src_host) == m
            )
            flow0 = np.zeros(m, dtype=np.int32)
            if warm:
                same = (self._prev_src_host == problem.src) & (
                    self._prev_dst_host == problem.dst
                )
                if self.journal_scoped_warm and plan_key is None and not same.all():
                    warm = False
                else:
                    flow0 = np.where(
                        same, np.minimum(self._prev, cap), 0
                    ).astype(np.int32)
            flow0_dev = jnp.asarray(flow0)
        had_state = self._prev is not None or self._prev_dev is not None
        self.last_warm_scope = (
            "warm" if warm else ("fresh" if had_state else "cold")
        )

        warm_p_ok = (
            self.warm_potentials
            and warm
            and self._prev_p is not None
            and self._prev_p.shape[0] == n
        )
        attempt1_budget = min(4096, self.max_supersteps)
        if warm and self.restart_budget is not None:
            attempt1_budget = min(attempt1_budget, self.restart_budget)
        zeros = jnp.zeros(m, jnp.int32)
        # attempt ladder (the JaxSolver.complete ladder, synchronous):
        # warm (budgeted) -> fresh restart (eps=1, zero flow) ->
        # cost scaling from max|cost|*n
        attempts = [(
            flow0_dev, 1, attempt1_budget, warm_p_ok,
        )]
        if warm:
            attempts.append((zeros, 1, min(4096, self.max_supersteps), False))
        attempts.append(
            (zeros, max(1, max_cost * n), self.max_supersteps, False)
        )
        flow = p = steps = tel_buf = None
        converged = p_overflow = False
        spent = 0
        for ai, (f0, eps_init, cap_steps, use_wp) in enumerate(attempts):
            fn = self._slot_fn(tel_cap, use_wp)
            args = dev_args + (
                f0, jnp.asarray(np.int32(eps_init)),
                jnp.asarray(np.int32(cap_steps)),
            ) + tuple(plan_dev)
            if use_wp:
                args = args + (self._prev_p,)
            out = fn(*args)
            if tel_cap:
                flow, p, steps, converged, p_overflow, tel_buf = out
            else:
                flow, p, steps, converged, p_overflow = out
            spent += int(steps)
            ok = bool(converged) and not bool(p_overflow)
            if ai == 0 and warm and not ok and not bool(converged):
                soltel.warm_price_war(
                    "sharded",
                    supersteps=int(steps),
                    budget=attempt1_budget,
                    escaped_to="fresh_restart",
                    tel=(
                        soltel.decode(
                            tel_buf, int(steps), tel_cap, "sharded",
                            attempt1_budget, converged=False,
                            nodes=n, arcs=m,
                        )
                        if tel_buf is not None
                        else None
                    ),
                )
            if ok:
                break
        self.last_supersteps = spent
        # the telemetry budget is the SOLVER's budget, not the warm
        # attempt's internal cap: a budgeted warm attempt that escapes
        # is escalated, not failed, and cap-proximity against the warm
        # cap would be a spurious stall event (JaxSolver.complete's
        # convention; the warm_price_war event above already carries
        # the attempt-local budget)
        self.last_telemetry = (
            soltel.decode(
                tel_buf, int(steps), tel_cap, "sharded", self.max_supersteps,
                converged=bool(converged) and not bool(p_overflow),
                nodes=n, arcs=m,
            )
            if tel_buf is not None
            else None
        )
        if bool(p_overflow) or not bool(converged):
            self.reset()
        if bool(p_overflow):
            raise OverflowError(
                "sharded push-relabel potentials approached int32 range"
            )
        if not bool(converged):
            tel = self.last_telemetry
            raise soltel.SolverStallError(
                f"sharded push-relabel did not converge within "
                f"{self.max_supersteps} supersteps; infeasible?",
                reason=soltel.detect_stall(tel) if tel is not None else None,
                telemetry=tel,
            )
        flow_np = np.asarray(flow)
        if self.warm_start:
            self._prev = flow_np.astype(np.int32)
            self._prev_dev = flow if resident else None
            self._prev_src_dev = problem.d_src if resident else None
            self._prev_dst_dev = problem.d_dst if resident else None
            self._prev_src_host = np.asarray(problem.src, np.int32)
            self._prev_dst_host = np.asarray(problem.dst, np.int32)
            self._key_solved = plan_key
            self._prev_p = p
        objective = int(
            (flow_np.astype(np.int64) * problem.cost.astype(np.int64)).sum()  # kschedlint: host-only (int64 objective math on host)
        ) + lower_bound_cost(problem)
        return FlowResult(flow=flow_np.astype(np.int64), objective=objective, iterations=spent)  # kschedlint: host-only (FlowResult contract is int64)

    def solve(self, problem: FlowProblem) -> FlowResult:
        m = len(problem.src)
        if m == 0 or problem.num_arcs == 0:
            if (problem.excess > 0).any():
                raise RuntimeError("infeasible flow problem: supply but no arcs")
            self.last_telemetry = None
            return FlowResult(flow=np.zeros(m, dtype=np.int64), objective=0, iterations=0)  # kschedlint: host-only (FlowResult contract is int64)
        plan_state = getattr(problem, "plan", None) if self.slot_stable else None
        if plan_state is not None:
            return self._solve_slot_stable(problem, plan_state)
        return self._solve_legacy(problem)

    def _solve_legacy(self, problem: FlowProblem) -> FlowResult:
        from ..obs import soltel

        self.last_path = "legacy"
        n = problem.num_nodes
        m = len(problem.src)
        if m == 0 or problem.num_arcs == 0:
            if (problem.excess > 0).any():
                raise RuntimeError("infeasible flow problem: supply but no arcs")
            self.last_telemetry = None
            return FlowResult(flow=np.zeros(m, dtype=np.int64), objective=0, iterations=0)  # kschedlint: host-only (FlowResult contract is int64)
        src = problem.src.astype(np.int32)
        dst = problem.dst.astype(np.int32)
        cap = problem.cap.astype(np.int32)
        supply = problem.excess.astype(np.int32)
        max_cost = int(np.abs(problem.cost).max()) if m else 0
        if max_cost * n >= (1 << 30):
            raise OverflowError("scaled costs overflow int32")
        cost = problem.cost.astype(np.int32) * np.int32(n)

        tel_cap = soltel.resolve_cap(self.telemetry)
        prev_plan = self._plan
        plan = prev_plan
        if plan is None or len(plan.src) != m or plan.node_first.shape[1] != n or not (
            np.array_equal(plan.src, src) and np.array_equal(plan.dst, dst)
        ):
            plan = build_sharded_plan(src, dst, n, self.num_shards)
            self._plan = plan
            self._plan_dev = tuple(
                jnp.asarray(x)
                for x in (
                    plan.s_arc, plan.s_sign, plan.s_src, plan.s_dst,
                    plan.s_segstart, plan.s_isstart, plan.s_valid,
                    plan.node_first, plan.node_last, plan.node_nonempty,
                    plan.owned, plan.pos_fwd, plan.pos_bwd,
                )
            )
            self._solve_fn = None
        if self._solve_fn is None or self._solve_fn_cap != tel_cap:
            self._solve_fn = make_sharded_solver(
                self.mesh, self.axis, self.alpha, self.max_supersteps,
                telemetry_cap=tel_cap,
            )
            self._solve_fn_cap = tel_cap

        flow0 = np.zeros(m, dtype=np.int32)
        if (
            self.warm_start
            and self._prev is not None
            and len(self._prev) == m
            and prev_plan is not None
            and len(prev_plan.src) == m
        ):
            # Compare against the endpoints the previous flow was solved
            # for (prev_plan), not the freshly rebuilt plan.
            same = (prev_plan.src == src) & (prev_plan.dst == dst)
            flow0 = np.where(same, np.minimum(self._prev, cap), 0).astype(np.int32)

        attempts = [
            (flow0, 1, min(4096, self.max_supersteps)),
            (np.zeros(m, dtype=np.int32), max(1, max_cost * n), self.max_supersteps),
        ]
        flow = steps = None
        tel_buf = None
        budget = self.max_supersteps
        converged = p_overflow = False
        for f0, eps_init, cap_steps in attempts:
            out = self._solve_fn(
                jnp.asarray(cap), jnp.asarray(cost), jnp.asarray(supply),
                jnp.asarray(f0), jnp.asarray(np.int32(eps_init)),
                jnp.asarray(np.int32(cap_steps)),
                *self._plan_dev,
            )
            if tel_cap:
                flow, steps, converged, p_overflow, tel_buf = out
            else:
                flow, steps, converged, p_overflow = out
            budget = cap_steps
            if bool(converged) and not bool(p_overflow):
                break
        self.last_supersteps = int(steps)
        self.last_telemetry = (
            soltel.decode(
                tel_buf, int(steps), tel_cap, "sharded", budget,
                converged=bool(converged) and not bool(p_overflow),
                nodes=n, arcs=m,
            )
            if tel_buf is not None
            else None
        )
        if bool(p_overflow) or not bool(converged):
            self._prev = None
        if bool(p_overflow):
            raise OverflowError("sharded push-relabel potentials approached int32 range")
        if not bool(converged):
            tel = self.last_telemetry
            raise soltel.SolverStallError(
                "sharded push-relabel did not converge; infeasible?",
                reason=soltel.detect_stall(tel) if tel is not None else None,
                telemetry=tel,
            )
        flow_np = np.asarray(flow)
        if self.warm_start:
            self._prev = flow_np.astype(np.int32)
        objective = int(
            (flow_np.astype(np.int64) * problem.cost.astype(np.int64)).sum()  # kschedlint: host-only (int64 objective math on host)
            + (problem.flow_offset.astype(np.int64) * problem.cost.astype(np.int64)).sum()  # kschedlint: host-only (int64 objective math on host)
        )
        return FlowResult(flow=flow_np.astype(np.int64), objective=objective, iterations=int(steps))  # kschedlint: host-only (FlowResult contract is int64)


# Level-3 registry ownership (ksched_tpu/analysis/program_registry.py)
from ..analysis.program_registry import declare_programs as _declare_programs

_declare_programs(
    __name__,
    "sharded_solve", "sharded_slot_solve", "sharded_slot_solve_warmp",
    "sharded_plan_apply", "replicated_plan_apply", "sharded_plan_fingerprint",
)
