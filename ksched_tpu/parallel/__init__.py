from .sharded_solver import ShardedJaxSolver, ShardedPlan, build_sharded_plan, make_sharded_solver

__all__ = [
    "ShardedJaxSolver",
    "ShardedPlan",
    "build_sharded_plan",
    "make_sharded_solver",
]
