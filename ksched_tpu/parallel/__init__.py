from .sharded_solver import ShardedJaxSolver, ShardedPlan, build_sharded_plan, make_sharded_solver
from .whatif import (
    ScenarioBatchResult,
    WhatIfSolver,
    drain_scenarios,
    surge_scenarios,
)

__all__ = [
    "ShardedJaxSolver",
    "ShardedPlan",
    "build_sharded_plan",
    "make_sharded_solver",
    "ScenarioBatchResult",
    "WhatIfSolver",
    "drain_scenarios",
    "surge_scenarios",
]
