from .sharded_solver import (
    ShardedJaxSolver,
    ShardedPlan,
    build_sharded_plan,
    make_sharded_slot_solver,
    make_sharded_solver,
    scan_csr_fits_hbm,
    sharded_fits_hbm,
    sharded_plan_apply_fn,
    sharded_plan_fingerprint_fn,
)
from .sharded_transport import ShardedLayeredSolver, sharded_transport_solve
from .whatif import (
    ScenarioBatchResult,
    WhatIfSolver,
    drain_scenarios,
    surge_scenarios,
)

__all__ = [
    "ShardedJaxSolver",
    "ShardedLayeredSolver",
    "sharded_transport_solve",
    "ShardedPlan",
    "build_sharded_plan",
    "make_sharded_solver",
    "make_sharded_slot_solver",
    "scan_csr_fits_hbm",
    "sharded_fits_hbm",
    "sharded_plan_apply_fn",
    "sharded_plan_fingerprint_fn",
    "ScenarioBatchResult",
    "WhatIfSolver",
    "drain_scenarios",
    "surge_scenarios",
]
