from .sharded_solver import ShardedJaxSolver, ShardedPlan, build_sharded_plan, make_sharded_solver
from .sharded_transport import ShardedLayeredSolver, sharded_transport_solve
from .whatif import (
    ScenarioBatchResult,
    WhatIfSolver,
    drain_scenarios,
    surge_scenarios,
)

__all__ = [
    "ShardedJaxSolver",
    "ShardedLayeredSolver",
    "sharded_transport_solve",
    "ShardedPlan",
    "build_sharded_plan",
    "make_sharded_solver",
    "ScenarioBatchResult",
    "WhatIfSolver",
    "drain_scenarios",
    "surge_scenarios",
]
