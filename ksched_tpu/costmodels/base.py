"""L4: the pluggable cost-model (policy) interface.

Reference: scheduling/flow/costmodel/interface.go:27-136. The 16-method
surface is kept intact — arc costs, preference/EC enumeration, lifecycle
hooks, and the stats traversal — because the graph manager drives policy
exclusively through it. TPU-specific extension: cost models may override
the vectorized batch hooks (``ec_to_resource_batch`` etc.) to emit whole
cost/capacity arrays at once for the array fast path; the default
implementations fan out to the scalar methods.
"""

from __future__ import annotations

import abc
import enum
from typing import List, Optional, Sequence, Tuple, TYPE_CHECKING

from ..data import ResourceDescriptor, ResourceTopologyNodeDescriptor
from ..utils import equiv_class_from_bytes

if TYPE_CHECKING:  # pragma: no cover
    from ..graph.flowgraph import Node


class CostModelType(enum.IntEnum):
    """Reference: costmodel/interface.go:33-43."""

    TRIVIAL = 0
    RANDOM = 1
    SJF = 2
    QUINCY = 3
    WHARE = 4
    COCO = 5
    OCTOPUS = 6
    VOID = 7
    NET = 8


# The wildcard equivalence class every task points at in aggregate-style
# cost models (reference: costmodel/interface.go:46).
CLUSTER_AGGREGATOR_EC = equiv_class_from_bytes(b"CLUSTER_AGG")

Cost = int


class CostModeler(abc.ABC):
    """Reference: costmodel/interface.go:54-136."""

    # -- arc costs --------------------------------------------------------

    @abc.abstractmethod
    def task_to_unscheduled_agg_cost(self, task_id: int) -> Cost:
        """Cost of leaving the task unscheduled this round; should rise
        monotonically across rounds so starvation is bounded."""

    @abc.abstractmethod
    def unscheduled_agg_to_sink_cost(self, job_id: int) -> Cost: ...

    @abc.abstractmethod
    def task_to_resource_node_cost(self, task_id: int, resource_id: int) -> Cost: ...

    @abc.abstractmethod
    def resource_node_to_resource_node_cost(
        self, source: Optional[ResourceDescriptor], destination: ResourceDescriptor
    ) -> Cost: ...

    @abc.abstractmethod
    def leaf_resource_node_to_sink_cost(self, resource_id: int) -> Cost: ...

    @abc.abstractmethod
    def task_continuation_cost(self, task_id: int) -> Cost: ...

    @abc.abstractmethod
    def task_preemption_cost(self, task_id: int) -> Cost: ...

    @abc.abstractmethod
    def task_to_equiv_class_aggregator(self, task_id: int, ec: int) -> Cost: ...

    @abc.abstractmethod
    def equiv_class_to_resource_node(self, ec: int, resource_id: int) -> Tuple[Cost, int]:
        """Returns (cost, capacity); capacity is typically free slots below."""

    @abc.abstractmethod
    def equiv_class_to_equiv_class(self, ec1: int, ec2: int) -> Tuple[Cost, int]: ...

    # -- preference enumeration -------------------------------------------

    @abc.abstractmethod
    def get_task_equiv_classes(self, task_id: int) -> List[int]: ...

    @abc.abstractmethod
    def get_outgoing_equiv_class_pref_arcs(self, ec: int) -> List[int]: ...

    @abc.abstractmethod
    def get_task_preference_arcs(self, task_id: int) -> List[int]: ...

    @abc.abstractmethod
    def get_equiv_class_to_equiv_classes_arcs(self, ec: int) -> List[int]: ...

    # -- lifecycle --------------------------------------------------------

    @abc.abstractmethod
    def add_machine(self, rtnd: ResourceTopologyNodeDescriptor) -> None: ...

    @abc.abstractmethod
    def add_task(self, task_id: int) -> None: ...

    @abc.abstractmethod
    def remove_machine(self, resource_id: int) -> None: ...

    @abc.abstractmethod
    def remove_task(self, task_id: int) -> None: ...

    # -- stats traversal (reverse BFS from the sink) ----------------------

    @abc.abstractmethod
    def gather_stats(self, accumulator: "Node", other: "Node") -> "Node": ...

    @abc.abstractmethod
    def prepare_stats(self, accumulator: "Node") -> None: ...

    @abc.abstractmethod
    def update_stats(self, accumulator: "Node", other: "Node") -> "Node": ...

    # -- policy feedback (no-op defaults; models override as needed) ------

    def note_round(self, unscheduled_task_ids: Sequence[int]) -> None:
        """Called by the scheduler after every round with the runnable
        tasks that stayed unscheduled (e.g. Quincy's wait-cost bound)."""

    def record_task_completion(self, td) -> None:
        """Called by the scheduler when a task completes; models that
        learn from observed runtimes (SJF, Whare-Map) override this."""

    # -- debug ------------------------------------------------------------

    def debug_info(self) -> str:
        return ""

    def debug_info_csv(self) -> str:
        return ""

    # -- vectorized batch hooks (TPU fast path; optional overrides) -------

    def ec_to_resource_batch(
        self, ec: int, resource_ids: Sequence[int]
    ) -> Tuple[List[Cost], List[int]]:
        """Batch form of equiv_class_to_resource_node: returns parallel
        (costs, capacities) lists for all given resources."""
        costs: List[Cost] = []
        caps: List[int] = []
        for rid in resource_ids:
            c, cap = self.equiv_class_to_resource_node(ec, rid)
            costs.append(c)
            caps.append(cap)
        return costs, caps

    def task_to_unscheduled_agg_cost_batch(self, task_ids: Sequence[int]) -> List[Cost]:
        return [self.task_to_unscheduled_agg_cost(t) for t in task_ids]

    def task_to_equiv_class_aggregator_batch(
        self, task_ids: Sequence[int], ec: int
    ) -> List[Cost]:
        return [self.task_to_equiv_class_aggregator(t, ec) for t in task_ids]
