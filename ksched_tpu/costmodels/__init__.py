from .base import CLUSTER_AGGREGATOR_EC, Cost, CostModeler, CostModelType
from .census import CLASS_ECS, NUM_TASK_CLASSES, ClassCensusKeeper, class_ec, ec_class
from .coco import CocoCostModel, coco_cost_matrix
from .trivial import TrivialCostModel
from .whare import WhareMapCostModel, whare_cost_matrix

__all__ = [
    "CLUSTER_AGGREGATOR_EC",
    "CLASS_ECS",
    "NUM_TASK_CLASSES",
    "ClassCensusKeeper",
    "class_ec",
    "ec_class",
    "Cost",
    "CostModeler",
    "CostModelType",
    "CocoCostModel",
    "coco_cost_matrix",
    "TrivialCostModel",
    "WhareMapCostModel",
    "whare_cost_matrix",
]
