from .base import CLUSTER_AGGREGATOR_EC, Cost, CostModeler, CostModelType
from .trivial import TrivialCostModel

__all__ = [
    "CLUSTER_AGGREGATOR_EC",
    "Cost",
    "CostModeler",
    "CostModelType",
    "TrivialCostModel",
]
