from .base import CLUSTER_AGGREGATOR_EC, Cost, CostModeler, CostModelType
from .census import CLASS_ECS, NUM_TASK_CLASSES, ClassCensusKeeper, class_ec, ec_class
from .coco import CocoCostModel, coco_cost_matrix
from .net import NetCostModel
from .quincy import BlockRegistry, QuincyCostModel
from .simple import OctopusCostModel, RandomCostModel, SjfCostModel, VoidCostModel
from .trivial import TrivialCostModel
from .whare import WhareMapCostModel, whare_cost_matrix

#: CostModelType -> implementation, the dispatch the reference plans in
#: costmodel/interface.go:33-43 — here every enumerated model exists.
MODEL_REGISTRY = {
    CostModelType.TRIVIAL: TrivialCostModel,
    CostModelType.RANDOM: RandomCostModel,
    CostModelType.SJF: SjfCostModel,
    CostModelType.QUINCY: QuincyCostModel,
    CostModelType.WHARE: WhareMapCostModel,
    CostModelType.COCO: CocoCostModel,
    CostModelType.OCTOPUS: OctopusCostModel,
    CostModelType.VOID: VoidCostModel,
    CostModelType.NET: NetCostModel,
}

__all__ = [
    "CLUSTER_AGGREGATOR_EC",
    "CLASS_ECS",
    "NUM_TASK_CLASSES",
    "ClassCensusKeeper",
    "class_ec",
    "ec_class",
    "Cost",
    "CostModeler",
    "CostModelType",
    "MODEL_REGISTRY",
    "BlockRegistry",
    "CocoCostModel",
    "coco_cost_matrix",
    "NetCostModel",
    "OctopusCostModel",
    "QuincyCostModel",
    "RandomCostModel",
    "SjfCostModel",
    "TrivialCostModel",
    "VoidCostModel",
    "WhareMapCostModel",
    "whare_cost_matrix",
]
