"""Shared class-census machinery for interference-aware cost models.

The reference carries a per-machine co-location census in
`WhareMapStats` (proto/whare_map_stats.proto:12-18) and per-class
penalties in `CoCoInterferenceScores` (proto/coco_interference_scores.
proto:11-16), but implements neither model (costmodel/interface.go:33-43
lists them as planned). Both models need the same input: for every
machine, how many running tasks of each CoCo class (Sheep/Rabbit/Devil/
Turtle, task_desc.proto:25-30) live below it, plus idle slots.

This module provides that census as part of the stats traversal the
graph manager already drives (ComputeTopologyStatistics, reference
graph_manager.go:480-511): `prepare` zeroes counts, `gather` re-seeds PU
leaves from their `current_running_tasks` and sums child counts upward —
exactly the aggregation discipline the trivial model uses for
slots/running counts (trivial_cost_modeler.go:147-176), extended with
the 4-class census.

Equivalence classes: one EC per task class (`class_ec(c)`), so the
flow-graph fan-out stays O(T + C·M) instead of O(T·M) — the same
aggregator trick the trivial model's single wildcard EC plays
(interface.go:46), refined per class so EC→machine arcs can carry
class-dependent interference costs.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ..data import (
    ResourceTopologyNodeDescriptor,
    TaskType,
    WhareMapStats,
)
from ..graph.flowgraph import Node, NodeType
from ..utils import ResourceMap, TaskMap, equiv_class_from_bytes, resource_id_from_string

NUM_TASK_CLASSES = 4  # Sheep, Rabbit, Devil, Turtle (task_desc.proto:25-30)

#: equivalence-class id per task class
CLASS_ECS = [
    equiv_class_from_bytes(b"TASK_CLASS_SHEEP"),
    equiv_class_from_bytes(b"TASK_CLASS_RABBIT"),
    equiv_class_from_bytes(b"TASK_CLASS_DEVIL"),
    equiv_class_from_bytes(b"TASK_CLASS_TURTLE"),
]
_EC_TO_CLASS = {ec: c for c, ec in enumerate(CLASS_ECS)}


def class_ec(task_type: TaskType) -> int:
    return CLASS_ECS[int(task_type)]


def ec_class(ec: int) -> Optional[int]:
    """Inverse of class_ec; None if the EC is not a class EC."""
    return _EC_TO_CLASS.get(ec)


def census_vector(w: WhareMapStats) -> np.ndarray:
    """WhareMapStats -> [4] counts in TaskType order."""
    return np.array(
        [w.num_sheep, w.num_rabbits, w.num_devils, w.num_turtles], dtype=np.int64
    )


class ClassCensusKeeper:
    """Maintains per-resource slot/running aggregates plus the 4-class
    census in each descriptor's `whare_map_stats`, via the stats
    traversal hooks (CostModeler.prepare_stats/gather_stats)."""

    def __init__(
        self,
        resource_map: ResourceMap,
        task_map: TaskMap,
        max_tasks_per_pu: int,
    ) -> None:
        self.resource_map = resource_map
        self.task_map = task_map
        self.max_tasks_per_pu = max_tasks_per_pu
        self.machines: Dict[int, ResourceTopologyNodeDescriptor] = {}

    # -- machine registry (cost models' add/remove_machine hooks) ---------

    def add_machine(self, rtnd: ResourceTopologyNodeDescriptor) -> None:
        rid = resource_id_from_string(rtnd.resource_desc.uuid)
        self.machines.setdefault(rid, rtnd)

    def remove_machine(self, resource_id: int) -> None:
        self.machines.pop(resource_id, None)

    # -- stats traversal ---------------------------------------------------

    def prepare(self, accumulator: Node) -> None:
        if not accumulator.is_resource_node:
            return
        rd = accumulator.resource_descriptor
        if rd is None:
            raise ValueError(f"node {accumulator.id} has no resource descriptor")
        rd.num_running_tasks_below = 0
        rd.num_slots_below = 0
        rd.whare_map_stats = WhareMapStats()

    def gather(self, accumulator: Node, other: Node) -> Node:
        if not accumulator.is_resource_node:
            return accumulator
        acc_rd = accumulator.resource_descriptor
        if not other.is_resource_node:
            if other.type == NodeType.SINK:
                # PU leaf: re-seed from its running-task list, counting
                # classes from the task descriptors.
                acc_rd.num_running_tasks_below = len(acc_rd.current_running_tasks)
                acc_rd.num_slots_below = self.max_tasks_per_pu
                w = acc_rd.whare_map_stats
                w.num_idle = max(
                    0, self.max_tasks_per_pu - len(acc_rd.current_running_tasks)
                )
                for tid in acc_rd.current_running_tasks:
                    td = self.task_map.find(tid)
                    ttype = td.task_type if td is not None else TaskType.SHEEP
                    if ttype == TaskType.SHEEP:
                        w.num_sheep += 1
                    elif ttype == TaskType.RABBIT:
                        w.num_rabbits += 1
                    elif ttype == TaskType.DEVIL:
                        w.num_devils += 1
                    else:
                        w.num_turtles += 1
            return accumulator
        o_rd = other.resource_descriptor
        if o_rd is None:
            raise ValueError(f"node {other.id} has no resource descriptor")
        acc_rd.num_running_tasks_below += o_rd.num_running_tasks_below
        acc_rd.num_slots_below += o_rd.num_slots_below
        aw, ow = acc_rd.whare_map_stats, o_rd.whare_map_stats
        aw.num_idle += ow.num_idle
        aw.num_sheep += ow.num_sheep
        aw.num_rabbits += ow.num_rabbits
        aw.num_devils += ow.num_devils
        aw.num_turtles += ow.num_turtles
        return accumulator

    # -- convenience -------------------------------------------------------

    def free_slots(self, resource_id: int) -> int:
        rs = self.resource_map.find(resource_id)
        if rs is None:
            raise KeyError(f"no resource status for {resource_id}")
        rd = rs.descriptor
        return rd.num_slots_below - rd.num_running_tasks_below

    def machine_census(self, resource_id: int) -> np.ndarray:
        rs = self.resource_map.find(resource_id)
        if rs is None:
            raise KeyError(f"no resource status for {resource_id}")
        return census_vector(rs.descriptor.whare_map_stats)

    def task_class(self, task_id: int) -> int:
        td = self.task_map.find(task_id)
        return int(td.task_type) if td is not None else int(TaskType.SHEEP)
