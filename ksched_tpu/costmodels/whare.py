"""Whare-Map: heterogeneity- and co-runner-aware cost model.

The reference declares WHARE (costmodel/interface.go:37) and carries its
input — the per-machine `WhareMapStats` census (whare_map_stats.proto:
12-18) — without implementing the model. This implements the Whare-MCs
idea (Mars et al., "Whare-Map: heterogeneity in 'homogeneous' warehouse-
scale computers", ISCA'13): score each (task class, machine) pair by the
*observed* slowdown of that class when running on that machine with its
current co-runner mix, and prefer placements with low expected slowdown.

The "map" is a 4×4 matrix psi[c, k]: EWMA-learned normalized slowdown
(scaled ×100) of class c co-located with class k. It starts from a
neutral prior and is refined online via `record_runtime` as task final
reports arrive (TaskFinalReport, task_final_report.proto:10-19, carries
the runtimes the reference would feed this with).

EC(c) → machine cost = expected slowdown of class c against the
machine's census, census-weighted:

    cost(c, m) = Σ_k census_k(m) · psi[c, k] / max(1, Σ_k census_k(m))
                 − IDLE_BONUS · idle(m)/slots(m)

so an idle machine costs its prior, a crowded noisy machine costs its
measured co-runner slowdown. Capacity = free slots below, as in the
trivial model (trivial_cost_modeler.go:76-83).

Vectorized form for the array fast path: `whare_cost_matrix(census,
idle, psi)` returns the [4, M] matrix in one shot.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..data import ResourceDescriptor, ResourceTopologyNodeDescriptor
from ..graph.flowgraph import Node
from ..utils import ResourceMap, TaskMap
from .base import Cost, CostModeler
from .census import CLASS_ECS, ClassCensusKeeper, ec_class

# Prior psi[c, k] ×100: neutral 100 = no slowdown; devils degrade
# co-runners, rabbits are the most sensitive.
PSI_PRIOR = np.array(
    [
        # co-runner: S    R    D    T
        [105, 103, 140, 100],  # sheep
        [115, 110, 200, 101],  # rabbit
        [120, 130, 150, 105],  # devil
        [100, 100, 102, 100],  # turtle
    ],
    # int32: psi values stay O(10^4) (slowdown x100), census counts
    # O(slots), so products sit far below 2^31 — and the matrix feeds
    # device-bound int32 cost arrays anyway
    dtype=np.int32,
)

IDLE_BONUS = 20
MAX_COST = 2_000
UNSCHEDULED_COST = MAX_COST + 500
EWMA_WEIGHT = 0.25  # weight of a new observation


def whare_cost_matrix(
    census: np.ndarray, idle: np.ndarray, slots: np.ndarray, psi: Optional[np.ndarray] = None
) -> np.ndarray:
    """Vectorized Whare-MCs costs.

    census: [M, 4] running-class counts; idle: [M] idle slots;
    slots: [M] total slots; psi: [4, 4] slowdown map (default prior).
    Returns [4, M] int32.
    """
    if psi is None:
        psi = PSI_PRIOR
    tot = np.maximum(1, census.sum(axis=1))  # [M]
    expected = (psi @ census.T.astype(np.int64)) // tot  # [4, M]
    bonus = (IDLE_BONUS * idle.astype(np.int64)) // np.maximum(1, slots.astype(np.int64))
    cost = expected - bonus[None, :]
    return np.clip(cost, 0, MAX_COST).astype(np.int32)


class WhareMapCostModel(CostModeler):
    """Observed-slowdown placement (TPU-rebuild implementation of the
    reference's planned WHARE model, costmodel/interface.go:37)."""

    def __init__(
        self,
        resource_map: ResourceMap,
        task_map: TaskMap,
        leaf_resource_ids,
        max_tasks_per_pu: int,
    ) -> None:
        self.resource_map = resource_map
        self.task_map = task_map
        self.leaf_resource_ids = leaf_resource_ids
        self.census = ClassCensusKeeper(resource_map, task_map, max_tasks_per_pu)
        # float32 is ample for an EWMA over x100 slowdowns (24-bit
        # mantissa vs values O(10^4)); 64-bit buys nothing here
        self.psi = PSI_PRIOR.astype(np.float32).copy()

    # -- the map (online learning) ----------------------------------------

    def record_runtime(self, task_class: int, corunner_class: int, slowdown_x100: float) -> None:
        """Fold an observed slowdown sample (×100; 100 = baseline) into
        the map — fed from TaskFinalReport runtimes in the reference's
        intended pipeline."""
        old = self.psi[task_class, corunner_class]
        self.psi[task_class, corunner_class] = (
            (1.0 - EWMA_WEIGHT) * old + EWMA_WEIGHT * slowdown_x100
        )

    def psi_int(self) -> np.ndarray:
        return np.rint(self.psi).astype(np.int32)

    # -- arc costs --------------------------------------------------------

    def task_to_unscheduled_agg_cost(self, task_id: int) -> Cost:
        return UNSCHEDULED_COST

    def unscheduled_agg_to_sink_cost(self, job_id: int) -> Cost:
        return 0

    def task_to_resource_node_cost(self, task_id: int, resource_id: int) -> Cost:
        return int(self._machine_cost(self.census.task_class(task_id), resource_id))

    def resource_node_to_resource_node_cost(
        self, source: Optional[ResourceDescriptor], destination: ResourceDescriptor
    ) -> Cost:
        return 0

    def leaf_resource_node_to_sink_cost(self, resource_id: int) -> Cost:
        return 0

    def task_continuation_cost(self, task_id: int) -> Cost:
        return 0

    def task_preemption_cost(self, task_id: int) -> Cost:
        return MAX_COST // 2

    def task_to_equiv_class_aggregator(self, task_id: int, ec: int) -> Cost:
        return 0

    def equiv_class_to_resource_node(self, ec: int, resource_id: int) -> Tuple[Cost, int]:
        c = ec_class(ec)
        if c is None:
            return 0, 0
        return int(self._machine_cost(c, resource_id)), self.census.free_slots(resource_id)

    def equiv_class_to_equiv_class(self, ec1: int, ec2: int) -> Tuple[Cost, int]:
        return 0, 0

    def _machine_cost(self, task_class: int, resource_id: int) -> int:
        rs = self.resource_map.find(resource_id)
        if rs is None:
            raise KeyError(f"no resource status for {resource_id}")
        rd = rs.descriptor
        census = self.census.machine_census(resource_id)
        tot = max(1, int(census.sum()))
        expected = int(self.psi_int()[task_class] @ census) // tot
        slots = max(1, rd.num_slots_below)
        idle = rd.whare_map_stats.num_idle
        cost = expected - (IDLE_BONUS * idle) // slots
        return int(np.clip(cost, 0, MAX_COST))

    # -- preference enumeration -------------------------------------------

    def get_task_equiv_classes(self, task_id: int) -> List[int]:
        return [CLASS_ECS[self.census.task_class(task_id)]]

    def get_outgoing_equiv_class_pref_arcs(self, ec: int) -> List[int]:
        if ec_class(ec) is None:
            return []
        return list(self.census.machines.keys())

    def get_task_preference_arcs(self, task_id: int) -> List[int]:
        return []

    def get_equiv_class_to_equiv_classes_arcs(self, ec: int) -> List[int]:
        return []

    # -- lifecycle --------------------------------------------------------

    def add_machine(self, rtnd: ResourceTopologyNodeDescriptor) -> None:
        self.census.add_machine(rtnd)

    def add_task(self, task_id: int) -> None:
        pass

    def remove_machine(self, resource_id: int) -> None:
        self.census.remove_machine(resource_id)

    def remove_task(self, task_id: int) -> None:
        pass

    # -- stats traversal --------------------------------------------------

    def gather_stats(self, accumulator: Node, other: Node) -> Node:
        return self.census.gather(accumulator, other)

    def prepare_stats(self, accumulator: Node) -> None:
        self.census.prepare(accumulator)

    def update_stats(self, accumulator: Node, other: Node) -> Node:
        return accumulator
