"""Net: network-bandwidth-aware cost model.

The reference enumerates MODEL_NET (costmodel/interface.go:42) without
implementing it. This implements Firmament's net-bw policy idea: tasks
declare a network-bandwidth request (TaskDescriptor.resource_request
.net_bw, proto/task_desc.proto:69 / resource_vector.proto:18) and
machines a capacity (ResourceDescriptor.capacity.net_bw,
resource_desc.proto:57); placement cost rises with the fraction of the
machine's bandwidth already reserved, and machines that cannot fit the
request at all are priced at the gate cost so the flow routes around
them.

Reserved bandwidth is tracked per machine from the tasks bound below it
(ResourceDescriptor.reserved_resources, resource_desc.proto:54) during
the stats traversal, keeping the one-pass-per-round contract of
gather_stats (costmodel/interface.go:120-127).

Known quantization limit (inherent to flow-based scheduling, the issue
the CoCo line of work exists to solve): the gate prices each task
against ROUND-START reservations, so several tasks placed in one round
can collectively overcommit a machine each would individually fit.
Reservations refresh between rounds, so steady-state incremental
scheduling (small per-round batches, the reference's operating regime)
converges; large cold batches of bandwidth-heavy tasks can transiently
overcommit.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..data import ResourceDescriptor, ResourceType
from ..graph.flowgraph import Node, NodeType
from ..utils import ResourceMap, TaskMap, resource_id_from_string
from .base import CLUSTER_AGGREGATOR_EC, Cost
from .trivial import TrivialCostModel

CONGESTION_SCALE = 100  # cost at 100% bandwidth reservation
GATE_COST = 10 * CONGESTION_SCALE  # machine cannot fit the request
# Above every feasible congestion price but BELOW the gate: a task whose
# request fits nowhere stays unscheduled rather than overcommitting a
# gated machine.
UNSCHEDULED_COST = 2 * CONGESTION_SCALE


class NetCostModel(TrivialCostModel):
    def __init__(
        self,
        resource_map: ResourceMap,
        task_map: TaskMap,
        leaf_resource_ids,
        max_tasks_per_pu: int,
    ) -> None:
        super().__init__(resource_map, task_map, leaf_resource_ids, max_tasks_per_pu)
        # machine rid -> (reserved net bw, capacity net bw)
        self._bw: Dict[int, Tuple[int, int]] = {}

    # -- bandwidth bookkeeping --------------------------------------------

    def _task_request(self, task_id: int) -> int:
        td = self.task_map.find(task_id)
        return int(td.resource_request.net_bw) if td is not None else 0

    def _machine_bw(self, resource_id: int) -> Tuple[int, int]:
        if resource_id in self._bw:
            return self._bw[resource_id]
        rs = self.resource_map.find(resource_id)
        cap = int(rs.descriptor.capacity.net_bw) if rs is not None else 0
        return 0, cap

    def _congestion_cost(self, task_id: int, resource_id: int) -> int:
        request = self._task_request(task_id)
        reserved, cap = self._machine_bw(resource_id)
        if cap <= 0:
            # machine declared no bandwidth capacity: bandwidth-neutral
            return 0 if request == 0 else GATE_COST
        if reserved + request > cap:
            return GATE_COST
        return (CONGESTION_SCALE * (reserved + request)) // cap

    # -- arc costs --------------------------------------------------------

    def task_to_unscheduled_agg_cost(self, task_id: int) -> Cost:
        return UNSCHEDULED_COST

    def task_to_resource_node_cost(self, task_id: int, resource_id: int) -> Cost:
        return self._congestion_cost(task_id, resource_id)

    def task_to_equiv_class_aggregator(self, task_id: int, ec: int) -> Cost:
        return 0

    def get_task_preference_arcs(self, task_id: int) -> List[int]:
        """Direct arcs to every machine, priced by congestion — the EC
        wildcard cannot carry per-(task, machine) bandwidth prices.
        Zero-request tasks route via the aggregator alone (identical
        pricing at a fraction of the arc count)."""
        if self._task_request(task_id) == 0:
            return []
        return list(self._machines.keys())

    def get_task_equiv_classes(self, task_id: int) -> List[int]:
        # A bandwidth-requesting task must NOT get the wildcard-EC route:
        # EC→machine arcs are per-(EC, machine) and cannot carry the
        # per-task gate, so the aggregator would bypass it. Such tasks
        # route only via their (gated) direct arcs + the unsched escape.
        if self._task_request(task_id) > 0:
            return []
        return [CLUSTER_AGGREGATOR_EC]

    def equiv_class_to_resource_node(self, ec: int, resource_id: int) -> Tuple[Cost, int]:
        cost, free = super().equiv_class_to_resource_node(ec, resource_id)
        reserved, cap = self._machine_bw(resource_id)
        if cap > 0:
            cost = (CONGESTION_SCALE * reserved) // cap
        return cost, free

    # -- stats traversal: accumulate reserved bandwidth -------------------

    def prepare_stats(self, accumulator: Node) -> None:
        super().prepare_stats(accumulator)
        if accumulator.is_resource_node and accumulator.resource_descriptor is not None:
            accumulator.resource_descriptor.reserved_resources.net_bw = 0

    def gather_stats(self, accumulator: Node, other: Node) -> Node:
        super().gather_stats(accumulator, other)
        if not accumulator.is_resource_node:
            return accumulator
        acc_rd = accumulator.resource_descriptor
        if not other.is_resource_node:
            if other.type == NodeType.SINK:
                # PU leaf: sum requests of tasks running here.
                acc_rd.reserved_resources.net_bw = sum(
                    self._task_request(t) for t in acc_rd.current_running_tasks
                )
                self._note_machine(acc_rd)
            return accumulator
        acc_rd.reserved_resources.net_bw += other.resource_descriptor.reserved_resources.net_bw
        self._note_machine(acc_rd)
        return accumulator

    def _note_machine(self, rd: ResourceDescriptor) -> None:
        if rd.type == ResourceType.MACHINE:
            rid = resource_id_from_string(rd.uuid)
            self._bw[rid] = (int(rd.reserved_resources.net_bw), int(rd.capacity.net_bw))

    def remove_machine(self, resource_id: int) -> None:
        super().remove_machine(resource_id)
        self._bw.pop(resource_id, None)
