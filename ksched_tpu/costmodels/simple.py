"""The simple policy family: Void, Random, Octopus, SJF.

The reference enumerates these models (costmodel/interface.go:33-43 —
MODEL_VOID, MODEL_RANDOM, MODEL_OCTOPUS, MODEL_SJF) without implementing
any of them; only Trivial exists. These are the TPU-rebuild
implementations, following the published Firmament semantics for each
policy. All four keep the Trivial graph shape — one wildcard cluster
aggregator fanning out to every machine with capacity = free slots
(trivial_cost_modeler.go:76-110) — and differ only in arc pricing, so
they subclass TrivialCostModel and override the cost methods.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from ..utils import ResourceMap, TaskMap, rng
from .base import CLUSTER_AGGREGATOR_EC, Cost
from .trivial import TrivialCostModel


class VoidCostModel(TrivialCostModel):
    """Every arc is free; placement is solver-arbitrary.

    The plumbing-test model (reference enum MODEL_VOID, interface.go:40):
    with all costs zero, any max-flow is optimal, so this isolates
    graph-construction and flow-decode bugs from pricing bugs. A task is
    as happy unscheduled as placed — tests using it must assert only
    conservation properties, not placement counts.
    """

    UNSCHEDULED_COST = 0
    CLUSTER_AGG_COST = 0


class RandomCostModel(TrivialCostModel):
    """Uniformly random arc prices (reference enum MODEL_RANDOM,
    interface.go:35): placement becomes a seeded shuffle. Useful as a
    chaos baseline — any policy that cannot beat random placement on a
    workload is not earning its arcs. Draws from the framework's global
    seeded RNG (utils.seed_rng) so rounds are reproducible.
    """

    MAX_RANDOM_COST = 1000

    def task_to_unscheduled_agg_cost(self, task_id: int) -> Cost:
        # Strictly above the dearest task→EC→machine path so capacity is
        # still used.
        return 2 * self.MAX_RANDOM_COST + 1

    def task_to_equiv_class_aggregator(self, task_id: int, ec: int) -> Cost:
        return rng().randrange(self.MAX_RANDOM_COST)

    def equiv_class_to_resource_node(self, ec: int, resource_id: int) -> Tuple[Cost, int]:
        _, free = super().equiv_class_to_resource_node(ec, resource_id)
        return rng().randrange(self.MAX_RANDOM_COST), free


class OctopusCostModel(TrivialCostModel):
    """Load balancing: a machine costs its current load (reference enum
    MODEL_OCTOPUS, interface.go:39; Firmament's octopus_cost_model prices
    EC→machine arcs by the number of running tasks below). The flow
    therefore spreads tasks to the least-loaded machines first, and the
    incremental re-solve keeps the spread as load shifts.
    """

    LOAD_COST_SCALE = 10

    def __init__(self, *args, **kwargs) -> None:
        super().__init__(*args, **kwargs)
        self._max_slots_seen = 1

    def equiv_class_to_resource_node(self, ec: int, resource_id: int) -> Tuple[Cost, int]:
        rs = self.resource_map.find(resource_id)
        if rs is None:
            raise KeyError(f"no resource status for {resource_id}")
        rd = rs.descriptor
        self._max_slots_seen = max(self._max_slots_seen, rd.num_slots_below)
        free = rd.num_slots_below - rd.num_running_tasks_below
        return self.LOAD_COST_SCALE * rd.num_running_tasks_below, free

    def task_to_unscheduled_agg_cost(self, task_id: int) -> Cost:
        # Must dominate any partially-free machine's price: a full machine
        # of S slots prices S*scale, so anything above (S+1)*scale keeps
        # the escape arc dearer than every machine with a free slot.
        return self.LOAD_COST_SCALE * (self._max_slots_seen + 2)


class SjfCostModel(TrivialCostModel):
    """Shortest job first (reference enum MODEL_SJF, interface.go:36).

    Placement price rises with the task's estimated runtime, so when
    slots are contended the min-cost flow gives them to the shortest
    tasks and routes the long ones through the unscheduled aggregator.
    Runtime estimates are learned per job: an EWMA over the runtimes of
    completed tasks (TaskFinalReport.runtime, task_final_report.proto:
    17), falling back to a neutral default until evidence arrives —
    the pipeline the reference's final_report field exists to feed.
    """

    DEFAULT_RUNTIME_COST = 100
    MAX_RUNTIME_COST = 10_000
    EWMA_WEIGHT = 0.3

    def __init__(
        self,
        resource_map: ResourceMap,
        task_map: TaskMap,
        leaf_resource_ids,
        max_tasks_per_pu: int,
    ) -> None:
        super().__init__(resource_map, task_map, leaf_resource_ids, max_tasks_per_pu)
        self._job_runtime_ewma: Dict[str, float] = {}

    def record_completion(self, job_id: str, runtime: float) -> None:
        """Fold a completed task's runtime into its job's estimate."""
        old = self._job_runtime_ewma.get(job_id)
        if old is None:
            self._job_runtime_ewma[job_id] = runtime
        else:
            self._job_runtime_ewma[job_id] = (
                (1.0 - self.EWMA_WEIGHT) * old + self.EWMA_WEIGHT * runtime
            )

    def estimated_runtime_cost(self, task_id: int) -> int:
        td = self.task_map.find(task_id)
        if td is None:
            return self.DEFAULT_RUNTIME_COST
        est = self._job_runtime_ewma.get(td.job_id)
        if est is None:
            return self.DEFAULT_RUNTIME_COST
        return int(min(max(est, 1.0), self.MAX_RUNTIME_COST))

    def task_to_equiv_class_aggregator(self, task_id: int, ec: int) -> Cost:
        if ec != CLUSTER_AGGREGATOR_EC:
            return 0
        return self.estimated_runtime_cost(task_id)

    def task_to_unscheduled_agg_cost(self, task_id: int) -> Cost:
        return self.MAX_RUNTIME_COST + 1

    def record_task_completion(self, td) -> None:
        runtime = 0.0
        if td.final_report is not None and td.final_report.runtime:
            runtime = float(td.final_report.runtime)
        elif td.finish_time and td.start_time:
            runtime = float(td.finish_time - td.start_time)
        elif td.total_run_time:
            runtime = float(td.total_run_time)
        if runtime > 0:
            self.record_completion(td.job_id, runtime)
