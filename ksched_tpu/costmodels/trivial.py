"""The trivial cost model: one wildcard aggregator, constant costs.

Reference: scheduling/flow/costmodel/trivial_cost_modeler.go. Policy:
leaving a task unscheduled costs 5, routing through the cluster
aggregator EC costs 2, everything else costs 0; the EC fans out to every
machine with capacity = free slots below (slots − running).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from ..data import ResourceDescriptor, ResourceTopologyNodeDescriptor
from ..graph.flowgraph import Node, NodeType
from ..utils import ResourceMap, TaskMap, resource_id_from_string
from .base import CLUSTER_AGGREGATOR_EC, Cost, CostModeler


class TrivialCostModel(CostModeler):
    UNSCHEDULED_COST = 5  # reference: trivial_cost_modeler.go:41-43
    CLUSTER_AGG_COST = 2  # reference: trivial_cost_modeler.go:69-74

    def __init__(
        self,
        resource_map: ResourceMap,
        task_map: TaskMap,
        leaf_resource_ids: Set[int],
        max_tasks_per_pu: int,
    ) -> None:
        self.resource_map = resource_map
        self.task_map = task_map
        self.leaf_resource_ids = leaf_resource_ids
        self.max_tasks_per_pu = max_tasks_per_pu
        # machine resource id -> topology node (reference:
        # trivial_cost_modeler.go:23-25,129-143)
        self._machines: Dict[int, ResourceTopologyNodeDescriptor] = {}

    # -- arc costs --------------------------------------------------------

    def task_to_unscheduled_agg_cost(self, task_id: int) -> Cost:
        return self.UNSCHEDULED_COST

    def unscheduled_agg_to_sink_cost(self, job_id: int) -> Cost:
        return 0

    def task_to_resource_node_cost(self, task_id: int, resource_id: int) -> Cost:
        return 0

    def resource_node_to_resource_node_cost(
        self, source: Optional[ResourceDescriptor], destination: ResourceDescriptor
    ) -> Cost:
        return 0

    def leaf_resource_node_to_sink_cost(self, resource_id: int) -> Cost:
        return 0

    def task_continuation_cost(self, task_id: int) -> Cost:
        return 0

    def task_preemption_cost(self, task_id: int) -> Cost:
        return 0

    def task_to_equiv_class_aggregator(self, task_id: int, ec: int) -> Cost:
        return self.CLUSTER_AGG_COST if ec == CLUSTER_AGGREGATOR_EC else 0

    def equiv_class_to_resource_node(self, ec: int, resource_id: int) -> Tuple[Cost, int]:
        rs = self.resource_map.find(resource_id)
        if rs is None:
            raise KeyError(f"no resource status for {resource_id}")
        free = rs.descriptor.num_slots_below - rs.descriptor.num_running_tasks_below
        return 0, free

    def equiv_class_to_equiv_class(self, ec1: int, ec2: int) -> Tuple[Cost, int]:
        return 0, 0

    # -- preference enumeration -------------------------------------------

    def get_task_equiv_classes(self, task_id: int) -> List[int]:
        if self.task_map.find(task_id) is None:
            raise KeyError(f"no task descriptor for {task_id}")
        return [CLUSTER_AGGREGATOR_EC]

    def get_outgoing_equiv_class_pref_arcs(self, ec: int) -> List[int]:
        if ec != CLUSTER_AGGREGATOR_EC:
            return []
        return list(self._machines.keys())

    def get_task_preference_arcs(self, task_id: int) -> List[int]:
        return []

    def get_equiv_class_to_equiv_classes_arcs(self, ec: int) -> List[int]:
        return []

    # -- lifecycle --------------------------------------------------------

    def add_machine(self, rtnd: ResourceTopologyNodeDescriptor) -> None:
        rid = resource_id_from_string(rtnd.resource_desc.uuid)
        self._machines.setdefault(rid, rtnd)

    def add_task(self, task_id: int) -> None:
        pass

    def remove_machine(self, resource_id: int) -> None:
        self._machines.pop(resource_id, None)

    def remove_task(self, task_id: int) -> None:
        pass

    # -- stats traversal --------------------------------------------------

    def gather_stats(self, accumulator: Node, other: Node) -> Node:
        """Accumulate running-task/slot counts up the resource tree;
        PU leaves re-seed from their running-task lists (reference:
        trivial_cost_modeler.go:147-165)."""
        if not accumulator.is_resource_node:
            return accumulator
        if not other.is_resource_node:
            if other.type == NodeType.SINK:
                rd = accumulator.resource_descriptor
                rd.num_running_tasks_below = len(rd.current_running_tasks)
                rd.num_slots_below = self.max_tasks_per_pu
            return accumulator
        if other.resource_descriptor is None:
            raise ValueError(f"node {other.id} has no resource descriptor")
        acc_rd = accumulator.resource_descriptor
        acc_rd.num_running_tasks_below += other.resource_descriptor.num_running_tasks_below
        acc_rd.num_slots_below += other.resource_descriptor.num_slots_below
        return accumulator

    def prepare_stats(self, accumulator: Node) -> None:
        if not accumulator.is_resource_node:
            return
        rd = accumulator.resource_descriptor
        if rd is None:
            raise ValueError(f"node {accumulator.id} has no resource descriptor")
        rd.num_running_tasks_below = 0
        rd.num_slots_below = 0

    def update_stats(self, accumulator: Node, other: Node) -> Node:
        return accumulator
