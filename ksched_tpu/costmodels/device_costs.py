"""Traceable (jnp) twins of the vectorized cost matrices, for the
device-resident scheduling round.

DeviceBulkCluster's `class_cost_fn` runs inside the jitted round and
receives the on-device running-class census [M, C]; these functions turn
it into the [C, M] arc-cost matrix the transport solve consumes — the
same policies as the numpy forms (costmodels/coco.py `coco_cost_matrix`,
costmodels/whare.py `whare_cost_matrix`; tests assert elementwise
equality), expressed in jnp so the whole round stays one compiled
program.

The reference plans these models but never implements them
(costmodel/interface.go:33-43); the policy inputs exist as protos
(coco_interference_scores.proto:11-16, whare_map_stats.proto:12-18).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from .coco import INTERFERENCE, MAX_COST as COCO_MAX_COST
from .whare import IDLE_BONUS, MAX_COST as WHARE_MAX_COST, PSI_PRIOR


def coco_device_cost_fn(penalties: Optional[np.ndarray] = None):
    """class_cost_fn for CoCo: census [M, 4] -> cost [4, M] int32.

    penalties: optional [M, 4] static per-machine per-incoming-class
    penalty matrix (CoCoInterferenceScores), closed over as a constant.
    """
    W = jnp.asarray(INTERFERENCE, jnp.int32)
    pen = None if penalties is None else jnp.asarray(penalties.T, jnp.int32)

    def fn(census):
        cost = W @ census.T.astype(jnp.int32)  # [4, M]
        if pen is not None:
            cost = cost + pen
        return jnp.minimum(cost, COCO_MAX_COST).astype(jnp.int32)

    return fn


def whare_device_cost_fn(
    slots_per_machine: int,
    psi: Optional[np.ndarray] = None,
    platform_factor: Optional[np.ndarray] = None,
):
    """class_cost_fn for Whare-Map: census [M, 4] -> cost [4, M] int32.

    slots_per_machine: total slots per machine (homogeneous topology, so
    idle(m) = slots - census row sum — the device round has no separate
    idle input).
    psi: optional [4, 4] slowdown map (default: the learning prior).
    platform_factor: optional [M] percentage multiplier (100 = neutral)
    modelling heterogeneous machine platforms (the "heterogeneity in
    homogeneous WSCs" axis of Whare-Map); applied to the expected
    slowdown before the idle bonus.
    """
    psi_d = jnp.asarray(PSI_PRIOR if psi is None else psi, jnp.int32)
    plat = None if platform_factor is None else jnp.asarray(platform_factor, jnp.int32)
    slots = int(slots_per_machine)

    def fn(census):
        c32 = census.astype(jnp.int32)
        tot = jnp.maximum(1, jnp.sum(c32, axis=1))  # [M]
        expected = (psi_d @ c32.T) // tot[None, :]  # [4, M]
        if plat is not None:
            expected = (expected * plat[None, :]) // 100
        idle = jnp.maximum(0, slots - jnp.sum(c32, axis=1))
        bonus = (IDLE_BONUS * idle) // slots
        cost = expected - bonus[None, :]
        return jnp.clip(cost, 0, WHARE_MAX_COST).astype(jnp.int32)

    return fn
