"""Quincy: data-locality-driven cost model.

The reference enumerates MODEL_QUINCY (costmodel/interface.go:38) without
implementing it, yet Quincy (Isard et al., SOSP'09) is the paper the
whole flow-scheduling architecture comes from. This implements its cost
structure over the rebuild's graph:

- each task has input blocks (TaskDescriptor.dependencies, carried as
  ReferenceDescriptors with ``size`` and ``location`` —
  proto/task_desc.proto:36, reference_desc.proto:38-41, fields the
  reference carries but never reads);
- a block registry maps block id → machines holding a replica;
- cost(task → machine m) = bytes the task would pull across the network
  if placed on m, i.e. total input size minus bytes local to m, scaled
  to COST_PER_MB. Machines holding enough input get direct preference
  arcs (Quincy's "preferred set": > PREFERENCE_FRACTION of input local);
- cost(task → cluster agg) = worst-case transfer (no locality), so the
  aggregator remains the fallback route to any machine;
- cost(task → unscheduled agg) grows with the rounds the task has
  waited (Quincy's wait-time term, bounding starvation: eventually
  waiting costs more than the worst placement).
"""

from __future__ import annotations

from typing import Dict, List, Set, Tuple

from ..data import ResourceTopologyNodeDescriptor
from ..utils import ResourceMap, TaskMap, resource_id_from_string
from .base import CLUSTER_AGGREGATOR_EC, Cost
from .trivial import TrivialCostModel

COST_PER_MB = 1  # cost units per megabyte pulled remotely
MB = 1 << 20
PREFERENCE_FRACTION = 0.5  # direct arc if > 50% of input is local
WAIT_COST_PER_ROUND = 10


class BlockRegistry:
    """block id → machines holding a replica (the GFS/TidyFS view Quincy
    reads; here a first-class registry fed by the driver/trace layer)."""

    def __init__(self) -> None:
        self._locations: Dict[int, Set[int]] = {}
        self._sizes: Dict[int, int] = {}

    def register(self, block_id: int, size: int, machine_ids) -> None:
        self._locations.setdefault(block_id, set()).update(machine_ids)
        self._sizes[block_id] = size

    def drop_machine(self, machine_id: int) -> None:
        for holders in self._locations.values():
            holders.discard(machine_id)

    def holders(self, block_id: int) -> Set[int]:
        return self._locations.get(block_id, set())

    def size(self, block_id: int) -> int:
        return self._sizes.get(block_id, 0)


class QuincyCostModel(TrivialCostModel):
    def __init__(
        self,
        resource_map: ResourceMap,
        task_map: TaskMap,
        leaf_resource_ids,
        max_tasks_per_pu: int,
    ) -> None:
        super().__init__(resource_map, task_map, leaf_resource_ids, max_tasks_per_pu)
        self.blocks = BlockRegistry()
        self._wait_rounds: Dict[int, int] = {}

    # -- locality arithmetic ----------------------------------------------

    def _input_bytes(self, task_id: int) -> Tuple[int, Dict[int, int]]:
        """Returns (total input bytes, {machine id: bytes local there})."""
        td = self.task_map.find(task_id)
        if td is None or not td.dependencies:
            return 0, {}
        total = 0
        local: Dict[int, int] = {}
        for dep in td.dependencies:
            size = dep.size or self.blocks.size(dep.id)
            total += size
            for m in self.blocks.holders(dep.id):
                local[m] = local.get(m, 0) + size
        return total, local

    def _transfer_cost(self, total: int, local_bytes: int) -> int:
        return (COST_PER_MB * max(0, total - local_bytes)) // MB

    # -- arc costs --------------------------------------------------------

    def task_to_unscheduled_agg_cost(self, task_id: int) -> Cost:
        total, _ = self._input_bytes(task_id)
        worst = self._transfer_cost(total, 0)
        waited = self._wait_rounds.get(task_id, 0)
        return worst + 1 + WAIT_COST_PER_ROUND * waited

    def task_to_resource_node_cost(self, task_id: int, resource_id: int) -> Cost:
        total, local = self._input_bytes(task_id)
        return self._transfer_cost(total, local.get(resource_id, 0))

    def task_to_equiv_class_aggregator(self, task_id: int, ec: int) -> Cost:
        if ec != CLUSTER_AGGREGATOR_EC:
            return 0
        total, _ = self._input_bytes(task_id)
        return self._transfer_cost(total, 0)  # worst case: nothing local

    # -- preference enumeration -------------------------------------------

    def get_task_preference_arcs(self, task_id: int) -> List[int]:
        total, local = self._input_bytes(task_id)
        if total == 0:
            return []
        threshold = PREFERENCE_FRACTION * total
        return [m for m, b in local.items() if b > threshold and m in self._machines]

    # -- lifecycle --------------------------------------------------------

    def add_task(self, task_id: int) -> None:
        self._wait_rounds.setdefault(task_id, 0)

    def remove_task(self, task_id: int) -> None:
        self._wait_rounds.pop(task_id, None)

    def remove_machine(self, resource_id: int) -> None:
        super().remove_machine(resource_id)
        self.blocks.drop_machine(resource_id)

    def note_round(self, unscheduled_task_ids) -> None:
        """Bump wait counters after a round; the scheduler calls this with
        the tasks that stayed unscheduled (Quincy's starvation bound)."""
        for t in unscheduled_task_ids:
            if t in self._wait_rounds:
                self._wait_rounds[t] += 1
