"""CoCo: co-location interference cost model.

The reference declares CoCo (costmodel/interface.go:33-43, enum value
COCO=5) and carries its inputs — per-task CoCo classes
(task_desc.proto:25-30: Sheep/Rabbit/Devil/Turtle) and per-machine
`CoCoInterferenceScores` penalties (coco_interference_scores.proto:
11-16) — but never implements the model. This is a from-scratch
implementation of the policy those inputs describe: the cost of placing
a task on a machine is the expected co-location interference, i.e. how
badly the machine's current residents and the incoming task hurt each
other.

Policy:

- Per-class equivalence classes (census.CLASS_ECS) keep arc fan-out at
  O(T + 4·M): task → class-EC → machine.
- EC(c) → machine cost = Σ_k census_k(machine) · W[c, k] + penalty(c,
  machine), where census is the running-class census maintained by the
  stats traversal, W is the 4×4 class-interaction matrix (devils hurt
  everyone; rabbits are sensitive; turtles barely interact — the
  qualitative CoCo taxonomy), and penalty(c, m) is the machine's own
  per-class score from `CoCoInterferenceScores`.
- Costs are clamped to MAX_COST so the unscheduled escape cost can be
  set above the worst placement: a task is left waiting only when every
  machine is full or pathologically noisy.
- Capacity on EC→machine arcs = free slots below, the same rule the
  trivial model uses (trivial_cost_modeler.go:76-83).

The vectorized form used by the array fast path is
`coco_cost_matrix(census, penalties)`: one [4, M] int32 matrix per
round from an [M, 4] census — pure numpy, no per-arc callbacks.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..data import ResourceDescriptor, ResourceTopologyNodeDescriptor
from ..graph.flowgraph import Node
from ..utils import ResourceMap, TaskMap
from .base import Cost, CostModeler
from .census import CLASS_ECS, ClassCensusKeeper, ec_class

# Class-interaction weights W[c, k]: marginal cost of placing a class-c
# task next to one resident class-k task. Order: Sheep, Rabbit, Devil,
# Turtle. Devils (antagonists) hurt everyone and everyone hurts the
# cache-sensitive rabbits; turtles neither give nor take.
INTERFERENCE = np.array(
    [
        # resident:  S   R   D   T
        [2, 1, 8, 0],  # incoming sheep
        [4, 3, 16, 0],  # incoming rabbit
        [8, 12, 10, 1],  # incoming devil
        [0, 0, 1, 0],  # incoming turtle
    ],
    dtype=np.int64,
)

MAX_COST = 2_000  # clamp so unsched cost can dominate
UNSCHEDULED_COST = MAX_COST + 500


def machine_penalty_matrix(rd: ResourceDescriptor) -> np.ndarray:
    """Per-machine additive penalty vector p[c] for incoming class c,
    from the machine's CoCoInterferenceScores."""
    s = rd.coco_interference_scores
    return np.array(
        [s.sheep_penalty, s.rabbit_penalty, s.devil_penalty, s.turtle_penalty],
        dtype=np.int64,
    )


def coco_cost_matrix(census: np.ndarray, penalties: Optional[np.ndarray] = None) -> np.ndarray:
    """Vectorized CoCo costs.

    census: [M, 4] running-class counts per machine.
    penalties: optional [M, 4] per-machine per-incoming-class penalties.
    Returns [4, M] int32 cost of placing each class on each machine.
    """
    cost = INTERFERENCE @ census.T.astype(np.int64)  # [4, M]
    if penalties is not None:
        cost = cost + penalties.T.astype(np.int64)
    return np.minimum(cost, MAX_COST).astype(np.int32)


class CocoCostModel(CostModeler):
    """Interference-aware placement (TPU-rebuild implementation of the
    reference's planned COCO model, costmodel/interface.go:39)."""

    def __init__(
        self,
        resource_map: ResourceMap,
        task_map: TaskMap,
        leaf_resource_ids,
        max_tasks_per_pu: int,
    ) -> None:
        self.resource_map = resource_map
        self.task_map = task_map
        self.leaf_resource_ids = leaf_resource_ids
        self.census = ClassCensusKeeper(resource_map, task_map, max_tasks_per_pu)

    # -- arc costs --------------------------------------------------------

    def task_to_unscheduled_agg_cost(self, task_id: int) -> Cost:
        return UNSCHEDULED_COST

    def unscheduled_agg_to_sink_cost(self, job_id: int) -> Cost:
        return 0

    def task_to_resource_node_cost(self, task_id: int, resource_id: int) -> Cost:
        c = self.census.task_class(task_id)
        return int(self._machine_cost(c, resource_id))

    def resource_node_to_resource_node_cost(
        self, source: Optional[ResourceDescriptor], destination: ResourceDescriptor
    ) -> Cost:
        return 0

    def leaf_resource_node_to_sink_cost(self, resource_id: int) -> Cost:
        return 0

    def task_continuation_cost(self, task_id: int) -> Cost:
        # Continuing in place is free of *new* interference.
        return 0

    def task_preemption_cost(self, task_id: int) -> Cost:
        return MAX_COST // 2

    def task_to_equiv_class_aggregator(self, task_id: int, ec: int) -> Cost:
        return 0

    def equiv_class_to_resource_node(self, ec: int, resource_id: int) -> Tuple[Cost, int]:
        c = ec_class(ec)
        if c is None:
            return 0, 0
        return int(self._machine_cost(c, resource_id)), self.census.free_slots(resource_id)

    def equiv_class_to_equiv_class(self, ec1: int, ec2: int) -> Tuple[Cost, int]:
        return 0, 0

    def _machine_cost(self, task_class: int, resource_id: int) -> int:
        census = self.census.machine_census(resource_id)
        rs = self.resource_map.find(resource_id)
        pen = machine_penalty_matrix(rs.descriptor)[task_class]
        raw = int(INTERFERENCE[task_class] @ census) + int(pen)
        return min(raw, MAX_COST)

    # -- preference enumeration -------------------------------------------

    def get_task_equiv_classes(self, task_id: int) -> List[int]:
        return [CLASS_ECS[self.census.task_class(task_id)]]

    def get_outgoing_equiv_class_pref_arcs(self, ec: int) -> List[int]:
        if ec_class(ec) is None:
            return []
        return list(self.census.machines.keys())

    def get_task_preference_arcs(self, task_id: int) -> List[int]:
        return []

    def get_equiv_class_to_equiv_classes_arcs(self, ec: int) -> List[int]:
        return []

    # -- lifecycle --------------------------------------------------------

    def add_machine(self, rtnd: ResourceTopologyNodeDescriptor) -> None:
        self.census.add_machine(rtnd)

    def add_task(self, task_id: int) -> None:
        pass

    def remove_machine(self, resource_id: int) -> None:
        self.census.remove_machine(resource_id)

    def remove_task(self, task_id: int) -> None:
        pass

    # -- stats traversal --------------------------------------------------

    def gather_stats(self, accumulator: Node, other: Node) -> Node:
        return self.census.gather(accumulator, other)

    def prepare_stats(self, accumulator: Node) -> None:
        self.census.prepare(accumulator)

    def update_stats(self, accumulator: Node, other: Node) -> Node:
        return accumulator
