"""Quincy on the device fast path: interchangeability-group registry.

The host graph path wires Quincy's per-task preference arcs directly
into the flow graph (graph/graph_manager.py; reference:
graph_manager.go:1229-1264 + costmodel/interface.go:105-110
GetTaskPreferenceArcs) and solves CSR — correct, but ~160 us/superstep:
no route to the <10 ms round regime at 10k x 1k. This module is the
TPU-first alternative: tasks with the SAME cost signature — class,
escape cost, and per-machine transfer-cost profile (i.e. the same input
blocks) — are one transport commodity, so per-TASK preference arcs
become per-GROUP preference columns (GroupSpec.pref_w) min'd into the
class cost row, and the whole Quincy policy rides the dense [G, M]
transport kernel (solver/layered.py; scheduler/device_bulk.py group
mode).

Exactness: grouping by full cost signature is the definition of
interchangeability, so the aggregate collapse argument of
solver/layered.py applies row-for-row; the effective per-cell cost
min(EC route, preference arc) is exactly the cheaper of the two
parallel paths a task has in the reference graph.

In Quincy workloads the grouping is massively compressive: tasks
reading the same block(s) share a signature (the map-task pattern), so
G tracks the number of distinct inputs, not the number of tasks. Tasks
whose signature would overflow the static group capacity fall back to
the class's OVERFLOW group — no preferences, priced at the largest
worst-case transfer seen among overflowed signatures, so their
reported cost is conservative (never under the true route cost); the
overflow count is reported so callers can size G_cap properly.

The wait-cost starvation bound (QuincyCostModel.note_round,
WAIT_COST_PER_ROUND) ages at GROUP granularity here: bump_wait raises
the escape cost of groups that still have backlog. Tasks of one group
are admitted and aged together, which preserves the bound's purpose —
eventually waiting costs more than the worst placement.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .quincy import (
    COST_PER_MB,
    MB,
    PREFERENCE_FRACTION,
    WAIT_COST_PER_ROUND,
    BlockRegistry,
)

#: re-exported sentinel (scheduler/device_bulk.py) so callers need one import
from ..scheduler.device_bulk import PREF_NONE  # noqa: F401

#: distinct overflowed signatures tracked exactly before the counter
#: degrades to a per-event upper bound (see QuincyGroupTable)
_OVERFLOW_TRACK_CAP = 1 << 16


def _transfer_cost(total: int, local: int, unit_mb: int = 1) -> int:
    return (COST_PER_MB * max(0, total - local)) // (MB * unit_mb)


class QuincyGroupTable:
    """Host-side registry: task input signature -> transport group.

    Maintains the numpy mirrors of GroupSpec and pushes them to a
    DeviceBulkCluster via ``sync`` (host -> device upload only; the
    round programs take the arrays as traced args, so no recompile).
    """

    def __init__(
        self,
        num_groups: int,
        num_machines: int,
        num_classes: int = 1,
        wait_cost_per_round: int = WAIT_COST_PER_ROUND,
        cost_unit_mb: int = 1,
        sig_unit_mb: Optional[int] = None,
    ) -> None:
        """cost_unit_mb quantizes transfer costs to that many megabytes
        per cost unit (default 1 = the QuincyCostModel scale). Large
        heterogeneous inputs (multi-GB reads) want coarser units: cost
        GAPS measured in units bound the price-war descent depth of the
        solve (a war burns ~gap/eps supersteps), and MB precision on
        GB-scale transfers buys no placement quality. Quantization also
        merges near-identical signatures — deliberate compression.

        sig_unit_mb (default = cost_unit_mb) quantizes the GROUPING KEY
        independently of the stored costs: the two pull opposite ways —
        a coarse signature quantum merges near-identical templates
        (fewer distinct signatures, less overflow, smaller quality
        gap), while a fine cost quantum keeps cross-group cost ties
        rare (exact ties herd the synchronous solve). A merged group
        carries its first-registered template's costs at cost_unit
        resolution — representative of the merged set, the same
        approximation grouping itself makes."""
        if num_groups < 2 * num_classes:
            raise ValueError(
                f"need a fallback and an overflow group per class: "
                f"G={num_groups} < 2*C={2 * num_classes}"
            )
        self.G = int(num_groups)
        self.M = int(num_machines)
        self.C = int(num_classes)
        self.wait_cost_per_round = int(wait_cost_per_round)
        self.cost_unit_mb = int(cost_unit_mb)
        self.sig_unit_mb = int(
            cost_unit_mb if sig_unit_mb is None else sig_unit_mb
        )
        if self.sig_unit_mb < self.cost_unit_mb:
            raise ValueError(
                f"sig_unit_mb ({self.sig_unit_mb}) must be >= cost_unit_mb "
                f"({self.cost_unit_mb}): a finer signature quantum would "
                "split cost-identical templates into distinct groups"
            )
        self.blocks = BlockRegistry()
        # Groups 0..C-1 are the classes' no-input fallback groups;
        # C..2C-1 are the per-class OVERFLOW groups (signatures that
        # arrive after the table fills): no preferences, e/u raised to
        # the largest worst-case transfer among overflowed signatures —
        # a conservative (never-undercharging) price.
        self.cls = np.zeros(self.G, np.int32)
        self.cls[: self.C] = np.arange(self.C)
        self.cls[self.C : 2 * self.C] = np.arange(self.C)
        self.job = np.zeros(self.G, np.int32)
        self.e = np.zeros(self.G, np.int64)
        self.u = np.ones(self.G, np.int64)  # worst(0) + 1
        self.pref_w = np.full((self.G, self.M), PREF_NONE, np.int64)
        self.wait_rounds = np.zeros(self.G, np.int64)
        # note: the class fallback groups (gid < C) are matched by the
        # explicit zero-cost check in group_for, not by this dict — a
        # coarse sig quantum can floor a NONZERO-cost signature to
        # (c, 0, ()), which must not collide with them
        self._sig2gid: Dict[tuple, int] = {}
        self._gid2sig: Dict[int, tuple] = {}
        #: signatures currently memoized to each class's overflow gid
        self._overflow_sigs: Dict[int, set] = {}
        #: signatures that have EVER overflowed — never cleared by
        #: evict_idle, so `overflowed` keeps counting DISTINCT
        #: signatures even when un-pinned memoizations re-overflow.
        #: Bounded: past _OVERFLOW_TRACK_CAP distinct signatures the
        #: set stops growing and the counter increments per overflow
        #: event instead (an upper bound) — a G_cap-sizing signal that
        #: large is already saturated, and exact distinctness forever
        #: would be unbounded history (the thing evict_idle exists to
        #: avoid).
        self._overflowed_ever: set = set()
        self._next = 2 * self.C
        self._free: List[int] = []  # evicted gids, reusable
        #: monotonic use clock + last-use stamp per gid (LRU eviction)
        self._clock = 0
        self._last_use: Dict[int, int] = {}
        self.overflowed = 0  # DISTINCT signatures dropped to the overflow group
        self.evicted = 0  # groups reclaimed by evict_idle

    # -- registration ------------------------------------------------------

    def group_for(
        self,
        task_class: int,
        block_ids: Sequence[int],
        job: int = 0,
    ) -> int:
        """The group for a task of `task_class` reading `block_ids`
        (sizes/locations from the block registry). Registers a new
        group on first sight of a signature; overflows to the class's
        no-preference fallback group when the table is full."""
        total = 0
        local: Dict[int, int] = {}
        for b in block_ids:
            size = self.blocks.size(b)
            total += size
            for m in self.blocks.holders(b):
                local[m] = local.get(m, 0) + size
        worst = _transfer_cost(total, 0, self.cost_unit_mb)
        threshold = PREFERENCE_FRACTION * total
        # one pass emits both the stored costs (cost_unit) and the
        # grouping key's quantized values (sig_unit >= cost_unit merges
        # near-identical templates; stored costs stay fine so
        # cross-group cost ties stay rare)
        prefs: List[Tuple[int, int]] = []
        sig_prefs: List[Tuple[int, int]] = []
        for m, b in sorted(local.items()):
            if b > threshold and 0 <= m < self.M:
                prefs.append((m, _transfer_cost(total, b, self.cost_unit_mb)))
                sig_prefs.append(
                    (m, _transfer_cost(total, b, self.sig_unit_mb))
                )
        # the TRUE (cost-unit) values decide fallback membership: a
        # coarse sig quantum must not collapse a nonzero-cost template
        # onto the zero-cost fallback group
        if not prefs and worst == 0:
            return int(task_class)  # the fallback group IS this signature
        sig = (
            int(task_class),
            _transfer_cost(total, 0, self.sig_unit_mb),
            tuple(sig_prefs),
        )
        self._clock += 1
        gid = self._sig2gid.get(sig)
        if gid is not None:
            self._last_use[gid] = self._clock
            if self.C <= gid < 2 * self.C:
                # overflow rows stay conservative across MERGED
                # templates too: a memoized hit can carry a worst up to
                # one sig quantum above the first registrant's
                self.e[gid] = max(self.e[gid], worst)
                self.u[gid] = self.e[gid] + 1
            return gid
        if self._free:
            gid = self._free.pop()
        elif self._next < self.G:
            gid = self._next
            self._next += 1
        else:
            # table full: land in the class's overflow group, repriced
            # upward to cover the costliest overflowed signature. The
            # signature is memoized to the overflow gid so repeated
            # registrations (task multiplicity) don't inflate the
            # distinct-signatures-dropped counter — and the persistent
            # ever-overflowed set keeps it distinct across evict_idle
            # cycles (which un-pin memoizations).
            if len(self._overflowed_ever) < _OVERFLOW_TRACK_CAP:
                self._overflowed_ever.add(sig)
                self.overflowed = len(self._overflowed_ever)
            elif sig not in self._overflowed_ever:
                self.overflowed += 1  # upper bound past the cap
            gid = self.C + int(task_class)
            self._sig2gid[sig] = gid
            self._overflow_sigs.setdefault(gid, set()).add(sig)
            self.e[gid] = max(self.e[gid], worst)
            self.u[gid] = self.e[gid] + 1
            return gid
        self._sig2gid[sig] = gid
        self._gid2sig[gid] = sig
        self._last_use[gid] = self._clock
        self.cls[gid] = int(task_class)
        self.job[gid] = int(job)
        # Route base: worst-case transfer (nothing local) — the task ->
        # EC arc cost (QuincyCostModel.task_to_equiv_class_aggregator);
        # escape: worst + 1 (+ wait aging) as in
        # QuincyCostModel.task_to_unscheduled_agg_cost.
        self.e[gid] = worst
        self.u[gid] = worst + 1
        self.pref_w[gid, :] = PREF_NONE
        for m, cost in prefs:
            self.pref_w[gid, m] = cost
        return gid

    def groups_for(
        self,
        classes: np.ndarray,
        deps: Sequence[Sequence[int]],
        jobs: Optional[np.ndarray] = None,
    ) -> np.ndarray:
        """Vector form of group_for for an admission batch."""
        out = np.empty(len(deps), np.int32)
        for i, blocks in enumerate(deps):
            out[i] = self.group_for(
                int(classes[i]),
                blocks,
                0 if jobs is None else int(jobs[i]),
            )
        return out

    # -- lifecycle ---------------------------------------------------------

    def evict_idle(
        self, live_per_group: np.ndarray, keep_fraction: float = 0.5
    ) -> int:
        """LRU signature eviction: reclaim registered groups with ZERO
        live tasks, least-recently-used first, until at most
        `keep_fraction` of the dynamic gid range stays occupied (or no
        idle group remains). A long-running cluster's signature table
        would otherwise fill permanently — every evicted gid returns to
        a free pool that group_for reuses BEFORE overflowing, so the
        table tracks the working set instead of history. Reserved
        fallback/overflow gids (< 2C) are never evicted; a group with
        live tasks is never evicted (its row still prices them).

        Call with per-group live counts (from the host mirror of
        admissions/completions, or a fetched state's grp/live arrays)
        at table-maintenance cadence — e.g. between timed chunks;
        follow with sync() to push the cleared rows. Returns the number
        of groups reclaimed."""
        dyn = max(1, self.G - 2 * self.C)
        occupied = len(self._gid2sig)
        target = int(dyn * keep_fraction)
        if occupied <= target:
            return 0
        live = np.asarray(live_per_group)
        idle = [
            gid for gid in self._gid2sig if live[gid] == 0
        ]
        idle.sort(key=lambda g: self._last_use.get(g, 0))
        n_evict = min(len(idle), occupied - target)
        for gid in idle[:n_evict]:
            sig = self._gid2sig.pop(gid)
            self._sig2gid.pop(sig, None)
            self._last_use.pop(gid, None)
            self._free.append(gid)
            self.e[gid] = 0
            self.u[gid] = 1
            self.pref_w[gid, :] = PREF_NONE
            self.wait_rounds[gid] = 0
        self.evicted += n_evict
        # Un-pin overflow memoizations too: once eviction frees room, a
        # signature that first appeared under table pressure must be
        # able to register PROPERLY on next sight — otherwise hot
        # overflowed signatures stay preference-less forever and the
        # table tracks history, not the working set. When an overflow
        # row is also idle, its ratcheted conservative price resets.
        if n_evict:
            for og, sigs in self._overflow_sigs.items():
                for sig in sigs:
                    self._sig2gid.pop(sig, None)
                sigs.clear()
                if live[og] == 0:
                    self.e[og] = 0
                    self.u[og] = 1
        return n_evict

    def drop_machine(self, machine_index: int) -> None:
        """Machine loss: its replicas disappear; existing groups keep
        their (now stale) preference until signatures re-register —
        mirroring the reference, whose preference arcs are pruned on
        the next task update (removeInvalidPrefResArcs,
        graph_manager.go:766-790). We prune eagerly instead: any group
        preferring the machine loses that column."""
        self.blocks.drop_machine(machine_index)
        self.pref_w[:, machine_index] = PREF_NONE

    def bump_wait(self, backlog_per_group: np.ndarray) -> None:
        """Age the escape cost of groups that still have unscheduled
        tasks (the starvation bound, at group granularity). Call with
        the per-group backlog derived from fetched state — outside the
        timed region, at the caller's binding-readback cadence."""
        waited = np.asarray(backlog_per_group) > 0
        self.wait_rounds[waited] += 1
        self.wait_rounds[~waited] = 0

    def effective_u(self) -> np.ndarray:
        return self.u + self.wait_cost_per_round * self.wait_rounds

    # -- device sync -------------------------------------------------------

    def sync(self, cluster) -> None:
        """Push the current table to a DeviceBulkCluster (group mode)."""
        cluster.set_groups(
            cls=self.cls,
            job=self.job,
            e=self.e,
            u=self.effective_u(),
            pref_w=self.pref_w,
        )
