"""Static-analysis suite: the codebase's TPU invariants, machine-checked.

Three levels (docs/static_analysis.md has the full rule catalog):

- Level 1, `ast_rules`: AST lint over the whole tree (driven by
  `tools/kschedlint.py`, gated by `tests/test_static_analysis.py`).
  Catches the invariants that live in *source text* — 64-bit dtypes in
  device-bound modules, dtype-less jnp array creation, `jax.jit` calls
  whose scalar knobs are missing from `static_argnames`, Python
  control flow on traced values, mutable default args, bare excepts,
  raw `print` in library code.
- Level 2, `jaxpr_contracts`: abstract traces (`jax.make_jaxpr` over
  `ShapeDtypeStruct`s — no device, no compile) of every registered
  solver backend, asserting the invariants that live in the *traced
  program* — no 64-bit `convert_element_type` anywhere, the
  megakernel's zero-HBM-gather/zero-scatter budget, jaxpr-hash
  stability across raw sizes sharing a pow2 padding bucket (the
  recompile-hazard detector), and a VMEM estimate from the kernel's
  actual operands cross-checked against the `mega_fits_vmem` gate.
- Level 3, `program_registry` + `engine`: a declarative registry where
  every compiled program in the tree registers once with its full
  contract spec (scatter policy, collective budget, dtype policy,
  donation spec, telemetry-off hash pin, hash-stability class), a
  generic engine enforcing every spec uniformly (including an AOT
  ``.lower().compile()`` donation/aliasing audit — XLA silently copies
  when a donated buffer is unusable), and an unaudited-program sweep
  (`unregistered-program` rule) that fails lint for any
  `jax.jit`/`pallas_call`/`shard_map` call site that is neither
  registered nor waived with a rationale.

The split mirrors what each level can see: the AST rules catch hazards
before a trace exists (and in code that never traces), the jaxpr
contracts catch what only the traced program knows (a float64 sneaking
in through promotion has no grep-able source form), and the registry
makes the per-program contracts declarative data instead of copy-pasted
assertions — so coverage is a checkable property, not a convention.
"""

from .ast_rules import (
    RULES,
    Directive,
    ProgramSite,
    Violation,
    collect_program_sites,
    iter_directives,
    lint_file,
    lint_paths,
    parse_directive,
    program_coverage,
)
from .baseline import fingerprint, load_baseline, split_by_baseline, write_baseline
from .program_registry import (
    PROGRAMS,
    SITE_NAMES,
    CollectiveBudget,
    DonationSpec,
    GatherBudget,
    HashStability,
    ProgramSpec,
    declare_programs,
    donating_programs,
    registered_names,
    specs_for_site,
)

__all__ = [
    "RULES",
    "Directive",
    "ProgramSite",
    "Violation",
    "collect_program_sites",
    "iter_directives",
    "lint_file",
    "lint_paths",
    "parse_directive",
    "program_coverage",
    "fingerprint",
    "load_baseline",
    "split_by_baseline",
    "write_baseline",
    "PROGRAMS",
    "SITE_NAMES",
    "CollectiveBudget",
    "DonationSpec",
    "GatherBudget",
    "HashStability",
    "ProgramSpec",
    "declare_programs",
    "donating_programs",
    "registered_names",
    "specs_for_site",
]
