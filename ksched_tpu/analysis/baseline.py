"""Checked-in lint baseline: only NEW violations fail the gate.

The baseline (tools/kschedlint_baseline.json) records fingerprints of
violations that were reviewed and accepted when the suite landed, so
the gate ratchets: existing debt is visible but non-blocking, anything
new fails CI. The repo's baseline is kept EMPTY — every violation the
suite surfaced was fixed or suppressed inline with a rationale — and
the mechanism exists so a future emergency landing can ratchet instead
of blocking.

Fingerprints are (path, rule, hash of the stripped line text), so
they survive unrelated edits moving a line, but an edit to the
offending line itself re-fires the rule (the right behavior: the line
was re-touched, re-justify it). The baseline is a MULTISET: one entry
waives one occurrence, so copy-pasting a baselined bad line elsewhere
in the same file still fails the gate.
"""

from __future__ import annotations

import hashlib
import json
from collections import Counter
from typing import Dict, Iterable, List, Tuple

from .ast_rules import Violation

_Key = Tuple[str, str, str]


def fingerprint(v: Violation) -> Dict[str, str]:
    digest = hashlib.sha1(
        f"{v.path}:{v.rule}:{v.line_text.strip()}".encode()
    ).hexdigest()[:16]
    return {"path": v.path, "rule": v.rule, "hash": digest}


def _key(entry: Dict[str, str]) -> _Key:
    return (entry["path"], entry["rule"], entry["hash"])


def load_baseline(path: str) -> Counter:
    """Multiset of accepted fingerprints (repeats waive repeats)."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
    except FileNotFoundError:
        return Counter()
    entries = data.get("violations", []) if isinstance(data, dict) else data
    return Counter(_key(e) for e in entries)


def write_baseline(path: str, violations: Iterable[Violation]) -> int:
    # one entry per occurrence (NOT deduplicated): the gate matches
    # entries to occurrences one-for-one
    entries = sorted(tuple(fingerprint(v).items()) for v in violations)
    payload = {
        "comment": "kschedlint ratchet: reviewed pre-existing violations. "
        "Keep empty; see docs/static_analysis.md.",
        "violations": [dict(e) for e in entries],
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return len(payload["violations"])


def split_by_baseline(
    violations: Iterable[Violation], baseline: Counter
) -> Tuple[List[Violation], List[Violation], Counter]:
    """(new, baselined, stale) — stale is the multiset of baseline
    entries no current violation consumed (fixed debt; shed them with
    --write-baseline)."""
    remaining = Counter(baseline)
    new: List[Violation] = []
    old: List[Violation] = []
    for v in violations:
        key = _key(fingerprint(v))
        if remaining[key] > 0:
            remaining[key] -= 1
            old.append(v)
        else:
            new.append(v)
    return new, old, +remaining
