"""Level-3 enforcement engine: one code path checks every registered
program's contract spec (analysis/program_registry.py).

Where Level 2 grew one hand-written test function per program, the
engine interprets `ProgramSpec` fields generically:

- **contracts**: trace the program abstractly (`jax.make_jaxpr` over
  ShapeDtypeStructs — CPU-safe, no compile) at the default call plus
  every extra shape bucket, then assert the 32-bit dtype policy, the
  scatter policy (forbidden / scoped-exempt-and-NON-VACUOUS /
  chaos-only), the gather budget, and the collective budget.
- **hash pin**: the telemetry-off normalized-jaxpr hash equals the
  pinned value byte-for-byte ("disabled telemetry costs zero traced
  ops" can never silently rot).
- **hash stability**: every `same` pair of tracer calls collides,
  every `cross` pair splits — the recompile-hazard detector.
- **telemetry knob**: knob=0 IS the default program, knob=512 is a
  DIFFERENT one that still satisfies dtype/scatter/gather budgets
  (and, for pow2-stable programs, still bucket-collides).
- **donation audit** (the genuinely new analysis): AOT-lower the real
  jitted callable (``.lower().compile()`` on CPU) and assert every
  declared donated input actually aliases an output in the compiled
  executable's ``input_output_alias`` config, with zero XLA
  "donated buffers were not usable" warnings. XLA silently copies
  when donation fails — doubling HBM for the delta/plan/sharded
  scatters — and before this audit nothing would have noticed.

All checks raise :class:`ContractError` (an AssertionError) with the
offending program named, so registry-driven parametrized tests get
readable failures and negative tests can assert the engine flags
seeded violations.

Import cost: this module lazily imports `jaxpr_contracts` (and hence
jax) on first use — the registry itself stays stdlib-only for the
lint CLI.
"""

from __future__ import annotations

import re
import warnings
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from .program_registry import PROGRAMS, ProgramSpec, TraceCall


class ContractError(AssertionError):
    """A registered program violates its declared contract."""


# ---------------------------------------------------------------------------
# tracing (memoized — the parametrized suite revisits default calls)
# ---------------------------------------------------------------------------

_TRACE_CACHE: Dict[Tuple, object] = {}


def _contracts():
    from . import jaxpr_contracts

    return jaxpr_contracts


def resolve_tracer(name: str):
    jc = _contracts()
    fn = getattr(jc, name, None)
    if fn is None or not callable(fn):
        raise ContractError(
            f"tracer {name!r} does not exist in analysis/jaxpr_contracts.py"
        )
    return fn


def trace_call(spec: ProgramSpec, tc: Optional[TraceCall] = None, **overrides):
    """Trace `spec` at `tc` (default: its registered default call),
    with optional kwarg overrides (the telemetry knob)."""
    tc = tc or spec.trace
    key = (spec.tracer, tc, tuple(sorted(overrides.items())))
    closed = _TRACE_CACHE.get(key)
    if closed is None:
        # Hash pins depend on the pretty-printed jaxpr, and the printer
        # hoists sub-jaxprs shared BY OBJECT IDENTITY (`let _where.. =`
        # blocks in pp_toplevel_jaxpr). Whether two call sites share one
        # traced Jaxpr object depends on jax's process-global tracing
        # caches — i.e. on whatever traced earlier in the process, which
        # makes str(jaxpr) order-dependent mid-suite. The pins were
        # derived in fresh processes (empty caches); clearing before
        # each fresh trace reproduces that state exactly, so the
        # normalized string is byte-stable no matter what ran before.
        import jax

        jax.clear_caches()
        kwargs = tc.as_kwargs()
        kwargs.update(overrides)
        closed = resolve_tracer(spec.tracer)(*tc.args, **kwargs)
        _TRACE_CACHE[key] = closed
    return closed


def report(spec: ProgramSpec, tc: Optional[TraceCall] = None, **overrides):
    jc = _contracts()
    closed = trace_call(spec, tc, **overrides)
    return jc.check_jaxpr(spec.name, closed, shape_key=(tc or spec.trace).args)


def program_hash(spec: ProgramSpec, tc: Optional[TraceCall] = None, **overrides) -> str:
    jc = _contracts()
    return jc.jaxpr_hash(trace_call(spec, tc, **overrides))


# ---------------------------------------------------------------------------
# contract checks
# ---------------------------------------------------------------------------


def _fail(spec: ProgramSpec, msg: str):
    raise ContractError(f"program {spec.name!r}: {msg}")


def _check_one(spec: ProgramSpec, tc: TraceCall, exact_collectives: bool = True,
               **overrides):
    jc = _contracts()
    rep = report(spec, tc, **overrides)
    where = f"at {tc.args}{dict(tc.kwargs) or ''}"
    if not rep.ok_64bit:
        _fail(spec, f"64-bit dtypes in traced program {where}: {rep.violations_64bit}")
    if spec.scatter_policy == "forbidden":
        if rep.scatter_eqns:
            _fail(spec, f"scatter primitives {rep.scatter_eqns} {where} but policy "
                        "is 'forbidden' (TPU serializes scatter-adds)")
    else:  # scoped-exempt / chaos-only must actually scatter
        if not rep.scatter_eqns:
            _fail(spec, f"scatter policy {spec.scatter_policy!r} is VACUOUS {where}: "
                        "the program never scatters — drop the exemption")
    g = spec.gathers
    if g is not None:
        got = (rep.hbm_loop_gathers, rep.kernel_gathers, rep.oneshot_gathers)
        for label, want, have in (
            ("hbm_loop", g.hbm_loop, got[0]),
            ("kernel", g.kernel, got[1]),
            ("oneshot", g.oneshot, got[2]),
        ):
            if want is not None and have != want:
                _fail(spec, f"{label} gathers {where}: expected {want}, traced {have}")
        if g.hbm_loop_min is not None and got[0] < g.hbm_loop_min:
            _fail(spec, f"hbm_loop gathers {where}: expected >= {g.hbm_loop_min}, "
                        f"traced {got[0]} — the gather classifier has rotted "
                        "(this program pays per-superstep HBM gathers by design)")
    if spec.collectives is not None:
        _check_collectives(
            spec, trace_call(spec, tc, **overrides), where, exact_collectives
        )


def _check_collectives(spec: ProgramSpec, closed, where: str, exact: bool = True):
    jc = _contracts()
    budget = spec.collectives
    loop = jc.count_collectives(closed, loop_only=True)
    total = jc.count_collectives(closed)
    if exact:  # exact counts pin the TELEMETRY-OFF program only — the
        # soltel counters legitimately add loop psums when enabled
        for prim, want in budget.loop:
            if loop.get(prim, 0) != want:
                _fail(spec, f"loop-body {prim} count {where}: expected {want}, "
                            f"traced {loop.get(prim, 0)} (per-superstep ICI budget)")
        for prim, want in budget.total:
            if total.get(prim, 0) != want:
                _fail(spec, f"total {prim} count {where}: expected {want}, "
                            f"traced {total.get(prim, 0)}")
    for prim in budget.forbidden:
        if total.get(prim, 0):
            _fail(spec, f"forbidden collective {prim} appears {total[prim]}x {where}")


def check_contracts(spec: ProgramSpec):
    """Dtype / scatter / gather / collective contracts at the default
    call and every extra shape bucket."""
    for tc in (spec.trace,) + spec.extra:
        _check_one(spec, tc)


def check_hash_pin(spec: ProgramSpec):
    if spec.telemetry_off_hash is None:
        return
    got = program_hash(spec)
    if got != spec.telemetry_off_hash:
        import os
        if os.environ.get("KSCHED_DEBUG_HASH_DUMP"):
            jc = _contracts()
            with open(f"/tmp/ksched_bad_jaxpr_{spec.name}.txt", "w") as f:
                f.write(jc._normalize_jaxpr_str(str(trace_call(spec))))
        _fail(spec, f"telemetry-off jaxpr hash {got} != pinned "
                    f"{spec.telemetry_off_hash} — the traced program CHANGED. "
                    "If intentional, re-derive and re-pin in program_registry.py")


def check_hash_stability(spec: ProgramSpec):
    hs = spec.hash_stability
    if hs is None or hs.kind == "exempt":
        return
    for a, b in hs.same:
        ha, hb = program_hash(spec, a), program_hash(spec, b)
        if ha != hb:
            _fail(spec, f"{hs.kind} hash split inside one bucket: "
                        f"{a.args}{dict(a.kwargs) or ''}={ha} vs "
                        f"{b.args}{dict(b.kwargs) or ''}={hb} — a raw size "
                        "leaked into the traced program (recompile hazard)")
    for a, b in hs.cross:
        ha, hb = program_hash(spec, a), program_hash(spec, b)
        if ha == hb:
            _fail(spec, f"cross-bucket calls {a.args} and {b.args} collide "
                        f"({ha}) — the stability check is vacuous")


def check_telemetry_knob(spec: ProgramSpec):
    if spec.telemetry_knob is None:
        return
    knob = spec.telemetry_knob
    # knob=0 must BE the default program. The tracers take the knob as
    # a keyword with default 0, so asserting the signature default is
    # equivalent to re-tracing with an explicit 0 — without paying a
    # second full solver trace per program.
    import inspect

    params = inspect.signature(resolve_tracer(spec.tracer)).parameters
    if knob not in params or params[knob].default != 0:
        _fail(spec, f"tracer {spec.tracer!r} does not default {knob}=0 — "
                    "the pinned hash would not be the telemetry-OFF program")
    off = default = program_hash(spec)
    on = program_hash(spec, **{knob: 512})
    if on == off:
        _fail(spec, f"{knob}=512 traces the SAME program as {knob}=0 — "
                    "the telemetry knob is dead")
    # the telemetry-ON program must hold the same structural contracts
    # (forbidden collectives included; exact counts are off-only)
    _check_one(spec, spec.trace, exact_collectives=False, **{knob: 512})
    if spec.collectives is not None and spec.collectives.knob_adds_loop_psum:
        jc = _contracts()
        loop_off = jc.count_collectives(trace_call(spec), loop_only=True)
        loop_on = jc.count_collectives(
            trace_call(spec, **{knob: 512}), loop_only=True
        )
        if loop_on.get("psum", 0) <= loop_off.get("psum", 0):
            _fail(spec, "telemetry-ON trace does not add loop psums (the "
                        "soltel counters ride the superstep reductions)")
    hs = spec.hash_stability
    if hs is not None and hs.kind != "exempt" and hs.same:
        a, b = hs.same[0]
        ha = program_hash(spec, a, **{knob: 512})
        hb = program_hash(spec, b, **{knob: 512})
        if ha != hb:
            _fail(spec, f"telemetry-ON trace splits the {hs.kind} hash "
                        f"({a.args} vs {b.args}) — the knob leaks a raw size")


def check_distinct(spec: ProgramSpec):
    if not spec.distinct_from:
        return
    mine = program_hash(spec)
    for other_name in spec.distinct_from:
        other = PROGRAMS[other_name]
        if mine == program_hash(other):
            _fail(spec, f"default trace collides with {other_name!r} — the "
                        "variant is vacuous (its distinguishing input is dead)")


def check_declared(spec: ProgramSpec):
    """The owning module's `declare_programs` hook names this spec."""
    import importlib

    from .program_registry import DECLARED

    importlib.import_module(spec.module)
    declared = DECLARED.get(spec.module, set())
    if spec.name not in declared:
        _fail(spec, f"owning module {spec.module} does not declare_programs() "
                    f"it (declared: {sorted(declared) or 'nothing'})")


def check_vmem_gate(spec: ProgramSpec):
    """Mega-only: the VMEM estimate counted from the traced
    pallas_call's block mappings must agree with the dispatch gate's
    budget, telemetry off (extra_tiles 0) and on (exactly 1 ring
    tile)."""
    if not spec.vmem_gate:
        return
    jc = _contracts()
    from ..ops.mcmf_pallas import MEGA_LANES

    est = jc.estimate_mega_vmem(trace_call(spec))
    if est.L != MEGA_LANES:
        _fail(spec, f"kernel lane extent {est.L} != MEGA_LANES {MEGA_LANES}")
    if not est.all_operands_on_chip:
        _fail(spec, "mega kernel has an operand outside VMEM/SMEM")
    if est.extra_tiles != 0:
        _fail(spec, f"telemetry-off kernel carries {est.extra_tiles} extra "
                    "VMEM tiles (the ring must be absent when disabled)")
    if not est.gate_is_safe:
        _fail(spec, f"dispatch gate budgets {est.gate_tiles} tiles < "
                    f"counted live set {est.est_tiles}")
    if not est.gate_is_tight:
        _fail(spec, f"dispatch gate {est.gate_tiles} tiles drifted above "
                    f"counted {est.est_tiles} + slack")
    if spec.telemetry_knob:
        est_on = jc.estimate_mega_vmem(
            trace_call(spec, **{spec.telemetry_knob: 512})
        )
        if est_on.extra_tiles != 1:
            _fail(spec, f"telemetry-ON ring occupies {est_on.extra_tiles} "
                        "tile-equivalents, expected exactly 1 (clamped ring)")
        if not est_on.gate_is_safe:
            _fail(spec, "telemetry-ON live set exceeds the gate's +1 budget")


# ---------------------------------------------------------------------------
# the donation/aliasing audit
# ---------------------------------------------------------------------------

#: substring XLA puts in its donation-fallback warning
_UNUSABLE = "donated buffers were not usable"

_ALIAS_BLOCK_RE = re.compile(
    # the alias config nests one brace level: { {out}: (param, {}, kind), ... }
    r"input_output_alias=\{((?:[^{}]|\{[^{}]*\})*)\}"
)
_ALIAS_PARAM_RE = re.compile(r":\s*\((\d+),")


@dataclass
class DonationReport:
    aliased_params: Tuple[int, ...]
    missing: Tuple[int, ...] = ()
    unusable_warnings: Tuple[str, ...] = ()
    header: str = ""

    @property
    def ok(self) -> bool:
        return not self.missing and not self.unusable_warnings


def audit_donation(fn, args: Sequence, donate_argnums: Sequence[int]) -> DonationReport:
    """AOT-lower `fn` (already jitted WITH its donation config) and
    read the compiled executable's ``input_output_alias``: every
    argnum in `donate_argnums` must appear as an aliased parameter.
    For the registered appliers every argument is a flat array, so HLO
    parameter numbers equal positional argnums. Also captures XLA's
    donation-unusable warning — either signal alone means a silent
    full-buffer copy in production."""
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        compiled = fn.lower(*args).compile()
    unusable = tuple(
        str(w.message) for w in caught if _UNUSABLE in str(w.message)
    )
    header = ""
    aliased: List[int] = []
    for line in compiled.as_text().splitlines():
        if line.startswith("HloModule"):
            header = line
            m = _ALIAS_BLOCK_RE.search(line)
            if m:
                aliased = sorted(
                    int(p) for p in _ALIAS_PARAM_RE.findall(m.group(1))
                )
            break
    missing = tuple(a for a in donate_argnums if a not in aliased)
    return DonationReport(
        aliased_params=tuple(aliased),
        missing=missing,
        unusable_warnings=unusable,
        header=header,
    )


def check_donation(spec: ProgramSpec):
    if spec.donation is None:
        return
    jc = _contracts()
    builder = getattr(jc, spec.donation.builder, None)
    if builder is None:
        _fail(spec, f"donation builder {spec.donation.builder!r} missing "
                    "from analysis/jaxpr_contracts.py")
    fn, args = builder()
    rep = audit_donation(fn, args, spec.donation.donate_argnums)
    if rep.unusable_warnings:
        _fail(spec, "XLA reports unusable donated buffers (silent copy in "
                    f"production): {rep.unusable_warnings}")
    if rep.missing:
        _fail(spec, f"donated argnums {rep.missing} are NOT aliased in the "
                    f"compiled executable (aliased: {rep.aliased_params}; "
                    f"header: {rep.header!r}) — XLA fell back to a copy")


# ---------------------------------------------------------------------------
# check registry (drives the parametrized suite)
# ---------------------------------------------------------------------------

CHECKS = {
    "contracts": check_contracts,
    "hash_pin": check_hash_pin,
    "stability": check_hash_stability,
    "telemetry_knob": check_telemetry_knob,
    "distinct": check_distinct,
    "donation": check_donation,
    "vmem_gate": check_vmem_gate,
    "declared": check_declared,
}


def applicable_checks(spec: ProgramSpec) -> Tuple[str, ...]:
    """Which CHECKS are non-trivial for this spec (the suite
    parametrizes over exactly these, so skipped work is visible as
    absent test ids, not silently-passing ones)."""
    names = ["contracts", "declared"]
    if spec.telemetry_off_hash is not None:
        names.append("hash_pin")
    hs = spec.hash_stability
    if hs is not None and hs.kind != "exempt" and (hs.same or hs.cross):
        names.append("stability")
    if spec.telemetry_knob is not None:
        names.append("telemetry_knob")
    if spec.distinct_from:
        names.append("distinct")
    if spec.donation is not None:
        names.append("donation")
    if spec.vmem_gate:
        names.append("vmem_gate")
    return tuple(names)


def run_all(spec: ProgramSpec):
    for name in applicable_checks(spec):
        CHECKS[name](spec)
