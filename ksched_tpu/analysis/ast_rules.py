"""Level-1 lint: AST rules for the repo's TPU invariants.

Each rule is a function `(ctx: FileContext) -> Iterable[Violation]`
registered in `RULES`. Rules are pure AST + comment-token analysis: no
imports of the linted code, so the linter can check files that need a
TPU (or a C++ toolchain) to import.

Suppressions are line-level comments on the line of the flagged node:

    x = np.zeros(n + 1, dtype=np.int64)  # kschedlint: host-only (why)
    y = risky()  # kschedlint: disable=bare-except,raw-print -- why

`host-only` silences only the `dtype64` rule (it is a semantic claim:
this 64-bit value never crosses the jit boundary); `disable=` silences
the named rules. Both forms should carry a rationale — the lint does
not parse it, reviewers do.

Level 3 adds two directive-audit rules and the compiled-program sweep:

- `unregistered-program`: every `jax.jit` / `pl.pallas_call` /
  `shard_map` call site in the library must carry
  `# kschedlint: program=<name>` naming a program registered in
  `program_registry.py`, or a `disable=unregistered-program` waiver
  WITH a `-- rationale`.
- `stale-waiver`: a directive that suppresses nothing (and a
  `program=` annotation attached to no call site) is itself an error —
  waivers can only shrink.
- `bad-waiver`: an unparseable directive, a `disable=` naming an
  unknown rule (the classic typo that silently checks nothing), or an
  `unregistered-program` waiver without a rationale.

Scoping (see docs/static_analysis.md):

- `dtype64` applies to *device-bound* modules: files under the library
  root that import `jax`. Pure-numpy host modules (graph codecs, cost
  models, the CPU reference solver) legitimately compute in int64.
- `raw-print` applies to library modules except CLI entry points
  (`cli.py`, `__main__.py`); tools and benches print by design.
- Everything else applies to every linted file.
"""

from __future__ import annotations

import ast
import io
import tokenize
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .program_registry import SITE_NAMES

#: module names whose import marks a file device-bound for `dtype64`
_JAX_MODULES = ("jax",)

#: attribute / dtype-string names the `dtype64` rule flags
_DTYPE64_NAMES = frozenset({"int64", "float64", "uint64"})

#: jnp constructors that must name their dtype, with the positional
#: index at which the dtype argument may appear instead of `dtype=`
_IMPLICIT_DTYPE_FUNCS = {"zeros": 1, "ones": 1, "empty": 1, "full": 2, "arange": 3}

#: annotations / default types that mark a jit parameter
#: "obviously static" for the `jit-static` rule
_STATIC_ANNOTATIONS = frozenset({"int", "bool", "str"})


@dataclass(frozen=True)
class Violation:
    path: str  # repo-relative, forward slashes
    rule: str
    line: int
    col: int
    message: str
    line_text: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: {self.message}"


@dataclass
class FileContext:
    path: str  # repo-relative
    source: str
    tree: ast.Module
    lines: List[str]
    comments: Dict[int, str]  # line -> comment text (without '#')
    device_bound: bool  # imports jax -> dtype64 applies
    in_library: bool  # under the library package root
    is_cli: bool  # CLI entry point (print allowed)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def suppressed(self, lineno: int, rule: str) -> bool:
        comment = self.comments.get(lineno, "")
        marker = comment.find("kschedlint:")
        if marker < 0:
            return False
        directive = comment[marker + len("kschedlint:"):].strip()
        if directive.startswith("host-only"):
            return rule == "dtype64"
        if directive.startswith("disable="):
            names = directive[len("disable="):].split("--")[0].split("(")[0]
            return rule in {n.strip() for n in names.split(",")}
        return False


def _collect_comments(source: str) -> Dict[int, str]:
    comments: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                comments[tok.start[0]] = tok.string.lstrip("#").strip()
    except (tokenize.TokenError, IndentationError):  # half-written file: lint what parsed
        pass
    return comments


def _imports_jax(tree: ast.Module) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            if any(a.name.split(".")[0] in _JAX_MODULES for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom):
            if node.module and node.module.split(".")[0] in _JAX_MODULES:
                return True
    return False


def _dotted(node: ast.AST) -> str:
    """'jax.jit' for Attribute(Name('jax'), 'jit'); '' when not a plain
    dotted path."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


# ---------------------------------------------------------------------------
# dtype64: no 64-bit dtypes in device-bound code
# ---------------------------------------------------------------------------


def rule_dtype64(ctx: FileContext) -> Iterable[Violation]:
    """TPU v5e has no native int64 (solver/jax_solver.py header): a
    64-bit array reaching a jit boundary either downcasts silently
    (x64 off) or trips slow XLA emulation (x64 on). Host-side prep
    that never crosses the boundary carries `# kschedlint: host-only`
    with a rationale."""
    if not (ctx.in_library and ctx.device_bound):
        return
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Attribute) and node.attr in _DTYPE64_NAMES:
            yield Violation(
                ctx.path, "dtype64", node.lineno, node.col_offset,
                f"64-bit dtype `{_dotted(node) or node.attr}` in a device-bound "
                "module; use int32/float32, or mark the line "
                "`# kschedlint: host-only` with a rationale",
                ctx.line_text(node.lineno),
            )
        elif isinstance(node, ast.Call):
            # dtype="int64" / astype("float64") / np.dtype("int64")
            callee = _dotted(node.func)
            is_astype = isinstance(node.func, ast.Attribute) and node.func.attr == "astype"
            is_dtype_ctor = callee.endswith(".dtype")
            for kw in node.keywords:
                if kw.arg == "dtype" and isinstance(kw.value, ast.Constant) \
                        and kw.value.value in _DTYPE64_NAMES:
                    yield Violation(
                        ctx.path, "dtype64", kw.value.lineno, kw.value.col_offset,
                        f'64-bit dtype string "{kw.value.value}" in a device-bound module',
                        ctx.line_text(kw.value.lineno),
                    )
            if (is_astype or is_dtype_ctor) and node.args:
                a0 = node.args[0]
                if isinstance(a0, ast.Constant) and a0.value in _DTYPE64_NAMES:
                    yield Violation(
                        ctx.path, "dtype64", a0.lineno, a0.col_offset,
                        f'64-bit dtype string "{a0.value}" in a device-bound module',
                        ctx.line_text(a0.lineno),
                    )


# ---------------------------------------------------------------------------
# implicit-dtype: jnp array creation must name its dtype
# ---------------------------------------------------------------------------


def rule_implicit_dtype(ctx: FileContext) -> Iterable[Violation]:
    """`jnp.zeros(n)` materializes float32 (or float64 under x64) where
    the solvers need int32 — every jnp constructor names its dtype, as
    a positional argument or `dtype=`."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not (isinstance(func, ast.Attribute) and func.attr in _IMPLICIT_DTYPE_FUNCS):
            continue
        base = _dotted(func.value)
        if base not in ("jnp", "jax.numpy"):
            continue
        dtype_pos = _IMPLICIT_DTYPE_FUNCS[func.attr]
        has_dtype = any(kw.arg == "dtype" for kw in node.keywords) or (
            len(node.args) > dtype_pos
            and not any(isinstance(a, ast.Starred) for a in node.args)
        )
        if not has_dtype:
            yield Violation(
                ctx.path, "implicit-dtype", node.lineno, node.col_offset,
                f"`{base}.{func.attr}(...)` without an explicit dtype",
                ctx.line_text(node.lineno),
            )


# ---------------------------------------------------------------------------
# jit-static / traced-branch: jit boundary hygiene
# ---------------------------------------------------------------------------


def _jit_decoration(node: ast.AST) -> Optional[Tuple[Set[str], ast.AST]]:
    """When `node` is a jit decorator, return (static_argnames, site).

    Recognized forms: `jax.jit`, `jit`, `jax.jit(...)`,
    `functools.partial(jax.jit, static_argnames=(...))`,
    `partial(jit, ...)`. static_argnums is resolved by the caller
    (needs the parameter list)."""
    target = node
    statics: Set[str] = set()
    if isinstance(node, ast.Call):
        callee = _dotted(node.func)
        if callee in ("functools.partial", "partial"):
            if not node.args or _dotted(node.args[0]) not in ("jax.jit", "jit"):
                return None
        elif callee not in ("jax.jit", "jit"):
            return None
        for kw in node.keywords:
            if kw.arg in ("static_argnames", "static_argnums"):
                vals = kw.value.elts if isinstance(kw.value, (ast.Tuple, ast.List)) \
                    else [kw.value]
                for v in vals:
                    if isinstance(v, ast.Constant):
                        statics.add(v.value)  # str names and int nums mixed
        return statics, target
    if _dotted(node) in ("jax.jit", "jit"):
        return statics, target
    return None


def _params_of(fn: ast.FunctionDef) -> List[ast.arg]:
    return list(fn.args.posonlyargs) + list(fn.args.args) + list(fn.args.kwonlyargs)


def _static_param_names(fn: ast.FunctionDef, statics: Set) -> Set[str]:
    params = _params_of(fn)
    names = {s for s in statics if isinstance(s, str)}
    for s in statics:
        if isinstance(s, int) and 0 <= s < len(params):
            names.add(params[s].arg)
    return names


def _looks_static(param: ast.arg, default: Optional[ast.AST]) -> bool:
    if isinstance(param.annotation, ast.Name) and param.annotation.id in _STATIC_ANNOTATIONS:
        return True
    if isinstance(default, ast.Constant) and isinstance(default.value, (bool, int, str)) \
            and default.value is not None:
        return True
    return False


def _defaults_by_param(fn: ast.FunctionDef) -> Dict[str, ast.AST]:
    out: Dict[str, ast.AST] = {}
    positional = list(fn.args.posonlyargs) + list(fn.args.args)
    for param, default in zip(reversed(positional), reversed(fn.args.defaults)):
        out[param.arg] = default
    for param, default in zip(fn.args.kwonlyargs, fn.args.kw_defaults):
        if default is not None:
            out[param.arg] = default
    return out


def _iter_jitted_functions(tree: ast.Module):
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for deco in node.decorator_list:
            hit = _jit_decoration(deco)
            if hit is not None:
                yield node, _static_param_names(node, hit[0])
                break


def rule_jit_static(ctx: FileContext) -> Iterable[Violation]:
    """A Python-scalar knob (int/bool/str annotation or default) passed
    through `jax.jit` without `static_argnames` becomes a traced 0-d
    array: `if knob:` then either fails or, worse, retraces per value.
    Every obviously-static parameter must be listed."""
    for fn, static_names in _iter_jitted_functions(ctx.tree):
        defaults = _defaults_by_param(fn)
        for param in _params_of(fn):
            if param.arg in static_names or param.arg in ("self", "cls"):
                continue
            if _looks_static(param, defaults.get(param.arg)):
                yield Violation(
                    ctx.path, "jit-static", param.lineno, param.col_offset,
                    f"jitted `{fn.name}` parameter `{param.arg}` looks static "
                    "(scalar annotation/default) but is missing from "
                    "static_argnames — it will be traced, and branching on it "
                    "will fail or silently retrace",
                    ctx.line_text(param.lineno),
                )


class _TracedBranchVisitor(ast.NodeVisitor):
    """Flag `if`/`while` whose test mentions a traced (non-static)
    parameter of the enclosing jitted function. Nested functions that
    rebind a name shadow it (their params are their own scope)."""

    def __init__(self, ctx: FileContext, fn: ast.FunctionDef, traced: Set[str]):
        self.ctx = ctx
        self.fn_name = fn.name
        self.traced = traced
        self.out: List[Violation] = []

    def _visit_scope(self, node, removed: Set[str]):
        saved = self.traced
        self.traced = self.traced - removed
        for child in ast.iter_child_nodes(node):
            self.visit(child)
        self.traced = saved

    def visit_FunctionDef(self, node: ast.FunctionDef):
        self._visit_scope(node, {a.arg for a in _params_of(node)})

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda):
        self._visit_scope(node, {a.arg for a in node.args.args})

    @staticmethod
    def _is_none_check(node) -> bool:
        """`x is None` / `x is not None`: a trace-time static fact (did
        the caller pass None), the standard optional-argument idiom."""
        return (
            isinstance(node, ast.Compare)
            and all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops)
            and all(
                isinstance(c, ast.Constant) and c.value is None
                for c in node.comparators
            )
        )

    def _names_outside_none_checks(self, node, acc: Set[str]):
        if self._is_none_check(node):
            return
        if isinstance(node, ast.Name):
            acc.add(node.id)
        for child in ast.iter_child_nodes(node):
            self._names_outside_none_checks(child, acc)

    def _check_test(self, node):
        referenced: Set[str] = set()
        self._names_outside_none_checks(node.test, referenced)
        names = referenced & self.traced
        if names:
            kind = "if" if isinstance(node, ast.If) else "while"
            self.out.append(Violation(
                self.ctx.path, "traced-branch", node.lineno, node.col_offset,
                f"Python `{kind}` on traced value(s) {sorted(names)} inside "
                f"jitted `{self.fn_name}` — use lax.cond/lax.while_loop or "
                "mark the argument static",
                self.ctx.line_text(node.lineno),
            ))

    def visit_If(self, node: ast.If):
        self._check_test(node)
        self.generic_visit(node)

    def visit_While(self, node: ast.While):
        self._check_test(node)
        self.generic_visit(node)


def rule_traced_branch(ctx: FileContext) -> Iterable[Violation]:
    """Heuristic for the classic jit trap: `if x > 0:` on a traced
    value raises TracerBoolConversionError at best, and at worst (when
    x is a numpy scalar on the first call) silently bakes one branch
    into the compiled program."""
    for fn, static_names in _iter_jitted_functions(ctx.tree):
        traced = {p.arg for p in _params_of(fn)} - static_names - {"self", "cls"}
        visitor = _TracedBranchVisitor(ctx, fn, traced)
        for stmt in fn.body:
            visitor.visit(stmt)
        yield from visitor.out


# ---------------------------------------------------------------------------
# generic Python hygiene
# ---------------------------------------------------------------------------


def rule_mutable_default(ctx: FileContext) -> Iterable[Violation]:
    """A list/dict/set default is evaluated once and shared by every
    call — state leaks across invocations."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        defaults = list(node.args.defaults) + [
            d for d in node.args.kw_defaults if d is not None
        ]
        for default in defaults:
            bad = isinstance(default, (ast.List, ast.Dict, ast.Set)) or (
                isinstance(default, ast.Call)
                and isinstance(default.func, ast.Name)
                and default.func.id in ("list", "dict", "set")
            )
            if bad:
                name = getattr(node, "name", "<lambda>")
                yield Violation(
                    ctx.path, "mutable-default", default.lineno, default.col_offset,
                    f"mutable default argument in `{name}` is shared across calls; "
                    "default to None and materialize inside",
                    ctx.line_text(default.lineno),
                )


def rule_bare_except(ctx: FileContext) -> Iterable[Violation]:
    """`except:` catches KeyboardInterrupt/SystemExit too; name the
    exception types (or `except Exception` at the very least)."""
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            yield Violation(
                ctx.path, "bare-except", node.lineno, node.col_offset,
                "bare `except:` swallows KeyboardInterrupt/SystemExit; name the "
                "exception types",
                ctx.line_text(node.lineno),
            )


def rule_raw_print(ctx: FileContext) -> Iterable[Violation]:
    """Library code reports through `warnings`/logging/return values so
    callers and tests can capture it; `print` is for CLI entry points
    (cli.py, tools/, bench.py)."""
    if not ctx.in_library or ctx.is_cli:
        return
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id == "print":
            yield Violation(
                ctx.path, "raw-print", node.lineno, node.col_offset,
                "raw `print` in library code; use warnings.warn/logging so "
                "callers can capture it",
                ctx.line_text(node.lineno),
            )


# ---------------------------------------------------------------------------
# Level 3: directive parsing, the compiled-program sweep, waiver audits
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Directive:
    """One parsed `# kschedlint: ...` comment."""

    line: int
    kind: str  # "host-only" | "disable" | "program" | "unknown"
    rules: Tuple[str, ...] = ()
    program: str = ""
    has_rationale: bool = False
    text: str = ""


def parse_directive(line: int, comment: str) -> Optional[Directive]:
    marker = comment.find("kschedlint:")
    if marker < 0:
        return None
    text = comment[marker + len("kschedlint:"):].strip()
    if text.startswith("host-only"):
        rest = text[len("host-only"):].strip()
        return Directive(line, "host-only", has_rationale=bool(rest), text=text)
    if text.startswith("disable="):
        body = text[len("disable="):]
        names_part = body.split("--")[0].split("(")[0]
        names = tuple(n.strip() for n in names_part.split(",") if n.strip())
        has_rat = "--" in body and bool(body.split("--", 1)[1].strip())
        return Directive(line, "disable", rules=names, has_rationale=has_rat, text=text)
    if text.startswith("program="):
        body = text[len("program="):]
        name = body.split("--")[0].split("(")[0].strip()
        has_rat = ("--" in body and bool(body.split("--", 1)[1].strip())) or "(" in body
        return Directive(line, "program", program=name, has_rationale=has_rat, text=text)
    return Directive(line, "unknown", text=text)


def iter_directives(ctx: FileContext) -> Iterable[Directive]:
    for line in sorted(ctx.comments):
        d = parse_directive(line, ctx.comments[line])
        if d is not None:
            yield d


@dataclass(frozen=True)
class ProgramSite:
    """One jax.jit / pl.pallas_call / shard_map call site."""

    line: int  # anchor: the line of the jit/pallas_call/shard_map token
    end_line: int  # last line of the call/decorator span
    kind: str  # "jit" | "pallas_call" | "shard_map"
    callee: str
    program: Optional[str] = None  # program= annotation found in the span
    program_line: Optional[int] = None
    waiver_line: Optional[int] = None  # disable=unregistered-program line


def _site_of_call(node: ast.Call) -> Optional[Tuple[str, str, int]]:
    """(kind, callee, anchor_line) when the Call compiles a program."""
    callee = _dotted(node.func)
    last = callee.rsplit(".", 1)[-1]
    if callee in ("functools.partial", "partial"):
        if node.args:
            inner = _dotted(node.args[0])
            if inner.rsplit(".", 1)[-1] == "jit":
                return "jit", inner or "jit", node.args[0].lineno
        return None
    if last == "jit":
        return "jit", callee, node.func.lineno
    if last == "pallas_call":
        return "pallas_call", callee, node.func.lineno
    if "shard_map" in last:  # shard_map / _shard_map / _shard_map_native
        return "shard_map", callee or last, node.func.lineno
    return None


def collect_program_sites(ctx: FileContext) -> List[ProgramSite]:
    """Every compiled-program call site, with any `program=` annotation
    or `disable=unregistered-program` waiver found on the lines the
    call spans (multi-line `functools.partial(jax.jit, ...)` decorators
    carry theirs next to the `jax.jit` argument)."""
    hits: List[Tuple[ast.AST, str, str, int]] = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            found = _site_of_call(node)
            if found is not None:
                hits.append((node, *found))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:  # bare @jax.jit / @jit
                if not isinstance(deco, ast.Call) and _dotted(deco) in ("jax.jit", "jit"):
                    hits.append((deco, "jit", _dotted(deco), deco.lineno))
    sites: List[ProgramSite] = []
    for node, kind, callee, anchor in hits:
        end = getattr(node, "end_lineno", None) or node.lineno
        program = program_line = waiver_line = None
        for ln in range(node.lineno, end + 1):
            comment = ctx.comments.get(ln)
            if not comment:
                continue
            d = parse_directive(ln, comment)
            if d is None:
                continue
            if d.kind == "program" and program is None:
                program, program_line = d.program, ln
            elif d.kind == "disable" and "unregistered-program" in d.rules \
                    and waiver_line is None:
                waiver_line = ln
        sites.append(ProgramSite(anchor, end, kind, callee, program,
                                 program_line, waiver_line))
    sites.sort(key=lambda s: (s.line, s.kind, s.callee))
    return sites


def rule_unregistered_program(ctx: FileContext) -> Iterable[Violation]:
    """The Level-3 coverage ratchet: a compiled program nobody
    registered is a program nobody audits — its donation config,
    scatter policy, and hash stability are all unchecked. Register it
    in analysis/program_registry.py and annotate the site, or waive
    with a rationale."""
    if not ctx.in_library:
        return
    for site in collect_program_sites(ctx):
        if site.program is not None:
            if site.program in SITE_NAMES:
                continue
            yield Violation(
                ctx.path, "unregistered-program", site.program_line, 0,
                f"`program={site.program}` names no registered program — "
                "register it in ksched_tpu/analysis/program_registry.py",
                ctx.line_text(site.program_line),
            )
            continue
        vline = site.waiver_line or site.line
        yield Violation(
            ctx.path, "unregistered-program", vline, 0,
            f"`{site.callee}` compiles an UNREGISTERED program (no contract "
            "audit covers it); register it in analysis/program_registry.py "
            "and annotate `# kschedlint: program=<name>`, or waive with "
            "`# kschedlint: disable=unregistered-program -- rationale`",
            ctx.line_text(vline),
        )


#: the directive-audit rules exclude themselves when re-running the
#: rule set to decide what a directive suppresses
_WAIVER_AUDIT_RULES = ("stale-waiver", "bad-waiver")


def _raw_violations(ctx: FileContext) -> List[Violation]:
    out: List[Violation] = []
    for name, fn in RULES.items():
        if name in _WAIVER_AUDIT_RULES:
            continue
        out.extend(fn(ctx))
    return out


def rule_stale_waiver(ctx: FileContext) -> Iterable[Violation]:
    """A suppression that suppresses nothing is a latent hole: the code
    it excused is gone (or was fixed), and the directive would silently
    excuse the NEXT violation someone introduces on that line. Same for
    a `program=` annotation attached to no call site. Waivers only
    shrink."""
    directives = list(iter_directives(ctx))
    if not directives:
        return
    by_line: Dict[int, Set[str]] = {}
    for v in _raw_violations(ctx):
        by_line.setdefault(v.line, set()).add(v.rule)
    program_lines = {
        s.program_line for s in collect_program_sites(ctx)
        if s.program_line is not None
    }
    for d in directives:
        if d.kind == "host-only":
            if "dtype64" not in by_line.get(d.line, ()):
                yield Violation(
                    ctx.path, "stale-waiver", d.line, 0,
                    "`host-only` waiver suppresses nothing (no dtype64 "
                    "violation on this line) — remove it",
                    ctx.line_text(d.line),
                )
        elif d.kind == "disable":
            known = [r for r in d.rules if r in RULES]
            dead = [r for r in known if r not in by_line.get(d.line, ())]
            if dead:
                yield Violation(
                    ctx.path, "stale-waiver", d.line, 0,
                    f"disable={','.join(dead)} suppresses nothing on this "
                    "line — remove the dead waiver",
                    ctx.line_text(d.line),
                )
        elif d.kind == "program":
            if d.line not in program_lines:
                yield Violation(
                    ctx.path, "stale-waiver", d.line, 0,
                    f"`program={d.program}` annotation is attached to no "
                    "jit/pallas_call/shard_map call site — remove it",
                    ctx.line_text(d.line),
                )


def rule_bad_waiver(ctx: FileContext) -> Iterable[Violation]:
    """A malformed directive checks nothing — the typo'd rule name is
    the classic case (satellite of ISSUE 18: it used to silently
    disable nothing and nobody noticed)."""
    for d in iter_directives(ctx):
        if d.kind == "unknown":
            yield Violation(
                ctx.path, "bad-waiver", d.line, 0,
                f"unrecognized kschedlint directive `{d.text}` (expected "
                "host-only, disable=<rules> -- rationale, or program=<name>)",
                ctx.line_text(d.line),
            )
        elif d.kind == "disable":
            unknown = [r for r in d.rules if r not in RULES]
            if not d.rules:
                yield Violation(
                    ctx.path, "bad-waiver", d.line, 0,
                    "disable= names no rules", ctx.line_text(d.line),
                )
            if unknown:
                yield Violation(
                    ctx.path, "bad-waiver", d.line, 0,
                    f"disable= names unknown rule(s) {unknown} — a typo here "
                    "would silently check nothing",
                    ctx.line_text(d.line),
                )
            if "unregistered-program" in d.rules and not d.has_rationale:
                yield Violation(
                    ctx.path, "bad-waiver", d.line, 0,
                    "an unregistered-program waiver must carry a "
                    "`-- rationale` (why is this program exempt from the "
                    "registry audit?)",
                    ctx.line_text(d.line),
                )
        elif d.kind == "program" and not d.program:
            yield Violation(
                ctx.path, "bad-waiver", d.line, 0,
                "program= names nothing", ctx.line_text(d.line),
            )


RULES: Dict[str, Callable[[FileContext], Iterable[Violation]]] = {
    "dtype64": rule_dtype64,
    "implicit-dtype": rule_implicit_dtype,
    "jit-static": rule_jit_static,
    "traced-branch": rule_traced_branch,
    "mutable-default": rule_mutable_default,
    "bare-except": rule_bare_except,
    "raw-print": rule_raw_print,
    "unregistered-program": rule_unregistered_program,
    "stale-waiver": rule_stale_waiver,
    "bad-waiver": rule_bad_waiver,
}

#: package whose modules count as "library" for dtype64/raw-print
LIBRARY_ROOT = "ksched_tpu"

#: library files that are CLI entry points (print allowed)
_CLI_BASENAMES = ("cli.py", "__main__.py")


def build_context(path: str, source: str) -> FileContext:
    tree = ast.parse(source, filename=path)
    norm = path.replace("\\", "/")
    in_library = norm.startswith(LIBRARY_ROOT + "/") or norm == LIBRARY_ROOT
    return FileContext(
        path=norm,
        source=source,
        tree=tree,
        lines=source.splitlines(),
        comments=_collect_comments(source),
        device_bound=_imports_jax(tree),
        in_library=in_library,
        is_cli=norm.rsplit("/", 1)[-1] in _CLI_BASENAMES,
    )


def lint_source(path: str, source: str, rules: Optional[Sequence[str]] = None) -> List[Violation]:
    """Lint one file's source; returns unsuppressed violations, sorted.

    An unparsable file is reported as a single `syntax-error` violation
    (a clean diagnostic that fails the gate) rather than a traceback."""
    try:
        ctx = build_context(path, source)
    except SyntaxError as e:
        return [Violation(
            path.replace("\\", "/"), "syntax-error",
            e.lineno or 1, (e.offset or 1) - 1,
            f"file does not parse: {e.msg}",
            (e.text or "").rstrip("\n"),
        )]
    selected = RULES if rules is None else {r: RULES[r] for r in rules}
    out: List[Violation] = []
    for rule_fn in selected.values():
        for v in rule_fn(ctx):
            if not ctx.suppressed(v.line, v.rule):
                out.append(v)
    out.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return out


def lint_file(
    path: str, repo_root: str = ".", rules: Optional[Sequence[str]] = None
) -> List[Violation]:
    import os

    abs_path = path if os.path.isabs(path) else os.path.join(repo_root, path)
    with open(abs_path, "r", encoding="utf-8") as fh:
        source = fh.read()
    rel = os.path.relpath(abs_path, repo_root)
    return lint_source(rel, source, rules=rules)


def iter_py_files(paths: Sequence[str], repo_root: str = "."):
    """Expand files/directories into .py paths (repo-relative)."""
    import os

    for p in paths:
        abs_p = p if os.path.isabs(p) else os.path.join(repo_root, p)
        if os.path.isfile(abs_p):
            yield os.path.relpath(abs_p, repo_root)
            continue
        for dirpath, dirnames, filenames in os.walk(abs_p):
            dirnames[:] = [
                d for d in dirnames if d not in ("__pycache__", ".git", ".pytest_cache")
            ]
            for fname in sorted(filenames):
                if fname.endswith(".py"):
                    yield os.path.relpath(os.path.join(dirpath, fname), repo_root)


def program_coverage(paths: Sequence[str], repo_root: str = ".") -> Dict[str, object]:
    """The Level-3 coverage report over library files in `paths`:
    every jit/pallas_call/shard_map call site bucketed into annotated
    (carries a `program=` naming a registered program), waived
    (`disable=unregistered-program`), or unaudited — plus the reverse
    cross-check: registered site names annotated at NO call site
    (a registry entry auditing a program that is never compiled from
    the swept tree is itself a coverage hole)."""
    annotated: List[Dict[str, object]] = []
    waived: List[Dict[str, object]] = []
    unaudited: List[Dict[str, object]] = []
    seen_programs: Set[str] = set()
    for rel in iter_py_files(paths, repo_root):
        import os

        with open(os.path.join(repo_root, rel), "r", encoding="utf-8") as fh:
            source = fh.read()
        try:
            ctx = build_context(rel, source)
        except SyntaxError:
            continue
        if not ctx.in_library:
            continue
        for site in collect_program_sites(ctx):
            entry = {
                "path": ctx.path, "line": site.line, "kind": site.kind,
                "callee": site.callee,
            }
            if site.program is not None and site.program in SITE_NAMES:
                entry["program"] = site.program
                annotated.append(entry)
                seen_programs.add(site.program)
            elif site.waiver_line is not None:
                waived.append(entry)
            else:
                if site.program is not None:
                    entry["program"] = site.program  # names no registered spec
                unaudited.append(entry)
    unannotated = sorted(SITE_NAMES - seen_programs)
    return {
        "annotated": annotated,
        "waived": waived,
        "unaudited": unaudited,
        "unannotated_registered": unannotated,
        "sites": len(annotated) + len(waived) + len(unaudited),
    }


def lint_paths(
    paths: Sequence[str], repo_root: str = ".", rules: Optional[Sequence[str]] = None
) -> List[Violation]:
    out: List[Violation] = []
    for rel in iter_py_files(paths, repo_root):
        out.extend(lint_file(rel, repo_root, rules=rules))
    out.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return out
