"""Level-3 registry: every compiled program in the tree, declared once.

Levels 1/2 (ast_rules, jaxpr_contracts) grew ~30 hand-written test
functions asserting per-program invariants — scoped scatter exemptions,
ICI psum budgets, donated in-place buffers, pow2-bucket hash pins —
with nothing proving the NEXT jit'd program gets audited at all. Level
3 closes that hole with two pieces:

- **This registry**: a declarative table (`PROGRAMS`) where every
  compiled program registers once — name, abstract tracer (a factory
  in `jaxpr_contracts`), shape-bucket calls, and a contract spec
  (scatter policy, collective budget, 32-bit dtype policy, donation
  spec, telemetry-off hash pin, hash-stability class). A generic
  engine (`analysis/engine.py`) enforces every spec uniformly via
  `jax.make_jaxpr` and AOT ``.lower().compile()`` — one code path, no
  copy-pasted per-program assertions.
- **The sweep** (`ast_rules.rule_unregistered_program`, surfaced by
  ``tools/kschedlint.py --coverage``): every `jax.jit` /
  `pl.pallas_call` / `shard_map` call site under `ksched_tpu/` must
  carry ``# kschedlint: program=<registered-name>`` or an inline
  waiver with a rationale — program coverage is a ratchet, not an
  honor system.

This module is import-light on purpose (stdlib only — NO jax, NO
numpy): the lint CLI reads the registry in environments without the
jax_graft toolchain. Tracers are named by string and resolved lazily
by the engine.

Program-owning modules confirm ownership with a one-line hook::

    from ..analysis.program_registry import declare_programs
    declare_programs(__name__, "delta_apply", "warm_flow", "scale_cost")

`declare_programs` validates names eagerly (a typo fails at import
time), and the engine cross-checks that every spec's owning module
really declares it — so the registry, the source annotations, and the
modules can never drift apart silently.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Mapping, Optional, Set, Tuple

# ---------------------------------------------------------------------------
# spec vocabulary
# ---------------------------------------------------------------------------

#: scatter policies a program may declare (docs/static_analysis.md):
#: - "forbidden": zero scatter-family primitives anywhere (every solve
#:   and audit program — TPU serializes scatter-adds).
#: - "scoped-exempt": the program MUST scatter (a vacuous exemption is
#:   an error): O(churn)-sized once-per-round maintenance outside any
#:   solve. Exactly the delta/plan/sharded/replicated appliers.
#: - "chaos-only": allowed to scatter, never dispatched in production
#:   (the corruption-injection poison used to prove the fingerprint
#:   audit catches bit flips).
SCATTER_POLICIES = ("forbidden", "scoped-exempt", "chaos-only")

#: hash-stability classes:
#: - "pow2-bucket": raw sizes sharing a pow2 padding bucket trace
#:   byte-identical jaxprs (the recompile-hazard detector).
#: - "record-bucket": same, over pow2-padded delta-record counts.
#: - "shard-bucket": same, per (bucket, shard count) — each mesh size
#:   is its own executable.
#: - "exempt": traced shapes depend on graph structure (degree
#:   buckets / per-shard maxima); the recompile unit is the plan
#:   rebuild, which the plan tests cover. `reason` is mandatory.
HASH_STABILITY_KINDS = ("pow2-bucket", "record-bucket", "shard-bucket", "exempt")


@dataclass(frozen=True)
class TraceCall:
    """One concrete invocation of a spec's tracer: (args, kwargs)."""

    args: Tuple = ()
    kwargs: Tuple = ()  # sorted (key, value) pairs — hashable

    def as_kwargs(self) -> Dict:
        return dict(self.kwargs)


def call(*args, **kwargs) -> TraceCall:
    return TraceCall(args=tuple(args), kwargs=tuple(sorted(kwargs.items())))


@dataclass(frozen=True)
class HashStability:
    """Which tracer calls must (and must not) collide."""

    kind: str
    #: pairs of TraceCalls that MUST trace byte-identical jaxprs
    same: Tuple[Tuple[TraceCall, TraceCall], ...] = ()
    #: pairs that MUST differ (keeps the stability check non-vacuous)
    cross: Tuple[Tuple[TraceCall, TraceCall], ...] = ()
    reason: str = ""  # mandatory when kind == "exempt"

    def __post_init__(self):
        if self.kind not in HASH_STABILITY_KINDS:
            raise ValueError(f"unknown hash-stability kind {self.kind!r}")
        if self.kind == "exempt" and not self.reason:
            raise ValueError("exempt hash stability requires a reason")


@dataclass(frozen=True)
class DonationSpec:
    """Declared in-place buffers, audited on the COMPILED executable.

    XLA silently falls back to a copy when a donated input cannot
    alias an output (dtype/shape/layout mismatch) — doubling HBM for
    the delta/plan/sharded scatters with no error anywhere. The engine
    AOT-lowers `builder`'s callable (``.lower().compile()`` on CPU)
    and asserts every argnum in `donate_argnums` appears in the
    executable's ``input_output_alias`` config, with zero
    donation-unusable warnings."""

    donate_argnums: Tuple[int, ...]
    #: name of a ``jaxpr_contracts`` function returning
    #: ``(jitted_callable, abstract_args)`` for AOT lowering
    builder: str


@dataclass(frozen=True)
class CollectiveBudget:
    """The ICI traffic contract of a (sharded) program.

    `loop` pins exact per-superstep counts (eqns inside while/scan
    bodies); `total` pins exact whole-program counts; `forbidden`
    names primitive families that must not appear anywhere. Counts are
    occurrences in the traced program (a loop body counts once)."""

    loop: Tuple[Tuple[str, int], ...] = ()
    total: Tuple[Tuple[str, int], ...] = ()
    forbidden: Tuple[str, ...] = ()
    #: telemetry-ON variant must add loop psums (the soltel counters)
    knob_adds_loop_psum: bool = False


@dataclass(frozen=True)
class GatherBudget:
    """HBM gather-traffic contract (None = unchecked)."""

    hbm_loop: Optional[int] = None  # exact gathers in loop bodies, off-kernel
    kernel: Optional[int] = None  # exact gathers inside pallas_call bodies
    oneshot: Optional[int] = None  # exact per-solve (outside loops) gathers
    hbm_loop_min: Optional[int] = None  # lower bound (classifier canary)


@dataclass(frozen=True)
class ProgramSpec:
    """One registered compiled program and its full contract."""

    name: str
    module: str  # dotted module owning the jit/pallas/shard_map site
    kind: str  # "solve" | "maintenance" | "audit" | "chaos"
    tracer: str  # factory name in analysis/jaxpr_contracts
    trace: TraceCall = field(default_factory=TraceCall)
    #: extra shape buckets the dtype/scatter/gather checks also sweep
    extra: Tuple[TraceCall, ...] = ()
    scatter_policy: str = "forbidden"
    dtype_policy: str = "int32"  # the only policy: no 64-bit anywhere
    collectives: Optional[CollectiveBudget] = None
    donation: Optional[DonationSpec] = None
    #: pinned normalized jaxpr hash of the DEFAULT (telemetry-off)
    #: trace — "disabled telemetry costs zero traced ops", held
    #: byte-identically across PRs (re-pin only with a jax upgrade)
    telemetry_off_hash: Optional[str] = None
    #: tracer kwarg enabling solver telemetry; the engine asserts
    #: knob=512 traces a DIFFERENT program and knob=0 the default one
    telemetry_knob: Optional[str] = None
    hash_stability: Optional[HashStability] = None
    gathers: Optional[GatherBudget] = None
    #: names of other registered programs whose default trace must
    #: hash differently (variant non-vacuity)
    distinct_from: Tuple[str, ...] = ()
    #: run the mega VMEM-estimate-vs-dispatch-gate cross-check
    vmem_gate: bool = False
    #: annotation name used at the call site (several variant specs
    #: share one physical jit site); defaults to `name`
    site: Optional[str] = None
    notes: str = ""

    def __post_init__(self):
        if self.scatter_policy not in SCATTER_POLICIES:
            raise ValueError(f"{self.name}: bad scatter policy {self.scatter_policy!r}")
        if self.dtype_policy != "int32":
            raise ValueError(f"{self.name}: bad dtype policy {self.dtype_policy!r}")

    @property
    def site_name(self) -> str:
        return self.site or self.name


# ---------------------------------------------------------------------------
# the registry
# ---------------------------------------------------------------------------

#: the three representative shape buckets the contract sweeps trace
#: (mirrored from the historical SHAPE_BUCKETS of the Level-2 suite)
_BUCKETS = ((12, 40), (20, 100), (40, 220))

#: pow2-bucket pairs per tracer family (same bucket -> same jaxpr)
_CSR_SAME = (
    (call(12, 40), call(15, 60)),
    (call(20, 100), call(30, 70)),
    (call(40, 220), call(60, 200)),
)
_CSR_CROSS = ((call(12, 40), call(12, 200)),)
_MEGA_CROSS = ((call(12, 40), call(12, 2000)),)
_LAYERED_SAME = (
    (call(4, 40), call(4, 100)),
    (call(4, 130), call(4, 250)),
    (call(8, 300), call(8, 370)),
)
_LAYERED_CROSS = ((call(4, 40), call(4, 300)),)
_RECORD_SAME = ((call(3, 2), call(7, 5)),)
_RECORD_CROSS = ((call(3, 2), call(100, 2)),)
_RECORD_GRAPH_SAME = ((call(3, 2, n_raw=20, m_raw=100), call(3, 2, n_raw=24, m_raw=110)),)
_RECORD_GRAPH_CROSS = ((call(3, 2, n_raw=20, m_raw=100), call(3, 2, n_raw=20, m_raw=300)),)

#: every collective family jaxpr_contracts counts — "forbid all"
_ALL_COLLECTIVES = ("psum", "pmin", "pmax", "all_gather", "all_to_all", "ppermute")

_SPECS = (
    # -- solver programs (solver/select.py rungs + variants) ------------
    ProgramSpec(
        name="csr_solve", module="ksched_tpu.solver.jax_solver", kind="solve",
        tracer="trace_jax", trace=call(20, 100),
        extra=(call(12, 40), call(40, 220)),
        telemetry_off_hash="92aa144400bd8869", telemetry_knob="telemetry_cap",
        hash_stability=HashStability("pow2-bucket", same=_CSR_SAME, cross=_CSR_CROSS),
        gathers=GatherBudget(hbm_loop_min=1),
        collectives=CollectiveBudget(forbidden=_ALL_COLLECTIVES),
        notes="scan-CSR push-relabel; hbm_loop_min=1 is the gather-"
        "classifier canary (CSR pays per-superstep HBM gathers by design)",
    ),
    ProgramSpec(
        name="csr_solve_warmp", module="ksched_tpu.solver.jax_solver", kind="solve",
        tracer="trace_jax_warmp", trace=call(20, 100), site="csr_solve",
        hash_stability=HashStability(
            "pow2-bucket", same=((call(20, 100), call(24, 110)),),
            cross=((call(20, 100), call(20, 300)),),
        ),
        distinct_from=("csr_solve",),
        notes="dirty-frontier warm-price refit; the DEFAULT trace staying "
        "on the pre-warm_p pin is csr_solve's telemetry_off_hash",
    ),
    ProgramSpec(
        name="csr_solve_slot", module="ksched_tpu.solver.jax_solver", kind="solve",
        tracer="trace_jax_slot_stable", trace=call(20, 100), site="csr_solve",
        hash_stability=HashStability(
            "pow2-bucket", same=((call(20, 100), call(24, 110)),),
            cross=((call(20, 100), call(20, 300)),),
        ),
        distinct_from=("csr_solve",),
        notes="slot-stable layout: dead rows masked through the sign column",
    ),
    ProgramSpec(
        name="csr_refit_slot", module="ksched_tpu.solver.jax_solver", kind="solve",
        tracer="trace_jax_warmp", trace=call(20, 100, slot_stable=True),
        site="csr_solve", distinct_from=("csr_solve_warmp",),
        notes="the production event-path program: refit ON TOP of the "
        "slot-stable plan",
    ),
    ProgramSpec(
        name="stacked_solve", module="ksched_tpu.solver.jax_solver", kind="solve",
        tracer="trace_stacked", trace=call(4, 20, 100),
        telemetry_knob="telemetry_cap",
        hash_stability=HashStability(
            "pow2-bucket",
            same=((call(3, 20, 100), call(4, 24, 110)),),
            cross=(
                (call(3, 20, 100), call(8, 20, 100)),  # lane bucket
                (call(3, 20, 100), call(4, 20, 300)),  # shape bucket
            ),
        ),
        collectives=CollectiveBudget(forbidden=_ALL_COLLECTIVES),
        notes="multi-tenant jit(vmap) batched solve; lane-count AND shape "
        "bucket stable (tenant churn must not recompile)",
    ),
    ProgramSpec(
        name="stacked_solve_warmp", module="ksched_tpu.solver.jax_solver",
        kind="solve", tracer="trace_stacked",
        trace=call(4, 20, 100, use_warm_p=True), site="stacked_solve",
        distinct_from=("stacked_solve",),
        notes="lane-batched dirty-frontier refit (the warm seed is a real invar)",
    ),
    ProgramSpec(
        name="ell_solve", module="ksched_tpu.solver.ell_solver", kind="solve",
        tracer="trace_ell", trace=call(20, 100),
        extra=(call(12, 40), call(40, 220)),
        telemetry_off_hash="9e101ad7b1bac615", telemetry_knob="telemetry_cap",
        hash_stability=HashStability(
            "exempt",
            reason="entry-table shapes depend on degree buckets; the "
            "recompile unit is the ELL plan rebuild (tests/test_ell_solver.py)",
        ),
        collectives=CollectiveBudget(forbidden=_ALL_COLLECTIVES),
    ),
    ProgramSpec(
        name="mega_solve", module="ksched_tpu.ops.mcmf_pallas", kind="solve",
        tracer="trace_mega", trace=call(20, 100),
        extra=(call(12, 40), call(40, 220)),
        telemetry_off_hash="2713247f0ce0fa0b", telemetry_knob="telemetry_cap",
        hash_stability=HashStability("pow2-bucket", same=_CSR_SAME, cross=_MEGA_CROSS),
        gathers=GatherBudget(hbm_loop=0, kernel=6),
        collectives=CollectiveBudget(forbidden=_ALL_COLLECTIVES),
        vmem_gate=True,
        notes="single-pallas_call megakernel; kernel=6 pins the partner-"
        "permutation reads, hbm_loop=0 locks the zero-HBM-gather claim",
    ),
    ProgramSpec(
        name="layered_solve", module="ksched_tpu.solver.layered", kind="solve",
        tracer="trace_layered", trace=call(20, 100),
        extra=(call(12, 40), call(40, 220)),
        telemetry_off_hash="efaf297e81829bd2", telemetry_knob="telemetry_cap",
        hash_stability=HashStability(
            "pow2-bucket", same=_LAYERED_SAME, cross=_LAYERED_CROSS
        ),
        collectives=CollectiveBudget(forbidden=_ALL_COLLECTIVES),
    ),
    ProgramSpec(
        name="sharded_solve", module="ksched_tpu.parallel.sharded_solver",
        kind="solve", tracer="trace_sharded", trace=call(20, 100),
        extra=(call(12, 40), call(40, 220)),
        telemetry_off_hash="b2c5ad0884934f47", telemetry_knob="telemetry_cap",
        hash_stability=HashStability(
            "exempt",
            reason="legacy ShardedPlan shapes depend on per-shard maxima; "
            "the recompile unit is build_sharded_plan (superseded by "
            "sharded_slot_solve on the event path)",
        ),
        notes="hash pin is mesh-size-dependent (conftest's 8-device "
        "virtual CPU mesh)",
    ),
    ProgramSpec(
        name="sharded_slot_solve", module="ksched_tpu.parallel.sharded_solver",
        kind="solve", tracer="trace_sharded_slot",
        trace=call(20, 100, num_devices=2), telemetry_knob="telemetry_cap",
        hash_stability=HashStability(
            "shard-bucket",
            same=tuple(
                (call(20, 100, num_devices=d), call(24, 110, num_devices=d))
                for d in (2, 4, 8)
            ),
            cross=(
                (call(20, 100, num_devices=2), call(20, 100, num_devices=4)),
                (call(20, 100, num_devices=4), call(20, 100, num_devices=8)),
                (call(20, 100, num_devices=2), call(20, 100, num_devices=8)),
            ),
        ),
        collectives=CollectiveBudget(
            loop=(("psum", 3), ("pmin", 1), ("pmax", 2)),
            forbidden=("all_gather", "all_to_all", "ppermute"),
            knob_adds_loop_psum=True,
        ),
        notes="exactly 3 vector psums cross ICI per superstep (the [N] "
        "excess, [M] arc-delta, [N] potential combines); pmin = tighten "
        "sweep, pmax = sat_full's fwd/bwd phase-boundary combines",
    ),
    ProgramSpec(
        name="sharded_slot_solve_warmp",
        module="ksched_tpu.parallel.sharded_solver", kind="solve",
        tracer="trace_sharded_slot",
        trace=call(20, 100, num_devices=2, use_warm_p=True),
        site="sharded_slot_solve", distinct_from=("sharded_slot_solve",),
    ),
    # -- maintenance programs (the scoped scatter exemptions) -----------
    ProgramSpec(
        name="delta_apply", module="ksched_tpu.graph.device_export",
        kind="maintenance", tracer="trace_delta_apply", trace=call(5, 3),
        scatter_policy="scoped-exempt",
        donation=DonationSpec(donate_argnums=(0, 3, 4), builder="aot_delta_apply"),
        hash_stability=HashStability(
            "record-bucket",
            same=_RECORD_SAME + _RECORD_GRAPH_SAME,
            cross=_RECORD_CROSS + _RECORD_GRAPH_CROSS,
        ),
        collectives=CollectiveBudget(forbidden=_ALL_COLLECTIVES),
        notes="O(churn) once-per-round problem-delta scatter; excess/cap/"
        "cost donated in place (measured 498 -> 8.7 us/apply at 256k rows)",
    ),
    ProgramSpec(
        name="plan_apply", module="ksched_tpu.graph.slot_plan",
        kind="maintenance", tracer="trace_plan_apply", trace=call(5, 3),
        scatter_policy="scoped-exempt",
        donation=DonationSpec(
            donate_argnums=tuple(range(10)), builder="aot_plan_apply"
        ),
        hash_stability=HashStability(
            "record-bucket",
            same=_RECORD_SAME + _RECORD_GRAPH_SAME,
            cross=_RECORD_CROSS + _RECORD_GRAPH_CROSS,
        ),
        collectives=CollectiveBudget(forbidden=_ALL_COLLECTIVES),
        notes="slot-stable plan-row + boundary-static apply; all ten plan "
        "tensors donated",
    ),
    ProgramSpec(
        name="sharded_plan_apply", module="ksched_tpu.parallel.sharded_solver",
        kind="maintenance", tracer="trace_sharded_plan_apply", trace=call(5, 3),
        scatter_policy="scoped-exempt",
        donation=DonationSpec(
            donate_argnums=(0, 1, 2, 3, 4, 5), builder="aot_sharded_plan_apply"
        ),
        hash_stability=HashStability(
            "record-bucket", same=_RECORD_SAME, cross=_RECORD_CROSS
        ),
        collectives=CollectiveBudget(forbidden=_ALL_COLLECTIVES),
        notes="per-shard routed plan scatter; zero collectives (routing "
        "happened on host), six entry tensors donated",
    ),
    ProgramSpec(
        name="replicated_plan_apply",
        module="ksched_tpu.parallel.sharded_solver", kind="maintenance",
        tracer="trace_replicated_plan_apply", trace=call(5, 3),
        scatter_policy="scoped-exempt",
        donation=DonationSpec(
            donate_argnums=(0, 1, 2, 3), builder="aot_replicated_plan_apply"
        ),
        hash_stability=HashStability(
            "record-bucket", same=_RECORD_SAME, cross=_RECORD_CROSS
        ),
        collectives=CollectiveBudget(forbidden=_ALL_COLLECTIVES),
        notes="the replicated remainder of a sharded plan sync (inv-order "
        "+ node boundaries). Shipped UNAUDITED in r15 — the registry "
        "sweep is what surfaced it; the fourth (and last) scoped "
        "scatter exemption",
    ),
    ProgramSpec(
        name="warm_flow", module="ksched_tpu.graph.device_export",
        kind="maintenance", tracer="trace_warm_flow",
        gathers=GatherBudget(hbm_loop=0, kernel=0, oneshot=0),
        hash_stability=HashStability(
            "pow2-bucket", same=((call(20, 100), call(24, 110)),),
            cross=((call(20, 100), call(20, 300)),),
        ),
        collectives=CollectiveBudget(forbidden=_ALL_COLLECTIVES),
        notes="pure elementwise warm-flow carry: scatter- AND gather-free",
    ),
    ProgramSpec(
        name="scale_cost", module="ksched_tpu.graph.device_export",
        kind="maintenance", tracer="trace_scale_cost",
        hash_stability=HashStability(
            "pow2-bucket", same=((call(20, 100), call(24, 110)),),
            cross=((call(20, 100), call(20, 300)),),
        ),
        collectives=CollectiveBudget(forbidden=_ALL_COLLECTIVES),
        notes="cost pre-scaling (cost * n) before a device solve",
    ),
    # -- audit programs (integrity fingerprints — normal round cadence,
    #    so NO scatter exemption) ---------------------------------------
    ProgramSpec(
        name="state_fingerprint", module="ksched_tpu.runtime.integrity",
        kind="audit", tracer="trace_state_fingerprint",
        hash_stability=HashStability(
            "pow2-bucket", same=((call(20, 100), call(24, 110)),),
            cross=((call(20, 100), call(20, 300)),),
        ),
        collectives=CollectiveBudget(forbidden=_ALL_COLLECTIVES),
    ),
    ProgramSpec(
        name="plan_fingerprint", module="ksched_tpu.runtime.integrity",
        kind="audit", tracer="trace_plan_fingerprint",
        hash_stability=HashStability(
            "pow2-bucket", same=((call(20, 100), call(24, 110)),),
            cross=((call(20, 100), call(20, 300)),),
        ),
        collectives=CollectiveBudget(forbidden=_ALL_COLLECTIVES),
    ),
    ProgramSpec(
        name="buffer_fingerprint", module="ksched_tpu.runtime.integrity",
        kind="audit", tracer="trace_buffer_fingerprint",
        hash_stability=HashStability(
            "pow2-bucket", same=((call(20, 100), call(24, 110)),),
            cross=((call(20, 100), call(20, 300)),),
        ),
        collectives=CollectiveBudget(forbidden=_ALL_COLLECTIVES),
        notes="single-buffer checksum (the warm-flow audit's _FP_ONE)",
    ),
    ProgramSpec(
        name="sharded_plan_fingerprint",
        module="ksched_tpu.parallel.sharded_solver", kind="audit",
        tracer="trace_sharded_plan_fingerprint", trace=call(),
        collectives=CollectiveBudget(
            total=(("psum", 6),),
            forbidden=("pmin", "pmax", "all_gather", "all_to_all", "ppermute"),
        ),
        notes="per-shard partials psum'd to one comparable checksum — "
        "exactly 6 psums (the entry-shaped tensors), nothing else",
    ),
    # -- chaos programs --------------------------------------------------
    ProgramSpec(
        name="corrupt_flip", module="ksched_tpu.runtime.integrity",
        kind="chaos", tracer="trace_corrupt_flip",
        scatter_policy="chaos-only",
        notes="the seeded poison scatter: flips one bit of one element "
        "to prove the fingerprint audit detects it; never dispatched in "
        "production",
    ),
)

PROGRAMS: Dict[str, ProgramSpec] = {s.name: s for s in _SPECS}
if len(PROGRAMS) != len(_SPECS):  # duplicate name = table bug
    raise RuntimeError("duplicate program name in registry")

#: annotation names valid at call sites (variant specs share a site)
SITE_NAMES: frozenset = frozenset(s.site_name for s in _SPECS)


def registered_names() -> frozenset:
    return frozenset(PROGRAMS)


def donating_programs() -> Tuple[ProgramSpec, ...]:
    return tuple(s for s in _SPECS if s.donation is not None)


def specs_for_site(site_name: str) -> Tuple[ProgramSpec, ...]:
    return tuple(s for s in _SPECS if s.site_name == site_name)


# ---------------------------------------------------------------------------
# ownership declarations
# ---------------------------------------------------------------------------

#: module -> names it declared (owners and consumers both appear here)
DECLARED: Dict[str, Set[str]] = {}


def declare_programs(module: str, *names: str) -> None:
    """Registration hook for program-owning (and consuming) modules.

    Validates eagerly: an unregistered name raises at the owning
    module's import — a typo can never silently declare nothing."""
    unknown = [n for n in names if n not in PROGRAMS]
    if unknown:
        raise ValueError(
            f"{module} declares unregistered program(s) {unknown}; "
            "register them in ksched_tpu/analysis/program_registry.py"
        )
    DECLARED.setdefault(module, set()).update(names)
