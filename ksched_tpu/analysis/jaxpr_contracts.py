"""Level-2 contracts: invariants checked on the TRACED program.

Every registered solver backend (solver/select.py: jax, ell, mega,
layered, plus parallel/sharded_*) is traced abstractly with
`jax.make_jaxpr` over `ShapeDtypeStruct`s — no device arrays, no
compile, CPU-safe — and the resulting jaxpr is walked recursively
(pjit / while / cond / scan / pallas_call sub-jaxprs included) to
assert:

- **no-64bit**: no `convert_element_type` (or iota/constant aval) with
  a 64-bit dtype anywhere. "Everything is int32" (solver/jax_solver.py
  header: TPU v5e has no native int64) holds in the traced program,
  not just in the source text the AST lint sees.
- **no-scatter**: zero scatter-family primitives in any backend's
  solve. TPU serializes scatter-adds (~68 ms for a 64k segment_sum,
  jax_solver.py header); every segment reduction must stay in
  cumsum/gather/associative-scan form. Exactly THREE programs hold
  scoped exemptions, all O(churn)-sized once-per-round maintenance
  scatters that run OUTSIDE every solve: the device-resident problem
  delta apply (graph/device_export.delta_apply_fn, pinned by
  `trace_delta_apply`), the slot-stable plan-row apply
  (graph/slot_plan.plan_apply_fn, pinned by `trace_plan_apply`), and
  the per-shard routed sharded plan apply (parallel/sharded_solver.
  sharded_plan_apply_fn, pinned by `trace_sharded_plan_apply`). Each
  pin asserts the exemption is real (the program actually scatters),
  stays 32-bit, and hashes stably within a pow2 record bucket; every
  solver program stays at zero — including the slot-stable solve
  variant (`trace_jax_slot_stable`), the dirty-frontier warm-price
  refit (`trace_jax_warmp`), and the slot-stable SHARDED solve
  (`trace_sharded_slot`, additionally hash-stable per shard-count
  bucket at 2/4/8 devices).
- **mega gather budget** (locking in the megakernel's zero-HBM-gather
  claim, ops/mcmf_pallas.py): inside the mega `pallas_call` body every
  operand is VMEM/SMEM-resident by BlockSpec construction, the only
  gathers are the pinned partner-permutation reads, and OUTSIDE the
  kernel no gather sits inside a loop body — so per-superstep HBM
  gather traffic is exactly zero; the one-shot entry materialization
  runs once per solve.
- **pow2-bucket stability** (recompile-hazard detector): two raw
  problem sizes sharing a pow2 padding bucket must produce
  byte-identical jaxprs — if a raw size leaks into a static argument
  or a host-derived shape, the hash splits and the gate names the
  recompile before a production cluster discovers it as a per-round
  compile stall.
- **VMEM estimate**: the megakernel's live set, counted from the
  actual `pallas_call` block mappings, must agree with the
  `_MEGA_LIVE_TILES` constant behind `mega_fits_vmem` — the dispatch
  gate can never drift from the kernel it guards.

The ELL and sharded backends build entry tables whose SHAPES depend on
graph structure (degree buckets / per-shard maxima), not only on
(n, m); they get the dtype/scatter contracts via plans built from a
deterministic generator graph, and are exempt from the bucket-hash
contract (their recompile unit is the plan rebuild, which existing
tests cover). See docs/static_analysis.md.
"""

from __future__ import annotations

import functools
import hashlib
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

#: the backend names this suite traces, mirroring solver/select.py
#: ("native" is C++, "ref" is pure numpy, "auto" composes the others)
REGISTERED_BACKENDS = ("jax", "ell", "mega", "layered", "sharded")

#: backends whose traced shapes are a function of the padded (n, m)
#: alone — the pow2-bucket hash contract applies to exactly these
HASH_STABLE_BACKENDS = ("jax", "mega", "layered")

_64BIT = frozenset({"int64", "uint64", "float64", "complex128"})

#: gathers inside the mega kernel body: one per `perm()` site in the
#: traced program (tighten body, post-tighten saturate, and the phase
#: loop's saturate + superstep rc/delta/relabel reads). All read the
#: VMEM-resident partner tables. A changed count means the kernel's
#: data-movement structure changed — re-derive, re-measure, re-pin.
MEGA_KERNEL_PERM_GATHERS = 6

#: VMEM tiles the kernel holds live beyond its I/O operands (loop
#: state flow/potential + excess/residual/admissibility temporaries +
#: the segmented-scan value/flag pair), matching the accounting that
#: sized _MEGA_LIVE_TILES in ops/mcmf_pallas.py
MEGA_SCAN_TEMP_TILES = 8

#: slack allowed between the counted estimate and the gate constant
#: before the contract demands the gate be re-derived
MEGA_VMEM_GATE_SLACK_TILES = 4


# ---------------------------------------------------------------------------
# jaxpr walking
# ---------------------------------------------------------------------------


def _sub_jaxprs(eqn) -> Iterable:
    for val in eqn.params.values():
        for sub in val if isinstance(val, (list, tuple)) else [val]:
            core = getattr(sub, "jaxpr", sub)
            if hasattr(core, "eqns"):
                yield core


def walk_eqns(jaxpr, in_pallas: bool = False, in_loop: bool = False):
    """Yield (eqn, in_pallas, in_loop) over the whole nested jaxpr.
    `in_loop` marks bodies whose eqns run per loop iteration (while /
    scan); `in_pallas` marks the kernel body, where every operand is
    on-chip by BlockSpec construction."""
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        yield eqn, in_pallas, in_loop
        child_pallas = in_pallas or name == "pallas_call"
        child_loop = in_loop or name in ("while", "scan")
        for sub in _sub_jaxprs(eqn):
            yield from walk_eqns(sub, child_pallas, child_loop)


def _aval_dtypes(eqn) -> Iterable[str]:
    for var in list(eqn.invars) + list(eqn.outvars):
        aval = getattr(var, "aval", None)
        dtype = getattr(aval, "dtype", None)
        if dtype is not None:
            yield str(dtype)


# ---------------------------------------------------------------------------
# contracts
# ---------------------------------------------------------------------------


@dataclass
class ContractReport:
    backend: str
    shape_key: Tuple
    num_eqns: int
    violations_64bit: List[str]
    scatter_eqns: List[str]
    hbm_loop_gathers: int  # gathers outside pallas_call, inside loop bodies
    kernel_gathers: int  # gathers inside a pallas_call body (VMEM reads)
    oneshot_gathers: int  # gathers outside any loop (per-solve, not per-step)
    jaxpr_hash: str

    @property
    def ok_64bit(self) -> bool:
        return not self.violations_64bit

    @property
    def ok_scatter(self) -> bool:
        return not self.scatter_eqns


_SRC_INFO_RE = None


def _normalize_jaxpr_str(text: str) -> str:
    """Strip trace metadata that varies without the PROGRAM changing:
    `origin='...'` operand labels and `... at /path/file.py:NN` source
    infos (pallas embeds both in its jaxpr params — a comment edit
    above a kernel would otherwise split the hash)."""
    global _SRC_INFO_RE
    if _SRC_INFO_RE is None:
        import re

        _SRC_INFO_RE = (
            re.compile(r"origin='[^']*'"),
            re.compile(r" at [^\s,)]+\.py:\d+"),
        )
    for pat in _SRC_INFO_RE:
        text = pat.sub("", text)
    return text


def jaxpr_hash(closed) -> str:
    return hashlib.sha256(
        _normalize_jaxpr_str(str(closed)).encode()
    ).hexdigest()[:16]


def check_jaxpr(backend: str, closed, shape_key: Tuple = ()) -> ContractReport:
    violations_64bit: List[str] = []
    scatter_eqns: List[str] = []
    hbm_loop = kernel = oneshot = 0
    num_eqns = 0
    for eqn, in_pallas, in_loop in walk_eqns(closed.jaxpr):
        num_eqns += 1
        name = eqn.primitive.name
        if name == "convert_element_type":
            new = str(eqn.params.get("new_dtype"))
            if new in _64BIT:
                violations_64bit.append(f"convert_element_type -> {new}")
        for dtype in _aval_dtypes(eqn):
            if dtype in _64BIT:
                violations_64bit.append(f"{name}: {dtype} aval")
        if name.startswith("scatter"):
            scatter_eqns.append(name)
        elif name == "gather":
            if in_pallas:
                kernel += 1
            elif in_loop:
                hbm_loop += 1
            else:
                oneshot += 1
    return ContractReport(
        backend=backend,
        shape_key=shape_key,
        num_eqns=num_eqns,
        violations_64bit=violations_64bit,
        scatter_eqns=scatter_eqns,
        hbm_loop_gathers=hbm_loop,
        kernel_gathers=kernel,
        oneshot_gathers=oneshot,
        jaxpr_hash=jaxpr_hash(closed),
    )


@dataclass
class MegaVmemEstimate:
    R: int
    L: int
    io_tiles: int  # VMEM [R, L] operands (inputs + outputs) of the kernel
    smem_operands: int
    io_bytes: int
    est_tiles: int  # io_tiles + MEGA_SCAN_TEMP_TILES + extra_tiles
    est_bytes: int
    gate_tiles: int  # _MEGA_LIVE_TILES, what mega_fits_vmem budgets with
    all_operands_on_chip: bool  # no ANY/HBM-spec'd kernel operands
    #: tile-equivalents of VMEM operands that are NOT [R, L] entry
    #: tiles (the solver-telemetry ring), rounded up — with telemetry
    #: on this is exactly 1 (the ring is clamped to one tile)
    extra_tiles: int = 0

    @property
    def gate_is_safe(self) -> bool:
        """The gate budgets at least the kernel's real live set (the
        telemetry ring's +1 tile is charged by
        mega_fits_vmem(telemetry=True), mirrored here)."""
        return self.gate_tiles + (1 if self.extra_tiles else 0) >= self.est_tiles

    @property
    def gate_is_tight(self) -> bool:
        """...and not so conservatively that it has clearly drifted."""
        return self.gate_tiles <= self.est_tiles + MEGA_VMEM_GATE_SLACK_TILES


def find_pallas_calls(closed) -> List:
    return [e for e, _, _ in walk_eqns(closed.jaxpr) if e.primitive.name == "pallas_call"]


def estimate_mega_vmem(closed) -> MegaVmemEstimate:
    from ..ops.mcmf_pallas import _MEGA_LIVE_TILES

    calls = find_pallas_calls(closed)
    assert len(calls) == 1, f"expected exactly one pallas_call, found {len(calls)}"
    grid_mapping = calls[0].params["grid_mapping"]
    vmem_shapes = []
    smem = 0
    on_chip = True
    for bm in grid_mapping.block_mappings:
        space = str(getattr(bm, "block_aval", "")).lower()
        if "vmem" in space:
            vmem_shapes.append(tuple(bm.block_shape))
        elif "smem" in space:
            smem += 1
        else:
            on_chip = False
    assert vmem_shapes, "mega kernel has no VMEM operands?"
    # the [R, L] entry tiling is the DOMINANT 2-D shape; any other VMEM
    # operand (the clamped solver-telemetry ring) is charged in
    # tile-equivalents, rounded up — mega_telemetry_cap bounds the ring
    # to one tile, so extra_tiles is 0 (telemetry off) or 1 (on)
    from collections import Counter as _Counter

    shape_counts = _Counter(s for s in vmem_shapes if len(s) == 2)
    (R, L), _n = shape_counts.most_common(1)[0]
    tile_bytes = int(R) * int(L) * 4
    io_tiles = 0
    extra_bytes = 0
    for s in vmem_shapes:
        if tuple(s) == (R, L):
            io_tiles += 1
        else:
            extra_bytes += int(np.prod(s)) * 4
    extra_tiles = -(-extra_bytes // tile_bytes) if extra_bytes else 0
    est_tiles = io_tiles + MEGA_SCAN_TEMP_TILES + extra_tiles
    return MegaVmemEstimate(
        R=int(R), L=int(L),
        io_tiles=io_tiles,
        smem_operands=smem,
        io_bytes=io_tiles * tile_bytes,
        est_tiles=est_tiles,
        est_bytes=est_tiles * tile_bytes,
        gate_tiles=_MEGA_LIVE_TILES,
        all_operands_on_chip=on_chip,
        extra_tiles=extra_tiles,
    )


# ---------------------------------------------------------------------------
# per-backend abstract tracing
# ---------------------------------------------------------------------------


def bucketed_sizes(n_raw: int, m_raw: int) -> Tuple[int, int]:
    """(Np, Mp): the padded extents DeviceGraphState hands every
    solver (graph/device_export.py full_build) — the pow2 bucket."""
    from ..utils import next_pow2

    return max(next_pow2(n_raw), 16), max(next_pow2(m_raw), 16)


def _sds(shape, dtype=jnp.int32):
    return jax.ShapeDtypeStruct(shape, dtype)


def _generator_graph(n: int, m: int, seed: int = 0):
    """Deterministic connected-ish multigraph with skewed degrees (so
    the ELL plan exercises both the small and hub buckets)."""
    rng = np.random.default_rng(seed)
    src = np.where(
        np.arange(m) % 3 == 0, 0, rng.integers(0, n, m)
    ).astype(np.int32)
    dst = ((src + 1 + rng.integers(0, n - 1, m)) % n).astype(np.int32)
    return src, dst


def trace_jax(n_raw: int, m_raw: int, seed: int = 0, telemetry_cap: int = 0):
    from ..solver.jax_solver import _solve_mcmf

    n, m = bucketed_sizes(n_raw, m_raw)
    fn = functools.partial(
        _solve_mcmf, alpha=8, max_supersteps=4096, tighten_sweeps=32,
        telemetry_cap=telemetry_cap,
    )
    e = 2 * m
    return jax.make_jaxpr(fn)(
        _sds((m,)), _sds((m,)), _sds((n,)), _sds((m,)), _sds(()),
        _sds((e,)), _sds((e,)), _sds((e,)), _sds((e,)), _sds((e,)),
        _sds((e,), jnp.bool_), _sds((e,)),
        _sds((n,)), _sds((n,)), _sds((n,), jnp.bool_),
    )


def trace_ell(n_raw: int, m_raw: int, seed: int = 0, telemetry_cap: int = 0):
    from ..solver.ell_solver import _solve_mcmf_ell, build_ell_plan, _plan_args

    n, m = bucketed_sizes(n_raw, m_raw)
    src, dst = _generator_graph(n, m, seed)
    plan_args = build_ell_plan(src, dst, n)
    fn = functools.partial(
        _solve_mcmf_ell, alpha=8, max_supersteps=4096, tighten_sweeps=32,
        telemetry_cap=telemetry_cap,
    )
    plan_sds = tuple(_sds(np.shape(x), np.asarray(x).dtype) for x in _plan_args(plan_args))
    return jax.make_jaxpr(fn)(
        _sds((m,)), _sds((m,)), _sds((n,)), _sds((m,)), _sds(()),
        *plan_sds,
    )


def trace_mega(n_raw: int, m_raw: int, seed: int = 0, telemetry_cap: int = 0):
    from ..ops.mcmf_pallas import MEGA_LANES, mcmf_loop_pallas, mega_entry_rows
    from ..utils import next_pow2

    n, m = bucketed_sizes(n_raw, m_raw)
    # mirrors MegaSolver's host prep: cap/cost/flow0/fwd_pos padded by
    # _pad_pow2 (floor 256), entry tables tiled [R, MEGA_LANES]
    mp = max(256, next_pow2(m))
    npad = max(256, next_pow2(n))
    R = mega_entry_rows(2 * m)
    L = MEGA_LANES
    e = R * L
    fn = functools.partial(
        mcmf_loop_pallas, R=R, L=L, alpha=8, max_supersteps=4096,
        tighten_sweeps=32, interpret=False, telemetry_cap=telemetry_cap,
    )
    return jax.make_jaxpr(fn)(
        _sds((mp,)), _sds((mp,)), _sds((npad,)), _sds((mp,)), _sds(()),
        _sds((e,)), _sds((e,)), _sds((e,)), _sds((e,)), _sds((e,)),
        _sds((e,)), _sds((e,)), _sds((mp,)),
    )


def trace_layered(n_raw: int, m_raw: int, seed: int = 0, telemetry_cap: int = 0):
    """(n_raw, m_raw) doubles as (num_classes, num_machines): the
    layered backend's problem geometry."""
    from ..solver.layered import _solve_transport, pad_geometry

    C = max(1, n_raw)
    Mp, _n_scale = pad_geometry(m_raw, C)
    fn = functools.partial(
        _solve_transport, alpha=8, max_supersteps=4096, refine_waves=0,
        telemetry_cap=telemetry_cap,
    )
    return jax.make_jaxpr(fn)(
        _sds((C, Mp)), _sds((C,)), _sds((Mp,)), _sds(()), _sds((Mp,))
    )


def trace_sharded(n_raw: int, m_raw: int, seed: int = 0, telemetry_cap: int = 0):
    from jax.sharding import Mesh

    from ..parallel.sharded_solver import build_sharded_plan, make_sharded_solver

    n, m = bucketed_sizes(n_raw, m_raw)
    src, dst = _generator_graph(n, m, seed)
    devices = np.array(jax.devices())
    mesh = Mesh(devices, ("x",))
    plan = build_sharded_plan(src, dst, n, len(devices))
    fn = make_sharded_solver(
        mesh, "x", alpha=8, max_supersteps=4096, telemetry_cap=telemetry_cap
    )
    plan_sds = tuple(
        _sds(np.shape(x), np.asarray(x).dtype)
        for x in (
            plan.s_arc, plan.s_sign, plan.s_src, plan.s_dst,
            plan.s_segstart, plan.s_isstart, plan.s_valid,
            plan.node_first, plan.node_last, plan.node_nonempty,
            plan.owned, plan.pos_fwd, plan.pos_bwd,
        )
    )
    return jax.make_jaxpr(fn)(
        _sds((m,)), _sds((m,)), _sds((n,)), _sds((m,)), _sds(()), _sds(()),
        *plan_sds,
    )


def _mesh_of(num_devices: int):
    from jax.sharding import Mesh

    devices = jax.devices()
    assert len(devices) >= num_devices, (
        f"need {num_devices} devices for the sharded contracts "
        "(conftest forces an 8-device virtual CPU mesh)"
    )
    return Mesh(np.array(devices[:num_devices]), ("x",))


def trace_sharded_slot(
    n_raw: int,
    m_raw: int,
    num_devices: int = 2,
    telemetry_cap: int = 0,
    use_warm_p: bool = False,
):
    """Abstract trace of the slot-stable SHARDED solve
    (parallel/sharded_solver.make_sharded_slot_solver): entry tensors
    stacked [D, Es] with Es the pow2 per-shard block extent — a
    function of the (m-bucket, shard count) alone, never the raw size,
    which is what the shard-count-bucket hash pins assert."""
    from ..parallel.sharded_solver import (
        make_sharded_slot_solver,
        sharded_entry_extent,
    )

    n, m = bucketed_sizes(n_raw, m_raw)
    D = num_devices
    es = sharded_entry_extent(m, D)
    mesh = _mesh_of(D)
    fn = make_sharded_slot_solver(
        mesh, "x", alpha=8, max_supersteps=4096,
        telemetry_cap=telemetry_cap, use_warm_p=use_warm_p,
    )
    args = [
        _sds((m,)), _sds((m,)), _sds((n,)), _sds((m,)), _sds(()), _sds(()),
        _sds((D, es)), _sds((D, es)), _sds((D, es)), _sds((D, es)),
        _sds((D, es)), _sds((D, es), jnp.bool_),
        _sds((2 * m,)), _sds((n,)), _sds((n,)), _sds((n,), jnp.bool_),
    ]
    if use_warm_p:
        args.append(_sds((n,)))
    return jax.make_jaxpr(fn)(*args)


def trace_sharded_plan_apply(
    kp_raw: int, ks_raw: int, num_devices: int = 2,
    n_raw: int = 20, m_raw: int = 100,
):
    """Abstract trace of the THIRD scatter-exempt program: the
    per-shard routed plan-row + segment-static apply
    (parallel/sharded_solver.sharded_plan_apply_fn) over pow2-bucketed
    per-shard record counts."""
    from ..graph.device_export import pad_record_count
    from ..graph.slot_plan import PLAN_RECORD_COLS, SEG_RECORD_COLS
    from ..parallel.sharded_solver import (
        sharded_entry_extent,
        sharded_plan_apply_fn,
    )

    _n, m = bucketed_sizes(n_raw, m_raw)
    D = num_devices
    es = sharded_entry_extent(m, D)
    kp = pad_record_count(kp_raw)
    ks = pad_record_count(ks_raw)
    fn = sharded_plan_apply_fn(_mesh_of(D), "x")
    return jax.make_jaxpr(fn)(
        _sds((D, es)), _sds((D, es)), _sds((D, es)), _sds((D, es)),
        _sds((D, es)), _sds((D, es), jnp.bool_),
        _sds((D, kp, PLAN_RECORD_COLS)), _sds((D, ks, SEG_RECORD_COLS)),
    )


def trace_sharded_plan_fingerprint(num_devices: int = 2, n_raw: int = 20, m_raw: int = 100):
    """Abstract trace of the sharded plan fingerprint (per-shard
    global-weight partials psum'd to one comparable checksum) — an
    audit program on the normal round cadence, so NO scatter
    exemption."""
    from ..parallel.sharded_solver import (
        sharded_entry_extent,
        sharded_plan_fingerprint_fn,
    )

    n, m = bucketed_sizes(n_raw, m_raw)
    D = num_devices
    es = sharded_entry_extent(m, D)
    fn = sharded_plan_fingerprint_fn(_mesh_of(D), "x")
    return jax.make_jaxpr(fn)(
        _sds((D, es)), _sds((D, es)), _sds((D, es)), _sds((D, es)),
        _sds((2 * m,)), _sds((D, es)), _sds((D, es), jnp.bool_),
        _sds((n,)), _sds((n,)), _sds((n,), jnp.bool_),
    )


#: collective primitive families counted by count_collectives — the
#: ICI traffic classes of a sharded program
_COLLECTIVE_PRIMS = ("psum", "pmin", "pmax", "all_gather", "all_to_all", "ppermute")


def count_collectives(closed, loop_only: bool = False) -> Dict[str, int]:
    """Occurrences of each collective primitive in the traced program
    (loop bodies count ONCE — multiply by superstep counts for traffic
    totals). With ``loop_only`` only eqns inside while/scan bodies
    count — the per-superstep ICI reduction budget of a sharded solve
    (prologue/one-shot collectives excluded). The bench's
    ICI-reduction assertions read both views."""
    counts: Dict[str, int] = {}
    for eqn, _p, in_loop in walk_eqns(closed.jaxpr):
        if loop_only and not in_loop:
            continue
        name = eqn.primitive.name
        for prim in _COLLECTIVE_PRIMS:
            if name == prim or name.startswith(prim + "_"):
                counts[prim] = counts.get(prim, 0) + 1
    return counts


def count_superstep_collectives(closed) -> Dict[str, int]:
    """Loop-body-only view of :func:`count_collectives`."""
    return count_collectives(closed, loop_only=True)


def trace_jax_warmp(n_raw: int, m_raw: int, seed: int = 0, telemetry_cap: int = 0,
                    slot_stable: bool = False):
    """The warm-potentials variant of the CSR solve — since the
    dirty-frontier refit landed, use_warm_p=True SEEDS the tightening
    Bellman sweep with the previous round's device-resident prices
    (clipped), so the relaxation touches only the journal-dirty
    frontier. The refit is plain data-parallel relaxation: it must
    stay scatter-free like every solve program. A distinct traced
    program — the default (warm_p=None, use_warm_p=False) trace stays
    byte-identical to the pinned pre-warm_p baseline, which
    test_static_analysis pins."""
    from ..solver.jax_solver import _solve_mcmf

    n, m = bucketed_sizes(n_raw, m_raw)
    fn = functools.partial(
        _solve_mcmf, alpha=8, max_supersteps=4096, tighten_sweeps=32,
        telemetry_cap=telemetry_cap, use_warm_p=True,
        slot_stable=slot_stable,
    )
    e = 2 * m
    return jax.make_jaxpr(fn)(
        _sds((m,)), _sds((m,)), _sds((n,)), _sds((m,)), _sds(()),
        _sds((e,)), _sds((e,)), _sds((e,)), _sds((e,)), _sds((e,)),
        _sds((e,), jnp.bool_), _sds((e,)),
        _sds((n,)), _sds((n,)), _sds((n,), jnp.bool_),
        _sds((n,)),  # warm_p
    )


def slot_stable_entry_cap(m_pad: int) -> int:
    """The entry-table extent the slot-stable layout pads to for an
    m_pad-arc bucket in the common case (graph/slot_plan.SlotPlanState
    ._rebuild: max(2*m_cap, next_pow2(need)) — need exceeds 2*m_cap
    only when per-node slack rows outgrow the doubled entries, which
    next_pow2 then absorbs; either way a pow2 of the bucket, never the
    raw size)."""
    return 2 * m_pad


def trace_jax_slot_stable(n_raw: int, m_raw: int, seed: int = 0,
                          telemetry_cap: int = 0):
    """The slot-stable variant of the CSR solve: entry rows live in
    fixed per-node regions with slack and liveness rides the sign
    column (graph/slot_plan.py), so the residual formula masks dead
    rows to zero. Still a solve program: zero scatters, no 64-bit,
    pow2-bucket hash stable (the entry extent is a function of the
    m-bucket alone)."""
    from ..solver.jax_solver import _solve_mcmf

    n, m = bucketed_sizes(n_raw, m_raw)
    fn = functools.partial(
        _solve_mcmf, alpha=8, max_supersteps=4096, tighten_sweeps=32,
        telemetry_cap=telemetry_cap, slot_stable=True,
    )
    e = slot_stable_entry_cap(m)
    return jax.make_jaxpr(fn)(
        _sds((m,)), _sds((m,)), _sds((n,)), _sds((m,)), _sds(()),
        _sds((e,)), _sds((e,)), _sds((e,)), _sds((e,)), _sds((e,)),
        _sds((e,), jnp.bool_), _sds((2 * m,)),
        _sds((n,)), _sds((n,)), _sds((n,), jnp.bool_),
    )


def trace_plan_apply(
    kp_raw: int, ki_raw: int, n_raw: int = 20, m_raw: int = 100,
    ks_raw: int = 0, kn_raw: int = 0,
):
    """Abstract trace of the SECOND (and last) scatter-exempt program:
    the slot-stable plan-row + boundary-static apply over pow2-bucketed
    record counts (graph/slot_plan.plan_apply_fn). The seg/node static
    streams carry real dirt only on region-relocation rounds; on
    ordinary churn rounds they are minimum-bucket idempotent pads, so
    the common-case program is the (kp, ki, 1, 1)-bucket one."""
    from ..graph.device_export import pad_record_count
    from ..graph.slot_plan import (
        INV_RECORD_COLS,
        NODE_RECORD_COLS,
        PLAN_RECORD_COLS,
        SEG_RECORD_COLS,
        plan_apply_fn,
    )

    n, m = bucketed_sizes(n_raw, m_raw)
    e = slot_stable_entry_cap(m)
    kp = pad_record_count(kp_raw)
    ki = pad_record_count(ki_raw)
    ks = pad_record_count(ks_raw)
    kn = pad_record_count(kn_raw)
    return jax.make_jaxpr(plan_apply_fn())(
        _sds((e,)), _sds((e,)), _sds((e,)), _sds((e,)), _sds((2 * m,)),
        _sds((e,)), _sds((e,), jnp.bool_),
        _sds((n,)), _sds((n,)), _sds((n,), jnp.bool_),
        _sds((kp, PLAN_RECORD_COLS)), _sds((ki, INV_RECORD_COLS)),
        _sds((ks, SEG_RECORD_COLS)), _sds((kn, NODE_RECORD_COLS)),
    )


def trace_stacked(
    lanes_raw: int,
    n_raw: int,
    m_raw: int,
    telemetry_cap: int = 0,
    use_warm_p: bool = False,
):
    """Abstract trace of the multi-tenant stacked-CSR batched solve
    (solver/jax_solver.stacked_solve_fn): same-bucket tenant lanes
    through one program, lane axis leading. Contracts pin it
    scatter-free (vmap's while-loop batching masks converged lanes
    with selects, never scatters), 32-bit, and hash-stable across raw
    sizes within a pow2 shape bucket AND raw lane counts within a pow2
    lane bucket — tenants joining/leaving must reuse executables."""
    from ..solver.jax_solver import pad_lane_count, stacked_solve_fn

    n, m = bucketed_sizes(n_raw, m_raw)
    L = pad_lane_count(lanes_raw)
    e = 2 * m
    fn = stacked_solve_fn(
        alpha=8, max_supersteps=4096, tighten_sweeps=32,
        telemetry_cap=telemetry_cap, use_warm_p=use_warm_p,
    )
    args = [
        _sds((L, m)), _sds((L, m)), _sds((L, n)), _sds((L, m)), _sds((L,)),
    ]
    if use_warm_p:
        args.append(_sds((L, n)))
    args += [
        _sds((L, e)), _sds((L, e)), _sds((L, e)), _sds((L, e)), _sds((L, e)),
        _sds((L, e), jnp.bool_), _sds((L, e)),
        _sds((L, n)), _sds((L, n)), _sds((L, n), jnp.bool_),
    ]
    return jax.make_jaxpr(fn)(*args)


def trace_delta_apply(ka_raw: int, kn_raw: int, n_raw: int = 20, m_raw: int = 100):
    """Abstract trace of the FIRST scatter-exempt program: the
    device-resident delta apply over pow2-bucketed record counts
    (graph/device_export.delta_apply_fn)."""
    from ..graph.device_export import (
        ARC_RECORD_COLS,
        NODE_RECORD_COLS,
        delta_apply_fn,
        pad_record_count,
    )

    n, m = bucketed_sizes(n_raw, m_raw)
    ka = pad_record_count(ka_raw)
    kn = pad_record_count(kn_raw)
    return jax.make_jaxpr(delta_apply_fn())(
        _sds((n,)), _sds((m,)), _sds((m,)), _sds((m,)), _sds((m,)),
        _sds((ka, ARC_RECORD_COLS)), _sds((kn, NODE_RECORD_COLS)),
    )


def trace_state_fingerprint(n_raw: int = 20, m_raw: int = 100):
    """Abstract trace of the device-state fingerprint program
    (runtime/integrity.state_fingerprint_fn): per-buffer weighted
    checksums of the five persistent problem buffers. Must stay
    scatter-free and 32-bit — the integrity audit rides the normal
    solve cadence and gets no scatter exemption."""
    from ..runtime.integrity import state_fingerprint_fn

    n, m = bucketed_sizes(n_raw, m_raw)
    return jax.make_jaxpr(state_fingerprint_fn())(
        _sds((n,)), _sds((m,)), _sds((m,)), _sds((m,)), _sds((m,)),
    )


def trace_plan_fingerprint(n_raw: int = 20, m_raw: int = 100, e_raw: int = 256):
    """Abstract trace of the slot-plan fingerprint program
    (runtime/integrity.plan_fingerprint_fn) over the ten maintained
    plan tensors."""
    from ..runtime.integrity import plan_fingerprint_fn
    from ..utils import next_pow2

    n, m = bucketed_sizes(n_raw, m_raw)
    e = max(next_pow2(e_raw), 2 * m)
    return jax.make_jaxpr(plan_fingerprint_fn())(
        _sds((e,)), _sds((e,)), _sds((e,)), _sds((e,)), _sds((2 * m,)),
        _sds((e,)), _sds((e,), jnp.bool_), _sds((n,)), _sds((n,)),
        _sds((n,), jnp.bool_),
    )


def trace_warm_flow(n_raw: int = 20, m_raw: int = 100):
    """Abstract trace of the device warm-flow carry
    (graph/device_export.device_warm_flow_fn) — elementwise only, so
    it must stay scatter- AND gather-free."""
    from ..graph.device_export import device_warm_flow_fn

    _n, m = bucketed_sizes(n_raw, m_raw)
    return jax.make_jaxpr(device_warm_flow_fn())(
        _sds((m,)), _sds((m,)), _sds((m,)), _sds((m,)), _sds((m,)), _sds((m,))
    )


def trace_replicated_plan_apply(
    ki_raw: int, kn_raw: int, n_raw: int = 20, m_raw: int = 100
):
    """Abstract trace of the FOURTH (and last) scatter-exempt program:
    the replicated remainder of a sharded plan sync — inv-order and
    node-boundary records scattered into the replicated plan tensors
    (parallel/sharded_solver.replicated_plan_apply_fn). Shipped
    unaudited in PR 15; the Level-3 registry sweep is what surfaced
    it."""
    from ..graph.device_export import pad_record_count
    from ..graph.slot_plan import INV_RECORD_COLS, NODE_RECORD_COLS
    from ..parallel.sharded_solver import replicated_plan_apply_fn

    n, m = bucketed_sizes(n_raw, m_raw)
    ki = pad_record_count(ki_raw)
    kn = pad_record_count(kn_raw)
    return jax.make_jaxpr(replicated_plan_apply_fn())(
        _sds((2 * m,)), _sds((n,)), _sds((n,)), _sds((n,), jnp.bool_),
        _sds((ki, INV_RECORD_COLS)), _sds((kn, NODE_RECORD_COLS)),
    )


def trace_scale_cost(n_raw: int = 20, m_raw: int = 100):
    """Abstract trace of the cost pre-scaling program
    (graph/device_export._scale_cost_fn) — cost * n ahead of a device
    solve."""
    from ..graph.device_export import _scale_cost_fn

    _n, m = bucketed_sizes(n_raw, m_raw)
    return jax.make_jaxpr(_scale_cost_fn())(_sds((m,)), _sds(()))


def trace_buffer_fingerprint(n_raw: int = 20, m_raw: int = 100):
    """Abstract trace of the single-buffer checksum (the warm-flow
    audit's runtime/integrity._FP_ONE program)."""
    from ..runtime.integrity import _device_fp1

    _n, m = bucketed_sizes(n_raw, m_raw)
    return jax.make_jaxpr(_device_fp1)(_sds((m,)))


def trace_corrupt_flip(n_raw: int = 20, m_raw: int = 100):
    """Abstract trace of the chaos-only poison scatter
    (runtime/integrity.corrupt_fn): flip one bit of one element. The
    only registered program with a chaos-only scatter policy."""
    from ..runtime.integrity import corrupt_fn

    _n, m = bucketed_sizes(n_raw, m_raw)
    return jax.make_jaxpr(corrupt_fn())(_sds((m,)), _sds(()), _sds(()))


TRACERS = {
    "jax": trace_jax,
    "ell": trace_ell,
    "mega": trace_mega,
    "layered": trace_layered,
    "sharded": trace_sharded,
}


# ---------------------------------------------------------------------------
# AOT builders for the donation/aliasing audit
# ---------------------------------------------------------------------------
#
# Each returns (jitted_callable, abstract_args) for the engine's
# compiled-executable donation audit: the callable is the REAL cached
# program factory's output (donate_argnums already applied at the jit
# site), and the args are the same ShapeDtypeStructs its tracer uses —
# so `.lower(*args).compile()` exercises exactly the production
# donation configuration.


def aot_delta_apply(ka_raw: int = 5, kn_raw: int = 3, n_raw: int = 20, m_raw: int = 100):
    from ..graph.device_export import (
        ARC_RECORD_COLS,
        NODE_RECORD_COLS,
        delta_apply_fn,
        pad_record_count,
    )

    n, m = bucketed_sizes(n_raw, m_raw)
    ka = pad_record_count(ka_raw)
    kn = pad_record_count(kn_raw)
    return delta_apply_fn(), (
        _sds((n,)), _sds((m,)), _sds((m,)), _sds((m,)), _sds((m,)),
        _sds((ka, ARC_RECORD_COLS)), _sds((kn, NODE_RECORD_COLS)),
    )


def aot_plan_apply(kp_raw: int = 5, ki_raw: int = 3, n_raw: int = 20, m_raw: int = 100):
    from ..graph.device_export import pad_record_count
    from ..graph.slot_plan import (
        INV_RECORD_COLS,
        NODE_RECORD_COLS,
        PLAN_RECORD_COLS,
        SEG_RECORD_COLS,
        plan_apply_fn,
    )

    n, m = bucketed_sizes(n_raw, m_raw)
    e = slot_stable_entry_cap(m)
    kp = pad_record_count(kp_raw)
    ki = pad_record_count(ki_raw)
    ks = pad_record_count(0)
    kn = pad_record_count(0)
    return plan_apply_fn(), (
        _sds((e,)), _sds((e,)), _sds((e,)), _sds((e,)), _sds((2 * m,)),
        _sds((e,)), _sds((e,), jnp.bool_),
        _sds((n,)), _sds((n,)), _sds((n,), jnp.bool_),
        _sds((kp, PLAN_RECORD_COLS)), _sds((ki, INV_RECORD_COLS)),
        _sds((ks, SEG_RECORD_COLS)), _sds((kn, NODE_RECORD_COLS)),
    )


def aot_sharded_plan_apply(
    kp_raw: int = 5, ks_raw: int = 3, num_devices: int = 2,
    n_raw: int = 20, m_raw: int = 100,
):
    from ..graph.device_export import pad_record_count
    from ..graph.slot_plan import PLAN_RECORD_COLS, SEG_RECORD_COLS
    from ..parallel.sharded_solver import (
        sharded_entry_extent,
        sharded_plan_apply_fn,
    )

    _n, m = bucketed_sizes(n_raw, m_raw)
    D = num_devices
    es = sharded_entry_extent(m, D)
    kp = pad_record_count(kp_raw)
    ks = pad_record_count(ks_raw)
    return sharded_plan_apply_fn(_mesh_of(D), "x"), (
        _sds((D, es)), _sds((D, es)), _sds((D, es)), _sds((D, es)),
        _sds((D, es)), _sds((D, es), jnp.bool_),
        _sds((D, kp, PLAN_RECORD_COLS)), _sds((D, ks, SEG_RECORD_COLS)),
    )


def aot_replicated_plan_apply(
    ki_raw: int = 5, kn_raw: int = 3, n_raw: int = 20, m_raw: int = 100
):
    from ..graph.device_export import pad_record_count
    from ..graph.slot_plan import INV_RECORD_COLS, NODE_RECORD_COLS
    from ..parallel.sharded_solver import replicated_plan_apply_fn

    n, m = bucketed_sizes(n_raw, m_raw)
    ki = pad_record_count(ki_raw)
    kn = pad_record_count(kn_raw)
    return replicated_plan_apply_fn(), (
        _sds((2 * m,)), _sds((n,)), _sds((n,)), _sds((n,), jnp.bool_),
        _sds((ki, INV_RECORD_COLS)), _sds((kn, NODE_RECORD_COLS)),
    )


@functools.lru_cache(maxsize=64)
def traced(backend: str, n_raw: int, m_raw: int, seed: int = 0,
           telemetry_cap: int = 0):
    """Cached abstract trace: the contract tests revisit the same
    (backend, bucket) pairs, and tracing (the megakernel especially)
    dominates the suite's tier-1 cost. telemetry_cap traces the
    solver-telemetry-ON program (obs/soltel.py); 0 is the baseline
    pre-telemetry program."""
    return TRACERS[backend](n_raw, m_raw, seed, telemetry_cap=telemetry_cap)


def backend_report(backend: str, n_raw: int, m_raw: int, seed: int = 0) -> ContractReport:
    closed = traced(backend, n_raw, m_raw, seed)
    return check_jaxpr(backend, closed, shape_key=(n_raw, m_raw))


def recompile_hazard(
    backend: str, raw_a: Tuple[int, int], raw_b: Tuple[int, int], seed: int = 0
) -> Tuple[str, str]:
    """Jaxpr hashes for two raw sizes; equal hashes = one executable
    serves both (no recompile inside the bucket)."""
    return (
        jaxpr_hash(traced(backend, *raw_a, seed)),
        jaxpr_hash(traced(backend, *raw_b, seed)),
    )
