// Native min-cost max-flow library: the framework's in-process equivalent
// of the reference's external Flowlessly C++ binary
// (scheduling/flow/placement/solver.go:31-34; build/Dockerfile:11-12).
//
// Where the reference streams DIMACS text to a solver daemon over pipes,
// this library takes flat arrays (src/dst/cap/cost/excess) in-process and
// writes per-arc flows back — the same "arrays in, arrays out" wire format
// the JAX/TPU backend uses, so all backends sit behind one seam.
//
// Two algorithms, mirroring Flowlessly's successive_shortest_path and
// cost_scaling flags (solver.go:32):
//   0 = successive shortest paths (multi-source Dijkstra + Johnson
//       potentials, Bellman-Ford bootstrap for negative costs) — exact,
//       the parity oracle.
//   1 = cost-scaling push-relabel (Goldberg-Tarjan) with FIFO discharge —
//       the fast path; node prices persist in an opaque context so
//       incremental rounds warm-start, the property Flowlessly's daemon
//       mode provides (solver.go:60-90).
//
// Build: g++ -O3 -shared -fPIC (see build.py). No external deps.

#include <algorithm>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <limits>
#include <queue>
#include <vector>

namespace {

constexpr int64_t kInf = std::numeric_limits<int64_t>::max() / 4;

// Residual graph: two directed edges per live input arc, stored so that
// edge 2k is arc k forward and 2k+1 its reverse (pair = e ^ 1).
struct Residual {
  int32_t n = 0;
  int64_t ne = 0;               // number of residual edges (2 * live arcs)
  std::vector<int32_t> to;      // head of each residual edge
  std::vector<int32_t> tail;    // tail of each residual edge
  std::vector<int64_t> resid;   // residual capacity
  std::vector<int64_t> cost;    // edge cost (reverse = -forward)
  std::vector<int64_t> arc_of;  // input arc index for edge (for flow readback)
  std::vector<int64_t> first;   // CSR row pointer [n+1]
  std::vector<int64_t> adj;     // CSR payload: residual edge ids
};

void build_residual(Residual &g, int32_t n, int64_t m, const int32_t *src,
                    const int32_t *dst, const int32_t *cap,
                    const int32_t *cost) {
  g.n = n;
  g.to.clear();
  g.tail.clear();
  g.resid.clear();
  g.cost.clear();
  g.arc_of.clear();
  for (int64_t k = 0; k < m; ++k) {
    if (cap[k] <= 0) continue;  // padded / deleted arc slot
    int32_t u = src[k], v = dst[k];
    g.tail.push_back(u);
    g.to.push_back(v);
    g.resid.push_back(cap[k]);
    g.cost.push_back(cost[k]);
    g.arc_of.push_back(k);
    g.tail.push_back(v);
    g.to.push_back(u);
    g.resid.push_back(0);
    g.cost.push_back(-static_cast<int64_t>(cost[k]));
    g.arc_of.push_back(k);
  }
  g.ne = static_cast<int64_t>(g.to.size());
  g.first.assign(static_cast<size_t>(n) + 1, 0);
  for (int64_t e = 0; e < g.ne; ++e) g.first[g.tail[e] + 1]++;
  for (int32_t v = 0; v < n; ++v) g.first[v + 1] += g.first[v];
  g.adj.assign(g.ne, 0);
  std::vector<int64_t> pos(g.first.begin(), g.first.end() - 1);
  for (int64_t e = 0; e < g.ne; ++e) g.adj[pos[g.tail[e]]++] = e;
}

// ---------------------------------------------------------------------------
// Algorithm 0: successive shortest paths.
// ---------------------------------------------------------------------------

int32_t solve_ssp(Residual &g, std::vector<int64_t> &excess, int64_t *iters) {
  const int32_t n = g.n;
  std::vector<int64_t> pot(n, 0);

  bool has_negative = false;
  for (int64_t e = 0; e < g.ne; e += 2)
    if (g.resid[e] > 0 && g.cost[e] < 0) {
      has_negative = true;
      break;
    }
  if (has_negative) {  // Bellman-Ford potential bootstrap
    for (int32_t round = 0; round <= n; ++round) {
      bool changed = false;
      for (int64_t e = 0; e < g.ne; ++e) {
        if (g.resid[e] <= 0) continue;
        int64_t cand = pot[g.tail[e]] + g.cost[e];
        if (cand < pot[g.to[e]]) {
          pot[g.to[e]] = cand;
          changed = true;
        }
      }
      if (!changed) break;
      if (round == n) return 4;  // negative cycle
    }
  }

  std::vector<int64_t> dist(n);
  std::vector<int64_t> parent(n);  // residual edge id into node, -1 = none
  using QE = std::pair<int64_t, int32_t>;
  int64_t augmentations = 0;

  for (;;) {
    std::priority_queue<QE, std::vector<QE>, std::greater<QE>> pq;
    std::fill(dist.begin(), dist.end(), kInf);
    std::fill(parent.begin(), parent.end(), int64_t{-1});
    bool any_supply = false;
    for (int32_t v = 0; v < n; ++v)
      if (excess[v] > 0) {
        dist[v] = 0;
        pq.emplace(0, v);
        any_supply = true;
      }
    if (!any_supply) break;
    int32_t demand = -1;
    while (!pq.empty()) {
      auto [d, v] = pq.top();
      pq.pop();
      if (d > dist[v]) continue;
      if (excess[v] < 0) {
        demand = v;
        break;
      }
      for (int64_t i = g.first[v]; i < g.first[v + 1]; ++i) {
        int64_t e = g.adj[i];
        if (g.resid[e] <= 0) continue;
        int32_t w = g.to[e];
        int64_t nd = d + g.cost[e] + pot[v] - pot[w];
        if (nd < dist[w]) {
          dist[w] = nd;
          parent[w] = e;
          pq.emplace(nd, w);
        }
      }
    }
    if (demand < 0) return 1;  // supply cannot reach any demand
    int64_t dt = dist[demand];
    for (int32_t v = 0; v < n; ++v)
      pot[v] += std::min(dist[v], dt);
    // bottleneck along the path
    int64_t bottleneck = -excess[demand];
    for (int32_t v = demand; parent[v] >= 0; v = g.tail[parent[v]])
      bottleneck = std::min(bottleneck, g.resid[parent[v]]);
    int32_t source = demand;
    while (parent[source] >= 0) source = g.tail[parent[source]];
    bottleneck = std::min(bottleneck, excess[source]);
    for (int32_t v = demand; parent[v] >= 0; v = g.tail[parent[v]]) {
      g.resid[parent[v]] -= bottleneck;
      g.resid[parent[v] ^ 1] += bottleneck;
    }
    excess[source] -= bottleneck;
    excess[demand] += bottleneck;
    ++augmentations;
  }
  *iters = augmentations;
  return 0;
}

// ---------------------------------------------------------------------------
// Algorithm 1: cost-scaling push-relabel.
//
// eps-optimality invariant: every residual edge e has reduced cost
// rc(e) = cost(e) + p[tail] - p[head] >= -eps. Costs are pre-scaled by
// (n + 1) so the eps == 1 phase yields an exact optimum for the original
// integer costs. Prices p persist across calls via SolverCtx (warm start).
// ---------------------------------------------------------------------------

struct SolverCtx {
  std::vector<int64_t> prices;
  int64_t supersteps = 0;  // total discharge operations, for stats
};

int32_t solve_cost_scaling(Residual &g, std::vector<int64_t> &excess,
                           SolverCtx *ctx, int64_t *iters) {
  const int32_t n = g.n;
  const int64_t scale = static_cast<int64_t>(n) + 1;
  int64_t max_c = 0;
  for (int64_t e = 0; e < g.ne; e += 2)
    max_c = std::max(max_c, std::abs(g.cost[e]));
  std::vector<int64_t> c(g.ne);
  for (int64_t e = 0; e < g.ne; ++e) c[e] = g.cost[e] * scale;

  std::vector<int64_t> local_prices;
  std::vector<int64_t> &p =
      (ctx != nullptr) ? ctx->prices : local_prices;
  if (static_cast<int32_t>(p.size()) != n) p.assign(n, 0);

  std::vector<int64_t> cur(n);  // current-arc pointers
  std::deque<int32_t> active;
  std::vector<uint8_t> in_queue(n, 0);
  int64_t total_discharges = 0;

  int64_t eps = std::max<int64_t>(1, max_c * scale);
  constexpr int64_t kAlpha = 8;

  for (;;) {
    // Make the pseudoflow eps-optimal: saturate negative-reduced-cost arcs.
    for (int64_t e = 0; e < g.ne; ++e) {
      if (g.resid[e] <= 0) continue;
      int64_t rc = c[e] + p[g.tail[e]] - p[g.to[e]];
      if (rc < -eps) {
        int64_t amt = g.resid[e];
        g.resid[e] = 0;
        g.resid[e ^ 1] += amt;
        excess[g.tail[e]] -= amt;
        excess[g.to[e]] += amt;
      }
    }
    active.clear();
    std::fill(in_queue.begin(), in_queue.end(), 0);
    for (int32_t v = 0; v < n; ++v) {
      cur[v] = g.first[v];
      if (excess[v] > 0) {
        active.push_back(v);
        in_queue[v] = 1;
      }
    }
    // Per-phase price floor: feasible discharge lowers a price by at most
    // O(n * eps); far past that means supply is cut off from all demand.
    int64_t p_min = 0;
    for (int32_t v = 0; v < n; ++v) p_min = std::min(p_min, p[v]);
    const int64_t floor =
        p_min - (kAlpha + 3) * (static_cast<int64_t>(n) + 2) * eps - 16;

    while (!active.empty()) {
      int32_t u = active.front();
      active.pop_front();
      in_queue[u] = 0;
      ++total_discharges;
      // discharge u
      while (excess[u] > 0) {
        bool pushed_or_scanned = false;
        for (; cur[u] < g.first[u + 1]; ++cur[u]) {
          int64_t e = g.adj[cur[u]];
          if (g.resid[e] <= 0) continue;
          int32_t w = g.to[e];
          if (c[e] + p[u] - p[w] < 0) {  // admissible
            int64_t amt = std::min(excess[u], g.resid[e]);
            g.resid[e] -= amt;
            g.resid[e ^ 1] += amt;
            excess[u] -= amt;
            excess[w] += amt;
            if (excess[w] > 0 && !in_queue[w] && w != u) {
              active.push_back(w);
              in_queue[w] = 1;
            }
            if (excess[u] == 0) {
              pushed_or_scanned = true;
              break;
            }
          }
        }
        if (excess[u] == 0) break;
        (void)pushed_or_scanned;
        // relabel: p[u] = max over residual (u,w) of (p[w] - c(u,w)) - eps
        // (the smallest decrease that makes one arc admissible; max keeps
        // rc >= -eps on every other residual arc out of u)
        int64_t best = -kInf;
        for (int64_t i = g.first[u]; i < g.first[u + 1]; ++i) {
          int64_t e = g.adj[i];
          if (g.resid[e] <= 0) continue;
          best = std::max(best, p[g.to[e]] - c[e]);
        }
        if (best <= -kInf) return 1;  // no residual arc at all: infeasible
        p[u] = best - eps;
        cur[u] = g.first[u];
        if (p[u] < floor) return 1;  // price divergence: infeasible
      }
    }
    if (eps == 1) break;
    eps = std::max<int64_t>(1, eps / kAlpha);
  }
  if (ctx != nullptr) ctx->supersteps = total_discharges;
  *iters = total_discharges;
  return 0;
}

}  // namespace

extern "C" {

void *ksched_mcmf_ctx_new() { return new SolverCtx(); }

void ksched_mcmf_ctx_free(void *ctx) {
  delete static_cast<SolverCtx *>(ctx);
}

// Returns 0 ok, 1 infeasible, 2 unbalanced excess, 3 bad args,
// 4 negative-cost cycle.
int32_t ksched_mcmf_solve(void *ctx_ptr, int32_t algorithm, int32_t n,
                          int64_t m, const int32_t *src, const int32_t *dst,
                          const int32_t *cap, const int32_t *cost,
                          const int64_t *excess_in, int64_t *flow_out,
                          int64_t *objective_out, int64_t *iters_out) {
  if (n <= 0 || m < 0 || !src || !dst || !cap || !cost || !excess_in ||
      !flow_out || !objective_out || !iters_out)
    return 3;
  for (int64_t k = 0; k < m; ++k)
    if (cap[k] > 0 && (src[k] < 0 || src[k] >= n || dst[k] < 0 || dst[k] >= n))
      return 3;
  int64_t balance = 0;
  for (int32_t v = 0; v < n; ++v) balance += excess_in[v];
  if (balance != 0) return 2;

  Residual g;
  build_residual(g, n, m, src, dst, cap, cost);
  std::vector<int64_t> excess(excess_in, excess_in + n);

  int64_t iters = 0;
  int32_t rc;
  if (algorithm == 0) {
    rc = solve_ssp(g, excess, &iters);
  } else {
    rc = solve_cost_scaling(g, excess, static_cast<SolverCtx *>(ctx_ptr),
                            &iters);
  }
  if (rc != 0) return rc;

  std::memset(flow_out, 0, static_cast<size_t>(m) * sizeof(int64_t));
  int64_t objective = 0;
  for (int64_t e = 0; e < g.ne; e += 2) {
    int64_t f = g.resid[e ^ 1];  // flow = reverse residual
    flow_out[g.arc_of[e]] = f;
    objective += f * g.cost[e];
  }
  *objective_out = objective;
  *iters_out = iters;
  return 0;
}

}  // extern "C"
