"""Native (C++) runtime components.

The reference delegates its MCMF solve to an external C++ binary
(Flowlessly) reached over pipes (scheduling/flow/placement/solver.go:
92-109). Here the native solver is an in-process shared library built
from mcmf.cpp on first use and bound via ctypes — no subprocess, no text
protocol, and a dead solver raises a Python exception instead of
panicking the scheduler (the reference's crash mode, solver.go:98-108).
"""

from .build import load_library, library_path

__all__ = ["load_library", "library_path"]
