"""Compile-on-first-use build of the native MCMF library.

Equivalent in role to the reference's build/Dockerfile:5-12 step that
builds Flowlessly via cmake — except the artifact is a shared library
loaded in-process, rebuilt automatically when mcmf.cpp is newer than the
cached .so. Thread-safe via an atomic rename.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
import threading

_SRC = os.path.join(os.path.dirname(__file__), "mcmf.cpp")
_BUILD_DIR = os.path.join(os.path.dirname(__file__), "_build")
_LIB = os.path.join(_BUILD_DIR, "libksched_mcmf.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None


def library_path() -> str:
    """Path to the compiled library, building it if missing or stale."""
    if os.path.exists(_LIB) and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC):
        return _LIB
    os.makedirs(_BUILD_DIR, exist_ok=True)
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_BUILD_DIR)
    os.close(fd)
    try:
        subprocess.run(
            ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-o", tmp, _SRC],
            check=True,
            capture_output=True,
            text=True,
        )
        os.replace(tmp, _LIB)
    except subprocess.CalledProcessError as e:
        raise RuntimeError(f"native solver build failed:\n{e.stderr}") from e
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return _LIB


def load_library() -> ctypes.CDLL:
    """Load (building if needed) and type the library. Cached per process."""
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        lib = ctypes.CDLL(library_path())
        lib.ksched_mcmf_ctx_new.restype = ctypes.c_void_p
        lib.ksched_mcmf_ctx_new.argtypes = []
        lib.ksched_mcmf_ctx_free.restype = None
        lib.ksched_mcmf_ctx_free.argtypes = [ctypes.c_void_p]
        lib.ksched_mcmf_solve.restype = ctypes.c_int32
        lib.ksched_mcmf_solve.argtypes = [
            ctypes.c_void_p,  # ctx (nullable)
            ctypes.c_int32,  # algorithm
            ctypes.c_int32,  # n
            ctypes.c_int64,  # m
            ctypes.POINTER(ctypes.c_int32),  # src
            ctypes.POINTER(ctypes.c_int32),  # dst
            ctypes.POINTER(ctypes.c_int32),  # cap
            ctypes.POINTER(ctypes.c_int32),  # cost
            ctypes.POINTER(ctypes.c_int64),  # excess
            ctypes.POINTER(ctypes.c_int64),  # flow_out
            ctypes.POINTER(ctypes.c_int64),  # objective_out
            ctypes.POINTER(ctypes.c_int64),  # iters_out
        ]
        _lib = lib
        return _lib
