"""ksched_tpu: a TPU-native flow-network cluster scheduler.

A ground-up rebuild of the capabilities of ksched (a Go reimplementation
of the Firmament min-cost max-flow scheduler): scheduling is modeled as
min-cost max-flow over a task → equivalence-class → resource-topology →
sink network, with per-job unscheduled-aggregator escape nodes. Instead
of streaming DIMACS text to an external C++ solver subprocess, the flow
network lives in flat device arrays and is solved by a JAX/Pallas
cost-scaling push-relabel kernel on TPU (with exact CPU and native C++
backends behind the same solver seam).
"""

__version__ = "0.1.0"
