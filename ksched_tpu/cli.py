"""Scheduler service + CLI: the main-binary equivalent.

Reference: cmd/k8sscheduler/scheduler.go — flag surface (:31-42),
pod↔task and node↔machine id maps (:44-62), topology init from polled
nodes or fabricated machines (:191-238), and the main loop (:114-189):
batch pods → add tasks → ScheduleAllJobs (the timed region, :146-150) →
diff bindings → walk PU up to its machine (:379-398) → post bindings.

Run: python -m ksched_tpu.cli --fake-machines --num-machines 10 \
         --podgen 100 --one-shot
"""

from __future__ import annotations

import argparse
import sys
import threading
import time
from typing import Dict, Optional

from .cluster import Binding, ClusterAPI, NodeEvent, PodEvent, SyntheticClusterAPI
from .costmodels import MODEL_REGISTRY, CostModelType
from .drivers.synthetic import (
    add_machine,
    add_task_to_job,
    build_machine_topology,
    make_coordinator_root,
)
from .scheduler import FlowScheduler
from .utils import (
    JobMap,
    ResourceMap,
    ResourceStatus,
    TaskMap,
    rand_uint64,
    resource_id_from_string,
)


class SchedulerService:
    """The long-running scheduler process state (reference:
    cmd/k8sscheduler/scheduler.go:44-87)."""

    def __init__(
        self,
        api: ClusterAPI,
        max_tasks_per_pu: int = 1000,
        cost_model: CostModelType = CostModelType.TRIVIAL,
        backend=None,
    ) -> None:
        self.api = api
        self.resource_map = ResourceMap()
        self.job_map = JobMap()
        self.task_map = TaskMap()
        self.root = make_coordinator_root()
        self.resource_map.insert(
            resource_id_from_string(self.root.resource_desc.uuid),
            ResourceStatus(descriptor=self.root.resource_desc, topology_node=self.root),
        )
        self.scheduler = FlowScheduler(
            self.resource_map,
            self.job_map,
            self.task_map,
            self.root,
            max_tasks_per_pu=max_tasks_per_pu,
            cost_model_factory=MODEL_REGISTRY[cost_model],
            backend=backend,
        )
        self.max_tasks_per_pu = max_tasks_per_pu
        # Bidirectional id maps (reference :44-62).
        self.pod_to_task: Dict[str, int] = {}
        self.task_to_pod: Dict[int, str] = {}
        self.node_to_machine: Dict[str, int] = {}
        self.machine_to_node: Dict[int, str] = {}
        # One job shelters every pod-task (reference :118, :241-257).
        self.job_id = rand_uint64()
        self.old_bindings: Dict[int, int] = {}
        self.round_latencies_s: list = []

    # -- topology ---------------------------------------------------------

    def add_node(self, node: NodeEvent) -> None:
        machine = add_machine(
            self.scheduler,
            self.resource_map,
            self.root,
            num_cores=node.num_cores,
            pus_per_core=node.pus_per_core,
            task_capacity_per_pu=self.max_tasks_per_pu,
            machine_index=len(self.node_to_machine),
        )
        machine.resource_desc.capacity.net_bw = node.net_bw_capacity
        mid = resource_id_from_string(machine.resource_desc.uuid)
        self.node_to_machine[node.node_id] = mid
        self.machine_to_node[mid] = node.node_id

    def init_topology(
        self,
        fake_machines: int = 0,
        node_batch_timeout_s: float = 2.0,
        cores_per_machine: int = 1,
        pus_per_core: int = 1,
    ) -> int:
        """Fabricate machines (-fakeMachines, reference :191-202) or poll
        the control plane for nodes (:206-238)."""
        if fake_machines > 0:
            for i in range(fake_machines):
                self.add_node(
                    NodeEvent(
                        node_id=f"fake_node_{i}",
                        num_cores=cores_per_machine,
                        pus_per_core=pus_per_core,
                    )
                )
            return fake_machines
        nodes = self.api.get_node_batch(node_batch_timeout_s)
        for node in nodes:
            self.add_node(node)
        return len(nodes)

    # -- pod → task -------------------------------------------------------

    def _add_pod(self, pod: PodEvent) -> None:
        existing = self.pod_to_task.get(pod.pod_id)
        if existing is not None:
            # Re-delivered pod: keep the existing task — a duplicate
            # would double-occupy capacity — and forget the emitted
            # binding so the next round's diff re-posts it. Two causes:
            # a failed binding POST (spec unchanged), or a pod deleted
            # and re-created under the same name (the watch reconcile
            # re-surfaces it). For the latter the new spec must win:
            # refresh the descriptor, and evict any stale placement so
            # the next round reschedules under the new request.
            td = self.task_map.find(existing)
            if td is not None and (
                td.resource_request.cpu_cores,
                td.resource_request.net_bw,
                int(td.task_type),
            ) != (pod.cpu_request, pod.net_bw_request, pod.task_class):
                td.resource_request.cpu_cores = pod.cpu_request
                td.resource_request.net_bw = pod.net_bw_request
                td.task_type = type(td.task_type)(pod.task_class)
                rid = self.scheduler.task_bindings.get(existing)
                if rid is not None:
                    rs = self.resource_map.find(rid)
                    self.scheduler.handle_task_eviction(td, rs.descriptor)
            self.old_bindings.pop(existing, None)
            return
        td = add_task_to_job(self.job_id, self.job_map, self.task_map, name=pod.pod_id)
        td.resource_request.cpu_cores = pod.cpu_request
        td.resource_request.net_bw = pod.net_bw_request
        td.task_type = type(td.task_type)(pod.task_class)
        # Leave state CREATED: the scheduler's runnable-task computation
        # promotes CREATED→RUNNABLE and registers the task (reference:
        # flowscheduler/scheduler.go:487-529).
        self.pod_to_task[pod.pod_id] = td.uid
        self.task_to_pod[td.uid] = pod.pod_id

    def _find_parent_machine(self, pu_rid: int) -> Optional[int]:
        """Walk a PU up the topology to its machine (reference :379-398)."""
        rs = self.resource_map.find(pu_rid)
        while rs is not None:
            if resource_id_from_string(rs.descriptor.uuid) in self.machine_to_node:
                return resource_id_from_string(rs.descriptor.uuid)
            if not rs.topology_node.parent_id:
                return None
            rs = self.resource_map.find(resource_id_from_string(rs.topology_node.parent_id))
        return None

    # -- the main loop ----------------------------------------------------

    def run_once(self, pods) -> int:
        """One iteration of the reference loop body (:120-187). Returns
        the number of new bindings pushed."""
        for pod in pods:
            self._add_pod(pod)
        jd = self.job_map.find(self.job_id)
        if jd is not None:
            self.scheduler.add_job(jd)
        t0 = time.perf_counter()
        self.scheduler.schedule_all_jobs()
        self.round_latencies_s.append(time.perf_counter() - t0)

        new_bindings = self.scheduler.get_task_bindings()
        out = []
        for task_id, pu_rid in new_bindings.items():
            if self.old_bindings.get(task_id) == pu_rid:
                continue
            machine_rid = self._find_parent_machine(pu_rid)
            if machine_rid is None:
                continue
            pod_id = self.task_to_pod.get(task_id)
            if pod_id is None:
                continue
            out.append(Binding(pod_id=pod_id, node_id=self.machine_to_node[machine_rid]))
        self.old_bindings = dict(new_bindings)
        if out:
            self.api.assign_bindings(out)
        return len(out)

    def run(self, pod_batch_timeout_s: float = 2.0, max_rounds: Optional[int] = None) -> None:
        rounds = 0
        while max_rounds is None or rounds < max_rounds:
            pods = self.api.get_pod_batch(pod_batch_timeout_s)
            if not pods:
                break  # control plane closed
            self.run_once(pods)
            rounds += 1


def podgen(api: ClusterAPI, num_pods: int, net_bw: int = 0) -> None:
    """Load generator (reference: cmd/podgen/podgen.go:34-74). Against
    an HTTP control plane, pods are created via the API server (as the
    reference's podgen does); against the synthetic one, enqueued
    directly."""
    try:
        for i in range(num_pods):
            if hasattr(api, "create_pod"):
                api.create_pod(f"pod_{i}", net_bw_request=net_bw)
            else:
                api.submit_pod(PodEvent(pod_id=f"pod_{i}", net_bw_request=net_bw))
    except Exception as e:  # noqa: BLE001 — runs in a daemon thread
        # Surface the failure and unblock get_pod_batch (which would
        # otherwise wait forever for pods that will never arrive).
        print(f"podgen failed: {e}", file=sys.stderr)
        api.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ksched-tpu", description="TPU-native flow-network cluster scheduler"
    )
    # Flag surface mirrors cmd/k8sscheduler/scheduler.go:31-42.
    ap.add_argument("--max-tasks-per-pu", "-mt", type=int, default=1000)
    ap.add_argument("--pod-batch-timeout", "-pbt", type=float, default=2.0)
    ap.add_argument("--node-batch-timeout", "-nbt", type=float, default=2.0)
    ap.add_argument("--pod-chan-size", "-pcs", type=int, default=5000)
    ap.add_argument("--fake-machines", action="store_true")
    ap.add_argument("--num-machines", "-nm", type=int, default=10)
    ap.add_argument("--cores-per-machine", type=int, default=1)
    ap.add_argument("--pus-per-core", type=int, default=1)
    ap.add_argument(
        "--cost-model",
        choices=[m.name.lower() for m in CostModelType],
        default="trivial",
    )
    ap.add_argument(
        "--backend", choices=["ref", "native", "jax", "ell", "auto"],
        default="native",
        help="MCMF backend (native C++ is the CPU production default; "
        "auto = per-solve dense-vs-CSR dispatch, solver/graph_collapse.py)",
    )
    ap.add_argument("--podgen", type=int, default=0, metavar="N",
                    help="generate N pods in-process (cmd/podgen equivalent)")
    ap.add_argument("--one-shot", action="store_true",
                    help="exit once the pod queue is drained")
    ap.add_argument(
        "--api-server", metavar="URL", default=None,
        help="schedule against a control plane over HTTP (the reference's "
        "-addr; see cluster/http_api.py) instead of the in-process "
        "synthetic API; --podgen then posts pods to the server",
    )
    args = ap.parse_args(argv)
    if args.one_shot and args.podgen <= 0:
        ap.error("--one-shot needs --podgen N: the pod wait blocks until a first pod arrives")

    from .solver.select import make_backend

    backend = make_backend(args.backend)

    if args.api_server:
        from .cluster.http_api import HTTPClusterAPI

        api = HTTPClusterAPI(args.api_server, pod_chan_size=args.pod_chan_size)
    else:
        api = SyntheticClusterAPI(pod_chan_size=args.pod_chan_size)
    svc = SchedulerService(
        api,
        max_tasks_per_pu=args.max_tasks_per_pu,
        cost_model=CostModelType[args.cost_model.upper()],
        backend=backend,
    )
    n = svc.init_topology(
        fake_machines=args.num_machines if args.fake_machines else 0,
        node_batch_timeout_s=args.node_batch_timeout,
        cores_per_machine=args.cores_per_machine,
        pus_per_core=args.pus_per_core,
    )
    print(f"topology: {n} machines", file=sys.stderr)

    if args.podgen > 0:
        threading.Thread(target=podgen, args=(api, args.podgen), daemon=True).start()

    try:
        if args.one_shot:
            pods = api.get_pod_batch(args.pod_batch_timeout)
            bound = svc.run_once(pods) if pods else 0
            lat = svc.round_latencies_s[-1] * 1e3 if svc.round_latencies_s else 0.0
            print(
                f"scheduled {bound}/{len(pods)} pods in {lat:.2f}ms "
                f"({len(api.bindings())} total bindings)",
                file=sys.stderr,
            )
            return 0
        svc.run(pod_batch_timeout_s=args.pod_batch_timeout)
        return 0
    finally:
        api.close()


if __name__ == "__main__":
    sys.exit(main())
