"""Scheduler service + CLI: the main-binary equivalent.

Reference: cmd/k8sscheduler/scheduler.go — flag surface (:31-42),
pod↔task and node↔machine id maps (:44-62), topology init from polled
nodes or fabricated machines (:191-238), and the main loop (:114-189):
batch pods → add tasks → ScheduleAllJobs (the timed region, :146-150) →
diff bindings → walk PU up to its machine (:379-398) → post bindings.

Run: python -m ksched_tpu.cli --fake-machines --num-machines 10 \
         --podgen 100 --one-shot
"""

from __future__ import annotations

import argparse
import pickle
import sys
import threading
import time
import urllib.error
import warnings
from typing import Dict, List, Optional, Tuple

from .cluster import Binding, ClusterAPI, NodeEvent, PodEvent, SyntheticClusterAPI
from .cluster.api import RETRY_STAT_KEYS
from .costmodels import MODEL_REGISTRY, CostModelType
from .obs import metrics as obs_metrics
from .obs.flight import FlightRecorder
from .obs.spans import SpanTracer, span
from .drivers.synthetic import (
    add_machine,
    add_task_to_job,
    build_machine_topology,
    make_coordinator_root,
)
from .runtime.chaos import FaultInjector, delta_counters
from .runtime.degrade import DegradingSolver, LadderExhausted, build_degradation_ladder
from .runtime.failure import HeartbeatMonitor, RoundWatchdog
from .runtime.trace import RoundTracer
from .scheduler import FlowScheduler
from .scheduler.flow_scheduler import RoundTiming
from .solver.cpu_ref import ReferenceSolver
from .utils import (
    ExpBackoff,
    JobMap,
    ResourceMap,
    ResourceStatus,
    TaskMap,
    rand_uint64,
    resource_id_from_string,
)

#: service-checkpoint sidecar version (the scheduler state itself rides
#: in runtime/checkpoint.py's save_scheduler format). v2 adds the warm
#: restore companion (path + ".wal": journal WAL + device-state
#: manifest) and the round/ladder counters; v1 sidecars still load.
SERVICE_CHECKPOINT_VERSION = 2


class SchedulerService:
    """The long-running scheduler process state (reference:
    cmd/k8sscheduler/scheduler.go:44-87), hardened: the configured
    backend rides a degradation ladder (configured → scan-CSR jax →
    cpu_ref → NOOP round, runtime/degrade.py), rounds run under a
    deadline watchdog, heartbeat sweeps are integrated into the loop,
    and every fault / retry / degradation is attributed to its round in
    the trace (runtime/trace.py RoundRecord)."""

    def __init__(
        self,
        api: ClusterAPI,
        max_tasks_per_pu: int = 1000,
        cost_model: CostModelType = CostModelType.TRIVIAL,
        backend=None,
        backend_name: str = "configured",
        degrade: bool = True,
        injector: Optional[FaultInjector] = None,
        tracer: Optional[RoundTracer] = None,
        round_deadline_s: float = 0.0,
        flight: Optional[FlightRecorder] = None,
        span_tracer: Optional[SpanTracer] = None,
        pipeline: bool = False,
        device_resident: bool = False,
        tenant: str = "",
        audit_every: int = 0,
        _restored: Optional[Tuple] = None,
    ) -> None:
        self.api = api
        self.injector = injector
        self.tracer = tracer
        self.flight = flight
        self.span_tracer = span_tracer
        #: owning cell label in a multi-tenant service ("" when the
        #: service is the whole process, as before) — stamped onto every
        #: RoundRecord and the service_round span
        self.tenant = tenant
        #: in-flight split-round state (dispatch_round/complete_round,
        #: the multi-tenant loop's seam) — None outside a split round
        self._split: Optional[dict] = None
        #: double-buffered round mode: each round DISPATCHES its solve,
        #: then posts the PREVIOUS round's bindings while the device
        #: crunches, then synchronizes/decodes/applies — so binding
        #: POSTs (and, in run(), the next poll) overlap the in-flight
        #: solve instead of serializing after it. Graph evolution and
        #: placements are bit-identical to the synchronous loop: only
        #: WHEN bindings are posted moves (one dispatch window later),
        #: never what the scheduler computes (tools/soak.py
        #: --verify-loop-parity asserts this under chaos).
        self.pipeline = pipeline
        self.device_resident = device_resident
        self._pending_bindings: List[Binding] = []
        # service-level gauges (inert singletons when obs is disabled)
        reg = obs_metrics.get_registry()
        self._g_pods = reg.gauge("ksched_live_pods", "pods the service tracks")
        self._g_bound = reg.gauge("ksched_bound_tasks", "tasks currently bound")
        self._g_machines = reg.gauge("ksched_machines", "machines in the topology")
        self.watchdog = RoundWatchdog(round_deadline_s)
        self.monitor: Optional[HeartbeatMonitor] = None
        if _restored is None:
            if degrade:
                backend = build_degradation_ladder(
                    backend if backend is not None else ReferenceSolver(),
                    backend_name,
                    injector=injector,
                )
            self.resource_map = ResourceMap()
            self.job_map = JobMap()
            self.task_map = TaskMap()
            self.root = make_coordinator_root()
            self.resource_map.insert(
                resource_id_from_string(self.root.resource_desc.uuid),
                ResourceStatus(descriptor=self.root.resource_desc, topology_node=self.root),
            )
            self.scheduler = FlowScheduler(
                self.resource_map,
                self.job_map,
                self.task_map,
                self.root,
                max_tasks_per_pu=max_tasks_per_pu,
                cost_model_factory=MODEL_REGISTRY[cost_model],
                backend=backend,
                device_resident=device_resident,
            )
        else:
            # restore path: the scheduler was rebuilt by replaying the
            # checkpoint through the event API (runtime/checkpoint.py)
            self.scheduler, self.resource_map, self.job_map, self.task_map = _restored
            self.root = self.scheduler.resource_topology
        ladder = self.scheduler.solver.backend
        self.ladder: Optional[DegradingSolver] = (
            ladder if isinstance(ladder, DegradingSolver) else None
        )
        #: device-state integrity audit cadence (0 = off): every Nth
        #: export, the placement solver fingerprints the device mirror
        #: against the host journal truth and repairs divergence
        #: through the escalating ladder (runtime/integrity.py)
        self.audit_every = audit_every
        self.scheduler.solver.audit_every = audit_every
        if audit_every and not device_resident:
            warnings.warn(
                "audit_every is set but device_resident is off: the "
                "integrity audit covers the persistent device mirror, "
                "so ZERO audits will run",
                RuntimeWarning,
                stacklevel=2,
            )
        #: True when this service came from restore() via the warm
        #: manifest path (False: fresh start or cold replay fallback)
        self.restored_warm = False
        self.max_tasks_per_pu = max_tasks_per_pu
        # Bidirectional id maps (reference :44-62).
        self.pod_to_task: Dict[str, int] = {}
        self.task_to_pod: Dict[int, str] = {}
        self.node_to_machine: Dict[str, int] = {}
        self.machine_to_node: Dict[int, str] = {}
        # One job shelters every pod-task (reference :118, :241-257).
        self.job_id = rand_uint64()
        self.old_bindings: Dict[int, int] = {}
        self.round_latencies_s: list = []
        self.noop_rounds = 0
        #: whether the runnable backlog may need a re-solve on a quiet
        #: poll (set by NOOP rounds and heartbeat evictions; cleared by
        #: a successful solve) — run() consults it so steady-state idle
        #: polls cost a sweep, not a full MCMF solve
        self.backlog_dirty = False
        # Persistent attribution marks: faults/retries can fire between
        # rounds (e.g. at batch-poll time, before run_round is entered),
        # and must land in the NEXT round's record, never vanish.
        self._fault_mark: Dict[str, int] = (
            injector.snapshot() if injector is not None else {}
        )
        self._api_stats_mark: Dict[str, int] = (
            api.stats() if hasattr(api, "stats") else {}
        )

    # -- topology ---------------------------------------------------------

    def add_node(self, node: NodeEvent) -> None:
        machine = add_machine(
            self.scheduler,
            self.resource_map,
            self.root,
            num_cores=node.num_cores,
            pus_per_core=node.pus_per_core,
            task_capacity_per_pu=self.max_tasks_per_pu,
            machine_index=len(self.node_to_machine),
        )
        machine.resource_desc.capacity.net_bw = node.net_bw_capacity
        mid = resource_id_from_string(machine.resource_desc.uuid)
        self.node_to_machine[node.node_id] = mid
        self.machine_to_node[mid] = node.node_id
        # fresh capacity: wake the quiet-channel loop for a re-solve —
        # waiting unbound pods must not starve until a new pod arrives
        if self._has_unbound_pods():
            self.backlog_dirty = True

    def init_topology(
        self,
        fake_machines: int = 0,
        node_batch_timeout_s: float = 2.0,
        cores_per_machine: int = 1,
        pus_per_core: int = 1,
    ) -> int:
        """Fabricate machines (-fakeMachines, reference :191-202) or poll
        the control plane for nodes (:206-238)."""
        if fake_machines > 0:
            for i in range(fake_machines):
                self.add_node(
                    NodeEvent(
                        node_id=f"fake_node_{i}",
                        num_cores=cores_per_machine,
                        pus_per_core=pus_per_core,
                    )
                )
            return fake_machines
        nodes = self.api.get_node_batch(node_batch_timeout_s)
        for node in nodes:
            self.add_node(node)
        return len(nodes)

    def enable_heartbeats(
        self,
        machine_timeout_s: float = 30.0,
        task_timeout_s: float = 60.0,
        clock=None,
    ) -> HeartbeatMonitor:
        """Attach a HeartbeatMonitor; run_round then sweeps it every
        round and cleans the node maps for machines it expires."""
        self.monitor = HeartbeatMonitor(
            self.scheduler,
            machine_timeout_s=machine_timeout_s,
            task_timeout_s=task_timeout_s,
            clock=clock,
        )
        return self.monitor

    def _has_unbound_pods(self) -> bool:
        """Known pods whose tasks hold no binding — the backlog fresh
        node capacity may now admit. O(live pods): fine on the rare
        node-arrival path, too hot for per-completion use."""
        bound = self.scheduler.task_bindings
        return any(tid not in bound for tid in self.pod_to_task.values())

    def _forget_machine(self, machine_rid: int) -> None:
        """Drop a lost machine from the node↔machine maps (the scheduler
        side was already deregistered by the heartbeat sweep)."""
        node_id = self.machine_to_node.pop(machine_rid, None)
        if node_id is not None and self.node_to_machine.get(node_id) == machine_rid:
            del self.node_to_machine[node_id]

    def complete_pod(self, pod_id: str) -> bool:
        """Retire a pod's task through the normal completion path and
        clean the service maps. False if the pod is unknown or its task
        is not currently bound (nothing to complete)."""
        task_id = self.pod_to_task.get(pod_id)
        if task_id is None or task_id not in self.scheduler.task_bindings:
            return False
        td = self.task_map.find(task_id)
        self.scheduler.handle_task_completion(td)
        self.pod_to_task.pop(pod_id, None)
        self.task_to_pod.pop(task_id, None)
        self.old_bindings.pop(task_id, None)
        # freed capacity may admit waiting unbound pods: wake the
        # quiet-channel loop for a re-solve. Unconditional — a spurious
        # re-solve on the next quiet poll is near-free, while scanning
        # for unbound pods here would make bulk completion bursts O(n²).
        self.backlog_dirty = True
        return True

    # -- pod → task -------------------------------------------------------

    def _add_pod(self, pod: PodEvent) -> None:
        existing = self.pod_to_task.get(pod.pod_id)
        if existing is not None:
            # Re-delivered pod: keep the existing task — a duplicate
            # would double-occupy capacity — and forget the emitted
            # binding so the next round's diff re-posts it. Two causes:
            # a failed binding POST (spec unchanged), or a pod deleted
            # and re-created under the same name (the watch reconcile
            # re-surfaces it). For the latter the new spec must win:
            # refresh the descriptor, and evict any stale placement so
            # the next round reschedules under the new request.
            td = self.task_map.find(existing)
            if td is not None and (
                td.resource_request.cpu_cores,
                td.resource_request.net_bw,
                int(td.task_type),
            ) != (pod.cpu_request, pod.net_bw_request, pod.task_class):
                td.resource_request.cpu_cores = pod.cpu_request
                td.resource_request.net_bw = pod.net_bw_request
                td.task_type = type(td.task_type)(pod.task_class)
                rid = self.scheduler.task_bindings.get(existing)
                if rid is not None:
                    rs = self.resource_map.find(rid)
                    self.scheduler.handle_task_eviction(td, rs.descriptor)
            self.old_bindings.pop(existing, None)
            return
        td = add_task_to_job(self.job_id, self.job_map, self.task_map, name=pod.pod_id)
        td.resource_request.cpu_cores = pod.cpu_request
        td.resource_request.net_bw = pod.net_bw_request
        td.task_type = type(td.task_type)(pod.task_class)
        # Leave state CREATED: the scheduler's runnable-task computation
        # promotes CREATED→RUNNABLE and registers the task (reference:
        # flowscheduler/scheduler.go:487-529).
        self.pod_to_task[pod.pod_id] = td.uid
        self.task_to_pod[td.uid] = pod.pod_id

    def _find_parent_machine(self, pu_rid: int) -> Optional[int]:
        """Walk a PU up the topology to its machine (reference :379-398)."""
        rs = self.resource_map.find(pu_rid)
        while rs is not None:
            if resource_id_from_string(rs.descriptor.uuid) in self.machine_to_node:
                return resource_id_from_string(rs.descriptor.uuid)
            if not rs.topology_node.parent_id:
                return None
            rs = self.resource_map.find(resource_id_from_string(rs.topology_node.parent_id))
        return None

    # -- the main loop ----------------------------------------------------

    def _collect_bindings(self) -> List[Binding]:
        """Diff the scheduler's bindings against what was last emitted
        and translate new/changed ones into pod→node bindings."""
        new_bindings = self.scheduler.get_task_bindings()
        out = []
        for task_id, pu_rid in new_bindings.items():
            if self.old_bindings.get(task_id) == pu_rid:
                continue
            machine_rid = self._find_parent_machine(pu_rid)
            if machine_rid is None:
                continue
            pod_id = self.task_to_pod.get(task_id)
            if pod_id is None:
                continue
            out.append(Binding(pod_id=pod_id, node_id=self.machine_to_node[machine_rid]))
        self.old_bindings = dict(new_bindings)
        return out

    def flush_pending_bindings(self) -> int:
        """POST the previous pipelined round's bindings. Called inside
        the next round's dispatch window (so the HTTP round-trips
        overlap the in-flight solve), by idle sweeps (a quiet channel
        must not strand the last active round's POSTs), and by
        run()/save_checkpoint at loop exit so no binding is ever left
        unposted. A failed POST restores the batch for retry at the
        next flush point instead of dropping it."""
        out, self._pending_bindings = self._pending_bindings, []
        if out:
            try:
                with span("bindings_post", n=len(out)):
                    self.api.assign_bindings(out)
            except BaseException:
                self._pending_bindings = out + self._pending_bindings
                raise
        return len(out)

    def run_once(self, pods) -> int:
        """One iteration of the reference loop body (:120-187). Returns
        the number of new bindings pushed (queued, in pipeline mode)."""
        for pod in pods:
            self._add_pod(pod)
        jd = self.job_map.find(self.job_id)
        if jd is not None:
            self.scheduler.add_job(jd)
        if self.pipeline:
            return self._run_once_pipelined()
        t0 = time.perf_counter()
        self.scheduler.schedule_all_jobs()
        self.round_latencies_s.append(time.perf_counter() - t0)
        out = self._collect_bindings()
        if out:
            self.api.assign_bindings(out)
        return len(out)

    def _run_once_pipelined(self) -> int:
        """The double-buffered round body: dispatch this round's solve,
        post the PREVIOUS round's bindings while the device crunches,
        then synchronize/decode/apply and queue this round's bindings
        for the next dispatch window. On a rung failure the ladder
        completes the round synchronously inside finish_scheduling
        (runtime/degrade.py solve_async/complete), and LadderExhausted
        propagates to run_round's NOOP backstop exactly as in the
        synchronous loop."""
        t0 = time.perf_counter()
        token = self.scheduler.schedule_all_jobs_async()
        # overlap window: the in-flight solve hides these POSTs. A
        # POST failure must not leave the dispatched round in flight
        # (every later event handler would refuse forever), so the
        # round is synchronized first and the error re-raised after —
        # with the batch already restored for retry by flush itself.
        flush_err = None
        try:
            self.flush_pending_bindings()
        except BaseException as e:  # noqa: BLE001 — re-raised below;
            # BaseException on purpose: a KeyboardInterrupt landing in
            # the POST must still let the dispatched round synchronize,
            # or the in-flight latch wedges every later event handler
            flush_err = e
        try:
            if token is not None:
                self.scheduler.finish_scheduling()
            else:
                self.scheduler.last_timing = RoundTiming()
        except BaseException as finish_err:
            # the flush error outranks the finish error (a Ctrl-C in
            # the POST must not be swallowed by a LadderExhausted that
            # run_round's NOOP backstop would absorb); the finish
            # failure rides along as the cause
            if flush_err is not None:
                raise flush_err from finish_err
            raise
        self.round_latencies_s.append(time.perf_counter() - t0)
        out = self._collect_bindings()
        self._pending_bindings.extend(out)
        if flush_err is not None:
            raise flush_err
        return len(out)

    def run_round(
        self, pods, now: Optional[float] = None, solve: bool = True
    ) -> int:
        """One hardened round: run_once under the deadline watchdog with
        the degradation ladder's NOOP backstop, then a heartbeat sweep,
        then trace attribution (faults / retries / degradations /
        expiries → this round's RoundRecord). ``now`` is the heartbeat
        sweep's injected clock (the chaos soak drives logical time).

        ``solve=False`` is the idle sweep: heartbeat check + trace
        attribution only, no graph rebuild/solve — run() uses it on
        quiet polls while the backlog is clean, so a steady-state
        service costs a sweep per batch timeout, not a full MCMF
        solve. Recorded with ``solver_rung`` -1 and ``noop_round``
        False (a NOOP is a *failed* solve; this is a skipped one).

        With a span tracer and flight recorder attached, the whole
        round runs under a ``service_round`` span and the round's
        record + span slice are deposited in the flight ring (which
        auto-dumps on a deadline miss or NOOP round)."""
        span_mark = self.span_tracer.mark() if self.span_tracer is not None else 0
        span_args = dict(pods=len(pods), solve=solve)
        if self.tenant:
            span_args["tenant"] = self.tenant
        rec = None
        with span("service_round", **span_args):
            rec, bound = self._run_round_body(pods, now, solve)
        self._note_flight(rec, span_mark)
        return bound

    def _note_flight(self, rec, span_mark: int, span_prefix=None) -> None:
        if self.flight is not None and rec is not None:
            events = (
                self.span_tracer.events_since(span_mark)
                if self.span_tracer is not None
                else None
            )
            if span_prefix:
                events = list(span_prefix) + (events or [])
            self.flight.note_round(rec, events)

    def _run_round_body(self, pods, now, solve):
        deg_mark = self.ladder.degradations_total if self.ladder is not None else 0
        noop = False
        bound = 0
        deadline_miss = False
        if solve:
            with self.watchdog as wd:
                try:
                    bound = self.run_once(pods)
                except LadderExhausted as e:
                    # Every rung failed: keep the previous assignments
                    # and carry on — the backlog stays runnable and the
                    # next round retries from the configured rung.
                    noop = True
                    self.noop_rounds += 1
                    self.scheduler.last_timing = RoundTiming()
                    warnings.warn(
                        f"NOOP round (previous assignments kept): {e}",
                        RuntimeWarning,
                        stacklevel=2,
                    )
            deadline_miss = wd.fired
        else:
            # no solve ran: keep stale phase timings out of the trace
            self.scheduler.last_timing = RoundTiming()
            # a quiet channel must not strand the last active round's
            # deferred POSTs: with no next dispatch window coming, the
            # idle sweep IS the flush point (pipeline mode only; the
            # list is always empty otherwise)
            self.flush_pending_bindings()
        rec = self._round_accounting(noop, bound, deadline_miss, now, solve, deg_mark)
        return rec, bound

    # -- split rounds: the multi-tenant loop's dispatch/complete seam ------

    def dispatch_round(self, pods) -> bool:
        """Phase A of a SPLIT round (ksched_tpu/tenancy): ingest the pod
        batch and DISPATCH the solve without synchronizing, so the
        multi-tenant loop can dispatch every cell, flush the shared
        stacked batch ONCE, and only then complete each cell. The
        watchdog starts here and stops in complete_round, so the
        per-tenant deadline covers the cell's whole round (its own
        phases plus its share of the batched-solve window). Returns
        True when a solve was dispatched (runnable work existed)."""
        if self._split is not None:
            raise RuntimeError("a split round is already in flight; call complete_round first")
        st = {
            "deg_mark": self.ladder.degradations_total if self.ladder is not None else 0,
            "t0": time.perf_counter(),
            "pods": len(pods),
        }
        self.watchdog.__enter__()
        try:
            for pod in pods:
                self._add_pod(pod)
            jd = self.job_map.find(self.job_id)
            if jd is not None:
                self.scheduler.add_job(jd)
            st["token"] = self.scheduler.schedule_all_jobs_async()
        except BaseException:
            self.watchdog.__exit__(*sys.exc_info())
            raise
        self._split = st
        return st["token"] is not None

    def complete_round(
        self,
        now: Optional[float] = None,
        span_mark: int = 0,
        span_prefix=None,
    ) -> int:
        """Phase B of a split round: synchronize the lane solve, apply
        deltas, queue/post this round's bindings, then the same
        heartbeat sweep + trace attribution as run_round (a failed
        ladder becomes a NOOP round exactly as in the synchronous
        loop). ``span_mark`` scopes the flight-ring span slice to this
        phase (pass a mark taken at its start); ``span_prefix`` carries
        the cell's OWN dispatch-phase events — in a multiplexed round
        the wall-clock window between a cell's dispatch and complete
        contains every other cell's spans, which must not leak into a
        tenant-scoped flight dump."""
        if self._split is None:
            raise RuntimeError("no split round in flight; call dispatch_round first")
        st, self._split = self._split, None
        noop = False
        bound = 0
        try:
            try:
                if st["token"] is not None:
                    self.scheduler.finish_scheduling()
                else:
                    self.scheduler.last_timing = RoundTiming()
            except LadderExhausted as e:
                noop = True
                self.noop_rounds += 1
                self.scheduler.last_timing = RoundTiming()
                warnings.warn(
                    f"NOOP round (previous assignments kept): {e}",
                    RuntimeWarning,
                    stacklevel=2,
                )
        finally:
            self.watchdog.__exit__(*sys.exc_info())
        deadline_miss = self.watchdog.fired
        self.round_latencies_s.append(time.perf_counter() - st["t0"])
        if not noop:
            out = self._collect_bindings()
            if self.pipeline:
                # per-tenant dispatch window: the POSTs ride the NEXT
                # round's batched-solve window (cell.post_window)
                self._pending_bindings.extend(out)
            elif out:
                self.api.assign_bindings(out)
            bound = len(out)
        # a round with no runnable work (token None) dispatched no
        # solve: record it as an idle sweep (solver_rung -1, zeroed
        # phase timings EXCLUDED from latency percentiles), not as a
        # solved round whose all-zero timings would drag a lightly
        # loaded tenant's published p50 toward zero
        rec = self._round_accounting(
            noop, bound, deadline_miss, now, st["token"] is not None,
            st["deg_mark"],
        )
        self._note_flight(rec, span_mark, span_prefix)
        return bound

    def _round_accounting(self, noop, bound, deadline_miss, now, solve, deg_mark):
        """The post-solve tail every round shape shares (run_round's
        body and the split complete_round): heartbeat sweep, backlog
        flag maintenance, service gauges, and the round's trace record
        with fault/retry/degradation attribution."""
        lost: List[int] = []
        failed: List[int] = []
        if self.monitor is not None:
            lost, failed = self.monitor.check(now)
            for rid in lost:
                self._forget_machine(rid)
        # NOOP rounds and evictions leave runnable work behind; a clean
        # full solve clears it. An idle sweep must not clear the flag —
        # it did not schedule anything.
        if noop or lost or failed:
            self.backlog_dirty = True
        elif solve:
            self.backlog_dirty = False
        self._g_pods.set(len(self.pod_to_task))
        self._g_bound.set(len(self.scheduler.task_bindings))
        self._g_machines.set(len(self.node_to_machine))
        # a state divergence this round already deposited its
        # structured soltel event; make sure a flight dump carries it
        # (rate-limited by the recorder, like the other triggers). The
        # flag is CONSUMED here — idle sweeps never run the gate, so a
        # stale flag would re-trigger dumps for a long-repaired event.
        sol = self.scheduler.solver
        if getattr(sol, "last_divergence", None):
            if self.flight is not None:
                self.flight.trigger("state_divergence")
            sol.last_divergence = None
        rec = None
        if self.tracer is not None:
            faults = {}
            if self.injector is not None:
                snap = self.injector.snapshot()
                faults = delta_counters(self._fault_mark, snap)
                self._fault_mark = snap
            api_stats = self.api.stats() if hasattr(self.api, "stats") else {}
            # Only retry/re-post counters belong in `retries`; the stats
            # surface also carries drop counters (binding_drops), which
            # are a different signal and would silently inflate it.
            retries = sum(
                api_stats.get(k, 0) - self._api_stats_mark.get(k, 0)
                for k in RETRY_STAT_KEYS
            )
            self._api_stats_mark = api_stats
            rec = self.tracer.record_flow_round(
                self.scheduler,
                bound,
                # idle sweeps must not re-report the previous solve's
                # graph-delta stats and solver work (a NOOP round's
                # graph update DID run, so it still reports)
                solved=solve,
                extra=dict(
                    faults_injected=faults,
                    retries=retries,
                    degradations=(
                        self.ladder.degradations_total - deg_mark
                        if self.ladder is not None
                        else 0
                    ),
                    solver_rung=(
                        -1 if (noop or not solve)
                        else (self.ladder.last_rung if self.ladder is not None else 0)
                    ),
                    noop_round=noop,
                    deadline_miss=deadline_miss,
                    machines_lost=len(lost),
                    tasks_failed=len(failed),
                    tenant=self.tenant,
                ),
            )
        return rec

    def run(self, pod_batch_timeout_s: float = 2.0, max_rounds: Optional[int] = None) -> None:
        """The hardened main loop. Exits only when the control plane is
        actually closed; an empty batch with the channel still open —
        the signature of a transient API-server outage (or plain quiet)
        — idles through a sweep-only round instead of exiting, so the
        scheduler rides out outages and still detects silent machines
        while no pods arrive. Idle rounds do not count against
        ``max_rounds`` (which counts scheduling rounds, as before)."""
        rounds = 0
        tick = 0  # injector rounds: one per loop iteration, idle or not
        while max_rounds is None or rounds < max_rounds:
            if self.injector is not None:
                # `tick`, not `rounds`: an idle round is still one full
                # pass (poll + run_round), so outage windows must count
                # down and fault draws advance exactly once per
                # iteration — re-passing a stale index would re-roll the
                # same round's draws every poll during an outage.
                self.injector.begin_round(tick)
            tick += 1
            pods = self.api.poll_pod_batch(pod_batch_timeout_s)
            if not pods:
                if self.api.is_closed():
                    break  # control plane closed: clean shutdown
                # Transient outage / quiet channel: sweep-only idle
                # round — unless a NOOP round or an eviction left
                # runnable backlog behind, in which case this quiet
                # poll is the moment to re-solve it.
                self.run_round([], solve=self.backlog_dirty)
                continue
            self.run_round(pods)
            rounds += 1
        # pipelined loops defer each round's POSTs into the next
        # dispatch window; the last round's must not be stranded
        self.flush_pending_bindings()

    # -- service checkpoint (scheduler state + the id maps) ----------------

    def save_checkpoint(self, path: str) -> None:
        """Snapshot the service: the scheduler's world state (via
        runtime/checkpoint.py, written to ``path + ".sched"``) plus the
        service-owned id maps and round bookkeeping as a sidecar at
        ``path`` — everything a restarted process needs to keep serving
        the same pods against the same nodes. Additionally writes the
        WARM manifest at ``path + ".wal"`` (journal WAL + device-state
        manifest + solver warm endpoints + ladder counters) so
        restore() can resume on the delta-sized warm path instead of
        the cold full_build; a damaged/missing manifest degrades
        restore to the cold event replay, never blocks it."""
        import os

        from .runtime.checkpoint import (
            atomic_pickle,
            save_scheduler,
            save_warm_manifest,
        )

        # bindings queued for the next pipelined dispatch window would
        # not survive the restart; post them before snapshotting
        self.flush_pending_bindings()
        save_scheduler(self.scheduler, path + ".sched")
        # per-CHECKPOINT nonce binding sidecar <-> warm manifest: the
        # job_id is a service-lifetime constant, so it cannot tell a
        # stale .wal (from an earlier save to the same path) apart
        # from this save's. Drawn OUTSIDE the seeded id stream — a
        # seeded draw here would shift every later task uid and break
        # kills-vs-control placement parity in the recovery soak.
        nonce = int.from_bytes(os.urandom(8), "little")
        state = {
            "version": SERVICE_CHECKPOINT_VERSION,
            "ckpt_nonce": nonce,
            "pod_to_task": dict(self.pod_to_task),
            "node_to_machine": dict(self.node_to_machine),
            "job_id": self.job_id,
            "old_bindings": dict(self.old_bindings),
            "max_tasks_per_pu": self.max_tasks_per_pu,
            # round/ladder continuity (the restart-budget/quarantine
            # counters of the manifest; per-tenant via `tenant`)
            "tenant": self.tenant,
            "noop_rounds": self.noop_rounds,
            "degradations_total": (
                self.ladder.degradations_total if self.ladder is not None else 0
            ),
            "backlog_dirty": self.backlog_dirty,
            "audit_every": self.audit_every,
        }
        atomic_pickle(state, path)
        try:
            save_warm_manifest(
                self.scheduler,
                path + ".wal",
                # the nonce binds the manifest to THIS sidecar: restore
                # refuses a stale .wal left by an earlier checkpoint
                # at the same path (job_id rides along for operators)
                meta={
                    "tenant": self.tenant,
                    "job_id": int(self.job_id),
                    "ckpt_nonce": nonce,
                },
            )
        except Exception as e:  # noqa: BLE001 — warm restore is an
            # optimization; an unpicklable cost model (or any manifest
            # writer defect) must not take checkpointing down with it.
            # A PREVIOUS checkpoint's manifest at this path must not
            # survive either: restore would pair the old scheduler
            # state with the new sidecar's id maps.
            try:
                os.remove(path + ".wal")
            except OSError:
                pass
            warnings.warn(
                f"warm manifest not written ({e}); restore will use the "
                "cold event replay",
                RuntimeWarning,
                stacklevel=2,
            )

    @classmethod
    def restore(
        cls,
        api: ClusterAPI,
        path: str,
        cost_model: CostModelType = CostModelType.TRIVIAL,
        backend=None,
        backend_name: str = "configured",
        degrade: bool = True,
        injector: Optional[FaultInjector] = None,
        tracer: Optional[RoundTracer] = None,
        round_deadline_s: float = 0.0,
        flight: Optional[FlightRecorder] = None,
        span_tracer: Optional[SpanTracer] = None,
        pipeline: bool = False,
        device_resident: bool = False,
        audit_every: Optional[int] = None,
    ) -> "SchedulerService":
        """Rebuild a service from save_checkpoint output. With an
        intact warm manifest (``path + ".wal"``) the scheduler resumes
        WARM: the device-state manifest is replayed into a rebuilt
        DeviceGraphState/SlotPlanState, the device mirror is primed
        outside any round, and the solver's carried flow/potentials/
        endpoint masks are re-imported — the first post-restore round
        is already delta-sized and its solve warm, bit-identical to
        the never-killed process. A missing or corrupted manifest
        (torn write, dropped/duplicated WAL record, version mismatch)
        is DETECTED and contained: restore warns and falls back to the
        cold event replay. Heartbeat history never survives the
        restart — machines are unmonitored until their next beat (the
        same cold-rebuild property the reference has).

        Damaged inputs raise distinct, actionable errors: a missing or
        garbage sidecar -> CheckpointDamaged, a missing ``.sched``
        companion -> CheckpointMissing, a version mismatch ->
        CheckpointVersionError."""
        import os

        from .runtime.checkpoint import (
            CheckpointDamaged,
            CheckpointMissing,
            CheckpointVersionError,
            load_warm_manifest,
            restore_scheduler,
        )

        try:
            with open(path, "rb") as f:
                state = pickle.load(f)
        except FileNotFoundError:
            raise
        except Exception as e:  # noqa: BLE001 — classified: damaged bytes
            raise CheckpointDamaged(
                f"service checkpoint sidecar {path} is truncated or not a "
                f"ksched checkpoint ({type(e).__name__}: {e}); restore from "
                "an intact checkpoint or start cold"
            ) from e
        if not isinstance(state, dict) or "version" not in state:
            raise CheckpointDamaged(
                f"service checkpoint sidecar {path} holds no version field "
                "— not a ksched service checkpoint"
            )
        if state["version"] not in (1, SERVICE_CHECKPOINT_VERSION):
            raise CheckpointVersionError(
                f"unsupported service checkpoint version {state['version']} "
                f"(this build reads 1..{SERVICE_CHECKPOINT_VERSION}); "
                "re-checkpoint from a matching build"
            )
        if not os.path.exists(path + ".sched"):
            raise CheckpointMissing(
                f"service checkpoint {path} is missing its scheduler "
                f"companion {path + '.sched'} — the sidecar alone cannot "
                "rebuild the world state; restore both files together"
            )
        if degrade:
            backend = build_degradation_ladder(
                backend if backend is not None else ReferenceSolver(),
                backend_name,
                injector=injector,
            )
        parts = None
        restored_warm = False
        wal_fallback = None  # fallback kind when the manifest was rejected
        wal_path = path + ".wal"
        if os.path.exists(wal_path):
            try:
                parts, meta = load_warm_manifest(
                    wal_path, backend=backend, device_resident=device_resident
                )
                if meta.get("ckpt_nonce") != state.get("ckpt_nonce"):
                    raise CheckpointDamaged(
                        f"warm manifest {wal_path} belongs to a different "
                        f"checkpoint (nonce {meta.get('ckpt_nonce')} != "
                        f"sidecar {state.get('ckpt_nonce')}) — a stale "
                        ".wal from an earlier save at this path"
                    )
                restored_warm = True
            except Exception as e:  # noqa: BLE001 — contained: any
                # manifest damage or rejection degrades to the cold
                # replay; CORRUPTION (torn/dropped/duplicated/bit-rot
                # records) is labelled apart from other rejections
                # (version drift, stale nonce, unpicklable payload) so
                # an operator fleet-upgrading builds doesn't read the
                # restore counter as bit rot
                from .runtime.integrity import WALCorrupted

                parts = None
                wal_fallback = (
                    "wal_corrupt_fallback"
                    if isinstance(e, WALCorrupted)
                    else "wal_rejected_fallback"
                )
                warnings.warn(
                    f"warm manifest {wal_path} rejected ({e}); falling "
                    "back to cold event replay",
                    RuntimeWarning,
                    stacklevel=2,
                )
        if parts is None:
            parts = restore_scheduler(
                path + ".sched",
                cost_model_factory=MODEL_REGISTRY[cost_model],
                backend=backend,
                device_resident=device_resident,
            )
        # mutually exclusive kinds: one restore, one increment
        obs_metrics.get_registry().counter(
            "ksched_restore_total",
            "service restores by path taken",
            labelnames=("kind",),
        ).labels(
            kind="warm" if restored_warm else (wal_fallback or "cold")
        ).inc()
        svc = cls(
            api,
            max_tasks_per_pu=state["max_tasks_per_pu"],
            cost_model=cost_model,
            degrade=False,
            injector=injector,
            tracer=tracer,
            round_deadline_s=round_deadline_s,
            flight=flight,
            span_tracer=span_tracer,
            pipeline=pipeline,
            device_resident=device_resident,
            tenant=state.get("tenant", ""),
            audit_every=(
                audit_every if audit_every is not None
                else state.get("audit_every", 0)
            ),
            _restored=parts,
        )
        svc.restored_warm = restored_warm
        svc.job_id = state["job_id"]
        svc.old_bindings = dict(state["old_bindings"])
        # counters ride the sidecar (v2): ladder/NOOP continuity
        svc.noop_rounds = state.get("noop_rounds", 0)
        if svc.ladder is not None:
            svc.ladder.degradations_total = state.get("degradations_total", 0)
        # Warm restores carry the exact pre-kill backlog flag; a cold
        # replay assumes dirty so the first quiet poll re-solves
        # anything a pre-kill NOOP round or eviction left runnable.
        svc.backlog_dirty = state.get("backlog_dirty", True) if restored_warm else True
        # only tasks that still exist ride along (completed pods whose
        # descriptors were dropped must not resurrect map entries)
        for pod_id, task_id in state["pod_to_task"].items():
            if svc.task_map.find(task_id) is not None:
                svc.pod_to_task[pod_id] = task_id
                svc.task_to_pod[task_id] = pod_id
        for node_id, mid in state["node_to_machine"].items():
            if svc.resource_map.find(mid) is not None:
                svc.node_to_machine[node_id] = mid
                svc.machine_to_node[mid] = node_id
        return svc


def _podgen_transient(e: Exception) -> bool:
    """Transient control-plane errors podgen retries: 5xx (rides in as
    HTTPError) and transport failures — URLError, ConnectionError, and
    TimeoutError are all OSError subclasses, so OSError is the whole
    net. Everything else (auth errors, schema rejections) is fatal."""
    if isinstance(e, urllib.error.HTTPError):
        return e.code >= 500
    return isinstance(e, OSError)


def podgen(
    api: ClusterAPI,
    num_pods: int,
    net_bw: int = 0,
    retry_budget: int = 4,
    backoff: Optional[ExpBackoff] = None,
) -> None:
    """Load generator (reference: cmd/podgen/podgen.go:34-74). Against
    an HTTP control plane, pods are created via the API server (as the
    reference's podgen does); against the synthetic one, enqueued
    directly.

    One transient 500 must not take the whole control plane down:
    transient create failures are retried with exponential backoff
    under a budget; only a fatal error (4xx, or a spent budget) warns
    and closes the API — which unblocks get_pod_batch, since the
    remaining pods will never arrive."""
    backoff = backoff or ExpBackoff(max_retries=retry_budget)
    i = 0
    try:
        while i < num_pods:
            try:
                if hasattr(api, "create_pod"):
                    api.create_pod(f"pod_{i}", net_bw_request=net_bw)
                else:
                    api.submit_pod(PodEvent(pod_id=f"pod_{i}", net_bw_request=net_bw))
            except Exception as e:  # noqa: BLE001 — classified below
                delay = backoff.next_delay() if _podgen_transient(e) else None
                if delay is None:
                    raise
                warnings.warn(
                    f"podgen: transient create_pod failure ({e}); retrying",
                    RuntimeWarning,
                    stacklevel=2,
                )
                time.sleep(delay)
                continue
            backoff.reset()
            i += 1
    except Exception as e:  # noqa: BLE001 — runs in a daemon thread
        warnings.warn(
            f"podgen failed fatally after {i}/{num_pods} pods: {e}; "
            "closing the control plane",
            RuntimeWarning,
            stacklevel=2,
        )
        api.close()


def _run_multi_tenant(args, span_tracer, metrics_server) -> int:
    """--tenants N: the scheduler-as-a-service demo path — N synthetic
    cells multiplexed through one warm batched solver (tenancy/)."""
    from .tenancy import MultiTenantService

    tenants = args.tenants
    mts = MultiTenantService(
        round_deadline_s=args.round_deadline,
        pipeline=args.pipeline,
        device_resident=args.device_resident,
        flight_dir=args.flight_dir,
        flight_capacity=args.flight_capacity,
        span_tracer=span_tracer,
    )
    per_cell = max(1, args.podgen // tenants) if args.podgen > 0 else 0
    try:
        for i in range(tenants):
            cell = mts.add_tenant(
                f"cell{i}",
                machines=args.num_machines,
                pus_per_core=args.pus_per_core,
                slots=args.max_tasks_per_pu,
                seed=1000 + i,
                machine_timeout_s=args.machine_timeout,
            )
            for j in range(per_cell):
                cell.api.submit_pod(PodEvent(pod_id=f"cell{i}_pod_{j}"))
        print(
            f"tenancy: {tenants} cells x {args.num_machines} machines, "
            f"{per_cell} pods each",
            file=sys.stderr,
        )
        rounds = 0
        while rounds < 512:
            mts.run_round(now=float(rounds))
            rounds += 1
            if per_cell and all(
                len(c.svc.scheduler.task_bindings) >= min(
                    per_cell,
                    args.num_machines * args.pus_per_core * args.max_tasks_per_pu,
                )
                for c in mts.cells.values()
            ):
                break
            if not per_cell and rounds >= 8:
                break
        mts.drain()
        for tid, summary in sorted(mts.tenant_summary().items()):
            bound = len(mts.cells[tid].svc.scheduler.task_bindings)
            print(
                f"{tid}: bound={bound} p50={summary.get('p50_ms', 0):.2f}ms "
                f"p99={summary.get('p99_ms', 0):.2f}ms",
                file=sys.stderr,
            )
        print(
            f"tenancy: {rounds} rounds, "
            f"{mts.batcher.flushes} batch flushes, last round "
            f"{mts.batcher.last_groups} stacked program(s) for "
            f"{mts.batcher.last_lanes} lanes",
            file=sys.stderr,
        )
        return 0
    finally:
        mts.close()
        if span_tracer is not None:
            span_tracer.uninstall()
            if args.trace_out:
                span_tracer.dump(args.trace_out)
        if metrics_server is not None:
            metrics_server.stop()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ksched-tpu", description="TPU-native flow-network cluster scheduler"
    )
    # Flag surface mirrors cmd/k8sscheduler/scheduler.go:31-42.
    ap.add_argument("--max-tasks-per-pu", "-mt", type=int, default=1000)
    ap.add_argument("--pod-batch-timeout", "-pbt", type=float, default=2.0)
    ap.add_argument("--node-batch-timeout", "-nbt", type=float, default=2.0)
    ap.add_argument("--pod-chan-size", "-pcs", type=int, default=5000)
    ap.add_argument("--fake-machines", action="store_true")
    ap.add_argument("--num-machines", "-nm", type=int, default=10)
    ap.add_argument("--cores-per-machine", type=int, default=1)
    ap.add_argument("--pus-per-core", type=int, default=1)
    ap.add_argument(
        "--cost-model",
        choices=[m.name.lower() for m in CostModelType],
        default="trivial",
    )
    ap.add_argument(
        "--backend", choices=["ref", "native", "jax", "ell", "auto"],
        default="native",
        help="MCMF backend (native C++ is the CPU production default; "
        "auto = per-solve dense-vs-CSR dispatch, solver/graph_collapse.py)",
    )
    ap.add_argument("--podgen", type=int, default=0, metavar="N",
                    help="generate N pods in-process (cmd/podgen equivalent)")
    ap.add_argument("--round-deadline", type=float, default=0.0, metavar="S",
                    help="per-round watchdog deadline in seconds (0 = off): "
                    "a round running past it warns and is recorded as a miss")
    ap.add_argument("--no-degrade", action="store_true",
                    help="disable the solver degradation ladder (a solver "
                    "failure then crashes the round, as the reference does)")
    ap.add_argument("--machine-timeout", type=float, default=0.0, metavar="S",
                    help="enable heartbeat-driven machine failure detection "
                    "with this timeout (0 = off); sweeps run every round")
    ap.add_argument("--one-shot", action="store_true",
                    help="exit once the pod queue is drained")
    ap.add_argument("--tenants", type=int, default=0, metavar="N",
                    help="multi-tenant mode: serve N independent synthetic "
                    "cells from this one warm process (ksched_tpu/tenancy; "
                    "--num-machines/--max-tasks-per-pu apply per cell, "
                    "--podgen pods are split across cells); prints "
                    "per-tenant p50/p99 on exit")
    ap.add_argument("--pipeline", action="store_true",
                    help="double-buffered rounds: dispatch the solve, "
                    "post the previous round's bindings while it is in "
                    "flight, then synchronize/decode (docs/round_pipeline"
                    ".md); placements are bit-identical to the "
                    "synchronous loop")
    ap.add_argument("--device-resident", action="store_true",
                    help="keep the flow problem's arrays live on device "
                    "between rounds: after the first full upload only "
                    "packed delta records cross the host/device boundary "
                    "(graph/device_export.DeviceResidentState)")
    ap.add_argument("--audit-every", type=int, default=0, metavar="N",
                    help="device-state integrity audit cadence: every Nth "
                    "round, fingerprint the persistent device buffers "
                    "against the host journal truth and repair divergence "
                    "through the escalating ladder "
                    "(ksched_state_audits_total{result}; 0 = off; "
                    "requires --device-resident — there is no persistent "
                    "mirror to audit otherwise; runtime/integrity.py)")
    # -- observability (ksched_tpu/obs; docs/observability.md) ----------
    ap.add_argument("--metrics-port", type=int, default=None, metavar="PORT",
                    help="serve Prometheus text on /metricsz (+ /healthz, "
                    "/varz) from this port (0 = ephemeral; off by default)")
    ap.add_argument("--obs-dump", metavar="PATH", default=None,
                    help="write the metrics-registry snapshot as JSON on exit")
    ap.add_argument("--trace-out", metavar="PATH", default=None,
                    help="record spans and write a Chrome/Perfetto "
                    "trace-event JSON on exit")
    ap.add_argument("--round-trace", metavar="PATH", default=None,
                    help="write the per-round RoundRecord JSONL on exit")
    ap.add_argument("--flight-dir", metavar="DIR", default=None,
                    help="enable the crash flight recorder: ring of the "
                    "last --flight-capacity rounds, auto-dumped into DIR "
                    "on deadline miss / NOOP round / crash")
    ap.add_argument("--flight-capacity", type=int, default=64)
    ap.add_argument("--devprof-capture", type=int, default=0, metavar="N",
                    help="capture a jax.profiler trace around the Nth "
                    "solve (0 = off)")
    ap.add_argument("--devprof-dir", metavar="DIR", default="./jax_profile")
    ap.add_argument("--no-obs", action="store_true",
                    help="disable the metrics registry entirely (null "
                    "registry; spans still time RoundTiming)")
    ap.add_argument(
        "--api-server", metavar="URL", default=None,
        help="schedule against a control plane over HTTP (the reference's "
        "-addr; see cluster/http_api.py) instead of the in-process "
        "synthetic API; --podgen then posts pods to the server",
    )
    args = ap.parse_args(argv)
    if args.one_shot and args.podgen <= 0:
        ap.error("--one-shot needs --podgen N: the pod wait blocks until a first pod arrives")
    if args.audit_every and not args.device_resident:
        ap.error(
            "--audit-every audits the persistent device mirror; without "
            "--device-resident there is nothing to audit (zero audits "
            "would run silently)"
        )
    if args.no_obs and (args.metrics_port is not None or args.obs_dump):
        ap.error(
            "--no-obs disables the metrics registry; --metrics-port/--obs-dump "
            "would serve/dump nothing (spans and --round-trace still work)"
        )

    # An operator SIGTERM must exit through main's finally so the
    # dump-on-exit artifacts (--obs-dump/--trace-out/--round-trace)
    # still land; default SIGTERM disposition would drop them.
    import signal

    signal.signal(signal.SIGTERM, lambda signum, frame: sys.exit(143))

    from .solver.select import make_backend

    backend = make_backend(args.backend)

    # -- observability setup (before any instrumented object resolves
    # its metric handles) ------------------------------------------------
    if args.no_obs:
        obs_metrics.set_enabled(False)
    metrics_server = None
    if args.metrics_port is not None:
        from .obs.exporter import MetricsServer

        metrics_server = MetricsServer(port=args.metrics_port)
        print(f"metrics: {metrics_server.url}/metricsz", file=sys.stderr)
    # the flight recorder needs a tracer too: its dumps carry each
    # round's span slice (and double as Perfetto traces)
    span_tracer = (
        SpanTracer().install() if (args.trace_out or args.flight_dir) else None
    )
    # flight-only services need records but not the whole history:
    # bound the tracer at the ring size so a weeks-long run does not
    # accumulate records nothing will ever dump. In --tenants mode the
    # multi-tenant service builds PER-TENANT tracers/recorders under
    # tenant-scoped registry views; constructing unscoped ones here
    # first would register the same family names without the tenant
    # label and the scoped views would (correctly) refuse to alias them
    tracer = None
    if args.round_trace and not args.tenants:
        tracer = RoundTracer()
    elif args.flight_dir and not args.tenants:
        tracer = RoundTracer(capacity=args.flight_capacity)
    flight = None
    if args.flight_dir and not args.tenants:
        flight = FlightRecorder(
            capacity=args.flight_capacity, dump_dir=args.flight_dir
        )
        flight.install_crash_hook()
    if args.devprof_capture > 0:
        from .obs.devprof import DeviceProfiler, set_profiler

        set_profiler(
            DeviceProfiler(
                capture_solve=args.devprof_capture, capture_dir=args.devprof_dir
            )
        )

    if args.tenants > 0:
        return _run_multi_tenant(args, span_tracer, metrics_server)

    if args.api_server:
        from .cluster.http_api import HTTPClusterAPI

        api = HTTPClusterAPI(
            args.api_server,
            pod_chan_size=args.pod_chan_size,
            registry=obs_metrics.get_registry(),
        )
    else:
        api = SyntheticClusterAPI(pod_chan_size=args.pod_chan_size)
    svc = SchedulerService(
        api,
        max_tasks_per_pu=args.max_tasks_per_pu,
        cost_model=CostModelType[args.cost_model.upper()],
        backend=backend,
        backend_name=args.backend,
        degrade=not args.no_degrade,
        round_deadline_s=args.round_deadline,
        tracer=tracer,
        flight=flight,
        span_tracer=span_tracer,
        pipeline=args.pipeline,
        device_resident=args.device_resident,
        audit_every=args.audit_every,
    )
    if args.machine_timeout > 0:
        svc.enable_heartbeats(machine_timeout_s=args.machine_timeout)
    n = svc.init_topology(
        fake_machines=args.num_machines if args.fake_machines else 0,
        node_batch_timeout_s=args.node_batch_timeout,
        cores_per_machine=args.cores_per_machine,
        pus_per_core=args.pus_per_core,
    )
    print(f"topology: {n} machines", file=sys.stderr)

    if args.podgen > 0:
        threading.Thread(target=podgen, args=(api, args.podgen), daemon=True).start()

    try:
        if args.one_shot:
            pods = api.get_pod_batch(args.pod_batch_timeout)
            # run_round, not run_once: the hardened round is also the
            # obs publication path (RoundRecord -> tracer/registry,
            # flight ring, service gauges) — one-shot must not produce
            # empty --round-trace/--flight-dir artifacts
            bound = svc.run_round(pods) if pods else 0
            svc.flush_pending_bindings()  # pipelined one-shot: post now
            lat = svc.round_latencies_s[-1] * 1e3 if svc.round_latencies_s else 0.0
            print(
                f"scheduled {bound}/{len(pods)} pods in {lat:.2f}ms "
                f"({len(api.bindings())} total bindings)",
                file=sys.stderr,
            )
            return 0
        svc.run(pod_batch_timeout_s=args.pod_batch_timeout)
        return 0
    finally:
        api.close()
        # dump-on-exit artifacts (after close so final counters settle)
        if args.obs_dump:
            from .obs.exporter import dump_registry

            dump_registry(obs_metrics.get_registry(), args.obs_dump)
            print(f"obs: registry snapshot -> {args.obs_dump}", file=sys.stderr)
        if span_tracer is not None:
            span_tracer.uninstall()
            if args.trace_out:
                span_tracer.dump(args.trace_out)
                print(f"obs: span trace -> {args.trace_out}", file=sys.stderr)
        if args.round_trace and tracer is not None:
            tracer.dump(args.round_trace)
            print(f"obs: round trace -> {args.round_trace}", file=sys.stderr)
        if metrics_server is not None:
            metrics_server.stop()


if __name__ == "__main__":
    sys.exit(main())
