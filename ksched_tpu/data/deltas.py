"""Scheduling deltas: the round's output diff.

Reference: proto/scheduling_delta.proto:10-21. A scheduling round emits a
set of deltas (PLACE / PREEMPT / MIGRATE / NOOP) that the service layer
applies to its bindings and pushes to the cluster adapter.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass


class DeltaType(enum.IntEnum):
    PLACE = 0
    PREEMPT = 1
    MIGRATE = 2
    NOOP = 3


@dataclass(frozen=True)
class SchedulingDelta:
    type: DeltaType
    task_id: int
    resource_id: str
