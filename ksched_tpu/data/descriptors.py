"""L0 data model: task/job/resource descriptors.

TPU-native rebuild of the reference protobuf schema (reference:
proto/task_desc.proto, proto/resource_desc.proto, proto/job_desc.proto,
proto/resource_topology_node_desc.proto, proto/resource_vector.proto,
proto/whare_map_stats.proto, proto/coco_interference_scores.proto,
proto/task_final_report.proto, proto/reference_desc.proto).

We keep field-level parity for every field the scheduling logic reads
(states, spawned children, num_slots_below, current_running_tasks,
CoCo/Whare stats) and represent them as plain dataclasses: the device
solver consumes flat arrays, so the descriptor layer exists for the
host-side event API, not for wire serialization.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import List, Optional


class TaskState(enum.IntEnum):
    """Task lifecycle (reference: proto/task_desc.proto:12-22)."""

    CREATED = 0
    BLOCKING = 1
    RUNNABLE = 2
    ASSIGNED = 3
    RUNNING = 4
    COMPLETED = 5
    FAILED = 6
    ABORTED = 7
    DELEGATED = 8
    UNKNOWN = 9


class TaskType(enum.IntEnum):
    """CoCo workload classes (reference: proto/task_desc.proto:25-30)."""

    SHEEP = 0
    RABBIT = 1
    DEVIL = 2
    TURTLE = 3


class ResourceState(enum.IntEnum):
    """Resource lifecycle (reference: proto/resource_desc.proto:18-23)."""

    UNKNOWN = 0
    IDLE = 1
    BUSY = 2
    LOST = 3


class ResourceType(enum.IntEnum):
    """Resource topology node kinds (reference: proto/resource_desc.proto:25-37)."""

    PU = 0
    CORE = 1
    CACHE = 2
    NIC = 3
    DISK = 4
    SSD = 5
    MACHINE = 6
    LOGICAL = 7
    NUMA_NODE = 8
    SOCKET = 9
    COORDINATOR = 10


class JobState(enum.IntEnum):
    """Job lifecycle (reference: proto/job_desc.proto:17-24)."""

    NEW = 0
    CREATED = 1
    RUNNING = 2
    COMPLETED = 3
    FAILED = 4
    ABORTED = 5
    UNKNOWN = 6


class ReferenceType(enum.IntEnum):
    """Dataflow reference kinds (reference: proto/reference_desc.proto:16-24)."""

    TOMBSTONE = 0
    FUTURE = 1
    CONCRETE = 2
    STREAM = 3
    VALUE = 4
    ERROR = 5


class ReferenceScope(enum.IntEnum):
    """Dataflow reference visibility (reference: proto/reference_desc.proto:26-30)."""

    PUBLIC = 0
    PRIVATE = 1


@dataclass
class ResourceVector:
    """Multi-dimensional resource quantity (reference: proto/resource_vector.proto:12-19)."""

    cpu_cores: float = 0.0
    ram_bw: int = 0
    ram_cap: int = 0
    disk_bw: int = 0
    disk_cap: int = 0
    net_bw: int = 0


@dataclass
class WhareMapStats:
    """Per-machine co-location census for the Whare-Map cost model
    (reference: proto/whare_map_stats.proto:12-18)."""

    num_idle: int = 0
    num_devils: int = 0
    num_rabbits: int = 0
    num_sheep: int = 0
    num_turtles: int = 0


@dataclass
class CoCoInterferenceScores:
    """Per-class co-location penalties for the CoCo cost model
    (reference: proto/coco_interference_scores.proto:11-16)."""

    turtle_penalty: int = 0
    sheep_penalty: int = 0
    rabbit_penalty: int = 0
    devil_penalty: int = 0


@dataclass
class TaskFinalReport:
    """Post-mortem perf counters (reference: proto/task_final_report.proto:10-19)."""

    instructions: int = 0
    cycles: int = 0
    llc_refs: int = 0
    llc_misses: int = 0
    runtime: float = 0.0


@dataclass
class ReferenceDescriptor:
    """Dataflow input/output reference (reference: proto/reference_desc.proto:15-45)."""

    id: int = 0
    type: ReferenceType = ReferenceType.TOMBSTONE
    scope: ReferenceScope = ReferenceScope.PUBLIC
    non_deterministic: bool = False
    size: int = 0
    location: str = ""
    producing_task: int = 0


@dataclass
class TaskDescriptor:
    """A schedulable task (reference: proto/task_desc.proto:11-79).

    ``spawned`` forms the per-job task tree rooted at the job's root task;
    ``uid`` is a cluster-unique integer id.
    """

    uid: int = 0
    name: str = ""
    state: TaskState = TaskState.CREATED
    job_id: str = ""
    index: int = 0
    dependencies: List[ReferenceDescriptor] = field(default_factory=list)
    outputs: List[ReferenceDescriptor] = field(default_factory=list)
    binary: bytes = b""
    args: List[str] = field(default_factory=list)
    spawned: List["TaskDescriptor"] = field(default_factory=list)
    scheduled_to_resource: str = ""
    last_heartbeat_location: str = ""
    last_heartbeat_time: int = 0
    delegated_to: str = ""
    delegated_from: str = ""
    submit_time: int = 0
    start_time: int = 0
    finish_time: int = 0
    total_unscheduled_time: int = 0
    total_run_time: int = 0
    relative_deadline: int = 0
    absolute_deadline: int = 0
    port: int = 0
    input_size: int = 0
    inject_task_lib: bool = False
    resource_request: ResourceVector = field(default_factory=ResourceVector)
    priority: int = 0
    task_type: TaskType = TaskType.SHEEP
    final_report: Optional[TaskFinalReport] = None
    trace_job_id: int = 0
    trace_task_id: int = 0


@dataclass
class ResourceDescriptor:
    """A node in the resource topology (reference: proto/resource_desc.proto:18-64)."""

    uuid: str = ""
    friendly_name: str = ""
    descriptive_name: str = ""
    state: ResourceState = ResourceState.UNKNOWN
    task_capacity: int = 0
    last_heartbeat: int = 0
    type: ResourceType = ResourceType.PU
    schedulable: bool = False
    current_running_tasks: List[int] = field(default_factory=list)
    # Aggregates maintained by the graph manager / stats traversal
    # (reference: proto/resource_desc.proto:48-51).
    num_running_tasks_below: int = 0
    num_slots_below: int = 0
    available_resources: ResourceVector = field(default_factory=ResourceVector)
    reserved_resources: ResourceVector = field(default_factory=ResourceVector)
    min_available_resources_below: ResourceVector = field(default_factory=ResourceVector)
    max_available_resources_below: ResourceVector = field(default_factory=ResourceVector)
    capacity: ResourceVector = field(default_factory=ResourceVector)
    max_unavailable_resources_below: ResourceVector = field(default_factory=ResourceVector)
    whare_map_stats: WhareMapStats = field(default_factory=WhareMapStats)
    coco_interference_scores: CoCoInterferenceScores = field(default_factory=CoCoInterferenceScores)
    trace_machine_id: int = 0


@dataclass
class ResourceTopologyNodeDescriptor:
    """Recursive resource-topology tree (reference:
    proto/resource_topology_node_desc.proto:16-20)."""

    resource_desc: ResourceDescriptor = field(default_factory=ResourceDescriptor)
    parent_id: str = ""
    children: List["ResourceTopologyNodeDescriptor"] = field(default_factory=list)


@dataclass
class JobDescriptor:
    """A job: a tree of tasks under a root task (reference: proto/job_desc.proto:16-31)."""

    uuid: str = ""
    name: str = ""
    state: JobState = JobState.NEW
    root_task: Optional[TaskDescriptor] = None
    output_ids: List[int] = field(default_factory=list)
