"""L6: the flow scheduler service — the event-driven round loop.

Reference: scheduling/flow/flowscheduler/{interface.go,scheduler.go}.
Same event surface: AddJob, Register/DeregisterResource, ScheduleAllJobs/
ScheduleJobs, HandleTask{Completion,Placement,Eviction,Migration,Failure},
HandleJobCompletion, KillRunningTask, GetTaskBindings. A scheduling round
is: compute topology statistics → add/update job nodes → solve → deltas
(PREEMPT first, then PLACE/MIGRATE) → apply → refresh topology.
"""

from __future__ import annotations

import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from ..costmodels.base import CostModeler
from ..costmodels.trivial import TrivialCostModel
from ..data import (
    DeltaType,
    JobDescriptor,
    JobState,
    ResourceDescriptor,
    ResourceState,
    ResourceTopologyNodeDescriptor,
    ResourceType,
    SchedulingDelta,
    TaskDescriptor,
    TaskState,
)
from ..graph.changes import ChangeStats
from ..graph.graph_manager import GraphManager, TaskMapping
from ..obs.spans import span, start_span
from ..solver.base import FlowSolver
from ..solver.cpu_ref import ReferenceSolver
from ..solver.placement import PlacementSolver
from ..utils import JobMap, ResourceMap, TaskMap, job_id_from_string, resource_id_from_string


@dataclass
class RoundTiming:
    """Per-phase wall-clock breakdown of one scheduling round (the
    reference only times the whole round ad hoc in its CLI,
    cmd/k8sscheduler/scheduler.go:146-150; we make phases first-class).

    Every field is the duration of an obs span (`round` → `stats`,
    `graph_update`, `solve`, `deltas`, `apply`), so the RoundRecord
    JSONL (runtime/trace.py) and a captured Perfetto trace are two
    views of the same measurement and can never disagree."""

    stats_s: float = 0.0
    graph_update_s: float = 0.0
    solve_s: float = 0.0
    deltas_s: float = 0.0
    apply_s: float = 0.0
    total_s: float = 0.0


class FlowScheduler:
    def __init__(
        self,
        resource_map: ResourceMap,
        job_map: JobMap,
        task_map: TaskMap,
        root: ResourceTopologyNodeDescriptor,
        max_tasks_per_pu: int = 1,
        cost_model: Optional[CostModeler] = None,
        cost_model_factory=None,
        backend: Optional[FlowSolver] = None,
        preemption: bool = False,
        device_resident: bool = False,
    ) -> None:
        self.resource_map = resource_map
        self.job_map = job_map
        self.task_map = task_map
        self.resource_topology = root

        leaf_resource_ids: Set[int] = set()
        self.dimacs_stats = ChangeStats()
        if cost_model is None and cost_model_factory is not None:
            # Every model shares the Trivial constructor signature; the
            # factory form exists because leaf_resource_ids is owned here.
            cost_model = cost_model_factory(
                resource_map, task_map, leaf_resource_ids, max_tasks_per_pu
            )
        self.cost_model = cost_model or TrivialCostModel(
            resource_map, task_map, leaf_resource_ids, max_tasks_per_pu
        )
        self.gm = GraphManager(
            self.cost_model,
            leaf_resource_ids,
            self.dimacs_stats,
            max_tasks_per_pu,
            preemption=preemption,
        )
        self.gm.add_resource_topology(root)
        self.solver = PlacementSolver(
            self.gm,
            backend or ReferenceSolver(),
            device_resident=device_resident,
        )

        self.resource_roots: Set[int] = set()  # ids of registered topology roots
        self._root_rtnds: Dict[int, ResourceTopologyNodeDescriptor] = {}
        # The coordinator root registered above IS a topology root: the
        # per-iteration UpdateResourceTopology pass (reference
        # flowscheduler/scheduler.go:371-375) walks the roots to refresh
        # num_slots_below/num_running_tasks_below — without this entry
        # the refresh walks nothing and running-task stats never update.
        root_rid = resource_id_from_string(root.resource_desc.uuid)
        self.resource_roots.add(root_rid)
        self._root_rtnds[root_rid] = root
        self.task_bindings: Dict[int, int] = {}
        self.resource_bindings: Dict[int, Set[int]] = {}
        self.jobs_to_schedule: Dict[int, JobDescriptor] = {}
        self.runnable_tasks: Dict[int, Set[int]] = {}
        self.last_timing = RoundTiming()
        #: pipelined-round state: (solver token, timing, round span)
        #: while a dispatched solve is in flight, else None
        self._round_in_flight = None

    # ------------------------------------------------------------------
    # Event API
    # ------------------------------------------------------------------

    def get_task_bindings(self) -> Dict[int, int]:
        return self.task_bindings

    def add_job(self, jd: JobDescriptor) -> None:
        self.jobs_to_schedule[job_id_from_string(jd.uuid)] = jd

    def handle_job_completion(self, job_id: int) -> None:
        """Reference: flowscheduler/scheduler.go:93-104."""
        self._check_not_in_flight("handle_job_completion")
        self.gm.job_completed(job_id)
        jd = self.job_map.find(job_id)
        assert jd is not None, f"job {job_id} must exist"
        self.jobs_to_schedule.pop(job_id, None)
        self.runnable_tasks.pop(job_id, None)
        jd.state = JobState.COMPLETED

    def handle_task_completion(self, td: TaskDescriptor) -> None:
        """Reference: flowscheduler/scheduler.go:106-132."""
        self._check_not_in_flight("handle_task_completion")
        rid = self.task_bindings.get(td.uid)
        assert rid is not None, f"task {td.uid} must be bound to a resource"
        if not self._unbind_task_from_resource(td, rid):
            raise RuntimeError(f"could not unbind task {td.uid} from resource {rid}")
        td.state = TaskState.COMPLETED
        self.cost_model.record_task_completion(td)
        self.gm.task_completed(td.uid)

    def register_resource(self, rtnd: ResourceTopologyNodeDescriptor) -> None:
        """Reference: flowscheduler/scheduler.go:134-160."""
        stack = [rtnd]
        while stack:
            cur = stack.pop()
            rd = cur.resource_desc
            if rd.type == ResourceType.PU:
                rd.schedulable = True
                if rd.state == ResourceState.UNKNOWN:
                    rd.state = ResourceState.IDLE
            stack.extend(cur.children)
        self.gm.add_resource_topology(rtnd)
        rid = resource_id_from_string(rtnd.resource_desc.uuid)
        if rtnd.parent_id == "":
            self.resource_roots.add(rid)
            self._root_rtnds[rid] = rtnd

    def deregister_resource(self, rtnd: ResourceTopologyNodeDescriptor) -> None:
        """Reference: flowscheduler/scheduler.go:162-210."""
        self._check_not_in_flight("deregister_resource")
        self._dfs_evict_tasks(rtnd)
        self.gm.remove_resource_topology(rtnd.resource_desc)
        rid = resource_id_from_string(rtnd.resource_desc.uuid)
        self.resource_roots.discard(rid)
        self._root_rtnds.pop(rid, None)
        self._dfs_clean_up_resource(rtnd)
        if rtnd.parent_id:
            parent_rs = self.resource_map.find(resource_id_from_string(rtnd.parent_id))
            assert parent_rs is not None, f"parent of {rtnd.resource_desc.uuid} must exist"
            parent_node = parent_rs.topology_node
            parent_node.children = [
                c for c in parent_node.children if c.resource_desc.uuid != rtnd.resource_desc.uuid
            ]

    def handle_task_placement(self, td: TaskDescriptor, rd: ResourceDescriptor) -> None:
        """Reference: flowscheduler/scheduler.go:212-229.

        Fenced like the other placement-mutating events: an external
        placement while a pipelined round is in flight would bind a
        task the dispatched snapshot still maps as schedulable. The
        internal caller (delta application) runs after the latch
        clears."""
        self._check_not_in_flight("handle_task_placement")
        self._handle_task_placement(td, rd)

    def _handle_task_placement(self, td: TaskDescriptor, rd: ResourceDescriptor) -> None:
        td.scheduled_to_resource = rd.uuid
        self.gm.task_scheduled(td.uid, resource_id_from_string(rd.uuid))
        self._bind_task_to_resource(td, rd)
        runnables = self.runnable_tasks.get(job_id_from_string(td.job_id))
        if runnables is not None:
            runnables.discard(td.uid)
        self._execute_task(td, rd)

    def handle_task_eviction(self, td: TaskDescriptor, rd: ResourceDescriptor) -> None:
        """Reference: flowscheduler/scheduler.go:231-246.

        Externally driven evictions are fenced like the other
        placement-mutating events: an eviction during an in-flight
        pipelined round would unbind a task the dispatched snapshot
        still maps, letting _finish_round decode a stale PLACE for it.
        Internal callers (delta application, deregister's evict-DFS)
        run after the latch clears and use _evict_task directly."""
        self._check_not_in_flight("handle_task_eviction")
        self._evict_task(td, rd)

    def _evict_task(self, td: TaskDescriptor, rd: ResourceDescriptor) -> None:
        rid = resource_id_from_string(rd.uuid)
        self.gm.task_evicted(td.uid, rid)
        if not self._unbind_task_from_resource(td, rid):
            raise RuntimeError(f"could not unbind task {td.uid} from resource {rid}")
        td.state = TaskState.RUNNABLE
        self._insert_task_into_runnables(job_id_from_string(td.job_id), td.uid)

    def handle_task_migration(self, td: TaskDescriptor, rd: ResourceDescriptor) -> None:
        """Reference: flowscheduler/scheduler.go:248-270. Fenced while
        a pipelined round is in flight (see handle_task_placement);
        delta application uses _handle_task_migration after the latch
        clears."""
        self._check_not_in_flight("handle_task_migration")
        self._handle_task_migration(td, rd)

    def _handle_task_migration(self, td: TaskDescriptor, rd: ResourceDescriptor) -> None:
        old_rid = self.task_bindings[td.uid]
        new_rid = resource_id_from_string(rd.uuid)
        # scheduledToResource must be up to date before TaskMigrated
        # (reference hack note at :254-259).
        td.scheduled_to_resource = rd.uuid
        self.gm.task_migrated(td.uid, old_rid, new_rid)
        rd.state = ResourceState.BUSY
        td.state = TaskState.RUNNING
        if not self._unbind_task_from_resource(td, old_rid):
            raise RuntimeError(f"binding {td.uid}->{old_rid} must exist")
        self._bind_task_to_resource(td, rd)

    def handle_task_failure(self, td: TaskDescriptor) -> None:
        """Reference: flowscheduler/scheduler.go:272-287."""
        self._check_not_in_flight("handle_task_failure")
        self.gm.task_failed(td.uid)
        rid = self.task_bindings.get(td.uid)
        assert rid is not None, f"failed task {td.uid} should have been bound"
        self._unbind_task_from_resource(td, rid)
        td.state = TaskState.FAILED

    def kill_running_task(self, task_id: int) -> None:
        """Reference: flowscheduler/scheduler.go:289-306."""
        self._check_not_in_flight("kill_running_task")
        self.gm.task_killed(task_id)
        td = self.task_map.find(task_id)
        assert td is not None, f"unknown task {task_id}"
        if td.state != TaskState.RUNNING or task_id not in self.task_bindings:
            raise RuntimeError(f"task {task_id} not bound or not running")
        td.state = TaskState.ABORTED

    # ------------------------------------------------------------------
    # The scheduling round
    # ------------------------------------------------------------------

    def schedule_all_jobs(self):
        """Reference: flowscheduler/scheduler.go:309-318."""
        jds = [
            jd for jd in self.jobs_to_schedule.values()
            if len(self._compute_runnable_tasks_for_job(jd)) > 0
        ]
        return self.schedule_jobs(jds)

    # ------------------------------------------------------------------
    # Pipelined rounds: dispatch the solve, overlap host work, finish
    # ------------------------------------------------------------------

    def schedule_all_jobs_async(self):
        """Phase 1 of a pipelined round: stats refresh + graph update +
        solve DISPATCH; returns before the solve completes. While the
        round is in flight the caller may keep ADDING jobs and tasks —
        their graph mutations journal for the next round, mirroring the
        reference's pod batching (k8sclient/client.go:153-193) which
        accumulates arrivals while the solver subprocess crunches.
        Events that mutate existing placements (completion, failure,
        kill, deregister) raise until finish_scheduling() applies the
        in-flight round's deltas. Returns None when no job has runnable
        tasks (nothing dispatched; finish_scheduling must not be
        called)."""
        if self._round_in_flight is not None:
            raise RuntimeError("a scheduling round is already in flight")
        jds = [
            jd for jd in self.jobs_to_schedule.values()
            if len(self._compute_runnable_tasks_for_job(jd)) > 0
        ]
        if not jds:
            return None
        timing, round_span = self._begin_round(jds)
        try:
            with span("solve_dispatch") as sp:
                token = self.solver.solve_async()
            timing.solve_s = sp.dur_s  # dispatch only
        except BaseException:
            round_span.__exit__(*sys.exc_info())
            raise
        self._round_in_flight = (token, timing, round_span)
        return token

    def finish_scheduling(self):
        """Phase 2: synchronize the solve, apply deltas, close the
        round. Returns (num_scheduled, deltas) like schedule_jobs."""
        if self._round_in_flight is None:
            raise RuntimeError("no scheduling round in flight")
        token, timing, round_span = self._round_in_flight
        try:
            try:
                with span("solve_sync") as sp:
                    task_mappings = self.solver.complete(token)
            finally:
                # the latch must clear even when the solver raises
                # (overflow / non-convergence), or every later event
                # handler would refuse with "in flight" forever — and it
                # must be off before delta application anyway, for the
                # internal placement/eviction handlers
                self._round_in_flight = None
            timing.solve_s += sp.dur_s  # + synchronize
            return self._finish_round(task_mappings, timing, round_span)
        except BaseException:
            round_span.__exit__(*sys.exc_info())
            raise

    def _begin_round(self, jds):
        """The pre-solve half of a round, shared by the synchronous
        and pipelined paths: mutation-counter reset, topology stats
        refresh, and the job/task graph update. Opens the `round` span
        (closed by _finish_round — or here, on an exception)."""
        timing = RoundTiming()
        round_span = start_span("round", jobs=len(jds))
        try:
            # Reset the mutation counters at round START (the reference
            # resets after the round, flowscheduler/scheduler.go:332,
            # which zeroes them before any post-round reader — e.g. the
            # round tracer — can observe the round's mutation counts).
            self.dimacs_stats.reset()
            with span("stats") as sp:
                self.gm.compute_topology_statistics(self.gm.sink_node)
            timing.stats_s = sp.dur_s
            with span("graph_update") as sp:
                self.gm.add_or_update_job_nodes(jds)
            timing.graph_update_s = sp.dur_s
        except BaseException:
            round_span.__exit__(*sys.exc_info())
            raise
        return timing, round_span

    def _finish_round(self, task_mappings, timing, round_span):
        """The post-solve half of a round, shared by the synchronous
        and pipelined paths (so delta decoding / feedback can never
        drift between them): preemption deltas + binding diffs, delta
        application, per-root topology refresh, EC purge, and the
        unscheduled-feedback hook. Closes the `round` span; its
        duration IS timing.total_s."""
        try:
            with span("deltas") as sp:
                deltas = self.gm.scheduling_deltas_for_preempted_tasks(
                    task_mappings, self.resource_map
                )
                for task_node_id, res_node_id in task_mappings.items():
                    delta = self.gm.node_binding_to_scheduling_delta(
                        task_node_id, res_node_id, self.task_bindings
                    )
                    if delta is not None:
                        deltas.append(delta)
            timing.deltas_s = sp.dur_s

            with span("apply") as sp:
                num_scheduled = self._apply_scheduling_deltas(deltas)
                for rid in self.resource_roots:
                    self.gm.update_resource_topology(self._root_rtnds[rid])
            timing.apply_s = sp.dur_s
            self.gm.purge_unconnected_equiv_class_nodes()
            # Policy feedback: which runnable tasks stayed unscheduled
            # (drives e.g. Quincy's wait-cost starvation bound).
            unscheduled = [
                t
                for tasks in self.runnable_tasks.values()
                for t in tasks
                if t not in self.task_bindings
            ]
            self.cost_model.note_round(unscheduled)
        except BaseException:
            round_span.__exit__(*sys.exc_info())
            raise
        round_span.set("num_scheduled", num_scheduled)
        timing.total_s = round_span.finish()
        self.last_timing = timing
        return num_scheduled, deltas

    def _check_not_in_flight(self, what: str) -> None:
        if self._round_in_flight is not None:
            raise RuntimeError(
                f"{what} while a pipelined scheduling round is in flight; "
                "call finish_scheduling() first (only job/task ADDITIONS "
                "may overlap an in-flight round)"
            )

    def schedule_jobs(self, jds: List[JobDescriptor]):
        """Reference: flowscheduler/scheduler.go:321-338."""
        self._check_not_in_flight("schedule_jobs")
        if not jds:
            timing = RoundTiming()
            self.last_timing = timing
            return 0, []
        timing, round_span = self._begin_round(jds)
        try:
            # Reference round body: flowscheduler/scheduler.go:340-375.
            with span("solve") as sp:
                task_mappings = self.solver.solve()
            timing.solve_s = sp.dur_s
            return self._finish_round(task_mappings, timing, round_span)
        except BaseException:
            round_span.__exit__(*sys.exc_info())
            raise

    def _apply_scheduling_deltas(self, deltas: List[SchedulingDelta]) -> int:
        """Reference: flowscheduler/scheduler.go:377-412."""
        num_scheduled = 0
        for d in deltas:
            td = self.task_map.find(d.task_id)
            assert td is not None, f"no descriptor for task {d.task_id}"
            rs = self.resource_map.find(resource_id_from_string(d.resource_id))
            assert rs is not None, f"no status for resource {d.resource_id}"
            if d.type == DeltaType.PLACE:
                jd = self.job_map.find(job_id_from_string(td.job_id))
                if jd.state != JobState.RUNNING:
                    jd.state = JobState.RUNNING
                self._handle_task_placement(td, rs.descriptor)
                num_scheduled += 1
            elif d.type == DeltaType.PREEMPT:
                self._evict_task(td, rs.descriptor)
            elif d.type == DeltaType.MIGRATE:
                self._handle_task_migration(td, rs.descriptor)
            elif d.type == DeltaType.NOOP:
                pass
            else:
                raise ValueError(f"unknown delta type {d.type}")
        return num_scheduled

    # ------------------------------------------------------------------
    # Bindings bookkeeping
    # ------------------------------------------------------------------

    def _bind_task_to_resource(self, td: TaskDescriptor, rd: ResourceDescriptor) -> None:
        """Reference: flowscheduler/scheduler.go:421-437."""
        task_id = td.uid
        rid = resource_id_from_string(rd.uuid)
        rd.state = ResourceState.BUSY
        rd.current_running_tasks.append(task_id)
        assert task_id not in self.task_bindings, f"task {task_id} already bound"
        self.task_bindings[task_id] = rid
        self.resource_bindings.setdefault(rid, set()).add(task_id)

    def _unbind_task_from_resource(self, td: TaskDescriptor, rid: int) -> bool:
        """Reference: flowscheduler/scheduler.go:443-464."""
        task_id = td.uid
        rs = self.resource_map.find(rid)
        rd = rs.descriptor
        if len(rd.current_running_tasks) == 0:
            rd.state = ResourceState.IDLE
        if task_id not in self.task_bindings:
            return False
        task_set = self.resource_bindings.get(rid, set())
        if task_id not in task_set:
            return False
        del self.task_bindings[task_id]
        task_set.discard(task_id)
        return True

    def _execute_task(self, td: TaskDescriptor, rd: ResourceDescriptor) -> None:
        """No real executor, as in the reference (scheduler.go:469-474)."""
        td.state = TaskState.RUNNING
        td.scheduled_to_resource = rd.uuid

    def _insert_task_into_runnables(self, job_id: int, task_id: int) -> None:
        self.runnable_tasks.setdefault(job_id, set()).add(task_id)

    def _compute_runnable_tasks_for_job(self, jd: JobDescriptor) -> Set[int]:
        """Dependency-free lazy graph reduction (reference:
        flowscheduler/scheduler.go:493-529)."""
        job_id = job_id_from_string(jd.uuid)
        root = jd.root_task
        queue: List[TaskDescriptor] = []
        if root.state in (
            TaskState.CREATED,
            TaskState.RUNNING,
            TaskState.RUNNABLE,
            TaskState.COMPLETED,
        ):
            queue.append(root)
        while queue:
            cur = queue.pop()
            queue.extend(cur.spawned)
            if cur.state in (TaskState.CREATED, TaskState.BLOCKING):
                cur.state = TaskState.RUNNABLE
                self._insert_task_into_runnables(job_id_from_string(cur.job_id), cur.uid)
        return self.runnable_tasks.setdefault(job_id, set())

    # ------------------------------------------------------------------
    # Resource removal helpers
    # ------------------------------------------------------------------

    def _dfs_evict_tasks(self, rtnd: ResourceTopologyNodeDescriptor) -> None:
        for child in rtnd.children:
            self._dfs_evict_tasks(child)
        self._evict_tasks_from_resource(rtnd)

    def _evict_tasks_from_resource(self, rtnd: ResourceTopologyNodeDescriptor) -> None:
        rd = rtnd.resource_desc
        rid = resource_id_from_string(rd.uuid)
        for task_id in list(self.resource_bindings.get(rid, ())):
            td = self.task_map.find(task_id)
            assert td is not None, f"descriptor for task {task_id} must exist"
            self._evict_task(td, rd)

    def _dfs_clean_up_resource(self, rtnd: ResourceTopologyNodeDescriptor) -> None:
        for child in rtnd.children:
            self._dfs_clean_up_resource(child)
        rid = resource_id_from_string(rtnd.resource_desc.uuid)
        self.resource_bindings.pop(rid, None)
        self.resource_map.remove(rid)
