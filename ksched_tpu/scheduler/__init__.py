from .flow_scheduler import FlowScheduler, RoundTiming

__all__ = ["FlowScheduler", "RoundTiming"]
