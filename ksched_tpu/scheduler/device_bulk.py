"""Device-resident bulk scheduling: the end-to-end TPU round.

scheduler/bulk.py keeps cluster state in host numpy and ships a problem
to the solver every round. That design pays a host<->device round trip
per scheduling round, which on real deployments (and especially over a
tunneled TPU) dominates the actual solve. This module is the next step
of the same design: the ENTIRE cluster state — task table, placements,
per-PU occupancy, machine membership — lives in device arrays, and one
scheduling round (capacity refresh -> class census -> transport solve ->
flow decode -> placement apply) is a single jitted program. Rounds chain
on device with no host synchronization; bindings are fetched
asynchronously outside the round, exactly where the reference's round
timer stops (the reference times ScheduleAllJobs and pushes Bindings to
the API server after the timed region — cmd/k8sscheduler/scheduler.go:
146-187).

The solve is the dense layered transport kernel — dispatched via
ops.transport_solve: the fused Pallas kernel on TPU, the XLA phase loop
elsewhere; both exit on convergence under a safety bound (`supersteps`),
and each round reports a `converged` flag that callers assert on fetch. The decode is fully vectorized and gather-free:
rank-matching placed tasks to machine grants via compare-matrix
reductions ([Tcap, M] masks) and a tiny [Tcap,M]x[M,P] matmul for the
within-machine PU split — MXU/VPU work instead of serialized gathers.

Graph semantics are identical to BulkCluster (same aggregate topology,
same pin-on-place preemption-off accounting, same unscheduled-escape
policy); tests drive both against the same scenario and require equal
placement counts and objectives.
"""

from __future__ import annotations

import functools
from typing import Callable, NamedTuple, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..solver.layered import (
    COST_SCALE_LIMIT,
    choose_eps0,
    pad_geometry,
    solve_row_constant,
    split_grants_by_class,
    transport_fori,
    transport_fori_tiered,
    validate_alpha,
    validate_job_unsched_cost,
)


class DeviceClusterState(NamedTuple):
    live: jnp.ndarray  # bool[Tcap]
    cls: jnp.ndarray  # int32[Tcap]
    job: jnp.ndarray  # int32[Tcap]
    pu: jnp.ndarray  # int32[Tcap]; PU index or -1
    pu_running: jnp.ndarray  # int32[num_pus]
    machine_enabled: jnp.ndarray  # bool[M]
    #: interchangeability group per task (group mode; all-zero otherwise)
    grp: jnp.ndarray  # int32[Tcap]


class GroupSpec(NamedTuple):
    """Device-resident group metadata (group mode): row g of the
    transport is one interchangeability class of tasks — same task
    class, same escape cost, same per-machine cost profile. This is how
    per-task preference arcs (graph_manager.go:1229-1264,
    costmodel/interface.go:105-110 GetTaskPreferenceArcs) ride the
    dense fast path: tasks sharing a preference signature share a row,
    and the signature's preferred machines become per-row cost
    overrides (pref_w) min'd into the class cost row. Arrays live on
    device and are passed as traced args, so the host can update them
    (new signatures, wait-cost aging) without recompiling the round."""

    cls: jnp.ndarray  # int32[G] class of each group (census/cost row)
    job: jnp.ndarray  # int32[G] job of each group (bookkeeping)
    e: jnp.ndarray  # int32[G] task->EC route base cost (per group)
    u: jnp.ndarray  # int32[G] escape (unsched) cost per group
    pref_w: jnp.ndarray  # int32[G, M] absolute route cost overrides;
    #                      PREF_NONE where the group has no preference


#: pref_w fill for "no preference": large enough to never win the min
#: against any guarded route cost, small enough that min() arithmetic
#: cannot overflow int32
PREF_NONE = 1 << 30


class DeviceBulkCluster:
    """Flat device-array cluster; one jitted program per scheduling round."""

    def __init__(
        self,
        num_machines: int,
        pus_per_machine: int,
        slots_per_pu: int,
        num_jobs: int,
        num_task_classes: int = 1,
        task_capacity: int = 2048,
        unsched_cost: int = 5,
        ec_cost: int = 2,
        class_cost_fn: Optional[Callable] = None,  # census[M,C] -> int32[C,M], traceable
        supersteps: Optional[int] = None,
        decode_width: Optional[int] = None,  # steady-round decode window
        alpha: int = 8,  # eps-schedule divisor for iterative solves
        job_unsched_cost: Optional[np.ndarray] = None,
        preemption: bool = False,
        continuation_discount: int = 1,
        preempt_every: int = 1,
        preempt_drift: int = 0,
        preempt_global_every: int = 0,
        preempt_scope_tau: int = 1,
        preempt_scoped_width: Optional[int] = None,
        preempt_incr_budget: Optional[int] = None,
        track_realized_cost: bool = False,
        num_groups: int = 0,
        active_groups_cap: int = 256,
        refine_waves: int = 8,
        two_stage_eps0: str = "one",
    ) -> None:
        self.M = num_machines
        self.P = pus_per_machine
        self.S = slots_per_pu
        self.J = num_jobs
        self.C = num_task_classes
        self.num_pus = num_machines * pus_per_machine
        self.Tcap = int(task_capacity)
        self.unsched_cost = int(unsched_cost)
        self.ec_cost = int(ec_cost)
        self.class_cost_fn = class_cost_fn
        self.alpha = validate_alpha(alpha)
        # Per-job unsched costs (graph_manager.go:1291-1305: each job's
        # unsched aggregator has its own cost). When set, (job, class)
        # pairs become distinct transport commodities: the solve's row
        # axis expands from C classes to G = J*C groups, g = j*C + c.
        # Intended for moderate J (tens to low hundreds): the dense
        # transport carries [G, M] state and the decode a [W, G]
        # one-hot, both linear in G — at thousands of jobs the CSR
        # graph path (per-task unsched arcs) is the right tool.
        self.job_unsched_cost = validate_job_unsched_cost(
            job_unsched_cost, num_jobs
        )
        job_unsched_cost = self.job_unsched_cost  # normalized array/None
        self.per_job = job_unsched_cost is not None
        self.G = num_jobs * num_task_classes if self.per_job else num_task_classes
        # Group mode: rows are caller-defined interchangeability groups
        # (see GroupSpec) instead of classes / (job, class) pairs. The
        # group axis is static (capacity num_groups); metadata arrives
        # as traced device arrays so signatures can be registered and
        # escape costs aged between rounds without recompiling.
        self.grouped = num_groups > 0
        if self.grouped:
            if self.per_job:
                raise ValueError(
                    "num_groups and job_unsched_cost are exclusive: group "
                    "escape costs (GroupSpec.u) subsume per-job unsched costs"
                )
            self.G = int(num_groups)
        # rows the COMPACTED grouped solve can hold. An int is one
        # compaction width; a sequence is a LADDER of widths — the
        # round picks the smallest width that fits the live active-row
        # count (nested lax.cond, each width compiled once), so
        # diversity-pressure configs whose active set exceeds the first
        # cap degrade to a mid-width solve instead of jumping straight
        # to full G width (VERDICT r3 #2: the multiblock tail was
        # full-width 512-row solves past a single 256-row cap).
        if isinstance(active_groups_cap, (int, np.integer)):
            caps = (int(active_groups_cap),)
        else:
            caps = tuple(int(c) for c in active_groups_cap)
        if not caps or any(c < 1 for c in caps):
            raise ValueError("active_groups_cap entries must be >= 1")
        caps = tuple(sorted({min(c, max(self.G, 1)) for c in caps}))
        self.active_groups_caps = caps
        #: largest ladder width (back-compat scalar view; == the single
        #: cap when an int was passed)
        self.active_groups_cap = caps[-1]
        # Price refinement between eps phases (solver/layered.py
        # _price_refine) for the iterative solves. Default ON for the
        # device path: measured 2.2x fewer supersteps on contended
        # CoCo-50k steady rounds (mean 2013 -> 925) and 6-12x on
        # grouped locality instances. The HOST solvers
        # (LayeredTransportSolver, ShardedLayeredSolver) keep
        # refine_waves=0 — their cross-backend bit-identity contracts
        # compare superstep-for-superstep.
        self.refine_waves = int(refine_waves)
        # Stage-1 eps schedule of the grouped two-stage solve — REGIME-
        # DEPENDENT (docs/NOTES.md): "one" (eps0=1, budget 256) wins on
        # near-uniform discounts (single-block Quincy: tens of waves
        # when pref capacity suffices); "quarter" (n_scale/4, budget
        # 1024) wins on heavy-tailed discounts (multi-block: captured
        # tail rounds 3580 -> 51 supersteps — the eps=1 schedule pays
        # for ~190-unit discount descents in unit bounces, r4 sweep
        # via tools/tail_repro.py replay-grouped).
        if two_stage_eps0 not in ("one", "quarter"):
            raise ValueError("two_stage_eps0 must be 'one' or 'quarter'")
        self.two_stage_eps0 = two_stage_eps0
        # Preemption (keep-arcs semantics, graph_manager.go:855-888):
        # every round's solve reconsiders PLACED tasks too — staying on
        # the current machine is discounted by `continuation_discount`
        # (the aggregate TaskContinuationCost, interface.go:75-79),
        # moving pays full price, escaping pays the unsched cost (the
        # aggregate TaskPreemptionCost). Machine capacity counts total
        # slots, not free ones (the :662-667 rule flips). The round
        # emits PLACE / MIGRATE / PREEMPT counts; the solve is the
        # tiered transport (solver/layered.py transport_fori_tiered).
        self.preemption = bool(preemption)
        self.continuation_discount = int(continuation_discount)
        # Stability-aware (incremental) preemption: the reference keeps
        # round cost proportional to the DELTA even with preemption on
        # (placement/solver.go:60-90 — running tasks keep their arcs,
        # the incremental solver re-prices only changes). The TPU form:
        # scanned rounds run the cheap incremental core (residents
        # pinned, bounded backlog decode) and a FULL tiered re-solve
        # fires every `preempt_every` rounds OR when the running-class
        # census has drifted by more than `preempt_drift` task
        # positions since the last full solve (L1 distance, device-
        # computed) — so migration opportunities accumulate bounded
        # staleness instead of being re-derived from scratch every
        # round. preempt_every=1 (default) is the pure per-round
        # re-solve; preempt_drift=0 disables the drift trigger.
        self.preempt_every = int(preempt_every)
        self.preempt_drift = int(preempt_drift)
        # Three-tier stability (VERDICT r4 #2): with this knob on,
        # cadence/drift re-solves become SCOPED (drifted columns +
        # backlog re-solve; out-of-scope residents pinned) and a truly
        # GLOBAL tiered re-solve fires only every preempt_global_every
        # rounds — rare enough to sit outside p99 while bounding how
        # long scoping can defer multi-hop migration chains.
        self.preempt_global_every = int(preempt_global_every)
        # Scope membership threshold: a machine joins a scoped
        # re-solve when the L1 distance between its running-class
        # census and the drift reference reaches tau. Measured at the
        # coco50k shape (docs/NOTES.md round-5): after 12 incremental
        # rounds 807/1000 machines have SOME drift (scope-on-any-change
        # is a full solve in disguise) but tau=12 concentrates 53% of
        # the total L1 on 144 machines — the thresholded scope is what
        # makes scoped rounds small.
        self.preempt_scope_tau = int(preempt_scope_tau)
        # Mover-decode window for scoped rounds (None = Tcap-wide).
        # Must comfortably exceed the plausible scoped mover count:
        # a binding window PARKS displaced residents (pu=-1) and the
        # resulting backlog craters the census — measured 2.8M -> 14M
        # realized cost on the toy config when scope-everything met a
        # 4096 window.
        self.preempt_scoped_width = (
            None if preempt_scoped_width is None
            else int(preempt_scoped_width)
        )
        # Incremental-round superstep budget (three-tier only): the
        # backlog-admission solve of an incremental round occasionally
        # hits the eps-slosh regime against drifted census costs —
        # measured monsters of 42.7k and 62.3k supersteps (~1-in-40k
        # rounds, top_rounds forensics r5; drift value at the monster
        # is 4-10k, i.e. NOT predicted by the drift trigger, and
        # lowering the trigger measured WORSE). With a budget set, the
        # incremental attempt is bounded and a non-converged attempt
        # ESCALATES the round to the scoped tier (discarding the
        # attempt, re-pricing the drifted columns) — the incremental
        # tail becomes min(monster, budget + scoped-round cost) by
        # construction. None disables (bit-identical legacy rounds).
        self.preempt_incr_budget = (
            None if preempt_incr_budget is None else int(preempt_incr_budget)
        )
        if self.preempt_every < 1:
            raise ValueError("preempt_every must be >= 1")
        if self.preempt_drift < 0:
            raise ValueError("preempt_drift must be >= 0")
        if self.preempt_global_every < 0:
            raise ValueError("preempt_global_every must be >= 0")
        if self.preempt_scope_tau < 1:
            raise ValueError("preempt_scope_tau must be >= 1")
        self.hybrid_preempt = self.preemption and (
            self.preempt_every > 1 or self.preempt_drift > 0
        )
        if self.preempt_global_every > 0 and not self.hybrid_preempt:
            raise ValueError(
                "preempt_global_every requires stability-aware "
                "preemption (preempt_every > 1 or preempt_drift > 0)"
            )
        if self.preempt_incr_budget is not None:
            if self.preempt_incr_budget < 1:
                raise ValueError("preempt_incr_budget must be >= 1")
            if self.preempt_global_every <= 0:
                raise ValueError(
                    "preempt_incr_budget requires the three-tier scheme "
                    "(preempt_global_every > 0) — escalation targets the "
                    "scoped tier"
                )
        # Opt-in quality metric: pricing the whole assignment costs an
        # extra cost_fn + Tcap gather per round INSIDE the timed scan —
        # the parity tests turn it on; benches leave it off so the
        # metric cannot inflate the latencies it exists to defend.
        self.track_realized_cost = bool(track_realized_cost)
        if self.preemption:
            if continuation_discount < 0:
                raise ValueError("continuation_discount must be >= 0")
            # decode_width in preemption mode bounds the MOVER decode
            # (stays keep their PU without decoding) — see
            # round_core_preempt
        if decode_width is not None:
            if decode_width <= 0:
                raise ValueError(
                    f"decode_width must be positive, got {decode_width}"
                )
            if decode_width >= task_capacity:
                decode_width = None  # wider than the pool = the full path
        self.decode_width = None if decode_width is None else int(decode_width)
        # Degenerate = every group shares one cost row (no class cost
        # model, and no per-job cost spread): the solve collapses to
        # the exact closed form regardless of G. Group mode is assumed
        # heterogeneous (preference overrides differentiate rows).
        self.class_degenerate = (
            not self.grouped
            and class_cost_fn is None
            and (
                job_unsched_cost is None
                or bool((job_unsched_cost == job_unsched_cost[0]).all())
            )
        )
        # Row-constant: each (job, class) row's cost is machine-uniform
        # (per-job unsched costs but no class cost model) — rows differ
        # from each other, so the class-degenerate collapse doesn't
        # apply, but the fractional-knapsack closed form
        # (solver/layered.py solve_row_constant) is exact. Without it
        # the iterative solve herds pathologically at scale (the
        # 12.5k-machine livelock of docs/NOTES.md, per-job flavor).
        self.row_constant = (
            not self.grouped and self.per_job and class_cost_fn is None
        )
        # A positive continuation discount makes cells residency-
        # dependent, so the degenerate collapse only applies to
        # preemption mode at discount 0 (where the tiers coincide and
        # the ordinary solve serves).
        if self.preemption and self.continuation_discount > 0:
            self.class_degenerate = False
            self.row_constant = False
        # Closed-form solves (G == 1 or degenerate) take no iterations;
        # otherwise the cost-scaling schedule runs under a
        # lax.while_loop that exits on convergence — this is only the
        # safety bound, not the cost.
        self.supersteps = int(
            supersteps if supersteps is not None
            else (
                1
                if (self.G == 1 or self.class_degenerate or self.row_constant)
                else 16384
            )
        )

        # Padded transport columns: [machines | zero-cap padding | unsched]
        self.Mp, self.n_scale = pad_geometry(num_machines, self.G)

        self.state = DeviceClusterState(
            live=jnp.zeros(self.Tcap, jnp.bool_),
            cls=jnp.zeros(self.Tcap, jnp.int32),
            job=jnp.zeros(self.Tcap, jnp.int32),
            pu=jnp.full(self.Tcap, -1, jnp.int32),
            pu_running=jnp.zeros(self.num_pus, jnp.int32),
            machine_enabled=jnp.ones(self.M, jnp.bool_),
            grp=jnp.zeros(self.Tcap, jnp.int32),
        )
        # stability-aware preemption bookkeeping (see preempt_every):
        # the running-class census at the last FULL re-solve and the
        # rounds elapsed since. k starts saturated so the first scanned
        # round is a full solve (host mutations before it are unseen
        # drift).
        self._hyb_census = jnp.zeros((self.M, self.C), jnp.int32)
        self._hyb_k = jnp.int32(self.preempt_every - 1)
        # rounds since the last GLOBAL re-solve; starts saturated so
        # the first fired re-solve of a scan is global (host mutations
        # before it are unseen drift for EVERY column)
        self._hyb_kg = jnp.int32(
            max(self.preempt_global_every - 1, 0)
        )
        # Benign defaults until set_groups: every group is class 0 /
        # job 0 at the scalar costs with no preferences.
        self.groups = GroupSpec(
            cls=jnp.zeros(self.G, jnp.int32),
            job=jnp.zeros(self.G, jnp.int32),
            e=jnp.full(self.G, self.ec_cost, jnp.int32),
            u=jnp.full(self.G, self.unsched_cost, jnp.int32),
            pref_w=jnp.full((self.G, self.M), PREF_NONE, jnp.int32),
        ) if self.grouped else None
        # Host mirror of GroupSpec.cls so group-only admissions can
        # derive per-task classes without a device fetch (which would
        # poison dispatch latency on tunneled TPUs — docs/NOTES.md).
        self._groups_cls_host = (
            np.zeros(self.G, np.int32) if self.grouped else None
        )
        #: steady-round arrival group draw map (see set_arrival_groups)
        self._arrival_map = jnp.arange(max(self.G, 1), dtype=jnp.int32)
        self._arrival_n = jnp.int32(max(self.G, 1))
        self._build_programs()
        self.last_stats: Optional[dict] = None
        self.last_admitted = None  # device i32 from the latest add_tasks

    # ------------------------------------------------------------------
    # jitted programs (closures over the static geometry)
    # ------------------------------------------------------------------

    def _build_programs(self) -> None:
        M, P, S, C, Tcap, Mp = self.M, self.P, self.S, self.C, self.Tcap, self.Mp
        num_pus, J = self.num_pus, self.J
        u_cost, e_cost = self.unsched_cost, self.ec_cost
        n_scale = self.n_scale
        supersteps = self.supersteps
        cost_fn = self.class_cost_fn
        alpha = self.alpha
        steady_decode_width = self.decode_width
        i32 = jnp.int32
        per_job, Gn = self.per_job, self.G
        grouped = self.grouped
        # The one-hot decode's [W, Gn] x [Gn, M] matmuls scale as
        # W*Gn*M MACs; beyond ~2M Gn*M cells the sort+row-gather decode
        # wins regardless of mode (e.g. per-job rows at trace scale:
        # 256 groups x 12.5k machines). Static choice per geometry.
        use_sorted_decode = grouped or (Gn * M >= (1 << 21))
        active_caps = self.active_groups_caps
        class_degenerate = self.class_degenerate
        row_constant = self.row_constant
        preempt, discount = self.preemption, self.continuation_discount
        stage1_quarter = self.two_stage_eps0 == "quarter"
        hybrid = self.hybrid_preempt
        preempt_every = self.preempt_every
        preempt_drift = self.preempt_drift
        global_every = self.preempt_global_every
        scope_tau = self.preempt_scope_tau
        scoped_width = self.preempt_scoped_width
        incr_budget = self.preempt_incr_budget
        track_realized = self.track_realized_cost
        refine_waves = self.refine_waves
        # Per-row (group) escape costs: row g = j*C + c escapes at job
        # j's unsched cost; without per-job costs every row uses the
        # scalar. Closure constant — baked into the compiled round.
        u_row = jnp.asarray(
            np.repeat(self.job_unsched_cost, C).astype(np.int32)
            if per_job
            else np.full(Gn, u_cost, np.int32)
        )

        def census_of(state: DeviceClusterState):
            """Per-machine running-class census [M, C] (the vectorized
            WhareMapStats, whare_map_stats.proto:12-18)."""
            placed = state.live & (state.pu >= 0)
            machine = jnp.clip(state.pu, 0, num_pus - 1) // P
            idx = jnp.where(placed, machine * C + state.cls, M * C)
            flat = jnp.zeros(M * C + 1, i32).at[idx].add(1)
            return flat[: M * C].reshape(M, C)

        def rank_match_decode(g_safe, grants_gm, pu_free):
            """Rank-match participant rows to machine grants — the
            shared decode of both round flavors. g_safe [W] holds each
            row's group (sentinel Gn = not participating), grants_gm
            [Gn, M] the solver's per-group machine grants, pu_free
            [num_pus] the slots these grants may occupy. Returns
            (granted bool[W], pu_abs i32[W]).

            Each group's cumulative-grant row is gathered per task via
            a one-hot [W, Gn] x [Gn, M] matmul (MXU), and in-group
            ranks come from one one-hot cumsum — no per-group Python
            loop. precision=HIGHEST throughout: TPU f32 matmuls default
            to bf16 passes, whose 8-bit mantissa corrupts counts beyond
            256; all counts here are < 2^24, so f32 at HIGHEST is
            exact."""
            hi = jax.lax.Precision.HIGHEST
            part = g_safe < i32(Gn)
            onehot = (
                g_safe[:, None] == jnp.arange(Gn, dtype=i32)[None, :]
            ).astype(jnp.float32)  # [W, Gn]; sentinel rows hit no column
            cum_oh = jnp.cumsum(onehot, axis=0)
            rank_f = jnp.sum((cum_oh - onehot) * onehot, axis=1)  # excl rank
            quota = jnp.einsum(
                "tg,g->t", onehot,
                jnp.sum(grants_gm, axis=1).astype(jnp.float32), precision=hi,
            )
            granted = part & (rank_f < quota)

            # group-row -> machine via cumulative-grant comparisons
            offs = jnp.cumsum(grants_gm, axis=0) - grants_gm  # [Gn, M]
            cum_all = jnp.cumsum(grants_gm, axis=1).astype(jnp.float32)
            cum_sel = jnp.einsum("tc,cm->tm", onehot, cum_all, precision=hi)
            off_sel = jnp.einsum(
                "tc,cm->tm", onehot, offs.astype(jnp.float32), precision=hi
            )
            cols = jnp.arange(M, dtype=i32)[None, :]
            cmp = cum_sel <= rank_f[:, None]  # [W, M]
            machine = jnp.sum(cmp, axis=1, dtype=i32)  # grant machine
            excl_at = jnp.max(jnp.where(cmp, cum_sel, 0.0), axis=1)
            oh = machine[:, None] == cols  # [W, M]
            off_at = jnp.sum(jnp.where(oh, off_sel, 0.0), axis=1)
            slot = off_at + (rank_f - excl_at)  # within-machine slot

            # split each machine's grant across its PUs in slot order
            t_m = jnp.sum(grants_gm, axis=0)
            pf2 = pu_free.reshape(M, P)
            exclg = jnp.cumsum(pf2, axis=1) - pf2
            grants_pu = jnp.clip(t_m[:, None] - exclg, 0, pf2)
            cumg = jnp.cumsum(grants_pu, axis=1).astype(jnp.float32)
            cg_at = jnp.einsum(
                "tm,mp->tp", oh.astype(jnp.float32), cumg, precision=hi
            )  # [W, P]
            pu_in = jnp.sum(cg_at <= slot[:, None], axis=1)
            pu_abs = machine * P + pu_in.astype(i32)
            return granted, pu_abs

        def rank_match_decode_grouped(g_safe, grants_gm, pu_free):
            """Group-mode twin of rank_match_decode for LARGE group
            counts: the one-hot path's [W, Gn] x [Gn, M] matmuls scale
            as W*Gn*M MACs — prohibitive at thousands of groups. This
            variant computes in-group ranks with ONE stable sort and
            selects each row's cumulative-grant rows by gather (two
            [W, M] ROW gathers — rows are lane-contiguous slices, the
            fast gather direction on TPU). Same output contract as
            rank_match_decode: (granted bool[W], pu_abs i32[W])."""
            W = g_safe.shape[0]
            hi = jax.lax.Precision.HIGHEST
            part = g_safe < i32(Gn)
            # in-group exclusive rank via one stable sort (same trick
            # as the preempt decode's per-cell resident ranks)
            order = jnp.argsort(g_safe, stable=True)
            counts = jnp.zeros(Gn + 1, i32).at[g_safe].add(1)
            starts = jnp.cumsum(counts) - counts
            rank_sorted = jnp.arange(W, dtype=i32) - starts[g_safe[order]]
            rank = jnp.zeros(W, i32).at[order].set(rank_sorted)
            quota = jnp.sum(grants_gm, axis=1)  # [Gn]
            quota_t = jnp.concatenate([quota, jnp.zeros(1, i32)])[g_safe]
            granted = part & (rank < quota_t)

            # group-row -> machine via the row's cumulative grants
            g_clip = jnp.clip(g_safe, 0, Gn - 1)
            cum_t = jnp.cumsum(grants_gm, axis=1)[g_clip]  # [W, M]
            offs_t = (jnp.cumsum(grants_gm, axis=0) - grants_gm)[g_clip]
            cmp = cum_t <= rank[:, None]  # [W, M]
            machine = jnp.sum(cmp, axis=1, dtype=i32)
            excl_at = jnp.max(jnp.where(cmp, cum_t, i32(0)), axis=1)
            cols = jnp.arange(M, dtype=i32)[None, :]
            oh = machine[:, None] == cols  # [W, M]
            off_at = jnp.sum(jnp.where(oh, offs_t, i32(0)), axis=1)
            slot = off_at + (rank - excl_at)  # within-machine slot

            # split each machine's grant across its PUs in slot order
            t_m = jnp.sum(grants_gm, axis=0)
            pf2 = pu_free.reshape(M, P)
            exclg = jnp.cumsum(pf2, axis=1) - pf2
            grants_pu = jnp.clip(t_m[:, None] - exclg, 0, pf2)
            cumg = jnp.cumsum(grants_pu, axis=1).astype(jnp.float32)
            cg_at = jnp.einsum(
                "tm,mp->tp", oh.astype(jnp.float32), cumg, precision=hi
            )  # [W, P]
            pu_in = jnp.sum(cg_at <= slot[:, None].astype(jnp.float32), axis=1)
            pu_abs = machine * P + pu_in.astype(i32)
            return granted, pu_abs

        def group_costs(gspec: GroupSpec, cost_cm):
            """[G, M] effective per-unit place cost and shifted solve
            matrix for group mode. Route via the class EC costs
            e_g + cost[cls_g, m]; a preference override (pref_w) wins
            where cheaper — exactly min(EC route, preference arc), the
            two parallel paths a task has in the reference graph
            (updateTaskNode wiring, graph_manager.go:1183-1264)."""
            if cost_fn is None:
                route = jnp.broadcast_to(gspec.e[:, None], (Gn, M))
            else:
                # exact integer row gather — costs are NOT counts, so
                # the one-hot f32 matmul trick (which silently rounds
                # values >= 2^24 even at HIGHEST) is not usable here;
                # G row gathers from a [C, M] table are cheap
                cost_gm = cost_cm[jnp.clip(gspec.cls, 0, C - 1)]
                route = cost_gm + gspec.e[:, None]
            cost_eff = jnp.minimum(route, gspec.pref_w)
            w = cost_eff - gspec.u[:, None]
            return cost_eff, w

        def round_core(state: DeviceClusterState, gspec=None,
                       decode_width=None, window_offset=None,
                       supersteps_cap=None):
            """One scheduling round. decode_width (static) bounds the
            decode to a compacted window of that many unplaced rows —
            the admission-batch bound (the reference bounds per-round
            work the same way via pod batching, k8sclient/client.go:
            153-193): tasks beyond the window stay pending for a later
            round. window_offset (traced scalar) rotates which backlog
            ranks the window covers; steady rounds pass a random offset
            so solver-escaped tasks parked in low rows cannot occupy
            the window forever and starve placeable tasks behind them.
            With decode_width=None the decode spans all Tcap rows (the
            fill path). Bounding matters at 50k+ tasks: the decode's
            [width, M] passes dominate the non-solve round cost.

            supersteps_cap (static) bounds this round's TOTAL
            transport budget below the cluster-wide `supersteps`
            safety bound — on the grouped two-stage path the cap is
            split across attempts (the stage-1 spend is subtracted
            from the full-solve fallback's budget), so a
            budget-exhausted stage 1 plus its fallback stay within
            the documented escalated-tail bound. A capped solve may
            return converged=False, which the three-tier hybrid uses
            as its escalation signal (the caller discards the
            attempt)."""
            ss_budget = (
                supersteps
                if supersteps_cap is None
                else min(int(supersteps_cap), supersteps)
            )
            pu_free = jnp.where(
                jnp.repeat(state.machine_enabled, P),
                S - state.pu_running,
                i32(0),
            )
            machine_free = pu_free.reshape(M, P).sum(axis=1)

            unplaced = state.live & (state.pu < 0)
            if decode_width is None:
                backlog = jnp.sum(unplaced, dtype=i32)
                W = Tcap
                idx = None  # identity window
                valid = unplaced
                cls_w = state.cls
                job_w = state.job
                grp_w = state.grp
            else:
                W = int(decode_width)
                # compact W unplaced rows into the window: select the
                # cyclic rank interval [off, off+W) of the backlog and
                # find each rank's row by binary search in the running
                # count (scatter-free; the [W] gathers that follow are
                # cheap at W << Tcap). Ranks within the valid prefix are
                # distinct, so no row enters the window twice.
                cum_act = jnp.cumsum(unplaced.astype(i32))
                backlog = cum_act[-1]  # one reduction serves window + stats
                num_active = jnp.minimum(backlog, i32(W))
                off = i32(0) if window_offset is None else window_offset
                # rotate only when the window binds: a non-binding
                # window covers the whole backlog anyway, and keeping
                # row order makes the bounded path bit-identical to the
                # full path in that regime
                off = jnp.where(backlog > i32(W), off, i32(0))
                denom = jnp.maximum(i32(1), backlog)
                target = (off % denom + jnp.arange(W, dtype=i32)) % denom
                idx = jnp.searchsorted(cum_act, target + 1).astype(i32)
                valid = jnp.arange(W, dtype=i32) < num_active
                idx = jnp.where(valid, jnp.clip(idx, 0, Tcap - 1), Tcap)
                cls_w = jnp.where(
                    valid, state.cls[jnp.clip(idx, 0, Tcap - 1)], i32(C)
                )
                job_w = jnp.where(
                    valid, state.job[jnp.clip(idx, 0, Tcap - 1)], i32(0)
                )
                grp_w = jnp.where(
                    valid, state.grp[jnp.clip(idx, 0, Tcap - 1)], i32(Gn)
                )
            # group index per window row; sentinel Gn for invalid rows
            if grouped:
                g_w = grp_w
            else:
                g_w = (job_w * i32(C) + cls_w) if per_job else cls_w
            g_safe = jnp.where(valid, g_w, i32(Gn))
            supply = jnp.zeros(Gn + 1, i32).at[g_safe].add(1)[:Gn]
            total = jnp.sum(supply)

            if cost_fn is not None:
                cost_cm = cost_fn(census_of(state)).astype(i32)
            else:
                cost_cm = jnp.zeros((C, M), i32)
            if grouped:
                cost_eff, w = group_costs(gspec, cost_cm)
            else:
                # group rows: g = j*C + c carries class c's cost row and
                # job j's escape cost (the per-job unsched differentiation)
                cost_gm = jnp.tile(cost_cm, (J, 1)) if per_job else cost_cm
                cost_eff = cost_gm + i32(e_cost)
                w = cost_eff - u_row[:, None]
            # int32 headroom guard: the host solver raises OverflowError
            # for the same condition (solver/layered.py solve_layered);
            # in a jitted round we can only flag it — surfaced in stats
            # and asserted by fetch_stats.
            cost_overflow = jnp.max(jnp.abs(w)) >= i32(
                COST_SCALE_LIMIT // n_scale
            )

            wS = jnp.zeros((Gn, Mp), i32).at[:, :M].set(w * i32(n_scale))
            col_cap = (
                jnp.zeros(Mp, i32).at[:M].set(machine_free).at[Mp - 1].set(total)
            )
            # With no class cost model the cost matrix is statically
            # uniform across classes — the degenerate collapse avoids
            # the iterative solve entirely (closed form + class split).
            # Deliberately COLD-started every round (pm0=None): carrying
            # the previous round's near-optimal machine prices flattens
            # reduced costs to ~0 across thousands of machines, which
            # destroys the cost discrimination the synchronous maximal
            # push relies on and recreates the identical-cost herding
            # pathology — measured 20x SLOWER (9ms -> 197ms/round on the
            # CoCo 50k config) than cold tightening, which re-derives
            # prices from the cost structure each round.
            if row_constant:
                # machine-uniform rows (per-job unsched, no cost model):
                # the fractional-knapsack closed form — no iterations
                y = solve_row_constant(w[:, 0], supply, col_cap)
                solve_steps, converged = i32(0), jnp.bool_(True)
            elif not grouped:
                # eps0 from choose_eps0 (n_scale/4 — see the round-3
                # tail study in default_eps0's docstring: deeply
                # sub-quantum starts cause multi-thousand-superstep
                # tail rounds; exactly optimal for any start, with the
                # in-graph fallback to the full schedule covering
                # pathologies). Oversubscribed rounds (backlog > free
                # slots) switch to the full-range start — choose_eps0.
                eps_full = jnp.maximum(jnp.max(jnp.abs(wS)), i32(1))
                y, _pm, solve_steps, converged = transport_fori(
                    wS, supply, col_cap, ss_budget,
                    alpha=alpha,
                    eps0=choose_eps0(
                        n_scale, eps_full, total, jnp.sum(machine_free)
                    ),
                    class_degenerate=class_degenerate,
                    refine_waves=refine_waves,
                )
            else:
                # Grouped solves: (a) EXACT two-stage decomposition for
                # the locality structure (row-constant ground + sparse
                # preference overrides — cost_fn None): with every
                # row's ground profitable and the round not
                # oversubscribed, all units place, so total cost =
                # sum(ground_g * supply_g) (a constant) minus the
                # discount recovered on pref cells; stage 1 maximizes
                # discounts on the SPARSE pref cells alone, stage 2
                # spreads leftovers in closed form. The one-shot dense
                # solve herds on the uniform ground cells instead —
                # measured 27k-43k supersteps on real steady rounds.
                # (b) Row COMPACTION: steady backlogs touch ~a hundred
                # of the G groups; compacting to the active rows cuts
                # per-superstep cost ~G/active and keeps the instance
                # inside the fused kernel's VMEM budget.
                # (c) alpha=2 + price refinement: fine phases whose
                # flows carry over (only violations re-flood) resolve
                # the pref-contention price fights in ~2.7k supersteps
                # where coarse re-flooding phases took ~35k.
                ground = gspec.e - gspec.u  # [G] route - escape
                can_two_stage = cost_fn is None
                if can_two_stage:
                    D = jnp.maximum(ground[:, None] - w, i32(0))  # [G, M]
                    w1 = jnp.where(D > 0, -D, i32(1))
                    wS1 = jnp.zeros((Gn, Mp), i32).at[:, :M].set(
                        w1 * i32(n_scale)
                    )
                else:
                    wS1 = wS  # unused

                def grouped_solve(wS_x, wS1_x, supply_x, ground_x):
                    """Solve one grouped instance (row count from the
                    input shapes); returns (y, steps, converged)."""
                    total_x = jnp.sum(supply_x)
                    eps_full_x = jnp.maximum(jnp.max(jnp.abs(wS_x)), i32(1))

                    def solve_full(_, budget=ss_budget):
                        # eps0 = n_scale for grouped instances (not the
                        # global n_scale/4 default): the round-3 tail
                        # study's grouped replay shows blocked quincy
                        # rounds at 1.0-3.3k supersteps from the
                        # full-unit start vs 7.2-13.3k from n/4 and
                        # ~134k from eps0=1 — the sparse strong
                        # discounts over uniform ground want full-unit
                        # price-war steps (tools/tail_repro.py
                        # replay-grouped).
                        y_f, _pmf, s_f, c_f = transport_fori(
                            wS_x, supply_x, col_cap, budget,
                            alpha=2, refine_waves=8,
                            eps0=choose_eps0(
                                n_scale, eps_full_x, total_x,
                                jnp.sum(machine_free),
                                short=n_scale,
                            ),
                        )
                        return y_f, s_f, c_f

                    if not can_two_stage:
                        return solve_full(None)

                    def solve_two_stage(_):
                        # Stage-1 schedule per two_stage_eps0 (see
                        # __init__): "one" finishes the sparse matching
                        # in tens of waves when discounts are near-
                        # uniform but pays deep descents in unit
                        # bounces on heavy-tailed discounts; "quarter"
                        # flips that trade. Bounded HONESTLY either way
                        # (eps0_retry=False: no internal full-range
                        # retry on the discount matrix) with the
                        # refined full solve of the ORIGINAL matrix as
                        # the fallback.
                        if stage1_quarter:
                            s1_eps0 = jnp.maximum(i32(1), i32(n_scale // 4))
                            # 2048, not 1024: the multiblock max-tail
                            # round was a pure budget exhaustion — the
                            # captured monster needed ~1286 stage-1
                            # supersteps, got cut at 1024, and paid a
                            # ~3350-superstep full fallback on top
                            # (4374 total, ~30 ms; 16-instance r5
                            # replay sweep, tools/tail_repro.py
                            # replay-grouped). Typical rounds converge
                            # far below either bound, so the extra
                            # headroom costs nothing except on
                            # instances that would blow BOTH budgets,
                            # which the capture population does not
                            # contain.
                            s1_budget = 2048
                        else:
                            s1_eps0 = i32(1)
                            s1_budget = 256
                        y1, _pm1, s1, conv1 = transport_fori(
                            wS1_x, supply_x, col_cap, ss_budget,
                            alpha=2, refine_waves=8,
                            eps0=s1_eps0, eps0_budget=s1_budget,
                            eps0_retry=False,
                        )

                        def finish_two_stage(_):
                            y1r = y1[:, :M]
                            left = supply_x - jnp.sum(y1r, axis=1).astype(i32)
                            rem = machine_free - jnp.sum(y1r, axis=0).astype(
                                i32
                            )
                            excl = jnp.cumsum(rem) - rem
                            grants_m = jnp.clip(jnp.sum(left) - excl, 0, rem)
                            y2 = split_grants_by_class(grants_m, left)
                            y_out = y1.at[:, :M].add(y2.astype(i32))
                            # escape column: anything beyond real capacity
                            y_out = y_out.at[:, Mp - 1].set(
                                supply_x
                                - jnp.sum(y_out[:, :M], axis=1).astype(i32)
                            )
                            return y_out, s1, conv1

                        def fall_back(_):
                            # round-total budget (ADVICE r5 #2): the
                            # exhausted stage 1 spent up to s1_budget
                            # of the cap, so the fallback gets the
                            # remainder — the two attempts together
                            # honor supersteps_cap instead of each
                            # claiming it. A cap at or below s1_budget
                            # leaves no remainder: return the failed
                            # attempt as-is (conv1 is False on this
                            # branch; the caller's escalation discards
                            # it) instead of a futile token solve.
                            fb_budget = ss_budget - min(s1_budget, ss_budget)
                            if fb_budget <= 0:
                                return y1, s1, conv1
                            y_f, s_f, c_f = solve_full(
                                None, budget=fb_budget
                            )
                            return y_f, s1 + s_f, c_f

                        return lax.cond(
                            conv1, finish_two_stage, fall_back, operand=None
                        )

                    two_stage_ok = (
                        (total_x <= jnp.sum(machine_free))
                        & jnp.all((ground_x < 0) | (supply_x == 0))
                    )
                    return lax.cond(
                        two_stage_ok, solve_two_stage, solve_full,
                        operand=None,
                    )

                caps = tuple(c for c in active_caps if c < Gn)
                n_active_rows = jnp.sum((supply > 0).astype(i32))
                if caps:
                    act = supply > 0
                    order = jnp.argsort(~act, stable=True)
                    n_act = n_active_rows

                    def compact_at(Gc):
                        sel = order[:Gc]
                        valid_c = act[sel]

                        def path(_):
                            sup_c = jnp.where(valid_c, supply[sel], i32(0))
                            y_c, s_c, c_c = grouped_solve(
                                wS[sel], wS1[sel], sup_c, ground[sel]
                            )
                            y_f = jnp.zeros((Gn, Mp), i32).at[sel].add(
                                jnp.where(valid_c[:, None], y_c, i32(0))
                            )
                            return y_f, s_c, c_c

                        return path

                    def full_path(_):
                        return grouped_solve(wS, wS1, supply, ground)

                    # ladder: smallest width that fits n_act wins; the
                    # widths are static (one compiled solve each), the
                    # choice is dynamic — no recompile as the live
                    # signature count drifts between maintenance points
                    def make_rung(Gc, wider):
                        def rung(_):
                            return lax.cond(
                                n_act <= i32(Gc), compact_at(Gc), wider,
                                operand=None,
                            )

                        return rung

                    branch = full_path
                    for Gc in reversed(caps):
                        branch = make_rung(Gc, branch)
                    y, solve_steps, converged = branch(None)
                else:
                    y, solve_steps, converged = grouped_solve(
                        wS, wS1, supply, ground
                    )
            y_real = y[:, :M]

            # ---- decode: rank-match placed tasks to machine grants ----
            decode = (rank_match_decode_grouped if use_sorted_decode
                      else rank_match_decode)
            placed_w, pu_abs = decode(g_safe, y_real, pu_free)

            if idx is None:
                # identity window: elementwise select, no scatter
                new_pu = jnp.where(placed_w, pu_abs, state.pu)
                pr_idx = jnp.where(placed_w, pu_abs, num_pus)
            else:
                # compacted window: scatter the W placements back (rows
                # beyond Tcap — invalid/unplaced — are dropped)
                tgt = jnp.where(placed_w, idx, Tcap)
                new_pu = state.pu.at[tgt].set(pu_abs, mode="drop")
                pr_idx = jnp.where(placed_w, pu_abs, num_pus)
            pu_running = (
                jnp.zeros(num_pus + 1, i32)
                .at[pr_idx].add(1)[:num_pus]
                + state.pu_running
            )
            placed_count = jnp.sum(placed_w, dtype=i32)
            # unscheduled counts the WHOLE backlog left pending (solver
            # escapes + rows beyond the decode window) — matches the
            # host BulkCluster's num_unsched accounting
            if per_job or grouped:
                # per-group escape pricing needs the whole-pool backlog
                # split by group, not just the window's
                g_all = (
                    state.grp if grouped
                    else state.job * i32(C) + state.cls
                )
                g_all_safe = jnp.where(unplaced, g_all, i32(Gn))
                backlog_g = jnp.zeros(Gn + 1, i32).at[g_all_safe].add(1)[:Gn]
                placed_g = jnp.sum(y_real, axis=1).astype(i32)
                u_g = gspec.u if grouped else u_row
                objective = jnp.sum(u_g * (backlog_g - placed_g)) + jnp.sum(
                    cost_eff * y_real
                )
            else:
                objective = i32(u_cost) * (backlog - placed_count) + jnp.sum(
                    cost_eff * y_real
                )
            stats = {
                "placed": placed_count,
                "unscheduled": backlog - placed_count,
                "converged": converged,
                "cost_overflow": cost_overflow,
                "objective": objective,
                "live": jnp.sum(state.live, dtype=i32),
                # solver supersteps this round (0 on closed-form paths)
                # — the observability the reference parses and discards
                # (placement/solver.go:169-170)
                "supersteps": solve_steps,
            }
            if grouped:
                # which compaction rung carried the solve (ladder tuning)
                stats["active_groups"] = n_active_rows
            return state._replace(pu=new_pu, pu_running=pu_running), stats

        def round_core_preempt(state: DeviceClusterState, gspec=None,
                               decode_width=None, window_offset=None,
                               scope_m=None):
            """Preemption-on round (keep-arcs semantics, graph_manager.
            go:855-888): every live task re-solves. Staying on the
            current machine is discounted, moving pays full price,
            escaping pays the group's unsched cost; machine capacity is
            TOTAL slots (the :662-667 capacity rule with preemption
            on). Decode: per cell (group, machine), min(grant,
            residents) residents are retained in row order; remaining
            grants go to "movers" (displaced residents + backlog),
            yielding MIGRATE for re-granted residents, PLACE for fresh
            tasks, PREEMPT for residents left without a grant. A
            displaced resident can never be re-granted its own machine
            (rem[g,m] > 0 forces retained[g,m] = R[g,m]), so the three
            delta kinds are disjoint by construction.

            decode_width (static) bounds the MOVER decode to a
            compacted window, as round_core's does for the backlog:
            stays need no decode (they keep their PU), and steady-state
            movers are ~churn-sized, so the [W, M] decode passes shrink
            from Tcap-wide (the 21 ms fixed floor measured at
            Tcap=65536 on coco50k-preempt) to window-wide. Movers
            beyond a binding window keep pu=-1 this round and re-enter
            the next solve — the same pending semantics as the bounded
            backlog window; window_offset rotates coverage so none
            starves.

            scope_m (traced bool[M] or None) is the SCOPED re-solve of
            the three-tier stability scheme (VERDICT r4 #2): residents
            on out-of-scope machines are pinned in place (they stay,
            consume capacity, and pay their discounted cost in the
            objective) and only residents on in-scope machines plus the
            backlog re-solve. Soundness (per-interval bound): cost
            columns are census-determined, and an out-of-scope machine
            moved < preempt_scope_tau (L1) SINCE THE LAST FIRED ROUND
            — so within one interval its cost column moved < tau times
            the cost model's census Lipschitz constant and pinning it
            is an eps-bounded approximation. The bound is per
            interval, not cumulative: the drift reference re-bases
            globally at every fired round (the per-machine variant ran
            away — see hybrid_round), so sub-tau-per-interval drift
            can accumulate unpriced until the GLOBAL re-solve
            (preempt_global_every) re-prices every column. That global
            backstop, plus the measured realized-cost parity tests,
            is the quality contract. Multi-hop chains through
            out-of-scope machines are deferred the same way — as the
            reference's delta-proportional incremental rounds defer
            them (placement/solver.go:60-90)."""
            enabled_pu = jnp.repeat(state.machine_enabled, P)
            live = state.live
            placed = live & (state.pu >= 0)
            cur_pu = jnp.clip(state.pu, 0, num_pus - 1)
            cur_m = jnp.where(placed, cur_pu // P, i32(M))  # sentinel M
            if grouped:
                g_t = state.grp
            else:
                g_t = (state.job * i32(C) + state.cls) if per_job else state.cls

            if scope_m is None:
                in_scope_res = placed
                forced = jnp.zeros_like(placed)
            else:
                scope_pad = jnp.concatenate(
                    [scope_m, jnp.zeros(1, jnp.bool_)]
                )
                in_scope_res = placed & scope_pad[cur_m]
                forced = placed & ~in_scope_res
            solve_live = live & (~placed | in_scope_res)
            g_safe = jnp.where(solve_live, g_t, i32(Gn))
            supply = jnp.zeros(Gn + 1, i32).at[g_safe].add(1)[:Gn]
            total = jnp.sum(supply)

            # forced (out-of-scope) stays consume machine capacity
            forced_m = jnp.where(forced, cur_m, i32(M))
            F_m = jnp.zeros(M + 1, i32).at[forced_m].add(1)[:M]
            col_cap_m = jnp.where(
                state.machine_enabled, i32(P * S) - F_m, i32(0)
            )

            if cost_fn is not None:
                cost_cm = cost_fn(census_of(state)).astype(i32)
            else:
                cost_cm = jnp.zeros((C, M), i32)
            if grouped:
                cost_eff, w = group_costs(gspec, cost_cm)
            else:
                cost_gm = jnp.tile(cost_cm, (J, 1)) if per_job else cost_cm
                cost_eff = cost_gm + i32(e_cost)
                w = cost_eff - u_row[:, None]
            cost_overflow = (
                jnp.max(jnp.abs(w)) + i32(discount)
            ) >= i32(COST_SCALE_LIMIT // n_scale)

            # resident census per cell [Gn, M] (in-scope placed tasks)
            cell = jnp.where(
                in_scope_res, g_t * i32(M) + cur_m, i32(Gn * M)
            )
            R_real = (
                jnp.zeros(Gn * M + 1, i32).at[cell].add(1)[: Gn * M]
            ).reshape(Gn, M)

            wS_hi = jnp.zeros((Gn, Mp), i32).at[:, :M].set(w * i32(n_scale))
            wS_lo = wS_hi.at[:, :M].add(-i32(discount * n_scale))
            R_pad = jnp.zeros((Gn, Mp), i32).at[:, :M].set(R_real)
            col_cap = (
                jnp.zeros(Mp, i32).at[:M].set(col_cap_m).at[Mp - 1].set(total)
            )
            eps_full = jnp.maximum(jnp.max(jnp.abs(wS_hi)), i32(1))
            # full-unit start for the tiered re-solve (short=n_scale):
            # the round-3 tiered replay sweep measured it 2-6x under
            # the global n/4 default on captured preemption rounds
            # (22.6k -> 8.5k supersteps worst), refinement on
            eps0 = choose_eps0(
                n_scale, eps_full, total, jnp.sum(col_cap_m),
                short=n_scale,
            )
            if discount == 0 and row_constant:
                # tiers coincide AND rows are machine-uniform: the
                # fractional-knapsack closed form on the all-live supply
                y = solve_row_constant(w[:, 0], supply, col_cap)
                solve_steps, converged = i32(0), jnp.bool_(True)
            elif discount == 0:
                # tiers coincide: the ordinary solve (incl. the
                # degenerate collapse) is exact on the all-live supply
                y, _pm, solve_steps, converged = transport_fori(
                    wS_hi, supply, col_cap, supersteps, alpha=alpha,
                    eps0=eps0,
                    class_degenerate=class_degenerate,
                    refine_waves=refine_waves,
                )
            else:
                y, _pm, solve_steps, converged = transport_fori_tiered(
                    wS_lo, wS_hi, R_pad, supply, col_cap, supersteps,
                    alpha=alpha, eps0=eps0, refine_waves=refine_waves,
                )
            y_real = y[:, :M]

            # ---- decode ----
            retained = jnp.minimum(y_real, R_real)  # residents kept
            rem = y_real - retained  # grants for movers

            # per-cell resident ranks (row order) via one stable sort
            order = jnp.argsort(cell, stable=True)
            counts = jnp.zeros(Gn * M + 1, i32).at[cell].add(1)
            starts = jnp.cumsum(counts) - counts
            rank_sorted = jnp.arange(Tcap, dtype=i32) - starts[cell[order]]
            rank_cell = jnp.zeros(Tcap, i32).at[order].set(rank_sorted)
            ret_flat = jnp.concatenate([retained.reshape(-1), jnp.zeros(1, i32)])
            stay = forced | (
                in_scope_res
                & (rank_cell < ret_flat[jnp.clip(cell, 0, Gn * M)])
            )

            # movers: every live task not staying; their grants fill
            # the slots left after stays
            mover = live & ~stay
            stay_pu = jnp.where(stay, cur_pu, num_pus)
            pu_stay = jnp.zeros(num_pus + 1, i32).at[stay_pu].add(1)[:num_pus]
            pu_free_mv = jnp.where(enabled_pu, i32(S) - pu_stay, i32(0))
            decode = (rank_match_decode_grouped if use_sorted_decode
                      else rank_match_decode)
            if decode_width is None:
                g_mv = jnp.where(mover, g_t, i32(Gn))
                granted, pu_abs = decode(g_mv, rem, pu_free_mv)
                new_pu = jnp.where(
                    stay, state.pu, jnp.where(granted, pu_abs, i32(-1))
                )
                granted_full = granted & mover
            else:
                Wm = int(decode_width)
                cum_mv = jnp.cumsum(mover.astype(i32))
                n_mv = cum_mv[-1]
                off = i32(0) if window_offset is None else window_offset
                off = jnp.where(n_mv > i32(Wm), off, i32(0))
                denom = jnp.maximum(i32(1), n_mv)
                target = (off % denom + jnp.arange(Wm, dtype=i32)) % denom
                idx = jnp.searchsorted(cum_mv, target + 1).astype(i32)
                valid = jnp.arange(Wm, dtype=i32) < jnp.minimum(n_mv, i32(Wm))
                idx = jnp.where(valid, jnp.clip(idx, 0, Tcap - 1), Tcap)
                g_mv_w = jnp.where(
                    valid, g_t[jnp.clip(idx, 0, Tcap - 1)], i32(Gn)
                )
                granted_w, pu_abs_w = decode(g_mv_w, rem, pu_free_mv)
                tgt = jnp.where(granted_w, idx, Tcap)
                base_pu = jnp.where(stay, state.pu, i32(-1))
                new_pu = base_pu.at[tgt].set(pu_abs_w, mode="drop")
                granted_full = (
                    jnp.zeros(Tcap + 1, jnp.bool_)
                    .at[tgt].set(True, mode="drop")[:Tcap]
                )
            final_on = live & (new_pu >= 0)
            pu_idx = jnp.where(final_on, new_pu, num_pus)
            pu_running = jnp.zeros(num_pus + 1, i32).at[pu_idx].add(1)[:num_pus]

            placed_total = jnp.sum(y_real, dtype=i32)
            # objective: placements at the effective route cost,
            # retained residents rebated by the discount, escapes at
            # the group unsched cost
            u_g = gspec.u if grouped else u_row
            objective = (
                jnp.sum(cost_eff * y_real)
                - i32(discount) * jnp.sum(retained)
                + jnp.sum(u_g * (supply - jnp.sum(y_real, axis=1)))
            )
            if scope_m is not None:
                # forced (out-of-scope) stays pay their discounted cost
                # so scoped and global objectives price the same pool
                F_gm = (
                    jnp.zeros(Gn * M + 1, i32)
                    .at[jnp.where(forced, g_t * i32(M) + cur_m, i32(Gn * M))]
                    .add(1)[: Gn * M]
                ).reshape(Gn, M)
                objective = (
                    objective + jnp.sum(cost_eff * F_gm)
                    - i32(discount) * jnp.sum(F_gm, dtype=i32)
                )
            stats = {
                "placed": jnp.sum(granted_full & ~placed, dtype=i32),
                "migrated": jnp.sum(granted_full & placed, dtype=i32),
                "preempted": jnp.sum(
                    placed & ~stay & ~granted_full, dtype=i32
                ),
                "unscheduled": total - placed_total,
                "converged": converged,
                "cost_overflow": cost_overflow,
                "objective": objective,
                "live": jnp.sum(live, dtype=i32),
                "supersteps": solve_steps,
            }
            return state._replace(pu=new_pu, pu_running=pu_running), stats

        def realized_cluster_cost(state: DeviceClusterState, gspec):
            """Price the CURRENT assignment at this state's census:
            every placed task pays its group's effective route cost on
            its machine, every unplaced live task pays its group's
            escape cost. One number both preemption regimes share, so
            the stability-aware scheme's objective drift vs the
            full-re-solve-every-round regime is directly measurable
            (the parity contract of VERDICT r3 #1)."""
            if cost_fn is not None:
                cost_cm = cost_fn(census_of(state)).astype(i32)
            else:
                cost_cm = jnp.zeros((C, M), i32)
            if grouped:
                cost_eff, _ = group_costs(gspec, cost_cm)
            else:
                cost_gm = jnp.tile(cost_cm, (J, 1)) if per_job else cost_cm
                cost_eff = cost_gm + i32(e_cost)
            if grouped:
                g_t = state.grp
            else:
                g_t = (state.job * i32(C) + state.cls) if per_job else state.cls
            on = state.live & (state.pu >= 0)
            m_t = jnp.clip(state.pu, 0, num_pus - 1) // P
            g_c = jnp.clip(g_t, 0, Gn - 1)
            c_task = cost_eff[g_c, m_t]
            u_g = gspec.u if grouped else u_row
            esc = u_g[g_c]
            # int32 is ample: Tcap * max cost stays well under 2^31 for
            # every wired model (costs clamp at ~2.5k, escape costs a
            # few units above)
            return (
                jnp.sum(jnp.where(on, c_task, i32(0)), dtype=i32)
                + jnp.sum(jnp.where(state.live & ~on, esc, i32(0)), dtype=i32)
            )

        def hybrid_round(state, census_ref, k_since, kg_since, gspec,
                         window_offset):
            """Stability-aware preemption round (see preempt_every /
            preempt_drift in __init__): the cheap incremental core
            (residents pinned, bounded backlog decode) serves steady
            rounds; the full tiered re-solve fires on schedule or when
            the running census drifts past the threshold. Both cores
            live under one lax.cond — only the taken branch executes,
            so round cost tracks the delta, as the reference's
            incremental solver does (placement/solver.go:60-90)."""
            cen = census_of(state)
            drift = jnp.sum(jnp.abs(cen - census_ref), dtype=i32)
            do_full = k_since + 1 >= i32(preempt_every)
            if preempt_drift > 0:
                do_full = do_full | (drift >= i32(preempt_drift))
            # three-tier scheme (preempt_global_every > 0): cadence /
            # drift rounds run the SCOPED re-solve over drifted columns
            # + backlog; a rare GLOBAL re-solve catches the multi-hop
            # chains scoping defers. With the knob off every full round
            # is global (round-4 behavior, bit-preserved).
            do_global = (
                kg_since + 1 >= i32(global_every)
                if global_every > 0 else do_full
            )

            def full_branch(_):
                s2, st = round_core_preempt(
                    state, gspec, decode_width=None, window_offset=None
                )
                return s2, census_of(s2), st

            def scoped_branch(_):
                scope = (
                    jnp.sum(jnp.abs(cen - census_ref), axis=1)
                    >= i32(scope_tau)
                )
                s2, st = round_core_preempt(
                    state, gspec,
                    decode_width=scoped_width,
                    window_offset=window_offset,
                    scope_m=scope,
                )
                # the reference re-bases GLOBALLY here, exactly like a
                # full round — deliberately. The per-machine variant
                # (advance only in-scope refs so sub-tau drifters
                # accumulate toward tau) was measured and REVERTED:
                # each scoped round's ~10k migration landings add ~10
                # L1 to machines outside the scope, so under per-
                # machine refs nearly every machine crosses tau within
                # one interval and both the scope and the drift trigger
                # run away (149/160 rounds fired, scoped supersteps
                # back at full-solve size — docs/NOTES.md round-5).
                # The price of global re-basing: a machine drifting
                # < tau per interval is re-based every fired round and
                # never enters scope; its stale pricing is corrected
                # only by the preempt_global_every backstop.
                return s2, census_of(s2), st

            def incr_branch(_):
                s2, st = round_core(
                    state, gspec,
                    decode_width=steady_decode_width,
                    window_offset=window_offset,
                    supersteps_cap=incr_budget,
                )
                st = dict(st)
                st.pop("active_groups", None)  # preempt core has none
                st["migrated"] = i32(0)
                st["preempted"] = i32(0)
                st["escalated"] = jnp.bool_(False)
                if incr_budget is None:
                    return s2, census_ref, st

                # Escalation (three-tier only, enforced in __init__): a
                # budget-exhausted incremental attempt is DISCARDED and
                # the round re-runs as a scoped re-solve from the same
                # pre-round state — re-pricing the drifted columns is
                # exactly what the sloshing admission solve was missing.
                # The attempt's supersteps stay in the round's count
                # (real work the round paid for).
                def keep(_):
                    return s2, census_ref, st

                def escalate(_):
                    s3, cen3, st3 = scoped_branch(None)
                    st3 = dict(st3)
                    st3["escalated"] = jnp.bool_(True)
                    st3["supersteps"] = st3["supersteps"] + st["supersteps"]
                    return s3, cen3, st3

                return lax.cond(st["converged"], keep, escalate, operand=None)

            if global_every > 0:
                def resolve_branch(_):
                    s2, cen2, st = lax.cond(
                        do_global, full_branch, scoped_branch, operand=None
                    )
                    st = dict(st)
                    st["escalated"] = jnp.bool_(False)
                    return s2, cen2, st

                state2, census_ref2, stats = lax.cond(
                    do_full | do_global, resolve_branch, incr_branch,
                    operand=None,
                )
                stats = dict(stats)
                escalated = stats.pop("escalated")
                # an escalated round IS a fired (scoped) round: census
                # re-based, cadence counter reset, scope forensics
                # attribute it to the scoped tier
                fired = do_full | do_global | escalated
                kg_since2 = jnp.where(do_global, i32(0), kg_since + 1)
            else:
                def full_branch_tagged(_):
                    s2, cen2, st = full_branch(None)
                    st = dict(st)
                    st["escalated"] = jnp.bool_(False)
                    return s2, cen2, st

                state2, census_ref2, stats = lax.cond(
                    do_full, full_branch_tagged, incr_branch, operand=None
                )
                stats = dict(stats)
                escalated = stats.pop("escalated")
                fired = do_full
                kg_since2 = kg_since
            k_since2 = jnp.where(fired, i32(0), k_since + 1)
            stats["full_round"] = fired
            stats["global_round"] = do_global if global_every > 0 else fired
            stats["escalated_round"] = escalated
            stats["census_drift"] = drift
            if track_realized:
                stats["realized_cost"] = realized_cluster_cost(state2, gspec)
            return state2, census_ref2, k_since2, kg_since2, stats

        def admit(state: DeviceClusterState, jobs, classes, groups, count):
            """Occupy the first `count` free rows with the first `count`
            entries of (jobs, classes, groups). Returns (state,
            admitted): admitted < count when the task pool is exhausted
            — the host BulkCluster raises for this; here the shortfall
            is reported so add_tasks can check it after fetch."""
            free_rank = jnp.cumsum(~state.live) - 1  # rank among free rows
            newmask = ~state.live & (free_rank < count)
            src_idx = jnp.clip(free_rank, 0, Tcap - 1)
            admitted = jnp.sum(newmask, dtype=i32)
            return state._replace(
                live=state.live | newmask,
                cls=jnp.where(newmask, classes[src_idx].astype(i32), state.cls),
                job=jnp.where(newmask, jobs[src_idx].astype(i32), state.job),
                grp=jnp.where(newmask, groups[src_idx].astype(i32), state.grp),
                pu=jnp.where(newmask, i32(-1), state.pu),
            ), admitted

        def complete(state: DeviceClusterState, rows, count):
            """Retire `count` task rows (first `count` entries of `rows`)."""
            k = jnp.arange(Tcap, dtype=jnp.int32)
            sel = k < count
            idx = jnp.where(sel, rows, Tcap)
            done = jnp.zeros(Tcap + 1, jnp.bool_).at[idx].set(True)[:Tcap]
            done = done & state.live
            pu_idx = jnp.where(done & (state.pu >= 0), state.pu, num_pus)
            pu_running = (
                jnp.zeros(num_pus + 1, i32).at[pu_idx].add(1)[:num_pus]
            )
            return state._replace(
                live=state.live & ~done,
                pu=jnp.where(done, i32(-1), state.pu),
                pu_running=state.pu_running - pu_running,
            )

        def set_machine(state: DeviceClusterState, machine_index, enabled):
            """Elastic membership (RegisterResource/DeregisterResource,
            flowscheduler/scheduler.go:134-210): disabling evicts the
            machine's tasks back to the unscheduled pool."""
            me = state.machine_enabled.at[machine_index].set(enabled)
            on_machine = (
                state.live
                & (state.pu >= 0)
                & ((jnp.clip(state.pu, 0, num_pus - 1) // P) == machine_index)
            )
            disabled = jnp.bool_(not enabled)
            evict = on_machine & disabled
            pu_mask = (jnp.arange(num_pus, dtype=i32) // P) == machine_index
            pu_running = jnp.where(
                pu_mask & disabled, i32(0), state.pu_running
            )
            return state._replace(
                machine_enabled=me,
                pu=jnp.where(evict, i32(-1), state.pu),
                pu_running=pu_running,
            )

        def steady_round(carry, gspec, key, churn_prob,
                         arrivals, arrival_map, arrival_n):
            """One benchmark round: complete ~churn_prob of running
            tasks, admit `arrivals` new ones (random job/class — or a
            random GROUP in group mode, drawn uniformly over the first
            `arrival_n` entries of `arrival_map` [Gn] so the host can
            restrict arrivals to REGISTERED signatures when the table
            churns under LRU eviction — exactly uniform over the
            registered set, no tiling skew; class
            and job gathered from the group metadata), then schedule.
            Entirely on device so rounds chain without host sync — the
            incremental re-solve regime Flowlessly's daemon mode serves
            in the reference (placement/solver.go:60-90)."""
            if hybrid:
                state, census_ref, k_since, kg_since = carry
            else:
                state = carry
            k1, k2, k3, k4 = jax.random.split(key, 4)
            placed = state.live & (state.pu >= 0)
            done = placed & (
                jax.random.uniform(k1, (Tcap,)) < churn_prob
            )
            pu_idx = jnp.where(done, state.pu, num_pus)
            dec = jnp.zeros(num_pus + 1, i32).at[pu_idx].add(1)[:num_pus]
            state = state._replace(
                live=state.live & ~done,
                pu=jnp.where(done, i32(-1), state.pu),
                pu_running=state.pu_running - dec,
            )
            free_rank = jnp.cumsum(~state.live) - 1
            newmask = ~state.live & (free_rank < arrivals)
            if grouped:
                new_grp = arrival_map[
                    jax.random.randint(k2, (Tcap,), 0, arrival_n)
                ]
                new_cls = gspec.cls[new_grp]
                new_job = gspec.job[new_grp]
            else:
                new_grp = jnp.zeros(Tcap, i32)
                new_cls = jax.random.randint(k2, (Tcap,), 0, C)
                new_job = jax.random.randint(k3, (Tcap,), 0, J)
            state = state._replace(
                live=state.live | newmask,
                cls=jnp.where(newmask, new_cls, state.cls),
                job=jnp.where(newmask, new_job, state.job),
                grp=jnp.where(newmask, new_grp, state.grp),
                pu=jnp.where(newmask, i32(-1), state.pu),
            )
            admitted = jnp.sum(newmask, dtype=i32)
            # steady rounds bound the decode to the configured window;
            # the one-shot round() keeps the full width (fill path).
            # The random offset rotates the window over the backlog so
            # no pending task can be starved by earlier-row escapees.
            # Preemption mode bounds its MOVER decode the same way
            # (stays need no decode; movers are ~churn-sized).
            if hybrid:
                state, census_ref, k_since, kg_since, stats = hybrid_round(
                    state, census_ref, k_since, kg_since, gspec,
                    jax.random.randint(k4, (), 0, 1 << 30),
                )
            elif preempt:
                state, stats = round_core_preempt(
                    state, gspec,
                    decode_width=steady_decode_width,
                    window_offset=jax.random.randint(k4, (), 0, 1 << 30),
                )
            else:
                state, stats = round_core(
                    state,
                    gspec,
                    decode_width=steady_decode_width,
                    window_offset=jax.random.randint(k4, (), 0, 1 << 30),
                )
            stats["completed"] = jnp.sum(done, dtype=i32)
            stats["admitted"] = admitted
            out = (
                (state, census_ref, k_since, kg_since)
                if hybrid else state
            )
            return out, stats

        def replay_round(carry, gspec, xs):
            """One trace-replay round: machine toggles (with evictions),
            completions, admissions, then the scheduling round — the
            whole round's events pre-staged as fixed-width device
            arrays so a windowed trace replays as ONE scanned program
            (the TPU-idiomatic form of the reference's event loop,
            cmd/k8sscheduler/scheduler.go:120-188: host batches events
            into windows ahead of time, device consumes them without
            per-round host round-trips)."""
            if hybrid:
                state, census_ref, k_since, kg_since = carry
            else:
                state = carry
            aj, ac, ag, an, dr, dn, ti, ton, tn, key = xs
            Emax = ti.shape[0]
            Dmax = dr.shape[0]
            Amax = aj.shape[0]

            # --- machine toggles + evictions (set_machine, batched;
            # the host stager dedups per-window toggles keep-last, so
            # duplicate scatter indices cannot race) ---
            valid_t = jnp.arange(Emax, dtype=i32) < tn
            idx_t = jnp.where(valid_t, ti, i32(M))
            me = state.machine_enabled.at[idx_t].set(ton, mode="drop")
            on = state.live & (state.pu >= 0)
            machine_of = jnp.clip(state.pu, 0, num_pus - 1) // P
            evict = on & ~me[machine_of]
            pu2 = jnp.where(evict, i32(-1), state.pu)
            on2 = state.live & (pu2 >= 0)
            pu_idx = jnp.where(on2, pu2, num_pus)
            pu_running = jnp.zeros(num_pus + 1, i32).at[pu_idx].add(1)[:num_pus]
            state = state._replace(
                machine_enabled=me, pu=pu2, pu_running=pu_running
            )
            evicted = jnp.sum(evict, dtype=i32)

            # --- completions (complete(), in-scan form) ---
            kk = jnp.arange(Dmax, dtype=i32)
            idx_d = jnp.where(kk < dn, dr, i32(Tcap))
            done = (
                jnp.zeros(Tcap + 1, jnp.bool_).at[idx_d].set(True)[:Tcap]
                & state.live
            )
            pu_idx = jnp.where(done & (state.pu >= 0), state.pu, num_pus)
            dec = jnp.zeros(num_pus + 1, i32).at[pu_idx].add(1)[:num_pus]
            state = state._replace(
                live=state.live & ~done,
                pu=jnp.where(done, i32(-1), state.pu),
                pu_running=state.pu_running - dec,
            )

            # --- admissions (admit(), [Amax]-wide sources; the host
            # mirror predicts the same first-free-rows assignment) ---
            free_rank = jnp.cumsum(~state.live) - 1
            newmask = ~state.live & (free_rank < an)
            src = jnp.clip(free_rank, 0, Amax - 1)
            state = state._replace(
                live=state.live | newmask,
                cls=jnp.where(newmask, ac[src], state.cls),
                job=jnp.where(newmask, aj[src], state.job),
                grp=jnp.where(newmask, ag[src], state.grp),
                pu=jnp.where(newmask, i32(-1), state.pu),
            )
            admitted = jnp.sum(newmask, dtype=i32)

            if hybrid:
                state, census_ref, k_since, kg_since, stats = hybrid_round(
                    state, census_ref, k_since, kg_since, gspec,
                    jax.random.randint(key, (), 0, 1 << 30),
                )
            elif preempt:
                state, stats = round_core_preempt(
                    state, gspec,
                    decode_width=steady_decode_width,
                    window_offset=jax.random.randint(key, (), 0, 1 << 30),
                )
            else:
                state, stats = round_core(
                    state, gspec,
                    decode_width=steady_decode_width,
                    window_offset=jax.random.randint(key, (), 0, 1 << 30),
                )
            stats["evicted"] = evicted
            stats["admitted"] = admitted
            stats["completed"] = jnp.sum(done, dtype=i32)
            out = (
                (state, census_ref, k_since, kg_since)
                if hybrid else state
            )
            return out, stats

        def replay_scan(carry, gspec, aj, ac, ag, an, dr, dn, ti, ton, tn,
                        key0):
            keys = jax.random.split(key0, aj.shape[0])

            def body(s, xs):
                return replay_round(s, gspec, xs)

            return lax.scan(
                body, carry, (aj, ac, ag, an, dr, dn, ti, ton, tn, keys)
            )

        self._replay_scan_jit = jax.jit(replay_scan)  # kschedlint: disable=unregistered-program -- device-bulk replay machinery, bit-parity gated by tests/test_device_bulk.py

        core = round_core_preempt if preempt else round_core
        self._round_jit = jax.jit(core)  # kschedlint: disable=unregistered-program -- device-bulk replay machinery, bit-parity gated by tests/test_device_bulk.py
        self._admit_jit = jax.jit(admit)  # kschedlint: disable=unregistered-program -- device-bulk replay machinery, bit-parity gated by tests/test_device_bulk.py
        self._complete_jit = jax.jit(complete)  # kschedlint: disable=unregistered-program -- device-bulk replay machinery, bit-parity gated by tests/test_device_bulk.py
        self._set_machine_jit = jax.jit(set_machine, static_argnums=(2,))  # kschedlint: disable=unregistered-program -- device-bulk replay machinery, bit-parity gated by tests/test_device_bulk.py
        self._census_jit = jax.jit(census_of)  # kschedlint: disable=unregistered-program -- device-bulk replay machinery, bit-parity gated by tests/test_device_bulk.py

        def steady_scan(carry, gspec, key0, churn_prob, arrivals, num_rounds,
                        arrival_map, arrival_n):
            keys = jax.random.split(key0, num_rounds)

            def body(s, k):
                return steady_round(s, gspec, k, churn_prob, arrivals,
                                    arrival_map, arrival_n)

            return lax.scan(body, carry, keys)

        self._steady_scan_jit = jax.jit(steady_scan, static_argnums=(4, 5))  # kschedlint: disable=unregistered-program -- device-bulk replay machinery, bit-parity gated by tests/test_device_bulk.py

    # ------------------------------------------------------------------
    # host API
    # ------------------------------------------------------------------

    def add_tasks(self, count, job_ids=None, classes=None, groups=None) -> None:
        """Admit up to `count` tasks. The admitted count is kept on
        device in ``last_admitted`` (fetching it mid-run would poison
        dispatch latency on tunneled TPUs — see bench.py); callers that
        need the host BulkCluster's pool-exhausted error should check
        ``int(jax.device_get(self.last_admitted)) == count`` at a safe
        point. In group mode, `groups` assigns each task its
        interchangeability group (see GroupSpec / set_groups)."""
        jobs = np.zeros(self.Tcap, np.int32)
        cls = np.zeros(self.Tcap, np.int32)
        grp = np.zeros(self.Tcap, np.int32)
        if job_ids is not None:
            jobs[: len(job_ids)] = job_ids
        if classes is not None:
            cls[: len(classes)] = classes
        if groups is not None:
            if not self.grouped:
                raise ValueError("groups requires num_groups > 0")
            g = np.asarray(groups, np.int32)
            if ((g < 0) | (g >= self.G)).any():
                raise ValueError(
                    f"task group out of range [0, {self.G}): "
                    f"{g.min()}..{g.max()}"
                )
            grp[: len(g)] = g
            # round_core's census feeds cost_fn from per-task cls, so
            # grouped admissions must carry classes consistent with the
            # group table: derive them when omitted, validate otherwise.
            derived = self._groups_cls_host[g]
            if classes is None:
                cls[: len(g)] = derived
            else:
                got = np.asarray(classes, np.int32)
                if len(got) < len(g):
                    raise ValueError(
                        f"classes ({len(got)}) shorter than groups "
                        f"({len(g)}): every grouped task needs both"
                    )
                got = got[: len(g)]
                if (got != derived).any():
                    bad = int(np.nonzero(got != derived)[0][0])
                    raise ValueError(
                        f"task {bad}: class {got[bad]} inconsistent with "
                        f"group {g[bad]}'s class {derived[bad]}"
                    )
        self.state, self.last_admitted = self._admit_jit(
            self.state, jnp.asarray(jobs), jnp.asarray(cls),
            jnp.asarray(grp), jnp.int32(count)
        )

    def set_groups(
        self, cls=None, job=None, e=None, u=None, pref_w=None
    ) -> None:
        """Upload group metadata (group mode). Each argument updates
        the corresponding GroupSpec field ([G] arrays; pref_w [G, M],
        PREF_NONE = no preference); omitted fields keep their current
        values. Host -> device only — no recompilation (the arrays are
        traced arguments of the round programs)."""
        if not self.grouped:
            raise ValueError("set_groups requires num_groups > 0")
        limit = COST_SCALE_LIMIT // self.n_scale

        def _vec(name, val, cur, index_range=None):
            if val is None:
                return cur
            a = np.asarray(val, np.int64)  # kschedlint: host-only (host staging; cast at the jit boundary)
            if a.shape != (self.G,):
                raise ValueError(f"{name} must have shape ({self.G},), got {a.shape}")
            if index_range is not None:
                if a.size and ((a < 0) | (a >= index_range)).any():
                    raise ValueError(
                        f"{name} out of range [0, {index_range}): "
                        f"{a.min()}..{a.max()}"
                    )
            elif a.size and np.abs(a).max() >= limit:
                raise OverflowError(
                    f"{name} magnitude {np.abs(a).max()} exceeds the "
                    f"scaled-cost limit {limit}"
                )
            return jnp.asarray(a.astype(np.int32))

        pw = self.groups.pref_w
        if pref_w is not None:
            a = np.asarray(pref_w, np.int64)  # kschedlint: host-only (host staging; cast at the jit boundary)
            if a.shape != (self.G, self.M):
                raise ValueError(
                    f"pref_w must have shape ({self.G}, {self.M}), got {a.shape}"
                )
            real = a[a < PREF_NONE]
            if real.size and np.abs(real).max() >= limit:
                raise OverflowError(
                    f"pref_w magnitude {np.abs(real).max()} exceeds the "
                    f"scaled-cost limit {limit}"
                )
            pw = jnp.asarray(np.minimum(a, PREF_NONE).astype(np.int32))
        self.groups = GroupSpec(
            cls=_vec("cls", cls, self.groups.cls, index_range=self.C),
            job=_vec("job", job, self.groups.job, index_range=self.J),
            e=_vec("e", e, self.groups.e),
            u=_vec("u", u, self.groups.u),
            pref_w=pw,
        )
        if cls is not None:
            self._groups_cls_host = np.asarray(cls, np.int32).copy()

    def complete_tasks(self, rows) -> None:
        pad = np.full(self.Tcap, self.Tcap, np.int32)
        pad[: len(rows)] = rows
        self.state = self._complete_jit(
            self.state, jnp.asarray(pad), jnp.int32(len(rows))
        )

    def set_machine_enabled(self, machine_index: int, enabled: bool) -> None:
        self.state = self._set_machine_jit(
            self.state, jnp.int32(machine_index), bool(enabled)
        )

    def _scan_carry(self):
        """Scan carry: bare state, or (state, census_ref, k_since,
        kg_since) in stability-aware preemption mode."""
        if self.hybrid_preempt:
            return (
                self.state, self._hyb_census, self._hyb_k, self._hyb_kg
            )
        return self.state

    def _store_carry(self, carry):
        if self.hybrid_preempt:
            (self.state, self._hyb_census, self._hyb_k,
             self._hyb_kg) = carry
        else:
            self.state = carry

    def round(self) -> dict:
        """One scheduling round; returns un-fetched device stats (call
        fetch_stats() to materialize — the analogue of the reference's
        binding push AFTER the timed region). In stability-aware
        preemption mode this one-shot round is always a FULL tiered
        re-solve and resets the drift reference."""
        self.state, stats = self._round_jit(self.state, self.groups)
        if self.hybrid_preempt:
            self._hyb_census = self._census_jit(self.state)
            self._hyb_k = jnp.int32(0)
            self._hyb_kg = jnp.int32(0)
        self.last_stats = stats
        return stats

    def run_steady_rounds(
        self, num_rounds: int, churn_prob: float, arrivals: int, seed: int = 0
    ):
        """`num_rounds` chained churn rounds fully on device. Returns
        stacked stats (device arrays, un-fetched). In group mode,
        arrivals draw their group through the arrival map (identity by
        default; see set_arrival_groups)."""
        carry, stats = self._steady_scan_jit(
            self._scan_carry(),
            self.groups,
            jax.random.PRNGKey(seed),
            jnp.float32(churn_prob),
            int(arrivals),
            int(num_rounds),
            self._arrival_map,
            self._arrival_n,
        )
        self._store_carry(carry)
        self.last_stats = stats
        return stats

    def set_arrival_groups(self, gids) -> None:
        """Restrict on-device steady-round arrivals to these group ids:
        with LRU signature eviction the table has FREED rows between
        maintenance points, and uniform draws over [0, G) would admit
        tasks into them — zero-signature rows the real policy never
        populates. Draws are EXACTLY uniform over the registered set:
        the map is padded to [G] but the device draw indexes only its
        first len(gids) entries (no tiling skew toward low-indexed
        groups). Host -> device upload only; recompile-free (the map
        and count are traced args)."""
        if not self.grouped:
            raise ValueError("set_arrival_groups requires group mode")
        g = np.asarray(gids, np.int32)
        if g.size == 0 or ((g < 0) | (g >= self.G)).any():
            raise ValueError("gids must be non-empty, within [0, G)")
        if g.size > self.G:
            raise ValueError("more arrival gids than groups")
        self._arrival_map = jnp.asarray(np.resize(g, self.G))
        self._arrival_n = jnp.int32(g.size)

    def run_replay_rounds(self, schedule, seed: int = 0):
        """Replay `schedule` (a staged window schedule — see
        drivers/trace_replay.py DeviceTraceReplayDriver.stage) as one
        scanned device program: K rounds of machine toggles +
        completions + admissions + solve chained without host sync.
        Returns stacked stats (device arrays, un-fetched)."""
        carry, stats = self._replay_scan_jit(
            self._scan_carry(),
            self.groups,
            jnp.asarray(schedule["adm_job"]),
            jnp.asarray(schedule["adm_cls"]),
            jnp.asarray(schedule["adm_grp"]),
            jnp.asarray(schedule["adm_n"]),
            jnp.asarray(schedule["done_rows"]),
            jnp.asarray(schedule["done_n"]),
            jnp.asarray(schedule["tog_idx"]),
            jnp.asarray(schedule["tog_on"]),
            jnp.asarray(schedule["tog_n"]),
            jax.random.PRNGKey(seed),
        )
        self._store_carry(carry)
        self.last_stats = stats
        return stats

    def fetch_stats(self, stats=None) -> dict:
        got = jax.device_get(stats if stats is not None else self.last_stats)
        out = {k: np.asarray(v) for k, v in got.items()}
        if "cost_overflow" in out and bool(np.any(out["cost_overflow"])):
            raise OverflowError(
                "scaled layered costs overflow int32 in a device round "
                "(class_cost_fn values too large for "
                f"n_scale={self.n_scale}); the solve result is invalid"
            )
        return out

    def fetch_state(self) -> dict:
        got = jax.device_get(self.state)
        return got._asdict()

    # convenience for tests
    @property
    def num_live_tasks(self) -> int:
        return int(jax.device_get(jnp.sum(self.state.live)))

    @property
    def num_placed_tasks(self) -> int:
        return int(jax.device_get(jnp.sum(self.state.live & (self.state.pu >= 0))))
