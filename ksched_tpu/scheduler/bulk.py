"""Bulk array-native scheduling: the TPU fast path.

The object/event layer (scheduler/flow_scheduler.py) mirrors the
reference's per-task API; this module is the scale path the TPU rebuild
exists for. Cluster state lives directly in flat numpy arrays (the same
layout graph/device_export.py produces), task arrival/completion are
bulk vector operations, and a scheduling round is a handful of numpy
ops + one device solve + a vectorized decode — no per-task Python work.

Graph shape (the quincy-style aggregate topology, reference:
trivial_cost_modeler.go + graph_manager.go), generalized to C task
classes (C=1 for the trivial model; C=4 Sheep/Rabbit/Devil/Turtle for
CoCo / Whare-Map, task_desc.proto:25-30):

    task --(cost u, cap 1)--> unsched_agg[job]   --(cap #tasks)--> sink
    task --(cost e, cap 1)--> EC[class(task)]
    EC[c] --(cost cost[c,m], cap free_m)--> machine_m
    machine_m --(cap s, cost 0)--> PU --(cap s)--> sink

Node-id layout (dense rows, row 0 reserved):
    1 .. J                       unscheduled aggregators (one per job)
    J+1 .. J+C                   class ECs
    J+C+1 .. J+C+M               machines
    J+C+M+1 .. +M*P              PUs (P per machine)
    next                         sink
    task rows allocated/recycled after that.

Every task row is pre-wired with 1+C arcs (unsched + one per class EC)
so arc ENDPOINTS never change as rows are recycled across classes and
jobs — the solver's CSR plan is built exactly once per cluster. Only
capacities/costs flip. Per-round costs come from a vectorized cost-model
callback (`class_cost_fn`): census [M, C] -> cost matrix [C, M] — e.g.
costmodels.coco.coco_cost_matrix / costmodels.whare.whare_cost_matrix.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..graph.device_export import FlowProblem
from ..obs.spans import span, unwind
from ..solver.base import FlowSolver
from ..utils import next_pow2


@dataclass
class BulkRoundResult:
    placed_tasks: np.ndarray  # task row ids newly placed this round
    placed_pus: np.ndarray  # PU row each was placed on
    preempted_tasks: np.ndarray  # task rows whose placement was revoked
    num_unscheduled: int
    timing: Dict[str, float] = field(default_factory=dict)


class BulkCluster:
    """Flat-array cluster state + vectorized scheduling rounds."""

    def __init__(
        self,
        num_machines: int,
        pus_per_machine: int,
        slots_per_pu: int,
        num_jobs: int,
        backend: FlowSolver,
        unsched_cost: int = 5,
        ec_cost: int = 2,
        machine_cost_fn: Optional[Callable[["BulkCluster"], np.ndarray]] = None,
        class_cost_fn: Optional[Callable[["BulkCluster"], np.ndarray]] = None,
        num_task_classes: int = 1,
        task_capacity: int = 2_048,
        job_unsched_cost: Optional[np.ndarray] = None,
    ) -> None:
        self.M = num_machines
        self.P = pus_per_machine
        self.S = slots_per_pu
        self.J = num_jobs
        self.C = num_task_classes
        self.backend = backend
        self.unsched_cost = unsched_cost
        self.ec_cost = ec_cost
        # Per-job unsched-arc costs (the reference's per-job unsched
        # aggregators each carry their own cost, graph_manager.go:
        # 1291-1305 + interface.go TaskToUnscheduledAggCost). None =
        # every job at the scalar unsched_cost.
        from ..solver.layered import validate_job_unsched_cost

        self.job_unsched_cost = validate_job_unsched_cost(
            job_unsched_cost, num_jobs
        )
        self.machine_cost_fn = machine_cost_fn
        self.class_cost_fn = class_cost_fn

        C = self.C
        self.unsched0 = 1
        self.ec0 = 1 + num_jobs
        self.machine0 = self.ec0 + C
        self.pu0 = self.machine0 + num_machines
        self.num_pus = num_machines * pus_per_machine
        self.sink = self.pu0 + self.num_pus
        self.task0 = self.sink + 1

        self.n_cap = next_pow2(self.task0 + task_capacity)
        self.task_cap = self.n_cap - self.task0

        # Static arc slots: EC->machine (C*M, class-major), machine->PU
        # (num_pus), PU->sink (num_pus), unsched->sink (J). Task arc
        # slots follow, 1+C per task row (-> unsched agg, -> each EC).
        self.a_ecm0 = 0
        self.a_mpu0 = self.a_ecm0 + C * num_machines
        self.a_pusink0 = self.a_mpu0 + self.num_pus
        self.a_unsink0 = self.a_pusink0 + self.num_pus
        self.a_task0 = self.a_unsink0 + num_jobs
        self.arcs_per_task = 1 + C
        self.m_cap = next_pow2(self.a_task0 + self.arcs_per_task * self.task_cap)

        self.src = np.zeros(self.m_cap, np.int32)
        self.dst = np.zeros(self.m_cap, np.int32)
        self.cap = np.zeros(self.m_cap, np.int32)
        self.cost = np.zeros(self.m_cap, np.int32)
        self.excess = np.zeros(self.n_cap, np.int64)
        self.node_type = np.full(self.n_cap, -1, np.int8)

        # Task bookkeeping (dense per task row, relative to task0).
        # Rows are partitioned into per-job pools (row r belongs to job
        # r % J) and every row's arcs are pre-wired at init, so arc
        # endpoints NEVER change: the solver's CSR plan is built once and
        # reused for the lifetime of the cluster (the structure-churn
        # killer for per-round host work).
        self.machine_enabled = np.ones(num_machines, bool)
        self.task_live = np.zeros(self.task_cap, bool)
        self.task_job = np.zeros(self.task_cap, np.int32)
        self.task_class = np.zeros(self.task_cap, np.int32)
        self.task_pu = np.full(self.task_cap, -1, np.int32)  # PU row or -1
        self.pu_running = np.zeros(self.num_pus, np.int32)
        # Per-machine running-class census [M, C] — the vectorized
        # WhareMapStats (whare_map_stats.proto:12-18).
        self.machine_census = np.zeros((num_machines, C), np.int64)
        self._job_free: List[List[int]] = [
            [r for r in range(self.task_cap - 1, -1, -1) if r % num_jobs == j]
            for j in range(num_jobs)
        ]

        self._wire_static()

    # ------------------------------------------------------------------

    def _wire_static(self) -> None:
        M, P, J, C = self.M, self.P, self.J, self.C
        machines = np.arange(M, dtype=np.int32)
        pus = np.arange(self.num_pus, dtype=np.int32)
        jobs = np.arange(J, dtype=np.int32)

        # EC[c] -> machine arcs, class-major: arc a_ecm0 + c*M + m.
        for c in range(C):
            sl = slice(self.a_ecm0 + c * M, self.a_ecm0 + (c + 1) * M)
            self.src[sl] = self.ec0 + c
            self.dst[sl] = self.machine0 + machines
            self.cap[sl] = 0  # refreshed per round from free slots
            self.cost[sl] = 0

        sl = slice(self.a_mpu0, self.a_mpu0 + self.num_pus)
        self.src[sl] = self.machine0 + (pus // P)
        self.dst[sl] = self.pu0 + pus
        self.cap[sl] = self.S

        sl = slice(self.a_pusink0, self.a_pusink0 + self.num_pus)
        self.src[sl] = self.pu0 + pus
        self.dst[sl] = self.sink
        self.cap[sl] = self.S

        sl = slice(self.a_unsink0, self.a_unsink0 + J)
        self.src[sl] = self.unsched0 + jobs
        self.dst[sl] = self.sink
        self.cap[sl] = 0  # grows with live tasks per job

        # Pre-wire every task row's arc endpoints (capacity 0 until the
        # row is occupied); row r's job is r % J. Arc layout per row:
        # [0] -> unsched agg, [1+c] -> EC c.
        rows = np.arange(self.task_cap, dtype=np.int32)
        abs_rows = self.task0 + rows
        a0 = self.a_task0 + self.arcs_per_task * rows
        self.src[a0] = abs_rows
        self.dst[a0] = self.unsched0 + (rows % J)
        for c in range(C):
            self.src[a0 + 1 + c] = abs_rows
            self.dst[a0 + 1 + c] = self.ec0 + c

        from ..graph.flowgraph import NodeType

        self.node_type[self.unsched0 : self.unsched0 + J] = int(NodeType.JOB_AGGREGATOR)
        self.node_type[self.ec0 : self.ec0 + C] = int(NodeType.EQUIV_CLASS)
        self.node_type[self.machine0 : self.machine0 + M] = int(NodeType.MACHINE)
        self.node_type[self.pu0 : self.pu0 + self.num_pus] = int(NodeType.PU)
        self.node_type[self.sink] = int(NodeType.SINK)

    # ------------------------------------------------------------------
    # Bulk task lifecycle
    # ------------------------------------------------------------------

    def add_tasks(
        self,
        count: int,
        job_ids: Optional[np.ndarray] = None,
        classes: Optional[np.ndarray] = None,
    ) -> np.ndarray:
        """Admit `count` new tasks; returns their task rows (absolute ids)."""
        if job_ids is None:
            job_ids = np.zeros(count, np.int32)
        if classes is None:
            classes = np.zeros(count, np.int32)
        else:
            classes = np.asarray(classes, np.int32)
            if ((classes < 0) | (classes >= self.C)).any():
                raise ValueError(
                    f"task class out of range [0, {self.C}): {classes.min()}..{classes.max()}"
                )
        rows = np.empty(count, dtype=np.int32)
        for i, j in enumerate(job_ids):
            pool = self._job_free[int(j)]
            if not pool:
                raise RuntimeError(
                    f"task pool for job {int(j)} exhausted "
                    f"(capacity {self.task_cap // self.J} rows per job)"
                )
            rows[i] = pool.pop()
        abs_rows = self.task0 + rows
        self.task_live[rows] = True
        self.task_job[rows] = job_ids
        self.task_class[rows] = classes
        self.task_pu[rows] = -1
        self.excess[abs_rows] = 1
        from ..graph.flowgraph import NodeType

        self.node_type[abs_rows] = int(NodeType.UNSCHEDULED_TASK)
        # Arc endpoints are pre-wired (row pools are per-job); only
        # capacities and costs flip on — unsched arc plus the arc to the
        # task's OWN class EC.
        a0 = self.a_task0 + self.arcs_per_task * rows
        self.cap[a0] = 1
        if self.job_unsched_cost is not None:
            self.cost[a0] = self.job_unsched_cost[job_ids]
        else:
            self.cost[a0] = self.unsched_cost
        a_cls = a0 + 1 + classes
        self.cap[a_cls] = 1
        self.cost[a_cls] = self.ec_cost
        # unsched agg capacity grows per live task
        np.add.at(self.cap, self.a_unsink0 + job_ids, 1)
        return abs_rows

    def complete_tasks(self, abs_rows: np.ndarray) -> None:
        """Retire tasks (vectorized TaskCompleted): free their slots and
        remove their nodes/arcs."""
        rows = abs_rows - self.task0
        assert self.task_live[rows].all(), "completing a task that is not live"
        on_pu = self.task_pu[rows]
        placed = on_pu >= 0
        if placed.any():
            np.add.at(self.pu_running, on_pu[placed], -1)
            np.add.at(
                self.machine_census,
                (on_pu[placed] // self.P, self.task_class[rows[placed]]),
                -1,
            )
        # Placed tasks already gave back their unsched-agg capacity when
        # they were pinned (see round()); only unplaced ones return it now.
        if (~placed).any():
            np.add.at(self.cap, self.a_unsink0 + self.task_job[rows[~placed]], -1)
        self.task_live[rows] = False
        self.task_pu[rows] = -1
        self.excess[abs_rows] = 0
        self.node_type[abs_rows] = -1
        a0 = self.a_task0 + self.arcs_per_task * rows
        for k in range(self.arcs_per_task):
            self.cap[a0 + k] = 0
            self.cost[a0 + k] = 0
        for r in rows:
            self._job_free[int(r) % self.J].append(int(r))

    def set_machine_enabled(self, machine_index: int, enabled: bool) -> np.ndarray:
        """Elastic membership: bring a machine in/out of service
        (vectorized RegisterResource / DeregisterResource — reference:
        flowscheduler/scheduler.go:134-210). Disabling evicts every task
        placed on the machine back to the unscheduled pool; the next
        round reschedules them elsewhere. Returns the evicted task rows
        (absolute ids; empty on enable)."""
        self.machine_enabled[machine_index] = enabled
        if enabled:
            return np.empty(0, np.int32)
        pu_lo = machine_index * self.P
        pu_hi = pu_lo + self.P
        rows = np.nonzero(
            self.task_live & (self.task_pu >= pu_lo) & (self.task_pu < pu_hi)
        )[0]
        if not len(rows):
            return np.empty(0, np.int32)
        abs_rows = (self.task0 + rows).astype(np.int32)
        np.add.at(self.pu_running, self.task_pu[rows], -1)
        np.add.at(self.machine_census, (machine_index, self.task_class[rows]), -1)
        self.task_pu[rows] = -1
        # Un-pin: restore supply, re-open the task's arcs, and regrow the
        # unsched-agg escape capacity the pin consumed (inverse of the
        # pin step in round()).
        self.excess[abs_rows] = 1
        a0 = self.a_task0 + self.arcs_per_task * rows
        self.cap[a0] = 1
        self.cap[a0 + 1 + self.task_class[rows]] = 1
        np.add.at(self.cap, self.a_unsink0 + self.task_job[rows], 1)
        from ..graph.flowgraph import NodeType

        self.node_type[abs_rows] = int(NodeType.UNSCHEDULED_TASK)
        return abs_rows

    # ------------------------------------------------------------------
    # The scheduling round
    # ------------------------------------------------------------------

    def _refresh_capacities(self) -> None:
        """Per-round stats + capacity refresh (the vectorized equivalent
        of ComputeTopologyStatistics + updateEquivToResArcs)."""
        M, C = self.M, self.C
        pu_free = self.S - self.pu_running
        # Disabled machines (elastic membership / machine loss) offer no
        # capacity; their PUs are fenced at every layer of the topology.
        pu_free[~np.repeat(self.machine_enabled, self.P)] = 0
        machine_free = pu_free.reshape(M, self.P).sum(axis=1)
        # Every class EC offers each machine its full free capacity; the
        # machine node's outgoing arcs bottleneck the aggregate.
        self.cap[self.a_ecm0 : self.a_ecm0 + C * M] = np.tile(machine_free, C)
        # PU->sink and machine->PU capacity excludes running tasks
        # (capacityFromResNodeToParent with preemption off,
        # graph_manager.go:662-667).
        self.cap[self.a_mpu0 : self.a_mpu0 + self.num_pus] = pu_free
        self.cap[self.a_pusink0 : self.a_pusink0 + self.num_pus] = pu_free
        if self.class_cost_fn is not None:
            cost_cm = np.asarray(self.class_cost_fn(self), dtype=np.int32)
            assert cost_cm.shape == (C, M), f"class_cost_fn must return [C={C}, M={M}]"
            self.cost[self.a_ecm0 : self.a_ecm0 + C * M] = cost_cm.reshape(-1)
        elif self.machine_cost_fn is not None:
            cost_m = np.asarray(self.machine_cost_fn(self), dtype=np.int32)
            self.cost[self.a_ecm0 : self.a_ecm0 + C * M] = np.tile(cost_m, C)

    def _problem(self) -> FlowProblem:
        live = int(self.task_live.sum())
        placed = int((self.task_pu >= 0)[self.task_live].sum())
        self.excess[self.sink] = -(live - placed)
        return FlowProblem(
            num_nodes=self.n_cap,
            excess=self.excess,
            node_type=self.node_type,
            src=self.src,
            dst=self.dst,
            cap=self.cap,
            cost=self.cost,
            flow_offset=np.zeros(self.m_cap, np.int32),
            num_arcs=self.m_cap,
        )

    def round(self) -> BulkRoundResult:
        # Backends exposing solve_layered get the dense fast path: the
        # aggregate topology collapses to a [C, M+1] transportation
        # problem (solver/layered.py) — no CSR, no per-arc work.
        if hasattr(self.backend, "solve_layered"):
            return self._round_layered()
        timing: Dict[str, float] = {}
        with span("round", path="bulk"):
            with span("stats") as sp:
                self._refresh_capacities()
                # Placed tasks are pinned: zero their graph presence
                # (their slot stays accounted via pu_running, mirroring
                # pinTaskToNode + capacity accounting, preemption off).
            timing["stats_s"] = sp.dur_s

            with span("solve") as sp:
                problem = self._problem()
                result = self.backend.solve_traced(problem)
            timing["solve_s"] = sp.dur_s

            with span("decode") as sp:
                placed_tasks, placed_pus, num_unsched = self._decode(result.flow)
            timing["decode_s"] = sp.dur_s

            with span("apply") as sp:
                self._apply_placements(placed_tasks, placed_pus)
            timing["apply_s"] = sp.dur_s
        return BulkRoundResult(
            placed_tasks=placed_tasks,
            placed_pus=placed_pus,
            preempted_tasks=np.empty(0, np.int32),
            num_unscheduled=num_unsched,
            timing=timing,
        )

    def _apply_placements(self, placed_tasks: np.ndarray, placed_pus: np.ndarray) -> None:
        if not len(placed_tasks):
            return
        rows = placed_tasks - self.task0
        self.task_pu[rows] = placed_pus - self.pu0
        np.add.at(self.pu_running, placed_pus - self.pu0, 1)
        np.add.at(
            self.machine_census,
            ((placed_pus - self.pu0) // self.P, self.task_class[rows]),
            1,
        )
        # pin: remove the placed tasks' supply and arcs from the
        # flow problem; their slots are excluded via pu_running.
        self.excess[placed_tasks] = 0
        a0 = self.a_task0 + self.arcs_per_task * rows
        self.cap[a0] = 0
        self.cap[a0 + 1 + self.task_class[rows]] = 0
        np.add.at(self.cap, self.a_unsink0 + self.task_job[rows], -1)
        from ..graph.flowgraph import NodeType

        self.node_type[placed_tasks] = int(NodeType.SCHEDULED_TASK)

    def _round_layered(self) -> BulkRoundResult:
        """The dense fast path: aggregate counts -> [C, M+1] transport
        solve -> rank-matched decode. Produces the same objective as the
        generic path (tasks within a class are cost-interchangeable)."""
        timing: Dict[str, float] = {}
        M, C = self.M, self.C
        round_span = span("round", path="bulk_layered").__enter__()
        try:
            return self._round_layered_body(timing, M, C, round_span)
        except BaseException:
            # close whatever manual span is still open (stats/decode)
            # plus the round span, so the error is recorded and the
            # span parenting is restored for later rounds
            import sys

            unwind(round_span, *sys.exc_info())
            raise

    def _round_layered_body(self, timing, M, C, round_span):
        from ..solver.layered import LayeredProblem

        sp = span("stats").__enter__()
        self._refresh_capacities()  # keeps arrays/costs consistent for
        # checkpoints and for any later generic-path round
        pu_free = self.S - self.pu_running
        pu_free[~np.repeat(self.machine_enabled, self.P)] = 0
        machine_free = pu_free.reshape(M, self.P).sum(axis=1)
        unplaced = np.nonzero(self.task_live & (self.task_pu < 0))[0]
        cls = self.task_class[unplaced]
        cost_cm = self.cost[self.a_ecm0 : self.a_ecm0 + C * M].reshape(C, M)
        if self.job_unsched_cost is not None:
            # Per-job unsched costs make (job, class) pairs distinct
            # commodities: expand the row axis to G = J*C groups, row
            # g = j*C + c carrying class c's cost row and job j's
            # escape cost. The collapse stays exact — tasks within a
            # group are still interchangeable.
            grp = self.task_job[unplaced] * C + cls
            G = self.J * C
            supply = np.bincount(grp, minlength=G).astype(np.int32)
            lp = LayeredProblem(
                supply=supply,
                col_cap=machine_free.astype(np.int32),
                cost_cm=np.tile(cost_cm, (self.J, 1)),
                unsched_cost=self.unsched_cost,
                ec_cost=self.ec_cost,
                row_unsched_cost=np.repeat(self.job_unsched_cost, C),
            )
            row_of_task = grp
        else:
            G = C
            supply = np.bincount(cls, minlength=C).astype(np.int32)
            lp = LayeredProblem(
                supply=supply,
                col_cap=machine_free.astype(np.int32),
                cost_cm=cost_cm,
                unsched_cost=self.unsched_cost,
                ec_cost=self.ec_cost,
            )
            row_of_task = cls
        timing["stats_s"] = sp.finish()

        with span("solve", path="layered") as sp:
            res = self.backend.solve_layered(lp)
            # solver-interior telemetry (obs/soltel.py): the layered
            # backend is dispatched here, not through solve_traced, so
            # this is its publication seam — registry histograms +
            # per-superstep child spans under this solve span
            tel = getattr(self.backend, "last_telemetry", None)
            if tel is not None:
                from ..obs import soltel

                soltel.publish(tel, sp)
        timing["solve_s"] = sp.dur_s

        sp = span("decode").__enter__()
        y = res.y  # int64[G, M]
        placed_per_row = y.sum(axis=1)
        # Stage 1 — pick which tasks place (any within-row choice is
        # cost-identical) and pair them rank-for-rank with the machine
        # grants, machine-major per row. One stable argsort groups the
        # unplaced tasks row-major (row order preserved within a row),
        # so each row's first placed_per_row[g] entries pair with that
        # row's grants — O(n log n), no per-group rescans.
        order = np.argsort(row_of_task, kind="stable")
        counts = np.bincount(row_of_task, minlength=G)
        starts = np.concatenate(([0], np.cumsum(counts)[:-1]))
        sorted_grp = row_of_task[order]
        rank_in_row = np.arange(len(order), dtype=np.int64) - starts[sorted_grp]
        take = rank_in_row < placed_per_row[sorted_grp]
        placed_rows = unplaced[order[take]]
        # grants expanded row-major then machine-major — the same order
        # as placed_rows after the argsort
        machine_of_task = np.repeat(
            np.tile(np.arange(M, dtype=np.int64), G), y.reshape(-1)
        )
        # Stage 2 — split each machine's grant across its PUs in slot
        # order, then pair with tasks sorted (stably) by machine.
        t_m = y.sum(axis=0)
        pf2 = pu_free.reshape(M, self.P)
        excl = np.cumsum(pf2, axis=1) - pf2
        grants = np.clip(t_m[:, None] - excl, 0, pf2)
        assert (grants.sum(axis=1) == t_m).all(), "PU split infeasible"
        pu_grants = np.repeat(np.arange(self.num_pus, dtype=np.int64), grants.reshape(-1))
        order = np.argsort(machine_of_task, kind="stable")
        placed_pus = np.empty(len(placed_rows), dtype=np.int32)
        placed_pus[order] = (self.pu0 + pu_grants).astype(np.int32)
        placed_tasks = (self.task0 + placed_rows).astype(np.int32)
        timing["decode_s"] = sp.finish()

        with span("apply") as sp:
            self._apply_placements(placed_tasks, placed_pus)
        timing["apply_s"] = sp.dur_s
        round_span.finish()
        return BulkRoundResult(
            placed_tasks=placed_tasks,
            placed_pus=placed_pus,
            preempted_tasks=np.empty(0, np.int32),
            num_unscheduled=res.num_unsched,
            timing=timing,
        )

    def _decode(self, flow: np.ndarray) -> Tuple[np.ndarray, np.ndarray, int]:
        """Vectorized flow decomposition for the class-EC topology: each
        EC is a single hub, so any bijection between its inflow units
        (tasks of that class) and its outflow units (EC->machine flows)
        is a valid decomposition; likewise rank-matching machine inflow
        units to PU grants within each machine."""
        M, C = self.M, self.C
        rows = np.nonzero(self.task_live & (self.task_pu < 0))[0]
        a_cls = self.a_task0 + self.arcs_per_task * rows + 1 + self.task_class[rows]
        placed_mask = flow[a_cls] > 0
        placed_rows = rows[placed_mask]
        cls_of_placed = self.task_class[placed_rows]

        ecm = flow[self.a_ecm0 : self.a_ecm0 + C * M].astype(np.int64).reshape(C, M)
        mpu = flow[self.a_mpu0 : self.a_mpu0 + self.num_pus].astype(np.int64)
        assert ecm.sum() == len(placed_rows), (
            f"EC outflow {ecm.sum()} != placed tasks {len(placed_rows)}"
        )
        assert mpu.sum() == ecm.sum(), "machine->PU flow mismatch"

        # Stage 1 — task -> machine, per class: tasks of class c (row
        # order) pair rank-for-rank with repeat(machines, ecm[c]) (flow
        # conservation at EC c makes the counts equal).
        machine_of_task = np.empty(len(placed_rows), dtype=np.int64)
        for c in range(C):
            sel = cls_of_placed == c
            machine_of_task[sel] = np.repeat(np.arange(M, dtype=np.int64), ecm[c])
        # Stage 2 — machine -> PU: total machine inflow equals its PU
        # outflow; expand PU grants machine-major and pair them with the
        # placed tasks sorted (stably) by machine. Any within-machine
        # bijection is a valid decomposition.
        pu_grants = np.repeat(np.arange(self.num_pus, dtype=np.int64), mpu)
        order = np.argsort(machine_of_task, kind="stable")
        pus_for_tasks = np.empty(len(placed_rows), dtype=np.int32)
        pus_for_tasks[order] = (self.pu0 + pu_grants).astype(np.int32)

        num_unsched = int(self.task_live.sum() - (self.task_pu >= 0).sum() - len(placed_rows))
        return (self.task0 + placed_rows).astype(np.int32), pus_for_tasks, num_unsched

    # ------------------------------------------------------------------

    @property
    def num_live_tasks(self) -> int:
        return int(self.task_live.sum())

    @property
    def num_placed_tasks(self) -> int:
        return int((self.task_pu >= 0).sum())
