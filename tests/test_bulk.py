"""Bulk array-native scheduler: correctness vs the exact oracle and the
object-layer invariants."""

import numpy as np
import pytest

from ksched_tpu.scheduler.bulk import BulkCluster
from ksched_tpu.solver import ReferenceSolver
from ksched_tpu.solver.jax_solver import JaxSolver


def make_cluster(backend=None, machines=4, pus=2, slots=1, jobs=3, cap=256):
    return BulkCluster(
        num_machines=machines,
        pus_per_machine=pus,
        slots_per_pu=slots,
        num_jobs=jobs,
        backend=backend or ReferenceSolver(),
        task_capacity=cap,
    )


def test_fill_and_overload():
    c = make_cluster()  # 8 slots
    rng = np.random.default_rng(0)
    c.add_tasks(6, rng.integers(0, 3, 6).astype(np.int32))
    r = c.round()
    assert len(r.placed_tasks) == 6
    assert r.num_unscheduled == 0
    assert c.num_placed_tasks == 6
    # overload
    c.add_tasks(5, rng.integers(0, 3, 5).astype(np.int32))
    r = c.round()
    assert len(r.placed_tasks) == 2  # only 2 slots left
    assert r.num_unscheduled == 3
    # PU capacity respected
    assert (c.pu_running <= c.S).all()


def test_completion_frees_slots():
    c = make_cluster(machines=2, pus=1, slots=1, jobs=1)  # 2 slots
    c.add_tasks(4)
    r = c.round()
    assert len(r.placed_tasks) == 2
    done = r.placed_tasks[:1]
    c.complete_tasks(done)
    r = c.round()
    assert len(r.placed_tasks) == 1
    assert c.num_live_tasks == 3
    assert c.num_placed_tasks == 2


def test_task_row_recycling():
    c = make_cluster(machines=1, pus=1, slots=4, jobs=1, cap=8)
    for _ in range(5):
        rows = c.add_tasks(4)
        c.round()
        c.complete_tasks(rows)
    assert c.num_live_tasks == 0
    assert (c.pu_running == 0).all()
    # unsched agg capacity fully returned
    assert c.cap[c.a_unsink0] == 0


def test_jax_backend_bulk_parity():
    rng = np.random.default_rng(7)
    placed_counts = []
    for backend in (ReferenceSolver(), JaxSolver()):
        np_rng = np.random.default_rng(7)
        c = make_cluster(backend=backend, machines=5, pus=2, slots=2, jobs=4)
        seq = []
        c.add_tasks(15, np_rng.integers(0, 4, 15).astype(np.int32))
        r = c.round()
        seq.append((len(r.placed_tasks), r.num_unscheduled))
        c.add_tasks(10, np_rng.integers(0, 4, 10).astype(np.int32))
        r = c.round()
        seq.append((len(r.placed_tasks), r.num_unscheduled))
        done = np.nonzero(c.task_pu >= 0)[0][:6]
        c.complete_tasks(c.task0 + done.astype(np.int32))
        r = c.round()
        seq.append((len(r.placed_tasks), r.num_unscheduled))
        placed_counts.append(seq)
    assert placed_counts[0] == placed_counts[1]


def test_decode_assignment_consistency():
    """Each placed task gets a distinct slot-unit; per-PU occupancy
    matches the flow."""
    c = make_cluster(machines=3, pus=2, slots=2, jobs=2)  # 12 slots
    c.add_tasks(10, np.zeros(10, np.int32))
    r = c.round()
    assert len(r.placed_tasks) == 10
    # occupancy consistent
    occ = np.zeros(c.num_pus, np.int32)
    np.add.at(occ, r.placed_pus - c.pu0, 1)
    assert (occ == c.pu_running).all()
    assert (occ <= c.S).all()
