"""Tier-1 gate for the static-analysis suite (ksched_tpu/analysis/).

Level 1: the AST lint must be clean over the whole tree (zero
unsuppressed, unbaselined violations), and every rule must actually
fire on a seeded bad snippet — a lint that silently stopped matching
is worse than no lint.

Level 2: generic trace-level machinery (jaxpr_contracts) plus negative
tests proving each analysis detects a seeded violation.

Level 3 (ISSUE 18): the declarative program registry drives the whole
per-program sweep — one parametrized test runs every applicable check
(dtype/scatter/gather/collective contracts, telemetry-off hash pin,
pow2-bucket hash stability, telemetry-knob semantics, variant
distinctness, the compiled donation/aliasing audit, the mega VMEM
gate, module ownership) for every registered program. The hand-written
per-program test functions this replaces live on as registry data.
"""

import dataclasses
import json
import os
import subprocess
import sys
import textwrap

import pytest

from ksched_tpu.analysis import (
    RULES,
    lint_paths,
    load_baseline,
    program_coverage,
    split_by_baseline,
)
from ksched_tpu.analysis.ast_rules import collect_program_sites, build_context, lint_source
from ksched_tpu.analysis import jaxpr_contracts as jc
from ksched_tpu.analysis import engine
from ksched_tpu.analysis.program_registry import (
    PROGRAMS,
    SITE_NAMES,
    CollectiveBudget,
    DonationSpec,
    HashStability,
    call,
    donating_programs,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT_TARGETS = ["ksched_tpu", "tools", "bench.py"]
BASELINE = os.path.join(REPO_ROOT, "tools", "kschedlint_baseline.json")


# ---------------------------------------------------------------------------
# Level 1: the repo is lint-clean
# ---------------------------------------------------------------------------


def test_repo_is_lint_clean():
    violations = lint_paths(LINT_TARGETS, repo_root=REPO_ROOT)
    baseline = load_baseline(BASELINE)
    new, _old, stale = split_by_baseline(violations, baseline)
    assert not new, "new kschedlint violations:\n" + "\n".join(
        v.render() for v in new
    )
    assert not stale, f"stale baseline entries (fixed debt): {dict(stale)}"


def test_baseline_is_empty():
    """The ratchet starts clean: every seed violation was fixed or
    suppressed inline with a rationale (ISSUE 3 acceptance)."""
    with open(BASELINE) as fh:
        data = json.load(fh)
    assert data["violations"] == []


def test_cli_exits_zero():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.kschedlint", "ksched_tpu", "tools", "bench.py"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# Level 1+3: every rule fires on a seeded bad snippet
# ---------------------------------------------------------------------------

BAD_SNIPPETS = {
    "dtype64": """
        import numpy as np
        import jax.numpy as jnp

        def prep(n):
            a = np.zeros(n, dtype=np.int64)
            b = a.astype("float64")
            return jnp.asarray(a), b
    """,
    "implicit-dtype": """
        import jax.numpy as jnp

        def build(n):
            return jnp.zeros(n), jnp.arange(n), jnp.full((n,), 3)
    """,
    "jit-static": """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnames=("alpha",))
        def solve(x, alpha: int = 8, max_steps: int = 100):
            return x * alpha
    """,
    "traced-branch": """
        import jax

        @jax.jit
        def f(x, flag):
            if flag > 0:
                return x + 1
            while x:
                x = x - 1
            return x
    """,
    "mutable-default": """
        def accumulate(item, acc=[]):
            acc.append(item)
            return acc
    """,
    "bare-except": """
        def risky():
            try:
                return 1
            except:
                return 0
    """,
    "raw-print": """
        def report(msg):
            print(msg)
    """,
    "unregistered-program": """
        import jax

        fn = jax.jit(lambda x: x + 1)
    """,
    "stale-waiver": """
        import jax

        x = 1  # kschedlint: disable=raw-print -- nothing here prints
    """,
    "bad-waiver": """
        import jax

        y = 2  # kschedlint: disable=raw-pirnt -- typo'd rule name
    """,
}


@pytest.mark.parametrize("rule", sorted(RULES))
def test_rule_fires_on_bad_snippet(rule):
    source = textwrap.dedent(BAD_SNIPPETS[rule])
    # lint under a library path so library-scoped rules apply
    violations = lint_source(f"ksched_tpu/_snippet_{rule.replace('-', '_')}.py", source)
    assert any(v.rule == rule for v in violations), (
        f"rule {rule} did not fire; got {[v.rule for v in violations]}"
    )


def test_suppression_comment_silences_rule():
    source = (
        "import numpy as np\nimport jax\n"
        "x = np.zeros(4, dtype=np.int64)  # kschedlint: host-only (test)\n"
        "print('hi')  # kschedlint: disable=raw-print -- test\n"
    )
    assert lint_source("ksched_tpu/_snippet_suppress.py", source) == []


def test_suppression_does_not_leak_to_other_rules():
    source = (
        "import numpy as np\nimport jax\n"
        "x = np.zeros(4, dtype=np.int64)  "
        "# kschedlint: disable=raw-print -- wrong rule on purpose\n"
    )
    rules = [v.rule for v in lint_source("ksched_tpu/_s.py", source)]
    # the dtype64 violation survives; the raw-print waiver is dead on
    # this line, so the staleness audit also fires
    assert "dtype64" in rules and "stale-waiver" in rules


def test_baseline_is_a_multiset():
    """One baselined entry waives ONE occurrence: copy-pasting an
    accepted bad line elsewhere in the file still fails the gate."""
    from collections import Counter

    from ksched_tpu.analysis.baseline import fingerprint as fp

    source = (
        "import numpy as np\nimport jax\n"
        "a = np.zeros(4, dtype=np.int64)\n"
        "b = np.zeros(4, dtype=np.int64)\n"
    )
    violations = lint_source("ksched_tpu/_dup.py", source)
    assert len(violations) == 2
    e = fp(violations[0])
    baseline = Counter([(e["path"], e["rule"], e["hash"])])
    new, old, stale = split_by_baseline(violations, baseline)
    assert len(old) == 1 and len(new) == 1 and not stale


def test_unparsable_file_reports_syntax_error_violation():
    """A half-written .py must fail the gate with a clean diagnostic,
    not an ast.parse traceback."""
    violations = lint_source("ksched_tpu/_broken.py", "def f(:\n")
    assert [v.rule for v in violations] == ["syntax-error"]
    assert "does not parse" in violations[0].message


def test_is_none_branch_is_not_flagged():
    source = textwrap.dedent("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x, pm0=None):
            if pm0 is None:
                pm0 = jnp.zeros_like(x)
            return x + pm0
    """)
    assert not any(
        v.rule == "traced-branch"
        for v in lint_source("ksched_tpu/_s.py", source)
    )


# ---------------------------------------------------------------------------
# Level 3: the unaudited-program sweep
# ---------------------------------------------------------------------------


def _sweep(source):
    return [
        v for v in lint_source("ksched_tpu/_sweep.py", textwrap.dedent(source))
        if v.rule == "unregistered-program"
    ]


def test_sweep_finds_every_compile_entry_point():
    hits = _sweep("""
        import functools
        import jax
        from jax.experimental import pallas as pl
        from jax.experimental.shard_map import shard_map

        f1 = jax.jit(lambda x: x)

        @jax.jit
        def f2(x):
            return x

        @functools.partial(jax.jit, static_argnames=("k",))
        def f3(x, k: int = 2):
            return x * k

        def f4(x):
            return pl.pallas_call(lambda ref, o: None, out_shape=x)(x)

        def f5(fn, mesh, spec):
            return shard_map(fn, mesh=mesh, in_specs=spec, out_specs=spec)
    """)
    assert len(hits) == 5, [(v.line, v.message) for v in hits]


def test_sweep_accepts_registered_annotation_and_waiver():
    assert not _sweep("""
        import jax

        f1 = jax.jit(lambda x: x)  # kschedlint: program=csr_solve
        f2 = jax.jit(lambda x: x)  # kschedlint: disable=unregistered-program -- test scaffolding
    """)


def test_sweep_rejects_unknown_program_name():
    hits = _sweep("""
        import jax

        f1 = jax.jit(lambda x: x)  # kschedlint: program=no_such_program
    """)
    assert len(hits) == 1 and "names no registered program" in hits[0].message


def test_sweep_annotation_found_across_multiline_span():
    """A decorator like @functools.partial(jax.jit, donate_argnums=...)
    spans lines; the annotation rides whichever line is natural."""
    assert not _sweep("""
        import functools
        import jax

        @functools.partial(
            jax.jit,  # kschedlint: program=delta_apply
            donate_argnums=(0,),
        )
        def apply(buf):
            return buf + 1
    """)


def test_sweep_ignores_non_library_and_method_names():
    # outside ksched_tpu/: no sweep
    src = "import jax\nfn = jax.jit(lambda x: x)\n"
    assert not [
        v for v in lint_source("tools/_t.py", src)
        if v.rule == "unregistered-program"
    ]
    # a method merely NAMED like the wrapped callable is not a site
    assert not _sweep("""
        class Cell:
            def _round_jit(self, x):
                return x

            def step(self, x):
                return self._round_jit(x)
    """)


def test_unregistered_program_waiver_requires_rationale():
    hits = [
        v for v in lint_source("ksched_tpu/_w.py", textwrap.dedent("""
            import jax

            f1 = jax.jit(lambda x: x)  # kschedlint: disable=unregistered-program
        """))
        if v.rule == "bad-waiver"
    ]
    assert len(hits) == 1 and "rationale" in hits[0].message


def test_stale_program_annotation_is_flagged():
    hits = [
        v for v in lint_source("ksched_tpu/_w.py", textwrap.dedent("""
            import jax

            x = 1  # kschedlint: program=csr_solve
        """))
        if v.rule == "stale-waiver"
    ]
    assert len(hits) == 1 and "no jit/pallas_call/shard_map" in hits[0].message


def test_stale_host_only_waiver_is_flagged():
    hits = [
        v for v in lint_source("ksched_tpu/_w.py", textwrap.dedent("""
            import numpy as np
            import jax

            x = np.zeros(4, dtype=np.int32)  # kschedlint: host-only (nothing 64-bit here)
        """))
        if v.rule == "stale-waiver"
    ]
    assert len(hits) == 1 and "host-only" in hits[0].message


def test_live_waivers_are_not_stale():
    source = (
        "import numpy as np\nimport jax\n"
        "x = np.zeros(4, dtype=np.int64)  # kschedlint: host-only (test)\n"
    )
    assert not any(
        v.rule == "stale-waiver" for v in lint_source("ksched_tpu/_w.py", source)
    )


def test_bad_waiver_catches_unknown_directive_and_empty_disable():
    src = textwrap.dedent("""
        import jax

        a = 1  # kschedlint: supress=raw-print
        b = 2  # kschedlint: disable= -- nothing named
    """)
    rules = [v.rule for v in lint_source("ksched_tpu/_w.py", src)]
    assert rules.count("bad-waiver") == 2


def test_repo_coverage_is_total():
    """The ISSUE 18 acceptance: 100% call-site coverage — every
    jit/pallas_call/shard_map site in the library is annotated with a
    registered program or waived with a rationale, and every registered
    site name is annotated somewhere."""
    cov = program_coverage(LINT_TARGETS, repo_root=REPO_ROOT)
    assert cov["unaudited"] == [], cov["unaudited"]
    assert cov["unannotated_registered"] == []
    assert cov["sites"] == len(cov["annotated"]) + len(cov["waived"])
    assert len(cov["annotated"]) >= len(SITE_NAMES)


def test_collect_program_sites_classifies_kinds():
    ctx = build_context("ksched_tpu/_k.py", textwrap.dedent("""
        import jax
        from jax.experimental import pallas as pl

        a = jax.jit(lambda x: x)  # kschedlint: program=csr_solve
        b = pl.pallas_call(lambda r, o: None)  # kschedlint: program=mega_solve
    """))
    kinds = {s.kind for s in collect_program_sites(ctx)}
    assert kinds == {"jit", "pallas_call"}


# ---------------------------------------------------------------------------
# Level 3: registry sanity
# ---------------------------------------------------------------------------


def test_registry_matches_select():
    """The registry must cover what select.py can hand out: every
    in-process array backend rung has a registered solve program."""
    with open(os.path.join(REPO_ROOT, "ksched_tpu", "solver", "select.py")) as fh:
        select_src = fh.read()
    for rung, program in (
        ("jax", "csr_solve"), ("ell", "ell_solve"), ("mega", "mega_solve"),
        ("layered", "layered_solve"),
    ):
        assert f'name == "{rung}"' in select_src
        assert program in PROGRAMS
    assert "sharded_solve" in PROGRAMS  # parallel/ rung


def test_registry_policies_are_coherent():
    """Solve and audit programs never scatter; every scoped exemption
    is a maintenance program; chaos programs are never donation-audited
    (they are never dispatched in production)."""
    for spec in PROGRAMS.values():
        if spec.kind in ("solve", "audit"):
            assert spec.scatter_policy == "forbidden", spec.name
        if spec.scatter_policy == "scoped-exempt":
            assert spec.kind == "maintenance", spec.name
        if spec.kind == "chaos":
            assert spec.donation is None, spec.name
    assert len(donating_programs()) == 4
    assert {s.name for s in donating_programs()} == {
        "delta_apply", "plan_apply", "sharded_plan_apply", "replicated_plan_apply",
    }


def test_registry_pins_are_the_pretelemetry_baselines():
    """The five telemetry-off hash pins captured on the pre-telemetry
    tree (PR 7 base, jax 0.4.37) now live in the registry; this literal
    copy guards against an accidental registry edit re-pinning them.
    A jax upgrade that changes jaxpr printing re-pins BOTH in the same
    commit (verify the off-trace is otherwise unchanged first)."""
    assert {
        n: s.telemetry_off_hash
        for n, s in PROGRAMS.items() if s.telemetry_off_hash
    } == {
        "csr_solve": "92aa144400bd8869",
        "ell_solve": "9e101ad7b1bac615",
        "mega_solve": "2713247f0ce0fa0b",
        # sharded traces over the conftest 8-virtual-device mesh; its
        # hash is mesh-size-dependent (the others' are not)
        "sharded_solve": "b2c5ad0884934f47",
        "layered_solve": "efaf297e81829bd2",
    }


# ---------------------------------------------------------------------------
# Level 3: the engine enforces every registered program's contract.
# One test id per (program, applicable check) — skipped work would be
# visible as absent ids, not silently-passing ones.
# ---------------------------------------------------------------------------

REGISTRY_CASES = [
    (name, check)
    for name in sorted(PROGRAMS)
    for check in engine.applicable_checks(PROGRAMS[name])
]


@pytest.mark.parametrize(
    "program,check", REGISTRY_CASES, ids=[f"{p}-{c}" for p, c in REGISTRY_CASES]
)
def test_program_contract(program, check):
    engine.CHECKS[check](PROGRAMS[program])


def test_every_program_gets_contract_and_ownership_checks():
    for spec in PROGRAMS.values():
        checks = engine.applicable_checks(spec)
        assert "contracts" in checks and "declared" in checks, spec.name


# ---------------------------------------------------------------------------
# Level 2/3 bespoke: checks the generic engine cannot express
# ---------------------------------------------------------------------------


def test_csr_backend_shows_the_contrast():
    """The scan-CSR backend pays per-superstep HBM gathers (that is
    the megakernel's whole reason to exist) — if this ever reads 0 the
    gather classifier is broken, not the solver fixed. (The registry
    pins this as csr_solve's hbm_loop_min=1 canary; asserted directly
    here so a GatherBudget refactor can't drop it.)"""
    report = engine.report(PROGRAMS["csr_solve"])
    assert report.hbm_loop_gathers > 0


def test_mega_gate_refuses_exactly_where_estimate_exceeds_budget():
    """Beyond check_vmem_gate's safety/tightness: the dispatch gate's
    refusal boundary must coincide with the counted estimate across
    entry counts spanning tiny to beyond-budget."""
    from ksched_tpu.ops.mcmf_pallas import (
        _MEGA_VMEM_BUDGET_BYTES,
        MEGA_LANES,
        mega_entry_rows,
        mega_fits_vmem,
    )

    est = jc.estimate_mega_vmem(engine.trace_call(PROGRAMS["mega_solve"]))
    for entries in (512, 1 << 15, 1 << 18, 1 << 20, 1 << 22):
        padded = mega_entry_rows(entries) * MEGA_LANES
        counted_fits = est.gate_tiles * padded * 4 <= _MEGA_VMEM_BUDGET_BYTES
        assert mega_fits_vmem(entries) == counted_fits


# ---------------------------------------------------------------------------
# Level 2: negative tests — the generic analyses detect seeded violations
# ---------------------------------------------------------------------------


def _make_jaxpr(fn, *shapes):
    import jax
    import jax.numpy as jnp

    return jax.make_jaxpr(fn)(
        *(jax.ShapeDtypeStruct(s, jnp.int32) for s in shapes)
    )


def test_contract_catches_64bit_convert():
    import jax
    import jax.numpy as jnp

    def bad(x):
        return x.astype(jnp.float64).sum()

    # without x64, jax downcasts the seeded violation to f32 before the
    # checker could see it — exactly why the contract exists: if anyone
    # flips x64 on, 64-bit types flow silently
    with jax.experimental.enable_x64():
        closed = _make_jaxpr(bad, (8,))
    report = jc.check_jaxpr("bad", closed)
    assert not report.ok_64bit


def test_contract_catches_scatter():
    def bad(x, idx):
        return x.at[idx].add(1)

    report = jc.check_jaxpr("bad", _make_jaxpr(bad, (8,), (3,)))
    assert not report.ok_scatter


def test_contract_catches_loop_gather():
    import jax.numpy as jnp
    from jax import lax

    def bad(x, idx):
        def body(_, carry):
            return carry + x[idx].sum()

        return lax.fori_loop(0, 4, body, jnp.int32(0))

    report = jc.check_jaxpr("bad", _make_jaxpr(bad, (8,), (3,)))
    assert report.hbm_loop_gathers > 0


def test_contract_catches_bucket_leak():
    """A raw size leaking into a static arg splits the jaxpr hash —
    the exact failure mode of a forgotten pow2 pad."""
    import functools

    def leaky(x, scale: int = 1):
        return x * scale

    def trace(m_raw):
        fn = functools.partial(leaky, scale=m_raw)  # raw size as static
        return _make_jaxpr(fn, (64,))

    assert jc.jaxpr_hash(trace(40)) != jc.jaxpr_hash(trace(60))


# ---------------------------------------------------------------------------
# Level 3 negatives: the engine flags a seeded violation of each spec field
# ---------------------------------------------------------------------------


def test_donation_audit_catches_broken_donation():
    """The analysis the registry exists to host: a donated input whose
    every output needs a different dtype/shape cannot alias — XLA
    SILENTLY copies (a UserWarning at best), and only the compiled
    executable's input_output_alias tells the truth."""
    import jax
    import jax.numpy as jnp

    sds = (
        jax.ShapeDtypeStruct((8,), jnp.int32),
        jax.ShapeDtypeStruct((8,), jnp.int32),
    )

    def broken(a, b):
        # no output is alias-compatible with donated `a` (f32 vs i32,
        # scalar vs vector), so the donation is unusable
        return a.astype(jnp.float32) * 2.0, b.sum()

    rep = engine.audit_donation(jax.jit(broken, donate_argnums=(0,)), sds, (0,))
    assert not rep.ok
    assert 0 in rep.missing

    def good(a, b):
        return a + 1, b.sum()

    rep = engine.audit_donation(jax.jit(good, donate_argnums=(0,)), sds, (0,))
    assert rep.ok, (rep.missing, rep.unusable_warnings, rep.header)
    assert 0 in rep.aliased_params


def test_donation_check_fails_on_undeclared_argnum():
    """Auditing MORE argnums than the program donates must fail — the
    registry can't claim in-place behavior the executable lacks."""
    spec = dataclasses.replace(
        PROGRAMS["delta_apply"],
        donation=DonationSpec(donate_argnums=(0, 1, 2, 3, 4), builder="aot_delta_apply"),
    )
    with pytest.raises(engine.ContractError, match="NOT aliased"):
        engine.check_donation(spec)


def test_donation_check_fails_on_missing_builder():
    spec = dataclasses.replace(
        PROGRAMS["delta_apply"],
        donation=DonationSpec(donate_argnums=(0,), builder="aot_no_such_builder"),
    )
    with pytest.raises(engine.ContractError, match="builder"):
        engine.check_donation(spec)


def test_engine_flags_forbidden_scatter():
    spec = dataclasses.replace(PROGRAMS["delta_apply"], scatter_policy="forbidden")
    with pytest.raises(engine.ContractError, match="forbidden"):
        engine.check_contracts(spec)


def test_engine_flags_vacuous_scatter_exemption():
    spec = dataclasses.replace(
        PROGRAMS["warm_flow"], kind="maintenance", scatter_policy="scoped-exempt"
    )
    with pytest.raises(engine.ContractError, match="VACUOUS"):
        engine.check_contracts(spec)


def test_engine_flags_collective_budget_mismatch():
    spec = dataclasses.replace(
        PROGRAMS["sharded_slot_solve"],
        collectives=CollectiveBudget(loop=(("psum", 99),)),
    )
    with pytest.raises(engine.ContractError, match="psum count"):
        engine.check_contracts(spec)


def test_engine_flags_forbidden_collective():
    spec = dataclasses.replace(
        PROGRAMS["sharded_slot_solve"],
        collectives=CollectiveBudget(forbidden=("psum",)),
    )
    with pytest.raises(engine.ContractError, match="forbidden collective"):
        engine.check_contracts(spec)


def test_engine_flags_hash_pin_mismatch():
    spec = dataclasses.replace(
        PROGRAMS["csr_solve"], telemetry_off_hash="0000000000000000"
    )
    with pytest.raises(engine.ContractError, match="pinned"):
        engine.check_hash_pin(spec)


def test_engine_flags_cross_bucket_hash_split():
    """A `same` pair straddling two buckets must fail (and proves the
    stability check isn't comparing a hash to itself)."""
    spec = dataclasses.replace(
        PROGRAMS["csr_solve"],
        hash_stability=HashStability(
            "pow2-bucket", same=((call(12, 40), call(12, 200)),)
        ),
    )
    with pytest.raises(engine.ContractError, match="recompile hazard"):
        engine.check_hash_stability(spec)


def test_engine_flags_vacuous_cross_pair():
    spec = dataclasses.replace(
        PROGRAMS["csr_solve"],
        hash_stability=HashStability(
            "pow2-bucket", cross=((call(12, 40), call(15, 60)),)
        ),
    )
    with pytest.raises(engine.ContractError, match="vacuous"):
        engine.check_hash_stability(spec)


def test_engine_flags_vacuous_distinct_variant():
    spec = dataclasses.replace(PROGRAMS["csr_solve"], distinct_from=("csr_solve",))
    with pytest.raises(engine.ContractError, match="collides"):
        engine.check_distinct(spec)


def test_engine_flags_undeclared_ownership():
    spec = dataclasses.replace(PROGRAMS["csr_solve"], module="ksched_tpu.solver.base")
    with pytest.raises(engine.ContractError, match="declare_programs"):
        engine.check_declared(spec)


def test_engine_flags_missing_tracer():
    spec = dataclasses.replace(PROGRAMS["csr_solve"], tracer="trace_no_such_thing")
    with pytest.raises(engine.ContractError, match="does not exist"):
        engine.check_contracts(spec)


def test_registry_rejects_bad_vocabulary():
    with pytest.raises(ValueError, match="scatter policy"):
        dataclasses.replace(PROGRAMS["csr_solve"], scatter_policy="whatever")
    with pytest.raises(ValueError, match="dtype policy"):
        dataclasses.replace(PROGRAMS["csr_solve"], dtype_policy="int64")
    with pytest.raises(ValueError, match="reason"):
        HashStability("exempt")
    with pytest.raises(ValueError, match="kind"):
        HashStability("no-such-kind")


def test_declare_programs_rejects_typo_eagerly():
    from ksched_tpu.analysis.program_registry import declare_programs

    with pytest.raises(ValueError, match="unregistered program"):
        declare_programs("tests._fake_module", "csr_slove")


# ---------------------------------------------------------------------------
# Level 3 satellites: CLI flags
# ---------------------------------------------------------------------------


def _run_cli(*argv, timeout=120):
    """Drive the CLI in-process (argparse + real repo walk, no
    interpreter spawn — the end-to-end subprocess path is covered once
    by test_cli_exits_zero)."""
    import contextlib
    import io

    from tools import kschedlint

    out, err = io.StringIO(), io.StringIO()
    cwd = os.getcwd()
    os.chdir(REPO_ROOT)
    try:
        with contextlib.redirect_stdout(out), contextlib.redirect_stderr(err):
            try:
                rc = kschedlint.main(list(argv))
            except SystemExit as e:
                rc = e.code if isinstance(e.code, int) else 2
    finally:
        os.chdir(cwd)
    return subprocess.CompletedProcess(argv, rc, out.getvalue(), err.getvalue())


def test_cli_unknown_rule_exits_2():
    proc = _run_cli("--rules", "dtype64,no-such-rule", "tools")
    assert proc.returncode == 2
    assert "unknown rule" in proc.stderr


def test_cli_rules_subset_runs():
    proc = _run_cli("--rules", "dtype64,raw-print", "tools", "bench.py")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "2 rules" in proc.stderr


def test_cli_coverage_summary_line():
    proc = _run_cli("--coverage", "ksched_tpu", "tools", "bench.py")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert f"kschedlint L3: {len(PROGRAMS)} programs registered" in proc.stderr
    assert "0 unaudited" in proc.stderr


def test_cli_json_mode(tmp_path):
    proc = _run_cli("--json", "--coverage", "ksched_tpu", "tools", "bench.py")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["ok"] is True
    assert payload["new"] == [] and payload["stale_baseline"] == []
    cov = payload["coverage"]
    assert cov["unaudited"] == [] and cov["unannotated_registered"] == []
    assert cov["programs_registered"] == len(PROGRAMS)
    assert cov["sites"] == len(cov["annotated"]) + len(cov["waived"])


def test_cli_stale_baseline_fails_and_prune_sheds(tmp_path):
    """The shrink-only ratchet: a baseline entry matching no current
    violation is an ERROR (the debt was paid; the entry would silently
    excuse a regression), and --prune-baseline sheds exactly those
    entries without admitting anything new."""
    tree = tmp_path / "pkg"
    tree.mkdir()
    (tree / "clean.py").write_text("def f():\n    return 1\n")
    stale = tmp_path / "baseline.json"
    stale.write_text(json.dumps({
        "violations": [
            {"path": "pkg/gone.py", "rule": "dtype64", "hash": "0" * 16}
        ]
    }))
    proc = _run_cli("--baseline", str(stale), str(tree))
    assert proc.returncode == 1
    assert "stale baseline" in proc.stderr
    proc = _run_cli("--prune-baseline", "--baseline", str(stale), str(tree))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert json.loads(stale.read_text())["violations"] == []
