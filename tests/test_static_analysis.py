"""Tier-1 gate for the static-analysis suite (ksched_tpu/analysis/).

Level 1: the AST lint must be clean over the whole tree (zero
unsuppressed, unbaselined violations), and every rule must actually
fire on a seeded bad snippet — a lint that silently stopped matching
is worse than no lint.

Level 2: the jaxpr contracts hold for every registered backend at 3
representative shape buckets — no 64-bit converts, no scatters, the
megakernel's zero-HBM-gather budget, pow2-bucket jaxpr-hash stability,
and the VMEM estimate consistent with `mega_fits_vmem` — plus negative
tests proving each contract detects a seeded violation.
"""

import json
import os
import textwrap

import numpy as np
import pytest

from ksched_tpu.analysis import (
    RULES,
    lint_paths,
    load_baseline,
    split_by_baseline,
)
from ksched_tpu.analysis.ast_rules import lint_source
from ksched_tpu.analysis import jaxpr_contracts as jc

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT_TARGETS = ["ksched_tpu", "tools", "bench.py"]

#: 3 representative (n, m) shape buckets — interpreted as (C, M) by the
#: layered backend — small enough that abstract tracing stays cheap
SHAPE_BUCKETS = [(12, 40), (20, 100), (40, 220)]

#: raw-size pairs sharing a pow2 bucket, per hash-stable backend:
#: (n pads 16/32/64..., m pads to next_pow2(max(.,16)); layered M pads
#: to a multiple of 128 via pad_geometry with C untouched)
BUCKET_PAIRS = {
    "jax": [((12, 40), (15, 60)), ((20, 100), (30, 70)), ((40, 220), (60, 200))],
    "mega": [((12, 40), (15, 60)), ((20, 100), (30, 70)), ((40, 220), (60, 200))],
    "layered": [((4, 40), (4, 100)), ((4, 130), (4, 250)), ((8, 300), (8, 370))],
}

#: and pairs in DIFFERENT buckets, which must produce different jaxprs
#: (otherwise the stability check is vacuous)
CROSS_BUCKET_PAIRS = {
    "jax": ((12, 40), (12, 200)),
    "mega": ((12, 40), (12, 2000)),
    "layered": ((4, 40), (4, 300)),
}


# ---------------------------------------------------------------------------
# Level 1: the repo is lint-clean
# ---------------------------------------------------------------------------


def test_repo_is_lint_clean():
    violations = lint_paths(LINT_TARGETS, repo_root=REPO_ROOT)
    baseline = load_baseline(os.path.join(REPO_ROOT, "tools", "kschedlint_baseline.json"))
    new, _old, _stale = split_by_baseline(violations, baseline)
    assert not new, "new kschedlint violations:\n" + "\n".join(
        v.render() for v in new
    )


def test_baseline_is_empty():
    """The ratchet starts clean: every seed violation was fixed or
    suppressed inline with a rationale (ISSUE 3 acceptance)."""
    with open(os.path.join(REPO_ROOT, "tools", "kschedlint_baseline.json")) as fh:
        data = json.load(fh)
    assert data["violations"] == []


def test_cli_exits_zero():
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "-m", "tools.kschedlint", "ksched_tpu", "tools", "bench.py"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# Level 1: every rule fires on a seeded bad snippet
# ---------------------------------------------------------------------------

BAD_SNIPPETS = {
    "dtype64": """
        import numpy as np
        import jax.numpy as jnp

        def prep(n):
            a = np.zeros(n, dtype=np.int64)
            b = a.astype("float64")
            return jnp.asarray(a), b
    """,
    "implicit-dtype": """
        import jax.numpy as jnp

        def build(n):
            return jnp.zeros(n), jnp.arange(n), jnp.full((n,), 3)
    """,
    "jit-static": """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnames=("alpha",))
        def solve(x, alpha: int = 8, max_steps: int = 100):
            return x * alpha
    """,
    "traced-branch": """
        import jax

        @jax.jit
        def f(x, flag):
            if flag > 0:
                return x + 1
            while x:
                x = x - 1
            return x
    """,
    "mutable-default": """
        def accumulate(item, acc=[]):
            acc.append(item)
            return acc
    """,
    "bare-except": """
        def risky():
            try:
                return 1
            except:
                return 0
    """,
    "raw-print": """
        def report(msg):
            print(msg)
    """,
}


@pytest.mark.parametrize("rule", sorted(RULES))
def test_rule_fires_on_bad_snippet(rule):
    source = textwrap.dedent(BAD_SNIPPETS[rule])
    # lint under a library path so library-scoped rules apply
    violations = lint_source(f"ksched_tpu/_snippet_{rule.replace('-', '_')}.py", source)
    assert any(v.rule == rule for v in violations), (
        f"rule {rule} did not fire; got {[v.rule for v in violations]}"
    )


def test_suppression_comment_silences_rule():
    source = (
        "import numpy as np\nimport jax\n"
        "x = np.zeros(4, dtype=np.int64)  # kschedlint: host-only (test)\n"
        "print('hi')  # kschedlint: disable=raw-print -- test\n"
    )
    assert lint_source("ksched_tpu/_snippet_suppress.py", source) == []


def test_suppression_does_not_leak_to_other_rules():
    source = (
        "import numpy as np\nimport jax\n"
        "x = np.zeros(4, dtype=np.int64)  # kschedlint: disable=raw-print\n"
    )
    assert [v.rule for v in lint_source("ksched_tpu/_s.py", source)] == ["dtype64"]


def test_baseline_is_a_multiset():
    """One baselined entry waives ONE occurrence: copy-pasting an
    accepted bad line elsewhere in the file still fails the gate."""
    from ksched_tpu.analysis.baseline import fingerprint as fp

    source = (
        "import numpy as np\nimport jax\n"
        "a = np.zeros(4, dtype=np.int64)\n"
        "b = np.zeros(4, dtype=np.int64)\n"
    )
    from collections import Counter

    violations = lint_source("ksched_tpu/_dup.py", source)
    assert len(violations) == 2
    e = fp(violations[0])
    baseline = Counter([(e["path"], e["rule"], e["hash"])])
    new, old, stale = split_by_baseline(violations, baseline)
    assert len(old) == 1 and len(new) == 1 and not stale


def test_unparsable_file_reports_syntax_error_violation():
    """A half-written .py must fail the gate with a clean diagnostic,
    not an ast.parse traceback."""
    violations = lint_source("ksched_tpu/_broken.py", "def f(:\n")
    assert [v.rule for v in violations] == ["syntax-error"]
    assert "does not parse" in violations[0].message


def test_is_none_branch_is_not_flagged():
    source = textwrap.dedent("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x, pm0=None):
            if pm0 is None:
                pm0 = jnp.zeros_like(x)
            return x + pm0
    """)
    assert not any(
        v.rule == "traced-branch"
        for v in lint_source("ksched_tpu/_s.py", source)
    )


# ---------------------------------------------------------------------------
# Level 2: jaxpr contracts for every registered backend
# ---------------------------------------------------------------------------


def test_backend_registry_matches_select():
    """The contract suite must trace what select.py can hand out: every
    in-process array backend name in make_backend appears here."""
    with open(os.path.join(REPO_ROOT, "ksched_tpu", "solver", "select.py")) as fh:
        select_src = fh.read()
    for name in ("jax", "ell", "mega", "layered"):
        assert f'name == "{name}"' in select_src
        assert name in jc.REGISTERED_BACKENDS
    assert "sharded" in jc.REGISTERED_BACKENDS  # parallel/sharded_*


@pytest.mark.parametrize("bucket", SHAPE_BUCKETS, ids=str)
@pytest.mark.parametrize("backend", jc.REGISTERED_BACKENDS)
def test_contracts_no_64bit_no_scatter(backend, bucket):
    report = jc.backend_report(backend, *bucket)
    assert report.ok_64bit, report.violations_64bit
    assert report.ok_scatter, report.scatter_eqns
    assert report.num_eqns > 0


@pytest.mark.parametrize("bucket", SHAPE_BUCKETS, ids=str)
def test_mega_gather_budget_zero(bucket):
    """PR 1's claim, locked in: zero per-superstep HBM gathers. The
    loop lives inside the pallas_call whose operands are all VMEM/SMEM
    by BlockSpec; in-kernel gathers are exactly the pinned partner-
    permutation reads; outside the kernel, gathers only run once per
    solve (the entry materialization), never inside a loop."""
    report = jc.backend_report("mega", *bucket)
    assert report.hbm_loop_gathers == 0
    assert report.kernel_gathers == jc.MEGA_KERNEL_PERM_GATHERS
    est = jc.estimate_mega_vmem(jc.traced("mega", *bucket))
    assert est.all_operands_on_chip


def test_csr_backend_shows_the_contrast():
    """The scan-CSR backend pays per-superstep HBM gathers (that is
    the megakernel's whole reason to exist) — if this ever reads 0 the
    gather classifier is broken, not the solver fixed."""
    report = jc.backend_report("jax", 20, 100)
    assert report.hbm_loop_gathers > 0


@pytest.mark.parametrize("backend", sorted(BUCKET_PAIRS))
def test_pow2_bucket_jaxpr_hash_stable(backend):
    for raw_a, raw_b in BUCKET_PAIRS[backend]:
        ha, hb = jc.recompile_hazard(backend, raw_a, raw_b)
        assert ha == hb, (
            f"{backend}: raw sizes {raw_a} and {raw_b} share a pow2 bucket "
            "but trace different jaxprs — a raw size is leaking into the "
            "traced program (recompile hazard)"
        )
    raw_a, raw_b = CROSS_BUCKET_PAIRS[backend]
    ha, hb = jc.recompile_hazard(backend, raw_a, raw_b)
    assert ha != hb, "cross-bucket hashes collide; the stability check is vacuous"


@pytest.mark.parametrize("bucket", SHAPE_BUCKETS, ids=str)
def test_mega_vmem_estimate_consistent_with_gate(bucket):
    from ksched_tpu.ops.mcmf_pallas import (
        _MEGA_VMEM_BUDGET_BYTES,
        MEGA_LANES,
        mega_entry_rows,
        mega_fits_vmem,
    )

    est = jc.estimate_mega_vmem(jc.traced("mega", *bucket))
    assert est.L == MEGA_LANES
    assert est.gate_is_safe, (
        f"kernel live set ({est.est_tiles} tiles) exceeds the "
        f"_MEGA_LIVE_TILES gate ({est.gate_tiles}): mega_fits_vmem would "
        "admit solves that cannot be VMEM-resident — raise the gate"
    )
    assert est.gate_is_tight, (
        f"gate ({est.gate_tiles} tiles) is far above the counted live set "
        f"({est.est_tiles}): it has drifted from the kernel it guards"
    )
    # the gate refuses exactly where the counted estimate exceeds budget
    for entries in (512, 1 << 15, 1 << 18, 1 << 20, 1 << 22):
        padded = mega_entry_rows(entries) * MEGA_LANES
        counted_fits = est.gate_tiles * padded * 4 <= _MEGA_VMEM_BUDGET_BYTES
        assert mega_fits_vmem(entries) == counted_fits


# ---------------------------------------------------------------------------
# Level 2: solver-telemetry contracts (obs/soltel.py, ISSUE 7)
# ---------------------------------------------------------------------------

#: normalized jaxpr hashes of every backend's TELEMETRY-OFF trace at
#: bucket (20, 100), captured on the pre-telemetry tree (PR 7 base,
#: jax 0.4.37) — the "no cost when off" contract: telemetry_cap=0 must
#: trace the EXACT pre-soltel program, op for op. The hash normalizes
#: source-location metadata (jaxpr_contracts._normalize_jaxpr_str), so
#: a comment edit can't split it — but a jax upgrade that changes
#: jaxpr printing will, and these pins must then be re-captured in the
#: same commit as the upgrade (verify the off-trace is otherwise
#: unchanged first).
SOLTEL_OFF_BASELINE_HASHES = {
    "jax": "92aa144400bd8869",
    "ell": "9e101ad7b1bac615",
    "mega": "2713247f0ce0fa0b",
    # sharded traces over the conftest 8-virtual-device mesh; its hash
    # is mesh-size-dependent (the other backends' are not)
    "sharded": "b2c5ad0884934f47",
    "layered": "efaf297e81829bd2",
}


@pytest.mark.parametrize("backend", sorted(SOLTEL_OFF_BASELINE_HASHES))
def test_soltel_off_trace_is_the_pretelemetry_baseline(backend):
    got = jc.jaxpr_hash(jc.traced(backend, 20, 100))
    assert got == SOLTEL_OFF_BASELINE_HASHES[backend], (
        f"{backend}: the telemetry-OFF trace drifted from the "
        "pre-telemetry baseline — disabled solver telemetry must cost "
        "zero traced ops (see SOLTEL_OFF_BASELINE_HASHES)"
    )


@pytest.mark.parametrize("backend", sorted(SOLTEL_OFF_BASELINE_HASHES))
def test_soltel_on_changes_and_off_matches_default(backend):
    """Sanity for the pin above: telemetry-on traces a DIFFERENT
    program (the contract isn't vacuous), and cap=0 is the default.
    Every soltel contract test traces cap=512 so the lru cache shares
    the (expensive) abstract traces across the suite."""
    off = jc.jaxpr_hash(jc.traced(backend, 20, 100, telemetry_cap=0))
    on = jc.jaxpr_hash(jc.traced(backend, 20, 100, telemetry_cap=512))
    assert off == jc.jaxpr_hash(jc.traced(backend, 20, 100))
    assert on != off


@pytest.mark.parametrize("bucket", SHAPE_BUCKETS, ids=str)
def test_soltel_mega_gather_budget_unchanged(bucket):
    """Telemetry must add ZERO gathers to the megakernel: the counters
    are reductions over VMEM state the superstep already holds, and
    the ring write is a masked elementwise select."""
    report = jc.check_jaxpr(
        "mega", jc.traced("mega", *bucket, telemetry_cap=512)
    )
    assert report.hbm_loop_gathers == 0
    assert report.kernel_gathers == jc.MEGA_KERNEL_PERM_GATHERS
    assert report.ok_64bit and report.ok_scatter


@pytest.mark.parametrize("bucket", SHAPE_BUCKETS, ids=str)
def test_soltel_mega_vmem_estimate_within_one_tile(bucket):
    """The telemetry ring is clamped to one [R, L] entry tile
    (mega_telemetry_cap), so the counted VMEM estimate grows by
    exactly 1 tile over _MEGA_LIVE_TILES — matching what
    mega_fits_vmem(telemetry=True) budgets."""
    from ksched_tpu.ops.mcmf_pallas import _MEGA_LIVE_TILES

    est = jc.estimate_mega_vmem(
        jc.traced("mega", *bucket, telemetry_cap=512)
    )
    assert est.extra_tiles == 1
    assert est.est_tiles <= _MEGA_LIVE_TILES + 1
    assert est.all_operands_on_chip
    assert est.gate_is_safe


@pytest.mark.parametrize("backend", ("jax", "mega", "layered"))
def test_soltel_on_pow2_bucket_hash_stable(backend):
    """The recompile detector holds WITH telemetry on: the ring shape
    is a function of the pow2 bucket alone, never the raw size. One
    pair per backend — the off-trace pairs already sweep all three;
    this guards the telemetry shapes specifically."""
    raw_a, raw_b = BUCKET_PAIRS[backend][0]
    ha = jc.jaxpr_hash(jc.traced(backend, *raw_a, telemetry_cap=512))
    hb = jc.jaxpr_hash(jc.traced(backend, *raw_b, telemetry_cap=512))
    assert ha == hb, f"{backend}: telemetry-on recompile hazard {raw_a} vs {raw_b}"


@pytest.mark.parametrize("backend", ("jax", "ell", "layered", "sharded"))
def test_soltel_on_no_64bit_no_scatter(backend):
    report = jc.check_jaxpr(
        backend, jc.traced(backend, 20, 100, telemetry_cap=512)
    )
    assert report.ok_64bit, report.violations_64bit
    assert report.ok_scatter, report.scatter_eqns


# ---------------------------------------------------------------------------
# Device-resident delta program: the SCOPED scatter exemption
# ---------------------------------------------------------------------------


def test_delta_apply_scatters_and_is_32bit():
    """The delta-apply program IS allowed scatters — it applies
    O(churn)-sized packed records once per round, where a serialized
    scatter is the right tool — and the exemption must not be vacuous:
    the traced program really contains scatter ops. Everything stays
    32-bit (the device mirror never carries int64)."""
    report = jc.check_jaxpr("delta_apply", jc.trace_delta_apply(5, 3))
    assert report.scatter_eqns, (
        "the delta-apply trace contains no scatters — the scoped "
        "exemption is vacuous (did the program change shape?)"
    )
    assert report.ok_64bit, report.violations_64bit


def test_delta_apply_exemption_is_scoped():
    """The exemptions cover EXACTLY THREE programs (the problem-delta
    apply, the slot-stable plan apply, and the per-shard routed
    sharded plan apply — all once-per-round maintenance outside any
    solve): every registered solver backend still traces zero scatters
    (the existing per-backend sweep re-asserted here so the exemption
    tests and the zero-scatter rule can never pass for contradictory
    reasons)."""
    for backend in jc.REGISTERED_BACKENDS:
        report = jc.backend_report(backend, 20, 100)
        assert report.ok_scatter, (backend, report.scatter_eqns)


def test_delta_apply_pow2_record_bucket_hash_stable():
    """Two record counts sharing a pow2 bucket trace byte-identical
    delta programs (one compiled scatter per bucket, no per-delta
    recompiles); cross-bucket hashes differ (the check isn't vacuous).
    The graph bucket behaves the same way."""
    assert jc.jaxpr_hash(jc.trace_delta_apply(3, 2)) == jc.jaxpr_hash(
        jc.trace_delta_apply(7, 5)
    )
    assert jc.jaxpr_hash(jc.trace_delta_apply(3, 2)) != jc.jaxpr_hash(
        jc.trace_delta_apply(100, 2)
    )
    assert jc.jaxpr_hash(jc.trace_delta_apply(3, 2, n_raw=20, m_raw=100)) == jc.jaxpr_hash(
        jc.trace_delta_apply(3, 2, n_raw=24, m_raw=110)
    )
    assert jc.jaxpr_hash(jc.trace_delta_apply(3, 2, n_raw=20, m_raw=100)) != jc.jaxpr_hash(
        jc.trace_delta_apply(3, 2, n_raw=20, m_raw=300)
    )


def test_warm_flow_program_is_elementwise():
    """The device warm-flow carry must stay scatter- AND gather-free
    (pure elementwise masking against the pre-delta endpoints)."""
    report = jc.check_jaxpr("warm_flow", jc.trace_warm_flow())
    assert report.ok_scatter, report.scatter_eqns
    assert report.ok_64bit, report.violations_64bit
    assert (
        report.hbm_loop_gathers == report.kernel_gathers
        == report.oneshot_gathers == 0
    )


def test_warmp_trace_is_distinct_and_scatter_free():
    """use_warm_p=True is a DIFFERENT traced program — since the
    dirty-frontier refit it consumes the carried potentials as the
    Bellman seed — still zero scatters, no 64-bit, pow2-bucket stable.
    The DEFAULT trace staying on the pinned pre-warm_p baseline is
    asserted by test_soltel_off_trace_is_the_pretelemetry_baseline."""
    closed = jc.trace_jax_warmp(20, 100)
    report = jc.check_jaxpr("jax+warmp", closed)
    assert report.ok_scatter and report.ok_64bit
    assert jc.jaxpr_hash(closed) != jc.jaxpr_hash(jc.traced("jax", 20, 100))
    assert jc.jaxpr_hash(jc.trace_jax_warmp(20, 100)) == jc.jaxpr_hash(
        jc.trace_jax_warmp(24, 110)
    )


# ---------------------------------------------------------------------------
# Slot-stable plan maintenance: the SECOND scoped scatter exemption
# ---------------------------------------------------------------------------


def test_plan_apply_scatters_and_is_32bit():
    """The plan-row apply program IS allowed scatters — it applies the
    round's O(churn)-sized dirty plan rows + inv-order records once per
    round — and the exemption must not be vacuous: the traced program
    really contains scatter ops. Everything stays 32-bit."""
    report = jc.check_jaxpr("plan_apply", jc.trace_plan_apply(5, 3))
    assert report.scatter_eqns, (
        "the plan-apply trace contains no scatters — the scoped "
        "exemption is vacuous (did the program change shape?)"
    )
    assert report.ok_64bit, report.violations_64bit


def test_plan_apply_pow2_record_bucket_hash_stable():
    """Two record counts sharing a pow2 bucket trace byte-identical
    plan-apply programs (one compiled scatter per bucket); cross-bucket
    hashes differ (the check isn't vacuous). The graph bucket behaves
    the same way."""
    assert jc.jaxpr_hash(jc.trace_plan_apply(3, 2)) == jc.jaxpr_hash(
        jc.trace_plan_apply(7, 5)
    )
    assert jc.jaxpr_hash(jc.trace_plan_apply(3, 2)) != jc.jaxpr_hash(
        jc.trace_plan_apply(100, 2)
    )
    assert jc.jaxpr_hash(jc.trace_plan_apply(3, 2, n_raw=20, m_raw=100)) == jc.jaxpr_hash(
        jc.trace_plan_apply(3, 2, n_raw=24, m_raw=110)
    )
    assert jc.jaxpr_hash(jc.trace_plan_apply(3, 2, n_raw=20, m_raw=100)) != jc.jaxpr_hash(
        jc.trace_plan_apply(3, 2, n_raw=20, m_raw=300)
    )


def test_slot_stable_trace_is_distinct_scatter_free_and_bucket_stable():
    """slot_stable=True is a DIFFERENT traced program (dead rows are
    masked through the sign column) but still a SOLVE program: zero
    scatters, no 64-bit, and hash-stable within a pow2 bucket (the
    entry extent is a function of the m-bucket, never the raw size —
    a raw-size leak here would mean a recompile per region rebuild)."""
    closed = jc.trace_jax_slot_stable(20, 100)
    report = jc.check_jaxpr("jax+slot_stable", closed)
    assert report.ok_scatter, report.scatter_eqns
    assert report.ok_64bit, report.violations_64bit
    assert jc.jaxpr_hash(closed) != jc.jaxpr_hash(jc.traced("jax", 20, 100))
    assert jc.jaxpr_hash(jc.trace_jax_slot_stable(20, 100)) == jc.jaxpr_hash(
        jc.trace_jax_slot_stable(24, 110)
    )
    assert jc.jaxpr_hash(jc.trace_jax_slot_stable(20, 100)) != jc.jaxpr_hash(
        jc.trace_jax_slot_stable(20, 300)
    )


def test_refit_slot_stable_combo_is_scatter_free():
    """The production event-path program — dirty-frontier refit ON TOP
    of the slot-stable plan (use_warm_p=True, slot_stable=True) — must
    also stay scatter-free and 32-bit: the refit is plain data-parallel
    Bellman relaxation over the maintained layout."""
    closed = jc.trace_jax_warmp(20, 100, slot_stable=True)
    report = jc.check_jaxpr("jax+refit+slot_stable", closed)
    assert report.ok_scatter, report.scatter_eqns
    assert report.ok_64bit, report.violations_64bit
    assert jc.jaxpr_hash(closed) != jc.jaxpr_hash(jc.trace_jax_warmp(20, 100))


# ---------------------------------------------------------------------------
# Slot-stable SHARDED solve + per-shard plan apply (parallel/, ISSUE 15)
# ---------------------------------------------------------------------------


def test_sharded_slot_trace_no_64bit_no_scatter():
    """The slot-stable sharded solve stays a SOLVE program: zero
    scatters (cross-shard combines are psum/pmin/pmax of owner-masked
    vectors), everything int32."""
    for warm in (False, True):
        closed = jc.trace_sharded_slot(20, 100, num_devices=2, use_warm_p=warm)
        report = jc.check_jaxpr("sharded_slot", closed)
        assert report.ok_scatter, (warm, report.scatter_eqns)
        assert report.ok_64bit, (warm, report.violations_64bit)
        assert report.num_eqns > 0


def test_sharded_slot_shard_count_bucket_stable():
    """One executable per (pow2 shape bucket, shard count): raw sizes
    within a bucket trace byte-identical programs at 2, 4, AND 8
    devices, and different shard counts trace DIFFERENT programs (each
    mesh size is its own bucket — the bench_compare series key mirrors
    this with mesh_devices)."""
    per_d = {}
    for d in (2, 4, 8):
        ha = jc.jaxpr_hash(jc.trace_sharded_slot(20, 100, num_devices=d))
        hb = jc.jaxpr_hash(jc.trace_sharded_slot(24, 110, num_devices=d))
        assert ha == hb, f"{d}-dev sharded solve leaks a raw size (recompile hazard)"
        per_d[d] = ha
    assert len(set(per_d.values())) == 3, (
        "different shard counts must trace different programs "
        f"(collision: {per_d})"
    )


def test_sharded_slot_warm_variant_is_distinct():
    assert jc.jaxpr_hash(jc.trace_sharded_slot(20, 100)) != jc.jaxpr_hash(
        jc.trace_sharded_slot(20, 100, use_warm_p=True)
    )


def test_sharded_slot_telemetry_off_is_default_and_on_differs():
    off = jc.jaxpr_hash(jc.trace_sharded_slot(20, 100, telemetry_cap=0))
    on = jc.jaxpr_hash(jc.trace_sharded_slot(20, 100, telemetry_cap=512))
    assert off == jc.jaxpr_hash(jc.trace_sharded_slot(20, 100))
    assert on != off
    report = jc.check_jaxpr(
        "sharded_slot+tel", jc.trace_sharded_slot(20, 100, telemetry_cap=512)
    )
    assert report.ok_scatter and report.ok_64bit


def test_sharded_superstep_ici_budget():
    """The documented ICI shape of a sharded superstep: exactly three
    psum families ride the solve loop (the [N] excess combine, the [M]
    arc-delta combine, the [N] potential combine), plus the segment
    pmin (tighten sweeps) and the phase-boundary saturate pmax — and
    nothing else (no all_gather / all_to_all / ppermute anywhere).
    Telemetry adds its scalar counter psums only when ON."""
    counts = jc.count_superstep_collectives(jc.trace_sharded_slot(20, 100))
    assert counts.get("psum", 0) == 3, counts
    assert counts.get("pmin", 0) == 1, counts  # tighten sweep (prologue loop)
    assert counts.get("pmax", 0) == 2, counts  # sat_full's fwd/bwd combines
    assert not counts.get("all_gather") and not counts.get("all_to_all")
    assert not counts.get("ppermute")
    on = jc.count_superstep_collectives(
        jc.trace_sharded_slot(20, 100, telemetry_cap=512)
    )
    assert on.get("psum", 0) > counts["psum"]  # the 4 counter psums


def test_sharded_plan_apply_scatters_and_is_32bit():
    """The per-shard routed plan apply is the THIRD (and last) scoped
    scatter exemption: really scatters, all 32-bit, and contains NO
    collectives — the owner routing happened on host, so the program
    is embarrassingly parallel across shards."""
    closed = jc.trace_sharded_plan_apply(5, 3)
    report = jc.check_jaxpr("sharded_plan_apply", closed)
    assert report.scatter_eqns, (
        "the sharded plan-apply trace contains no scatters — the "
        "scoped exemption is vacuous"
    )
    assert report.ok_64bit, report.violations_64bit
    assert jc.count_collectives(closed) == {}


def test_sharded_plan_apply_pow2_record_bucket_hash_stable():
    assert jc.jaxpr_hash(jc.trace_sharded_plan_apply(3, 2)) == jc.jaxpr_hash(
        jc.trace_sharded_plan_apply(7, 5)
    )
    assert jc.jaxpr_hash(jc.trace_sharded_plan_apply(3, 2)) != jc.jaxpr_hash(
        jc.trace_sharded_plan_apply(100, 2)
    )


def test_sharded_plan_fingerprint_scatter_free_psummed():
    """The sharded audit program: scatter-free, 32-bit, and its ONLY
    collectives are the per-tensor psums that fold per-shard partials
    into the one comparable checksum (6 entry-shaped tensors)."""
    closed = jc.trace_sharded_plan_fingerprint()
    report = jc.check_jaxpr("sharded_plan_fp", closed)
    assert report.ok_scatter, report.scatter_eqns
    assert report.ok_64bit, report.violations_64bit
    assert jc.count_collectives(closed).get("psum", 0) == 6


# ---------------------------------------------------------------------------
# Multi-tenant stacked-CSR batched solve (tenancy/batch.py, ISSUE 12)
# ---------------------------------------------------------------------------


def test_stacked_no_64bit_no_scatter():
    """The batched lane program stays a SOLVE program: vmap's while-
    loop batching freezes converged lanes with selects, never
    scatters, and everything is int32 — per-lane convergence masks
    cost zero scatter traffic."""
    for warm in (False, True):
        closed = jc.trace_stacked(4, 20, 100, use_warm_p=warm)
        report = jc.check_jaxpr("stacked", closed)
        assert report.ok_scatter, (warm, report.scatter_eqns)
        assert report.ok_64bit, (warm, report.violations_64bit)
        assert report.num_eqns > 0


def test_stacked_telemetry_variant_no_scatter():
    report = jc.check_jaxpr(
        "stacked", jc.trace_stacked(4, 20, 100, telemetry_cap=512)
    )
    assert report.ok_scatter and report.ok_64bit


def test_stacked_lane_count_and_bucket_hash_stable():
    """The executable-reuse contract behind the warm multi-tenant
    process: raw sizes within a pow2 shape bucket AND raw lane counts
    within a pow2 lane bucket trace byte-identical programs (tenant
    churn must not recompile); cross-bucket/cross-lane-count hashes
    differ (the check isn't vacuous)."""
    base = jc.jaxpr_hash(jc.trace_stacked(3, 20, 100))
    assert base == jc.jaxpr_hash(jc.trace_stacked(4, 24, 110))  # same buckets
    assert base != jc.jaxpr_hash(jc.trace_stacked(8, 20, 100))  # lane bucket
    assert base != jc.jaxpr_hash(jc.trace_stacked(4, 20, 300))  # shape bucket
    from ksched_tpu.solver.jax_solver import pad_lane_count

    assert pad_lane_count(3) == pad_lane_count(4) == 4


def test_stacked_warm_variant_is_distinct():
    """use_warm_p batches the dirty-frontier refit across lanes — a
    DIFFERENT traced program (the warm seed is a real invar), so the
    fresh pin above isn't accidentally covering it."""
    assert jc.jaxpr_hash(jc.trace_stacked(4, 20, 100)) != jc.jaxpr_hash(
        jc.trace_stacked(4, 20, 100, use_warm_p=True)
    )


# ---------------------------------------------------------------------------
# Level 2: negative tests — each contract detects a seeded violation
# ---------------------------------------------------------------------------


def _make_jaxpr(fn, *shapes):
    import jax
    import jax.numpy as jnp

    return jax.make_jaxpr(fn)(
        *(jax.ShapeDtypeStruct(s, jnp.int32) for s in shapes)
    )


def test_contract_catches_64bit_convert():
    import jax
    import jax.numpy as jnp

    def bad(x):
        return x.astype(jnp.float64).sum()

    # without x64, jax downcasts the seeded violation to f32 before the
    # checker could see it — exactly why the contract exists: if anyone
    # flips x64 on, 64-bit types flow silently
    with jax.experimental.enable_x64():
        closed = _make_jaxpr(bad, (8,))
    report = jc.check_jaxpr("bad", closed)
    assert not report.ok_64bit


def test_contract_catches_scatter():
    def bad(x, idx):
        return x.at[idx].add(1)

    report = jc.check_jaxpr("bad", _make_jaxpr(bad, (8,), (3,)))
    assert not report.ok_scatter


def test_contract_catches_loop_gather():
    import jax
    import jax.numpy as jnp
    from jax import lax

    def bad(x, idx):
        def body(_, carry):
            return carry + x[idx].sum()

        return lax.fori_loop(0, 4, body, jnp.int32(0))

    report = jc.check_jaxpr("bad", _make_jaxpr(bad, (8,), (3,)))
    assert report.hbm_loop_gathers > 0


def test_contract_catches_bucket_leak():
    """A raw size leaking into a static arg splits the jaxpr hash —
    the exact failure mode of a forgotten pow2 pad."""
    import functools
    import jax

    def leaky(x, scale: int = 1):
        return x * scale

    def trace(m_raw):
        fn = functools.partial(leaky, scale=m_raw)  # raw size as static
        return _make_jaxpr(fn, (64,))

    assert jc.jaxpr_hash(trace(40)) != jc.jaxpr_hash(trace(60))


# ---------------------------------------------------------------------------
# State-integrity fingerprint programs (runtime/integrity.py, r14)
# ---------------------------------------------------------------------------


def test_fingerprint_programs_scatter_free_and_32bit():
    """The integrity audit rides the normal round cadence, so its
    checksum programs get NO scatter exemption: pure elementwise
    multiply + reduction, all 32-bit. (The delta/plan scatter programs
    themselves are untouched by fingerprinting — their off-hash pins
    above hold byte-identically, which is the 'fingerprint-off traces
    byte-identical to the r12 pins' contract.)"""
    for name, trace in (
        ("state_fingerprint", jc.trace_state_fingerprint()),
        ("plan_fingerprint", jc.trace_plan_fingerprint()),
    ):
        report = jc.check_jaxpr(name, trace)
        assert report.ok_scatter, (name, report.scatter_eqns)
        assert report.ok_64bit, (name, report.violations_64bit)


def test_fingerprint_programs_pow2_bucket_hash_stable():
    """One compiled fingerprint program per pow2 shape bucket — the
    audit must never force per-round recompiles."""
    assert jc.jaxpr_hash(jc.trace_state_fingerprint(20, 100)) == jc.jaxpr_hash(
        jc.trace_state_fingerprint(24, 110)
    )
    assert jc.jaxpr_hash(jc.trace_state_fingerprint(20, 100)) != jc.jaxpr_hash(
        jc.trace_state_fingerprint(20, 300)
    )
    assert jc.jaxpr_hash(jc.trace_plan_fingerprint(20, 100)) == jc.jaxpr_hash(
        jc.trace_plan_fingerprint(24, 110)
    )
