"""Batched what-if solves (parallel/whatif.py): a K-scenario batch must
agree with K sequential solves, and the drain/surge builders must model
their scenarios faithfully against a live cluster."""

import numpy as np
import pytest

from ksched_tpu.parallel.whatif import WhatIfSolver, drain_scenarios, surge_scenarios
from ksched_tpu.scheduler.bulk import BulkCluster
from ksched_tpu.solver.layered import LayeredProblem, LayeredTransportSolver


@pytest.mark.parametrize("C", [1, 3])
def test_batch_matches_sequential(C):
    rng = np.random.default_rng(0)
    M, K = 20, 6
    solver = WhatIfSolver(M, C, unsched_cost=25, ec_cost=2)
    seq = LayeredTransportSolver()
    cost_cm = rng.integers(0, 15, (K, C, M)).astype(np.int64)
    supply = rng.integers(0, 50, (K, C)).astype(np.int64)
    col_cap = rng.integers(0, 8, (K, M)).astype(np.int64)

    batch = solver.solve_batch(cost_cm, supply, col_cap)
    assert batch.converged.all()
    for k in range(K):
        res = seq.solve_layered(
            LayeredProblem(
                supply=supply[k].astype(np.int32),
                col_cap=col_cap[k].astype(np.int32),
                cost_cm=cost_cm[k].astype(np.int32),
                unsched_cost=25,
                ec_cost=2,
            )
        )
        assert batch.objective[k] == res.objective, f"scenario {k}"
        assert batch.num_unsched[k] == res.num_unsched


def _cluster(C=2, M=6, seed=3):
    rng = np.random.default_rng(seed)
    cost = rng.integers(0, 10, (C, M)).astype(np.int32)
    cluster = BulkCluster(
        num_machines=M,
        pus_per_machine=2,
        slots_per_pu=2,
        num_jobs=3,
        backend=LayeredTransportSolver(),
        task_capacity=256,
        num_task_classes=C,
        class_cost_fn=lambda cl: cost,
        unsched_cost=25,
    )
    n = 20
    cluster.add_tasks(
        n, rng.integers(0, 3, n).astype(np.int32), rng.integers(0, C, n).astype(np.int32)
    )
    cluster.round()
    return cluster


def test_drain_scenarios_cover_displaced_tasks():
    cluster = _cluster()
    res = drain_scenarios(cluster, np.arange(cluster.M))
    assert res.converged.all()
    # each scenario k: capacity of machine k gone, so nothing lands there
    for k in range(cluster.M):
        assert res.y[k, :, k].sum() == 0
    # scenario supply included the displaced tasks: placements+unsched
    # must account for backlog + displaced of that machine
    placed_machine = np.where(
        cluster.task_live & (cluster.task_pu >= 0), cluster.task_pu // cluster.P, -1
    )
    backlog = int((cluster.task_live & (cluster.task_pu < 0)).sum())
    for k in range(cluster.M):
        displaced = int((placed_machine == k).sum())
        assert res.y[k].sum() + res.num_unsched[k] == backlog + displaced


def test_degenerate_batch_and_index_guard():
    """Uniform cost rows take the closed-form collapse (stock
    no-cost-model config), and negative drain indices raise instead of
    aliasing the unplaced sentinel."""
    s = WhatIfSolver(8, 3, unsched_cost=25, ec_cost=2)
    cost = np.zeros((3, 8), np.int64)
    res = s.solve_batch(
        cost, np.full((4, 3), 7, np.int64), np.full((4, 8), 2, np.int64)
    )
    assert res.converged.all()
    assert (res.num_unsched == 5).all()  # 21 supply into 16 slots

    cluster = _cluster()
    with pytest.raises(IndexError):
        drain_scenarios(cluster, [-1])
    with pytest.raises(IndexError):
        drain_scenarios(cluster, [cluster.M])


def test_surge_scenarios_monotone_unsched():
    """More surge can never mean fewer unscheduled tasks."""
    cluster = _cluster()
    C = cluster.C
    surges = np.stack([np.full(C, s) for s in (0, 5, 50, 500)])
    res = surge_scenarios(cluster, surges)
    assert res.converged.all()
    assert (np.diff(res.num_unsched) >= 0).all()
