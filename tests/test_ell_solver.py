"""Bucketed-ELL push-relabel (solver/ell_solver.py): parity vs the
exact CPU oracle and the CSR JaxSolver.

Same invariant as test_jax_solver.py: MCMF optima are non-unique, so
parity = identical objective; flow validity checked directly. The ELL
layout additionally gets structural tests (every doubled entry lands in
exactly one block cell; hub row-splitting covers hub degrees).
"""

import numpy as np
import pytest

from ksched_tpu.solver import ReferenceSolver
from ksched_tpu.solver.ell_solver import EllSolver, build_ell_plan
from ksched_tpu.solver.jax_solver import JaxSolver
from ksched_tpu.solver.mega_solver import MegaSolver

from test_jax_solver import (
    assert_valid_flow,
    random_scheduling_problem,
)
from test_solver_oracle import make_problem


def _general_backend(name, **ell_kw):
    """The general-graph backends that must pass the same oracle-parity
    suite: the bucketed-ELL layout and the Pallas megakernel (run under
    the interpreter in this CPU env). ell_kw reaches EllSolver only, so
    the small cases keep exercising the DEFAULT hub width while the
    random suite pins w_hub=16 as it always has."""
    if name == "ell":
        return EllSolver(**ell_kw)
    return MegaSolver(interpret=True)


def test_plan_structure():
    rng = np.random.default_rng(3)
    p = random_scheduling_problem(
        rng, num_tasks=40, num_machines=4, slots_per_machine=3
    )
    src = p.src.astype(np.int32)
    dst = p.dst.astype(np.int32)
    plan = build_ell_plan(src, dst, p.num_nodes, w_small=8, w_hub=16)
    m = len(src)
    deg = np.bincount(np.concatenate([src, dst]), minlength=p.num_nodes)
    # hub split must exist at this scale (unsched/EC/sink are hubs)
    assert (deg > 8).any()
    # every doubled entry occupies exactly one cell: total non-pad cells
    assert int((plan.s_sign != 0).sum() + (plan.h_sign != 0).sum()) == 2 * m
    # fwd/bwd flat positions address distinct cells
    assert len(np.unique(np.concatenate([plan.fwd_flat, plan.bwd_flat]))) == 2 * m
    # per-node bookkeeping: each small node's row carries exactly deg entries
    for row in range(min(10, len(plan.s_node))):
        node = plan.s_node[row]
        if plan.node_kind[node] == 1 and plan.node_slot[node] == row:
            assert int((plan.s_sign[row] != 0).sum()) == int(deg[node])
    # hub rows, concatenated in k order, carry exactly the hub's degree
    for h in range(len(plan.hub_node)):
        rows = plan.hub_rows[h][plan.hub_rows_valid[h]]
        if len(rows) == 0:
            continue
        node = plan.hub_node[h]
        assert int((plan.h_sign[rows] != 0).sum()) == int(deg[node])
        assert (plan.h_node[rows] == node).all()


@pytest.mark.parametrize("backend", ["ell", "mega"])
@pytest.mark.parametrize("case", ["single", "cheap", "split", "assign", "escape"])
def test_small_parity(case, backend):
    problems = {
        "single": make_problem(4, {1: 1, 3: -1}, [(1, 2, 0, 1, 2), (2, 3, 0, 1, 3)]),
        "cheap": make_problem(
            4, {1: 1, 3: -1}, [(1, 3, 0, 1, 10), (1, 2, 0, 1, 2), (2, 3, 0, 1, 3)]
        ),
        "split": make_problem(
            4, {1: 2, 3: -2}, [(1, 3, 0, 9, 10), (1, 2, 0, 1, 2), (2, 3, 0, 9, 3)]
        ),
        "assign": make_problem(
            8,
            {1: 1, 2: 1, 6: -2},
            [
                (1, 3, 0, 1, 2),
                (2, 3, 0, 1, 2),
                (3, 4, 0, 1, 0),
                (3, 5, 0, 1, 4),
                (4, 6, 0, 1, 0),
                (5, 6, 0, 1, 0),
                (1, 7, 0, 1, 50),
                (2, 7, 0, 1, 50),
                (7, 6, 0, 2, 0),
            ],
        ),
        "escape": make_problem(
            8,
            {1: 1, 2: 1, 6: -2},
            [
                (1, 3, 0, 1, 2),
                (2, 3, 0, 1, 2),
                (3, 4, 0, 1, 0),
                (4, 6, 0, 1, 0),
                (1, 7, 0, 1, 5),
                (2, 7, 0, 1, 5),
                (7, 6, 0, 2, 0),
            ],
        ),
    }
    p = problems[case]
    ref = ReferenceSolver().solve(p)
    el = _general_backend(backend).solve(p)
    assert_valid_flow(p, el.flow)
    assert el.objective == ref.objective


@pytest.mark.parametrize("backend", ["ell", "mega"])
def test_random_parity_vs_oracle_and_csr(backend):
    rng = np.random.default_rng(11)
    for trial in range(8):
        p = random_scheduling_problem(
            rng,
            num_tasks=int(rng.integers(3, 40)),
            num_machines=int(rng.integers(1, 6)),
            slots_per_machine=int(rng.integers(1, 4)),
        )
        ref = ReferenceSolver().solve(p)
        el = _general_backend(backend, w_hub=16).solve(p)
        jx = JaxSolver().solve(p)
        assert el.objective == ref.objective, f"trial {trial}"
        assert jx.objective == el.objective, f"trial {trial}"
        assert_valid_flow(p, el.flow)


def test_warm_start_incremental():
    rng = np.random.default_rng(5)
    p = random_scheduling_problem(
        rng, num_tasks=12, num_machines=3, slots_per_machine=2
    )
    solver = EllSolver(w_hub=16)
    r1 = solver.solve(p)
    ref1 = ReferenceSolver().solve(p)
    assert r1.objective == ref1.objective
    cold_steps = solver.last_supersteps

    from ksched_tpu.graph.device_export import FlowProblem

    p2 = FlowProblem(
        num_nodes=p.num_nodes,
        excess=p.excess.copy(),
        node_type=p.node_type,
        src=p.src,
        dst=p.dst,
        cap=p.cap.copy(),
        cost=p.cost.copy(),
        flow_offset=p.flow_offset,
        num_arcs=p.num_arcs,
    )
    p2.cost[0] += 2
    r2 = solver.solve(p2)
    ref2 = ReferenceSolver().solve(p2)
    assert r2.objective == ref2.objective
    assert solver.last_supersteps <= max(cold_steps * 2, 50)
