"""Slot-stable CSR plan maintenance: scatter-vs-rebuild parity.

The tentpole claim (graph/slot_plan.py): a churn trace driven through
the scatter-maintained device plan mirror produces BIT-IDENTICAL plan
tensors, flows, superstep counts, and telemetry rows as the same trace
consumed through the full-rebuild materialization path (the maintained
host arrays re-shipped wholesale). Asserted at 3 shape buckets over a
script that hits every churn kind: cost/capacity-only rounds (clean
plan), endpoint rewires, slot recycling through the free list, supply
movement, and a forced layout rebuild.

MCMF optima are non-unique under cost ties, so the LEGACY plan
(slot_stable=False, host argsort per endpoint change) is held to
objective parity per round, plus bit-identical flows on the first
layout (where the slot-stable entry order is constructed to match the
stable argsort exactly).
"""

import numpy as np
import pytest

from ksched_tpu.graph.changes import (
    ArcType,
    ChangeArcChange,
    NewArcChange,
    NodeType,
)
from ksched_tpu.graph.device_export import (
    DeviceGraphState,
    DeviceResidentState,
)
from ksched_tpu.graph.flowgraph import FlowGraph
from ksched_tpu.obs import soltel
from ksched_tpu.solver.jax_solver import JaxSolver


# ---------------------------------------------------------------------------
# churn trace driver
# ---------------------------------------------------------------------------


def _build_graph(num_tasks, num_machines, machine_cap=(2, 6)):
    """tasks -> machines -> sink, plus a high-cost escape machine so
    every churn step stays feasible.

    The default machine capacities STARVE the cluster (most tasks
    overflow to the cost-40 escape), which drives every arm through the
    cost-scaling fallback — good stress for plan parity, but NOT the
    regime where the ~10-superstep fresh-restart band claim holds
    (discharging starved excess relabels down the full cost range one
    eps at a time regardless of plan or policy). Superstep-band tests
    pass an ample ``machine_cap`` instead."""
    g = FlowGraph()
    sink = g.add_node()
    sink.type = NodeType.SINK
    machines = [g.add_node() for _ in range(num_machines)]
    escape = g.add_node()
    tasks = [g.add_node() for _ in range(num_tasks)]
    rng = np.random.default_rng(num_tasks * 1000 + num_machines)
    for m in machines:
        a = g.add_arc(m, sink)
        g.change_arc(a, 0, int(rng.integers(*machine_cap)), int(rng.integers(0, 4)))
    a = g.add_arc(escape, sink)
    g.change_arc(a, 0, num_tasks, 50)
    for t in tasks:
        t.excess = 1
        for m in rng.choice(num_machines, size=min(3, num_machines), replace=False):
            a = g.add_arc(t, machines[int(m)])
            g.change_arc(a, 0, 1, int(rng.integers(0, 10)))
        a = g.add_arc(t, escape)
        g.change_arc(a, 0, 1, 40)
    sink.excess = -num_tasks
    return g, sink.id, [m.id for m in machines], [t.id for t in tasks]


def _churn_round(st, kind, task_ids, machine_ids, rng):
    """One round of mutations against the DeviceGraphState journal."""
    arc = lambda s, d, cap, cost: st.apply_changes(  # noqa: E731
        [NewArcChange(s, d, 0, cap, cost, ArcType.OTHER)]
    )
    kill = lambda s, d: st.apply_changes(  # noqa: E731
        [ChangeArcChange(s, d, 0, 0, 0, ArcType.OTHER, 0)]
    )
    live = lambda: sorted(st._arc_slot.keys())  # noqa: E731
    if kind == "cost":
        # cap/cost-only: endpoint_gen stays put, the plan round is clean
        for s, d in [live()[i % len(live())] for i in range(4)]:
            arc(s, d, int(rng.integers(1, 4)), int(rng.integers(0, 10)))
    elif kind == "rewire":
        # endpoint change within existing slots: kill (t, m1), add
        # (t, m2) — the freed slot rides the free list into the new arc
        for t in rng.choice(task_ids, size=3, replace=False):
            t = int(t)
            outs = [(s, d) for (s, d) in live() if s == t and d in machine_ids]
            if not outs:
                continue
            s, d = outs[int(rng.integers(len(outs)))]
            kill(s, d)
            choices = [m for m in machine_ids if (t, m) not in st._arc_slot]
            if choices:
                arc(t, choices[int(rng.integers(len(choices)))], 1,
                    int(rng.integers(0, 10)))
    elif kind == "recycle":
        # pure deletions one round; the NEXT round's additions recycle
        for t in rng.choice(task_ids, size=2, replace=False):
            t = int(t)
            outs = [(s, d) for (s, d) in live() if s == t and d in machine_ids]
            if len(outs) > 1:
                kill(*outs[0])
    elif kind == "supply":
        # move supply between tasks (sink balances): node-only deltas
        a, b = (int(x) for x in rng.choice(task_ids, size=2, replace=False))
        ea, eb = int(st.excess[a]), int(st.excess[b])
        if ea > 0:
            st.set_excess(a, ea - 1)
            st.set_excess(b, eb + 1)
    else:  # pragma: no cover - script typo guard
        raise AssertionError(kind)


SCRIPT = ("cost", "rewire", "recycle", "rewire", "supply", "cost",
          "rewire", "recycle", "rewire")


def _drive(num_tasks, num_machines, *, resident, slot_stable=True,
           force_layout_rebuild=False, telemetry=64, rounds=len(SCRIPT)):
    """Run the churn script through one solver arm; returns per-round
    (flow, supersteps, telemetry rows, objective)."""
    g, sink, machines, tasks = _build_graph(num_tasks, num_machines)
    st = DeviceGraphState()
    st.full_build(g)
    res = DeviceResidentState(st) if resident else None
    solver = JaxSolver(slot_stable=slot_stable, telemetry=telemetry)
    rng = np.random.default_rng(7)
    out = []
    for rnd in range(rounds + 1):
        if rnd:
            _churn_round(st, SCRIPT[(rnd - 1) % len(SCRIPT)], tasks, machines, rng)
        if force_layout_rebuild:
            st.plan.invalidate()
        prob = res.refresh() if resident else st.problem()
        r = solver.solve(prob)
        tel = solver.last_telemetry
        out.append((
            np.asarray(r.flow).copy(),
            solver.last_supersteps,
            tel.rows.copy() if tel is not None else None,
            r.objective,
        ))
        if resident:
            res.parity_check()
            res.plan_parity_check()
        if slot_stable and not st.plan.needs_rebuild:
            st.plan.check_invariants()
    return out


BUCKETS = [(8, 3), (24, 5), (56, 9)]


# ---------------------------------------------------------------------------
# the tentpole parity claims
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("nt,nm", BUCKETS)
def test_scatter_vs_rebuild_plan_bit_parity(nt, nm):
    """The scatter-maintained device plan (resident mirror, packed
    records through plan_apply_fn) and the full-upload path (maintained
    host arrays re-shipped wholesale) produce bit-identical flows,
    superstep counts, and telemetry rows on every round of the churn
    script — including slot-recycle and endpoint-rewire rounds."""
    scatter = _drive(nt, nm, resident=True)
    rebuild = _drive(nt, nm, resident=False)
    assert len(scatter) == len(rebuild)
    for rnd, (a, b) in enumerate(zip(scatter, rebuild)):
        assert np.array_equal(a[0], b[0]), f"flow diverged at round {rnd}"
        assert a[1] == b[1], f"supersteps diverged at round {rnd}: {a[1]} vs {b[1]}"
        assert np.array_equal(a[2], b[2]), f"telemetry rows diverged at round {rnd}"
        assert a[3] == b[3]


@pytest.mark.parametrize("nt,nm", BUCKETS)
def test_slot_stable_objective_parity_vs_legacy_and_forced_rebuild(nt, nm):
    """Entry order inside a node's region drifts from a fresh argsort
    once slots recycle, so cost-tied optima may differ arc-wise — but
    every arm must land the same objective every round, and the FIRST
    layout (fresh full_build, before any churn) is constructed
    allocation-order identical to the stable argsort, so round 0 flows
    match the legacy plan bit-for-bit."""
    stable = _drive(nt, nm, resident=False)
    legacy = _drive(nt, nm, resident=False, slot_stable=False)
    forced = _drive(nt, nm, resident=False, force_layout_rebuild=True)
    for rnd, (a, b, c) in enumerate(zip(stable, legacy, forced)):
        assert a[3] == b[3] == c[3], f"objective diverged at round {rnd}"
    assert np.array_equal(stable[0][0], legacy[0][0])
    assert np.array_equal(stable[0][0], forced[0][0])


def test_plan_survives_bucket_growth_and_region_overflow():
    """m_cap growth and a region overflow both invalidate the layout;
    the next consumer rebuilds and the scatter path resumes with bit
    parity (the mirror re-uploads on the layout generation bump)."""
    g, sink, machines, tasks = _build_graph(6, 3)
    st = DeviceGraphState()
    st.full_build(g)
    res = DeviceResidentState(st)
    solver = JaxSolver(telemetry=32)
    solver.solve(res.refresh())
    assert res.last_plan_kind == "none" or st.plan.enabled
    gen0 = st.plan.layout_gen
    # flood in fresh arcs until m_cap grows (layout invalidated);
    # positive-cost side arcs never change feasibility, and objective
    # parity vs the legacy plan is asserted on the final state below
    m0 = st.m_cap
    pairs = iter(
        (a, b)
        for a in tasks + machines
        for b in tasks + machines
        if a != b
    )
    while st.m_cap == m0:
        a, b = next(pairs)
        if (a, b) in st._arc_slot:
            continue
        st.apply_changes([NewArcChange(a, b, 0, 1, 5, ArcType.OTHER)])
    assert st.plan.needs_rebuild
    r = solver.solve(res.refresh())
    assert st.plan.layout_gen > gen0
    assert res.last_plan_kind in ("rebuild", "none")
    res.plan_parity_check()
    st.plan.check_invariants()
    assert r.objective == JaxSolver(slot_stable=False).solve(st.problem()).objective


def test_drain_records_coalesce_and_pad():
    """Multiple writes to one plan row in a round ship once (final
    value), records are sorted/deterministic, padding repeats a real
    record so duplicate scatters stay idempotent."""
    g, sink, machines, tasks = _build_graph(6, 3)
    st = DeviceGraphState()
    st.full_build(g)
    st.plan.ensure_built()
    st.plan.clear_pending()
    # same (src, dst) killed and re-added twice in one round
    for _ in range(2):
        st.apply_changes([
            ChangeArcChange(tasks[0], machines[0], 0, 0, 0, ArcType.OTHER, 0),
            NewArcChange(tasks[0], machines[0], 0, 1, 9, ArcType.OTHER),
        ])
    row_rec, inv_rec, seg_rec, node_rec = st.plan.drain_records()
    assert not st.plan.has_pending
    # no relocation happened, so the static streams are pure idempotent
    # pads: rewrites of dead position 0 / node 0's current meta
    assert (seg_rec[:, 0] == 0).all()
    assert (seg_rec[:, 1] == st.plan.seg_start[0]).all()
    assert (node_rec[:, 0] == 0).all()
    assert (node_rec[:, 1] == st.plan.node_first[0]).all()
    pos = row_rec[:, 0]
    # padded tail repeats row 0; the real prefix is strictly sorted
    uniq = np.unique(pos)
    k = len(uniq)
    assert np.array_equal(pos[:k], uniq)
    assert (row_rec[k:] == row_rec[0]).all()
    # final values only: rows agree with the maintained host arrays
    assert np.array_equal(row_rec[:k, 1], st.plan.p_arc[uniq])
    assert np.array_equal(row_rec[:k, 2], st.plan.p_sign[uniq])
    ents = inv_rec[:, 0]
    ku = len(np.unique(ents))
    assert np.array_equal(inv_rec[:ku, 1], st.plan.inv_order[np.unique(ents)])
    st.plan.check_invariants()


def test_clean_round_ships_no_plan_bytes():
    """A cap/cost-only round leaves the plan untouched: the resident
    mirror reports a clean plan sync (zero plan bytes) while the
    problem delta still flows."""
    g, sink, machines, tasks = _build_graph(8, 3)
    st = DeviceGraphState()
    st.full_build(g)
    res = DeviceResidentState(st)
    solver = JaxSolver(telemetry=0)
    solver.solve(res.refresh())  # round 0: plan becomes enabled
    solver.solve(res.refresh())  # round 1: mirror uploads the layout
    assert res.last_plan_kind in ("rebuild", "clean")
    s, d = sorted(st._arc_slot.keys())[0]
    st.apply_changes([NewArcChange(s, d, 0, 2, 7, ArcType.OTHER)])
    ep_gen = st.endpoint_gen
    solver.solve(res.refresh())
    assert st.endpoint_gen == ep_gen, "cap/cost change must not bump endpoint_gen"
    assert res.last_plan_kind == "clean"
    assert res.last_plan_bytes == 0
    assert res.last_upload_kind == "delta"


def test_recycled_id_rebuilds_once_then_scatters():
    """Region sizing uses the per-id degree HIGH-WATER MARK: a node id
    whose new tenant needs more rows than the old one held pays at
    most ONE relocation/rebuild while the id sets its degree record,
    after which the steady completion/arrival recycle dance runs
    entirely through the scatter path — no layout rebuilds. Sizing by
    instantaneous degree instead turns EVERY such recycle round into a
    rebuild (the r12 bench regression this pins)."""
    g, sink, machines, tasks = _build_graph(10, 4)
    st = DeviceGraphState()
    st.full_build(g)
    res = DeviceResidentState(st)
    solver = JaxSolver(telemetry=0)
    solver.solve(res.refresh())
    solver.solve(res.refresh())  # mirror uploads the layout

    def recycle_round(t):
        """Complete task t (kill ALL its arcs — the node drops to
        degree 0, like a completed task) and re-wire it as an arriving
        task with a FULL preference set (max degree)."""
        for s, d in [k for k in sorted(st._arc_slot.keys()) if k[0] == t]:
            st.apply_changes([ChangeArcChange(s, d, 0, 0, 0, ArcType.OTHER, 0)])
        for m in machines:
            st.apply_changes([NewArcChange(t, m, 0, 1, 3, ArcType.OTHER)])

    # round A: the recycled id wires MORE arcs than it held at layout
    # time (every machine vs the build's 3-of-4 preference sample) —
    # allowed to overflow once while the id sets its degree record
    recycle_round(tasks[0])
    solver.solve(res.refresh())
    res.plan_parity_check()
    rebuilds_after_record = st.plan.layout_rebuilds
    # rounds B..E: the same recycle shape again — the high-water mark
    # now covers it, so every round must ride the scatter (or clean)
    # path with zero further rebuilds
    for rnd in range(4):
        recycle_round(tasks[0])
        solver.solve(res.refresh())
        assert st.plan.layout_rebuilds == rebuilds_after_record, (
            f"steady recycle round {rnd} forced a layout rebuild"
        )
        assert res.last_plan_kind == "delta", res.last_plan_kind
        res.plan_parity_check()
        st.plan.check_invariants()


def test_region_relocation_rides_the_scatter():
    """A node that out-churns its region slack is RELOCATED into the
    tail pool — an O(degree) journaled move that rides the same
    per-round scatter as ordinary endpoint churn (plan kind stays
    "delta", ZERO layout rebuilds), with the segment/node boundary
    statics scattered alongside and full mirror parity + invariants
    held."""
    g, sink, machines, tasks = _build_graph(10, 4)
    st = DeviceGraphState()
    st.full_build(g)
    res = DeviceResidentState(st)
    solver = JaxSolver(telemetry=0)
    solver.solve(res.refresh())
    solver.solve(res.refresh())  # mirror uploads the layout
    rebuilds0 = st.plan.layout_rebuilds
    t = tasks[0]
    # wire the task far past its region (mark + slack): every machine
    # plus a handful of peer tasks as extra endpoints
    for d in machines + tasks[1:8]:
        if (t, d) not in st._arc_slot:
            st.apply_changes([NewArcChange(t, d, 0, 1, 3, ArcType.OTHER)])
    assert st.plan.region_relocations >= 1, "region never relocated"
    assert st.plan.layout_rebuilds == rebuilds0, "relocation must not rebuild"
    r = solver.solve(res.refresh())
    assert res.last_plan_kind == "delta", res.last_plan_kind
    res.plan_parity_check()
    st.plan.check_invariants()
    assert r.objective == JaxSolver(slot_stable=False).solve(st.problem()).objective
    # steady churn keeps riding the scatter after the move
    rng = np.random.default_rng(11)
    for _ in range(3):
        _churn_round(st, "rewire", tasks, machines, rng)
        solver.solve(res.refresh())
        assert st.plan.layout_rebuilds == rebuilds0
        res.plan_parity_check()
        st.plan.check_invariants()


def test_plan_key_skips_endpoint_scans(monkeypatch):
    """Satellite: a clean round returns the cached device plan straight
    off the generation key — np.array_equal is never consulted (the two
    O(M) endpoint scans are gone from the clean-round path)."""
    import ksched_tpu.solver.jax_solver as jxs

    g, sink, machines, tasks = _build_graph(8, 3)
    st = DeviceGraphState()
    st.full_build(g)
    solver = JaxSolver(slot_stable=False)
    solver.solve(st.problem())
    def _boom(*a, **k):  # pragma: no cover - only fires on regression
        raise AssertionError("endpoint scan ran on a clean round")
    monkeypatch.setattr(jxs.np, "array_equal", _boom)
    solver.solve(st.problem())  # clean round: key matches, no scan
    monkeypatch.undo()
    # ...and an endpoint change bumps the key, forcing a real rebuild
    st.apply_changes([
        ChangeArcChange(tasks[0], machines[0], 0, 0, 0, ArcType.OTHER, 0),
    ])
    key2 = st.plan_key()
    assert key2 != solver._plan_key
    solver.solve(st.problem())
    assert solver._plan_key == key2


def test_warm_price_war_event_structured():
    """Satellite: a kept-flow warm attempt that burns its budget
    deposits a structured `warm_price_war` stall event (flight dumps
    can tell a price war from genuine non-convergence), then the
    escape hatch still lands the solve."""
    soltel.reset_stalls()
    g, sink, machines, tasks = _build_graph(10, 4)
    st = DeviceGraphState()
    st.full_build(g)
    # restart_budget=0: the warm attempt can never converge (zero
    # supersteps allowed). Round 2's churn is cost-only (NO endpoint
    # change), so the journal-scoped policy keeps the carried flow,
    # runs the warm attempt, deterministically blows the budget, and
    # escapes to the fresh restart.
    solver = JaxSolver(restart_budget=0, telemetry=32)
    solver.solve(st.problem())
    s, d = sorted(st._arc_slot.keys())[0]
    st.apply_changes([
        ChangeArcChange(s, d, 0, int(st.cap[st._arc_slot[(s, d)]]), 9,
                        ArcType.OTHER, 0),
    ])
    r1 = solver.solve(st.problem())
    assert solver.last_warm_scope == "warm"
    legacy = JaxSolver(slot_stable=False, warm_start=False).solve(st.problem())
    assert r1.objective == legacy.objective
    events = [e for e in soltel.recent_stalls() if e["kind"] == "warm_price_war"]
    assert events, "no warm_price_war event deposited"
    ev = events[-1]
    assert ev["backend"] == "jax"
    assert ev["budget"] == 0 and ev["supersteps"] == 0
    assert ev["converged"] is False
    assert "escaping to fresh_restart" in ev["detail"]
    soltel.reset_stalls()


def test_journal_scoped_warm_policy():
    """The journal decides the warm scope per round: an endpoint-churn
    round dispatches the fresh restart (scope "fresh", fresh-restart-
    band supersteps — the kept-flow discharge would be the
    hundreds-to-thousands price war), while a cost-only round keeps
    the carried flow + refit prices (scope "warm") and converges well
    inside the warm budget. Objectives stay exact either way.

    Ample machine capacity on purpose: the superstep-band claims hold
    in the feasible regime (the bench regime); a starved cluster
    relabels down the full cost range for ANY policy (see
    _build_graph)."""
    g, sink, machines, tasks = _build_graph(24, 5, machine_cap=(10, 16))
    st = DeviceGraphState()
    st.full_build(g)
    solver = JaxSolver()
    solver.solve(st.problem())
    assert solver.last_warm_scope == "cold"
    rng = np.random.default_rng(3)
    for _ in range(4):
        _churn_round(st, "rewire", tasks, machines, rng)
        r = solver.solve(st.problem())
        assert solver.last_warm_scope == "fresh"
        assert solver.last_supersteps <= 64, (
            f"journal-scoped restart ran {solver.last_supersteps} supersteps"
        )
        assert r.objective == JaxSolver(
            slot_stable=False, warm_start=False
        ).solve(st.problem()).objective
    # Cost-only round: reprice task->machine arcs WITHOUT touching
    # caps (the script's "cost" kind rewrites caps too, which in this
    # ample regime would slash machine->sink capacity and displace
    # most of the carried flow — a capacity regime change, not the
    # mild repricing the warm path is for). endpoint_gen must not
    # move; the carried flow survives and the refit repairs prices.
    live = sorted(st._arc_slot.keys())
    tm = [(s, d) for (s, d) in live if s in tasks and d in machines]
    ep_gen = st.endpoint_gen
    for s, d in tm[:4]:
        slot = st._arc_slot[(s, d)]
        st.apply_changes([
            NewArcChange(s, d, 0, int(st.cap[slot]),
                         int(rng.integers(0, 10)), ArcType.OTHER),
        ])
    assert st.endpoint_gen == ep_gen
    r = solver.solve(st.problem())
    assert solver.last_warm_scope == "warm"
    # displaced-by-repricing excess crawls proportional to the cost
    # DELTA (here <= 10*N ~ a few hundred supersteps), not the full
    # price-war band; the warm attempt must converge without burning
    # its 4096-step budget
    assert solver.last_supersteps <= 1024, (
        f"warm refit round ran {solver.last_supersteps} supersteps"
    )
    assert r.objective == JaxSolver(
        slot_stable=False, warm_start=False
    ).solve(st.problem()).objective
