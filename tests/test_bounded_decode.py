"""Bounded-decode steady rounds (DeviceBulkCluster decode_width): when
the window doesn't bind, results are identical to the full-width path;
when it binds, each round places at most `decode_width` tasks and the
backlog drains across rounds."""

import numpy as np
import jax.numpy as jnp
import pytest

from ksched_tpu.scheduler.device_bulk import DeviceBulkCluster


def _cluster(decode_width, C=2, task_capacity=512, slots=2, seed=9):
    cost = np.random.default_rng(seed).integers(0, 20, (C, 12)).astype(np.int32)
    cost_d = jnp.asarray(cost)
    return DeviceBulkCluster(
        num_machines=12, pus_per_machine=2, slots_per_pu=slots, num_jobs=3,
        num_task_classes=C, task_capacity=task_capacity,
        class_cost_fn=lambda census: cost_d, unsched_cost=25,
        decode_width=decode_width,
    )


@pytest.mark.parametrize("C", [1, 2])
def test_unbinding_window_matches_full_path(C):
    """Same seeds, same initial tasks: a window larger than any round's
    backlog must produce identical steady-round stats to the full path."""
    rng = np.random.default_rng(3)
    jobs = rng.integers(0, 3, 40).astype(np.int32)
    cls = rng.integers(0, C, 40).astype(np.int32)

    def run(width):
        dev = _cluster(width, C=C)
        dev.add_tasks(40, jobs, cls)
        dev.fetch_stats(dev.round())
        return dev.fetch_stats(dev.run_steady_rounds(6, 0.15, 4, seed=7))

    full = run(None)
    bounded = run(256)
    for k in full:
        np.testing.assert_array_equal(full[k], bounded[k], err_msg=f"stat {k}")


def test_binding_window_caps_and_drains():
    """Backlog 90 >> window 16 with ample capacity: each steady round
    places exactly 16 until the backlog drains; unscheduled reports the
    full pending backlog, not just the solver's escapes."""
    dev = _cluster(16, C=2, slots=4)  # 12*2*4 = 96 slots
    rng = np.random.default_rng(0)
    dev.add_tasks(90, rng.integers(0, 3, 90).astype(np.int32),
                  rng.integers(0, 2, 90).astype(np.int32))
    s = dev.fetch_stats(dev.run_steady_rounds(6, 0.0, 0, seed=1))
    assert bool(np.asarray(s["converged"]).all())
    np.testing.assert_array_equal(
        np.asarray(s["placed"]), [16, 16, 16, 16, 16, 10]
    )
    np.testing.assert_array_equal(
        np.asarray(s["unscheduled"]), [74, 58, 42, 26, 10, 0]
    )
    assert dev.num_placed_tasks == 90


def test_window_wider_than_pool_is_full_path():
    dev = _cluster(10_000, task_capacity=512)
    assert dev.decode_width is None


def test_preempt_mover_window_matches_full_when_unbinding():
    """Preemption mode: a mover window wider than any round's mover set
    must produce identical steady-round stats and final state to the
    full-width decode."""
    cost = np.random.default_rng(4).integers(0, 15, (2, 8)).astype(np.int32)
    cost_d = jnp.asarray(cost)

    def make(width):
        return DeviceBulkCluster(
            num_machines=8, pus_per_machine=1, slots_per_pu=3, num_jobs=2,
            num_task_classes=2, task_capacity=128,
            class_cost_fn=lambda census: cost_d, unsched_cost=40,
            preemption=True, continuation_discount=3,
            decode_width=width, supersteps=1 << 14,
        )

    rng = np.random.default_rng(1)
    jobs = rng.integers(0, 2, 30).astype(np.int32)
    cls = rng.integers(0, 2, 30).astype(np.int32)
    outs = []
    for width in (None, 127):  # 127 < Tcap (width >= Tcap means full)
        dev = make(width)
        dev.add_tasks(30, jobs, cls)
        s0 = dev.fetch_stats(dev.round())
        assert bool(s0["converged"])
        stats = dev.fetch_stats(
            dev.run_steady_rounds(12, churn_prob=0.1, arrivals=3, seed=5)
        )
        assert stats["converged"].all()
        outs.append((stats, dev.fetch_state()))
    (sa, sta), (sb, stb) = outs
    for key in ("placed", "migrated", "preempted", "unscheduled"):
        assert sa[key].tolist() == sb[key].tolist(), key
    for key in sta:
        assert np.array_equal(np.asarray(sta[key]), np.asarray(stb[key])), key


def test_preempt_mover_window_binds_and_drains():
    """A binding mover window grants at most W movers per round; the
    remainder stays pending and drains across rounds (occupancy stays
    consistent throughout)."""
    dev = DeviceBulkCluster(
        num_machines=6, pus_per_machine=1, slots_per_pu=4, num_jobs=1,
        num_task_classes=1, task_capacity=64, unsched_cost=40,
        preemption=True, continuation_discount=1,
        decode_width=4, supersteps=1 << 14,
    )
    dev.add_tasks(20)
    # the one-shot fill round decodes full-width (fill path): all place
    s = dev.fetch_stats(dev.round())
    assert bool(s["converged"]) and int(s["placed"]) == 20
    # steady rounds: complete nothing, admit 4/round into 4 free slots;
    # each round's movers (the fresh arrivals) fit the window
    stats = dev.fetch_stats(
        dev.run_steady_rounds(4, churn_prob=0.0, arrivals=1, seed=2)
    )
    assert stats["converged"].all()
    assert (stats["placed"] <= 4).all()
    st = {k: np.asarray(v) for k, v in dev.fetch_state().items()}
    live, pu = st["live"], st["pu"]
    recount = np.bincount(pu[live & (pu >= 0)], minlength=dev.num_pus)
    assert (recount == st["pu_running"]).all()
    assert (st["pu_running"] <= dev.S).all()


def test_invalid_width_rejected():
    with pytest.raises(ValueError):
        _cluster(0)
    with pytest.raises(ValueError):
        _cluster(-4)


def test_rotating_window_defeats_escapee_starvation():
    """Solver-escaped tasks parked in low rows must not pin the window:
    class-0 tasks are unplaceable everywhere (cost > unsched), class-1
    tasks are free to place. With a window smaller than the escapee
    count, rotation must still let every class-1 task (admitted in
    HIGHER rows) get placed within a few rounds."""
    C = 2
    cost = np.zeros((C, 12), np.int32)
    cost[0, :] = 100  # class 0: placement always worse than unsched (25)
    cost_d = jnp.asarray(cost)
    dev = DeviceBulkCluster(
        num_machines=12, pus_per_machine=2, slots_per_pu=2, num_jobs=3,
        num_task_classes=C, task_capacity=512,
        class_cost_fn=lambda census: cost_d, unsched_cost=25,
        decode_width=8,
    )
    # rows 0..23: unplaceable class-0 escapees; rows 24..39: class-1
    dev.add_tasks(24, np.zeros(24, np.int32), np.zeros(24, np.int32))
    dev.add_tasks(16, np.zeros(16, np.int32), np.ones(16, np.int32))
    s = dev.fetch_stats(dev.run_steady_rounds(32, 0.0, 0, seed=3))
    assert bool(np.asarray(s["converged"]).all())
    st = dev.fetch_state()
    pu = np.asarray(st["pu"])
    cls = np.asarray(st["cls"])
    live = np.asarray(st["live"])
    assert (pu[live & (cls == 1)] >= 0).all(), "a placeable task starved"
    assert (pu[live & (cls == 0)] < 0).all()  # escapees correctly pend
