"""The bench-trajectory ratchet (tools/bench_compare.py + `make
bench-gate`): append normalizes bench records into trajectory entries,
gate fails on >tolerance p50 regression within a (config, platform)
series and never compares across platforms or against cpu-fallback
readings."""

import json
import subprocess
import sys

import pytest

from tools.bench_compare import (
    DEFAULT_TOLERANCE,
    entry_from_record,
    load_trajectory,
)


def _write(path, entries):
    with open(path, "w") as f:
        for e in entries:
            f.write(json.dumps(e) + "\n")


def _gate(path, tolerance=DEFAULT_TOLERANCE):
    return subprocess.run(
        [sys.executable, "-m", "tools.bench_compare", "gate", str(path),
         "--tolerance", str(tolerance)],
        capture_output=True, text=True,
    )


def _entry(config, p50, platform="cpu", **kw):
    return {"config": config, "platform": platform, "p50_ms": p50,
            "commit": "t", **kw}


def test_gate_passes_within_tolerance(tmp_path):
    p = tmp_path / "traj.jsonl"
    _write(p, [_entry("a", 10.0), _entry("a", 11.0)])
    r = _gate(p)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "OK" in r.stdout


def test_gate_fails_on_regression(tmp_path):
    p = tmp_path / "traj.jsonl"
    _write(p, [_entry("a", 10.0), _entry("a", 12.0)])
    r = _gate(p)
    assert r.returncode == 1
    assert "REGRESSED" in r.stdout and "BENCH GATE FAILED" in r.stderr


def test_gate_compares_last_two_only(tmp_path):
    """A recovered regression does not keep failing the gate."""
    p = tmp_path / "traj.jsonl"
    _write(p, [_entry("a", 10.0), _entry("a", 20.0), _entry("a", 20.5)])
    assert _gate(p).returncode == 0


def test_gate_ignores_cross_platform_series(tmp_path):
    p = tmp_path / "traj.jsonl"
    _write(p, [_entry("a", 2.0, platform="tpu"), _entry("a", 50.0)])
    r = _gate(p)
    assert r.returncode == 0  # different platforms: no comparison


def test_gate_skips_fallback_vs_device_baseline(tmp_path):
    p = tmp_path / "traj.jsonl"
    _write(p, [
        _entry("a", 2.0),
        _entry("a", 50.0, accelerator_unreachable=True),
    ])
    # same platform label but one is a cpu-fallback stamp: skipped
    _write(p, [
        {**_entry("a", 2.0)},
        {**_entry("a", 50.0), "accelerator_unreachable": True},
    ])
    assert _gate(p).returncode == 0


def test_gate_ratchets_supersteps_p50(tmp_path):
    """Series carrying supersteps_p50 ratchet it alongside latency: a
    warm-start price war creeping back (10 → 600 supersteps) fails the
    gate even when the idle-CPU wall clock stayed flat."""
    p = tmp_path / "traj.jsonl"
    _write(p, [
        _entry("churn", 10.0, supersteps_p50=10),
        _entry("churn", 10.0, supersteps_p50=600),
    ])
    r = _gate(p)
    assert r.returncode == 1
    assert "supersteps_p50" in r.stderr and "price war" in r.stderr


def test_gate_supersteps_slack_absorbs_quantization(tmp_path):
    """Small integer jitter near the healthy ~10 band is quantization,
    not regression: +25% relative alone (10 → 13) must pass — the
    absolute slack gates it out."""
    p = tmp_path / "traj.jsonl"
    _write(p, [
        _entry("churn", 10.0, supersteps_p50=10),
        _entry("churn", 10.0, supersteps_p50=13),
    ])
    assert _gate(p).returncode == 0


def test_gate_supersteps_absent_is_not_gated(tmp_path):
    """A series without the field (non-churn configs) never trips the
    supersteps ratchet, and a series that only just gained it has no
    baseline to compare against."""
    p = tmp_path / "traj.jsonl"
    _write(p, [
        _entry("a", 10.0),
        _entry("a", 10.5, supersteps_p50=9),
    ])
    assert _gate(p).returncode == 0


def test_gate_single_entry_series_passes(tmp_path):
    p = tmp_path / "traj.jsonl"
    _write(p, [_entry("a", 10.0), _entry("b", 5.0)])
    assert _gate(p).returncode == 0


def test_gate_rejects_bad_json_line(tmp_path):
    p = tmp_path / "traj.jsonl"
    p.write_text('{"config": "a"}\nnot json\n')
    r = _gate(p)
    assert r.returncode != 0


def test_entry_from_record_normalizes():
    rec = {
        "metric": "p50 ... backend=jax/cpu",
        "value": 12.5,
        "vs_baseline": 0.8,
        "config": "10kx1k",
        "detail": {"supersteps_p50": 7, "supersteps_max": 40},
    }
    e = entry_from_record(rec)
    assert e["config"] == "10kx1k" and e["platform"] == "cpu"
    assert e["p50_ms"] == 12.5 and e["supersteps_p50"] == 7
    assert "utc" in e and "commit" in e


def test_entry_marks_fallback():
    rec = {"metric": "p50 ... backend=device/cpu", "value": 1.0,
           "accelerator_unreachable": True}
    e = entry_from_record(rec, config="x")
    assert e["accelerator_unreachable"] and e["platform"] == "cpu-fallback"


def test_mesh_shape_is_part_of_the_series_key():
    """A 2-dev CPU reading must never baseline (or gate) an 8-dev
    series: entries with different mesh_devices are different series,
    so a fast small-mesh run followed by a slower big-mesh run is NOT
    a regression (and vice versa can't mask one)."""
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        p = d + "/traj.jsonl"
        _write(p, [
            _entry("sharded", 10.0, mesh_devices=2),
            _entry("sharded", 50.0, mesh_devices=8),  # not a regression
        ])
        assert _gate(p).returncode == 0
        _write(p, [
            _entry("sharded", 10.0, mesh_devices=8),
            _entry("sharded", 50.0, mesh_devices=8),  # IS a regression
        ])
        r = _gate(p)
        assert r.returncode == 1 and "8dev" in r.stdout


def test_entry_from_record_lifts_mesh_devices():
    rec = {
        "metric": "p50 ... backend=sharded/cpu",
        "value": 5.0,
        "detail": {"mesh_devices": 8},
    }
    e = entry_from_record(rec, config="gtrace100k")
    assert e["mesh_devices"] == 8


def test_checked_in_trajectory_is_wellformed_and_gates_clean():
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(root, "BENCH_TRAJECTORY.jsonl")
    entries = load_trajectory(path)
    assert entries, "BENCH_TRAJECTORY.jsonl must not be empty"
    for e in entries:
        assert e.get("config") and e.get("p50_ms") is not None
    assert _gate(path).returncode == 0, "checked-in trajectory must gate clean"
