"""Sharded (multi-chip) solver: parity with the single-chip backend and
the exact oracle on a virtual 8-device CPU mesh."""

import numpy as np
import jax
import pytest
from jax.sharding import Mesh

from ksched_tpu.parallel.sharded_solver import ShardedJaxSolver
from ksched_tpu.solver import ReferenceSolver
from ksched_tpu.solver.jax_solver import JaxSolver

from test_jax_solver import random_scheduling_problem, assert_valid_flow
from test_solver_oracle import make_problem


@pytest.fixture(scope="module")
def mesh():
    devs = jax.devices()
    assert len(devs) >= 8, "conftest should provide 8 virtual CPU devices"
    return Mesh(np.array(devs[:8]), ("x",))


def test_sharded_small(mesh):
    p = make_problem(
        8,
        {1: 1, 2: 1, 6: -2},
        [
            (1, 3, 0, 1, 2),
            (2, 3, 0, 1, 2),
            (3, 4, 0, 1, 0),
            (3, 5, 0, 1, 4),
            (4, 6, 0, 1, 0),
            (5, 6, 0, 1, 0),
            (1, 7, 0, 1, 50),
            (2, 7, 0, 1, 50),
            (7, 6, 0, 2, 0),
        ],
    )
    ref = ReferenceSolver().solve(p)
    sh = ShardedJaxSolver(mesh).solve(p)
    assert sh.objective == ref.objective
    assert_valid_flow(p, sh.flow)


def test_sharded_random_parity(mesh):
    rng = np.random.default_rng(3)
    solver = ShardedJaxSolver(mesh)
    for trial in range(4):
        p = random_scheduling_problem(
            rng,
            num_tasks=int(rng.integers(5, 30)),
            num_machines=int(rng.integers(2, 6)),
            slots_per_machine=int(rng.integers(1, 4)),
        )
        ref = ReferenceSolver().solve(p)
        sh = ShardedJaxSolver(mesh).solve(p)
        assert sh.objective == ref.objective, f"trial {trial}"
        assert_valid_flow(p, sh.flow)


def test_sharded_warm_rounds(mesh):
    rng = np.random.default_rng(4)
    p = random_scheduling_problem(rng, num_tasks=12, num_machines=3, slots_per_machine=2)
    solver = ShardedJaxSolver(mesh)
    r1 = solver.solve(p)
    assert r1.objective == ReferenceSolver().solve(p).objective
    # cost perturbation, warm re-solve
    p.cost[0] += 3
    r2 = solver.solve(p)
    assert r2.objective == ReferenceSolver().solve(p).objective
