"""L7 integration tests: cluster API debouncing, the scheduler service
main loop (CLI), podgen, and Google-trace replay."""

import threading
import time

import numpy as np

from ksched_tpu.cli import SchedulerService, podgen
from ksched_tpu.cluster import Binding, NodeEvent, PodEvent, SyntheticClusterAPI
from ksched_tpu.costmodels import CostModelType
from ksched_tpu.drivers.trace_replay import (
    FINISH,
    SUBMIT,
    TraceReplayDriver,
    TraceTaskEvent,
    parse_task_events,
    synthesize_trace,
)


# -- cluster API ----------------------------------------------------------


def test_pod_batch_debounce_drains_queue():
    api = SyntheticClusterAPI()
    for i in range(7):
        api.submit_pod(PodEvent(pod_id=f"p{i}"))
    batch = api.get_pod_batch(timeout_s=0.05)
    assert len(batch) == 7


def test_pod_batch_timer_resets_on_arrival():
    """A trickle of pods slower than the quiet period still lands in ONE
    batch because each arrival resets the timer (client.go:153-193)."""
    api = SyntheticClusterAPI()

    def trickle():
        for i in range(4):
            api.submit_pod(PodEvent(pod_id=f"p{i}"))
            time.sleep(0.03)

    t = threading.Thread(target=trickle)
    t.start()
    batch = api.get_pod_batch(timeout_s=0.15)
    t.join()
    assert len(batch) == 4


def test_node_batch_startup_window_expires():
    api = SyntheticClusterAPI()
    t0 = time.monotonic()
    assert api.get_node_batch(timeout_s=0.1) == []
    assert time.monotonic() - t0 < 1.0  # bounded, no hang


def test_closed_api_returns_empty():
    api = SyntheticClusterAPI()
    api.close()
    assert api.get_pod_batch(timeout_s=0.05) == []


# -- scheduler service (CLI main loop) ------------------------------------


def _service(machines=4, pus=2, cost_model=CostModelType.TRIVIAL, max_tasks_per_pu=1):
    api = SyntheticClusterAPI()
    svc = SchedulerService(api, max_tasks_per_pu=max_tasks_per_pu, cost_model=cost_model)
    svc.init_topology(fake_machines=machines, pus_per_core=pus)
    return api, svc


def test_service_schedules_podgen_load():
    api, svc = _service(machines=4, pus=2)
    podgen(api, 6)
    pods = api.get_pod_batch(0.05)
    bound = svc.run_once(pods)
    assert bound == 6
    bindings = api.bindings()
    assert len(bindings) == 6
    assert all(n.startswith("fake_node_") for n in bindings.values())


def test_service_binds_only_deltas_across_rounds():
    api, svc = _service(machines=2, pus=2)
    podgen(api, 2)
    svc.run_once(api.get_pod_batch(0.05))
    first = dict(api.bindings())
    # second round: two more pods; existing bindings must not be re-posted
    for i in range(2):
        api.submit_pod(PodEvent(pod_id=f"late_{i}"))
    bound = svc.run_once(api.get_pod_batch(0.05))
    assert bound == 2
    assert dict(list(api.bindings().items())[: len(first)]) == first


def test_service_overload_leaves_surplus_unscheduled():
    api, svc = _service(machines=2, pus=1)  # 2 slots total
    podgen(api, 5)
    bound = svc.run_once(api.get_pod_batch(0.05))
    assert bound == 2
    assert len(api.bindings()) == 2


# -- trace replay ---------------------------------------------------------


def test_synthesized_machine_churn_evicts_in_replay():
    """The synthesizer's mid-trace outages must actually displace
    running tasks during replay (evictions observed, cluster recovers)."""
    from ksched_tpu.drivers.trace_replay import (
        MACHINE_ADD,
        MACHINE_REMOVE,
        TraceReplayDriver,
        synthesize_trace,
    )
    from ksched_tpu.solver.layered import LayeredTransportSolver

    machines, events = synthesize_trace(
        num_machines=50, num_tasks=600, duration_s=300.0,
        mean_runtime_s=200.0, seed=5, machine_churn=0.3,
    )
    removes = [e for e in machines if e.event_type == MACHINE_REMOVE]
    assert len(removes) == 15
    assert any(e.event_type == MACHINE_ADD and e.time_us > 0 for e in machines)
    driver = TraceReplayDriver(
        machines, backend=LayeredTransportSolver(), slots_per_machine=4
    )
    stats = driver.replay(events, window_s=10.0)
    assert stats.evicted > 0
    # every submitted task eventually retires (evicted ones included —
    # either re-placed or finishing from the unscheduled pool)
    assert stats.finished == stats.submitted
    assert driver.cluster.num_live_tasks == 0


def test_synthesize_trace_schema():
    machines, events = synthesize_trace(num_machines=10, num_tasks=50, seed=1)
    assert len(machines) == 10
    kinds = {e.event_type for e in events}
    assert kinds == {SUBMIT, FINISH}
    times = [e.time_us for e in events]
    assert times == sorted(times)


def test_trace_replay_places_and_retires():
    machines, events = synthesize_trace(
        num_machines=20, num_tasks=200, duration_s=300.0, mean_runtime_s=60.0, seed=2
    )
    driver = TraceReplayDriver(machines, slots_per_machine=16, num_jobs_hint=8)
    stats = driver.replay(events, window_s=10.0)
    assert stats.submitted == 200
    assert stats.finished == 200
    assert stats.placed >= 180  # nearly everything should find a slot
    assert stats.rounds > 5
    assert stats.p50_ms > 0
    # all tasks retired: cluster is empty again
    assert driver.cluster.num_live_tasks == 0


def test_trace_replay_machine_churn_evicts_and_reschedules():
    """A mid-trace machine REMOVE must evict its tasks; later rounds
    reschedule them onto surviving machines."""
    from ksched_tpu.drivers.trace_replay import MACHINE_ADD, MACHINE_REMOVE, TraceMachineEvent

    machines = [
        TraceMachineEvent(time_us=0, machine_id=1, event_type=MACHINE_ADD),
        TraceMachineEvent(time_us=0, machine_id=2, event_type=MACHINE_ADD),
        # machine 1 dies at t=30s
        TraceMachineEvent(time_us=30_000_000, machine_id=1, event_type=MACHINE_REMOVE),
    ]
    events = [
        TraceTaskEvent(time_us=1_000_000 * i, job_id=1, task_index=i, event_type=SUBMIT)
        for i in range(8)
    ] + [
        TraceTaskEvent(time_us=60_000_000 + 1_000_000 * i, job_id=1, task_index=i,
                       event_type=FINISH)
        for i in range(8)
    ]
    events.sort(key=lambda e: e.time_us)
    driver = TraceReplayDriver(machines, slots_per_machine=8, num_jobs_hint=2)
    stats = driver.replay(events, window_s=5.0)
    assert stats.submitted == 8 and stats.finished == 8
    assert not driver.cluster.machine_enabled[driver._machine_index[1]]
    # anything evicted from machine 1 was re-placed (placed >= submitted)
    assert stats.placed >= stats.submitted
    assert driver.cluster.num_live_tasks == 0


def test_bulk_set_machine_enabled_invariants():
    from ksched_tpu.scheduler.bulk import BulkCluster
    from ksched_tpu.solver.native import NativeSolver

    c = BulkCluster(num_machines=2, pus_per_machine=2, slots_per_pu=2,
                    num_jobs=1, backend=NativeSolver(), task_capacity=16)
    c.add_tasks(8, np.zeros(8, np.int32))
    r = c.round()
    assert len(r.placed_tasks) == 8
    evicted = c.set_machine_enabled(0, False)
    assert len(evicted) == 4  # half the slots lived on machine 0
    assert (c.excess[evicted] == 1).all()
    r2 = c.round()
    # machine 1 is full (4 tasks): evictees stay unscheduled
    assert len(r2.placed_tasks) == 0 and r2.num_unscheduled == 4
    c.set_machine_enabled(0, True)
    r3 = c.round()
    assert len(r3.placed_tasks) == 4  # rescheduled after recovery
    assert c.num_placed_tasks == 8


def test_parse_task_events_csv(tmp_path):
    p = tmp_path / "task_events.csv"
    p.write_text(
        "0,,3,0,,0,u,2,1,0.5,0.1,0.0,0\n"
        "1000000,,3,0,,4,u,2,1,,,,\n"
    )
    evs = list(parse_task_events(str(p)))
    assert evs[0] == TraceTaskEvent(
        time_us=0, job_id=3, task_index=0, event_type=SUBMIT,
        scheduling_class=2, priority=1, cpu_req=0.5,
    )
    assert evs[1].event_type == FINISH
