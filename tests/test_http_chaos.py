"""Hermetic HTTP fault injection: the fake API server's fault hooks
(5xx / hang / latency over real sockets) against the HTTP adapter's
retry/backoff machinery, plus the hardened podgen (transient errors
retried, fatal ones close the control plane)."""

import threading

import pytest

from ksched_tpu.cli import SchedulerService, podgen
from ksched_tpu.cluster import Binding, FakeAPIServer, HTTPClusterAPI
from ksched_tpu.utils import ExpBackoff


def _api(server, **kw):
    kw.setdefault("poll_interval_s", 0.05)
    kw.setdefault("request_timeout_s", 0.5)
    kw.setdefault("backoff_base_s", 0.01)
    kw.setdefault("backoff_max_s", 0.05)
    return HTTPClusterAPI(server.base_url, **kw)


class _FaultNTimes:
    """Fail the first N requests to a route kind, then heal."""

    def __init__(self, route, action, n):
        self.route, self.action, self.left = route, action, n
        self.lock = threading.Lock()

    def __call__(self, route):
        with self.lock:
            if route == self.route and self.left > 0:
                self.left -= 1
                return dict(self.action)
        return None


# -- backoff primitive -----------------------------------------------------


def test_exp_backoff_schedule_budget_and_jitter():
    import random

    b = ExpBackoff(base_s=0.1, max_s=0.5, factor=2.0, jitter=0.0, max_retries=4)
    assert [b.next_delay() for _ in range(5)] == [0.1, 0.2, 0.4, 0.5, None]
    b.reset()
    assert b.next_delay() == 0.1
    j = ExpBackoff(base_s=0.1, jitter=0.5, max_retries=3, rng=random.Random(0))
    delays = [j.next_delay() for _ in range(3)]
    assert all(0.05 <= d <= 0.15 * (2 ** i) for i, d in enumerate(delays))
    with pytest.raises(ValueError):
        ExpBackoff(base_s=0.0)


# -- binding POST retry/backoff -------------------------------------------


def test_binding_post_retries_through_5xx_and_lands():
    hook = _FaultNTimes("bind", {"kind": "error", "code": 503}, 2)
    server = FakeAPIServer(fault_hook=hook).start()
    api = _api(server)
    try:
        server.create_pods(1)
        api.get_pod_batch(timeout_s=0.5)
        api.assign_bindings([Binding("pod_0", "node_x")])
        assert server.bindings() == {"pod_0": "node_x"}  # landed despite 2x 503
        stats = api.stats()
        assert stats["binding_retries"] == 2
        assert stats.get("binding_drops", 0) == 0
    finally:
        api.close()
        server.stop()


def test_binding_post_budget_exhausted_drops_and_pod_resurfaces():
    hook = _FaultNTimes("bind", {"kind": "error", "code": 503}, 99)
    server = FakeAPIServer(fault_hook=hook).start()
    api = _api(server, retry_budget=2)
    try:
        server.create_pods(1)
        assert [p.pod_id for p in api.get_pod_batch(timeout_s=0.5)] == ["pod_0"]
        api.assign_bindings([Binding("pod_0", "node_x")])
        assert server.bindings() == {}
        stats = api.stats()
        assert stats["binding_retries"] == 2 and stats["binding_drops"] == 1
        # the pod is pending server-side and re-enters a later batch
        assert [p.pod_id for p in api.get_pod_batch(timeout_s=0.5)] == ["pod_0"]
        server.set_fault_hook(None)  # control plane heals
        api.assign_bindings([Binding("pod_0", "node_x")])
        assert server.bindings() == {"pod_0": "node_x"}
    finally:
        api.close()
        server.stop()


def test_binding_post_4xx_is_not_retried():
    server = FakeAPIServer().start()
    api = _api(server)
    try:
        # pod never created: the server answers 404, a state error —
        # retrying would be useless; it must drop immediately
        api.assign_bindings([Binding("ghost", "node_x")])
        stats = api.stats()
        assert stats.get("binding_retries", 0) == 0
        assert stats["binding_drops"] == 1
    finally:
        api.close()
        server.stop()


def test_hang_fault_bounded_by_client_timeout_then_retry_lands():
    """A hung request (server stalls past the client timeout, then drops
    the connection) must cost one retry, not wedge the adapter."""
    hook = _FaultNTimes("bind", {"kind": "hang", "seconds": 0.8}, 1)
    server = FakeAPIServer(fault_hook=hook).start()
    api = _api(server, request_timeout_s=0.2)
    try:
        server.create_pods(1)
        api.get_pod_batch(timeout_s=0.5)
        api.assign_bindings([Binding("pod_0", "node_x")])
        assert server.bindings() == {"pod_0": "node_x"}
        assert api.stats()["binding_retries"] == 1
    finally:
        api.close()
        server.stop()


def test_latency_spike_absorbed_without_retry():
    hook = _FaultNTimes("bind", {"kind": "latency", "seconds": 0.1}, 1)
    server = FakeAPIServer(fault_hook=hook).start()
    api = _api(server)
    try:
        server.create_pods(1)
        api.get_pod_batch(timeout_s=0.5)
        api.assign_bindings([Binding("pod_0", "node_x")])
        assert server.bindings() == {"pod_0": "node_x"}
        assert api.stats().get("binding_retries", 0) == 0
    finally:
        api.close()
        server.stop()


# -- watch loops ride an outage -------------------------------------------


def test_watch_loop_rides_listing_outage_with_backoff():
    hook = _FaultNTimes("list_pods", {"kind": "error", "code": 503}, 3)
    server = FakeAPIServer(fault_hook=hook).start()
    api = _api(server)
    try:
        server.create_pods(2)
        pods = api.get_pod_batch(timeout_s=3.0)  # outage spans ~3 polls
        assert sorted(p.pod_id for p in pods) == ["pod_0", "pod_1"]
        assert api.stats()["watch_retries"] >= 3
    finally:
        api.close()
        server.stop()


# -- hardened podgen (satellite) ------------------------------------------


def test_podgen_rides_transient_500s_without_closing(recwarn):
    hook = _FaultNTimes("create_pod", {"kind": "error", "code": 503}, 2)
    server = FakeAPIServer(fault_hook=hook).start()
    # create_pod posts exactly once (podgen owns the retry layer);
    # budget 0 also disables binding retries, irrelevant here
    api = _api(server, retry_budget=0)
    try:
        podgen(api, 3, backoff=ExpBackoff(base_s=0.01, max_retries=4))
        assert not api.is_closed()  # transient blips must NOT close it
        assert server.pending_pods() == 3
        assert any("transient" in str(w.message) for w in recwarn.list)
    finally:
        api.close()
        server.stop()


def test_podgen_fatal_error_warns_and_closes():
    server = FakeAPIServer(bearer="sekret").start()
    api = _api(server, retry_budget=0)  # no token: every create is a 401
    try:
        with pytest.warns(RuntimeWarning, match="failed fatally"):
            podgen(api, 2, backoff=ExpBackoff(base_s=0.01, max_retries=2))
        assert api.is_closed()  # fatal: close, unblocking get_pod_batch
        assert api.get_pod_batch(timeout_s=0.2) == []
    finally:
        api.close()
        server.stop()


def test_podgen_budget_exhaustion_is_fatal():
    hook = _FaultNTimes("create_pod", {"kind": "error", "code": 503}, 99)
    server = FakeAPIServer(fault_hook=hook).start()
    api = _api(server, retry_budget=0)
    try:
        with pytest.warns(RuntimeWarning, match="failed fatally"):
            podgen(api, 2, backoff=ExpBackoff(base_s=0.005, max_retries=2))
        assert api.is_closed()
    finally:
        api.close()
        server.stop()


# -- end to end under chaos ------------------------------------------------


def test_service_end_to_end_with_flaky_bindings():
    """Full service over HTTP with the first 4 binding POST attempts
    503ing: all pods still land (inside the per-POST retry budget),
    observably through the retry counters."""
    hook = _FaultNTimes("bind", {"kind": "error", "code": 503}, 4)
    server = FakeAPIServer(fault_hook=hook).start()
    for i in range(2):
        server.add_node(f"node_{i}", cores=1, pus_per_core=2)
    api = _api(server)
    try:
        svc = SchedulerService(api, max_tasks_per_pu=1)
        svc.init_topology(node_batch_timeout_s=0.4)
        server.create_pods(4)
        svc.run(pod_batch_timeout_s=0.3, max_rounds=1)
        import time

        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and len(server.bindings()) < 4:
            time.sleep(0.05)
        assert len(server.bindings()) == 4
        assert api.stats()["binding_retries"] >= 4
    finally:
        api.close()
        server.stop()
