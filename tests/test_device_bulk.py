"""Device-resident cluster (scheduler/device_bulk.py): behavioral parity
with the host BulkCluster, state invariants, steady-round chains, and
elastic membership — all on the CPU backend (conftest forces
JAX_PLATFORMS=cpu), the same code path the TPU runs."""

import numpy as np
import pytest

import jax.numpy as jnp

from ksched_tpu.scheduler.bulk import BulkCluster
from ksched_tpu.scheduler.device_bulk import DeviceBulkCluster
from ksched_tpu.solver.layered import LayeredTransportSolver


def _pair(C, M=12, jobs=3, seed=9, unsched_cost=25):
    cost = np.random.default_rng(seed).integers(0, 20, (C, M)).astype(np.int32)
    cost_d = jnp.asarray(cost)
    host = BulkCluster(
        num_machines=M, pus_per_machine=2, slots_per_pu=2, num_jobs=jobs,
        backend=LayeredTransportSolver(), task_capacity=256,
        num_task_classes=C, class_cost_fn=lambda cl: cost, unsched_cost=unsched_cost,
    )
    dev = DeviceBulkCluster(
        num_machines=M, pus_per_machine=2, slots_per_pu=2, num_jobs=jobs,
        num_task_classes=C, task_capacity=256,
        class_cost_fn=lambda census: cost_d, unsched_cost=unsched_cost,
    )
    return host, dev


@pytest.mark.parametrize("C", [1, 2])
def test_device_matches_host_over_churn_rounds(C):
    host, dev = _pair(C)
    rng = np.random.default_rng(3)
    jobs = rng.integers(0, 3, 100).astype(np.int32)
    cls = rng.integers(0, C, 100).astype(np.int32)
    host.add_tasks(100, jobs, cls)
    dev.add_tasks(100, jobs, cls)
    for i in range(5):
        rh = host.round()
        sd = dev.fetch_stats(dev.round())
        assert bool(sd["converged"])
        assert len(rh.placed_tasks) == int(sd["placed"])
        assert rh.num_unscheduled == int(sd["unscheduled"])
        st = dev.fetch_state()
        ph = np.nonzero(host.task_live & (host.task_pu >= 0))[0]
        pd = np.nonzero(np.asarray(st["live"]) & (np.asarray(st["pu"]) >= 0))[0]
        common = np.intersect1d(ph, pd)
        done = rng.choice(common, 8, replace=False)
        host.complete_tasks((host.task0 + done).astype(np.int32))
        dev.complete_tasks(done.astype(np.int32))
        nj = rng.integers(0, 3, 5).astype(np.int32)
        nc = rng.integers(0, C, 5).astype(np.int32)
        host.add_tasks(5, nj, nc)
        dev.add_tasks(5, nj, nc)
    st = dev.fetch_state()
    live = np.asarray(st["live"])
    pu = np.asarray(st["pu"])
    recount = np.bincount(pu[live & (pu >= 0)], minlength=dev.num_pus)
    assert (recount == np.asarray(st["pu_running"])).all()
    assert (np.asarray(st["pu_running"]) <= dev.S).all()


def test_device_steady_round_chain_consistency():
    """A scan of steady rounds must keep supply conservation: every
    round converges, placed+unscheduled equals that round's demand, and
    the final state's occupancy must be internally consistent."""
    dev = DeviceBulkCluster(
        num_machines=20, pus_per_machine=2, slots_per_pu=2, num_jobs=4,
        num_task_classes=1, task_capacity=256,
    )
    rng = np.random.default_rng(0)
    dev.add_tasks(60, rng.integers(0, 4, 60).astype(np.int32))
    s = dev.fetch_stats(dev.round())
    assert bool(s["converged"]) and int(s["placed"]) == 60

    stats = dev.fetch_stats(dev.run_steady_rounds(20, churn_prob=0.05, arrivals=3, seed=7))
    assert stats["converged"].all()
    # each round's demand is fully accounted: placed + unscheduled
    assert (stats["placed"] + stats["unscheduled"] >= 0).all()
    st = dev.fetch_state()
    live = np.asarray(st["live"])
    pu = np.asarray(st["pu"])
    recount = np.bincount(pu[live & (pu >= 0)], minlength=dev.num_pus)
    assert (recount == np.asarray(st["pu_running"])).all()
    assert (np.asarray(st["pu_running"]) <= dev.S).all()
    assert int(live.sum()) == int(stats["live"][-1])


def test_device_machine_loss_and_rejoin():
    dev = DeviceBulkCluster(
        num_machines=4, pus_per_machine=1, slots_per_pu=2, num_jobs=1,
        num_task_classes=1, task_capacity=64, unsched_cost=100,
    )
    dev.add_tasks(8)
    s = dev.fetch_stats(dev.round())
    assert int(s["placed"]) == 8
    dev.set_machine_enabled(0, False)
    s2 = dev.fetch_stats(dev.round())
    # 2 evicted tasks compete for 6 remaining slots (all full) -> unsched
    assert int(s2["unscheduled"]) == 2
    st = dev.fetch_state()
    pu = np.asarray(st["pu"])
    live = np.asarray(st["live"])
    assert not ((pu[live] >= 0) & (pu[live] < dev.P)).any(), "machine 0 still hosts tasks"
    dev.set_machine_enabled(0, True)
    s3 = dev.fetch_stats(dev.round())
    assert int(s3["placed"]) == 2 and int(s3["unscheduled"]) == 0


def test_device_overflow_goes_unscheduled():
    dev = DeviceBulkCluster(
        num_machines=2, pus_per_machine=1, slots_per_pu=2, num_jobs=1,
        num_task_classes=1, task_capacity=64,
    )
    dev.add_tasks(10)
    s = dev.fetch_stats(dev.round())
    assert int(s["placed"]) == 4
    assert int(s["unscheduled"]) == 6
    # objective: 4 placed at (e=2) + 6 unsched at (u=5)
    assert int(s["objective"]) == 4 * 2 + 6 * 5


def test_device_admit_shortfall_reported():
    import jax

    dev = DeviceBulkCluster(
        num_machines=2, pus_per_machine=1, slots_per_pu=2, num_jobs=1,
        num_task_classes=1, task_capacity=8,
    )
    dev.add_tasks(6)
    assert int(jax.device_get(dev.last_admitted)) == 6
    # pool has 2 free rows left; asking for 5 only admits 2
    dev.add_tasks(5)
    assert int(jax.device_get(dev.last_admitted)) == 2
    assert dev.num_live_tasks == 8


def test_device_cost_overflow_flagged():
    huge = 1 << 27  # * n_scale (>= 2^3 here: C+M+3=7 -> 8) reaches 2^30 >= COST_SCALE_LIMIT

    def cost_fn(census):
        return jnp.full((2, 2), huge, jnp.int32)

    dev = DeviceBulkCluster(
        num_machines=2, pus_per_machine=1, slots_per_pu=2, num_jobs=1,
        num_task_classes=2, task_capacity=8, class_cost_fn=cost_fn,
    )
    dev.add_tasks(4, classes=np.array([0, 1, 0, 1], np.int32))
    with pytest.raises(OverflowError):
        dev.fetch_stats(dev.round())


# ---------------------------------------------------------------------------
# per-job unscheduled aggregation (graph_manager.go:1291-1305)
# ---------------------------------------------------------------------------


def _per_job_graph_path_counts(u_a: int, u_b: int):
    """Host graph-path oracle: 2 machines x 1 PU x 1 slot, two 2-task
    jobs with unsched costs (u_a, u_b). Returns placed count per job."""
    from ksched_tpu.costmodels.trivial import TrivialCostModel
    from ksched_tpu.drivers import add_job, build_cluster

    costs = {}

    class PerJobUnschedModel(TrivialCostModel):
        def task_to_unscheduled_agg_cost(self, task_id):
            return costs.get(self.task_map.find(task_id).job_id, self.UNSCHEDULED_COST)

    sched, rmap, jmap, tmap, root = build_cluster(
        num_machines=2, pus_per_core=1, cost_model_factory=PerJobUnschedModel
    )
    jid_a = add_job(sched, jmap, tmap, num_tasks=2)
    jid_b = add_job(sched, jmap, tmap, num_tasks=2)
    costs[str(jid_a)] = u_a
    costs[str(jid_b)] = u_b
    sched.schedule_all_jobs()
    placed = {str(jid_a): 0, str(jid_b): 0}
    for tid in sched.task_bindings:
        placed[tmap.find(tid).job_id] += 1
    return placed[str(jid_a)], placed[str(jid_b)]


def test_per_job_unsched_device_matches_graph_path():
    """Jobs become distinct commodities when their unsched (escape)
    costs differ: a job whose escape is cheaper than placing stays
    unscheduled while a dear-escape job fills the slots. The device
    path must reproduce the host graph path's per-job placement counts
    (tasks within a job/class are interchangeable, so counts are the
    right equivalence)."""
    # u=1 < EC cost 2: strictly cheaper to stay; u=10: strictly places.
    graph_counts = _per_job_graph_path_counts(1, 10)
    assert graph_counts == (0, 2)

    dev = DeviceBulkCluster(
        num_machines=2, pus_per_machine=1, slots_per_pu=1, num_jobs=2,
        task_capacity=16, job_unsched_cost=np.array([1, 10]),
    )
    dev.add_tasks(4, np.array([0, 0, 1, 1], np.int32))
    stats = dev.fetch_stats(dev.round())
    assert bool(stats["converged"])
    st = {k: np.asarray(v) for k, v in dev.fetch_state().items()}
    rows = np.nonzero(st["live"] & (st["pu"] >= 0))[0]
    dev_counts = (
        int((st["job"][rows] == 0).sum()),
        int((st["job"][rows] == 1).sum()),
    )
    assert dev_counts == graph_counts
    # objective: 2 job-0 escapes at u=1 + 2 placements at e=2
    assert int(stats["objective"]) == 2 * 1 + 2 * 2
    assert int(stats["unscheduled"]) == 2


def test_per_job_unsched_host_bulk_layered_matches_csr():
    """BulkCluster's layered fast path (group-expanded rows) and the
    generic CSR path (per-job arc costs) must agree on per-job
    placements and unscheduled counts."""
    from ksched_tpu.solver.cpu_ref import ReferenceSolver

    u = np.array([1, 10])
    outs = []
    for backend in (LayeredTransportSolver(), ReferenceSolver()):
        cl = BulkCluster(
            num_machines=2, pus_per_machine=1, slots_per_pu=1, num_jobs=2,
            backend=backend, job_unsched_cost=u, task_capacity=16,
        )
        cl.add_tasks(4, np.array([0, 0, 1, 1], np.int32))
        r = cl.round()
        rows = r.placed_tasks - cl.task0
        outs.append(
            (sorted(cl.task_job[rows].tolist()), r.num_unscheduled)
        )
    assert outs[0] == outs[1] == ([1, 1], 2)


def test_arrival_group_map_restricts_steady_draws():
    """set_arrival_groups must confine on-device steady-round arrival
    groups to the given set (the LRU-churn invariant: freed rows are
    not valid commodities)."""
    dev = DeviceBulkCluster(
        num_machines=4, pus_per_machine=1, slots_per_pu=4, num_jobs=2,
        task_capacity=128, num_groups=8, supersteps=1 << 12,
    )
    dev.set_arrival_groups([2, 5])
    dev.add_tasks(4, np.zeros(4, np.int32), groups=np.full(4, 2, np.int32))
    stats = dev.fetch_stats(
        dev.run_steady_rounds(6, churn_prob=0.2, arrivals=4, seed=3)
    )
    assert stats["converged"].all()
    assert int(stats["admitted"].sum()) > 0  # the map was exercised
    st = {k: np.asarray(v) for k, v in dev.fetch_state().items()}
    assert set(st["grp"][st["live"]].tolist()) <= {2, 5}
    with pytest.raises(ValueError):
        dev.set_arrival_groups([99])


def test_per_job_unsched_equal_costs_stays_degenerate():
    """Equal per-job costs must collapse to the closed form (no
    iterations) — the group expansion alone must not force the
    iterative solve."""
    dev = DeviceBulkCluster(
        num_machines=4, pus_per_machine=1, slots_per_pu=2, num_jobs=3,
        task_capacity=32, job_unsched_cost=np.array([5, 5, 5]),
    )
    assert dev.class_degenerate and dev.supersteps == 1
    dev.add_tasks(6, np.array([0, 1, 2, 0, 1, 2], np.int32))
    stats = dev.fetch_stats(dev.round())
    assert bool(stats["converged"])
    assert int(stats["supersteps"]) == 0  # closed form, no iterations
    assert int(stats["placed"]) == 6
