"""The fused Pallas transport kernel (ops/transport_pallas.py) must be a
bit-exact twin of the XLA phase loop (solver/layered.py _transport_loop):
both run the same synchronous integer push-relabel schedule, so the
resulting flows — not just objectives — are identical. Tests run the
kernel under the Pallas interpreter (CPU env); the TPU-compiled path is
the same kernel code, exercised by bench.py on hardware.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from ksched_tpu.ops import get_pallas_mode, set_pallas_mode, transport_loop_pallas
from ksched_tpu.scheduler.bulk import BulkCluster
from ksched_tpu.scheduler.device_bulk import DeviceBulkCluster
from ksched_tpu.solver.cpu_ref import ReferenceSolver
from ksched_tpu.solver.layered import (
    LayeredProblem,
    LayeredTransportSolver,
    _transport_loop,
    pad_geometry,
)


@pytest.fixture
def pallas_interpret():
    prev = get_pallas_mode()
    set_pallas_mode("interpret")
    yield
    set_pallas_mode(prev)


def _random_instance(seed, C, M):
    """A padded transport instance in the exact form the bulk scheduler
    emits: scaled costs with a zero-cost unsched column of capacity
    sum(supply)."""
    rng = np.random.default_rng(seed)
    Mp, n_scale = pad_geometry(M, C)
    w = rng.integers(-30, 30, (C, M)).astype(np.int64)
    wS = np.zeros((C, Mp), np.int32)
    wS[:, :M] = w * n_scale
    supply = rng.integers(0, 60, C).astype(np.int32)
    col_cap = np.zeros(Mp, np.int32)
    col_cap[:M] = rng.integers(0, 25, M).astype(np.int32)
    col_cap[-1] = supply.sum()
    return wS, supply, col_cap, n_scale


@pytest.mark.parametrize("seed", range(8))
@pytest.mark.parametrize("C,M", [(2, 5), (3, 40), (5, 130), (8, 250)])
def test_kernel_flow_identical_to_xla_loop(seed, C, M):
    wS, supply, col_cap, n_scale = _random_instance(seed, C, M)
    eps0 = np.int32(max(1, np.abs(wS).max()))
    U = jnp.minimum(jnp.asarray(supply)[:, None], jnp.asarray(col_cap)[None, :])
    y_xla, _z, pm_xla, steps_xla, conv_xla = _transport_loop(
        jnp.asarray(wS), U, jnp.asarray(supply), jnp.asarray(col_cap),
        jnp.asarray(eps0), 8, 20_000,
    )
    y_pl, pm_pl, steps_pl, conv_pl = transport_loop_pallas(
        jnp.asarray(wS), jnp.asarray(supply), jnp.asarray(col_cap),
        jnp.asarray(eps0), alpha=8, max_supersteps=20_000, interpret=True,
    )
    assert bool(conv_xla) and bool(conv_pl)
    assert int(steps_xla) == int(steps_pl)
    np.testing.assert_array_equal(np.asarray(y_xla), np.asarray(y_pl))
    np.testing.assert_array_equal(np.asarray(pm_xla), np.asarray(pm_pl))


@pytest.mark.parametrize("seed", range(6))
@pytest.mark.parametrize("C,M", [(2, 5), (4, 40), (6, 130)])
def test_tiered_kernel_identical_to_xla_tiered_loop(seed, C, M):
    """The fused TIERED kernel (preemption pricing: residents at
    wLo = w - discount, the rest at wHi) must match the XLA tiered
    phase loop bit-for-bit — flows, prices, and superstep counts —
    with and without price refinement."""
    from ksched_tpu.ops import transport_loop_pallas_tiered
    from ksched_tpu.solver.layered import _transport_loop_tiered

    wS, supply, col_cap, n_scale = _random_instance(seed, C, M)
    rng = np.random.default_rng(seed + 77)
    discount = int(rng.integers(1, 12)) * n_scale
    wHi = wS
    wLo = wS.copy()
    wLo[:, :M] -= discount
    # resident census: scattered residents under the cell capacities
    R = rng.integers(0, 6, (C, wS.shape[1])).astype(np.int32)
    R[:, -1] = 0
    eps0 = np.int32(max(1, np.abs(wHi).max()))
    RJ = jnp.minimum(
        jnp.asarray(R),
        jnp.minimum(jnp.asarray(supply)[:, None], jnp.asarray(col_cap)[None, :]),
    )
    U = jnp.minimum(jnp.asarray(supply)[:, None], jnp.asarray(col_cap)[None, :])
    for refine in (0, 8):
        y_xla, _z, pm_xla, steps_xla, conv_xla = _transport_loop_tiered(
            jnp.asarray(wLo), jnp.asarray(wHi), RJ, U,
            jnp.asarray(supply), jnp.asarray(col_cap),
            jnp.asarray(eps0), 8, 50_000, refine_waves=refine,
        )
        y_pl, pm_pl, steps_pl, conv_pl = transport_loop_pallas_tiered(
            jnp.asarray(wLo), jnp.asarray(wHi), jnp.asarray(R),
            jnp.asarray(supply), jnp.asarray(col_cap), jnp.asarray(eps0),
            alpha=8, max_supersteps=50_000, interpret=True,
            refine_waves=refine,
        )
        assert bool(conv_xla) and bool(conv_pl), refine
        assert int(steps_xla) == int(steps_pl), refine
        np.testing.assert_array_equal(np.asarray(y_xla), np.asarray(y_pl))
        np.testing.assert_array_equal(np.asarray(pm_xla), np.asarray(pm_pl))


@pytest.mark.parametrize("seed", range(4))
def test_warm_start_stays_exact(seed):
    """Re-solving a perturbed instance from the previous solve's machine
    prices must stay exactly optimal (same objective as cold). No
    superstep-count guarantee exists — warm prices can be slower (they
    flatten reduced costs; see scheduler/device_bulk.py), which is why
    production solves are cold — but correctness must never depend on
    the start point."""
    C, M = 4, 60
    wS, supply, col_cap, n_scale = _random_instance(seed, C, M)
    eps0 = jnp.asarray(np.int32(n_scale))
    a = (jnp.asarray(wS), jnp.asarray(supply), jnp.asarray(col_cap))
    y0, pm0, s0, c0 = transport_loop_pallas(
        *a, eps0, alpha=8, max_supersteps=50_000, interpret=True
    )
    assert bool(c0)
    # perturb: a few tasks of each class finish, a few arrive
    rng = np.random.default_rng(seed + 100)
    supply2 = np.maximum(0, supply + rng.integers(-3, 4, C)).astype(np.int32)
    cap2 = col_cap.copy()
    cap2[-1] = supply2.sum()
    a2 = (jnp.asarray(wS), jnp.asarray(supply2), jnp.asarray(cap2))
    y_cold, _pm, s_cold, c_cold = transport_loop_pallas(
        *a2, eps0, alpha=8, max_supersteps=50_000, interpret=True
    )
    y_warm, _pm2, s_warm, c_warm = transport_loop_pallas(
        *a2, eps0, pm0, alpha=8, max_supersteps=50_000, interpret=True
    )
    assert bool(c_cold) and bool(c_warm)
    w = wS.astype(np.int64)
    obj_cold = int((np.asarray(y_cold) * w).sum())
    obj_warm = int((np.asarray(y_warm) * w).sum())
    assert obj_warm == obj_cold  # warm start never sacrifices optimality


@pytest.mark.parametrize("seed", [0, 3])
def test_layered_solver_via_pallas_matches_oracle(seed, pallas_interpret):
    """End-to-end through LayeredTransportSolver: objective parity with
    the exact SSP oracle on the full flow graph."""
    rng = np.random.default_rng(seed)
    C, M = 3, 12
    cost = rng.integers(0, 20, (C, M)).astype(np.int32)
    solver = LayeredTransportSolver()
    cluster = BulkCluster(
        num_machines=M,
        pus_per_machine=2,
        slots_per_pu=2,
        num_jobs=3,
        backend=solver,
        task_capacity=256,
        num_task_classes=C,
        class_cost_fn=lambda cl: cost,
        unsched_cost=25,
    )
    n = int(rng.integers(40, 120))
    cluster.add_tasks(
        n,
        rng.integers(0, 3, n).astype(np.int32),
        rng.integers(0, C, n).astype(np.int32),
    )
    cluster._refresh_capacities()
    want = ReferenceSolver().solve(cluster._problem()).objective

    unplaced = np.nonzero(cluster.task_live & (cluster.task_pu < 0))[0]
    supply = np.bincount(cluster.task_class[unplaced], minlength=C).astype(np.int32)
    pu_free = cluster.S - cluster.pu_running
    machine_free = pu_free.reshape(cluster.M, cluster.P).sum(axis=1)
    res = solver.solve_layered(
        LayeredProblem(
            supply=supply,
            col_cap=machine_free.astype(np.int32),
            cost_cm=cost,
            unsched_cost=cluster.unsched_cost,
            ec_cost=cluster.ec_cost,
        )
    )
    assert res.objective == want


def test_device_bulk_rounds_same_with_and_without_pallas():
    """A multi-class device cluster run (round + churn rounds) must
    produce identical stats under pallas and XLA dispatch."""
    def run():
        rng = np.random.default_rng(0)
        cost = np.asarray([[0, 4, 9], [9, 4, 0]], np.int32)
        dev = DeviceBulkCluster(
            num_machines=3,
            pus_per_machine=2,
            slots_per_pu=2,
            num_jobs=2,
            num_task_classes=2,
            task_capacity=64,
            class_cost_fn=lambda census: jnp.asarray(cost),
        )
        dev.add_tasks(
            20,
            rng.integers(0, 2, 20).astype(np.int32),
            rng.integers(0, 2, 20).astype(np.int32),
        )
        r = dev.fetch_stats(dev.round())
        s = dev.fetch_stats(dev.run_steady_rounds(4, 0.2, 2, seed=5))
        return r, s

    prev = get_pallas_mode()
    try:
        set_pallas_mode("off")
        r_x, s_x = run()
        set_pallas_mode("interpret")
        r_p, s_p = run()
    finally:
        set_pallas_mode(prev)
    for k in r_x:
        np.testing.assert_array_equal(r_x[k], r_p[k], err_msg=f"round stat {k}")
    for k in s_x:
        np.testing.assert_array_equal(s_x[k], s_p[k], err_msg=f"steady stat {k}")
