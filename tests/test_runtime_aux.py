"""Aux-subsystem tests: heartbeat failure detection, checkpoint/resume
(event path and array path), and round tracing."""

import json

import numpy as np
import pytest

from ksched_tpu.data import ResourceState, TaskState
from ksched_tpu.drivers import add_job, build_cluster
from ksched_tpu.runtime import (
    HeartbeatMonitor,
    RoundTracer,
    load_bulk_checkpoint,
    restore_scheduler,
    save_bulk_checkpoint,
    save_scheduler,
)
from ksched_tpu.utils import resource_id_from_string

# -- failure detection ----------------------------------------------------


def _machine_rids(sched, rmap):
    return [
        rid for rid, rs in rmap.items() if rs.descriptor.type.name == "MACHINE"
    ]


def test_machine_loss_detected_and_tasks_requeued():
    sched, rmap, jmap, tmap, root = build_cluster(
        num_machines=2, pus_per_core=2, max_tasks_per_pu=1
    )
    add_job(sched, jmap, tmap, num_tasks=4)
    n, _ = sched.schedule_all_jobs()
    assert n == 4
    mon = HeartbeatMonitor(sched, machine_timeout_s=10.0, clock=lambda: 0.0)
    machines = _machine_rids(sched, rmap)
    for m in machines:
        mon.record_machine_heartbeat(m, now=100.0)
    # machine 0 goes silent; machine 1 keeps beating
    mon.record_machine_heartbeat(machines[1], now=130.0)
    lost, failed = mon.check(now=130.0)
    assert lost == [machines[0]]
    assert rmap.find(machines[0]) is None  # pruned from the map
    # its two tasks are runnable again and the other machine still holds 2
    assert len(sched.get_task_bindings()) == 2
    # next round can replace nothing (machine 1 full) but supply conserved
    assert sched.gm.sink_node.excess == -len(sched.gm.task_to_node)


def test_task_silence_fails_task():
    sched, rmap, jmap, tmap, root = build_cluster(num_machines=1, pus_per_core=2)
    add_job(sched, jmap, tmap, num_tasks=2)
    sched.schedule_all_jobs()
    mon = HeartbeatMonitor(sched, task_timeout_s=5.0, clock=lambda: 0.0)
    bound = list(sched.get_task_bindings().keys())
    mon.record_task_heartbeat(bound[0], now=100.0)
    mon.record_task_heartbeat(bound[1], now=109.0)
    lost, failed = mon.check(now=110.0)
    assert failed == [bound[0]]
    assert tmap.find(bound[0]).state == TaskState.FAILED
    assert bound[0] not in sched.get_task_bindings()
    assert bound[1] in sched.get_task_bindings()


def test_never_heartbeated_entities_not_monitored():
    sched, rmap, jmap, tmap, root = build_cluster(num_machines=1)
    mon = HeartbeatMonitor(sched, clock=lambda: 1e9)
    lost, failed = mon.check()
    assert lost == [] and failed == []


# -- checkpoint / resume (event path) -------------------------------------


def test_scheduler_checkpoint_roundtrip(tmp_path):
    sched, rmap, jmap, tmap, root = build_cluster(
        num_machines=3, pus_per_core=2, max_tasks_per_pu=1
    )
    add_job(sched, jmap, tmap, num_tasks=4)
    n, _ = sched.schedule_all_jobs()
    assert n == 4
    before = dict(sched.get_task_bindings())

    path = tmp_path / "sched.ckpt"
    save_scheduler(sched, str(path))
    sched2, rmap2, jmap2, tmap2 = restore_scheduler(str(path))

    assert dict(sched2.get_task_bindings()) == before
    # restored tasks are RUNNING and bound resources BUSY
    for tid, rid in before.items():
        assert tmap2.find(tid).state == TaskState.RUNNING
        assert rmap2.find(rid).descriptor.state == ResourceState.BUSY
    # supply invariant holds in the restored graph
    assert sched2.gm.sink_node.excess == -len(sched2.gm.task_to_node)
    # the restored scheduler keeps scheduling: new job lands on free slots
    add_job(sched2, jmap2, tmap2, num_tasks=2)
    n2, _ = sched2.schedule_all_jobs()
    assert n2 == 2


def test_scheduler_checkpoint_preserves_unscheduled_backlog(tmp_path):
    sched, rmap, jmap, tmap, root = build_cluster(num_machines=1, max_tasks_per_pu=1)
    add_job(sched, jmap, tmap, num_tasks=3)  # 1 slot, 3 tasks
    n, _ = sched.schedule_all_jobs()
    assert n == 1
    save_scheduler(sched, str(tmp_path / "s.ckpt"))
    sched2, rmap2, jmap2, tmap2 = restore_scheduler(str(tmp_path / "s.ckpt"))
    assert len(sched2.get_task_bindings()) == 1
    # backlog survives: nothing placed (cluster full), but both runnable
    assert sched2.gm.sink_node.excess == -len(sched2.gm.task_to_node)


# -- checkpoint / resume (array path) -------------------------------------


def test_bulk_checkpoint_roundtrip(tmp_path):
    from ksched_tpu.scheduler.bulk import BulkCluster
    from ksched_tpu.solver.native import NativeSolver

    c = BulkCluster(num_machines=4, pus_per_machine=2, slots_per_pu=2,
                    num_jobs=2, backend=NativeSolver(), task_capacity=64)
    rng = np.random.default_rng(0)
    c.add_tasks(10, rng.integers(0, 2, 10).astype(np.int32))
    c.round()
    placed_before = c.num_placed_tasks

    path = str(tmp_path / "bulk.npz")
    save_bulk_checkpoint(c, path)
    c2 = load_bulk_checkpoint(path, backend=NativeSolver())
    assert c2.num_live_tasks == c.num_live_tasks
    assert c2.num_placed_tasks == placed_before
    assert (c2.task_pu == c.task_pu).all()
    # resumed cluster schedules on: add more tasks and run a round
    c2.add_tasks(6, rng.integers(0, 2, 6).astype(np.int32))
    r = c2.round()
    assert len(r.placed_tasks) == 6
    assert c2.num_placed_tasks == placed_before + 6


# -- checkpoint / resume (device path) ------------------------------------


def _device_state_equal(a, b):
    return all(np.array_equal(np.asarray(a[k]), np.asarray(b[k])) for k in a)


def test_device_checkpoint_roundtrip_group_mode(tmp_path):
    """A group-mode DeviceBulkCluster (the production Quincy path)
    survives a restart: restored state is bit-identical, and the
    restored cluster continues under churn in lockstep with the
    original (same placements, same stats, same final state)."""
    from ksched_tpu.costmodels.quincy_device import QuincyGroupTable
    from ksched_tpu.runtime import load_device_checkpoint, save_device_checkpoint
    from ksched_tpu.scheduler.device_bulk import DeviceBulkCluster

    MB = 1 << 20
    G, M = 16, 8
    dev = DeviceBulkCluster(
        num_machines=M, pus_per_machine=2, slots_per_pu=2, num_jobs=2,
        task_capacity=128, num_groups=G, supersteps=1 << 14,
    )
    table = QuincyGroupTable(num_groups=G, num_machines=M)
    rng = np.random.default_rng(5)
    for b in range(1, 9):
        table.blocks.register(
            b, 64 * MB, rng.choice(M, size=2, replace=False).tolist()
        )
    blocks = rng.integers(1, 9, 40)
    groups = table.groups_for(
        np.zeros(40, np.int32), [[int(b)] for b in blocks]
    )
    table.sync(dev)
    dev.add_tasks(40, rng.integers(0, 2, 40).astype(np.int32), groups=groups)
    s = dev.fetch_stats(dev.round())
    assert bool(s["converged"])

    path = str(tmp_path / "dev.npz")
    save_device_checkpoint(dev, path)
    dev2 = load_device_checkpoint(path)

    assert _device_state_equal(dev.fetch_state(), dev2.fetch_state())
    # restart-under-churn parity: identical ops on both clusters from
    # here on must produce identical rounds and identical final state
    rng_ops = np.random.default_rng(11)
    for _ in range(3):
        st = dev.fetch_state()
        placed = np.nonzero(np.asarray(st["live"]) & (np.asarray(st["pu"]) >= 0))[0]
        done = rng_ops.choice(placed, size=min(5, len(placed)), replace=False)
        nb = rng_ops.integers(1, 9, 4)
        ng = table.groups_for(np.zeros(4, np.int32), [[int(b)] for b in nb])
        nj = rng_ops.integers(0, 2, 4).astype(np.int32)
        for d in (dev, dev2):
            table.sync(d)
            d.complete_tasks(done.astype(np.int32))
            d.add_tasks(4, nj, groups=ng)
            d.round()
        sa = dev.fetch_stats()
        sb = dev2.fetch_stats()
        assert int(sa["placed"]) == int(sb["placed"])
        assert int(sa["unscheduled"]) == int(sb["unscheduled"])
    assert _device_state_equal(dev.fetch_state(), dev2.fetch_state())


def test_device_checkpoint_roundtrip_preemption(tmp_path):
    """Preemption mode: residency (continuation pricing) is part of the
    state; the restored cluster must keep preempting identically."""
    import jax.numpy as jnp

    from ksched_tpu.runtime import load_device_checkpoint, save_device_checkpoint
    from ksched_tpu.scheduler.device_bulk import DeviceBulkCluster

    cost = np.random.default_rng(2).integers(0, 12, (2, 6)).astype(np.int32)
    cost_d = jnp.asarray(cost)

    def cost_fn(census):
        return cost_d

    def make():
        return DeviceBulkCluster(
            num_machines=6, pus_per_machine=1, slots_per_pu=2, num_jobs=2,
            num_task_classes=2, task_capacity=64, class_cost_fn=cost_fn,
            preemption=True, continuation_discount=2, supersteps=1 << 14,
        )

    dev = make()
    rng = np.random.default_rng(0)
    dev.add_tasks(10, rng.integers(0, 2, 10).astype(np.int32),
                  rng.integers(0, 2, 10).astype(np.int32))
    s = dev.fetch_stats(dev.round())
    assert bool(s["converged"])

    path = str(tmp_path / "devp.npz")
    save_device_checkpoint(dev, path)
    dev2 = load_device_checkpoint(path, class_cost_fn=cost_fn)
    assert dev2.preemption and dev2.continuation_discount == 2
    assert _device_state_equal(dev.fetch_state(), dev2.fetch_state())

    rng_ops = np.random.default_rng(3)
    for _ in range(3):
        nj = rng_ops.integers(0, 2, 3).astype(np.int32)
        nc = rng_ops.integers(0, 2, 3).astype(np.int32)
        for d in (dev, dev2):
            d.add_tasks(3, nj, nc)
            d.round()
        sa, sb = dev.fetch_stats(), dev2.fetch_stats()
        assert int(sa["placed"]) == int(sb["placed"])
        assert int(sa["preempted"]) == int(sb["preempted"])
    assert _device_state_equal(dev.fetch_state(), dev2.fetch_state())


# -- tracing ---------------------------------------------------------------


def test_tracer_records_flow_rounds(tmp_path):
    sched, rmap, jmap, tmap, root = build_cluster(num_machines=2, pus_per_core=2)
    tracer = RoundTracer()
    for k in range(3):
        add_job(sched, jmap, tmap, num_tasks=1)
        n, _ = sched.schedule_all_jobs()
        tracer.record_flow_round(sched, n)
    assert len(tracer.records) == 3
    s = tracer.summary("total")
    assert s["rounds"] == 3 and s["p50_ms"] > 0
    p = tmp_path / "trace.jsonl"
    tracer.dump(str(p))
    lines = [json.loads(line) for line in p.read_text().splitlines()]
    assert len(lines) == 3
    assert lines[0]["phases_ms"]["solve"] >= 0
    assert lines[0]["num_scheduled"] == 1
    # the round's mutation counts are observable (stats reset at round
    # START, not after — a post-round reset would zero these)
    assert lines[0]["nodes_added"] > 0 and lines[0]["arcs_added"] > 0


def test_tracer_records_bulk_rounds():
    from ksched_tpu.scheduler.bulk import BulkCluster
    from ksched_tpu.solver.native import NativeSolver

    c = BulkCluster(num_machines=2, pus_per_machine=1, slots_per_pu=2,
                    num_jobs=1, backend=NativeSolver(), task_capacity=16)
    tracer = RoundTracer(capacity=2)
    for _ in range(3):
        c.add_tasks(1, np.zeros(1, np.int32))
        tracer.record_bulk_round(c, c.round())
    assert len(tracer.records) == 2  # ring capacity
    assert tracer.records[-1].phases_ms["solve"] >= 0
