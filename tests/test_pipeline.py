"""Pipelined graph-path rounds: solve dispatch overlapping host build.

The reference's daemon-mode solver (placement/solver.go:60-90) crunches
DIMACS in a subprocess while the Go process is free; the TPU rebuild
gets the same overlap from asynchronous dispatch:
schedule_all_jobs_async() exports the journal snapshot and dispatches
the device solve, the host keeps ingesting ARRIVALS (their mutations
journal for the next round — the reference's pod-batching pattern), and
finish_scheduling() synchronizes, decodes, and applies deltas.
"""

import numpy as np
import pytest

from ksched_tpu.drivers import add_job, build_cluster
from ksched_tpu.solver.jax_solver import JaxSolver
from ksched_tpu.utils import seed_rng


def _cluster(backend=None):
    seed_rng(7)
    return build_cluster(
        num_machines=3, num_cores=1, pus_per_core=2, max_tasks_per_pu=1,
        backend=backend,
    )


@pytest.mark.parametrize("backend_factory", [None, JaxSolver])
def test_pipelined_round_matches_sync(backend_factory):
    """Round-for-round parity: async dispatch + finish produces the
    same bindings as the synchronous path on the same scenario."""
    outs = []
    for mode in ("sync", "async"):
        backend = backend_factory() if backend_factory else None
        sched, rmap, jmap, tmap, root = _cluster(backend)
        add_job(sched, jmap, tmap, num_tasks=4)
        if mode == "sync":
            n1, _ = sched.schedule_all_jobs()
        else:
            token = sched.schedule_all_jobs_async()
            assert token is not None
            n1, _ = sched.finish_scheduling()
        add_job(sched, jmap, tmap, num_tasks=3)
        if mode == "sync":
            n2, _ = sched.schedule_all_jobs()
        else:
            token = sched.schedule_all_jobs_async()
            n2, _ = sched.finish_scheduling()
        outs.append((n1, n2, len(sched.get_task_bindings())))
    assert outs[0] == outs[1], outs


def test_arrivals_overlap_in_flight_round():
    """Jobs added while a round is in flight are NOT placed by it (the
    solve works on the dispatched snapshot) but are picked up by the
    next round — the batching semantics of the reference's pod loop."""
    sched, rmap, jmap, tmap, root = _cluster()
    add_job(sched, jmap, tmap, num_tasks=2)
    token = sched.schedule_all_jobs_async()
    # overlap: a new job arrives while the solve is in flight
    add_job(sched, jmap, tmap, num_tasks=2)
    n1, _ = sched.finish_scheduling()
    assert n1 == 2  # only the snapshot's tasks
    n2, _ = sched.schedule_all_jobs()
    assert n2 == 2  # the overlapped arrivals place next round
    assert len(sched.get_task_bindings()) == 4


def test_mutating_events_fenced_while_in_flight():
    sched, rmap, jmap, tmap, root = _cluster()
    job = add_job(sched, jmap, tmap, num_tasks=2)
    n, _ = sched.schedule_all_jobs()
    assert n == 2
    add_job(sched, jmap, tmap, num_tasks=1)
    token = sched.schedule_all_jobs_async()
    (tid, td) = next(iter(
        (t, d) for t, d in tmap.items() if d.job_id == str(job)
    ))
    with pytest.raises(RuntimeError, match="in flight"):
        sched.handle_task_completion(td)
    with pytest.raises(RuntimeError, match="in flight"):
        sched.schedule_jobs([])
    sched.finish_scheduling()
    # after the round closes, the event proceeds normally
    sched.handle_task_completion(td)


def test_async_empty_round_returns_none():
    sched, rmap, jmap, tmap, root = _cluster()
    assert sched.schedule_all_jobs_async() is None
    with pytest.raises(RuntimeError, match="no scheduling round"):
        sched.finish_scheduling()


def test_placement_and_migration_fenced_while_in_flight():
    """The extended in-flight guard: external placement/migration
    events raise while a pipelined round is in flight (the dispatched
    snapshot still maps those tasks); delta application still works
    because it runs after the latch clears."""
    sched, rmap, jmap, tmap, root = _cluster()
    add_job(sched, jmap, tmap, num_tasks=2)
    n, _ = sched.schedule_all_jobs()
    assert n == 2
    add_job(sched, jmap, tmap, num_tasks=1)
    sched.schedule_all_jobs_async()
    tid, rid = next(iter(sched.task_bindings.items()))
    td = tmap.find(tid)
    rs = rmap.find(rid)
    with pytest.raises(RuntimeError, match="in flight"):
        sched.handle_task_migration(td, rs.descriptor)
    with pytest.raises(RuntimeError, match="in flight"):
        sched.handle_task_placement(td, rs.descriptor)
    sched.finish_scheduling()


# ---------------------------------------------------------------------------
# Device-resident rounds (graph/device_export.DeviceResidentState)
# ---------------------------------------------------------------------------


def _churn_rounds(sched, jmap, tmap, job_id, rounds, k=2, seed=11):
    """Deterministic churn driver: complete k bound tasks + add k new
    ones per round; yields after each schedule."""
    from ksched_tpu.drivers.synthetic import add_task_to_job

    rng = np.random.default_rng(seed)
    for _ in range(rounds):
        bound = sorted(sched.task_bindings.items())
        if len(bound) >= k:
            for i in sorted(
                (int(x) for x in rng.choice(len(bound), k, replace=False)),
                reverse=True,
            ):
                sched.handle_task_completion(tmap.find(bound[i][0]))
        for _ in range(k):
            add_task_to_job(job_id, jmap, tmap)
        sched.add_job(jmap.find(job_id))
        sched.schedule_all_jobs()
        yield


def test_device_resident_rounds_match_host_rounds():
    """The tentpole parity claim at unit scale: a device-resident
    scheduler (persistent buffers + delta-record scatter + device-
    carried warm flow) decodes bit-identical bindings to the host
    export path, round for round, under churn."""
    from ksched_tpu.scheduler.flow_scheduler import FlowScheduler  # noqa: F401

    snaps = {}
    for resident in (False, True):
        seed_rng(7)
        sched, rmap, jmap, tmap, root = build_cluster(
            num_machines=4, num_cores=1, pus_per_core=2, max_tasks_per_pu=2,
            backend=JaxSolver(),
        )
        sched.solver.device_resident = resident
        if resident:
            from ksched_tpu.graph.device_export import DeviceResidentState

            sched.solver.resident = DeviceResidentState(sched.solver.state)
        job_id = add_job(sched, jmap, tmap, num_tasks=10)
        sched.schedule_all_jobs()
        hist = [dict(sched.task_bindings)]
        for _ in _churn_rounds(sched, jmap, tmap, job_id, rounds=6):
            hist.append({tmap.find(t).name: r for t, r in sched.task_bindings.items()})
        snaps[resident] = hist[1:]
        if resident:
            # the mirror itself must equal the host folded arrays
            sched.solver.resident.parity_check()
            assert sched.solver.resident.last_upload_kind == "delta"
    assert snaps[False] == snaps[True]


def test_resident_delta_bytes_track_churn_not_graph():
    """After the initial full upload, refreshes ship packed records
    sized by the round's dirty slots/nodes — not the padded arrays."""
    from ksched_tpu.graph.device_export import DeviceResidentState
    from ksched_tpu.obs.devprof import problem_nbytes
    from ksched_tpu.solver.cpu_ref import ReferenceSolver

    seed_rng(3)
    sched, rmap, jmap, tmap, root = build_cluster(
        num_machines=4, num_cores=1, pus_per_core=2, max_tasks_per_pu=2,
        backend=ReferenceSolver(),
    )
    sched.solver.device_resident = True
    sched.solver.resident = DeviceResidentState(sched.solver.state)
    job_id = add_job(sched, jmap, tmap, num_tasks=10)
    sched.schedule_all_jobs()
    res = sched.solver.resident
    assert res.last_upload_kind == "full_build"
    full_bytes = problem_nbytes(sched.solver.state.problem())
    deltas = []
    for _ in _churn_rounds(sched, jmap, tmap, job_id, rounds=5):
        if res.last_upload_kind == "delta":
            deltas.append(res.last_upload_bytes)
        res.parity_check()
    assert len(deltas) >= 2, "no steady delta refreshes in 5 churn rounds"
    # steady-state records are churn-sized; the FIRST churn round also
    # carries the fill round's post-solve mutations (and at this toy
    # scale the pow2 record padding), so judge the steady tail
    assert max(deltas[1:]) < full_bytes / 2, (deltas, full_bytes)


def test_problem_cache_reuses_and_isolates():
    """Satellite: problem() returns the cached object when nothing was
    journaled since the last materialize; a later mutation builds NEW
    arrays instead of touching the snapshot a solver may still hold."""
    from ksched_tpu.solver.cpu_ref import ReferenceSolver

    seed_rng(5)
    sched, rmap, jmap, tmap, root = build_cluster(
        num_machines=2, num_cores=1, pus_per_core=2, max_tasks_per_pu=1,
        backend=ReferenceSolver(),
    )
    add_job(sched, jmap, tmap, num_tasks=2)
    sched.schedule_all_jobs()
    state = sched.solver.state
    p1 = state.problem()
    assert state.problem() is p1  # clean: cached object comes back
    snap_excess = p1.excess.copy()
    snap_cap = p1.cap.copy()
    # mutate the sink excess through the tracked path
    state.set_excess(1, int(state.excess[1]) + 5)
    p2 = state.problem()
    assert p2 is not p1
    # the old snapshot is untouched (solvers may still hold it)...
    assert np.array_equal(p1.excess, snap_excess)
    assert np.array_equal(p1.cap, snap_cap)
    # ...and clean groups are shared, dirty groups rebuilt
    assert p2.cap is p1.cap
    assert p2.excess is not p1.excess
    state.set_excess(1, int(snap_excess[1]))  # restore


def test_device_warm_flow_matches_host_mask():
    """The device warm-flow program is bit-identical to the host
    mask: keep flow where endpoints are unchanged, clipped to the new
    cap; zero where they changed."""
    from ksched_tpu.graph.device_export import device_warm_flow_fn

    rng = np.random.default_rng(0)
    m = 64
    src0 = rng.integers(1, 9, m).astype(np.int32)
    dst0 = rng.integers(1, 9, m).astype(np.int32)
    src1 = src0.copy()
    dst1 = dst0.copy()
    moved = rng.random(m) < 0.3
    src1[moved] = rng.integers(1, 9, int(moved.sum())).astype(np.int32)
    prev = rng.integers(0, 10, m).astype(np.int32)
    cap = rng.integers(0, 6, m).astype(np.int32)
    got = np.asarray(device_warm_flow_fn()(prev, src0, dst0, src1, dst1, cap))
    same = (src0 == src1) & (dst0 == dst1)
    want = np.where(same, np.minimum(prev, cap), 0).astype(np.int32)
    assert np.array_equal(got, want)


def test_restart_budget_same_objectives_fewer_wasted_steps():
    """The budgeted warm attempt escapes a price-war round to a fresh
    restart — every solve still lands on an EXACT optimum (objectives
    match the unbudgeted solver's round for round)."""
    objs = {}
    for budget in (None, 8):
        seed_rng(7)
        solver = JaxSolver(restart_budget=budget)
        sched, rmap, jmap, tmap, root = build_cluster(
            num_machines=4, num_cores=1, pus_per_core=2, max_tasks_per_pu=2,
            backend=solver,
        )
        job_id = add_job(sched, jmap, tmap, num_tasks=10)
        sched.schedule_all_jobs()
        seq = []
        for _ in _churn_rounds(sched, jmap, tmap, job_id, rounds=5):
            seq.append(sched.solver.last_result.objective)
        objs[budget] = seq
    assert objs[None] == objs[8], objs


# ---------------------------------------------------------------------------
# The double-buffered service loop (cli.SchedulerService pipeline mode)
# ---------------------------------------------------------------------------


def _service(pipeline, device_resident=False, backend_name="jax"):
    from ksched_tpu.cli import SchedulerService
    from ksched_tpu.cluster import SyntheticClusterAPI
    from ksched_tpu.solver.select import make_backend

    seed_rng(9)
    api = SyntheticClusterAPI()
    svc = SchedulerService(
        api,
        max_tasks_per_pu=2,
        backend=make_backend(backend_name),
        backend_name=backend_name,
        pipeline=pipeline,
        device_resident=device_resident,
    )
    svc.init_topology(fake_machines=3, pus_per_core=2)
    return svc, api


def test_pipelined_service_defers_posts_to_next_dispatch_window():
    from ksched_tpu.cluster import PodEvent

    svc, api = _service(pipeline=True)
    bound = svc.run_round([PodEvent(pod_id=f"p{i}") for i in range(4)])
    assert bound == 4
    # scheduler state is complete, but the POSTs ride the NEXT window
    assert len(svc.scheduler.task_bindings) == 4
    assert len(api.bindings()) == 0
    assert len(svc._pending_bindings) == 4
    # next round's dispatch window flushes them
    svc.run_round([PodEvent(pod_id="p4")])
    assert len(api.bindings()) == 4
    # an explicit flush drains the rest (loop exit / checkpoint path)
    svc.flush_pending_bindings()
    assert len(api.bindings()) == 5


def test_idle_sweep_flushes_stranded_posts():
    """A quiet pod channel must not strand the last active round's
    deferred POSTs: the idle sweep (run_round with solve=False) is a
    flush point, so pods bind on the control plane even when no new
    pod ever arrives."""
    from ksched_tpu.cluster import PodEvent
    from ksched_tpu.runtime.trace import RoundTracer

    svc, api = _service(pipeline=True)
    svc.tracer = RoundTracer()
    svc.run_round([PodEvent(pod_id=f"p{i}") for i in range(3)])
    assert len(api.bindings()) == 0 and len(svc._pending_bindings) == 3
    svc.run_round([], solve=False)  # the quiet-channel idle sweep
    assert len(api.bindings()) == 3
    assert not svc._pending_bindings


def test_service_loop_modes_bit_identical():
    """sync / pipelined / pipelined+device-resident services fed the
    same pod + completion schedule end with identical scheduler
    bindings AND identical API-side bindings after the final flush."""
    from ksched_tpu.cluster import PodEvent

    finals = {}
    for label, pipeline, resident in (
        ("sync", False, False),
        ("pipelined", True, False),
        ("resident", True, True),
    ):
        svc, api = _service(pipeline=pipeline, device_resident=resident)
        seq = 0
        rng = np.random.default_rng(2)
        for r in range(6):
            pods = [PodEvent(pod_id=f"p{seq + i}") for i in range(2)]
            seq += 2
            svc.flush_pending_bindings()  # logical-round driver (see soak)
            svc.run_round(pods)
            bound_pods = sorted(
                p for p, t in svc.pod_to_task.items()
                if t in svc.scheduler.task_bindings
            )
            if len(bound_pods) > 2:
                k = int(rng.integers(1, 3))
                for j in sorted(int(x) for x in rng.choice(len(bound_pods), k, replace=False)):
                    svc.complete_pod(bound_pods[j])
        svc.flush_pending_bindings()
        finals[label] = (
            {svc.task_to_pod[t]: r for t, r in svc.scheduler.task_bindings.items()},
            dict(api.bindings()),
        )
    assert finals["sync"] == finals["pipelined"] == finals["resident"]


def test_ladder_async_rung_failure_degrades_synchronously():
    """A pipelined round whose configured rung fails mid-flight falls
    back to the synchronous ladder path inside complete(): the round
    still produces placements (from a lower rung) and the degradation
    is counted."""
    from ksched_tpu.cluster import PodEvent
    from ksched_tpu.runtime.chaos import ChaosPolicy, FaultInjector

    policy = ChaosPolicy(seed=1, solver_fault_prob=1.0, solver_fault_kinds=("nonconverge",))
    injector = FaultInjector(policy)
    from ksched_tpu.cli import SchedulerService
    from ksched_tpu.cluster import SyntheticClusterAPI
    from ksched_tpu.solver.select import make_backend

    seed_rng(9)
    api = SyntheticClusterAPI()
    svc = SchedulerService(
        api,
        max_tasks_per_pu=2,
        backend=make_backend("jax"),
        backend_name="jax",
        injector=injector,
        pipeline=True,
    )
    svc.init_topology(fake_machines=2, pus_per_core=2)
    injector.begin_round(0)
    bound = svc.run_round([PodEvent(pod_id="p0"), PodEvent(pod_id="p1")])
    assert bound == 2  # the cpu_ref rung still placed the round
    assert svc.ladder is not None and svc.ladder.last_degradations >= 1
    assert svc.ladder.last_rung_name == "cpu_ref"
