"""Pipelined graph-path rounds: solve dispatch overlapping host build.

The reference's daemon-mode solver (placement/solver.go:60-90) crunches
DIMACS in a subprocess while the Go process is free; the TPU rebuild
gets the same overlap from asynchronous dispatch:
schedule_all_jobs_async() exports the journal snapshot and dispatches
the device solve, the host keeps ingesting ARRIVALS (their mutations
journal for the next round — the reference's pod-batching pattern), and
finish_scheduling() synchronizes, decodes, and applies deltas.
"""

import numpy as np
import pytest

from ksched_tpu.drivers import add_job, build_cluster
from ksched_tpu.solver.jax_solver import JaxSolver
from ksched_tpu.utils import seed_rng


def _cluster(backend=None):
    seed_rng(7)
    return build_cluster(
        num_machines=3, num_cores=1, pus_per_core=2, max_tasks_per_pu=1,
        backend=backend,
    )


@pytest.mark.parametrize("backend_factory", [None, JaxSolver])
def test_pipelined_round_matches_sync(backend_factory):
    """Round-for-round parity: async dispatch + finish produces the
    same bindings as the synchronous path on the same scenario."""
    outs = []
    for mode in ("sync", "async"):
        backend = backend_factory() if backend_factory else None
        sched, rmap, jmap, tmap, root = _cluster(backend)
        add_job(sched, jmap, tmap, num_tasks=4)
        if mode == "sync":
            n1, _ = sched.schedule_all_jobs()
        else:
            token = sched.schedule_all_jobs_async()
            assert token is not None
            n1, _ = sched.finish_scheduling()
        add_job(sched, jmap, tmap, num_tasks=3)
        if mode == "sync":
            n2, _ = sched.schedule_all_jobs()
        else:
            token = sched.schedule_all_jobs_async()
            n2, _ = sched.finish_scheduling()
        outs.append((n1, n2, len(sched.get_task_bindings())))
    assert outs[0] == outs[1], outs


def test_arrivals_overlap_in_flight_round():
    """Jobs added while a round is in flight are NOT placed by it (the
    solve works on the dispatched snapshot) but are picked up by the
    next round — the batching semantics of the reference's pod loop."""
    sched, rmap, jmap, tmap, root = _cluster()
    add_job(sched, jmap, tmap, num_tasks=2)
    token = sched.schedule_all_jobs_async()
    # overlap: a new job arrives while the solve is in flight
    add_job(sched, jmap, tmap, num_tasks=2)
    n1, _ = sched.finish_scheduling()
    assert n1 == 2  # only the snapshot's tasks
    n2, _ = sched.schedule_all_jobs()
    assert n2 == 2  # the overlapped arrivals place next round
    assert len(sched.get_task_bindings()) == 4


def test_mutating_events_fenced_while_in_flight():
    sched, rmap, jmap, tmap, root = _cluster()
    job = add_job(sched, jmap, tmap, num_tasks=2)
    n, _ = sched.schedule_all_jobs()
    assert n == 2
    add_job(sched, jmap, tmap, num_tasks=1)
    token = sched.schedule_all_jobs_async()
    (tid, td) = next(iter(
        (t, d) for t, d in tmap.items() if d.job_id == str(job)
    ))
    with pytest.raises(RuntimeError, match="in flight"):
        sched.handle_task_completion(td)
    with pytest.raises(RuntimeError, match="in flight"):
        sched.schedule_jobs([])
    sched.finish_scheduling()
    # after the round closes, the event proceeds normally
    sched.handle_task_completion(td)


def test_async_empty_round_returns_none():
    sched, rmap, jmap, tmap, root = _cluster()
    assert sched.schedule_all_jobs_async() is None
    with pytest.raises(RuntimeError, match="no scheduling round"):
        sched.finish_scheduling()
