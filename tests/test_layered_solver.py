"""The dense layered transport solver (solver/layered.py): exactness
against the SSP oracle, and BulkCluster fast-path equivalence.

The layered solver must produce the SAME objective as the generic MCMF
backends on the aggregate topology (placement parity in the reference's
sense: MCMF has many optima, so parity = equal objective cost —
SURVEY.md §7 "Hard parts").
"""

import numpy as np
import pytest

from ksched_tpu.scheduler.bulk import BulkCluster
from ksched_tpu.solver.cpu_ref import ReferenceSolver
from ksched_tpu.solver.layered import (
    LayeredProblem,
    LayeredTransportSolver,
)


def _objective_via_oracle(cluster: BulkCluster) -> int:
    """Solve the cluster's full FlowProblem with the exact SSP oracle."""
    cluster._refresh_capacities()
    problem = cluster._problem()
    return ReferenceSolver().solve(problem).objective


def _make_cluster(backend, C, M=12, jobs=3, seed=7, unsched_cost=25):
    rng = np.random.default_rng(seed)
    cost = rng.integers(0, 20, (C, M)).astype(np.int32)
    return BulkCluster(
        num_machines=M,
        pus_per_machine=2,
        slots_per_pu=2,
        num_jobs=jobs,
        backend=backend,
        task_capacity=256,
        num_task_classes=C,
        class_cost_fn=lambda cl: cost,
        unsched_cost=unsched_cost,
    )


@pytest.mark.parametrize("seed", range(6))
@pytest.mark.parametrize("C", [1, 3])
def test_layered_objective_matches_oracle(seed, C):
    rng = np.random.default_rng(seed)
    solver = LayeredTransportSolver()
    cluster = _make_cluster(solver, C=C, seed=seed)
    n = int(rng.integers(10, 120))
    cluster.add_tasks(
        n,
        rng.integers(0, 3, n).astype(np.int32),
        rng.integers(0, C, n).astype(np.int32),
    )
    want = _objective_via_oracle(cluster)

    cluster._refresh_capacities()
    unplaced = np.nonzero(cluster.task_live & (cluster.task_pu < 0))[0]
    supply = np.bincount(cluster.task_class[unplaced], minlength=C).astype(np.int32)
    pu_free = cluster.S - cluster.pu_running
    machine_free = pu_free.reshape(cluster.M, cluster.P).sum(axis=1)
    cost_cm = cluster.cost[
        cluster.a_ecm0 : cluster.a_ecm0 + C * cluster.M
    ].reshape(C, cluster.M)
    res = solver.solve_layered(
        LayeredProblem(
            supply=supply,
            col_cap=machine_free.astype(np.int32),
            cost_cm=cost_cm,
            unsched_cost=cluster.unsched_cost,
            ec_cost=cluster.ec_cost,
        )
    )
    assert res.objective == want


def test_bulk_fast_path_matches_generic_over_rounds():
    """Multi-round churn: the layered fast path and the generic oracle
    path must place the same number of tasks every round and end with
    consistent capacity accounting."""

    def drive(backend):
        rng = np.random.default_rng(3)
        cluster = _make_cluster(backend, C=2, seed=11)
        cluster.add_tasks(
            100, rng.integers(0, 3, 100).astype(np.int32),
            rng.integers(0, 2, 100).astype(np.int32),
        )
        history = []
        for i in range(6):
            r = cluster.round()
            history.append((len(r.placed_tasks), r.num_unscheduled))
            placed = np.nonzero(cluster.task_live & (cluster.task_pu >= 0))[0]
            if len(placed) >= 8:
                done = rng.choice(placed, 8, replace=False)
                cluster.complete_tasks((cluster.task0 + done).astype(np.int32))
            cluster.add_tasks(
                5, rng.integers(0, 3, 5).astype(np.int32),
                rng.integers(0, 2, 5).astype(np.int32),
            )
        return history, cluster

    h_ref, _ = drive(ReferenceSolver())
    h_fast, cluster = drive(LayeredTransportSolver())
    assert h_ref == h_fast
    live_placed = cluster.task_live & (cluster.task_pu >= 0)
    recount = np.bincount(
        cluster.task_pu[live_placed], minlength=cluster.num_pus
    )
    assert (recount == cluster.pu_running).all()
    assert (cluster.pu_running <= cluster.S).all()


def test_layered_machine_loss_reschedules():
    """Elastic membership through the fast path: disabling a machine
    evicts its tasks and the next round re-places them elsewhere."""
    solver = LayeredTransportSolver()
    cluster = _make_cluster(solver, C=1, M=4, jobs=1, unsched_cost=100)
    cluster.add_tasks(8)
    r = cluster.round()
    assert len(r.placed_tasks) == 8
    victim = int(cluster.task_pu[cluster.task_pu >= 0][0] // cluster.P)
    evicted = cluster.set_machine_enabled(victim, False)
    assert len(evicted) >= 1
    r2 = cluster.round()
    assert len(r2.placed_tasks) == len(evicted)
    lo, hi = victim * cluster.P, (victim + 1) * cluster.P
    on_victim = (cluster.task_pu >= lo) & (cluster.task_pu < hi) & cluster.task_live
    assert not on_victim.any()


def test_layered_prefers_cheap_machines():
    """With a steep cost gradient and scarce tasks, every placement must
    land on the cheapest machines (exactness, not just feasibility)."""
    solver = LayeredTransportSolver()
    M = 8
    cost = (np.arange(M, dtype=np.int32) * 10)[None, :]  # machine m costs 10m
    cluster = BulkCluster(
        num_machines=M, pus_per_machine=1, slots_per_pu=2, num_jobs=1,
        backend=solver, task_capacity=64, num_task_classes=1,
        class_cost_fn=lambda cl: cost, unsched_cost=1000,
    )
    cluster.add_tasks(4)  # 4 tasks, 2 slots per machine -> machines 0,1
    r = cluster.round()
    machines = (r.placed_pus - cluster.pu0) // cluster.P
    assert sorted(machines.tolist()) == [0, 0, 1, 1]


def test_layered_unsched_when_placement_too_expensive():
    """Tasks stay unscheduled when u < e + cost (the escape-arc policy,
    reference trivial_cost_modeler.go:41-43)."""
    solver = LayeredTransportSolver()
    cost = np.full((1, 4), 50, np.int32)
    cluster = BulkCluster(
        num_machines=4, pus_per_machine=1, slots_per_pu=4, num_jobs=1,
        backend=solver, task_capacity=64, num_task_classes=1,
        class_cost_fn=lambda cl: cost, unsched_cost=5,
    )
    cluster.add_tasks(10)
    r = cluster.round()
    assert len(r.placed_tasks) == 0
    assert r.num_unscheduled == 10
