"""The dense layered transport solver (solver/layered.py): exactness
against the SSP oracle, and BulkCluster fast-path equivalence.

The layered solver must produce the SAME objective as the generic MCMF
backends on the aggregate topology (placement parity in the reference's
sense: MCMF has many optima, so parity = equal objective cost —
SURVEY.md §7 "Hard parts").
"""

import numpy as np
import pytest

from ksched_tpu.scheduler.bulk import BulkCluster
from ksched_tpu.solver.cpu_ref import ReferenceSolver
from ksched_tpu.solver.layered import (
    LayeredProblem,
    LayeredTransportSolver,
)


def _objective_via_oracle(cluster: BulkCluster) -> int:
    """Solve the cluster's full FlowProblem with the exact SSP oracle."""
    cluster._refresh_capacities()
    problem = cluster._problem()
    return ReferenceSolver().solve(problem).objective


def _make_cluster(backend, C, M=12, jobs=3, seed=7, unsched_cost=25):
    rng = np.random.default_rng(seed)
    cost = rng.integers(0, 20, (C, M)).astype(np.int32)
    return BulkCluster(
        num_machines=M,
        pus_per_machine=2,
        slots_per_pu=2,
        num_jobs=jobs,
        backend=backend,
        task_capacity=256,
        num_task_classes=C,
        class_cost_fn=lambda cl: cost,
        unsched_cost=unsched_cost,
    )


@pytest.mark.parametrize("seed", range(6))
@pytest.mark.parametrize("C", [1, 3])
def test_layered_objective_matches_oracle(seed, C):
    rng = np.random.default_rng(seed)
    solver = LayeredTransportSolver()
    cluster = _make_cluster(solver, C=C, seed=seed)
    n = int(rng.integers(10, 120))
    cluster.add_tasks(
        n,
        rng.integers(0, 3, n).astype(np.int32),
        rng.integers(0, C, n).astype(np.int32),
    )
    want = _objective_via_oracle(cluster)

    cluster._refresh_capacities()
    unplaced = np.nonzero(cluster.task_live & (cluster.task_pu < 0))[0]
    supply = np.bincount(cluster.task_class[unplaced], minlength=C).astype(np.int32)
    pu_free = cluster.S - cluster.pu_running
    machine_free = pu_free.reshape(cluster.M, cluster.P).sum(axis=1)
    cost_cm = cluster.cost[
        cluster.a_ecm0 : cluster.a_ecm0 + C * cluster.M
    ].reshape(C, cluster.M)
    res = solver.solve_layered(
        LayeredProblem(
            supply=supply,
            col_cap=machine_free.astype(np.int32),
            cost_cm=cost_cm,
            unsched_cost=cluster.unsched_cost,
            ec_cost=cluster.ec_cost,
        )
    )
    assert res.objective == want


def test_bulk_fast_path_matches_generic_over_rounds():
    """Multi-round churn: the layered fast path and the generic oracle
    path must place the same number of tasks every round and end with
    consistent capacity accounting."""

    def drive(backend):
        rng = np.random.default_rng(3)
        cluster = _make_cluster(backend, C=2, seed=11)
        cluster.add_tasks(
            100, rng.integers(0, 3, 100).astype(np.int32),
            rng.integers(0, 2, 100).astype(np.int32),
        )
        history = []
        for i in range(6):
            r = cluster.round()
            history.append((len(r.placed_tasks), r.num_unscheduled))
            placed = np.nonzero(cluster.task_live & (cluster.task_pu >= 0))[0]
            if len(placed) >= 8:
                done = rng.choice(placed, 8, replace=False)
                cluster.complete_tasks((cluster.task0 + done).astype(np.int32))
            cluster.add_tasks(
                5, rng.integers(0, 3, 5).astype(np.int32),
                rng.integers(0, 2, 5).astype(np.int32),
            )
        return history, cluster

    h_ref, _ = drive(ReferenceSolver())
    h_fast, cluster = drive(LayeredTransportSolver())
    assert h_ref == h_fast
    live_placed = cluster.task_live & (cluster.task_pu >= 0)
    recount = np.bincount(
        cluster.task_pu[live_placed], minlength=cluster.num_pus
    )
    assert (recount == cluster.pu_running).all()
    assert (cluster.pu_running <= cluster.S).all()


def test_layered_machine_loss_reschedules():
    """Elastic membership through the fast path: disabling a machine
    evicts its tasks and the next round re-places them elsewhere."""
    solver = LayeredTransportSolver()
    cluster = _make_cluster(solver, C=1, M=4, jobs=1, unsched_cost=100)
    cluster.add_tasks(8)
    r = cluster.round()
    assert len(r.placed_tasks) == 8
    victim = int(cluster.task_pu[cluster.task_pu >= 0][0] // cluster.P)
    evicted = cluster.set_machine_enabled(victim, False)
    assert len(evicted) >= 1
    r2 = cluster.round()
    assert len(r2.placed_tasks) == len(evicted)
    lo, hi = victim * cluster.P, (victim + 1) * cluster.P
    on_victim = (cluster.task_pu >= lo) & (cluster.task_pu < hi) & cluster.task_live
    assert not on_victim.any()


def test_layered_prefers_cheap_machines():
    """With a steep cost gradient and scarce tasks, every placement must
    land on the cheapest machines (exactness, not just feasibility)."""
    solver = LayeredTransportSolver()
    M = 8
    cost = (np.arange(M, dtype=np.int32) * 10)[None, :]  # machine m costs 10m
    cluster = BulkCluster(
        num_machines=M, pus_per_machine=1, slots_per_pu=2, num_jobs=1,
        backend=solver, task_capacity=64, num_task_classes=1,
        class_cost_fn=lambda cl: cost, unsched_cost=1000,
    )
    cluster.add_tasks(4)  # 4 tasks, 2 slots per machine -> machines 0,1
    r = cluster.round()
    machines = (r.placed_pus - cluster.pu0) // cluster.P
    assert sorted(machines.tolist()) == [0, 0, 1, 1]


@pytest.mark.parametrize("seed", range(5))
def test_row_constant_closed_form_matches_iterative(seed):
    """solve_row_constant (the per-job-unsched closed form) must match
    the iterative cost-scaling solve's objective on random row-constant
    instances — and the host solve_layered dispatch must take it
    (supersteps == 0)."""
    import jax.numpy as jnp

    from ksched_tpu.solver.layered import (
        _solve_transport,
        pad_geometry,
        solve_row_constant_np,
    )

    rng = np.random.default_rng(seed)
    G, M = 6, 10
    v = rng.integers(-12, 6, G).astype(np.int32)  # mixed signs
    supply = rng.integers(0, 30, G).astype(np.int32)
    cap = rng.integers(0, 12, M).astype(np.int32)
    Mp, n_scale = pad_geometry(M, G)
    col_cap = np.zeros(Mp, np.int32)
    col_cap[:M] = cap
    col_cap[-1] = supply.sum()

    y = solve_row_constant_np(v, supply, col_cap)
    # feasibility
    assert (y >= 0).all()
    assert (y.sum(axis=1) == supply).all()
    assert (y[:, :-1].sum(axis=0) <= col_cap[:-1]).all()
    obj = int((v.astype(np.int64)[:, None] * y[:, :-1]).sum())

    # iterative exact solve on the same (machine-uniform) instance
    wP = np.zeros((G, Mp), np.int64)
    wP[:, :M] = v[:, None]
    eps_full = int(max(1, np.abs(wP).max() * n_scale))
    y2, _pm, steps, conv = _solve_transport(
        jnp.asarray((wP * n_scale).astype(np.int32)),
        jnp.asarray(supply), jnp.asarray(col_cap),
        jnp.int32(eps_full), None, alpha=8, max_supersteps=1 << 16,
    )
    assert bool(conv)
    obj2 = int((wP[:, :M] * np.asarray(y2, np.int64)[:, :M]).sum())
    assert obj == obj2

    # dispatch: solve_layered_host must hit the closed form
    solver = LayeredTransportSolver()
    res = solver.solve_layered(
        LayeredProblem(
            supply=supply, col_cap=cap,
            cost_cm=np.zeros((G, M), np.int32),
            unsched_cost=0, ec_cost=0,
            row_unsched_cost=-v.astype(np.int64),
        )
    )
    assert res.supersteps == 0
    # res.objective is in full-graph units (u*unplaced + (e+cost)*y);
    # here cost = e = 0, so it is exactly the escape charges — and the
    # shifted objective (v * placed) must equal the iterative solve's
    unplaced_row = supply - res.y.sum(axis=1)
    assert res.objective == int(
        (-v.astype(np.int64) * unplaced_row).sum()
    )
    assert int((v.astype(np.int64)[:, None] * res.y).sum()) == obj


def test_device_per_job_row_constant_closed_form():
    """The device per-job path with distinct unsched costs and no cost
    model must take the row-constant closed form (0 supersteps) and
    prioritize the rows with the most expensive escapes."""
    from ksched_tpu.scheduler.device_bulk import DeviceBulkCluster

    dev = DeviceBulkCluster(
        num_machines=2, pus_per_machine=1, slots_per_pu=1, num_jobs=3,
        task_capacity=16, ec_cost=2,
        job_unsched_cost=np.array([1, 10, 20]),
    )
    assert dev.row_constant and not dev.class_degenerate
    assert dev.supersteps == 1
    # 2 slots, 3 tasks: job-2 and job-1 tasks must win (escape dearest),
    # job-0 stays (escape at 1 < EC cost 2)
    dev.add_tasks(3, np.array([0, 1, 2], np.int32))
    stats = dev.fetch_stats(dev.round())
    assert bool(stats["converged"]) and int(stats["supersteps"]) == 0
    st = {k: np.asarray(v) for k, v in dev.fetch_state().items()}
    rows = np.nonzero(st["live"] & (st["pu"] >= 0))[0]
    assert sorted(st["job"][rows].tolist()) == [1, 2]
    # objective: job-0 escapes at 1; jobs 1,2 place at e=2 each
    assert int(stats["objective"]) == 1 + 2 + 2


def test_layered_unsched_when_placement_too_expensive():
    """Tasks stay unscheduled when u < e + cost (the escape-arc policy,
    reference trivial_cost_modeler.go:41-43)."""
    solver = LayeredTransportSolver()
    cost = np.full((1, 4), 50, np.int32)
    cluster = BulkCluster(
        num_machines=4, pus_per_machine=1, slots_per_pu=4, num_jobs=1,
        backend=solver, task_capacity=64, num_task_classes=1,
        class_cost_fn=lambda cl: cost, unsched_cost=5,
    )
    cluster.add_tasks(10)
    r = cluster.round()
    assert len(r.placed_tasks) == 0
    assert r.num_unscheduled == 10
