"""Class-degenerate transport collapse (solver/layered.py): when every
class has the same cost row, the multi-class solve must collapse to the
exact C=1 closed form plus a feasible class split — the iterative
push-relabel herds on identical costs (observed: a trivially easy
12.5k-machine instance exceeding 20k supersteps), so this path is a
correctness-of-latency requirement for the Google-trace config."""

import numpy as np
import jax.numpy as jnp
import pytest

from ksched_tpu.scheduler.bulk import BulkCluster
from ksched_tpu.solver.cpu_ref import ReferenceSolver
from ksched_tpu.solver.layered import (
    LayeredProblem,
    LayeredTransportSolver,
    split_grants_by_class,
)


@pytest.mark.parametrize("seed", range(6))
def test_split_grants_feasible_and_exhaustive(seed):
    rng = np.random.default_rng(seed)
    M, C = int(rng.integers(2, 40)), int(rng.integers(2, 6))
    supply = rng.integers(0, 30, C).astype(np.int64)
    y_tot = np.zeros(M, np.int64)
    budget = int(supply.sum())
    caps = rng.integers(0, 10, M)
    for m in range(M):  # grants never exceed total supply
        y_tot[m] = min(caps[m], budget - y_tot[:m].sum())
    y = split_grants_by_class(y_tot, supply)
    assert (y >= 0).all()
    np.testing.assert_array_equal(y.sum(axis=0), y_tot)  # col sums exact
    assert (y.sum(axis=1) <= supply).all()  # row sums within supply
    # jnp twin agrees
    y_j = np.asarray(split_grants_by_class(jnp.asarray(y_tot), jnp.asarray(supply)))
    np.testing.assert_array_equal(y_j, y)


@pytest.mark.parametrize("seed", range(4))
def test_degenerate_multiclass_matches_oracle(seed):
    """Uniform-cost multi-class cluster: collapsed solve == SSP oracle
    objective (no class_cost_fn -> all cost rows identical zeros)."""
    rng = np.random.default_rng(seed)
    C = 4
    solver = LayeredTransportSolver()
    cluster = BulkCluster(
        num_machines=10,
        pus_per_machine=2,
        slots_per_pu=2,
        num_jobs=3,
        backend=solver,
        task_capacity=256,
        num_task_classes=C,
    )
    n = int(rng.integers(20, 120))
    cluster.add_tasks(
        n,
        rng.integers(0, 3, n).astype(np.int32),
        rng.integers(0, C, n).astype(np.int32),
    )
    cluster._refresh_capacities()
    want = ReferenceSolver().solve(cluster._problem()).objective

    unplaced = np.nonzero(cluster.task_live & (cluster.task_pu < 0))[0]
    supply = np.bincount(cluster.task_class[unplaced], minlength=C).astype(np.int32)
    pu_free = cluster.S - cluster.pu_running
    machine_free = pu_free.reshape(cluster.M, cluster.P).sum(axis=1)
    res = solver.solve_layered(
        LayeredProblem(
            supply=supply,
            col_cap=machine_free.astype(np.int32),
            cost_cm=np.zeros((C, cluster.M), np.int32),
            unsched_cost=cluster.unsched_cost,
            ec_cost=cluster.ec_cost,
        )
    )
    assert res.objective == want
    assert res.supersteps == 0  # closed form, no iterations


def test_trace_replay_scale_smoke():
    """The shape that exposed the herding stall: thousands of machines,
    uniform costs, C=4 — must converge instantly via the collapse."""
    from ksched_tpu.drivers.trace_replay import TraceReplayDriver, synthesize_trace

    machines, events = synthesize_trace(num_machines=3000, num_tasks=2000, seed=3)
    driver = TraceReplayDriver(
        machines, backend=LayeredTransportSolver(), slots_per_machine=4
    )
    stats = driver.replay(events, window_s=20.0, max_rounds=8)
    assert stats.rounds > 0
    assert stats.placed > 0
