"""Pallas MCMF megakernel (ops/mcmf_pallas.py, solver/mega_solver.py):
BIT-parity with the CSR solver, oracle parity, and the dense -> mega ->
scan-CSR dispatch escalation.

The kernel runs the same synchronous push-relabel schedule as
solver/jax_solver.py `_solve_mcmf` over the same sorted-entry order, so
parity here is exact flow equality superstep-for-superstep — stronger
than the objective parity the ELL suite asserts (MCMF optima are
non-unique, but these two implementations must pick the SAME one).
Tests run the kernel under the Pallas interpreter (CPU env); the
TPU-compiled path is the same kernel code, exercised by
tools/mcmf_mega_bench.py on hardware.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from ksched_tpu.solver.cpu_ref import ReferenceSolver
from ksched_tpu.solver.graph_collapse import AutoSolver
from ksched_tpu.solver.jax_solver import (
    JaxSolver,
    _solve_mcmf,
    build_csr_plan,
)
from ksched_tpu.solver.mega_solver import MegaSolver, build_mega_plan

from test_jax_solver import assert_valid_flow, random_scheduling_problem
from test_solver_oracle import make_problem


def _plan_pair(problem):
    src = problem.src.astype(np.int32)
    dst = problem.dst.astype(np.int32)
    csr = build_csr_plan(src, dst, problem.num_nodes)
    return csr, build_mega_plan(csr)


def test_mega_plan_structure():
    rng = np.random.default_rng(3)
    p = random_scheduling_problem(
        rng, num_tasks=40, num_machines=4, slots_per_machine=3
    )
    csr, mega = _plan_pair(p)
    m2 = 2 * len(p.src)
    E = mega.R * mega.L
    assert E >= m2 and E % mega.L == 0
    # live region mirrors the CSR ordering
    np.testing.assert_array_equal(mega.e_arc[:m2], csr.s_arc)
    np.testing.assert_array_equal(mega.e_sign[:m2], csr.s_sign)
    assert (mega.e_sign[m2:] == 0).all()
    # the partner permutation is an involution pairing opposite signs
    # of the same arc (and self on pads)
    ppos = mega.e_prow.astype(np.int64) * mega.L + mega.e_pcol
    np.testing.assert_array_equal(ppos[ppos], np.arange(E))
    live = mega.e_sign != 0
    assert (mega.e_arc[ppos[live]] == mega.e_arc[live]).all()
    assert (mega.e_sign[ppos[live]] == -mega.e_sign[live]).all()
    assert (ppos[~live] == np.nonzero(~live)[0]).all()
    # the partner's source is the entry's destination
    np.testing.assert_array_equal(mega.e_src[ppos[:m2]], csr.s_dst)
    # one start and one end per segment, pad segment included
    n_seg = len(np.unique(csr.s_src)) + (1 if E > m2 else 0)
    assert int(mega.e_hs.sum()) == n_seg
    assert int(mega.e_he.sum()) == n_seg
    # fwd_pos addresses exactly the forward entries
    assert (mega.e_sign[mega.fwd_pos] == 1).all()
    np.testing.assert_array_equal(mega.e_arc[mega.fwd_pos], np.arange(len(p.src)))


@pytest.mark.parametrize("lanes", [None, 8])
def test_kernel_bit_parity_vs_csr_64_nodes(lanes):
    """The fast tier-1 kernel check (64-node scheduling graph): the
    megakernel's flows and superstep counts must equal the CSR
    solver's exactly, warm (eps=1) and cold (full eps schedule).
    lanes=8 shrinks the tile width so the entries span R=31 block
    rows — exercising the cross-block segmented-scan carry the
    production 10k x 1k shape (R=256) relies on; lanes=None is the
    default single-row tiling."""
    from ksched_tpu.ops.mcmf_pallas import mcmf_loop_pallas

    rng = np.random.default_rng(7)
    p = random_scheduling_problem(
        rng, num_tasks=40, num_machines=4, slots_per_machine=3
    )
    assert p.num_nodes <= 64
    n = p.num_nodes
    csr = build_csr_plan(
        p.src.astype(np.int32), p.dst.astype(np.int32), n
    )
    mega = build_mega_plan(csr, lanes)
    if lanes is not None:
        assert mega.R > 1  # the cross-block carry path is live
    cap = jnp.asarray(p.cap.astype(np.int32))
    cost = jnp.asarray(p.cost.astype(np.int32) * np.int32(n))
    supply = jnp.asarray(p.excess.astype(np.int32))
    flow0 = jnp.zeros(len(p.src), jnp.int32)
    csr_dev = tuple(
        jnp.asarray(x)
        for x in (
            csr.s_arc, csr.s_sign, csr.s_src, csr.s_dst,
            csr.s_segstart, csr.s_isstart, csr.inv_order,
            csr.node_first, csr.node_last, csr.node_nonempty,
        )
    )
    mega_dev = tuple(
        jnp.asarray(x)
        for x in (
            mega.e_arc, mega.e_sign, mega.e_src, mega.e_hs, mega.e_he,
            mega.e_prow, mega.e_pcol, mega.fwd_pos,
        )
    )
    max_cost = int(np.abs(p.cost).max())
    for eps0 in (1, max(1, max_cost * n)):
        f_c, _p, s_c, conv_c, ovf_c = _solve_mcmf(
            cap, cost, supply, flow0, jnp.asarray(np.int32(eps0)), *csr_dev,
            alpha=8, max_supersteps=50_000,
        )
        f_m, s_m, conv_m, ovf_m = mcmf_loop_pallas(
            cap, cost, supply, flow0, jnp.asarray(np.int32(eps0)), *mega_dev,
            R=mega.R, L=mega.L, alpha=8, max_supersteps=50_000,
            interpret=True,
        )
        assert bool(conv_c) and bool(conv_m), eps0
        assert not bool(ovf_c) and not bool(ovf_m), eps0
        assert int(s_c) == int(s_m), eps0
        np.testing.assert_array_equal(np.asarray(f_c), np.asarray(f_m))


def test_solver_bit_parity_and_warm_start():
    """End-to-end MegaSolver vs JaxSolver across warm-started rounds:
    identical flows every round, oracle-equal objectives."""
    rng = np.random.default_rng(5)
    p = random_scheduling_problem(
        rng, num_tasks=12, num_machines=3, slots_per_machine=2
    )
    jx = JaxSolver()
    mg = MegaSolver(interpret=True)
    r_j = jx.solve(p)
    r_m = mg.solve(p)
    ref = ReferenceSolver().solve(p)
    assert r_m.objective == ref.objective == r_j.objective
    assert mg.last_supersteps == jx.last_supersteps
    np.testing.assert_array_equal(r_j.flow, r_m.flow)
    assert_valid_flow(p, r_m.flow)

    from ksched_tpu.graph.device_export import FlowProblem

    p2 = FlowProblem(
        num_nodes=p.num_nodes,
        excess=p.excess.copy(),
        node_type=p.node_type,
        src=p.src,
        dst=p.dst,
        cap=p.cap.copy(),
        cost=p.cost.copy(),
        flow_offset=p.flow_offset,
        num_arcs=p.num_arcs,
    )
    p2.cost[0] += 2
    r_j2 = jx.solve(p2)
    r_m2 = mg.solve(p2)
    ref2 = ReferenceSolver().solve(p2)
    assert r_m2.objective == ref2.objective == r_j2.objective
    np.testing.assert_array_equal(r_j2.flow, r_m2.flow)
    # the warm re-solve stays incremental, as for the CSR solver
    assert mg.last_supersteps == jx.last_supersteps


def test_autosolver_escalates_dense_mega_csr():
    """The AutoSolver ladder: a non-collapsible graph inside the VMEM
    budget takes the mega rung; an 'oversized' graph (budget shrunk to
    force it) falls through to scan-CSR; a collapsible graph still
    takes the dense transport."""
    # untyped nodes -> the collapse audit refuses -> general path
    p = make_problem(
        8,
        {1: 1, 2: 1, 6: -2},
        [
            (1, 3, 0, 1, 2),
            (2, 3, 0, 1, 2),
            (3, 4, 0, 1, 0),
            (3, 5, 0, 1, 4),
            (4, 6, 0, 1, 0),
            (5, 6, 0, 1, 0),
            (1, 7, 0, 1, 50),
            (2, 7, 0, 1, 50),
            (7, 6, 0, 2, 0),
        ],
    )
    want = ReferenceSolver().solve(p).objective

    auto = AutoSolver(JaxSolver(), mega=MegaSolver(interpret=True))
    res = auto.solve(p)
    assert auto.last_path == "mega"
    assert res.objective == want

    tiny = AutoSolver(
        JaxSolver(), mega=MegaSolver(interpret=True, vmem_budget_bytes=64)
    )
    res2 = tiny.solve(p)
    assert tiny.last_path == "csr"
    assert "VMEM" in tiny.last_mega_refusal
    assert res2.objective == want

    no_mega = AutoSolver(JaxSolver())
    res3 = no_mega.solve(p)
    assert no_mega.last_path == "csr"
    assert no_mega.last_mega_refusal == "no megakernel attached"
    assert res3.objective == want


def test_autosolver_mega_refuses_overflow_costs():
    """Costs whose node-count scaling overflows int32 are a fits()
    refusal (the ladder stays total and routes to the fallback rung),
    not an OverflowError out of the mega rung."""
    p = make_problem(
        4, {1: 1, 3: -1}, [(1, 2, 0, 1, 1 << 28), (2, 3, 0, 1, 1)]
    )
    want = ReferenceSolver().solve(p).objective
    auto = AutoSolver(ReferenceSolver(), mega=MegaSolver(interpret=True))
    res = auto.solve(p)
    assert auto.last_path == "csr"
    assert "overflow" in auto.last_mega_refusal
    assert res.objective == want


def test_backend_mega_fallback_delegation():
    """--backend mega is total: a graph the kernel refuses (budget
    forced to zero here) delegates to the attached CSR fallback with
    the same result; without a fallback the refusal raises."""
    from ksched_tpu.solver.select import make_backend

    rng = np.random.default_rng(2)
    p = random_scheduling_problem(
        rng, num_tasks=12, num_machines=3, slots_per_machine=2
    )
    want = ReferenceSolver().solve(p).objective

    mg = make_backend("mega")
    assert isinstance(mg, MegaSolver) and mg.fallback is not None
    mg.interpret = True
    assert mg.solve(p).objective == want

    mg.vmem_budget_bytes = 64  # force the delegation path
    assert not mg.fits(p)
    assert mg.solve(p).objective == want

    bare = MegaSolver(interpret=True, vmem_budget_bytes=64)
    with pytest.raises(RuntimeError, match="VMEM"):
        bare.solve(p)


def test_auto_backend_attaches_mega_under_forced_pallas():
    """make_backend('auto') hangs the mega rung on the ladder exactly
    when Pallas dispatch is live (forced interpret here); in plain CPU
    auto mode the ladder is the historical dense -> CSR."""
    from ksched_tpu.ops import get_pallas_mode, set_pallas_mode
    from ksched_tpu.solver.select import make_backend

    prev = get_pallas_mode()
    try:
        set_pallas_mode("interpret")
        auto = make_backend("auto", fallback=True)
        assert isinstance(auto, AutoSolver)
        assert isinstance(auto.mega, MegaSolver)
        set_pallas_mode("off")
        auto2 = make_backend("auto", fallback=True)
        assert auto2.mega is None
    finally:
        set_pallas_mode(prev)
