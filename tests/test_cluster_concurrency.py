"""Concurrency stress for the threaded cluster adapters + auth plumbing.

The reference's only concurrency check is `go test --race`
(hack/test.sh:17). Python has no TSan; the analogue here is adversarial
stress: hammer the adapters' shared state from many threads and assert
the conservation invariants that a race would break (events neither
lost nor duplicated, bindings consistent between client and server,
seen-sets bounded). Auth: the TLS + bearer modes of the fake API server
(k8s/k8sclient/client.go:34-42 builds an authenticated client) are
exercised hermetically over loopback with a self-signed cert.
"""

import threading
import time


from ksched_tpu.cluster import Binding, FakeAPIServer, HTTPClusterAPI
from ksched_tpu.cluster.synthetic_api import SyntheticClusterAPI
from ksched_tpu.cluster.api import PodEvent


def _drain_and_bind(api, server, want, nodes, deadline_s=20.0):
    """Consume pod batches and bind round-robin until `want` pods are
    bound server-side (or the deadline passes)."""
    bound = 0
    t_end = time.monotonic() + deadline_s
    i = 0
    while bound < want and time.monotonic() < t_end:
        batch = api.get_pod_batch(timeout_s=0.3)
        if batch:
            api.assign_bindings(
                [Binding(p.pod_id, nodes[(i + k) % len(nodes)])
                 for k, p in enumerate(batch)]
            )
            i += len(batch)
        bound = len(server.bindings())
    return bound


# ---------------------------------------------------------------------------
# auth: TLS + bearer token
# ---------------------------------------------------------------------------


def test_tls_bearer_end_to_end():
    server = FakeAPIServer(tls=True, bearer="s3cret-token").start()
    try:
        for i in range(2):
            server.add_node(f"node_{i}", cores=1, pus_per_core=2)
        server.create_pods(4)
        api = HTTPClusterAPI(
            server.base_url,
            poll_interval_s=0.05,
            bearer_token="s3cret-token",
            ca_cert=server.ca_cert_path,
        )
        try:
            assert server.base_url.startswith("https://")
            nodes = [n.node_id for n in api.get_node_batch(timeout_s=2.0)]
            assert sorted(nodes) == ["node_0", "node_1"]
            bound = _drain_and_bind(api, server, want=4, nodes=nodes)
            assert bound == 4
            assert api.bindings() == server.bindings()
        finally:
            api.close()
    finally:
        server.stop()


def test_wrong_bearer_token_rejected():
    server = FakeAPIServer(tls=True, bearer="right").start()
    try:
        server.add_node("node_0")
        server.create_pods(2)
        api = HTTPClusterAPI(
            server.base_url,
            poll_interval_s=0.05,
            bearer_token="wrong",
            ca_cert=server.ca_cert_path,
        )
        try:
            # 401s: the watches surface nothing (get_pod_batch BLOCKS
            # for the first pod by design — reference debounce
            # semantics — so peek at the channel instead of draining),
            # and binding POSTs fail without recording anything
            time.sleep(1.0)
            assert api._chan._pods.empty()
            assert api._chan._nodes.empty()
            api.assign_bindings([Binding("pod_0", "node_0")])
            assert server.bindings() == {}
            assert api.bindings() == {}
        finally:
            api.close()
    finally:
        server.stop()


def test_tls_rejects_unpinned_client():
    server = FakeAPIServer(tls=True).start()
    try:
        server.add_node("node_0")
        # no ca_cert: the self-signed server cert fails verification,
        # the informers keep retrying, nothing surfaces (channel peek —
        # the batch getters block for the first event by design)
        api = HTTPClusterAPI(server.base_url, poll_interval_s=0.05)
        try:
            time.sleep(1.0)
            assert api._chan._pods.empty()
            assert api._chan._nodes.empty()
        finally:
            api.close()
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# race stress
# ---------------------------------------------------------------------------


def test_http_adapter_stress_concurrent_producers_and_binder():
    """3 producer threads POST pods through the adapter while the main
    thread drains batches and posts bindings; watch threads reconcile
    concurrently. Invariants: every pod bound exactly once, client and
    server agree, and the seen-set stays bounded by the pending
    listing."""
    server = FakeAPIServer().start()
    n_nodes, per_producer, producers = 4, 25, 3
    total = per_producer * producers
    try:
        for i in range(n_nodes):
            server.add_node(f"node_{i}", cores=2, pus_per_core=2)
        api = HTTPClusterAPI(server.base_url, poll_interval_s=0.02)
        try:
            nodes = [n.node_id for n in api.get_node_batch(timeout_s=2.0)]
            assert len(nodes) == n_nodes

            def produce(k):
                for i in range(per_producer):
                    api.create_pod(f"pod_{k}_{i}", task_class=i % 4)

            threads = [
                threading.Thread(target=produce, args=(k,))
                for k in range(producers)
            ]
            for t in threads:
                t.start()
            bound = _drain_and_bind(api, server, want=total, nodes=nodes)
            for t in threads:
                t.join(timeout=5)
            assert bound == total
            server_bindings = server.bindings()
            assert len(server_bindings) == total  # each pod exactly once
            assert api.bindings() == server_bindings
            # reconcile: with nothing pending, the seen-set drains
            t_end = time.monotonic() + 5
            while time.monotonic() < t_end:
                with api._bindings_lock:
                    if not api._seen_pods:
                        break
                time.sleep(0.05)
            with api._bindings_lock:
                assert not api._seen_pods
        finally:
            api.close()
    finally:
        server.stop()


def test_synthetic_channel_conserves_events_under_contention():
    """Many offerers vs one drainer vs close: accepted offers must all
    be drained exactly once (no loss, no duplication), rejected offers
    must not surface, and close() must not deadlock anyone."""
    api = SyntheticClusterAPI(pod_chan_size=64)  # << total: backpressure
    per_producer, producers = 300, 4
    total = per_producer * producers
    accepted = []
    acc_lock = threading.Lock()

    def offerer(k):
        for i in range(per_producer):
            ev = PodEvent(pod_id=f"p{k}_{i}")
            # bounded-wait offers retried to acceptance: exactly
            # per_producer accepted events per producer, with plenty of
            # queue-full rejections along the way
            while not api.offer_pod(ev, timeout_s=0.02):
                pass
            with acc_lock:
                accepted.append(ev.pod_id)

    threads = [
        threading.Thread(target=offerer, args=(k,), daemon=True)
        for k in range(producers)
    ]
    for t in threads:
        t.start()
    drained = []
    # the total is known, so the drain can stop BEFORE a blocking call
    # (get_pod_batch waits indefinitely for a first event by design —
    # the reference's pod-channel contract)
    while len(drained) < total:
        drained.extend(p.pod_id for p in api.get_pod_batch(timeout_s=0.05))
    for t in threads:
        t.join(timeout=5)
    api.close()
    with acc_lock:
        want = list(accepted)
    assert sorted(drained) == sorted(want)
    assert len(set(drained)) == len(drained)  # no duplication
