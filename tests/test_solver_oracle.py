"""CPU oracle solver tests: hand-built MCMF instances with known optima,
plus lower-bound folding via DeviceGraphState."""

import numpy as np

from ksched_tpu.graph.device_export import DeviceGraphState, FlowProblem
from ksched_tpu.solver import ReferenceSolver


def make_problem(num_nodes, excess, arcs):
    """arcs: list of (src, dst, low, cap, cost)."""
    ex = np.zeros(num_nodes, dtype=np.int64)
    for node, e in excess.items():
        ex[node] = e
    src = np.array([a[0] for a in arcs], dtype=np.int32)
    dst = np.array([a[1] for a in arcs], dtype=np.int32)
    low = np.array([a[2] for a in arcs], dtype=np.int32)
    cap = np.array([a[3] for a in arcs], dtype=np.int32)
    cost = np.array([a[4] for a in arcs], dtype=np.int32)
    # fold lower bounds like DeviceGraphState.problem()
    flow_offset = low.copy()
    for i in range(len(arcs)):
        if low[i] > 0:
            ex[src[i]] -= low[i]
            ex[dst[i]] += low[i]
            cap[i] -= low[i]
    return FlowProblem(
        num_nodes=num_nodes,
        excess=ex,
        node_type=np.zeros(num_nodes, dtype=np.int8),
        src=src,
        dst=dst,
        cap=cap,
        cost=cost,
        flow_offset=flow_offset,
        num_arcs=len(arcs),
    )


def test_single_path():
    # 1 -> 2 -> 3(sink), supply 1
    p = make_problem(4, {1: 1, 3: -1}, [(1, 2, 0, 1, 2), (2, 3, 0, 1, 3)])
    r = ReferenceSolver().solve(p)
    assert r.objective == 5
    assert list(r.flow) == [1, 1]


def test_chooses_cheaper_path():
    # 1 -> 3 direct (cost 10) vs 1 -> 2 -> 3 (cost 2+3)
    p = make_problem(
        4, {1: 1, 3: -1}, [(1, 3, 0, 1, 10), (1, 2, 0, 1, 2), (2, 3, 0, 1, 3)]
    )
    r = ReferenceSolver().solve(p)
    assert r.objective == 5
    assert r.flow[0] == 0 and r.flow[1] == 1 and r.flow[2] == 1


def test_capacity_forces_split():
    # two units from 1; cheap path has capacity 1
    p = make_problem(
        4, {1: 2, 3: -2}, [(1, 3, 0, 9, 10), (1, 2, 0, 1, 2), (2, 3, 0, 9, 3)]
    )
    r = ReferenceSolver().solve(p)
    assert r.objective == 15  # one unit at 5, one at 10
    assert r.flow[0] == 1


def test_multi_source_assignment():
    # Tasks 1,2 -> EC 3 -> machines 4,5 -> sink 6; machine arcs capacity 1 each.
    arcs = [
        (1, 3, 0, 1, 2),
        (2, 3, 0, 1, 2),
        (3, 4, 0, 1, 0),
        (3, 5, 0, 1, 4),
        (4, 6, 0, 1, 0),
        (5, 6, 0, 1, 0),
        # unsched escape: expensive
        (1, 7, 0, 1, 50),
        (2, 7, 0, 1, 50),
        (7, 6, 0, 2, 0),
    ]
    p = make_problem(8, {1: 1, 2: 1, 6: -2}, arcs)
    r = ReferenceSolver().solve(p)
    # both placed: 2+0+0 and 2+4+0 => 8
    assert r.objective == 8


def test_unsched_escape_when_capacity_exhausted():
    # One machine slot, two tasks; second should drain via unsched agg.
    arcs = [
        (1, 3, 0, 1, 2),
        (2, 3, 0, 1, 2),
        (3, 4, 0, 1, 0),
        (4, 6, 0, 1, 0),
        (1, 7, 0, 1, 5),
        (2, 7, 0, 1, 5),
        (7, 6, 0, 2, 0),
    ]
    p = make_problem(8, {1: 1, 2: 1, 6: -2}, arcs)
    r = ReferenceSolver().solve(p)
    assert r.objective == 2 + 5
    # exactly one unit through the EC
    assert r.flow[2] == 1


def test_negative_costs_bootstrap():
    p = make_problem(4, {1: 1, 3: -1}, [(1, 2, 0, 1, -2), (2, 3, 0, 1, 3), (1, 3, 0, 1, 5)])
    r = ReferenceSolver().solve(p)
    assert r.objective == 1


def test_lower_bound_running_arc():
    # Running arc 1->2 with low=1: the unit is forced through even though
    # the direct path 1->3 would be cheaper.
    p = make_problem(4, {1: 1, 3: -1}, [(1, 2, 1, 1, 7), (2, 3, 0, 1, 0), (1, 3, 0, 1, 1)])
    r = ReferenceSolver().solve(p)
    total = r.total_flow(p)
    assert total[0] == 1  # lower bound respected
    assert r.objective == 7


def test_device_graph_state_roundtrip():
    st = DeviceGraphState()
    from ksched_tpu.graph import FlowGraph

    g = FlowGraph()
    a, b, c = g.add_node(), g.add_node(), g.add_node()
    a.excess = 1
    c.excess = -1
    arc1 = g.add_arc(a, b)
    arc1.cap_upper, arc1.cost = 1, 2
    arc2 = g.add_arc(b, c)
    arc2.cap_upper, arc2.cost = 1, 3
    st.full_build(g)
    p = st.problem()
    r = ReferenceSolver().solve(p)
    assert r.objective == 5
