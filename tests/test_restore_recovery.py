"""Crash recovery: hardened restore inputs + warm delta-sized resume.

Satellites of the r14 integrity PR: damaged checkpoint inputs must each
raise a DISTINCT actionable error; a kill-and-restore through the warm
manifest must resume on the delta-sized warm path (plan_sync delta, no
full_build, warm/fresh solve scope) with the restored timeline
bit-identical to the uninterrupted one; and a checkpoint taken after
(or straddling) a pow2 bucket growth must bring the warm geometry and
slot-plan regions back consistent.
"""

import os
import pickle
import warnings

import numpy as np
import pytest

from ksched_tpu.cli import SERVICE_CHECKPOINT_VERSION, SchedulerService
from ksched_tpu.cluster import PodEvent, SyntheticClusterAPI
from ksched_tpu.runtime.checkpoint import (
    CheckpointDamaged,
    CheckpointMissing,
    CheckpointVersionError,
    find_jax_solver,
)
from ksched_tpu.runtime.integrity import corrupt_wal_file
from ksched_tpu.solver.select import make_backend
from ksched_tpu.utils import seed_rng


def _service(api, machines=4, slots=4, device_resident=True, audit_every=1):
    svc = SchedulerService(
        api,
        max_tasks_per_pu=slots,
        backend=make_backend("jax"),
        backend_name="jax",
        device_resident=device_resident,
        audit_every=audit_every,
    )
    svc.init_topology(fake_machines=machines, pus_per_core=2)
    return svc


def _drive(svc, api, rounds, tag, pods_per_round=3, complete=True):
    for r in range(rounds):
        for i in range(pods_per_round):
            api.submit_pod(PodEvent(pod_id=f"{tag}_{r}_{i}"))
        svc.run_round(api.poll_pod_batch(0.01))
        if complete and r % 2 == 1:
            bound = sorted(
                p for p, t in svc.pod_to_task.items()
                if t in svc.scheduler.task_bindings
            )
            if bound:
                svc.complete_pod(bound[0])


def _pod_placements(svc):
    bindings = svc.scheduler.task_bindings
    return {
        pod: bindings[tid]
        for pod, tid in sorted(svc.pod_to_task.items())
        if tid in bindings
    }


# ---------------------------------------------------------------------------
# damaged inputs: three distinct, actionable errors
# ---------------------------------------------------------------------------


def _checkpoint(tmp_path, **kw):
    seed_rng(0)
    api = SyntheticClusterAPI()
    svc = _service(api, **kw)
    _drive(svc, api, 4, "p")
    ck = str(tmp_path / "svc.ckpt")
    svc.save_checkpoint(ck)
    return api, svc, ck


def test_restore_garbage_sidecar_raises_damaged(tmp_path):
    api, _, ck = _checkpoint(tmp_path)
    with open(ck, "wb") as f:
        f.write(b"\x80\x04 garbage, definitely not a checkpoint")
    with pytest.raises(CheckpointDamaged, match="truncated or not a ksched"):
        SchedulerService.restore(api, ck, backend=make_backend("jax"))


def test_restore_truncated_sidecar_raises_damaged(tmp_path):
    api, _, ck = _checkpoint(tmp_path)
    data = open(ck, "rb").read()
    with open(ck, "wb") as f:
        f.write(data[: len(data) // 2])
    with pytest.raises(CheckpointDamaged):
        SchedulerService.restore(api, ck, backend=make_backend("jax"))


def test_restore_wrong_payload_type_raises_damaged(tmp_path):
    api, _, ck = _checkpoint(tmp_path)
    with open(ck, "wb") as f:
        pickle.dump(["not", "a", "dict"], f)
    with pytest.raises(CheckpointDamaged, match="no version field"):
        SchedulerService.restore(api, ck, backend=make_backend("jax"))


def test_restore_missing_sched_companion_raises_missing(tmp_path):
    api, _, ck = _checkpoint(tmp_path)
    os.remove(ck + ".sched")
    with pytest.raises(CheckpointMissing, match="missing its scheduler companion"):
        SchedulerService.restore(api, ck, backend=make_backend("jax"))


def test_restore_version_mismatch_raises_version_error(tmp_path):
    api, _, ck = _checkpoint(tmp_path)
    with open(ck, "rb") as f:
        state = pickle.load(f)
    state["version"] = SERVICE_CHECKPOINT_VERSION + 41
    with open(ck, "wb") as f:
        pickle.dump(state, f)
    with pytest.raises(CheckpointVersionError, match="unsupported service checkpoint"):
        SchedulerService.restore(api, ck, backend=make_backend("jax"))
    # distinct types: the three failure classes never alias
    assert not issubclass(CheckpointVersionError, CheckpointDamaged)
    assert not issubclass(CheckpointDamaged, CheckpointMissing)


# ---------------------------------------------------------------------------
# warm restore: delta-sized + bit-identical continuation
# ---------------------------------------------------------------------------


def test_warm_restore_resumes_delta_sized_and_bit_identical(tmp_path):
    # two identical timelines from one seed; one is killed + restored
    seed_rng(1)
    api_a = SyntheticClusterAPI()
    svc_a = _service(api_a)
    _drive(svc_a, api_a, 6, "p")
    seed_rng(1)
    api_b = SyntheticClusterAPI()
    svc_b = _service(api_b)
    _drive(svc_b, api_b, 6, "p")
    assert _pod_placements(svc_a) == _pod_placements(svc_b)

    ck = str(tmp_path / "svc.ckpt")
    svc_b.save_checkpoint(ck)
    assert os.path.exists(ck + ".wal")
    before = _pod_placements(svc_b)
    svc_b = SchedulerService.restore(
        api_b, ck, backend=make_backend("jax"), backend_name="jax",
        device_resident=True,
    )
    assert svc_b.restored_warm
    assert _pod_placements(svc_b) == before
    # RNG state is process-global and both timelines share it; park the
    # survivor's stream so each continuation draws what it would have
    _drive(svc_b, api_b, 3, "q", complete=False)
    seed_rng(1)  # not the real stream; what matters is both draw alike
    # replay the SAME continuation on the uninterrupted timeline: the
    # task uids drawn differ (global RNG), so compare by pod id
    _drive(svc_a, api_a, 3, "q", complete=False)
    pa, pb = _pod_placements(svc_a), _pod_placements(svc_b)
    assert set(pa) == set(pb)
    # solve cost class of the restored timeline's first round
    sol = svc_b.scheduler.solver
    assert sol._started, "restored solver fell back to the cold export"
    jaxs = find_jax_solver(sol.backend)
    assert jaxs is not None
    assert jaxs.last_warm_scope in ("warm", "fresh"), jaxs.last_warm_scope
    assert sol.resident.last_upload_kind == "delta"
    assert sol.resident.last_plan_kind in ("delta", "clean")
    # and the mirror is still bit-exact after the continuation
    sol.resident.parity_check()
    sol.resident.plan_parity_check()


def test_corrupted_warm_manifest_falls_back_cold(tmp_path):
    api, svc, ck = _checkpoint(tmp_path)
    corrupt_wal_file(ck + ".wal", "wal_torn", np.random.default_rng(0))
    before = dict(svc.scheduler.task_bindings)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        svc2 = SchedulerService.restore(
            api, ck, backend=make_backend("jax"), backend_name="jax",
            device_resident=True,
        )
    assert not svc2.restored_warm
    assert any("falling back to cold event replay" in str(w.message) for w in caught)
    assert dict(svc2.scheduler.task_bindings) == before
    # the cold-replayed service still serves rounds
    _drive(svc2, api, 2, "r", complete=False)


def test_stale_warm_manifest_from_prior_checkpoint_rejected(tmp_path):
    """A .wal left behind by an EARLIER checkpoint at the same path
    (e.g. the later save's manifest write failed) must not be paired
    with the newer sidecar: the job_id binding detects it and restore
    falls back cold instead of serving mixed-generation state."""
    api, svc, ck = _checkpoint(tmp_path)
    stale = open(ck + ".wal", "rb").read()
    # a "newer" checkpoint whose manifest write failed: different
    # service generation (job_id), old manifest still on disk
    svc.job_id += 1
    svc.save_checkpoint(ck)
    with open(ck + ".wal", "wb") as f:
        f.write(stale)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        svc2 = SchedulerService.restore(
            api, ck, backend=make_backend("jax"), backend_name="jax",
            device_resident=True,
        )
    assert not svc2.restored_warm
    assert any("different checkpoint" in str(w.message) for w in caught)


def test_failed_manifest_write_removes_stale_wal(tmp_path, monkeypatch):
    api, svc, ck = _checkpoint(tmp_path)
    assert os.path.exists(ck + ".wal")
    import ksched_tpu.runtime.checkpoint as ckpt

    def boom(*a, **k):
        raise RuntimeError("unpicklable cost model")

    monkeypatch.setattr(ckpt, "save_warm_manifest", boom)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        svc.save_checkpoint(ck)
    assert any("warm manifest not written" in str(w.message) for w in caught)
    assert not os.path.exists(ck + ".wal")  # the stale manifest is gone


def test_missing_warm_manifest_restores_cold(tmp_path):
    api, svc, ck = _checkpoint(tmp_path)
    os.remove(ck + ".wal")
    svc2 = SchedulerService.restore(
        api, ck, backend=make_backend("jax"), backend_name="jax",
        device_resident=True,
    )
    assert not svc2.restored_warm
    _drive(svc2, api, 2, "r", complete=False)


# ---------------------------------------------------------------------------
# restore across pow2 growth
# ---------------------------------------------------------------------------


def test_restore_across_growth(tmp_path):
    """Save at one n_cap/m_cap bucket, mutate PAST a pow2 boundary,
    kill, restore: the warm geometry and slot-plan regions must come
    back consistent (and keep absorbing churn)."""
    seed_rng(2)
    api = SyntheticClusterAPI()
    svc = _service(api, machines=3, slots=8)
    _drive(svc, api, 3, "p")
    st = svc.scheduler.solver.state
    caps0 = (st.n_cap, st.m_cap)
    # mutate past the arc/node pow2 boundary (a pod burst), then kill
    grew = 0
    while (st.n_cap, st.m_cap) == caps0:
        _drive(svc, api, 1, f"grow{grew}", pods_per_round=16, complete=False)
        grew += 1
        assert grew < 32, "workload never crossed the pow2 bucket"
    ck = str(tmp_path / "svc.ckpt")
    svc.save_checkpoint(ck)
    svc2 = SchedulerService.restore(
        api, ck, backend=make_backend("jax"), backend_name="jax",
        device_resident=True,
    )
    assert svc2.restored_warm
    st2 = svc2.scheduler.solver.state
    assert (st2.n_cap, st2.m_cap) == (st.n_cap, st.m_cap)
    # slot-plan regions and the device mirror come back consistent
    st2.plan.check_invariants()
    svc2.scheduler.solver.resident.parity_check()
    svc2.scheduler.solver.resident.plan_parity_check()
    # the restored bucket keeps absorbing churn delta-sized
    _drive(svc2, api, 2, "post", complete=False)
    assert svc2.scheduler.solver.resident.last_upload_kind == "delta"
    st2.plan.check_invariants()


def test_restore_then_growth_stays_consistent(tmp_path):
    """The mirror restored at a small bucket must survive growth AFTER
    the restore (node+arc rebuild paths on a restored state)."""
    seed_rng(3)
    api = SyntheticClusterAPI()
    svc = _service(api, machines=3, slots=8)
    _drive(svc, api, 3, "p")
    ck = str(tmp_path / "svc.ckpt")
    svc.save_checkpoint(ck)
    svc2 = SchedulerService.restore(
        api, ck, backend=make_backend("jax"), backend_name="jax",
        device_resident=True,
    )
    st2 = svc2.scheduler.solver.state
    caps0 = (st2.n_cap, st2.m_cap)
    grew = 0
    while (st2.n_cap, st2.m_cap) == caps0:
        _drive(svc2, api, 1, f"g{grew}", pods_per_round=16, complete=False)
        grew += 1
        assert grew < 32
    st2.plan.check_invariants()
    svc2.scheduler.solver.resident.parity_check()
    svc2.scheduler.solver.resident.plan_parity_check()


# ---------------------------------------------------------------------------
# per-tenant checkpointing
# ---------------------------------------------------------------------------


def test_tenant_checkpoint_writes_manifest(tmp_path):
    from ksched_tpu.obs.metrics import Registry
    from ksched_tpu.tenancy import MultiTenantService

    mts = MultiTenantService(registry=Registry(), pipeline=False)
    try:
        cell = mts.add_tenant("t0", machines=2, slots=4, seed=5, audit_every=2)
        for i in range(4):
            cell.api.submit_pod(PodEvent(pod_id=f"t0_p{i}"))
        for r in range(3):
            mts.run_round(now=float(r))
        mts.drain()
        ck = str(tmp_path / "t0.ckpt")
        mts.save_tenant_checkpoint("t0", ck)
        assert os.path.exists(ck) and os.path.exists(ck + ".sched")
        with open(ck, "rb") as f:
            side = pickle.load(f)
        assert side["tenant"] == "t0"
        assert side["audit_every"] == 2
        account = mts.manager.accounts["t0"]
        assert account.extra["checkpoint"] == ck
        assert "quarantine_streak" in account.extra
    finally:
        mts.close()
