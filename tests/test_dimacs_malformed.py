"""Malformed DIMACS input must fail loudly with the offending line —
never decode into a mis-placed flow (ISSUE 3 satellite).

The text codec is the interop seam with external solvers
(graph/dimacs.py): a truncated pipe or a corrupted line that parsed
"successfully" would feed garbage arc/flow data straight into the
device arrays indexed by these ids.
"""

import io

import pytest

from ksched_tpu.graph.dimacs import export, parse_flow, parse_graph
from ksched_tpu.graph.flowgraph import FlowGraph

GOOD = """\
c a well-formed instance
p min 4 3
n 1 2
n 4 -2
a 1 2 0 2 5
a 2 4 0 2 0
a 1 4 0 1 9
c EOI
"""


def test_well_formed_parses():
    header, nodes, arcs = parse_graph(io.StringIO(GOOD))
    assert header == (4, 3)
    assert len(nodes) == 2 and len(arcs) == 3
    assert arcs[0] == (1, 2, 0, 2, 5)


def test_roundtrip_with_real_graph():
    g = FlowGraph()
    a, b = g.add_node(), g.add_node()
    arc = g.add_arc(a, b)
    arc.cap_upper = 3
    arc.cost = 7
    a.excess, b.excess = 1, -1
    buf = io.StringIO()
    export(g, buf)
    header, nodes, arcs = parse_graph(io.StringIO(buf.getvalue()))
    assert header == (2, 1)
    assert (a.id, b.id, 0, 3, 7) in arcs


@pytest.mark.parametrize("bad_line,match", [
    ("a 1 2 0 2", "truncated arc line"),
    ("a 1 2", "truncated arc line"),
    ("a 1 2 0 -2 5", "negative capacity"),
    ("a 1 2 -1 2 5", "negative capacity"),
    ("a 1 2 3 2 5", "below lower bound"),
    ("a 1 9 0 2 5", "out of range"),
    ("a 9 2 0 2 5", "out of range"),
    ("a -3 2 0 2 5", "out of range"),
    ("a 1 2 0 x 5", "non-integer"),
    ("n 1", "truncated node line"),
    ("n 9 2", "out of range"),
    ("n -1 2", "out of range"),
    ("q 1 2", "unknown record type"),
])
def test_malformed_lines_raise(bad_line, match):
    text = GOOD.replace("a 1 4 0 1 9", bad_line)
    with pytest.raises(ValueError, match=match):
        parse_graph(io.StringIO(text))


def test_records_before_header_raise():
    with pytest.raises(ValueError, match="before `p min` header"):
        parse_graph(io.StringIO("n 1 2\np min 4 3\n"))
    with pytest.raises(ValueError, match="before `p min` header"):
        parse_graph(io.StringIO("a 1 2 0 2 5\np min 4 3\n"))


def test_malformed_header_raises():
    with pytest.raises(ValueError, match="malformed header"):
        parse_graph(io.StringIO("p max 4 3\n"))
    with pytest.raises(ValueError, match="malformed header"):
        parse_graph(io.StringIO("p min 4\n"))
    with pytest.raises(ValueError, match="negative extent"):
        parse_graph(io.StringIO("p min -4 3\n"))


def test_stream_without_terminator_raises():
    # a cut pipe dropping the tail (incl. `c EOI`) must not decode as
    # a partial graph
    with pytest.raises(ValueError, match="no 'c EOI' terminator"):
        parse_graph(io.StringIO(GOOD.replace("c EOI\n", "")))


def test_stream_with_missing_arcs_raises():
    truncated = GOOD.replace("a 1 4 0 1 9\n", "")  # EOI intact, one arc lost
    with pytest.raises(ValueError, match="declares 3 arcs, got 2"):
        parse_graph(io.StringIO(truncated))


def test_error_names_the_line_number():
    text = "p min 4 3\nn 1 2\na 1 2 0 2\n"
    with pytest.raises(ValueError, match="line 3"):
        parse_graph(io.StringIO(text))


# -- flow responses ----------------------------------------------------------


def test_flow_response_truncated_line_raises():
    with pytest.raises(ValueError, match="truncated flow line"):
        parse_flow(io.StringIO("f 1 2\nc EOI\n"))


def test_flow_response_non_integer_raises():
    with pytest.raises(ValueError, match="non-integer"):
        parse_flow(io.StringIO("f 1 2 x\nc EOI\n"))


def test_flow_response_trailing_fields_raise():
    # `f 1 2 3 5` for an intended flow 35 must not decode as flow 3
    with pytest.raises(ValueError, match="trailing fields"):
        parse_flow(io.StringIO("f 1 2 3 5\nc EOI\n"))


def test_flow_response_negative_flow_raises():
    with pytest.raises(ValueError, match="negative flow"):
        parse_flow(io.StringIO("f 1 2 -1\nc EOI\n"))


def test_flow_response_missing_terminator_still_raises():
    # pre-existing contract (a dead solver must not decode partially)
    with pytest.raises(ValueError, match="truncated"):
        parse_flow(io.StringIO("f 1 2 1\n"))
