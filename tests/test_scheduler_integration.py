"""End-to-end scheduling-iteration tests.

Reproduces the reference's only real test (flowscheduler/
schedule_iteration_test.go:16-91): 2 machines × 1 core × 1 PU × 1 slot,
3 single-task jobs, then a 2-task job-add event, then 2 task completions,
across 5 scheduling rounds — but with programmatic assertions the
reference lacks (it only printed).
"""

from ksched_tpu.data import TaskState
from ksched_tpu.drivers import add_job, build_cluster
from ksched_tpu.scheduler import FlowScheduler


def running_tasks(task_map):
    return [td for td in task_map.unsafe_get().values() if td.state == TaskState.RUNNING]


def test_multi_schedule_iteration():
    scheduler, resource_map, job_map, task_map, root = build_cluster(
        num_machines=2, num_cores=1, pus_per_core=1, max_tasks_per_pu=1
    )

    # 3 jobs x 1 task; only 2 PUs exist -> 2 placed, 1 unscheduled.
    for _ in range(3):
        add_job(scheduler, job_map, task_map, num_tasks=1)
    num_scheduled, deltas = scheduler.schedule_all_jobs()
    assert num_scheduled == 2
    assert len(scheduler.get_task_bindings()) == 2
    assert len(running_tasks(task_map)) == 2

    # New job with 2 tasks; no capacity -> nothing new scheduled.
    add_job(scheduler, job_map, task_map, num_tasks=2)
    num_scheduled, _ = scheduler.schedule_all_jobs()
    assert num_scheduled == 0
    assert len(scheduler.get_task_bindings()) == 2

    # Complete 2 running tasks -> 2 slots free.
    done = running_tasks(task_map)[:2]
    for td in done:
        scheduler.handle_task_completion(td)
    assert len(scheduler.get_task_bindings()) == 0

    # Third iteration: resource stats still carry the completed tasks
    # (current_running_tasks is only reconciled during a round's
    # preempt-scan — reference graph_manager.go:327-337), so nothing is
    # placed yet. This one-round lag is reference behavior.
    num_scheduled, _ = scheduler.schedule_all_jobs()
    assert num_scheduled == 0
    assert len(scheduler.get_task_bindings()) == 0

    # Fourth iteration: stats are fresh -> 2 of the 3 waiting tasks land.
    num_scheduled, _ = scheduler.schedule_all_jobs()
    assert num_scheduled == 2
    assert len(scheduler.get_task_bindings()) == 2

    # Fifth iteration: steady state, no churn.
    num_scheduled, _ = scheduler.schedule_all_jobs()
    assert num_scheduled == 0
    assert len(scheduler.get_task_bindings()) == 2

    # Supply conservation: sink excess equals -(live task nodes).
    live_tasks = len(scheduler.gm.task_to_node)
    assert scheduler.gm.sink_node.excess == -live_tasks


def test_all_tasks_fit():
    scheduler, resource_map, job_map, task_map, root = build_cluster(
        num_machines=4, num_cores=2, pus_per_core=1, max_tasks_per_pu=1
    )
    add_job(scheduler, job_map, task_map, num_tasks=5)
    num_scheduled, _ = scheduler.schedule_all_jobs()
    assert num_scheduled == 5
    # each task on a distinct PU (1 slot each)
    bindings = scheduler.get_task_bindings()
    assert len(set(bindings.values())) == 5


def test_machine_deregistration_evicts_and_reschedules():
    scheduler, resource_map, job_map, task_map, root = build_cluster(
        num_machines=2, num_cores=1, pus_per_core=1, max_tasks_per_pu=1
    )
    add_job(scheduler, job_map, task_map, num_tasks=2)
    num_scheduled, _ = scheduler.schedule_all_jobs()
    assert num_scheduled == 2

    # Tear down one machine; its task is evicted and becomes runnable.
    machine_rtnd = root.children[0]
    scheduler.deregister_resource(machine_rtnd)
    assert len(scheduler.get_task_bindings()) == 1

    # Next round: evicted task cannot fit (other PU busy) -> unscheduled.
    num_scheduled, _ = scheduler.schedule_all_jobs()
    assert len(scheduler.get_task_bindings()) == 1

    # Complete the surviving task; evicted one takes its slot (after the
    # one-round stats lag, see test_multi_schedule_iteration).
    td = running_tasks(task_map)[0]
    scheduler.handle_task_completion(td)
    scheduler.schedule_all_jobs()  # stats-reconciliation round
    num_scheduled, _ = scheduler.schedule_all_jobs()
    assert num_scheduled == 1
    assert len(scheduler.get_task_bindings()) == 1


def test_task_failure_removes_node():
    scheduler, resource_map, job_map, task_map, root = build_cluster(
        num_machines=1, num_cores=1, pus_per_core=1, max_tasks_per_pu=2
    )
    add_job(scheduler, job_map, task_map, num_tasks=2)
    num_scheduled, _ = scheduler.schedule_all_jobs()
    assert num_scheduled == 2
    td = running_tasks(task_map)[0]
    scheduler.handle_task_failure(td)
    assert td.state == TaskState.FAILED
    assert td.uid not in scheduler.get_task_bindings()
    assert len(scheduler.gm.task_to_node) == 1
