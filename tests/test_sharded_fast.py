"""Fast (tier-1) sharded coverage on a 2-device virtual mesh.

The original sharded suites (test_sharded_solver / test_sharded_
transport) compile 8-way shard_map programs and are `slow`-marked, so
the default tier-1 wall never exercised shard_map at all. This module
keeps the multi-chip rung inside the wall: small-bucket bit-parity of
the slot-stable sharded solve against the single-chip scan-CSR arm,
delta-sized resident rounds through the per-shard routed plan scatter,
the AutoSolver HBM fitting gate, and ladder degradation off the
sharded rung — all on a 2-device mesh where the compiles stay cheap.
"""

import warnings

import numpy as np
import jax
import pytest
from jax.sharding import Mesh

from test_slot_plan import SCRIPT, _build_graph, _churn_round

from ksched_tpu.graph.device_export import (
    DeviceGraphState,
    DeviceResidentState,
)
from ksched_tpu.parallel.sharded_solver import (
    ShardedJaxSolver,
    csr_working_set_bytes,
    scan_csr_fits_hbm,
    sharded_entry_extent,
    sharded_fits_hbm,
    sharded_shard_bytes,
)
from ksched_tpu.runtime.integrity import FP_PLAN_ARRAYS, host_fingerprint
from ksched_tpu.solver.jax_solver import JaxSolver


@pytest.fixture(scope="module")
def mesh2():
    devs = jax.devices()
    assert len(devs) >= 2, "conftest should provide 8 virtual CPU devices"
    return Mesh(np.array(devs[:2]), ("x",))


def _drive(make_solver, *, resident=False, sharded_resident_mesh=None,
           rounds=6, tasks=24, machines=5):
    g, _sink, machine_ids, task_ids = _build_graph(tasks, machines)
    st = DeviceGraphState()
    st.full_build(g)
    res = None
    if resident:
        res = DeviceResidentState(st)
        if sharded_resident_mesh is not None:
            res.enable_sharded_plan(sharded_resident_mesh, "x")
    solver = make_solver()
    rng = np.random.default_rng(7)
    out, kinds = [], {}
    for rnd in range(rounds + 1):
        if rnd:
            _churn_round(
                st, SCRIPT[(rnd - 1) % len(SCRIPT)], task_ids, machine_ids, rng
            )
        prob = res.refresh() if resident else st.problem()
        r = solver.solve(prob)
        if resident:
            kinds[res.last_plan_kind] = kinds.get(res.last_plan_kind, 0) + 1
        out.append(
            (np.asarray(r.flow).copy(), solver.last_supersteps, r.objective)
        )
        if not st.plan.needs_rebuild:
            st.plan.check_invariants()
    return out, kinds, st, res, solver


def _assert_rounds_equal(a, b):
    for rnd, ((fa, sa, oa), (fb, sb, ob)) in enumerate(zip(a, b)):
        assert oa == ob, (rnd, oa, ob)
        assert np.array_equal(fa, fb), (rnd, "flows diverged")
        assert sa == sb, (rnd, "superstep counts diverged", sa, sb)


def test_slot_stable_parity_with_single_chip(mesh2):
    """Flows, superstep counts, AND objectives bit-identical between
    the single-chip slot-stable solve and the 2-device sharded solve
    over a churn script (cost/rewire/recycle/supply rounds)."""
    a, _, _, _, _ = _drive(lambda: JaxSolver(slot_stable=True, restart_budget=64))
    b, _, _, _, solver = _drive(lambda: ShardedJaxSolver(mesh2))
    _assert_rounds_equal(a, b)
    assert solver.last_path == "slot_stable"


def test_resident_sharded_rounds_are_delta_sized(mesh2):
    """The device-resident sharded arm: after the first layout upload
    every churn round syncs the plan as per-shard routed records
    (kind "delta" / "clean"), the scatter-maintained [D, Es] tensors
    equal the host truth bit-for-bit, and the psum'd per-shard
    fingerprints equal the host twins."""
    a, _, _, _, _ = _drive(
        lambda: JaxSolver(slot_stable=True, restart_budget=64), resident=True
    )
    b, kinds, st, res, _ = _drive(
        lambda: ShardedJaxSolver(mesh2), resident=True,
        sharded_resident_mesh=mesh2,
    )
    _assert_rounds_equal(a, b)
    assert kinds.get("rebuild", 0) == 1, kinds  # the initial layout only
    assert kinds.get("delta", 0) >= 3, kinds
    res.parity_check()
    res.plan_parity_check()
    fps = res.plan_fingerprints()
    for i, name in enumerate(FP_PLAN_ARRAYS):
        assert int(fps[i]) == host_fingerprint(getattr(st.plan, name)), name
    # entry tensors really are stacked per-shard tables
    assert np.asarray(res.d_p_arc).shape == (2, st.plan.block_extent)


def test_single_chip_solver_consumes_sharded_mirror(mesh2):
    """The degradation ladder's jax rung (and AutoSolver's too-big-
    even-per-shard CSR fallback) must be able to solve a problem whose
    resident mirror is in SHARDED plan mode: the [D, Es] entry tensors
    flatten losslessly back to the single-chip layout. Regression for
    the dead-middle-rung bug (ValueError on 2-D d_plan) the r15 review
    caught."""
    a, _, _, _, _ = _drive(
        lambda: JaxSolver(slot_stable=True, restart_budget=64),
        resident=True,
    )
    b, _, _, _, solver = _drive(
        lambda: JaxSolver(slot_stable=True, restart_budget=64),
        resident=True, sharded_resident_mesh=mesh2,
    )
    _assert_rounds_equal(a, b)


def test_autosolver_escalates_by_fitting_gate(mesh2):
    """dense -> mega -> csr -> sharded: with a budget between the
    per-shard and single-chip working sets the general-graph solve
    escalates to the sharded rung and stays bit-identical to the CSR
    arm; with the default budget this small bucket never escalates."""
    from ksched_tpu.solver.graph_collapse import AutoSolver

    g, _sink, _m, _t = _build_graph(24, 5)
    st = DeviceGraphState()
    st.full_build(g)
    prob = st.problem()
    n_cap, m_cap = prob.num_nodes, len(prob.src)

    auto = AutoSolver(JaxSolver(slot_stable=True))
    base = auto.solve(prob)
    assert auto.last_path == "csr"  # not collapsible, no sharded attached

    budget = (
        sharded_shard_bytes(n_cap, m_cap, 2)
        + csr_working_set_bytes(n_cap, m_cap)
    ) // 2
    made = []

    def factory():
        made.append(1)
        return ShardedJaxSolver(mesh2)

    auto_sh = AutoSolver(
        JaxSolver(slot_stable=True), sharded=factory,
        hbm_budget_bytes=budget,
    )
    res = auto_sh.solve(st.problem())
    assert auto_sh.last_path == "sharded"
    assert made == [1]  # factory resolved lazily, exactly once
    assert res.objective == base.objective
    assert np.array_equal(np.asarray(res.flow), np.asarray(base.flow))

    auto_default = AutoSolver(JaxSolver(slot_stable=True), sharded=factory)
    auto_default.solve(st.problem())
    assert auto_default.last_path == "csr"  # default budget: fits one chip


def test_sharded_layout_tolerates_empty_shards():
    """ceil-division ownership ranges leave trailing shards EMPTY when
    the shard count approaches (or exceeds) the node bucket — e.g. the
    minimum n_cap=16 bucket on a 5-way mesh, or make_backend("sharded")
    building the mesh over all devices for a tiny problem. An empty
    shard's block is one dead slot plus tail; the rebuild must not
    crash and the invariants must hold. Regression for the r15
    review's empty-shard broadcast crash."""
    g, _sink, _m, _t = _build_graph(8, 3)
    st = DeviceGraphState()
    st.full_build(g)
    for d in (5, 7, st.n_cap + 3):
        st.plan.invalidate()
        st.plan.enable_sharding(d)
        st.plan.ensure_built()
        st.plan.check_invariants()
    # and it still solves (single-chip consumer over the odd layout)
    r = JaxSolver(slot_stable=True).solve(st.problem())
    st.plan.invalidate()
    st.plan.enable_sharding(1)
    st.plan.ensure_built()
    r2 = JaxSolver(slot_stable=True).solve(st.problem())
    assert r.objective == r2.objective


def test_fitting_gate_arithmetic():
    """The estimators mirror mega_fits_vmem's shape: monotone in the
    graph bucket, per-shard strictly below single-chip for D > 1, and
    a graph that fits nobody escalates nowhere (falls back to CSR)."""
    assert csr_working_set_bytes(1 << 10, 1 << 12) < csr_working_set_bytes(
        1 << 10, 1 << 14
    )
    n, m = 1 << 17, 1 << 22
    assert sharded_shard_bytes(n, m, 8) < csr_working_set_bytes(n, m)
    assert scan_csr_fits_hbm(64, 256)  # tiny bucket, default budget
    assert not scan_csr_fits_hbm(n, m, budget_bytes=1 << 20)
    assert not sharded_fits_hbm(n, m, 8, budget_bytes=1 << 20)
    assert sharded_entry_extent(1 << 10, 4) == (1 << 11) // 4


def test_ladder_degrades_sharded_to_jax(mesh2):
    """Chaos containment on the sharded rung: a failing sharded solve
    degrades through the ladder (sharded -> jax -> cpu_ref) and the
    round still lands with the same placements."""
    from ksched_tpu.runtime.degrade import build_degradation_ladder

    class FailingOnce(ShardedJaxSolver):
        fails = 0

        def solve(self, problem):
            if FailingOnce.fails == 0:
                FailingOnce.fails += 1
                raise RuntimeError("injected sharded-rung failure")
            return super().solve(problem)

    g, _sink, _m, _t = _build_graph(16, 4)
    st = DeviceGraphState()
    st.full_build(g)
    ladder = build_degradation_ladder(FailingOnce(mesh2), "sharded")
    assert ladder.rung_names() == ["sharded", "jax", "cpu_ref"]
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        r1 = ladder.solve(st.problem())
    assert ladder.last_rung_name == "jax"
    r2 = ladder.solve(st.problem())
    assert ladder.last_rung_name == "sharded"
    assert r1.objective == r2.objective


#: pinned telemetry-OFF hash of the 2-device slot-stable sharded solve
#: at bucket (20, 100) — the "no cost when off" contract extended to
#: the multi-chip rung (the SOLTEL_OFF_BASELINE_HASHES convention of
#: tests/test_static_analysis.py: normalized jaxpr hash, jax 0.4.37;
#: re-capture in the same commit as any jax upgrade)
SHARDED_SLOT_OFF_HASH_2DEV = "c08b45189b949d42"


def test_sharded_slot_telemetry_off_hash_pinned():
    from ksched_tpu.analysis import jaxpr_contracts as jc

    got = jc.jaxpr_hash(jc.trace_sharded_slot(20, 100, num_devices=2))
    assert got == SHARDED_SLOT_OFF_HASH_2DEV, (
        "the slot-stable sharded telemetry-OFF trace drifted — "
        "disabled solver telemetry must cost zero traced ops, and an "
        "intentional program change must re-pin this hash "
        f"(got {got})"
    )


def test_compat_fallback_warning_fires_once():
    """The shard_map fallback is no longer silent: exactly one
    RuntimeWarning naming the jax version and check_rep=False, then
    quiet."""
    from ksched_tpu.parallel import _compat

    if not _compat.IS_EXPERIMENTAL:
        pytest.skip("native jax.shard_map: no fallback in play")
    old = _compat._WARNED
    try:
        _compat._WARNED = False
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            _compat.warn_if_fallback()
            _compat.warn_if_fallback()
        msgs = [w for w in caught if issubclass(w.category, RuntimeWarning)]
        assert len(msgs) == 1
        text = str(msgs[0].message)
        assert jax.__version__ in text and "check_rep=False" in text
    finally:
        _compat._WARNED = old
