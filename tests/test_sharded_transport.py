"""Sharded layered transport (parallel/sharded_transport.py) on the
virtual 8-device mesh: bit-exact parity with the single-device solve,
and the solve_layered seam against the SSP oracle."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh

# Compiling ~30 while_loop-heavy shard_map programs for the 8-device
# CPU mesh costs ~9 min — past the budgeted tier-1 wall on its own —
# so this parity file runs in the full/slow suite
# (`pytest tests/` without -m 'not slow'). The sharded CSR solver's
# tier-1 coverage (test_sharded_solver.py) stays in the fast set.
pytestmark = pytest.mark.slow

from ksched_tpu.parallel.sharded_transport import (
    ShardedLayeredSolver,
    sharded_transport_solve,
)
from ksched_tpu.scheduler.bulk import BulkCluster
from ksched_tpu.solver.cpu_ref import ReferenceSolver
from ksched_tpu.solver.layered import LayeredProblem, _transport_loop


def _mesh(n=8):
    devs = jax.devices()
    assert len(devs) >= n
    return Mesh(np.array(devs[:n]), ("x",))


def _instance(seed, C, M, Mp):
    rng = np.random.default_rng(seed)
    n_scale = 2048
    w = rng.integers(-30, 30, (C, M)).astype(np.int64)
    wS = np.zeros((C, Mp), np.int32)
    wS[:, :M] = w * n_scale
    supply = rng.integers(0, 60, C).astype(np.int32)
    col_cap = np.zeros(Mp, np.int32)
    col_cap[:M] = rng.integers(0, 25, M).astype(np.int32)
    col_cap[-1] = supply.sum()
    return wS, supply, col_cap


@pytest.mark.parametrize("seed", range(6))
@pytest.mark.parametrize("C,M,Mp", [(2, 30, 1024), (4, 200, 1024), (3, 900, 2048)])
def test_sharded_matches_single_device_exactly(seed, C, M, Mp):
    wS, supply, col_cap = _instance(seed, C, M, Mp)
    eps0 = np.int32(max(1, np.abs(wS).max()))
    mesh = _mesh()
    y_sh, steps_sh, conv_sh = sharded_transport_solve(
        mesh, jnp.asarray(wS), jnp.asarray(supply), jnp.asarray(col_cap),
        jnp.asarray(eps0),
    )
    U = jnp.minimum(jnp.asarray(supply)[:, None], jnp.asarray(col_cap)[None, :])
    y_1, _z, _pm, steps_1, conv_1 = _transport_loop(
        jnp.asarray(wS), U, jnp.asarray(supply), jnp.asarray(col_cap),
        jnp.asarray(eps0), 8, 1 << 17,
    )
    assert bool(conv_sh) and bool(conv_1)
    assert int(steps_sh) == int(steps_1)
    np.testing.assert_array_equal(np.asarray(y_sh), np.asarray(y_1))


@pytest.mark.parametrize("seed", [0, 4])
def test_sharded_solver_seam_matches_oracle(seed):
    """Through BulkCluster's solve_layered seam: objective parity with
    the exact SSP oracle on the 8-device mesh."""
    rng = np.random.default_rng(seed)
    C, M = 3, 12
    cost = rng.integers(0, 20, (C, M)).astype(np.int32)
    solver = ShardedLayeredSolver(_mesh())
    cluster = BulkCluster(
        num_machines=M, pus_per_machine=2, slots_per_pu=2, num_jobs=3,
        backend=solver, task_capacity=256, num_task_classes=C,
        class_cost_fn=lambda cl: cost, unsched_cost=25,
    )
    n = int(rng.integers(40, 120))
    cluster.add_tasks(
        n, rng.integers(0, 3, n).astype(np.int32), rng.integers(0, C, n).astype(np.int32)
    )
    cluster._refresh_capacities()
    want = ReferenceSolver().solve(cluster._problem()).objective
    unplaced = np.nonzero(cluster.task_live & (cluster.task_pu < 0))[0]
    supply = np.bincount(cluster.task_class[unplaced], minlength=C).astype(np.int32)
    pu_free = cluster.S - cluster.pu_running
    machine_free = pu_free.reshape(cluster.M, cluster.P).sum(axis=1)
    res = solver.solve_layered(
        LayeredProblem(
            supply=supply,
            col_cap=machine_free.astype(np.int32),
            cost_cm=cost,
            unsched_cost=25,
            ec_cost=cluster.ec_cost,
        )
    )
    assert res.objective == want
    assert res.supersteps > 0  # the mesh solve actually ran


def test_degenerate_and_single_class_use_closed_form():
    solver = ShardedLayeredSolver(_mesh())
    res = solver.solve_layered(
        LayeredProblem(
            supply=np.asarray([7, 7], np.int32),
            col_cap=np.full(6, 2, np.int32),
            cost_cm=np.zeros((2, 6), np.int32),
            unsched_cost=25, ec_cost=2,
        )
    )
    assert res.supersteps == 0  # closed form, no mesh solve
    assert res.num_unsched == 2  # 14 supply into 12 slots


def test_sharded_superstep_parity_with_single_device():
    """The dryrun_multichip instance shape (3 classes x 16 machines):
    the mesh solve must take exactly as many supersteps as the
    single-device solve. n_scale derives from the REAL node count
    (pad_geometry), not the padded width, so the 128*devices column
    padding the mesh requires cannot inflate the eps schedule; padded
    columns carry no arcs and are inert in every superstep."""
    from ksched_tpu.solver.layered import LayeredTransportSolver

    rng = np.random.default_rng(1)
    C, M = 3, 16
    lp = LayeredProblem(
        supply=rng.integers(5, 20, C).astype(np.int32),
        col_cap=rng.integers(0, 4, M).astype(np.int32),
        cost_cm=rng.integers(0, 20, (C, M)).astype(np.int32),
        unsched_cost=25,
        ec_cost=2,
    )
    sharded = ShardedLayeredSolver(_mesh())
    single = LayeredTransportSolver()
    res_sh = sharded.solve_layered(lp)
    res_1 = single.solve_layered(lp)
    assert res_sh.objective == res_1.objective
    np.testing.assert_array_equal(res_sh.y, res_1.y)
    assert res_sh.supersteps == res_1.supersteps
    # and the count is the real-node-count, oversubscription-aware
    # schedule (choose_eps0): a couple hundred supersteps on this toy,
    # not the ~1.5k that n_scale-from-Mp + a short eps0 start produced
    # (the MULTICHIP_r01 anomaly; see docs/NOTES.md).
    assert 0 < res_sh.supersteps < 500


@pytest.mark.parametrize("seed", range(4))
@pytest.mark.parametrize("C,M,Mp", [(2, 30, 1024), (4, 200, 1024)])
def test_sharded_tiered_matches_single_device_exactly(seed, C, M, Mp):
    """The sharded TIERED (preemption) solve must be bit-identical to
    the single-device tiered loop — flows and superstep counts — on
    the virtual 8-device mesh: multi-chip preemption rounds carry the
    same keep-arcs semantics as single-chip ones."""
    from ksched_tpu.parallel.sharded_transport import (
        sharded_transport_solve_tiered,
    )
    from ksched_tpu.solver.layered import _transport_loop_tiered

    wS, supply, col_cap = _instance(seed, C, M, Mp)
    rng = np.random.default_rng(seed + 31)
    n_scale = 2048
    discount = int(rng.integers(1, 10)) * n_scale
    wHi = wS
    wLo = wS.copy()
    wLo[:, :M] -= discount
    R = rng.integers(0, 5, (C, Mp)).astype(np.int32)
    R[:, -1] = 0
    eps0 = np.int32(max(1, np.abs(wHi).max()))
    mesh = _mesh()
    RJ = jnp.minimum(
        jnp.asarray(R),
        jnp.minimum(jnp.asarray(supply)[:, None], jnp.asarray(col_cap)[None, :]),
    )
    U = jnp.minimum(jnp.asarray(supply)[:, None], jnp.asarray(col_cap)[None, :])
    # both refinement regimes: refine 0 (the host bit-parity
    # convention) and refine 8 (the production preemption setting)
    for refine in (0, 8):
        y_sh, steps_sh, conv_sh = sharded_transport_solve_tiered(
            mesh, jnp.asarray(wLo), jnp.asarray(wHi), jnp.asarray(R),
            jnp.asarray(supply), jnp.asarray(col_cap), jnp.asarray(eps0),
            refine_waves=refine,
        )
        y_1, _z, _pm, steps_1, conv_1 = _transport_loop_tiered(
            jnp.asarray(wLo), jnp.asarray(wHi), RJ, U,
            jnp.asarray(supply), jnp.asarray(col_cap),
            jnp.asarray(eps0), 8, 1 << 17, refine_waves=refine,
        )
        assert bool(conv_sh) and bool(conv_1), refine
        assert int(steps_sh) == int(steps_1), refine
        np.testing.assert_array_equal(np.asarray(y_sh), np.asarray(y_1))
