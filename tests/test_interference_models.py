"""CoCo / Whare-Map cost models: vectorized matrices, object-layer
parity, and end-to-end class-aware bulk scheduling."""

import numpy as np


from ksched_tpu.costmodels import (
    CLASS_ECS,
    CocoCostModel,
    WhareMapCostModel,
    class_ec,
    coco_cost_matrix,
    ec_class,
    whare_cost_matrix,
)
from ksched_tpu.costmodels.coco import INTERFERENCE, MAX_COST
from ksched_tpu.costmodels.whare import PSI_PRIOR
from ksched_tpu.data import TaskType
from ksched_tpu.scheduler.bulk import BulkCluster
from ksched_tpu.solver.cpu_ref import ReferenceSolver


def test_class_ec_roundtrip():
    for t in TaskType:
        ec = class_ec(t)
        assert ec_class(ec) == int(t)
    assert ec_class(12345) is None
    assert len(set(CLASS_ECS)) == 4


def test_coco_cost_matrix_shape_and_policy():
    census = np.zeros((3, 4), np.int64)
    census[0] = [0, 0, 0, 0]  # empty machine
    census[1] = [0, 0, 5, 0]  # devil-heavy machine
    census[2] = [5, 0, 0, 0]  # sheep-only machine
    cost = coco_cost_matrix(census)
    assert cost.shape == (4, 3)
    # empty machine is free
    assert (cost[:, 0] == 0).all()
    # a rabbit avoids the devil machine more than the sheep machine
    rabbit = int(TaskType.RABBIT)
    assert cost[rabbit, 1] > cost[rabbit, 2]
    # a turtle barely cares
    turtle = int(TaskType.TURTLE)
    assert cost[turtle, 1] <= cost[rabbit, 1]
    # clamped
    big = np.full((1, 4), 10_000, np.int64)
    assert coco_cost_matrix(big).max() <= MAX_COST


def test_whare_cost_matrix_idle_bonus():
    census = np.zeros((2, 4), np.int64)
    census[0] = [2, 0, 0, 0]
    census[1] = [2, 0, 0, 0]
    idle = np.array([8, 0])
    slots = np.array([16, 16])
    cost = whare_cost_matrix(census, idle, slots)
    assert cost.shape == (4, 2)
    # same census, more idle slots -> cheaper
    assert (cost[:, 0] <= cost[:, 1]).all()


def test_whare_online_map_update():
    from ksched_tpu.utils import ResourceMap, TaskMap

    m = WhareMapCostModel(ResourceMap(), TaskMap(), set(), 4)
    before = m.psi_int()[1, 2]
    for _ in range(10):
        m.record_runtime(1, 2, 300.0)
    after = m.psi_int()[1, 2]
    assert after > before  # learned that rabbits suffer next to devils


def _bulk(class_cost_fn, C=4, M=4, P=2, S=2, J=2, cap=256):
    return BulkCluster(
        num_machines=M,
        pus_per_machine=P,
        slots_per_pu=S,
        num_jobs=J,
        backend=ReferenceSolver(),
        num_task_classes=C,
        class_cost_fn=class_cost_fn,
        task_capacity=cap,
        unsched_cost=3_000,
    )


def test_bulk_classes_coco_end_to_end():
    def fn(cluster):
        return coco_cost_matrix(cluster.machine_census)

    cluster = _bulk(fn)
    rng = np.random.default_rng(0)
    classes = rng.integers(0, 4, 12).astype(np.int32)
    jobs = rng.integers(0, 2, 12).astype(np.int32)
    cluster.add_tasks(12, jobs, classes)
    r = cluster.round()
    assert len(r.placed_tasks) == 12
    assert r.num_unscheduled == 0
    # census bookkeeping matches placements
    assert cluster.machine_census.sum() == 12
    rows = r.placed_tasks - cluster.task0
    for m in range(cluster.M):
        on_m = (r.placed_pus - cluster.pu0) // cluster.P == m
        for c in range(4):
            expect = int((cluster.task_class[rows[on_m]] == c).sum())
            assert cluster.machine_census[m, c] == expect
    # completion decrements census
    cluster.complete_tasks(r.placed_tasks[:5])
    assert cluster.machine_census.sum() == 7


def test_bulk_coco_devils_spread_from_rabbits():
    """With strong interference costs and ample capacity, the solver
    should not co-locate rabbits onto devil-saturated machines."""

    def fn(cluster):
        return coco_cost_matrix(cluster.machine_census)

    cluster = _bulk(fn, M=2, P=2, S=4, J=1)
    # Fill machine 0 with devils (place 4 devils first).
    devils = cluster.add_tasks(4, np.zeros(4, np.int32), np.full(4, int(TaskType.DEVIL), np.int32))
    r1 = cluster.round()
    assert len(r1.placed_tasks) == 4
    devil_machines = set((r1.placed_pus - cluster.pu0) // cluster.P)
    # Now add rabbits; they should land on the other machine(s) first.
    cluster.add_tasks(4, np.zeros(4, np.int32), np.full(4, int(TaskType.RABBIT), np.int32))
    r2 = cluster.round()
    rabbit_machines = (r2.placed_pus - cluster.pu0) // cluster.P
    census = cluster.machine_census
    # The devil machine should not have received the bulk of the rabbits
    # while an emptier machine existed.
    if len(devil_machines) == 1:
        dm = devil_machines.pop()
        other = 1 - dm
        assert census[other, int(TaskType.RABBIT)] >= census[dm, int(TaskType.RABBIT)]


def test_bulk_whare_prefers_idle_machines():
    def fn(cluster):
        pu_free = cluster.S - cluster.pu_running
        machine_free = pu_free.reshape(cluster.M, cluster.P).sum(axis=1)
        slots = np.full(cluster.M, cluster.P * cluster.S)
        return whare_cost_matrix(cluster.machine_census, machine_free, slots)

    cluster = _bulk(fn, M=3, P=1, S=4, J=1)
    cluster.add_tasks(6, np.zeros(6, np.int32), np.zeros(6, np.int32))
    r = cluster.round()
    assert len(r.placed_tasks) == 6
    # load should spread (no machine takes everything)
    per_machine = np.bincount((r.placed_pus - cluster.pu0) // cluster.P, minlength=3)
    assert per_machine.max() < 6


def test_object_layer_coco_model_costs():
    """CocoCostModel against hand-built resource state."""
    from ksched_tpu.data import (
        ResourceDescriptor,
        ResourceTopologyNodeDescriptor,
        ResourceType,
        TaskDescriptor,
    )
    from ksched_tpu.utils import ResourceMap, ResourceStatus, TaskMap, resource_id_from_string

    rmap, tmap = ResourceMap(), TaskMap()
    model = CocoCostModel(rmap, tmap, set(), 4)

    rd = ResourceDescriptor(uuid="41", type=ResourceType.MACHINE)
    rd.num_slots_below = 8
    rd.num_running_tasks_below = 2
    rd.whare_map_stats.num_devils = 2
    rtnd = ResourceTopologyNodeDescriptor(resource_desc=rd)
    rid = resource_id_from_string("41")
    rmap.insert(rid, ResourceStatus(rd, rtnd, "", 0))
    model.add_machine(rtnd)

    rabbit_ec = CLASS_ECS[int(TaskType.RABBIT)]
    cost, cap = model.equiv_class_to_resource_node(rabbit_ec, rid)
    assert cap == 6
    assert cost == int(INTERFERENCE[int(TaskType.RABBIT), int(TaskType.DEVIL)]) * 2

    td = TaskDescriptor(uid=7, task_type=TaskType.RABBIT)
    tmap.insert(7, td)
    assert model.get_task_equiv_classes(7) == [rabbit_ec]
    # unscheduled escape must dominate any machine cost
    assert model.task_to_unscheduled_agg_cost(7) > MAX_COST
