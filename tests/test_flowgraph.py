"""Flow-graph core unit tests (model: reference flowgraph/graph_test.go)."""

from ksched_tpu.graph import ArcType, FlowGraph, NodeType
from ksched_tpu.graph.changes import ChangeManager, ChangeType
from ksched_tpu.graph.changes import AddNodeChange, ChangeArcChange, NewArcChange, RemoveNodeChange


def test_add_nodes_and_arcs():
    g = FlowGraph()
    a, b = g.add_node(), g.add_node()
    assert a.id == 1 and b.id == 2
    arc = g.add_arc(a, b)
    arc.cap_upper = 5
    assert g.num_nodes == 2
    assert g.num_arcs == 1
    assert g.get_arc(a, b) is arc
    assert g.get_arc(b, a) is None


def test_change_arc_zero_capacity_removes_from_arc_set():
    g = FlowGraph()
    a, b = g.add_node(), g.add_node()
    arc = g.add_arc(a, b)
    g.change_arc(arc, 0, 10, 3)
    assert g.num_arcs == 1
    g.change_arc(arc, 0, 0, 3)
    assert g.num_arcs == 0
    # still attached to endpoints
    assert g.get_arc(a, b) is arc
    # restoring capacity re-registers it (fixes a reference gap)
    g.change_arc(arc, 0, 4, 3)
    assert g.num_arcs == 1


def test_delete_node_removes_arcs_and_recycles_id():
    g = FlowGraph()
    a, b, c = g.add_node(), g.add_node(), g.add_node()
    g.add_arc(a, b)
    g.add_arc(b, c)
    g.add_arc(c, a)
    g.delete_node(b)
    assert g.num_nodes == 2
    assert g.num_arcs == 1  # only c->a survives
    d = g.add_node()
    assert d.id == b.id  # id recycled


def test_change_manager_journals_mutations():
    cm = ChangeManager()
    n1 = cm.add_node(NodeType.SINK, 0, ChangeType.ADD_SINK_NODE, "SINK")
    n2 = cm.add_node(NodeType.UNSCHEDULED_TASK, 1, ChangeType.ADD_TASK_NODE)
    arc = cm.add_arc(n2, n1, 0, 1, 5, ArcType.OTHER, ChangeType.ADD_ARC_TO_UNSCHED)
    changes = cm.get_graph_changes()
    assert len(changes) == 3
    assert isinstance(changes[0], AddNodeChange)
    assert isinstance(changes[2], NewArcChange)

    # idempotent change journals nothing
    cm.change_arc(arc, 0, 1, 5, ChangeType.CHG_ARC_TO_UNSCHED)
    assert len(cm.get_graph_changes()) == 3

    # repeated updates to one arc are merged into the NewArc entry
    cm.change_arc(arc, 0, 1, 7, ChangeType.CHG_ARC_TO_UNSCHED)
    cm.change_arc(arc, 0, 2, 7, ChangeType.CHG_ARC_TO_UNSCHED)
    changes = cm.get_graph_changes()
    assert len(changes) == 3
    merged = changes[2]
    assert isinstance(merged, NewArcChange)
    assert merged.cost == 7 and merged.cap_upper == 2

    cm.reset_changes()
    assert not cm.has_changes

    cm.delete_arc(arc, ChangeType.DEL_ARC_TASK_TO_RES)
    changes = cm.get_graph_changes()
    assert len(changes) == 1
    assert isinstance(changes[0], ChangeArcChange)
    assert changes[0].cap_upper == 0 and changes[0].cap_lower == 0

    cm.delete_node(n2, ChangeType.DEL_TASK_NODE)
    assert isinstance(cm.get_graph_changes()[-1], RemoveNodeChange)


def test_change_stats_counts():
    cm = ChangeManager()
    n1 = cm.add_node(NodeType.SINK, 0, ChangeType.ADD_SINK_NODE)
    n2 = cm.add_node(NodeType.UNSCHEDULED_TASK, 1, ChangeType.ADD_TASK_NODE)
    cm.add_arc(n2, n1, 0, 1, 5, ArcType.OTHER, ChangeType.ADD_ARC_TO_UNSCHED)
    s = cm.stats
    assert s.nodes_added == 2
    assert s.arcs_added == 1
    assert s.by_type[ChangeType.ADD_TASK_NODE] == 1
    csv = s.to_csv()
    assert csv.startswith("2,0,1,0,0")
    s.reset()
    assert s.nodes_added == 0
