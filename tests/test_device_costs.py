"""The traceable device cost matrices (costmodels/device_costs.py) must
agree elementwise with the numpy policy implementations the host path
uses (coco_cost_matrix / whare_cost_matrix)."""

import numpy as np
import jax.numpy as jnp

from ksched_tpu.costmodels.coco import coco_cost_matrix
from ksched_tpu.costmodels.device_costs import coco_device_cost_fn, whare_device_cost_fn
from ksched_tpu.costmodels.whare import whare_cost_matrix


def test_coco_device_matches_numpy():
    rng = np.random.default_rng(0)
    for seed in range(5):
        rng = np.random.default_rng(seed)
        M = int(rng.integers(3, 50))
        census = rng.integers(0, 10, (M, 4)).astype(np.int64)
        penalties = rng.integers(0, 50, (M, 4)).astype(np.int64)
        want = coco_cost_matrix(census, penalties)
        got = np.asarray(coco_device_cost_fn(penalties)(jnp.asarray(census)))
        np.testing.assert_array_equal(got, want)
        # and the no-penalty form
        want0 = coco_cost_matrix(census)
        got0 = np.asarray(coco_device_cost_fn()(jnp.asarray(census)))
        np.testing.assert_array_equal(got0, want0)


def test_whare_device_matches_numpy_homogeneous():
    for seed in range(5):
        rng = np.random.default_rng(seed)
        M = int(rng.integers(3, 50))
        slots = 16
        census = rng.integers(0, 5, (M, 4)).astype(np.int64)
        census = np.minimum(census, slots)  # can't run more than slots
        idle = np.maximum(0, slots - census.sum(axis=1))
        want = whare_cost_matrix(census, idle, np.full(M, slots, np.int64))
        got = np.asarray(
            whare_device_cost_fn(slots_per_machine=slots)(jnp.asarray(census))
        )
        np.testing.assert_array_equal(got, want)


def test_whare_platform_factor_scales_expected_slowdown():
    """Heterogeneity: a slower platform (factor > 100) must never be
    cheaper than a faster one with the same census."""
    census = np.full((2, 4), 2, np.int64)
    fast_slow = np.asarray([90, 130], np.int64)
    cost = np.asarray(
        whare_device_cost_fn(slots_per_machine=16, platform_factor=fast_slow)(
            jnp.asarray(census)
        )
    )
    assert (cost[:, 1] >= cost[:, 0]).all()
